from crimp_tpu.utils.logging import configure_logging, get_logger

__all__ = ["configure_logging", "get_logger"]
