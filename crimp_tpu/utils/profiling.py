"""Kernel timing + JAX profiler tracing.

The reference has no tracing/profiling hooks at all (SURVEY.md §5: the only
temporal record is log timestamps). The TPU framework exposes two layers:

- ``timed(name)``: wall-clock a device call (forces completion — under some
  PJRT transports ``block_until_ready`` returns early, so the timer
  round-trips the result via ``np.asarray``) and log it;
- ``trace(dir)``: a ``jax.profiler`` trace context for TensorBoard-level
  kernel analysis, enabled by the CRIMP_TPU_TRACE_DIR environment variable
  so production pipelines can be profiled without code changes.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time

import numpy as np

from crimp_tpu import knobs, obs
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# The legacy flat timing registry — kept as a shim over crimp_tpu.obs
# (timed() records into both). The lock matters: the double-buffered
# host->device streaming path times blocks from producer threads, which
# would race the bare setdefault/append pattern.
_KERNEL_TIMES: dict[str, list[float]] = {}
_TIMES_LOCK = threading.Lock()


def force(result):
    """Materialize a JAX value (or pytree leaf dict) on the host."""
    if isinstance(result, dict):
        return {k: force(v) for k, v in result.items()}
    if isinstance(result, tuple) and hasattr(result, "_fields"):
        # namedtuple: the constructor takes fields positionally, not an
        # iterable — type(result)(generator) is a TypeError.
        return type(result)(*(force(v) for v in result))
    if isinstance(result, (list, tuple)):
        return type(result)(force(v) for v in result)
    try:
        return np.asarray(result)
    except TypeError:
        return result


def _trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for an already-imported jax.

    Aligns XLA trace timelines with obs span names without this module
    ever importing jax itself (``sys.modules`` peek only — a pure-host
    caller that never touched jax stays jax-free). None when unavailable.
    """
    jaxmod = sys.modules.get("jax")
    if jaxmod is None:
        return None
    try:
        return jaxmod.profiler.TraceAnnotation(str(name))
    except Exception:  # noqa: BLE001 — trace alignment is best-effort telemetry  # graftlint: disable=GL006 (telemetry guard: TraceAnnotation availability is jax-version-dependent; timing must proceed without it)
        return None


@contextlib.contextmanager
def timed(name: str, sync=None):
    """Time a block; if ``sync`` is a callable it is invoked at exit to
    force device completion (e.g. ``lambda: np.asarray(out)``).

    Recorded in the legacy per-process registry (``kernel_times()``) and,
    when a flight-recorder run is active, as a ``kind="kernel"`` span of
    the current stage (crimp_tpu.obs supersedes this module's registry;
    the dict survives as a shim for existing callers). A raising body
    still records its measurement, with an ``error`` attribute on the
    span — a failed kernel that vanished from the manifest used to be
    indistinguishable from one that never ran. The block also runs under
    a ``jax.profiler.TraceAnnotation`` when jax is already imported, so
    XLA trace timelines carry the same names the spans do."""
    t0 = time.perf_counter()
    annotation = _trace_annotation(name)
    if annotation is not None:
        annotation.__enter__()
    error = None
    try:
        yield
        if sync is not None:
            force(sync() if callable(sync) else sync)
    except BaseException as exc:
        error = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        if annotation is not None:
            annotation.__exit__(None, None, None)
        dt = time.perf_counter() - t0
        with _TIMES_LOCK:
            _KERNEL_TIMES.setdefault(name, []).append(dt)
        if error is None:
            obs.record_span(name, dt, kind="kernel")
            logger.info("[timing] %s: %.3fs", name, dt)
        else:
            obs.record_span(name, dt, kind="kernel", error=error)
            logger.warning("[timing] %s: %.3fs (FAILED: %s)", name, dt, error)


def kernel_times() -> dict[str, list[float]]:
    """All recorded block timings of this process (name -> durations)."""
    with _TIMES_LOCK:
        return {k: list(v) for k, v in _KERNEL_TIMES.items()}


def reset_kernel_times() -> None:
    with _TIMES_LOCK:
        _KERNEL_TIMES.clear()


_COMPILE_EVENTS: dict[str, int] = {}
_COMPILE_DURATIONS: dict[str, float] = {}
_LISTENERS_INSTALLED = False


def install_compile_listeners() -> bool:
    """Subscribe to jax's monitoring stream for compile/cache telemetry.

    Counts ``/jax/compilation_cache/{cache_hits,cache_misses,...}`` events
    and accumulates compile/retrieval durations, so ``compile_counters()``
    can report persistent-cache effectiveness without parsing logs. Tries
    the public ``jax.monitoring`` first and falls back to
    ``jax._src.monitoring`` (older jax exposed only the private path) —
    guarded so a jax upgrade that moves either degrades to "no counters",
    never to a broken import. Idempotent; installing is config-only (no
    backend).
    """
    global _LISTENERS_INSTALLED
    if _LISTENERS_INSTALLED:
        return True
    monitoring = None
    try:
        from jax import monitoring as public_monitoring
        if hasattr(public_monitoring, "register_event_listener"):
            monitoring = public_monitoring
    except ImportError:
        pass
    if monitoring is None:
        try:
            from jax._src import monitoring
        except ImportError:
            return False

    def _on_event(event: str, **kw) -> None:
        # jax may emit monitoring events from compilation worker threads
        if event.startswith("/jax/compilation_cache/"):
            key = event.rsplit("/", 1)[-1]
            with _TIMES_LOCK:
                _COMPILE_EVENTS[key] = _COMPILE_EVENTS.get(key, 0) + 1

    def _on_duration(event: str, duration: float, **kw) -> None:
        if event.startswith(("/jax/compilation_cache/", "/jax/core/compile/")):
            key = event.rsplit("/", 1)[-1]
            with _TIMES_LOCK:
                _COMPILE_DURATIONS[key] = (
                    _COMPILE_DURATIONS.get(key, 0.0) + duration)

    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001 — telemetry must never break import  # graftlint: disable=GL006 (telemetry guard: jax.monitoring listeners are optional; failing to install them must not break import)
        return False
    with _TIMES_LOCK:
        _LISTENERS_INSTALLED = True
    return True


def compile_counters() -> dict:
    """Cache hit/miss counts + accumulated compile durations (seconds)."""
    return {
        "cache_hits": _COMPILE_EVENTS.get("cache_hits", 0),
        "cache_misses": _COMPILE_EVENTS.get("cache_misses", 0),
        "events": dict(_COMPILE_EVENTS),
        "backend_compile_s": round(
            _COMPILE_DURATIONS.get("backend_compile_duration", 0.0), 4),
        "cache_retrieval_s": round(
            _COMPILE_DURATIONS.get("cache_retrieval_time_sec", 0.0), 4),
        "compile_time_saved_s": round(
            _COMPILE_DURATIONS.get("compile_time_saved_sec", 0.0), 4),
    }


def reset_compile_counters() -> None:
    with _TIMES_LOCK:
        _COMPILE_EVENTS.clear()
        _COMPILE_DURATIONS.clear()


@contextlib.contextmanager
def trace(trace_dir: str | None = None):
    """jax.profiler trace context; no-op when no directory is configured.

    Directory resolution: explicit argument, else CRIMP_TPU_TRACE_DIR.
    """
    target = trace_dir or knobs.env_str("CRIMP_TPU_TRACE_DIR")
    if not target:
        yield
        return
    import jax

    with jax.profiler.trace(target):
        logger.info("[timing] jax profiler trace -> %s", target)
        yield
