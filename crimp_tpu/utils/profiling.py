"""Kernel timing + JAX profiler tracing.

The reference has no tracing/profiling hooks at all (SURVEY.md §5: the only
temporal record is log timestamps). The TPU framework exposes two layers:

- ``timed(name)``: wall-clock a device call (forces completion — under some
  PJRT transports ``block_until_ready`` returns early, so the timer
  round-trips the result via ``np.asarray``) and log it;
- ``trace(dir)``: a ``jax.profiler`` trace context for TensorBoard-level
  kernel analysis, enabled by the CRIMP_TPU_TRACE_DIR environment variable
  so production pipelines can be profiled without code changes.
"""

from __future__ import annotations

import contextlib
import os
import time

import numpy as np

from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_KERNEL_TIMES: dict[str, list[float]] = {}


def force(result):
    """Materialize a JAX value (or pytree leaf dict) on the host."""
    if isinstance(result, dict):
        return {k: force(v) for k, v in result.items()}
    if isinstance(result, (list, tuple)):
        return type(result)(force(v) for v in result)
    try:
        return np.asarray(result)
    except TypeError:
        return result


@contextlib.contextmanager
def timed(name: str, sync=None):
    """Time a block; if ``sync`` is a callable it is invoked at exit to
    force device completion (e.g. ``lambda: np.asarray(out)``)."""
    t0 = time.perf_counter()
    yield
    if sync is not None:
        force(sync() if callable(sync) else sync)
    dt = time.perf_counter() - t0
    _KERNEL_TIMES.setdefault(name, []).append(dt)
    logger.info("[timing] %s: %.3fs", name, dt)


def kernel_times() -> dict[str, list[float]]:
    """All recorded block timings of this process (name -> durations)."""
    return {k: list(v) for k, v in _KERNEL_TIMES.items()}


def reset_kernel_times() -> None:
    _KERNEL_TIMES.clear()


@contextlib.contextmanager
def trace(trace_dir: str | None = None):
    """jax.profiler trace context; no-op when no directory is configured.

    Directory resolution: explicit argument, else CRIMP_TPU_TRACE_DIR.
    """
    target = trace_dir or os.environ.get("CRIMP_TPU_TRACE_DIR")
    if not target:
        yield
        return
    import jax

    with jax.profiler.trace(target):
        logger.info("[timing] jax profiler trace -> %s", target)
        yield
