"""Shared logging bootstrap (console + truncating rotating file handler).

Behavioral parity with the reference's logging_utils
(/root/reference/src/crimp/logging_utils.py:14-63): every CLI tool writes a
``<output>.log`` file that is truncated per run and records the full input
parameters, while console verbosity is controlled by -v/-vv.
"""

from __future__ import annotations

import logging
from logging.handlers import RotatingFileHandler

_FORMAT = "[%(asctime)s] %(levelname)8s %(message)s (%(name)s:%(lineno)s)"
_DATEFMT = "%Y-%m-%d %H:%M:%S"


def configure_logging(
    *,
    console_level: str = "WARNING",
    file_path: str | None = None,
    file_level: str = "INFO",
    file_max_bytes: int = 10_000_000,
    file_backup_count: int = 3,
    force: bool = False,
) -> None:
    """Configure the root logger with a console handler and, optionally, a
    truncate-on-run rotating file handler."""
    root = logging.getLogger()
    if force:
        for handler in list(root.handlers):
            root.removeHandler(handler)
    # Root sits at the lowest level any of our handlers wants — NOT at DEBUG:
    # third-party libraries (jax) attach their own stderr handlers that
    # inherit the root's effective level, so an unconditional DEBUG root
    # floods the console with their internals.
    console_lvl = getattr(logging, console_level.upper(), logging.WARNING)
    file_lvl = getattr(logging, file_level.upper(), logging.INFO)
    root.setLevel(min(console_lvl, file_lvl) if file_path else console_lvl)

    console = logging.StreamHandler()
    console.setLevel(console_lvl)
    console.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
    root.addHandler(console)

    if file_path:
        # Truncate any pre-existing log from an earlier run.
        open(file_path, "w").close()
        file_handler = RotatingFileHandler(
            file_path, mode="w", maxBytes=file_max_bytes, backupCount=file_backup_count
        )
        file_handler.setLevel(file_lvl)
        file_handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        root.addHandler(file_handler)


def get_logger(name: str) -> logging.Logger:
    """Module logger with a NullHandler so imports never configure logging."""
    logger = logging.getLogger(name)
    if not logger.handlers and not logger.propagate:
        logger.addHandler(logging.NullHandler())
    return logger


def verbosity_to_level(verbose_count: int) -> str:
    """Map argparse -v count to a console level (WARNING/INFO/DEBUG)."""
    return ("WARNING", "INFO", "DEBUG")[min(verbose_count, 2)]
