"""Platform forcing for scripts and dry-runs.

The deployment's site hook overrides the ``JAX_PLATFORMS`` environment
variable, so env alone CANNOT keep a process off the accelerator relay —
the only reliable mechanism is ``jax.config.update("jax_platforms", "cpu")``
after import and before the first array op (the same one
tests/conftest.py and __graft_entry__.dryrun_multichip use). This module
keeps that workaround in one place for every script that needs a
``--cpu`` dry-run mode.
"""

from __future__ import annotations

import pathlib

from crimp_tpu import knobs


def add_cpu_flag(parser) -> None:
    """Add the standard ``--cpu`` dry-run flag to an argparse parser."""
    parser.add_argument(
        "--cpu",
        action="store_true",
        help="force the CPU platform (the site hook overrides the "
             "JAX_PLATFORMS env var; only jax.config wins)",
    )


def force_cpu_platform() -> None:
    """Pin this process to the CPU backend (call before any array op)."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def compilation_cache_dir() -> pathlib.Path | None:
    """Resolved persistent-compile-cache dir, or None when disabled.

    ``CRIMP_TPU_COMPILE_CACHE``: unset/empty -> default
    ``$XDG_CACHE_HOME/crimp_tpu/jax_cache``; ``0/off/none`` -> disabled;
    anything else is used as the directory path.
    """
    env = knobs.raw("CRIMP_TPU_COMPILE_CACHE")
    if env.lower() in ("0", "off", "none", "false"):
        return None
    if env:
        return pathlib.Path(env)
    return pathlib.Path(knobs.cache_home()) / "crimp_tpu" / "jax_cache"


def configure_compilation_cache() -> pathlib.Path | None:
    """Point jax's persistent compilation cache at our directory.

    Config-only: sets jax.config values without initializing a backend
    (``import crimp_tpu`` must stay side-effect-free w.r.t. device
    acquisition — the relay-window scripts rely on that). Every scarce
    relay window was burning minutes recompiling identical kernels; with
    this cache a second cold process retrieves them from disk instead.
    The min-compile-time floor defaults to 0 so even the sub-second CPU
    test kernels round-trip (``CRIMP_TPU_COMPILE_CACHE_MIN_S`` raises it
    for installs that only want the expensive TPU binaries persisted).
    """
    target = compilation_cache_dir()
    if target is None:
        return None
    import jax

    try:
        target.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(target))
        min_s = knobs.env_float("CRIMP_TPU_COMPILE_CACHE_MIN_S", 0.0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_s)
    except (OSError, ValueError, AttributeError):
        return None
    return target
