"""Platform forcing for scripts and dry-runs.

The deployment's site hook overrides the ``JAX_PLATFORMS`` environment
variable, so env alone CANNOT keep a process off the accelerator relay —
the only reliable mechanism is ``jax.config.update("jax_platforms", "cpu")``
after import and before the first array op (the same one
tests/conftest.py and __graft_entry__.dryrun_multichip use). This module
keeps that workaround in one place for every script that needs a
``--cpu`` dry-run mode.
"""

from __future__ import annotations


def add_cpu_flag(parser) -> None:
    """Add the standard ``--cpu`` dry-run flag to an argparse parser."""
    parser.add_argument(
        "--cpu",
        action="store_true",
        help="force the CPU platform (the site hook overrides the "
             "JAX_PLATFORMS env var; only jax.config wins)",
    )


def force_cpu_platform() -> None:
    """Pin this process to the CPU backend (call before any array op)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
