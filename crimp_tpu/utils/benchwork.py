"""The shared on-chip A/B measurement workload.

The Z^2 trig-path A/B (tests/test_tpu_tier.py), the block-size sweep
(scripts/sweep_blocks.py), and the recorded perf-guard rates
(docs/onchip_rates.json via scripts/extract_rates.py) must all measure the
SAME workload, or sweep winners and guard thresholds silently stop being
comparable. This module is that single definition: bench scale (8e5
events x 1e5 trials on a uniform grid around the 1E 2259+586 spin
frequency), best-of-N timing after one warmup.
"""

from __future__ import annotations

import time

import numpy as np

AB_N_EVENTS = 800_000
AB_N_TRIALS = 100_000
AB_SEED = 7


def ab_workload(n_events: int = AB_N_EVENTS, n_trials: int = AB_N_TRIALS,
                seed: int = AB_SEED):
    """(sec, freqs, f0, df): the canonical A/B scan problem."""
    from crimp_tpu.ops import search

    rng = np.random.RandomState(seed)
    sec = np.sort(rng.uniform(-4e5, 4e5, n_events))
    freqs = np.linspace(0.1430, 0.1436, n_trials)
    f0, df = search.uniform_grid(freqs)
    return sec, freqs, f0, df


def best_rate(fn, n_trials: int, repeats: int = 3) -> float:
    """trials/s from the best of ``repeats`` timed runs after one warmup."""
    fn().block_until_ready()
    best = np.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn().block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return n_trials / best
