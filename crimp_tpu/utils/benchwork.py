"""The shared on-chip A/B measurement workload.

The Z^2 trig-path A/B (tests/test_tpu_tier.py), the block-size sweep
(scripts/sweep_blocks.py), and the recorded perf-guard rates
(docs/onchip_rates.json via scripts/extract_rates.py) must all measure the
SAME workload, or sweep winners and guard thresholds silently stop being
comparable. This module is that single definition: bench scale (8e5
events x 1e5 trials on a uniform grid around the 1E 2259+586 spin
frequency), best-of-N timing after one warmup.
"""

from __future__ import annotations

import time

import numpy as np

AB_N_EVENTS = 800_000
AB_N_TRIALS = 100_000
AB_SEED = 7


def ab_workload(n_events: int = AB_N_EVENTS, n_trials: int = AB_N_TRIALS,
                seed: int = AB_SEED):
    """(sec, freqs, f0, df): the canonical A/B scan problem."""
    from crimp_tpu.ops import search

    rng = np.random.RandomState(seed)
    sec = np.sort(rng.uniform(-4e5, 4e5, n_events))
    freqs = np.linspace(0.1430, 0.1436, n_trials)
    f0, df = search.uniform_grid(freqs)
    return sec, freqs, f0, df


def best_rate(fn, n_trials: int, repeats: int = 3) -> float:
    """trials/s from the best of ``repeats`` timed runs after one warmup."""
    fn().block_until_ready()
    best = np.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn().block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return n_trials / best


def candidate_rate(kernel: str, sec, freqs, f0, df, n_trials: int,
                   nharm: int, event_block: int, trial_block: int,
                   poly: bool, repeats: int = 3) -> float:
    """trials/s of ONE (event_block, trial_block) candidate on the A/B
    problem — the measurement primitive the block autotuner ranks with.

    ``kernel`` selects the variant family being tuned: "grid" times the
    uniform-grid fast path (harmonic_sums_uniform, the same jitted core
    z2/h _power_grid call), "grid_mxu" the factorized matmul variant,
    "general" the arbitrary-frequency blockwise kernel, "multisource" the
    survey batch engine's vmapped per-row H reduction — there the A/B
    events reshape into rows of ``event_block`` events (the padded
    per-source width) dispatched ``trial_block`` source rows at a time,
    and the returned rate is source rows/s. Returns a device-synchronized
    rate via best_rate.
    """
    import jax.numpy as jnp

    from crimp_tpu.ops import search

    times = jnp.asarray(sec)
    # the kernels return a (c, s) pair; best_rate syncs on its return
    # value, so hand it one array (syncing either syncs the whole computation)
    if kernel == "grid":
        fn = lambda: search.harmonic_sums_uniform(  # noqa: E731
            times, float(f0), float(df), int(n_trials), nharm,
            event_block=event_block, trial_block=trial_block, poly=poly)[0]
    elif kernel == "grid_mxu":
        fn = lambda: search.harmonic_sums_uniform_mxu(  # noqa: E731
            times, float(f0), float(df), int(n_trials), nharm,
            event_block=event_block, trial_block=trial_block, poly=poly)[0]
    elif kernel == "grid3d":
        # small (fdot, fddot) cross axes around the A/B target: the cube
        # kernel's rate is quoted in CUBE trials/s so candidates at
        # different cross-axis sizes stay comparable
        fdots = jnp.asarray([-9.2e-14, -9.3e-14, -9.4e-14, -9.5e-14])
        fddots = jnp.asarray([-1e-20, 1e-20])
        n_freq = max(int(trial_block), int(n_trials) // 8)
        fn = lambda: search.harmonic_sums_uniform_3d(  # noqa: E731
            times, float(f0), float(df), n_freq, fdots, fddots, nharm,
            event_block=event_block, trial_block=trial_block, poly=poly)[0]
        return best_rate(fn, n_freq * 4 * 2, repeats=repeats)
    elif kernel == "semicoherent":
        from crimp_tpu.ops import semicoherent as semi

        fdots = np.asarray([-9.2e-14, -9.3e-14, -9.4e-14, -9.5e-14])
        fddots = np.asarray([-1e-20, 1e-20])
        n_freq = max(int(trial_block), int(n_trials) // 8)
        fn = lambda: semi.semicoherent_z2_grid(  # noqa: E731
            np.asarray(sec), float(f0), float(df), n_freq, fdots, fddots,
            nharm=nharm, n_segments=4, poly=poly,
            event_block=event_block, trial_block=trial_block, mxu=False)
        return best_rate(fn, n_freq * 4 * 2, repeats=repeats)
    elif kernel == "general":
        freqs_dev = jnp.asarray(freqs)
        fn = lambda: search.harmonic_sums_1d(  # noqa: E731
            times, freqs_dev, nharm, event_block=event_block,
            trial_block=trial_block, poly=poly)[0]
    elif kernel == "multisource":
        n_src = max(1, len(sec) // int(event_block))
        rows = jnp.asarray(
            np.asarray(sec[: n_src * int(event_block)]).reshape(
                n_src, int(event_block))
        )
        masks = jnp.ones(rows.shape, dtype=bool)
        row_freqs = jnp.asarray(np.resize(np.asarray(freqs), n_src))
        chunk = max(1, min(int(trial_block), n_src))

        def fn():  # noqa: E731 — chunked like h_power_sources dispatches
            outs = [
                search.h_power_segments(rows[lo:lo + chunk],
                                        masks[lo:lo + chunk],
                                        row_freqs[lo:lo + chunk],
                                        nharm=nharm)
                for lo in range(0, n_src, chunk)
            ]
            return jnp.concatenate(outs)

        return best_rate(fn, n_src, repeats=repeats)
    else:
        raise ValueError(f"unknown kernel variant {kernel!r}")
    return best_rate(fn, int(n_trials), repeats=repeats)
