"""graftlint core: findings, waivers, file loading, report, baseline.

The analyzer is a plain-AST pass — it never imports the modules it
checks (so a trace-discipline bug in a kernel module cannot take the
linter down with it) and never imports jax (it must run in the
relay-window shells where no backend exists).

Waiver grammar (one line):

    some_code()  # graftlint: disable=GL005 (fixed-order column accumulation, see mesh.py note)
    # graftlint: disable-file=GL004 (host-side longdouble Taylor phase math by design)

A waiver suppresses only the named rules on its own line (or, for
``disable-file``, in its whole file). The parenthesized reason is
MANDATORY: a reasonless waiver still suppresses its target but raises an
unwaivable GL000 finding, so the tier-1 gate stays red until the reason
that survives review is written down.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize

RULES: dict[str, str] = {
    "GL000": "waiver hygiene / unparseable source",
    "GL001": "trace purity: no env/time/random/file-I/O reachable from traced code",
    "GL002": "host-sync hazards: concretizing coercions / tracer branching in traced code",
    "GL003": "knob-registry consistency (crimp_tpu/knobs.py <-> env reads <-> docs <-> numeric_mode)",
    "GL004": "dtype discipline: longdouble/float128 confined to host-side anchor modules",
    "GL005": "order-sensitive reductions in sharded/parity-pinned modules",
    "GL006": "failure-domain discipline: bare `except Exception` must classify "
             "through resilience.taxonomy or carry a waiver reason",
    "GL007": "sharding-registry discipline: hand-written PartitionSpec outside "
             "parallel/registry.py needs a waiver",
    "GL008": "concurrency discipline: thread-reachable module-global mutations "
             "hold a declared lock; lock-declaring modules guard every mutation",
    "GL009": "resilience contract web (LADDERS/FAULT_POINTS <-> "
             "record_degradation/fire sites <-> tests <-> docs/robustness.md)",
    "GL010": "telemetry-surface drift (obs counters/gauges <-> "
             "docs/observability.md <-> consumers; ledger METRICS <-> bench.py)",
}

_RULE_LIST = r"GL\d{3}(?:\s*,\s*GL\d{3})*"
WAIVER_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<file>-file)?=(?P<rules>" + _RULE_LIST + r")"
    r"(?:\s*\((?P<reason>[^()]*(?:\([^()]*\)[^()]*)*)\))?"
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # root-relative posix path
    line: int
    message: str
    waived: bool = False
    reason: str = ""

    @property
    def key(self) -> str:
        """Line-number-free identity used by the baseline (a pure-motion
        edit above a finding must not make it count as new)."""
        return f"{self.rule}|{self.path}|{self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "waived": self.waived,
            "reason": self.reason,
        }

    def render(self) -> str:
        tag = f"  [waived: {self.reason}]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclasses.dataclass
class Waiver:
    rules: frozenset[str]
    reason: str
    line: int
    file_level: bool


@dataclasses.dataclass
class SourceFile:
    path: pathlib.Path
    rel: str
    text: str
    tree: ast.AST | None
    parse_error: str | None
    line_waivers: dict[int, Waiver]
    file_waivers: dict[str, Waiver]  # rule -> waiver

    @property
    def is_python(self) -> bool:
        return self.rel.endswith(".py")


# a comment opening with the tool name + "disable" shows directive intent
# even when the rest fails to parse; prose mentions of the tool do not
_DIRECTIVE_RE = re.compile(r"graftlint:\s*" + "disable")


def _comment_lines(text: str, is_python: bool) -> list[tuple[int, str]]:
    """(lineno, comment text) pairs. Python files go through tokenize so
    waiver syntax quoted in strings/docstrings (e.g. this linter's own
    error messages) is never mistaken for a directive; everything else
    (shell) falls back to a per-line scan of the '#...' tail."""
    if is_python:
        try:
            return [(tok.start[0], tok.string)
                    for tok in tokenize.generate_tokens(io.StringIO(text).readline)
                    if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable source already yields GL000 via load_source
    out = []
    for i, line in enumerate(text.splitlines(), start=1):
        if "#" in line:
            out.append((i, line[line.index("#"):]))
    return out


def _scan_waivers(text: str, is_python: bool) -> tuple[dict[int, Waiver], dict[str, Waiver], list[tuple[int, str]]]:
    """Parse waiver comments; returns (line waivers, file waivers,
    [(line, problem)] for reasonless/malformed ones)."""
    line_waivers: dict[int, Waiver] = {}
    file_waivers: dict[str, Waiver] = {}
    problems: list[tuple[int, str]] = []
    for i, comment in _comment_lines(text, is_python):
        if not _DIRECTIVE_RE.search(comment):
            continue
        m = WAIVER_RE.search(comment)
        if m is None:
            problems.append((i, "malformed graftlint waiver (expected "
                                "'# graftlint: disable=GLxxx (reason)')"))
            continue
        rules = frozenset(r.strip() for r in m.group("rules").split(","))
        reason = (m.group("reason") or "").strip()
        if not reason:
            problems.append((i, f"waiver for {'/'.join(sorted(rules))} has no "
                                "(reason) — a waiver must say why it survives review"))
        w = Waiver(rules=rules, reason=reason, line=i,
                   file_level=bool(m.group("file")))
        if w.file_level:
            for r in rules:
                file_waivers[r] = w
        else:
            line_waivers[i] = w
    return line_waivers, file_waivers, problems


def load_source(path: pathlib.Path, root: pathlib.Path) -> SourceFile:
    text = path.read_text(encoding="utf-8", errors="replace")
    tree, err = None, None
    if path.suffix == ".py":
        try:
            tree = ast.parse(text, filename=str(path))
        except SyntaxError as exc:
            err = f"could not parse: {exc.msg} (line {exc.lineno})"
    lw, fw, problems = _scan_waivers(text, path.suffix == ".py")
    src = SourceFile(path=path, rel=path.relative_to(root).as_posix(),
                     text=text, tree=tree, parse_error=err,
                     line_waivers=lw, file_waivers=fw)
    src._waiver_problems = problems  # type: ignore[attr-defined]
    return src


EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build",
                "dist", ".pytest_cache"}


def collect_files(paths: list[pathlib.Path], root: pathlib.Path) -> list[pathlib.Path]:
    """Expand the given files/directories into the .py + .sh scan set."""
    out: list[pathlib.Path] = []
    seen: set[pathlib.Path] = set()
    for p in paths:
        p = p if p.is_absolute() else root / p
        if p.is_dir():
            found = [f for f in sorted(p.rglob("*"))
                     if f.suffix in (".py", ".sh")
                     and not (set(f.relative_to(p).parts[:-1]) & EXCLUDE_DIRS)]
        elif p.exists():
            found = [p]
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
        for f in found:
            rp = f.resolve()
            if rp not in seen:
                seen.add(rp)
                out.append(f)
    return out


DEFAULT_GL004_ALLOWLIST = (
    "crimp_tpu/ops/anchored.py",   # the longdouble anchor is this module's contract
    "crimp_tpu/ops/deltafold.py",  # basis construction differences exact longdouble phases
    "crimp_tpu/io/",               # parsing .par/.tim timestamps at full precision
)

DEFAULT_GL005_MODULES = ("crimp_tpu/parallel/",)
DEFAULT_GL006_MODULES = ("crimp_tpu/",)
DEFAULT_GL007_MODULES = ("crimp_tpu/",)
DEFAULT_GL007_REGISTRY = "crimp_tpu/parallel/registry.py"
DEFAULT_GL008_MODULES = ("crimp_tpu/",)
DEFAULT_GL010_MODULES = ("crimp_tpu/",)
# files whose text counts as "something reads this metric" for GL010
DEFAULT_TELEMETRY_CONSUMERS = ("crimp_tpu/obs/report.py",
                               "crimp_tpu/obs/ledger.py")


@dataclasses.dataclass
class Config:
    """One analysis run's inputs (everything injectable for tests)."""

    root: pathlib.Path
    paths: list[pathlib.Path]
    registry: dict | None = None  # default: crimp_tpu.knobs.REGISTRY
    tools_md: pathlib.Path | None = None  # default: root/docs/tools.md
    resumable_py: pathlib.Path | None = None  # default: root/crimp_tpu/ops/resumable.py
    knobs_rel: str = "crimp_tpu/knobs.py"  # the one sanctioned env-read site
    gl004_allowlist: tuple[str, ...] = DEFAULT_GL004_ALLOWLIST
    gl005_modules: tuple[str, ...] = DEFAULT_GL005_MODULES
    gl006_modules: tuple[str, ...] = DEFAULT_GL006_MODULES
    gl007_modules: tuple[str, ...] = DEFAULT_GL007_MODULES
    gl007_registry: str = DEFAULT_GL007_REGISTRY
    gl008_modules: tuple[str, ...] = DEFAULT_GL008_MODULES
    gl010_modules: tuple[str, ...] = DEFAULT_GL010_MODULES
    telemetry_consumers: tuple[str, ...] = DEFAULT_TELEMETRY_CONSUMERS
    observability_md: pathlib.Path | None = None  # default: root/docs/observability.md
    robustness_md: pathlib.Path | None = None  # default: root/docs/robustness.md
    tests_dir: pathlib.Path | None = None  # default: root/tests
    bench_py: pathlib.Path | None = None  # default: root/bench.py
    rules: tuple[str, ...] | None = None  # None = all

    def resolved_registry(self) -> dict:
        if self.registry is not None:
            return self.registry
        from crimp_tpu import knobs

        return knobs.REGISTRY

    def resolved_tools_md(self) -> pathlib.Path:
        return self.tools_md or self.root / "docs" / "tools.md"

    def resolved_resumable(self) -> pathlib.Path:
        return self.resumable_py or self.root / "crimp_tpu" / "ops" / "resumable.py"

    def resolved_observability_md(self) -> pathlib.Path:
        return self.observability_md or self.root / "docs" / "observability.md"

    def resolved_robustness_md(self) -> pathlib.Path:
        return self.robustness_md or self.root / "docs" / "robustness.md"

    def resolved_tests_dir(self) -> pathlib.Path:
        return self.tests_dir or self.root / "tests"

    def resolved_bench_py(self) -> pathlib.Path:
        return self.bench_py or self.root / "bench.py"

    def rule_enabled(self, rule: str) -> bool:
        return self.rules is None or rule in self.rules


@dataclasses.dataclass
class Report:
    findings: list[Finding]
    files_scanned: int

    @property
    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.unwaived:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "tool": "graftlint",
            "files_scanned": self.files_scanned,
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
        }

    def render_text(self, show_waived: bool = False) -> str:
        shown = self.findings if show_waived else self.unwaived
        lines = [f.render() for f in sorted(
            shown, key=lambda f: (f.path, f.line, f.rule))]
        n = len(self.unwaived)
        waived = len(self.findings) - n
        lines.append(f"graftlint: {self.files_scanned} files, "
                     f"{n} finding{'s' if n != 1 else ''} "
                     f"({waived} waived)")
        return "\n".join(lines)


def apply_waivers(findings: list[Finding], sources: dict[str, SourceFile]) -> list[Finding]:
    """Mark findings covered by line/file waivers; append GL000 findings
    for waiver-hygiene problems. GL000 itself is not waivable."""
    out: list[Finding] = []
    for f in findings:
        src = sources.get(f.path)
        if src is not None and f.rule != "GL000":
            fw = src.file_waivers.get(f.rule)
            lw = src.line_waivers.get(f.line)
            if fw is not None:
                f.waived, f.reason = True, fw.reason or "(no reason given)"
            elif lw is not None and f.rule in lw.rules:
                f.waived, f.reason = True, lw.reason or "(no reason given)"
        out.append(f)
    for src in sources.values():
        for line, problem in getattr(src, "_waiver_problems", []):
            out.append(Finding("GL000", src.rel, line, problem))
        if src.is_python and src.parse_error:
            out.append(Finding("GL000", src.rel, 1, src.parse_error))
    return out


# -- baseline ----------------------------------------------------------------


def save_baseline(report: Report, path: pathlib.Path) -> None:
    keys = sorted(f.key for f in report.unwaived)
    path.write_text(json.dumps({"version": 1, "keys": keys}, indent=2) + "\n")


def load_baseline(path: pathlib.Path) -> set[str]:
    doc = json.loads(path.read_text())
    if not isinstance(doc, dict) or doc.get("version") != 1:
        raise ValueError(f"{path}: not a graftlint baseline file")
    return set(doc.get("keys", []))


def new_findings(report: Report, baseline_keys: set[str]) -> list[Finding]:
    """Unwaived findings not present in the baseline — the --baseline gate
    fails only on these, so a PR inheriting old debt sees only its own."""
    return [f for f in report.unwaived if f.key not in baseline_keys]
