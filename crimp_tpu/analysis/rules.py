"""graftlint rules GL001/GL002/GL004-GL010 (GL003 lives in knobcheck.py).

Each rule is a function ``(cfg, sources, project) -> list[Finding]``
over the parsed scan set. The rules encode invariants the repo's kernel
PRs established in prose (CHANGES.md, docs/parity.md) but nothing
enforced mechanically:

GL001  trace purity — no ``os.environ``/``time``/``random``/file-I/O
       reachable from jit/pjit/shard_map/pallas_call/lax-control-flow
       bodies. Knob resolution is host-side by contract ("no implicit
       timing"), so calls into ``crimp_tpu.knobs`` or the
       ``ops/autotune.py`` resolvers from traced code are violations too.
GL002  host-sync hazards — ``float()``/``int()``/``bool()`` and
       ``np.asarray``/``np.array`` applied to (non-static) parameters of
       traced functions, ``.item()``/``.tolist()`` anywhere in traced
       code, and Python ``if``/``while`` branching on a non-static
       parameter of a trace entry point.
GL004  dtype discipline — ``longdouble``/``float128`` confined to the
       host-side anchor modules (the allowlist in core.DEFAULT_GL004_ALLOWLIST);
       everywhere else the f64 device path is the contract.
GL005  order-sensitive reductions — matmul/dot/einsum/axis-sums in the
       sharded parity-pinned modules (crimp_tpu/parallel/) must carry a
       waiver stating the fixed-order/parity argument (the PR-4 lesson:
       XLA re-tiles matvec reductions per shape, so a sharded matvec
       broke the 8-device bitwise pin).
GL006  failure-domain discipline — a bare ``except Exception`` inside
       crimp_tpu/ must route the exception through
       ``resilience.classify``/``error_record`` (so retry/degradation
       policy sees a FailureKind, not a swallowed traceback), bare-
       re-raise it, or carry a waiver stating why this handler is a
       deliberate swallow domain (telemetry guards are the baseline).
GL007  sharding-registry discipline — ``PartitionSpec(...)`` written by
       hand anywhere in crimp_tpu/ except parallel/registry.py must
       carry a waiver: specs scattered across call sites are exactly the
       bespoke-sharded-twin drift the registry exists to end (dispatch
       sites ask ``registry.specs_for(kernel, mesh)`` instead).
GL008  concurrency discipline — a module-level global mutated from code
       reachable from a thread spawn / executor callback must hold a
       declared module lock, and a module that declares such a lock
       keeps ALL its global mutations lock-guarded (the obs/core.py
       ``_LOCK`` and profiling ``_TIMES_LOCK`` patterns, enforced).
       Intentionally lock-free paths carry a mandatory-reason waiver.
GL009  resilience contract web — LADDERS engine/rung pairs, the
       FAULT_POINTS registry, their ``record_degradation()``/``fire()``
       call sites, firing tests in tests/, and docs/robustness.md are
       cross-checked in all directions (the GL003 pattern, applied to
       the resilience layer).
GL010  telemetry-surface drift — every obs counter/gauge literal is
       unique, documented in docs/observability.md, and consumed by
       obs/report.py, obs/ledger.py or a test (or waived); dynamic
       f-string families document their static prefix; every ledger
       METRICS key names a bench-record field bench.py produces.

GL008-GL010 consume the cross-file facts layer (analysis/facts.py).
"""

from __future__ import annotations

import ast
import pathlib
import re

from crimp_tpu.analysis import facts as facts_mod
from crimp_tpu.analysis.callgraph import (
    FunctionInfo,
    Project,
    call_tail,
    dotted,
    iter_body_nodes,
)
from crimp_tpu.analysis.core import Config, Finding, SourceFile

# -- GL001 -------------------------------------------------------------------

TIME_FUNCS = {"time", "perf_counter", "perf_counter_ns", "monotonic",
              "monotonic_ns", "sleep", "process_time", "thread_time"}
FILE_IO_TAILS = {"read_text", "write_text", "read_bytes", "write_bytes"}
# host-side knob/tuner resolution entry points (ops/autotune.py): calling
# these from traced code would re-introduce implicit env reads/timing
RESOLVER_PREFIXES = ("resolve_", "cached_", "autotune_mode", "tune",
                     "sweep_candidates")


def _gl001_banned(node: ast.AST, mod, project: Project,
                  scope: str | None) -> str | None:
    """A human message if this node is a banned host operation."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        if isinstance(node.value, ast.Name) and node.value.id == "os":
            return "os.environ access"
    if not isinstance(node, ast.Call):
        return None
    path = dotted(node.func) or ""
    tail = call_tail(node.func)
    if path == "os.getenv":
        return "os.getenv() call"
    head = path.split(".")[0] if path else ""
    if head == "time" and tail in TIME_FUNCS:
        return f"time.{tail}() call (no implicit timing in traced code)"
    if head == "random":
        return f"random.{tail}() call (host RNG in traced code)"
    if isinstance(node.func, ast.Name) and node.func.id == "open":
        return "open() call (file I/O in traced code)"
    if tail in FILE_IO_TAILS:
        return f".{tail}() call (file I/O in traced code)"
    target = project.resolve_callable(mod, scope, node.func)
    if target is not None:
        if target.module.endswith("crimp_tpu/knobs.py") or target.module == "crimp_tpu/knobs.py":
            return (f"knob accessor {target.name}() reached from traced code "
                    "(knobs must resolve host-side)")
        if (target.module.endswith("ops/autotune.py")
                and target.name.startswith(RESOLVER_PREFIXES)):
            return (f"autotune resolver {target.name}() reached from traced "
                    "code (resolution is host-side by contract)")
        if "crimp_tpu/obs/" in target.module:
            return (f"obs API {target.name}() reached from traced code "
                    "(telemetry is host-side by construction)")
    return None


def rule_gl001(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    out: list[Finding] = []
    for info in project.traced_functions().values():
        mod = project.modules[info.module]
        scope = info.qualname if not info.qualname.startswith("<lambda") else None
        for node in iter_body_nodes(info.node):
            msg = _gl001_banned(node, mod, project, scope)
            if msg:
                out.append(Finding(
                    "GL001", info.module, getattr(node, "lineno", info.lineno),
                    f"{msg} inside traced function {info.qualname!r} "
                    f"({info.traced_via})"))
    return out


# -- GL002 -------------------------------------------------------------------


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _tracer_params(info: FunctionInfo) -> set[str]:
    skip = set(info.static_params)
    if info.class_name is not None:
        skip.add("self")
        skip.add("cls")
    return set(info.params) - skip


def _is_none_check(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` tests are static in a trace."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops))


def rule_gl002(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    out: list[Finding] = []
    for info in project.traced_functions().values():
        tracers = _tracer_params(info)
        for node in iter_body_nodes(info.node):
            if isinstance(node, ast.Call):
                tail = call_tail(node.func)
                path = dotted(node.func) or ""
                if tail in ("item", "tolist") and not node.args:
                    out.append(Finding(
                        "GL002", info.module, node.lineno,
                        f".{tail}() in traced function {info.qualname!r} "
                        "forces a device sync / concretization"))
                    continue
                coercer = None
                if (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")):
                    coercer = node.func.id
                elif path in ("np.asarray", "np.array", "numpy.asarray",
                              "numpy.array", "np.float64", "np.float32"):
                    coercer = path
                if coercer and node.args:
                    touched = _names_in(node.args[0]) & tracers
                    if touched:
                        out.append(Finding(
                            "GL002", info.module, node.lineno,
                            f"{coercer}() applied to parameter "
                            f"{'/'.join(sorted(touched))} of traced function "
                            f"{info.qualname!r} (concretizes a tracer)"))
            elif (isinstance(node, (ast.If, ast.While))
                  and info.entry_reason is not None
                  and not _is_none_check(node.test)):
                touched = _names_in(node.test) & tracers
                if touched:
                    out.append(Finding(
                        "GL002", info.module, node.lineno,
                        f"Python branch on parameter "
                        f"{'/'.join(sorted(touched))} of trace entry "
                        f"{info.qualname!r} ({info.entry_reason}); mark it "
                        "static or use lax.cond/jnp.where"))
    return out


# -- GL004 -------------------------------------------------------------------

EXTENDED_DTYPES = {"longdouble", "float128"}


def rule_gl004(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    out: list[Finding] = []
    for rel, src in sources.items():
        if not src.is_python or src.tree is None:
            continue
        if any(rel == a or rel.startswith(a) for a in cfg.gl004_allowlist):
            continue
        for node in ast.walk(src.tree):
            name = None
            if isinstance(node, ast.Attribute) and node.attr in EXTENDED_DTYPES:
                name = dotted(node) or node.attr
            elif isinstance(node, ast.Name) and node.id in EXTENDED_DTYPES:
                name = node.id
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                modname = getattr(node, "module", None) or ""
                if modname.split(".")[0] == "mpmath" or any(
                        a.name.split(".")[0] == "mpmath" for a in node.names):
                    name = "mpmath import"
            if name:
                out.append(Finding(
                    "GL004", rel, node.lineno,
                    f"{name} outside the host-side anchor allowlist "
                    f"({', '.join(cfg.gl004_allowlist)}) — extended precision "
                    "is confined so device kernels stay f64-reproducible"))
    return out


# -- GL005 -------------------------------------------------------------------

ORDER_SENSITIVE_TAILS = {"dot", "matmul", "einsum", "tensordot", "inner",
                         "vdot"}


def rule_gl005(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    out: list[Finding] = []
    for rel, src in sources.items():
        if not src.is_python or src.tree is None:
            continue
        if not any(rel == m or rel.startswith(m) for m in cfg.gl005_modules):
            continue
        for node in ast.walk(src.tree):
            msg = None
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                msg = "matmul operator (@)"
            elif isinstance(node, ast.Call):
                tail = call_tail(node.func)
                if tail in ORDER_SENSITIVE_TAILS:
                    msg = f"{tail}()"
                elif tail == "sum" and (node.args or any(
                        k.arg == "axis" for k in node.keywords)):
                    msg = "axis reduction sum()"
            if msg:
                out.append(Finding(
                    "GL005", rel, node.lineno,
                    f"{msg} in sharded/parity-pinned module — XLA re-tiles "
                    "matvec/axis reductions per shape, which broke the "
                    "8-device bitwise pin once (parallel/mesh.py); use "
                    "fixed-order accumulation or waive with the parity "
                    "argument"))
    return out


# -- GL006 -------------------------------------------------------------------

# Calls whose dotted tail proves the handler classified the failure:
# resilience.classify(exc) or resilience.error_record(exc) (the latter
# embeds classify and is the info-dict form the survey uses).
CLASSIFY_TAILS = {"classify", "error_record"}


def _gl006_broad(type_node) -> bool:
    """Whether an ExceptHandler's type catches everything."""
    if type_node is None:
        return True  # bare `except:`
    elts = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    return any(isinstance(n, ast.Name)
               and n.id in ("Exception", "BaseException") for n in elts)


def _gl006_classifies(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Call) and call_tail(sub.func) in CLASSIFY_TAILS:
            return True
        if isinstance(sub, ast.Raise) and sub.exc is None:
            # a bare re-raise keeps the exception in flight — the caller's
            # failure domain owns classification
            return True
    return False


# -- GL007 -------------------------------------------------------------------


def _gl007_aliases(tree: ast.AST) -> set[str]:
    """Local names bound to PartitionSpec by a ``from ...sharding import``
    (``from jax.sharding import PartitionSpec as P`` is the repo idiom)."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        if not str(node.module or "").endswith("sharding"):
            continue
        for a in node.names:
            if a.name == "PartitionSpec":
                aliases.add(a.asname or a.name)
    return aliases


def rule_gl007(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    out: list[Finding] = []
    for rel, src in sources.items():
        if not src.is_python or src.tree is None:
            continue
        if rel == cfg.gl007_registry:
            continue  # the registry is the one sanctioned spec-writing site
        if not any(rel == m or rel.startswith(m) for m in cfg.gl007_modules):
            continue
        aliases = _gl007_aliases(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = call_tail(node.func) == "PartitionSpec" or (
                isinstance(node.func, ast.Name) and node.func.id in aliases)
            if hit:
                out.append(Finding(
                    "GL007", rel, node.lineno,
                    "hand-written PartitionSpec outside "
                    f"{cfg.gl007_registry} — dispatch sites take their specs "
                    "from registry.specs_for(kernel, mesh) so shardings "
                    "cannot drift per call site; waive with the reason this "
                    "spec cannot live in the registry"))
    return out


def rule_gl006(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    out: list[Finding] = []
    for rel, src in sources.items():
        if not src.is_python or src.tree is None:
            continue
        if not any(rel == m or rel.startswith(m) for m in cfg.gl006_modules):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _gl006_broad(node.type):
                continue
            if _gl006_classifies(node):
                continue
            out.append(Finding(
                "GL006", rel, node.lineno,
                "bare `except Exception` without failure classification — "
                "route it through resilience.classify/error_record so "
                "retry/degradation policy sees its FailureKind, or waive "
                "with the reason this handler is a deliberate swallow "
                "domain"))
    return out


# -- GL008/GL009/GL010 helpers ------------------------------------------------


def _in_modules(rel: str, modules: tuple[str, ...]) -> bool:
    return any(rel == m or rel.startswith(m) for m in modules)


def _mentions(text: str, name: str) -> bool:
    """Word-boundary-ish containment: ``grid`` must not match
    ``grid_mxu`` (identifier characters end the word)."""
    return re.search(r"(?<![A-Za-z0-9_])" + re.escape(name)
                     + r"(?![A-Za-z0-9_])", text) is not None


def _read_optional(path: pathlib.Path) -> str:
    try:
        return path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return ""


def _tests_corpus(cfg: Config) -> str:
    """Concatenated text of tests/*.py — the 'is there a test touching
    this name' side of the GL009/GL010 webs."""
    tests_dir = cfg.resolved_tests_dir()
    if not tests_dir.is_dir():
        return ""
    return "\n".join(_read_optional(p) for p in sorted(tests_dir.glob("*.py")))


# -- GL008 -------------------------------------------------------------------


def rule_gl008(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    pf = facts_mod.for_project(project)
    reachable = pf.thread_reachable()
    out: list[Finding] = []
    for rel in sorted(pf.modules):
        if not _in_modules(rel, cfg.gl008_modules):
            continue
        mf = pf.modules[rel]
        lock_list = ", ".join(sorted(mf.locks)) or None
        for m in mf.mutations:
            if m.locks_held:
                continue
            if f"{rel}:{m.func}" in reachable:
                out.append(Finding(
                    "GL008", rel, m.line,
                    f"module global {m.name!r} mutated ({m.how}) in "
                    f"{m.func}(), which runs off the main thread (reachable "
                    "from a Thread target / executor callback), without "
                    "holding a declared lock — guard it with a module "
                    "threading.Lock or waive with the lock-free argument"))
            elif lock_list is not None:
                out.append(Finding(
                    "GL008", rel, m.line,
                    f"module global {m.name!r} mutated ({m.how}) in "
                    f"{m.func}() outside any `with` on a declared lock "
                    f"({lock_list}) — a lock-declaring module keeps every "
                    "global mutation guarded, or waives the site with the "
                    "single-threaded argument"))
    return out


# -- GL009 -------------------------------------------------------------------


def rule_gl009(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    pf = facts_mod.for_project(project)
    ladders, lad_rel, lad_line = pf.ladders()
    points, pts_rel, pts_line = pf.fault_points()
    rob_path = cfg.resolved_robustness_md()
    rob_rel = rob_path.name if rob_path.parent.name != "docs" \
        else f"docs/{rob_path.name}"
    rob = _read_optional(rob_path)
    tests = _tests_corpus(cfg)
    out: list[Finding] = []

    deg_literal = {(s.engine, s.rung)
                   for s in pf.degradation_sites() if s.engine and s.rung}
    for engine, rungs in sorted(ladders.items()):
        # rungs[0] is the normal (non-degraded) path — reaching it never
        # goes through record_degradation, so only fallback rungs need a
        # call site
        for rung in rungs[1:]:
            if (engine, rung) not in deg_literal:
                out.append(Finding(
                    "GL009", lad_rel, lad_line,
                    f"LADDERS[{engine!r}] rung {rung!r} has no "
                    f"record_degradation({engine!r}, {rung!r}, ...) call "
                    "site in the scan set — an unreachable rung is dead "
                    "policy"))
        for name in dict.fromkeys((engine, *rungs)):
            if not _mentions(rob, name):
                out.append(Finding(
                    "GL009", lad_rel, lad_line,
                    f"ladder name {name!r} (engine {engine!r}) is missing "
                    f"from {rob_rel} — the degradation-ladder table is the "
                    "operator contract"))
    if ladders:
        for s in pf.degradation_sites():
            if s.engine is None or s.rung is None:
                continue  # dynamic args — validated at runtime by policy.py
            if s.engine not in ladders:
                out.append(Finding(
                    "GL009", s.rel, s.line,
                    f"record_degradation names unregistered engine "
                    f"{s.engine!r} — every engine degrades along a declared "
                    "LADDERS entry"))
            elif s.rung not in ladders[s.engine]:
                out.append(Finding(
                    "GL009", s.rel, s.line,
                    f"record_degradation names rung {s.rung!r} not in "
                    f"LADDERS[{s.engine!r}] {ladders[s.engine]!r}"))

    fired = {f.point for f in pf.fire_sites() if f.point}
    for point in sorted(points):
        if point not in fired:
            out.append(Finding(
                "GL009", pts_rel, pts_line,
                f"fault point {point!r} has no fire({point!r}) site in the "
                "scan set — an unfireable point cannot be chaos-tested"))
        if f":{point}:" not in tests:
            out.append(Finding(
                "GL009", pts_rel, pts_line,
                f"fault point {point!r} has no firing test in tests/ "
                f"(no 'kind:{point}:n' fault spec) — every recovery path "
                "is exercised in CI, not discovered in production"))
        if not _mentions(rob, point):
            out.append(Finding(
                "GL009", pts_rel, pts_line,
                f"fault point {point!r} is missing from {rob_rel}"))
    if points:
        for f in pf.fire_sites():
            if f.point is not None and f.point not in points:
                out.append(Finding(
                    "GL009", f.rel, f.line,
                    f"fire() names unregistered fault point {f.point!r} — "
                    "the FAULT_POINTS registry is closed"))
    return out


# -- GL010 -------------------------------------------------------------------


def rule_gl010(cfg: Config, sources: dict[str, SourceFile],
               project: Project) -> list[Finding]:
    pf = facts_mod.for_project(project)
    obs_path = cfg.resolved_observability_md()
    obs_rel = obs_path.name if obs_path.parent.name != "docs" \
        else f"docs/{obs_path.name}"
    obs_doc = _read_optional(obs_path)
    consumers = _tests_corpus(cfg) + "\n" + "\n".join(
        _read_optional(cfg.root / rel) for rel in cfg.telemetry_consumers)
    out: list[Finding] = []

    emits = [m for m in pf.metric_emits()
             if _in_modules(m.rel, cfg.gl010_modules)]
    # first emission site per literal name (stable anchor for waivers)
    first: dict[tuple[str, str], facts_mod.MetricEmit] = {}
    kinds_by_name: dict[str, set[str]] = {}
    for m in sorted(emits, key=lambda m: (m.rel, m.line)):
        if m.name is None:
            continue
        first.setdefault((m.kind, m.name), m)
        if m.kind in ("counter", "gauge"):
            kinds_by_name.setdefault(m.name, set()).add(m.kind)

    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            m = min((first[(k, name)] for k in kinds),
                    key=lambda m: (m.rel, m.line))
            out.append(Finding(
                "GL010", m.rel, m.line,
                f"metric name {name!r} is emitted as both "
                f"{' and '.join(sorted(kinds))} — names are unique across "
                "metric types"))

    for (kind, name), m in sorted(first.items()):
        if kind == "beat":
            continue  # heartbeat labels are phase tags, not ledger metrics
        if not _mentions(obs_doc, name):
            out.append(Finding(
                "GL010", m.rel, m.line,
                f"{kind} {name!r} is not documented in {obs_rel} — every "
                "emitted metric has an inventory row"))
        if not _mentions(consumers, name):
            out.append(Finding(
                "GL010", m.rel, m.line,
                f"{kind} {name!r} is emitted but never consumed by "
                "obs/report.py, obs/ledger.py or a test — dead telemetry "
                "drifts silently; consume it or waive with the reason it "
                "is operator-facing only"))

    seen_dynamic: set[tuple[str, str]] = set()
    for m in sorted(emits, key=lambda m: (m.rel, m.line)):
        if m.name is not None or m.kind == "beat":
            continue
        if not m.prefix:
            out.append(Finding(
                "GL010", m.rel, m.line,
                f"{m.kind} name at this site is not a string literal or "
                "prefixed f-string — the telemetry surface must be "
                "statically enumerable; use a literal family prefix or "
                "waive with the reason"))
            continue
        if (m.kind, m.prefix) in seen_dynamic:
            continue
        seen_dynamic.add((m.kind, m.prefix))
        if m.prefix not in obs_doc:
            out.append(Finding(
                "GL010", m.rel, m.line,
                f"dynamic {m.kind} family with prefix {m.prefix!r} is not "
                f"documented in {obs_rel} — document the "
                f"'{m.prefix}<...>' pattern"))

    ledger, led_rel, led_line = pf.ledger_metrics()
    bench_text = _read_optional(cfg.resolved_bench_py())
    for key, field in sorted(ledger.items()):
        if not _mentions(bench_text, field):
            out.append(Finding(
                "GL010", led_rel, led_line,
                f"ledger metric {key!r} reads bench-record field {field!r} "
                f"but {cfg.resolved_bench_py().name} never produces it — a "
                "gate metric nothing feeds can never ratchet"))
    return out
