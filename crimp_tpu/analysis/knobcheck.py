"""graftlint GL003: knob-registry consistency.

The invariant web, enforced in all four directions:

1. every env read of a ``CRIMP_TPU_*`` name in the scan set (Python AST:
   ``os.environ[...]`` / ``os.environ.get`` / ``os.getenv``; shell: any
   ``$CRIMP_TPU_*`` / ``${CRIMP_TPU_*...}`` expansion) names a knob
   declared in ``crimp_tpu.knobs.REGISTRY``;
2. Python reads of CRIMP_TPU names happen ONLY inside crimp_tpu/knobs.py
   (everything else goes through the registry accessors);
3. every registered knob has a ``CRIMP_TPU_*`` row in docs/tools.md;
4. every registered knob with a ``numeric_key`` has that key pinned in
   the ``_numeric_mode`` fingerprint dict of ops/resumable.py — numeric
   modes that are not fingerprinted can silently mix chunks computed
   under different kernels into one resumable store.

Checks 3 and 4 read the doc/fingerprint files directly (they may sit
outside the scanned paths), so deleting a tools.md row or a fingerprint
key fails the gate even when only ``crimp_tpu/`` is scanned.
"""

from __future__ import annotations

import ast
import re

from crimp_tpu.analysis.callgraph import dotted
from crimp_tpu.analysis.core import Config, Finding, SourceFile

ENV_NAME_RE = re.compile(r"CRIMP_TPU_[A-Z0-9_]+")
# shell expansions only — a mention in a comment or log string is not a read
SHELL_READ_RE = re.compile(r"\$\{?(CRIMP_TPU_[A-Z0-9_]+)")


def _env_read_name(node: ast.AST) -> tuple[str, int] | None:
    """(env var name, lineno) when this AST node reads an environment
    variable with a literal name."""
    key = None
    if isinstance(node, ast.Subscript):  # os.environ["X"]
        if dotted(node.value) == "os.environ":
            key = node.slice
    elif isinstance(node, ast.Call):
        path = dotted(node.func)
        if path in ("os.environ.get", "os.getenv") and node.args:
            key = node.args[0]
    if (key is not None and isinstance(key, ast.Constant)
            and isinstance(key.value, str)):
        return key.value, node.lineno
    return None


def rule_gl003(cfg: Config, sources: dict[str, SourceFile],
               project) -> list[Finding]:
    registry = cfg.resolved_registry()
    out: list[Finding] = []

    # 1 + 2: env reads in the scan set
    for rel, src in sources.items():
        if src.is_python and src.tree is not None:
            for node in ast.walk(src.tree):
                hit = _env_read_name(node)
                if hit is None or not hit[0].startswith("CRIMP_TPU_"):
                    continue
                name, line = hit
                if name not in registry:
                    out.append(Finding(
                        "GL003", rel, line,
                        f"env read of unregistered knob {name} — declare it "
                        "in crimp_tpu/knobs.py REGISTRY (docs/analysis.md)"))
                elif rel != cfg.knobs_rel and not rel.endswith("/" + cfg.knobs_rel):
                    out.append(Finding(
                        "GL003", rel, line,
                        f"direct os.environ read of {name} outside "
                        f"{cfg.knobs_rel} — use the crimp_tpu.knobs accessors "
                        "so parsing and registration stay uniform"))
        elif rel.endswith(".sh"):
            for i, text in enumerate(src.text.splitlines(), start=1):
                code = text.split("#", 1)[0]
                for m in SHELL_READ_RE.finditer(code):
                    if m.group(1) not in registry:
                        out.append(Finding(
                            "GL003", rel, i,
                            f"shell read of unregistered knob {m.group(1)} — "
                            "declare it in crimp_tpu/knobs.py REGISTRY"))

    # 3: docs/tools.md coverage
    tools_md = cfg.resolved_tools_md()
    tools_rel = _rel(tools_md, cfg)
    try:
        documented = set(ENV_NAME_RE.findall(tools_md.read_text()))
    except OSError:
        documented = None
        out.append(Finding("GL003", tools_rel, 1,
                           f"cannot read {tools_md} to check knob docs"))
    if documented is not None:
        for name in sorted(registry):
            if name not in documented:
                out.append(Finding(
                    "GL003", tools_rel, 1,
                    f"registered knob {name} has no row in the docs/tools.md "
                    "environment-variable table"))

    # 4: numeric_mode fingerprint coverage
    resumable = cfg.resolved_resumable()
    res_rel = _rel(resumable, cfg)
    keys = _numeric_mode_keys(resumable)
    if keys is None:
        out.append(Finding(
            "GL003", res_rel, 1,
            f"could not locate the _numeric_mode fingerprint dict in "
            f"{resumable} — numeric-affecting knobs cannot be verified"))
    else:
        for name in sorted(registry):
            k = registry[name]
            if k.numeric and k.numeric_key not in keys:
                out.append(Finding(
                    "GL003", res_rel, 1,
                    f"numeric-affecting knob {name} expects fingerprint key "
                    f"{k.numeric_key!r} in the resumable numeric_mode dict, "
                    "which only has "
                    f"{sorted(keys)} — resumed stores could mix numeric modes"))
    return out


def _rel(path, cfg: Config) -> str:
    try:
        return path.relative_to(cfg.root).as_posix()
    except ValueError:
        return path.as_posix()


def _numeric_mode_keys(path) -> set[str] | None:
    """String keys of the ``*_numeric_mode = {...}`` dict literal."""
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Dict):
            continue
        for tgt in node.targets:
            name = tgt.attr if isinstance(tgt, ast.Attribute) else (
                tgt.id if isinstance(tgt, ast.Name) else "")
            if name.endswith("_numeric_mode") or name == "numeric_mode":
                return {k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return None
