"""SARIF 2.1.0 rendering for graftlint reports.

SARIF (Static Analysis Results Interchange Format) is the lingua franca
code-scanning UIs ingest — GitHub code scanning, VS Code SARIF viewers,
CI annotators. ``render_sarif`` turns a :class:`~.core.Report` into a
single-run SARIF document; waived findings are carried as suppressed
results (``suppressions[].kind = "inSource"`` with the waiver reason as
the justification) rather than dropped, so a scanning UI can show the
waiver inventory next to the live findings.

``validate_minimal`` is a hand-rolled structural check of the subset of
the SARIF schema this module emits — the repo vendors no jsonschema
dependency, and the repo-gate test needs *some* executable definition of
"valid SARIF" to pin the output against.
"""

from __future__ import annotations

import json
import pathlib

from crimp_tpu.analysis.core import RULES, Report

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_sarif(report: Report, root=None) -> dict:
    """One SARIF ``run`` for the whole report.

    ``root`` (when given) becomes the ``PROJECT_ROOT`` uriBaseId so
    result locations stay root-relative — the same paths the text
    renderer and the baseline use.
    """
    rule_ids = sorted(RULES)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in sorted(report.findings,
                    key=lambda f: (f.path, f.line, f.rule)):
        result = {
            "ruleId": f.rule,
            "ruleIndex": rule_index.get(f.rule, -1),
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "PROJECT_ROOT",
                    },
                    "region": {"startLine": max(1, int(f.line))},
                },
            }],
        }
        if f.waived:
            result["suppressions"] = [{
                "kind": "inSource",
                "justification": f.reason,
            }]
        results.append(result)
    run: dict = {
        "tool": {
            "driver": {
                "name": "graftlint",
                "rules": [
                    {
                        "id": rid,
                        "shortDescription": {"text": RULES[rid]},
                    }
                    for rid in rule_ids
                ],
            },
        },
        "results": results,
    }
    if root is not None:
        run["originalUriBaseIds"] = {
            "PROJECT_ROOT": {
                "uri": pathlib.Path(root).resolve().as_uri() + "/",
            },
        }
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }


def render_sarif_text(report: Report, root=None) -> str:
    return json.dumps(render_sarif(report, root), indent=2, sort_keys=True)


def validate_minimal(doc) -> list[str]:
    """Structural problems with a SARIF document (empty list = valid).

    Covers the required spine of SARIF 2.1.0 as this module emits it:
    top-level version/runs, tool.driver.name, per-result ruleId +
    message.text + physical locations with positive startLine, and
    well-formed suppressions.
    """
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("version") != SARIF_VERSION:
        problems.append(f"version must be {SARIF_VERSION!r}")
    runs = doc.get("runs")
    if not isinstance(runs, list) or not runs:
        return problems + ["runs must be a non-empty array"]
    for i, run in enumerate(runs):
        where = f"runs[{i}]"
        if not isinstance(run, dict):
            problems.append(f"{where} is not an object")
            continue
        driver = (run.get("tool") or {}).get("driver") \
            if isinstance(run.get("tool"), dict) else None
        if not isinstance(driver, dict) or not driver.get("name"):
            problems.append(f"{where}.tool.driver.name is required")
        rules = (driver or {}).get("rules", [])
        rule_ids = {r.get("id") for r in rules if isinstance(r, dict)}
        results = run.get("results")
        if not isinstance(results, list):
            problems.append(f"{where}.results must be an array")
            continue
        for j, res in enumerate(results):
            rwhere = f"{where}.results[{j}]"
            if not isinstance(res, dict):
                problems.append(f"{rwhere} is not an object")
                continue
            if not res.get("ruleId"):
                problems.append(f"{rwhere}.ruleId is required")
            elif rule_ids and res["ruleId"] not in rule_ids:
                problems.append(
                    f"{rwhere}.ruleId {res['ruleId']!r} not in driver rules")
            msg = res.get("message")
            if not isinstance(msg, dict) or not isinstance(
                    msg.get("text"), str) or not msg["text"]:
                problems.append(f"{rwhere}.message.text is required")
            for k, loc in enumerate(res.get("locations", [])):
                lwhere = f"{rwhere}.locations[{k}]"
                phys = loc.get("physicalLocation") \
                    if isinstance(loc, dict) else None
                art = (phys or {}).get("artifactLocation") \
                    if isinstance(phys, dict) else None
                if not isinstance(art, dict) or not art.get("uri"):
                    problems.append(
                        f"{lwhere}.physicalLocation.artifactLocation.uri "
                        "is required")
                region = (phys or {}).get("region") \
                    if isinstance(phys, dict) else None
                if region is not None:
                    start = region.get("startLine") \
                        if isinstance(region, dict) else None
                    if not isinstance(start, int) or start < 1:
                        problems.append(
                            f"{lwhere}.physicalLocation.region.startLine "
                            "must be a positive integer")
            for k, sup in enumerate(res.get("suppressions", [])):
                if not isinstance(sup, dict) or not sup.get("kind"):
                    problems.append(
                        f"{rwhere}.suppressions[{k}].kind is required")
    return problems
