"""graftlint CLI: ``python -m crimp_tpu.analysis [paths...]``.

Exit codes: 0 = clean (or nothing new vs --baseline), 1 = unwaived
findings, 2 = usage / I-O error. ``--write-baseline`` records today's
unwaived findings so future runs with ``--baseline`` fail only on NEW
findings (ratchet mode for incremental adoption); re-writing an existing
baseline refuses to *grow* it unless ``--allow-growth`` is passed — the
ratchet only ever tightens by default.

``--changed-only`` scopes the *report* to files git considers changed.
The analysis itself always runs over the full tree: the cross-layer
rules (GL003 knob web, GL008 thread reachability, GL009/GL010 contract
webs) need whole-program facts, so scoping the scan would silently
weaken them. Only the displayed/failing findings are filtered.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys

from crimp_tpu.analysis import engine, sarif
from crimp_tpu.analysis.core import (
    RULES,
    Config,
    collect_files,
    load_baseline,
    load_source,
    new_findings,
    save_baseline,
)

DEFAULT_PATHS = ("crimp_tpu", "scripts", "bench.py")


def find_root(start: pathlib.Path) -> pathlib.Path:
    """Nearest ancestor carrying pyproject.toml (the repo root the GL003
    cross-checks are anchored to), else the start directory."""
    for cand in [start, *start.parents]:
        if (cand / "pyproject.toml").exists():
            return cand
    return start


def changed_paths(root: pathlib.Path) -> set[str]:
    """Root-relative posix paths git reports as changed (staged,
    unstaged, or untracked). Raises CalledProcessError/OSError on a
    broken git invocation — the caller turns that into exit 2."""
    out = subprocess.run(
        ["git", "-C", str(root), "status", "--porcelain"],
        check=True, capture_output=True, text=True).stdout
    changed: set[str] = set()
    for line in out.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        # a rename is "R  old -> new"; the new path is the live one
        if " -> " in path:
            path = path.split(" -> ", 1)[1]
        changed.add(path.strip().strip('"'))
    return changed


def waiver_inventory(cfg: Config) -> list[tuple[str, str, int, str]]:
    """Every waiver in the scan set as (rule, rel, line, reason) rows,
    sorted by rule then location — the generated table docs/analysis.md
    embeds."""
    rows: list[tuple[str, str, int, str]] = []
    for f in collect_files(cfg.paths, cfg.root):
        src = load_source(f, cfg.root)
        for w in src.line_waivers.values():
            for rule in sorted(w.rules):
                rows.append((rule, src.rel, w.line, w.reason))
        for rule, w in sorted(src.file_waivers.items()):
            rows.append((rule, src.rel, w.line, w.reason))
    return sorted(set(rows))


def render_waiver_table(rows: list[tuple[str, str, int, str]]) -> str:
    lines = ["| Rule | Site | Reason |", "|---|---|---|"]
    for rule, rel, line, reason in rows:
        lines.append(f"| {rule} | `{rel}:{line}` | {reason} |")
    lines.append(f"\n{len(rows)} waivers.")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m crimp_tpu.analysis",
        description="graftlint: trace-discipline, knob-registry and "
                    "parity-invariant static analyzer for crimp_tpu.")
    p.add_argument("paths", nargs="*", help="files/directories to scan "
                   f"(default: {' '.join(DEFAULT_PATHS)} under the repo root)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--root", type=pathlib.Path, default=None,
                   help="repo root (default: nearest ancestor with pyproject.toml)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset, e.g. GL001,GL003")
    p.add_argument("--baseline", type=pathlib.Path, default=None,
                   help="fail only on findings absent from this baseline file")
    p.add_argument("--write-baseline", type=pathlib.Path, default=None,
                   help="record current unwaived findings and exit 0")
    p.add_argument("--allow-growth", action="store_true",
                   help="let --write-baseline add finding keys to an "
                        "existing baseline (refused by default: the "
                        "ratchet only tightens)")
    p.add_argument("--changed-only", action="store_true",
                   help="report only findings in git-changed files (the "
                        "analysis still scans the full tree — cross-layer "
                        "rules need whole-program facts)")
    p.add_argument("--show-waived", action="store_true",
                   help="include waived findings in text output")
    p.add_argument("--waivers", action="store_true",
                   help="print the waiver inventory as a markdown table "
                        "and exit")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0
    root = (args.root or find_root(pathlib.Path.cwd())).resolve()
    raw_paths = args.paths or [p for p in DEFAULT_PATHS if (root / p).exists()]
    cfg = Config(
        root=root,
        paths=[pathlib.Path(p) for p in raw_paths],
        rules=tuple(r.strip() for r in args.rules.split(",")) if args.rules else None,
    )
    if args.waivers:
        try:
            print(render_waiver_table(waiver_inventory(cfg)))
        except FileNotFoundError as exc:
            print(f"graftlint: {exc}", file=sys.stderr)
            return 2
        return 0
    try:
        report = engine.run(cfg)
    except FileNotFoundError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline is not None:
        if args.write_baseline.exists() and not args.allow_growth:
            try:
                prior = load_baseline(args.write_baseline)
            except (OSError, ValueError) as exc:
                print(f"graftlint: bad baseline: {exc}", file=sys.stderr)
                return 2
            grown = {f.key for f in report.unwaived} - prior
            if grown:
                print(f"graftlint: refusing to grow baseline "
                      f"{args.write_baseline} by {len(grown)} new finding "
                      f"key{'s' if len(grown) != 1 else ''} (pass "
                      "--allow-growth to accept new debt)", file=sys.stderr)
                for key in sorted(grown):
                    print(f"  + {key}", file=sys.stderr)
                return 2
        save_baseline(report, args.write_baseline)
        print(f"graftlint: wrote baseline with {len(report.unwaived)} "
              f"finding keys to {args.write_baseline}")
        return 0

    failing = report.unwaived
    if args.baseline is not None:
        try:
            failing = new_findings(report, load_baseline(args.baseline))
        except (OSError, ValueError) as exc:
            print(f"graftlint: bad baseline: {exc}", file=sys.stderr)
            return 2

    scope_note = ""
    if args.changed_only:
        try:
            changed = changed_paths(root)
        except (OSError, subprocess.CalledProcessError) as exc:
            print(f"graftlint: --changed-only needs a working git checkout: "
                  f"{exc}", file=sys.stderr)
            return 2
        failing = [f for f in failing if f.path in changed]
        scope_note = f" (changed-only: {len(changed)} changed files)"

    if args.format == "sarif":
        shown = report
        if args.changed_only:
            from crimp_tpu.analysis.core import Report
            shown = Report(
                findings=[f for f in report.findings if f.path in changed],
                files_scanned=report.files_scanned)
        print(sarif.render_sarif_text(shown, root))
    elif args.format == "json":
        doc = report.to_dict()
        doc["new_findings"] = [f.to_dict() for f in failing]
        print(json.dumps(doc, indent=2))
    else:
        print(report.render_text(show_waived=args.show_waived))
        if args.baseline is not None:
            print(f"graftlint: {len(failing)} new vs baseline")
        if scope_note:
            print(f"graftlint: {len(failing)} failing{scope_note}")
    return 1 if failing else 0
