"""graftlint CLI: ``python -m crimp_tpu.analysis [paths...]``.

Exit codes: 0 = clean (or nothing new vs --baseline), 1 = unwaived
findings, 2 = usage / I-O error. ``--write-baseline`` records today's
unwaived findings so future runs with ``--baseline`` fail only on NEW
findings (ratchet mode for incremental adoption).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from crimp_tpu.analysis import engine
from crimp_tpu.analysis.core import (
    RULES,
    Config,
    load_baseline,
    new_findings,
    save_baseline,
)

DEFAULT_PATHS = ("crimp_tpu", "scripts", "bench.py")


def find_root(start: pathlib.Path) -> pathlib.Path:
    """Nearest ancestor carrying pyproject.toml (the repo root the GL003
    cross-checks are anchored to), else the start directory."""
    for cand in [start, *start.parents]:
        if (cand / "pyproject.toml").exists():
            return cand
    return start


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m crimp_tpu.analysis",
        description="graftlint: trace-discipline, knob-registry and "
                    "parity-invariant static analyzer for crimp_tpu.")
    p.add_argument("paths", nargs="*", help="files/directories to scan "
                   f"(default: {' '.join(DEFAULT_PATHS)} under the repo root)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--root", type=pathlib.Path, default=None,
                   help="repo root (default: nearest ancestor with pyproject.toml)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset, e.g. GL001,GL003")
    p.add_argument("--baseline", type=pathlib.Path, default=None,
                   help="fail only on findings absent from this baseline file")
    p.add_argument("--write-baseline", type=pathlib.Path, default=None,
                   help="record current unwaived findings and exit 0")
    p.add_argument("--show-waived", action="store_true",
                   help="include waived findings in text output")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0
    root = (args.root or find_root(pathlib.Path.cwd())).resolve()
    raw_paths = args.paths or [p for p in DEFAULT_PATHS if (root / p).exists()]
    cfg = Config(
        root=root,
        paths=[pathlib.Path(p) for p in raw_paths],
        rules=tuple(r.strip() for r in args.rules.split(",")) if args.rules else None,
    )
    try:
        report = engine.run(cfg)
    except FileNotFoundError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline is not None:
        save_baseline(report, args.write_baseline)
        print(f"graftlint: wrote baseline with {len(report.unwaived)} "
              f"finding keys to {args.write_baseline}")
        return 0

    failing = report.unwaived
    if args.baseline is not None:
        try:
            failing = new_findings(report, load_baseline(args.baseline))
        except (OSError, ValueError) as exc:
            print(f"graftlint: bad baseline: {exc}", file=sys.stderr)
            return 2

    if args.format == "json":
        doc = report.to_dict()
        doc["new_findings"] = [f.to_dict() for f in failing]
        print(json.dumps(doc, indent=2))
    else:
        print(report.render_text(show_waived=args.show_waived))
        if args.baseline is not None:
            print(f"graftlint: {len(failing)} new vs baseline")
    return 1 if failing else 0
