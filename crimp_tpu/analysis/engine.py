"""graftlint engine: wire sources -> call graph -> rules -> report."""

from __future__ import annotations

from crimp_tpu.analysis import knobcheck, rules
from crimp_tpu.analysis.callgraph import Project
from crimp_tpu.analysis.core import (
    Config,
    Report,
    SourceFile,
    apply_waivers,
    collect_files,
    load_source,
)

RULE_FUNCS = {
    "GL001": rules.rule_gl001,
    "GL002": rules.rule_gl002,
    "GL003": knobcheck.rule_gl003,
    "GL004": rules.rule_gl004,
    "GL005": rules.rule_gl005,
    "GL006": rules.rule_gl006,
    "GL007": rules.rule_gl007,
    "GL008": rules.rule_gl008,
    "GL009": rules.rule_gl009,
    "GL010": rules.rule_gl010,
}


def run(cfg: Config) -> Report:
    files = collect_files(cfg.paths, cfg.root)
    sources: dict[str, SourceFile] = {}
    for f in files:
        src = load_source(f, cfg.root)
        sources[src.rel] = src
    project = Project({rel: s.tree for rel, s in sources.items()
                       if s.is_python and s.tree is not None})
    findings = []
    for rule, fn in RULE_FUNCS.items():
        if cfg.rule_enabled(rule):
            findings.extend(fn(cfg, sources, project))
    findings = apply_waivers(findings, sources)
    return Report(findings=findings, files_scanned=len(sources))
