"""graftlint facts layer: typed cross-file facts for the contract web.

GL001–GL007 are (mostly) per-file properties. The v2 rules — GL008
concurrency discipline, GL009 resilience contract web, GL010 telemetry-
surface drift — need *whole-program* facts: who spawns threads, which
module globals are mutated under which locks, where `LADDERS` /
`FAULT_POINTS` literals live versus their `record_degradation()` /
`fire()` call sites, and which obs counter/gauge names are emitted
where. This module extracts those facts once per analysis run, from
plain ASTs only (same contract as the rest of graftlint: no imports of
checked modules, no jax).

Extraction is deliberately conservative, mirroring the call graph's
philosophy: a string argument that is not a literal (or an f-string /
two-armed conditional of literals) is recorded as *dynamic* — rules
validate what they can read and never guess at runtime values. An
unresolvable thread target adds no reachability edge, so it can hide a
violation but never invent one.
"""

from __future__ import annotations

import ast
import dataclasses

from crimp_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleIndex,
    Project,
    call_tail,
    dotted,
    iter_body_nodes,
)

# module-level ``NAME = threading.X()`` declarations recognized as locks
LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

# method calls that mutate their receiver in place
MUTATING_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
}


@dataclasses.dataclass(frozen=True)
class MetricEmit:
    """One obs ``counter_add`` / ``gauge_set`` / ``beat`` call site."""

    kind: str  # "counter" | "gauge" | "beat"
    name: str | None  # literal name/label; None when dynamic
    prefix: str | None  # static f-string prefix when dynamic
    rel: str
    line: int


@dataclasses.dataclass(frozen=True)
class DegradationSite:
    """One ``record_degradation(engine, rung, ...)`` call site; a non-
    literal engine/rung is recorded as None (dynamic, not validated)."""

    engine: str | None
    rung: str | None
    rel: str
    line: int


@dataclasses.dataclass(frozen=True)
class FireSite:
    """One ``fire(point)`` fault-injection call site."""

    point: str | None  # None = dynamic argument
    rel: str
    line: int


@dataclasses.dataclass(frozen=True)
class ThreadSpawn:
    """A ``threading.Thread(target=f)`` or ``<executor>.submit(f, ...)``
    site. ``target`` is the resolved callable when name resolution
    succeeds — the seed of GL008's off-main-thread reachability."""

    api: str  # "Thread" | "submit"
    rel: str
    line: int
    target: FunctionInfo | None


@dataclasses.dataclass(frozen=True)
class GlobalMutation:
    """A mutation of a module-level name inside a function body, with
    the set of declared locks held (via lexically enclosing ``with``)
    at the mutation site."""

    name: str
    how: str  # "assign" | "augassign" | "subscript" | "delete" | "method:<m>" | "attribute"
    func: str  # enclosing function qualname
    rel: str
    line: int
    locks_held: frozenset[str]


@dataclasses.dataclass
class ModuleFacts:
    rel: str
    locks: dict[str, int] = dataclasses.field(default_factory=dict)
    tls: set[str] = dataclasses.field(default_factory=set)
    module_globals: dict[str, int] = dataclasses.field(default_factory=dict)
    mutations: list[GlobalMutation] = dataclasses.field(default_factory=list)
    spawns: list[ThreadSpawn] = dataclasses.field(default_factory=list)
    degradations: list[DegradationSite] = dataclasses.field(default_factory=list)
    fires: list[FireSite] = dataclasses.field(default_factory=list)
    metrics: list[MetricEmit] = dataclasses.field(default_factory=list)
    # LADDERS = {"engine": ("rung0", ...)} literal, when this module has one
    ladders: dict[str, tuple[str, ...]] | None = None
    ladders_line: int = 0
    # FAULT_POINTS = frozenset({...}) literal
    fault_points: frozenset[str] | None = None
    fault_points_line: int = 0
    # METRICS = {"metric": {"field": ...}} ledger literal: name -> field tail
    ledger_metrics: dict[str, str] | None = None
    ledger_metrics_line: int = 0


def _root_name(node: ast.AST) -> str | None:
    """The root Name of an attribute/subscript chain: ``_RUN.counters[k]``
    -> ``_RUN``. Mutating through any such chain mutates the root
    module global."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _str_args(node: ast.AST) -> list[str]:
    """Constant-string elements of a tuple/list/set/frozenset(...) literal."""
    if isinstance(node, ast.Call) and call_tail(node.func) in ("frozenset", "set", "tuple"):
        if not node.args:
            return []
        node = node.args[0]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            s = _const_str(el)
            if s is not None:
                out.append(s)
        return out
    return []


def _joined_prefix(node: ast.JoinedStr) -> str:
    """Leading constant text of an f-string — the static family prefix of
    a dynamic metric name like f"degraded_{engine}_{rung}"."""
    prefix = ""
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            prefix += part.value
        else:
            break
    return prefix


def _metric_name_args(node: ast.AST) -> list[tuple[str | None, str | None]]:
    """(literal name, dynamic prefix) alternatives for one metric-name
    argument. A two-armed conditional of literals yields both arms."""
    s = _const_str(node)
    if s is not None:
        return [(s, None)]
    if isinstance(node, ast.JoinedStr):
        return [(None, _joined_prefix(node))]
    if isinstance(node, ast.IfExp):
        return _metric_name_args(node.body) + _metric_name_args(node.orelse)
    return [(None, None)]


def _module_level_names(tree: ast.Module) -> dict[str, int]:
    """Names bound by top-level Assign/AnnAssign — the module globals
    whose mutation GL008 polices."""
    out: dict[str, int] = {}
    for stmt in tree.body:
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                out.setdefault(t.id, stmt.lineno)
            elif isinstance(t, ast.Tuple):
                for el in t.elts:
                    if isinstance(el, ast.Name):
                        out.setdefault(el.id, stmt.lineno)
    return out


def _bound_names(target: ast.AST):
    """Names BOUND by an assignment/for/with-as target. A Subscript or
    Attribute target mutates an existing object — it binds nothing, so
    it must not shadow a module global here."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from _bound_names(el)
    elif isinstance(target, ast.Starred):
        yield from _bound_names(target.value)


def _local_bindings(fn_node: ast.AST) -> set[str]:
    """Names bound locally in a function body (params, assignments, for
    targets, with-as, conservative set). A module global shadowed by a
    local binding is not a global mutation."""
    out: set[str] = set()
    if not isinstance(fn_node, ast.Lambda):
        a = fn_node.args
        for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
            out.add(arg.arg)
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
    for node in iter_body_nodes(fn_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                out.update(_bound_names(t))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            out.update(_bound_names(node.target))
        elif isinstance(node, ast.For):
            out.update(_bound_names(node.target))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    out.update(_bound_names(item.optional_vars))
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
    return out


class _ModuleExtractor:
    """One pass over a module: locks, globals, mutations-with-held-locks,
    thread spawns, resilience/telemetry call sites, registry literals."""

    def __init__(self, project: Project, mod: ModuleIndex):
        self.project = project
        self.mod = mod
        self.facts = ModuleFacts(rel=mod.rel)
        self._extract_module_level()
        self._extract_calls()
        for info in list(mod.functions.values()):
            if isinstance(info.node, ast.Lambda):
                continue
            self._extract_mutations(info)

    # -- module level --------------------------------------------------------

    def _extract_module_level(self) -> None:
        f = self.facts
        f.module_globals = _module_level_names(self.mod.tree)
        for stmt in self.mod.tree.body:
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            target = stmt.targets[0]
            if not isinstance(target, ast.Name):
                continue
            name, value = target.id, stmt.value
            if isinstance(value, ast.Call):
                tail = call_tail(value.func)
                if tail in LOCK_FACTORIES:
                    f.locks[name] = stmt.lineno
                elif tail == "local" and (dotted(value.func) or "").startswith("threading"):
                    f.tls.add(name)
            if name == "LADDERS" and isinstance(value, ast.Dict):
                ladders: dict[str, tuple[str, ...]] = {}
                for k, v in zip(value.keys, value.values):
                    ks = _const_str(k) if k is not None else None
                    if ks is not None:
                        ladders[ks] = tuple(_str_args(v))
                if ladders:
                    f.ladders, f.ladders_line = ladders, stmt.lineno
            elif name == "FAULT_POINTS":
                points = _str_args(value)
                if points:
                    f.fault_points = frozenset(points)
                    f.fault_points_line = stmt.lineno
            elif name == "METRICS" and isinstance(value, ast.Dict):
                metrics: dict[str, str] = {}
                for k, v in zip(value.keys, value.values):
                    ks = _const_str(k) if k is not None else None
                    if ks is None or not isinstance(v, ast.Dict):
                        continue
                    field = ""
                    for fk, fv in zip(v.keys, v.values):
                        if fk is not None and _const_str(fk) == "field":
                            parts = _str_args(fv)
                            field = parts[-1] if parts else (_const_str(fv) or "")
                    if field:
                        metrics[ks] = field
                if metrics:
                    f.ledger_metrics = metrics
                    f.ledger_metrics_line = stmt.lineno

    # -- call sites (any scope) ----------------------------------------------

    def _extract_calls(self) -> None:
        extractor = self
        mod, facts = self.mod, self.facts
        scope_stack: list[str] = []

        class V(ast.NodeVisitor):
            def _scoped(self, node):
                scope_stack.append(getattr(node, "name", f"<lambda@{node.lineno}>"))
                self.generic_visit(node)
                scope_stack.pop()

            visit_FunctionDef = _scoped
            visit_AsyncFunctionDef = _scoped
            visit_ClassDef = _scoped

            def visit_Call(self, node: ast.Call):
                extractor._one_call(node, ".".join(scope_stack) or None)
                self.generic_visit(node)

        V().visit(mod.tree)

    def _one_call(self, node: ast.Call, scope: str | None) -> None:
        facts, mod = self.facts, self.mod
        tail = call_tail(node.func)
        if tail in ("counter_add", "gauge_set") and node.args:
            kind = "counter" if tail == "counter_add" else "gauge"
            for name, prefix in _metric_name_args(node.args[0]):
                facts.metrics.append(MetricEmit(
                    kind=kind, name=name, prefix=prefix,
                    rel=mod.rel, line=node.lineno))
        elif tail == "beat":
            label = None
            for kw in node.keywords:
                if kw.arg == "label":
                    label = kw.value
            if label is not None:
                for name, prefix in _metric_name_args(label):
                    facts.metrics.append(MetricEmit(
                        kind="beat", name=name, prefix=prefix,
                        rel=mod.rel, line=node.lineno))
        elif tail == "record_degradation" and node.args:
            engine = _const_str(node.args[0])
            rung = _const_str(node.args[1]) if len(node.args) > 1 else None
            facts.degradations.append(DegradationSite(
                engine=engine, rung=rung, rel=mod.rel, line=node.lineno))
        elif tail == "fire" and node.args:
            facts.fires.append(FireSite(
                point=_const_str(node.args[0]), rel=mod.rel, line=node.lineno))
        elif tail == "Thread":
            path = dotted(node.func) or tail
            if path in ("Thread", "threading.Thread"):
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = self.project.resolve_callable(mod, scope, kw.value)
                facts.spawns.append(ThreadSpawn(
                    api="Thread", rel=mod.rel, line=node.lineno, target=target))
        elif tail == "submit" and isinstance(node.func, ast.Attribute) and node.args:
            target = self.project.resolve_callable(mod, scope, node.args[0])
            facts.spawns.append(ThreadSpawn(
                api="submit", rel=mod.rel, line=node.lineno, target=target))

    # -- mutations with held locks -------------------------------------------

    def _lock_names_in_with(self, node: ast.With | ast.AsyncWith) -> set[str]:
        """Declared-lock names acquired by a with statement. A bare Name
        must be one of this module's locks; ``mod._LOCK`` resolves through
        the import alias to a lock declared in another scanned module."""
        held: set[str] = set()
        for item in node.items:
            expr = item.context_expr
            # ``with lock:`` and ``with lock.acquire_timeout():`` style
            if isinstance(expr, ast.Call):
                expr = expr.func if not isinstance(expr.func, ast.Attribute) \
                    else expr.func.value
            if isinstance(expr, ast.Name) and expr.id in self.facts.locks:
                held.add(expr.id)
            elif isinstance(expr, ast.Attribute):
                path = dotted(expr)
                if path is None:
                    continue
                head, _, rest = path.partition(".")
                target = self.mod.module_aliases.get(head)
                if target is not None and "." not in rest:
                    tmod = self.project.by_dotted.get(target)
                    if tmod is not None:
                        tfacts = _module_locks(tmod)
                        if rest in tfacts:
                            held.add(f"{target}.{rest}")
        return held

    def _extract_mutations(self, info: FunctionInfo) -> None:
        fn_node = info.node
        globals_declared: set[str] = set()
        for n in iter_body_nodes(fn_node):
            if isinstance(n, ast.Global):
                globals_declared.update(n.names)
        local = _local_bindings(fn_node) - globals_declared
        mod_globals = set(self.facts.module_globals) | globals_declared
        tls = self.facts.tls

        def is_global(name: str) -> bool:
            return name in mod_globals and name not in local and name not in tls

        def record(name: str, how: str, line: int, held: frozenset[str]) -> None:
            self.facts.mutations.append(GlobalMutation(
                name=name, how=how, func=info.qualname, rel=self.mod.rel,
                line=line, locks_held=held))

        def check(node: ast.AST, held: frozenset[str]) -> None:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in globals_declared \
                            and t.id not in tls:
                        record(t.id, "assign", node.lineno, held)
                    elif isinstance(t, (ast.Subscript, ast.Attribute)):
                        root = _root_name(t)
                        how = "subscript" if isinstance(t, ast.Subscript) else "attribute"
                        if root is not None and is_global(root):
                            record(root, how, node.lineno, held)
            elif isinstance(node, ast.AugAssign):
                t = node.target
                if isinstance(t, ast.Name) and t.id in globals_declared and t.id not in tls:
                    record(t.id, "augassign", node.lineno, held)
                elif isinstance(t, (ast.Subscript, ast.Attribute)):
                    root = _root_name(t)
                    if root is not None and is_global(root):
                        record(root, "subscript", node.lineno, held)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        root = _root_name(t)
                        if root is not None and is_global(root):
                            record(root, "delete", node.lineno, held)
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                root = _root_name(node.func.value)
                if root is not None and node.func.attr in MUTATING_METHODS \
                        and is_global(root):
                    record(root, f"method:{node.func.attr}", node.lineno, held)

        def walk(node: ast.AST, held: frozenset[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return  # nested defs are their own FunctionInfos
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held | self._lock_names_in_with(node)
                for item in node.items:
                    walk(item.context_expr, held)
                for b in node.body:
                    walk(b, inner)
                return
            check(node, held)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        roots = [fn_node.body] if isinstance(fn_node, ast.Lambda) else fn_node.body
        for stmt in (roots if isinstance(roots, list) else [roots]):
            walk(stmt, frozenset())


_LOCKS_CACHE_ATTR = "_graftlint_locks"


def _module_locks(mod: ModuleIndex) -> dict[str, int]:
    """Module-level lock declarations of one module (cached on the index
    — cross-module ``with other._LOCK:`` resolution needs it before that
    module's own facts exist)."""
    cached = getattr(mod, _LOCKS_CACHE_ATTR, None)
    if cached is None:
        cached = {}
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call) \
                    and call_tail(stmt.value.func) in LOCK_FACTORIES:
                cached[stmt.targets[0].id] = stmt.lineno
        setattr(mod, _LOCKS_CACHE_ATTR, cached)
    return cached


class ProjectFacts:
    """Facts for every scanned python module + cross-module closures."""

    def __init__(self, project: Project):
        self.project = project
        self.modules: dict[str, ModuleFacts] = {}
        for rel, mod in project.modules.items():
            self.modules[rel] = _ModuleExtractor(project, mod).facts
        self._thread_closure: set[str] | None = None

    # -- aggregates ----------------------------------------------------------

    def ladders(self) -> tuple[dict[str, tuple[str, ...]], str, int]:
        """Merged LADDERS literals: (engine -> rungs, defining rel, line).
        Empty dict when no scanned module declares one."""
        merged: dict[str, tuple[str, ...]] = {}
        rel, line = "", 0
        for f in self.modules.values():
            if f.ladders:
                merged.update(f.ladders)
                rel, line = f.rel, f.ladders_line
        return merged, rel, line

    def fault_points(self) -> tuple[frozenset[str], str, int]:
        points: set[str] = set()
        rel, line = "", 0
        for f in self.modules.values():
            if f.fault_points:
                points |= f.fault_points
                rel, line = f.rel, f.fault_points_line
        return frozenset(points), rel, line

    def ledger_metrics(self) -> tuple[dict[str, str], str, int]:
        merged: dict[str, str] = {}
        rel, line = "", 0
        for f in self.modules.values():
            if f.ledger_metrics:
                merged.update(f.ledger_metrics)
                rel, line = f.rel, f.ledger_metrics_line
        return merged, rel, line

    def degradation_sites(self) -> list[DegradationSite]:
        return [s for f in self.modules.values() for s in f.degradations]

    def fire_sites(self) -> list[FireSite]:
        return [s for f in self.modules.values() for s in f.fires]

    def metric_emits(self) -> list[MetricEmit]:
        return [m for f in self.modules.values() for m in f.metrics]

    # -- thread reachability -------------------------------------------------

    def thread_reachable(self) -> set[str]:
        """Labels (``module:qualname``) of every function reachable from a
        resolved thread target / executor callback — code that runs off
        the main thread. BFS over the same conservative call graph GL001
        uses: an unresolved edge can hide reachability, never invent it."""
        if self._thread_closure is not None:
            return self._thread_closure
        seeds: list[FunctionInfo] = []
        for f in self.modules.values():
            for spawn in f.spawns:
                if spawn.target is not None:
                    seeds.append(spawn.target)
        seen: set[str] = set()
        queue = list(seeds)
        while queue:
            cur = queue.pop()
            if cur.label in seen:
                continue
            seen.add(cur.label)
            for callee in self.project._callees(cur):
                if callee.label not in seen:
                    queue.append(callee)
        self._thread_closure = seen
        return seen

    def spawn_origin(self, label: str) -> str:
        """Human-readable seed description for a thread-reachable label
        (best-effort; used only in finding messages)."""
        for f in self.modules.values():
            for spawn in f.spawns:
                if spawn.target is not None and spawn.target.label == label:
                    return f"{spawn.api} at {f.rel}"
        return "thread callback"


_FACTS_CACHE_ATTR = "_graftlint_facts"


def for_project(project: Project) -> ProjectFacts:
    """The (cached) facts for one Project — GL008/GL009/GL010 share one
    extraction pass."""
    cached = getattr(project, _FACTS_CACHE_ATTR, None)
    if cached is None:
        cached = ProjectFacts(project)
        setattr(project, _FACTS_CACHE_ATTR, cached)
    return cached
