"""graftlint — crimp_tpu's trace-discipline / knob-registry / parity
static analyzer.

Usage::

    python -m crimp_tpu.analysis [--format json|text|sarif] [paths...]
    python -m crimp_tpu.analysis --changed-only      # git-diff scoped report
    python -m crimp_tpu.analysis --waivers           # waiver inventory table
    bash scripts/lint.sh [--changed] [--sarif]

Rules (docs/analysis.md has the full contract + waiver syntax):

- GL001 trace purity (env/time/random/file-I/O unreachable from traced code)
- GL002 host-sync hazards (tracer coercions / branching)
- GL003 knob-registry consistency (crimp_tpu/knobs.py <-> reads <-> docs
  <-> resumable numeric_mode fingerprint)
- GL004 dtype discipline (longdouble confined to host-side anchor modules)
- GL005 order-sensitive reductions in sharded/parity-pinned modules
- GL006 failure-domain discipline (bare except / swallowed errors outside
  sanctioned telemetry guards)
- GL007 sharding-registry discipline (mesh-axis names vs parallel registry)
- GL008 concurrency discipline (thread-reachable module-global mutations
  must hold a declared lock; lock-declaring modules guard every mutation)
- GL009 resilience contract web (LADDERS/FAULT_POINTS <-> degradation and
  fire sites <-> firing tests <-> docs/robustness.md)
- GL010 telemetry-surface drift (obs counter/gauge literals <->
  docs/observability.md <-> consumers; ledger METRICS <-> bench.py)

GL008-GL010 are powered by the cross-file facts layer
(:mod:`crimp_tpu.analysis.facts`); SARIF 2.1.0 output lives in
:mod:`crimp_tpu.analysis.sarif`.

The tier-1 gate (tests/test_analysis.py) runs the full rule set over
crimp_tpu/, scripts/ and bench.py and requires zero unwaived findings.
"""

from crimp_tpu.analysis import facts, sarif
from crimp_tpu.analysis.cli import main
from crimp_tpu.analysis.core import RULES, Config, Finding, Report
from crimp_tpu.analysis.engine import run

__all__ = ["main", "run", "Config", "Finding", "Report", "RULES",
           "facts", "sarif"]
