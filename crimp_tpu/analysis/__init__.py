"""graftlint — crimp_tpu's trace-discipline / knob-registry / parity
static analyzer.

Usage::

    python -m crimp_tpu.analysis [--format json|text] [paths...]
    bash scripts/lint.sh

Rules (docs/analysis.md has the full contract + waiver syntax):

- GL001 trace purity (env/time/random/file-I/O unreachable from traced code)
- GL002 host-sync hazards (tracer coercions / branching)
- GL003 knob-registry consistency (crimp_tpu/knobs.py <-> reads <-> docs
  <-> resumable numeric_mode fingerprint)
- GL004 dtype discipline (longdouble confined to host-side anchor modules)
- GL005 order-sensitive reductions in sharded/parity-pinned modules

The tier-1 gate (tests/test_analysis.py) runs the full rule set over
crimp_tpu/, scripts/ and bench.py and requires zero unwaived findings.
"""

from crimp_tpu.analysis.cli import main
from crimp_tpu.analysis.core import RULES, Config, Finding, Report
from crimp_tpu.analysis.engine import run

__all__ = ["main", "run", "Config", "Finding", "Report", "RULES"]
