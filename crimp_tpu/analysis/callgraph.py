"""graftlint call graph: which functions run under a JAX trace?

Builds a project-wide, name-resolved call graph from plain ASTs and
computes the set of functions reachable from *trace entry points* —
functions handed to ``jax.jit``/``pjit``/``vmap``/``pmap``/``shard_map``/
``pallas_call``/``checkpoint``/``remat`` (as decorators, ``partial``
decorators, or call-site wrappers) and the body/branch callables of
``lax.scan``/``while_loop``/``fori_loop``/``cond``/``switch``.

Resolution is deliberately name-based and conservative:

- ``Name`` callees resolve through the lexical scope chain (nested defs,
  enclosing class, module level), then ``from x import y`` aliases;
- ``mod.f`` attribute callees resolve when ``mod`` is an import alias of
  a module inside the scan set;
- ``self.m`` resolves to methods of the lexically enclosing class.

Anything unresolvable (external libraries, dynamic dispatch) simply adds
no edge — the rules that consume the graph (GL001/GL002) look at call
*sites* inside traced bodies for the banned host operations, so an
unresolved edge can hide a transitive violation but never invent one.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

# wrapper name -> positions of the traced callable argument(s).
# Unambiguous jax-only names accept bare-Name or any-attribute forms;
# AMBIGUOUS_TAILS additionally require a lax-ish qualifier (``jax.lax.scan``,
# ``lax.scan``) or a recorded ``from jax.lax import scan``.
TRACE_WRAPPERS: dict[str, tuple[int, ...]] = {
    "jit": (0,),
    "pjit": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2, 3),
    "switch": (1,),
}
AMBIGUOUS_TAILS = {"scan", "while_loop", "fori_loop", "cond", "switch"}

# Parameter annotations / default types treated as static configuration
# (never tracers) by the GL002 heuristics.
STATIC_ANNOTATIONS = {"int", "bool", "str", "float"}


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_tail(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


@dataclasses.dataclass
class FunctionInfo:
    module: str  # root-relative posix path
    qualname: str  # e.g. "Class.method" / "outer.<locals>.inner"
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    name: str
    lineno: int
    params: tuple[str, ...]
    static_params: frozenset[str]  # annotation/default-typed config params
    class_name: str | None = None
    entry_reason: str | None = None  # set when this is a trace entry point
    traced_via: str | None = None  # entry (or caller) that makes it traced

    @property
    def label(self) -> str:
        return f"{self.module}:{self.qualname}"


def _param_info(node: ast.AST) -> tuple[tuple[str, ...], frozenset[str]]:
    """(param names, statically-typed param names) for a def/lambda."""
    if isinstance(node, ast.Lambda):
        a = node.args
    else:
        a = node.args
    args = [*a.posonlyargs, *a.args, *a.kwonlyargs]
    names = tuple(arg.arg for arg in args)
    static: set[str] = set(arg.arg for arg in a.kwonlyargs)
    for arg in args:
        ann = arg.annotation
        if ann is not None:
            text = dotted(ann) or (ann.value if isinstance(ann, ast.Constant)
                                   and isinstance(ann.value, str) else "")
            base = str(text).split("|")[0].strip().split(".")[-1]
            if base in STATIC_ANNOTATIONS:
                static.add(arg.arg)
    defaults = list(a.defaults)
    if defaults and not isinstance(node, ast.Lambda):
        for arg, dflt in zip(args[len(args) - len(a.kwonlyargs) - len(defaults):],
                             defaults):
            if isinstance(dflt, ast.Constant) and isinstance(
                    dflt.value, (bool, int, str, type(None))):
                static.add(arg.arg)
    for arg, dflt in zip(a.kwonlyargs, a.kw_defaults):
        if isinstance(dflt, ast.Constant):
            static.add(arg.arg)
    return names, frozenset(static)


class ModuleIndex:
    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.functions: dict[str, FunctionInfo] = {}
        # import alias -> dotted module name ("search" -> "crimp_tpu.ops.search")
        self.module_aliases: dict[str, str] = {}
        # from-import: local name -> (dotted module, original name)
        self.from_imports: dict[str, tuple[str, str]] = {}
        self._index()

    def _index(self) -> None:
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: list[tuple[str, str]] = []  # (kind, name)

            def _qual(self, name: str) -> str:
                parts = [n for _, n in self.stack] + [name]
                return ".".join(parts)

            def visit_Import(self, node: ast.Import) -> None:
                for alias in node.names:
                    mod.module_aliases[alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0])

            def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
                if node.module is None or node.level:
                    return
                for alias in node.names:
                    mod.from_imports[alias.asname or alias.name] = (
                        node.module, alias.name)
                    # ``from crimp_tpu.parallel import mesh`` binds a module
                    mod.module_aliases.setdefault(
                        alias.asname or alias.name,
                        f"{node.module}.{alias.name}")

            def _def(self, node) -> None:
                params, static = _param_info(node)
                cls = self.stack[-1][1] if self.stack and self.stack[-1][0] == "class" else None
                qual = self._qual(node.name)
                mod.functions[qual] = FunctionInfo(
                    module=mod.rel, qualname=qual, node=node, name=node.name,
                    lineno=node.lineno, params=params, static_params=static,
                    class_name=cls)
                self.stack.append(("func", node.name))
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _def
            visit_AsyncFunctionDef = _def

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                self.stack.append(("class", node.name))
                self.generic_visit(node)
                self.stack.pop()

        V().visit(self.tree)

    def lambda_info(self, node: ast.Lambda) -> FunctionInfo:
        qual = f"<lambda@{node.lineno}>"
        if qual not in self.functions:
            params, static = _param_info(node)
            self.functions[qual] = FunctionInfo(
                module=self.rel, qualname=qual, node=node, name=qual,
                lineno=node.lineno, params=params, static_params=static)
        return self.functions[qual]


def _module_dotted_name(rel: str) -> str:
    p = pathlib.PurePosixPath(rel)
    parts = list(p.with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class Project:
    """All scanned modules + the traced-reachability closure."""

    def __init__(self, sources: dict[str, ast.Module]):
        self.modules: dict[str, ModuleIndex] = {
            rel: ModuleIndex(rel, tree) for rel, tree in sources.items()}
        self.by_dotted: dict[str, ModuleIndex] = {
            _module_dotted_name(rel): m for rel, m in self.modules.items()}
        self._traced: dict[str, FunctionInfo] | None = None

    # -- name resolution ----------------------------------------------------

    def _resolve_in_module(self, mod: ModuleIndex, scope: str | None,
                           name: str) -> FunctionInfo | None:
        # lexical chain: nested defs of the scope, enclosing scopes, module
        prefixes: list[str] = []
        if scope:
            parts = scope.split(".")
            prefixes = [".".join(parts[:i]) for i in range(len(parts), 0, -1)]
        for prefix in prefixes:
            hit = mod.functions.get(f"{prefix}.{name}")
            if hit is not None:
                return hit
        hit = mod.functions.get(name)
        if hit is not None:
            return hit
        imp = mod.from_imports.get(name)
        if imp is not None:
            target_mod = self.by_dotted.get(imp[0])
            if target_mod is not None:
                return target_mod.functions.get(imp[1])
        return None

    def resolve_callable(self, mod: ModuleIndex, scope: str | None,
                         node: ast.AST) -> FunctionInfo | None:
        """Resolve a callable-valued expression to a scanned function."""
        # partial(f, ...) and functools.partial(f, ...): unwrap
        if isinstance(node, ast.Call) and call_tail(node.func) == "partial" and node.args:
            return self.resolve_callable(mod, scope, node.args[0])
        if isinstance(node, ast.Lambda):
            return mod.lambda_info(node)
        if isinstance(node, ast.Name):
            return self._resolve_in_module(mod, scope, node.id)
        if isinstance(node, ast.Attribute):
            path = dotted(node)
            if path is None:
                return None
            head, _, rest = path.partition(".")
            if head == "self" and scope:
                # method on the lexically enclosing class
                cls_prefix = scope.split(".")[0]
                return mod.functions.get(f"{cls_prefix}.{rest}")
            target = mod.module_aliases.get(head)
            if target is not None:
                target_mod = self.by_dotted.get(target)
                if target_mod is None and "." in path:
                    # ``import crimp_tpu.ops.search as s`` style full path
                    target_mod = self.by_dotted.get(
                        ".".join([target] + rest.split(".")[:-1]))
                    rest = rest.split(".")[-1]
                if target_mod is not None:
                    return target_mod.functions.get(rest)
        return None

    # -- trace entries ------------------------------------------------------

    def _is_wrapper_call(self, mod: ModuleIndex, node: ast.Call) -> str | None:
        tail = call_tail(node.func)
        if tail not in TRACE_WRAPPERS:
            return None
        if tail in AMBIGUOUS_TAILS:
            path = dotted(node.func) or ""
            parts = path.split(".")
            qualified = len(parts) > 1 and parts[-2] in ("lax", "pl", "pallas")
            imported = mod.from_imports.get(tail, ("", ""))[0].endswith("lax")
            if not (qualified or imported):
                return None
        return tail

    def _entry_points(self) -> list[tuple[FunctionInfo, str]]:
        entries: list[tuple[FunctionInfo, str]] = []
        for mod in self.modules.values():
            # decorator-based entries
            for info in list(mod.functions.values()):
                node = info.node
                if isinstance(node, ast.Lambda):
                    continue
                for dec in node.decorator_list:
                    reason = self._decorator_entry(mod, dec, info)
                    if reason:
                        entries.append((info, reason))
                        self._absorb_static_argnames(dec, info)
                        break
            # call-site entries: jit(f), lax.scan(body, ...), vmap(f)...
            scope_stack: list[str] = []
            project = self

            class W(ast.NodeVisitor):
                def _scoped(self, node):
                    scope_stack.append(node.name if hasattr(node, "name")
                                       else f"<lambda@{node.lineno}>")
                    self.generic_visit(node)
                    scope_stack.pop()

                visit_FunctionDef = _scoped
                visit_AsyncFunctionDef = _scoped

                def visit_ClassDef(self, node):
                    self._scoped(node)

                def visit_Call(self, node: ast.Call):
                    tail = project._is_wrapper_call(mod, node)
                    if tail is not None:
                        scope = ".".join(scope_stack) or None
                        for pos in TRACE_WRAPPERS[tail]:
                            if pos >= len(node.args):
                                continue
                            arg = node.args[pos]
                            cands = (arg.elts if isinstance(
                                arg, (ast.List, ast.Tuple)) else [arg])
                            for cand in cands:
                                info = project.resolve_callable(mod, scope, cand)
                                if info is not None:
                                    entries.append((
                                        info, f"passed to {tail}() at "
                                              f"{mod.rel}:{node.lineno}"))
                                    project._absorb_static_argnames(node, info)
                    self.generic_visit(node)

            W().visit(mod.tree)
        return entries

    def _decorator_entry(self, mod: ModuleIndex, dec: ast.AST,
                         info: FunctionInfo) -> str | None:
        tail = call_tail(dec)
        if tail in TRACE_WRAPPERS and tail not in AMBIGUOUS_TAILS:
            return f"@{tail}"
        if isinstance(dec, ast.Call):
            ctail = call_tail(dec.func)
            if ctail in TRACE_WRAPPERS and ctail not in AMBIGUOUS_TAILS:
                return f"@{ctail}(...)"
            if ctail == "partial" and dec.args:
                inner = call_tail(dec.args[0])
                if inner in TRACE_WRAPPERS and inner not in AMBIGUOUS_TAILS:
                    return f"@partial({inner}, ...)"
        return None

    def _absorb_static_argnames(self, call: ast.AST, info: FunctionInfo) -> None:
        """Fold jit static_argnames/static_argnums literals into the
        function's static-parameter set (GL002 must not flag branching on
        a static argument)."""
        if not isinstance(call, ast.Call):
            return
        static = set(info.static_params)
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        static.add(el.value)
            elif kw.arg == "static_argnums":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        if 0 <= el.value < len(info.params):
                            static.add(info.params[el.value])
        info.static_params = frozenset(static)

    # -- reachability --------------------------------------------------------

    def _callees(self, info: FunctionInfo) -> list[FunctionInfo]:
        mod = self.modules[info.module]
        scope = info.qualname if not info.qualname.startswith("<lambda") else None
        out: list[FunctionInfo] = []
        for node in iter_body_nodes(info.node):
            if isinstance(node, ast.Call):
                target = self.resolve_callable(mod, scope, node.func)
                if target is not None:
                    out.append(target)
                # callables passed onward (e.g. body funcs) also traced
                tail = self._is_wrapper_call(mod, node)
                if tail is not None:
                    for pos in TRACE_WRAPPERS[tail]:
                        if pos < len(node.args):
                            t = self.resolve_callable(mod, scope, node.args[pos])
                            if t is not None:
                                out.append(t)
        return out

    def traced_functions(self) -> dict[str, FunctionInfo]:
        """label -> FunctionInfo for every function reachable from a trace
        entry point (the entry points included)."""
        if self._traced is not None:
            return self._traced
        traced: dict[str, FunctionInfo] = {}
        queue: list[FunctionInfo] = []
        for info, reason in self._entry_points():
            if info.label not in traced:
                info.entry_reason = reason
                info.traced_via = f"entry: {reason}"
                traced[info.label] = info
                queue.append(info)
        while queue:
            cur = queue.pop()
            for callee in self._callees(cur):
                if callee.label not in traced:
                    callee.traced_via = f"called from {cur.label}"
                    traced[callee.label] = callee
                    queue.append(callee)
        self._traced = traced
        return traced


def iter_body_nodes(func_node: ast.AST):
    """Walk a function body WITHOUT descending into nested function /
    lambda definitions (those are separate FunctionInfos — a nested def
    only matters if it is itself traced-reachable)."""
    if isinstance(func_node, ast.Lambda):
        roots = [func_node.body]
    else:
        roots = list(func_node.body)
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)
