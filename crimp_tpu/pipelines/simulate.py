"""Synthetic modulated event-list generator (test fixture / fake backend).

Behavioral parity with the reference simulator
(simulatemodulatedlc.py:19-96): a sinusoidal profile sampled in phase bins,
Poisson counts per bin, uniform rotation assignment, plus Poisson uniform
background; returns event times with and without background. Also used by
bench.py to build merged-dataset surrogates (the reference's large merged
FITS file is absent from the snapshot)."""

from __future__ import annotations

import numpy as np


def simulate_modulated_lc(
    freq: float,
    srcrate: float = 1.0,
    exposure: float = 10000.0,
    pulsedfraction: float = 0.2,
    bgrrate: float = 0.05,
    resolution: float = 0.073,
    nbrPhaseBins: int | None = None,
    rng: np.random.RandomState | None = None,
) -> dict:
    """Simulate a sinusoidally modulated light curve.

    Returns {'assigned_t_wBgr', 'assigned_t_nobgr'}: sorted event times (s)
    with and without background.
    """
    if rng is None:
        rng = np.random.RandomState()

    n_rotations = int(exposure * freq)
    exposure_norm = n_rotations / freq

    amp = np.sqrt(2) * pulsedfraction * srcrate
    if amp > srcrate:
        raise ValueError("RMS pulsed fraction cannot be larger than 1/sqrt(2)")

    if nbrPhaseBins is None:
        nbrPhaseBins = int(np.floor(1 / (resolution * freq)))
    if nbrPhaseBins < 4:
        raise ValueError(
            "nbrPhaseBins is very small; increase time resolution or set it manually"
        )

    bin_phases = np.linspace(0, 1, nbrPhaseBins, endpoint=False)
    # peak mid-cycle (cos shifted by pi), counts per phase bin over the run
    expected = (srcrate + amp * np.cos(2 * np.pi * bin_phases + np.pi)) * (
        exposure_norm / nbrPhaseBins
    )

    chunks = []
    for k in range(nbrPhaseBins):
        n_events = rng.poisson(expected[k])
        rotation = rng.uniform(0, n_rotations, n_events).astype(int)
        within = rng.uniform(bin_phases[k], bin_phases[k] + 1 / nbrPhaseBins, n_events)
        chunks.append(rotation + within)
    phases = np.sort(np.concatenate(chunks)) if chunks else np.zeros(0)

    t_nobgr = np.sort(phases / freq)
    n_bkg = rng.poisson(bgrrate * exposure_norm)
    t_bkg = np.sort(rng.uniform(0, exposure_norm, n_bkg))
    t_wbgr = np.sort(np.concatenate([t_nobgr, t_bkg]))
    return {"assigned_t_wBgr": t_wbgr, "assigned_t_nobgr": t_nobgr}


# Reference-named alias (simulatemodulatedlc.py:19).
simulatemodulatedlc = simulate_modulated_lc


def main(argv=None):
    """Module-level entry (parity with simulatemodulatedlc.py:99; the
    reference does not register this as a console script either)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="Simulate a sinusoidally modulated event list"
    )
    parser.add_argument("freq", help="Signal frequency (Hz)", type=float)
    parser.add_argument("-sr", "--srcrate", help="Source count rate (cts/s), default=1", type=float, default=1.0)
    parser.add_argument("-ex", "--exposure", help="Exposure (s), default=10000", type=float, default=10000.0)
    parser.add_argument("-pf", "--pulsedfraction", help="RMS pulsed fraction, default=0.2", type=float, default=0.2)
    parser.add_argument("-bg", "--bgrrate", help="Background rate (cts/s), default=0.05", type=float, default=0.05)
    parser.add_argument("-rs", "--resolution", help="Time resolution (s), default=0.073", type=float, default=0.073)
    parser.add_argument("-nb", "--nbrPhaseBins", help="Phase bins (default: from resolution)", type=int, default=None)
    parser.add_argument("-of", "--outputfile", help="Output .txt stem (time column)", type=str, default="simulatedlc")
    args = parser.parse_args(argv)

    sim = simulate_modulated_lc(
        args.freq, args.srcrate, args.exposure, args.pulsedfraction, args.bgrrate,
        args.resolution, args.nbrPhaseBins,
    )
    np.savetxt(args.outputfile + ".txt", sim["assigned_t_wBgr"])
    print(
        f"Simulated {len(sim['assigned_t_nobgr'])} source + "
        f"{len(sim['assigned_t_wBgr']) - len(sim['assigned_t_nobgr'])} background events "
        f"-> {args.outputfile}.txt"
    )


if __name__ == "__main__":
    main()
