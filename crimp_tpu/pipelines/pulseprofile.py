"""Template pulse-profile construction pipeline (CLI: templatepulseprofile).

Workflow parity with the reference (pulseprofile.py:57-247): fold events ->
binned profile -> binned-ML template fit (Fourier / von Mises / Cauchy),
optional warm start from an initial template with per-parameter vary flags
and fixPhases, chi2 reporting, RMS pulsed flux/fraction with Monte-Carlo
uncertainties, PDF plot, and the template .txt artifact.

TPU re-design: the fold runs through the anchored f64 kernel, the fit is a
jitted BFGS (ops.templatefit), and the 1000-draw Monte-Carlo error loop
(pulseprofile.py:629-664) collapses into one vectorized draw."""

from __future__ import annotations

import numpy as np

from crimp_tpu.io.events import EventFile
from crimp_tpu.io import template as template_io
from crimp_tpu.models import profiles
from crimp_tpu.ops.anchored import fold_chunked
from crimp_tpu.ops.binprofile import bin_phases
from crimp_tpu.ops.templatefit import fit_binned_template
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PulseProfileFromEventFile:
    """Build and model a pulse profile starting from an event file."""

    def __init__(self, evtFile: str, timMod: str, eneLow: float = 0.5, eneHigh: float = 10.0, nbrBins: int = 30):
        self.evtFile = evtFile
        self.timMod = timMod
        self.eneLow = eneLow
        self.eneHigh = eneHigh
        self.nbrBins = nbrBins

    def createpulseprofile(self) -> dict:
        """Fold the event file and bin it into a count-rate profile."""
        ef = EventFile(self.evtFile)
        _, gti = ef.read_gti()
        livetime = np.sum(gti[:, 1] - gti[:, 0]) * 86400.0
        df = ef.build_time_energy_df().filtenergy(self.eneLow, self.eneHigh).time_energy_df
        folded = fold_chunked(df["TIME"].to_numpy(), self.timMod)
        binned = bin_phases(folded, self.nbrBins)
        per_bin_exp = livetime / self.nbrBins
        return {
            "ppBins": binned["ppBins"],
            "ppBinsRange": binned["ppBinsRange"],
            "countRate": binned["ctsBins"] / per_bin_exp,
            "countRateErr": binned["ctsBinsErr"] / per_bin_exp,
        }

    def fitpulseprofile(
        self,
        ppmodel: str = "fourier",
        nbrComp: int = 2,
        initTemplateMod: str | None = None,
        fixPhases: bool = False,
        figure: str | None = None,
        templateFile: str | None = None,
        calcPulsedFraction: bool = False,
    ):
        """Fit the binned profile to a template model.

        Returns (fitResultsDict, bestFitModel, pulsedProperties)."""
        logger.info(
            "\n Running fitpulseprofile: evtFile=%s timMod=%s eneLow=%s eneHigh=%s "
            "nbrBins=%s ppmodel=%s nbrComp=%s initTemplateMod=%s fixPhases=%s "
            "figure=%s templateFile=%s calcPulsedFraction=%s",
            self.evtFile, self.timMod, self.eneLow, self.eneHigh, self.nbrBins,
            ppmodel, nbrComp, initTemplateMod, fixPhases, figure, templateFile,
            calcPulsedFraction,
        )
        pulse_profile = self.createpulseprofile()
        rate = pulse_profile["countRate"]
        err = pulse_profile["countRateErr"]

        if initTemplateMod is not None:
            tpl_dict = template_io.read_template(initTemplateMod)
            kind = tpl_dict["model"]
            nbrComp = tpl_dict["nbrComp"]
            _, init = profiles.from_template(tpl_dict)
            vary = [tpl_dict["norm"]["vary"]]
            vary += [tpl_dict[f"amp_{k}"]["vary"] for k in range(1, nbrComp + 1)]
            if kind == profiles.FOURIER:
                loc_vary = [
                    (False if fixPhases else tpl_dict[f"ph_{k}"]["vary"])
                    for k in range(1, nbrComp + 1)
                ]
                wid_vary = [False] * nbrComp
            else:
                loc_vary = [
                    (False if fixPhases else tpl_dict[f"cen_{k}"]["vary"])
                    for k in range(1, nbrComp + 1)
                ]
                wid_vary = [tpl_dict[f"wid_{k}"]["vary"] for k in range(1, nbrComp + 1)]
            vary = np.array(vary + loc_vary + wid_vary, dtype=bool)
        else:
            kind = ppmodel.casefold()
            if kind not in profiles.KINDS:
                raise ValueError(
                    f"model {ppmodel!r} is not supported; fourier, vonmises, cauchy are supported"
                )
            import jax.numpy as jnp

            if kind == profiles.FOURIER:
                init = profiles.ProfileParams(
                    norm=jnp.asarray(float(np.mean(rate))),
                    amp=jnp.full(nbrComp, 0.1 * float(np.mean(rate))),
                    loc=jnp.zeros(nbrComp),
                    wid=jnp.zeros(nbrComp),
                    ph_shift=jnp.asarray(0.0),
                    amp_shift=jnp.asarray(1.0),
                )
            else:
                init = profiles.ProfileParams(
                    norm=jnp.asarray(float(np.min(rate))),
                    amp=jnp.full(nbrComp, 1.3 * float(np.min(rate))),
                    loc=jnp.full(nbrComp, np.pi),
                    wid=jnp.ones(nbrComp),
                    ph_shift=jnp.asarray(0.0),
                    amp_shift=jnp.asarray(1.0),
                )
            vary = None

        bins = pulse_profile["ppBins"].copy()
        if kind in (profiles.CAUCHY, profiles.VONMISES):
            bins = bins * 2 * np.pi  # radians convention for these families
            pulse_profile["ppBins"] = bins

        best, model, stats = fit_binned_template(kind, init, bins, rate, err, vary)
        fit_results = profiles.to_theta(kind, best)
        fit_results.pop("phShift", None)
        fit_results.pop("ampShift", None)
        fit_results.update(stats)
        fit_results["model"] = kind
        print(
            "Template {} best fit statistics\n chi2 = {} for dof = {}\n Reduced chi2 = {}".format(
                kind, stats["chi2"], stats["dof"], stats["redchi2"]
            )
        )

        if templateFile is not None:
            template_io.write_template(templateFile, fit_results)
            logger.info("\n Created best fit template file : %s.txt", templateFile)

        if calcPulsedFraction and kind == profiles.FOURIER:
            pulsed = calc_pulse_properties(pulse_profile, nbrComp)
            pulsed.update(calc_pulse_properties_uncertainty(pulse_profile, nbrComp))
        else:
            if calcPulsedFraction:
                logger.warning(
                    "Cannot calculate rms pulsed fraction for %s; returning None", kind
                )
            pulsed = None

        if figure is not None:
            plot_pulse_profile(pulse_profile, outFile=figure, fittedModel=model)

        return fit_results, model, pulsed


def calc_pulse_properties(pulse_profile: dict, nbrComp: int) -> dict:
    """RMS pulsed flux / fraction and per-harmonic pulsed fluxes.

    Value parity with the reference (pulseprofile.py:594-626), including its
    quirk of subtracting the *squares* of the Fourier-coefficient variances.
    """
    bins = pulse_profile["ppBins"]
    rate = pulse_profile["countRate"]
    err = pulse_profile["countRateErr"]
    N = len(bins)
    k = np.arange(1, nbrComp + 1)[:, None]
    cos_k = np.cos(k * 2 * np.pi * bins[None, :])
    sin_k = np.sin(k * 2 * np.pi * bins[None, :])
    ak = (rate[None, :] * cos_k).sum(axis=1) / N
    bk = (rate[None, :] * sin_k).sum(axis=1) / N
    sak = (err[None, :] ** 2 * cos_k**2).sum(axis=1) / N**2
    sbk = (err[None, :] ** 2 * sin_k**2).sum(axis=1) / N**2
    per_harm = (ak**2 + bk**2) - (sak**2 + sbk**2)
    frms = np.sqrt(per_harm.sum() * 2)
    return {
        "pulsedFlux": frms,
        "pulsedFraction": frms / np.mean(rate),
        "harmonicPulsedFractions": per_harm,
    }


def calc_pulse_properties_uncertainty(
    pulse_profile: dict, nbrComp: int, n_simulations: int = 1000, rng=None
) -> dict:
    """Monte-Carlo uncertainties on the pulsed properties — the reference's
    1000-iteration loop (pulseprofile.py:629-664) as one vectorized draw."""
    if rng is None:
        rng = np.random.RandomState()
    rate = pulse_profile["countRate"]
    err = pulse_profile["countRateErr"]
    draws = rng.normal(rate[None, :], err[None, :], size=(n_simulations, len(rate)))
    fluxes = np.empty(n_simulations)
    fractions = np.empty(n_simulations)
    harmonics = np.empty((n_simulations, nbrComp))
    sim_profile = dict(pulse_profile)
    for i in range(n_simulations):  # cheap: nbins-sized numpy ops
        sim_profile["countRate"] = draws[i]
        props = calc_pulse_properties(sim_profile, nbrComp)
        fluxes[i] = props["pulsedFlux"]
        fractions[i] = props["pulsedFraction"]
        harmonics[i] = props["harmonicPulsedFractions"]
    return {
        "pulsedFluxErr": float(np.std(fluxes)),
        "pulsedFractionErr": float(np.std(fractions)),
        "harmonicPulsedFractionsErr": np.std(harmonics, axis=0),
    }


def plot_pulse_profile(pulse_profile: dict, outFile: str = "pulseprof", fittedModel=None) -> str:
    """Two-cycle pulse-profile plot with optional best-fit overlay."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    bins = pulse_profile["ppBins"]
    rate = pulse_profile["countRate"]
    err = pulse_profile["countRateErr"]
    cycle = 2 * np.pi if np.max(bins) > 1 else 1.0
    bins2 = np.concatenate([bins, bins + cycle])
    rate2 = np.concatenate([rate, rate])
    err2 = np.concatenate([err, err])

    fig, ax = plt.subplots(1, figsize=(6, 4))
    ax.step(bins2, rate2, "k+-", where="mid")
    ax.errorbar(bins2, rate2, yerr=err2, fmt="ok")
    if fittedModel is not None:
        ax.plot(bins2, np.concatenate([fittedModel, fittedModel]), "r-", lw=2)
    ax.set_xlabel("Phase (cycles)")
    ax.set_ylabel("Rate (counts/s)")
    fig.tight_layout()
    path = outFile + ".pdf"
    fig.savefig(path, format="pdf")
    plt.close(fig)
    return path
