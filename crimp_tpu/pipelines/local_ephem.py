"""Local [F0, F1] ephemerides in a sliding window (CLI: localephemerides).

Workflow parity with the reference (get_local_ephem.py:27-265): slide a
window (interval_days, jump_days) over the ToAs, truncating at glitch
epochs and resuming after them; per window, build a minimal 14-key timing
model anchored at the window-mid integer-rotation epoch (TRACK -2), fit
F0/F1 with the ensemble MCMC under span-scaled box priors, record
F0, F1 +/- err and chi2; finally detrend F0 by the global F0+F1 trend and
write the CSV + plot.

TPU re-design (SURVEY §3.5: "windows are independent given glitch
boundaries -> vmap over windows", BASELINE config 4): window DISCOVERY is
data-dependent host logic and stays a host loop, but every window's
1000-step ensemble run executes together in ONE batched device program
(ops.mcmc.ensemble_sample_batch, ToAs padded/masked per window) — the
reference runs one serial emcee per window (get_local_ephem.py:195-198).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from crimp_tpu import obs
from crimp_tpu.io import parfile as parfile_io
from crimp_tpu.io import tim as tim_io
from crimp_tpu.models import timing
from crimp_tpu.ops import deltafold
from crimp_tpu.ops import mcmc as mcmc_ops
from crimp_tpu.ops.ephem import integer_rotation_host
from crimp_tpu.pipelines import fit_utils
from crimp_tpu.pipelines.fit_toas import corner_plot, load_toas_for_fit, plot_residuals
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

FIT_KEYS = ["F0", "F1"]


# Delta-parameterized local model: mu = basis @ theta with the rank-2
# Taylor basis [dt, dt^2/2] (seconds from the window anchor) — a window's
# [dF0, dF1] trial is exactly a rank-2 delta-fold (ops/deltafold.py
# taylor_basis_seconds), so the per-trial model is one small matmul,
# mean-subtracted over valid ToAs, masked for padding. This is the SAME
# masked basis-matmul likelihood the delta-basis MCMC engine uses
# everywhere (ops/mcmc.py), so the windowed batch shares its compiled
# ensemble core with the other pipelines.
_window_log_prob = mcmc_ops.delta_logprob


def _fit_windows_batched(windows: list[dict], steps: int, burn: int, walkers: int,
                         debug_with_plots: bool):
    """One batched ensemble run over all discovered windows; returns the
    per-window posterior summaries in window order."""
    import jax
    import jax.numpy as jnp

    n_max = max(len(w["dt_sec"]) for w in windows)
    W = len(windows)
    dt = np.zeros((W, n_max))
    y = np.zeros((W, n_max))
    err = np.ones((W, n_max))
    mask = np.zeros((W, n_max))
    lo = np.zeros((W, 2))
    hi = np.zeros((W, 2))
    p0 = np.empty((W, walkers, 2))
    for i, w in enumerate(windows):
        n = len(w["dt_sec"])
        dt[i, :n] = w["dt_sec"]
        y[i, :n] = w["phase"]
        err[i, :n] = w["phase_err"]
        mask[i, :n] = 1.0
        lo[i], hi[i] = w["lo"], w["hi"]
        rng = np.random.default_rng(w["seed"])
        for d in range(2):
            p0[i, :, d] = rng.uniform(lo[i, d], hi[i, d], size=walkers)

    data = {
        "basis": jnp.asarray(deltafold.taylor_basis_seconds(dt, 2)),
        "y": jnp.asarray(y), "err": jnp.asarray(err),
        "mask": jnp.asarray(mask), "lo": jnp.asarray(lo), "hi": jnp.asarray(hi),
    }
    chains, lps = mcmc_ops.ensemble_sample_batch(
        mcmc_ops.delta_logprob, jnp.asarray(p0), data, steps, jax.random.PRNGKey(0)
    )
    chains = np.asarray(chains)
    lps = np.asarray(lps)
    out = []
    for i, w in enumerate(windows):
        flat, _, summaries = mcmc_ops.summarize_chain(
            chains[i], lps[i], FIT_KEYS, burn=max(0, burn)
        )
        if debug_with_plots:
            corner_plot(flat, FIT_KEYS, f"corner_interval_{w['seed']}")
        out.append(summaries)
    return out


def generate_local_ephemerides(*args, **kwargs) -> pd.DataFrame:
    """Sliding-window local F0/F1; returns the detrended ephemerides table.

    Flight-recorded as an obs run (``local_ephem``): window discovery and
    the single batched ensemble fit land as stage spans, with a
    windows-fit counter (docs/observability.md).
    """
    with obs.run("local_ephem"):
        return _generate_local_ephemerides_impl(*args, **kwargs)


def _generate_local_ephemerides_impl(
    tim_file: str,
    parfile: str,
    interval_days: float = 90.0,
    jump_days: float = 15.0,
    t_start: float | None = None,
    t_end: float | None = None,
    min_interval: float = 45.0,
    debug_with_plots: bool = False,
    outputfile: str | None = "local_ephemerides",
    ephem_plot: str | None = None,
    clobber: bool = False,
    mcmc_steps: int = 1000,
    mcmc_burn: int = 100,
    mcmc_walkers: int = 24,
) -> pd.DataFrame:
    """Sliding-window local F0/F1; returns the detrended ephemerides table."""
    logger.info(
        "\n Running generate_local_ephemerides: tim_file=%s parfile=%s interval_days=%s "
        "jump_days=%s t_start=%s t_end=%s min_interval=%s outputfile=%s",
        tim_file, parfile, interval_days, jump_days, t_start, t_end, min_interval, outputfile,
    )
    par_values, _, _ = parfile_io.read_timing_model(parfile)
    pepoch_global = par_values["PEPOCH"]
    f0_global = par_values["F0"]
    f1_global = par_values["F1"]
    glitch_epochs = sorted(v for k, v in par_values.items() if k.startswith("GLEP_"))

    toa_df = tim_io.read_tim(tim_file)
    if t_start is None:
        t_start = float(toa_df["pulse_ToA"].min())
    if t_end is None:
        t_end = float(toa_df["pulse_ToA"].max())

    tm = timing.resolve(parfile)
    current_start = t_start
    records = []
    windows_found: list[dict] = []
    eps = 1e-5
    window_counter = 0

    while current_start is not None and current_start < t_end:
        valid = toa_df.loc[toa_df["pulse_ToA"] >= current_start, "pulse_ToA"]
        current_start = float(valid.min()) if not valid.empty else None
        if current_start is None:
            break
        current_end = min(current_start + interval_days, t_end)
        window = toa_df.loc[
            (toa_df["pulse_ToA"] >= current_start) & (toa_df["pulse_ToA"] <= current_end)
        ]
        if window.empty:
            current_start += jump_days
            continue
        current_end = float(window["pulse_ToA"].max())

        crossing_glitch = next(
            (g for g in glitch_epochs if current_start < g < current_end), None
        )
        if crossing_glitch is not None:
            window = window.loc[window["pulse_ToA"] <= crossing_glitch]
            if window.empty:
                current_start = crossing_glitch + eps
                continue
            current_end = float(window["pulse_ToA"].max())

        mid = current_start + (current_end - current_start) / 2
        span_days = current_end - current_start

        if len(window) >= 4 and span_days > min_interval:
            anchor = integer_rotation_host(tm, np.atleast_1d(mid))
            mid_anchor = float(anchor["Tmjd_intRotation"][0])
            f0_mid = float(anchor["freq_intRotation"][0])
            f1_mid = float(anchor["freqdot_intRotation"][0])

            # Minimal local model: PEPOCH at the anchor; F0, F1 free.
            keys13 = ["PEPOCH"] + [f"F{i}" for i in range(13)]
            values = [mid_anchor, f0_mid, f1_mid] + [0.0] * 11
            flags = [0, 1, 1] + [0] * 11
            local_par = {
                k: {"value": np.float64(v), "flag": f}
                for k, v, f in zip(keys13, values, flags)
            }
            local_par["TRACK"] = -2

            span_sec = span_days * 86400.0
            toas_to_fit = load_toas_for_fit(window, local_par)
            y = toas_to_fit["phase"].to_numpy(dtype=float)
            windows_found.append(
                {
                    "seed": window_counter,
                    "mid_anchor": mid_anchor,
                    "span_days": span_days,
                    "local_par": local_par,
                    "toas_to_fit": toas_to_fit,
                    "dt_sec": (toas_to_fit["ToA"].to_numpy(dtype=float) - mid_anchor)
                    * 86400.0,
                    "phase": y,  # already mean-subtracted by load_toas_for_fit
                    "phase_err": toas_to_fit["phase_err_cycle"].to_numpy(dtype=float),
                    "lo": np.array([-100 / span_sec, -100 / span_sec**2]),
                    "hi": np.array([100 / span_sec, 100 / span_sec**2]),
                }
            )
            window_counter += 1

        if crossing_glitch is not None:
            current_start = crossing_glitch + eps
        else:
            current_start += jump_days

    # ---- all windows sample together in one batched device program -------
    obs.counter_add("ephem_windows_fit", len(windows_found))
    with obs.span("ephem_batched_fit", windows=len(windows_found),
                  steps=mcmc_steps, walkers=mcmc_walkers):
        all_summaries = (
            _fit_windows_batched(
                windows_found, mcmc_steps, mcmc_burn, mcmc_walkers, debug_with_plots
            )
            if windows_found
            else []
        )
    for w, summaries in zip(windows_found, all_summaries):
        med_vec = np.array([summaries[k]["median"] for k in FIT_KEYS])
        _, full_dict = fit_utils.inject_free_params(w["local_par"], med_vec, FIT_KEYS)
        post_fit = fit_utils.model_phase_residuals(
            w["toas_to_fit"]["ToA"].to_numpy(), w["local_par"], med_vec, FIT_KEYS
        )
        if debug_with_plots:
            plot_residuals(
                w["toas_to_fit"], post_fit, plotname=f"residuals_interval_{w['seed']}"
            )
        stats = fit_utils.chi2_fit(
            w["toas_to_fit"]["phase"], post_fit, w["toas_to_fit"]["phase_err_cycle"], 2
        )
        records.append(
            {
                "TOA_MJD_ref": w["mid_anchor"],
                "TOA_MJD_ref_err": w["span_days"] / 2.0,
                "F0": full_dict["F0"],
                "F0_err": max(summaries["F0"]["plus"], summaries["F0"]["minus"]),
                "F1": full_dict["F1"],
                "F1_err": max(summaries["F1"]["plus"], summaries["F1"]["minus"]),
                "CHI2R": stats["redchi2"],
                "DOF": stats["dof"],
            }
        )

    if not records:
        logger.warning(
            "No interval made the criteria - decrease min_interval and/or increase "
            "interval_days; returning empty dataframe"
        )
        return pd.DataFrame(records)

    table = pd.DataFrame(records)
    # Detrend F0 by the global linear trend (get_local_ephem.py:247-249).
    trend = f0_global + f1_global * ((table["TOA_MJD_ref"] - pepoch_global) * 86400.0)
    table["F0"] -= trend

    if outputfile is not None:
        table.to_csv(f"{outputfile}.txt", sep="\t", index=True, header=True, mode="w" if clobber else "x")
    if ephem_plot is not None:
        from crimp_tpu.pipelines.plot_local_ephem import plot_local_ephemerides

        plot_local_ephemerides(table, glitch_epochs, ephem_plot)
    return table
