"""Pulse ToA measurement pipeline (CLI: measuretoas) — the main product.

Workflow parity with the reference engine (measureToAs.py:64-251): for each
ToA interval from the interval file, select events, fold with the timing
model, fit the template by unbinned extended maximum likelihood with the
phase shift and normalization free, derive +/-1-sigma likelihood-profile
uncertainties by 2*pi/phShiftRes stepping, compute the per-ToA H-test at
the local ephemeris frequency and the binned-profile chi2, then write
ToAs.txt, the optional .tim file, and the phase-residual plot.

TPU re-design (SURVEY.md §2.4 "backends.xla.toafit"): the per-ToA loop is
gone — every interval is anchored at its own epoch (ops.anchored keeps the
fold under 1e-8 cycles), segments are padded/masked into one batch, and the
entire run (global phase grid + golden refine + vectorized error scans +
batched H-test + binned chi2) executes as a few jitted device programs.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from crimp_tpu import obs
from crimp_tpu.io import template as template_io
from crimp_tpu.io.events import EventFile
from crimp_tpu.models import profiles, timing
from crimp_tpu.ops import anchored, deltafold, search, toafit
from crimp_tpu.ops.ephem import spin_frequency_host
from crimp_tpu.utils.logging import get_logger
from crimp_tpu.utils.profiling import timed, trace

logger = get_logger(__name__)

TOA_COLUMNS = [
    "ToA", "ToA_mid", "ToA_start", "ToA_end", "ToA_lenInt", "ToA_exp",
    "nbr_events", "count_rate", "phShift", "phShift_LL", "phShift_UL",
    "Hpower", "redChi2",
]


def measure_toas(*args, **kwargs) -> pd.DataFrame:
    """Measure ToAs for every interval; returns the ToA table.

    Flight-recorded as an obs run (``measure_toas``) when CRIMP_TPU_OBS
    is on: the anchored-fold / batched-fit / H-test stages land as spans
    in the run manifest, with events-folded / ToAs-fit / padding-waste
    counters from the ops layer (docs/observability.md).
    """
    with obs.run("measure_toas"):
        return _measure_toas_impl(*args, **kwargs)


def _measure_toas_impl(
    evtFile: str,
    timMod: str,
    tempModPP: str,
    toagtifile: str,
    eneLow: float = 0.5,
    eneHigh: float = 10.0,
    toaStart: int = 0,
    toaEnd: int | None = None,
    phShiftRes: int = 1000,
    nbrBins: int = 15,
    varyAmps: bool = False,
    readvaryparam: bool = False,
    brutemin: bool = False,
    plotPPs: bool = False,
    plotLLs: bool = False,
    toaFile: str = "ToAs",
    timFile: str | None = None,
) -> pd.DataFrame:
    """Measure ToAs for every interval; returns the ToA table."""
    logger.info(
        "\n Running measure_toas: evtFile=%s timMod=%s tempModPP=%s toagtifile=%s "
        "eneLow=%s eneHigh=%s toaStart=%s toaEnd=%s phShiftRes=%s nbrBins=%s "
        "varyAmps=%s readvaryparam=%s brutemin=%s toaFile=%s timFile=%s",
        evtFile, timMod, tempModPP, toagtifile, eneLow, eneHigh, toaStart, toaEnd,
        phShiftRes, nbrBins, varyAmps, readvaryparam, brutemin, toaFile, timFile,
    )
    ef = EventFile(evtFile)
    df = ef.build_time_energy_df().filtenergy(eneLow, eneHigh).time_energy_df
    times_all = df["TIME"].to_numpy()

    intervals = pd.read_csv(toagtifile, sep=r"\s+", comment="#")
    if toaEnd is None:
        toaEnd = len(intervals)
    else:
        toaEnd += 1  # inclusive, like the reference CLI
    idx_range = range(toaStart, toaEnd)

    tm = timing.resolve(timMod)
    tpl_dict = template_io.read_template(tempModPP)
    kind, tpl = profiles.from_template(tpl_dict)
    logger.info("\n Using best fit model of template %s to measure ToAs", kind)

    # ---- per-interval event selection + anchored fold --------------------
    starts = intervals["ToA_tstart"].to_numpy()
    ends = intervals["ToA_tend"].to_numpy()
    exposures = intervals["ToA_exposure"].to_numpy()

    idx_list = list(idx_range)
    # one O(n) sortedness check, then every slice call gets the binary-search
    # fast path without re-checking (FITS event lists are time-ordered)
    times_sorted = bool(np.all(np.diff(times_all) >= 0))
    seg_times = toafit.slice_sorted_intervals(
        times_all, starts[idx_list], ends[idx_list], assume_sorted=times_sorted
    )
    for ii, t_seg in zip(idx_list, seg_times):
        if t_seg.size == 0:
            raise ValueError(f"ToA interval {ii} contains no events")

    # One anchor per ToA interval: the fold of every segment is exact, and
    # all segments fold in a SINGLE device call (anchored.fold_segments) so
    # the kernel compiles once regardless of per-interval raggedness.
    seg_sizes = [t.size for t in seg_times]
    with timed("anchored_fold"):
        seg_phase_list, toa_mids = anchored.fold_segments(tm, seg_times)
    fold_info = deltafold.last_fold_info()
    if fold_info.get("mode") in ("cache", "delta"):
        # re-measure under an updated .par reused the fingerprinted fold
        # product (pure hit or B@dp refold) instead of a fresh exact fold
        logger.info("delta-fold engine served the re-measure fold: %s",
                    fold_info)
    if kind in (profiles.CAUCHY, profiles.VONMISES):
        # radians convention for these families (measureToAs.py:195-200)
        seg_phase_list = [p * (2 * np.pi) for p in seg_phase_list]

    phases, masks = toafit.pad_segments(seg_phase_list)

    if readvaryparam:
        # General path: free parameters follow the template 'vary' flags
        # (reference defineinitialfitparam readvaryparam mode); ampShift is
        # appended to the free set when varyAmps is also requested.
        free_idx, free_lo, free_hi, n_free = toafit.free_param_spec(
            kind, tpl_dict, vary_amps=varyAmps
        )
        cfg = toafit.ToAFitConfig(
            kind=kind,
            ph_shift_res=phShiftRes,
            nbins=nbrBins,
            free_idx=free_idx,
            free_lo=free_lo,
            free_hi=free_hi,
            n_free=n_free,
            # all-fixed template: only phShift floats and the norm stays at
            # the template value (reference readvaryparam with no vary flags)
            fix_norm=not free_idx,
        )
    else:
        # ampShift box bounds per family (measureToAs.py:308,461,605)
        amp_lo, amp_hi = {
            profiles.FOURIER: (0.01, 100.0),
            profiles.CAUCHY: (1e-6, 1e6),  # reference: [0, inf)
            profiles.VONMISES: (1e-6, 500.0),
        }[kind]
        cfg = toafit.ToAFitConfig(
            kind=kind,
            ph_shift_res=phShiftRes,
            nbins=nbrBins,
            vary_amps=varyAmps,
            amp_lo=amp_lo,
            amp_hi=amp_hi,
        )
    exp_batch = exposures[toaStart:toaEnd].astype(float)
    size_ratio = max(seg_sizes) / max(min(seg_sizes), 1)
    with trace(), timed("toa_fit_batch"):
        if size_ratio > 4.0:
            # heterogeneous campaign: size-bucketed padding avoids inflating
            # every likelihood sweep to the largest interval's event count
            results = toafit.fit_toas_bucketed(
                kind, tpl, seg_phase_list, exp_batch, cfg
            )
        else:
            # segment axis auto-shards across all local devices (multi-chip
            # hosts run the batch data-parallel; CRIMP_TPU_SHARD=0 opts out)
            results = toafit.fit_toas_batch_auto(
                kind, tpl, phases, masks, exp_batch, cfg
            )
            results = {k: np.asarray(v) for k, v in results.items()}

    # ---- per-ToA H-test at the local ephemeris frequency -----------------
    freqs_mid, _ = spin_frequency_host(tm, toa_mids)
    sec_padded = np.zeros_like(phases)
    sec_masks = np.zeros_like(masks)
    for out_i, t_seg in enumerate(seg_times):
        centered = (t_seg - (t_seg[0] + t_seg[-1]) / 2) * 86400.0
        sec_padded[out_i, : t_seg.size] = centered
        sec_masks[out_i, : t_seg.size] = True
    with timed("per_toa_htest"):
        h_powers = np.asarray(
            search.h_power_segments(sec_padded, sec_masks, freqs_mid, nharm=5)
        )

    # ---- outputs ---------------------------------------------------------
    with open(toaFile + ".txt", "w") as fh:
        fh.write(
            "ToA \t ToA_mid \t ToA_start \t ToA_end \t ToA_lenInt \t ToA_exp \t "
            "nbr_events \t count_rate \t phShift \t phShift_LL \t phShift_UL \t "
            "Hpower \t redChi2\n"
        )
        for out_i, ii in enumerate(idx_range):
            print(f"ToA {ii}")
            fh.write(
                f"{ii}\t{toa_mids[out_i]}\t{starts[ii]}\t{ends[ii]}\t"
                f"{intervals['ToA_lenInt'].iloc[ii]}\t{exposures[ii]}\t"
                f"{intervals['Events'].iloc[ii]}\t{intervals['ct_rate'].iloc[ii]}\t"
                f"{results['phShift'][out_i]}\t{results['phShift_LL'][out_i]}\t"
                f"{results['phShift_UL'][out_i]}\t{h_powers[out_i]}\t"
                f"{results['redChi2'][out_i]}\n"
            )
    logger.info("\n Wrote ToA properties to %s.txt", toaFile)

    if plotLLs or plotPPs:
        _diagnostic_plots(
            kind, tpl, phases, masks, exp_batch, results, cfg, list(idx_range),
            plotPPs=plotPPs, plotLLs=plotLLs,
        )

    if timFile is not None:
        from crimp_tpu.pipelines.tim_tools import phshift_to_timfile

        phshift_to_timfile(toaFile + ".txt", timMod, timFile, tempModPP=tempModPP)
        logger.info("\n Wrote timfile %s.tim", timFile)

    plot_phase_residuals(
        toa_mids, results["phShift"], results["phShift_LL"], results["phShift_UL"],
        outFile=toaFile,
    )
    logger.info("\n Created phase residual plot %s_phaseResiduals.pdf", toaFile)

    return pd.read_csv(toaFile + ".txt", sep=r"\s+", comment="#")


def _diagnostic_plots(kind, tpl, phases, masks, exposures, results, cfg, toa_ids, plotPPs, plotLLs):
    """Optional per-ToA debug plots (profile + likelihood curve)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import jax.numpy as jnp

    from crimp_tpu.ops.binprofile import bin_phases
    from crimp_tpu.ops.toafit import _unflatten_tpl, profile_loglik, shape_at_shifts

    half = np.pi if kind == profiles.FOURIER else 1.5 * np.pi
    for out_i, toa_id in enumerate(toa_ids):
        x = phases[out_i][masks[out_i].astype(bool)]
        exposure = exposures[out_i]
        phi_best = results["phShift"][out_i]
        # per-ToA best-fit template (carries the REFIT shape in
        # readvaryparam mode, where amps/locs/wids may have moved)
        tpl_best = _unflatten_tpl(jnp.asarray(results["theta_best"][out_i]), tpl)
        if plotLLs:
            span = 40 * (2 * np.pi / cfg.ph_shift_res)
            phis = np.linspace(phi_best - span, phi_best + span, 161)
            ll, _ = profile_loglik(kind, tpl, jnp.asarray(x), jnp.ones(len(x), bool), exposure, jnp.asarray(phis), cfg)
            fig, ax = plt.subplots(figsize=(7, 5))
            ax.plot(phis / (2 * np.pi), np.asarray(ll), "k.")
            ax.set_xlabel("Phase (cycles)")
            ax.set_ylabel("Log(L)")
            fig.tight_layout()
            fig.savefig(f"LogL_ToA{toa_id}.pdf", format="pdf")
            plt.close(fig)
        if plotPPs:
            binned = bin_phases(x, cfg.nbins)
            per_bin = exposure / cfg.nbins
            rate = binned["ctsBins"] / per_bin
            err = binned["ctsBinsErr"] / per_bin
            centers = binned["ppBins"]
            # tpl_best already folds norm/ampShift (and any refit shape
            # params) into the template, so only the shape term is added
            model_best = float(tpl_best.norm) + np.asarray(
                shape_at_shifts(kind, tpl_best, jnp.asarray(centers), jnp.asarray([phi_best]))
            )[0]
            model_init = results["norm"][out_i] + np.asarray(
                shape_at_shifts(kind, tpl, jnp.asarray(centers), jnp.asarray([0.0]))
            )[0]
            cycle = 1.0 if kind == profiles.FOURIER else 2 * np.pi
            c2 = np.concatenate([centers, centers + cycle])
            fig, ax = plt.subplots(figsize=(7, 5))
            ax.errorbar(c2, np.tile(rate, 2), yerr=np.tile(err, 2), fmt="ok", zorder=10)
            ax.step(c2, np.tile(rate, 2), "k+-", where="mid", zorder=10)
            ax.plot(c2, np.tile(model_init, 2), "g-", lw=2, label="Initial template")
            ax.plot(c2, np.tile(model_best, 2), "r-", lw=2, label="After fitting for phase-shift")
            ax.legend()
            ax.set_xlabel("Phase (cycles)")
            ax.set_ylabel("Normalized rate")
            fig.tight_layout()
            fig.savefig(f"pp_ToA{toa_id}.pdf", format="pdf")
            plt.close(fig)


def plot_phase_residuals(toa_mjds, ph_shifts, ph_lls, ph_uls, outFile: str = "") -> str:
    """Phase residuals (cycles) vs MJD with asymmetric 1-sigma bars."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    ax.errorbar(
        toa_mjds,
        np.asarray(ph_shifts) / (2 * np.pi),
        yerr=(np.asarray(ph_lls) / (2 * np.pi), np.asarray(ph_uls) / (2 * np.pi)),
        fmt="ok",
    )
    ax.set_xlabel("Time (MJD)")
    ax.set_ylabel(r"$\Delta\phi$ (cycles)")
    fig.tight_layout()
    path = str(outFile) + "_phaseResiduals.pdf"
    fig.savefig(path, format="pdf")
    plt.close(fig)
    return path


# Reference-named alias (measureToAs.py:64).
measureToAs = measure_toas
