"""Survey-scale ToA measurement: many pulsars per device invocation.

The per-source pipeline (pipelines/measure_toas.py) runs one pulsar per
process end to end. This driver lifts it to fleet scale on the
ops/multisource batch engine: per-source timing models stack into
struct-of-arrays blocks, whole sources bucket by padded event-count shape
(one compiled executable per bucket), and the anchored fold, the per-ToA
H-test and the template fit all vmap across the source axis. A
100-source survey runs as a handful of device programs instead of 100
serial pipeline invocations.

Failure domain: one pathological source (empty interval, malformed
model/template, a bucket-level device failure) degrades to the
single-source path — ``measure_source_toas`` — instead of poisoning its
batch; sources whose fallback also fails get ``None`` with the error
recorded in :func:`last_survey_info`.

Parity contract (pinned by tests/test_survey.py): the batched path
matches ``measure_source_toas`` looped over sources BIT-IDENTICALLY
per source when padding is exact — every source in a bucket padded to
the width its solo run would use (equal max segment event counts, and a
segment-size ratio that keeps the solo path off its own bucketed branch).
Ragged buckets change the padded reduction widths of the fit and H-test,
so they match to documented tolerance instead (docs/performance.md
"Survey mode"); the fold itself is elementwise and stays bitwise for
every source regardless of padding.

Knobs (ops/autotune.resolve_multisource): ``CRIMP_TPU_MULTISOURCE=0``
forces the per-source loop; ``CRIMP_TPU_MULTISOURCE_MAX_PAD`` caps the
bucket-merge padding waste; ``CRIMP_TPU_MULTISOURCE_BATCH`` caps sources
per dispatch.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import numpy as np
import pandas as pd

from crimp_tpu import obs, resilience
from crimp_tpu.io import template as template_io
from crimp_tpu.resilience import faultinject
from crimp_tpu.models import profiles, timing
from crimp_tpu.ops import anchored, multisource, search, toafit
from crimp_tpu.ops.ephem import spin_frequency_host
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SURVEY_TOA_COLUMNS = [
    "ToA", "ToA_mid", "ToA_start", "ToA_end", "ToA_lenInt", "ToA_exp",
    "nbr_events", "count_rate", "phShift", "phShift_LL", "phShift_UL",
    "Hpower", "redChi2",
]

_last_info: dict = {}


def last_survey_info() -> dict:
    """Telemetry for the most recent survey_measure_toas call: source
    counts per path, per-source errors, bucket layout and padding
    occupancy."""
    return dict(_last_info)


@dataclass
class SourceSpec:
    """One survey target, fully in memory.

    ``times``: event MJDs (sorted); ``timing_model``: anything
    ``timing.resolve`` accepts (TimingParams, parameter dict, .par path);
    ``template``: a template dict (``template_io.read_template`` shape) or
    a path to one; ``intervals``: the ToA interval table — a DataFrame
    with ``ToA_tstart`` / ``ToA_tend`` / ``ToA_exposure`` columns
    (``ToA_lenInt`` optional) or a path to a whitespace interval file.
    """

    name: str
    times: np.ndarray
    timing_model: object
    template: object
    intervals: object

    def interval_frame(self) -> pd.DataFrame:
        if isinstance(self.intervals, pd.DataFrame):
            return self.intervals
        return pd.read_csv(self.intervals, sep=r"\s+", comment="#")

    def template_dict(self) -> dict:
        if isinstance(self.template, dict):
            return self.template
        return template_io.read_template(self.template)


@dataclass
class _Prepped:
    """Host-side per-source prep shared by the batched and solo paths."""

    spec: SourceSpec
    tm: object
    kind: str
    tpl: object
    cfg: object
    seg_times: list = field(default_factory=list)
    starts: np.ndarray = None
    ends: np.ndarray = None
    exposures: np.ndarray = None
    len_int: np.ndarray = None

    @property
    def max_seg(self) -> int:
        return max((t.size for t in self.seg_times), default=0)


def _build_cfg(kind: str, phShiftRes: int, nbrBins: int, varyAmps: bool):
    # the non-readvaryparam branch of measure_toas, verbatim: ampShift box
    # bounds per family (measureToAs.py:308,461,605)
    amp_lo, amp_hi = {
        profiles.FOURIER: (0.01, 100.0),
        profiles.CAUCHY: (1e-6, 1e6),
        profiles.VONMISES: (1e-6, 500.0),
    }[kind]
    return toafit.ToAFitConfig(
        kind=kind, ph_shift_res=phShiftRes, nbins=nbrBins,
        vary_amps=varyAmps, amp_lo=amp_lo, amp_hi=amp_hi,
    )


def _prep_source(spec: SourceSpec, phShiftRes: int, nbrBins: int,
                 varyAmps: bool) -> _Prepped:
    tm = timing.resolve(spec.timing_model)
    kind, tpl = profiles.from_template(spec.template_dict())
    intervals = spec.interval_frame()
    starts = intervals["ToA_tstart"].to_numpy()
    ends = intervals["ToA_tend"].to_numpy()
    exposures = intervals["ToA_exposure"].to_numpy().astype(float)
    len_int = (intervals["ToA_lenInt"].to_numpy()
               if "ToA_lenInt" in intervals else ends - starts)
    times = np.asarray(spec.times, dtype=np.float64)
    seg_times = toafit.slice_sorted_intervals(times, starts, ends)
    for ii, t_seg in enumerate(seg_times):
        if t_seg.size == 0:
            raise ValueError(
                f"source {spec.name!r}: ToA interval {ii} contains no events"
            )
    return _Prepped(
        spec=spec, tm=tm, kind=kind, tpl=tpl,
        cfg=_build_cfg(kind, phShiftRes, nbrBins, varyAmps),
        seg_times=seg_times, starts=starts, ends=ends, exposures=exposures,
        len_int=np.asarray(len_int, dtype=float),
    )


def _assemble_frame(prep: _Prepped, toa_mids, results: dict,
                    h_powers) -> pd.DataFrame:
    n_seg = len(prep.seg_times)
    nbr_events = np.asarray([t.size for t in prep.seg_times])
    return pd.DataFrame({
        "ToA": np.arange(n_seg),
        "ToA_mid": np.asarray(toa_mids),
        "ToA_start": prep.starts[:n_seg],
        "ToA_end": prep.ends[:n_seg],
        "ToA_lenInt": prep.len_int[:n_seg],
        "ToA_exp": prep.exposures[:n_seg],
        "nbr_events": nbr_events,
        "count_rate": nbr_events / prep.exposures[:n_seg],
        "phShift": np.asarray(results["phShift"]),
        "phShift_LL": np.asarray(results["phShift_LL"]),
        "phShift_UL": np.asarray(results["phShift_UL"]),
        "Hpower": np.asarray(h_powers),
        "redChi2": np.asarray(results["redChi2"]),
    }, columns=SURVEY_TOA_COLUMNS)


def _empty_frame() -> pd.DataFrame:
    return pd.DataFrame({c: [] for c in SURVEY_TOA_COLUMNS},
                        columns=SURVEY_TOA_COLUMNS)


def _centered_seconds(seg_times: list[np.ndarray]) -> tuple[np.ndarray, np.ndarray]:
    n_max = max((t.size for t in seg_times), default=1)
    sec = np.zeros((len(seg_times), max(n_max, 1)))
    msk = np.zeros(sec.shape, dtype=bool)
    for i, t_seg in enumerate(seg_times):
        if t_seg.size:
            sec[i, : t_seg.size] = (t_seg - (t_seg[0] + t_seg[-1]) / 2) * 86400.0
            msk[i, : t_seg.size] = True
    return sec, msk


def measure_source_toas(spec: SourceSpec, phShiftRes: int = 1000,
                        nbrBins: int = 15, varyAmps: bool = False,
                        _prep: _Prepped | None = None,
                        delta_fold=None) -> pd.DataFrame:
    """Single-source in-memory ToA measurement — the survey's per-source
    fallback AND parity reference.

    The computation mirrors ``measure_toas`` (anchored per-interval fold,
    padded batch fit with the same size-ratio bucketing branch, per-ToA
    H-test at the local ephemeris frequency) without any of its file
    outputs; returns the per-source ToA DataFrame (SURVEY_TOA_COLUMNS).
    ``delta_fold`` passes through to ``anchored.fold_segments`` (the
    serving engine forces the delta engine on for returning clients;
    ``None`` defers to the autotune resolution, off by default, and stays
    bit-identical to the pre-engine path).
    """
    prep = _prep if _prep is not None else _prep_source(
        spec, phShiftRes, nbrBins, varyAmps
    )
    if not prep.seg_times:
        return _empty_frame()
    seg_phase_list, toa_mids = anchored.fold_segments(
        prep.tm, prep.seg_times, cache_tag=spec.name, delta_fold=delta_fold
    )
    if prep.kind in (profiles.CAUCHY, profiles.VONMISES):
        seg_phase_list = [p * (2 * np.pi) for p in seg_phase_list]
    seg_sizes = [t.size for t in prep.seg_times]
    size_ratio = max(seg_sizes) / max(min(seg_sizes), 1)
    if size_ratio > 4.0:
        results = toafit.fit_toas_bucketed(
            prep.kind, prep.tpl, seg_phase_list, prep.exposures, prep.cfg
        )
    else:
        phases, masks = toafit.pad_segments(seg_phase_list)
        results = toafit.fit_toas_batch_auto(
            prep.kind, prep.tpl, phases, masks, prep.exposures, prep.cfg
        )
        results = {k: np.asarray(v) for k, v in results.items()}
    freqs_mid, _ = spin_frequency_host(prep.tm, toa_mids)
    sec, msk = _centered_seconds(prep.seg_times)
    h_powers = np.asarray(search.h_power_segments(sec, msk, freqs_mid, nharm=5))
    return _assemble_frame(prep, toa_mids, results, h_powers)


def compute_bucket(ps: list[_Prepped], phase_lists=None, t_refs=None):
    """Batched fold + fit + H-test for one bucket of prepped sources.

    ``ps`` share (kind, cfg, n_comp) — the executable-sharing grouping the
    survey driver and the serving engine both apply before bucketing.
    Returns ``(frames, phase_lists, t_refs)``: the per-source ToA frames,
    plus the RAW cycle-folded phase lists and anchors (pre any radians
    conversion) so callers can seed the delta-fold cache with the
    bit-identical fold product.  Shared by :func:`_survey_impl` and the
    serving engine's continuous-batching dispatch (crimp_tpu/serve).

    Callers that already hold the cycle-folded phases — the serving
    engine's batched warm path refolds them via
    ``deltafold.delta_refold_batch`` — pass ``phase_lists``/``t_refs``
    (both, aligned with ``ps``) to skip the fold and route straight into
    the batched fits and H-test.
    """
    kind, cfg = ps[0].kind, ps[0].cfg
    if phase_lists is None or t_refs is None:
        phase_lists, t_refs = multisource.fold_sources(
            [p.tm for p in ps], [p.seg_times for p in ps]
        )
    fit_lists = phase_lists
    if kind in (profiles.CAUCHY, profiles.VONMISES):
        fit_lists = [[ph * (2 * np.pi) for ph in pl] for pl in phase_lists]
    results, slices = multisource.fit_sources(
        kind, [p.tpl for p in ps], fit_lists,
        [p.exposures for p in ps], cfg,
    )
    freqs_list = [spin_frequency_host(p.tm, t_refs[r])[0]
                  for r, p in enumerate(ps)]
    h_list = multisource.h_power_sources(
        [p.seg_times for p in ps], freqs_list
    )
    frames = []
    for r, p in enumerate(ps):
        res_r = {k: v[slices[r]] for k, v in results.items()}
        frames.append(_assemble_frame(p, t_refs[r], res_r, h_list[r])
                      if p.seg_times else _empty_frame())
    return frames, phase_lists, t_refs


def survey_measure_toas(specs, phShiftRes: int = 1000, nbrBins: int = 15,
                        varyAmps: bool = False) -> list[pd.DataFrame | None]:
    """Measure ToAs for MANY sources in batched device invocations.

    Returns one DataFrame per spec (order preserved); ``None`` for sources
    whose fallback also failed (error in :func:`last_survey_info`).
    Flight-recorded as an obs run with ``sources_batched`` /
    ``bucket_count`` / ``bucket_occupancy_pct`` telemetry and an
    ``obs.beat(label="sources")`` per-bucket heartbeat.

    Multi-host contract: bucket assignment is a pure function of the spec
    list — grouping keys, bucket widths and bucket membership never
    consult ``process_index`` — so on a multi-process job every host
    walks the identical bucket sequence and compiles the identical SPMD
    program (the batched dispatches inside ``compute_bucket`` shard the
    source axis across hosts through the global source mesh). Only the
    per-source FALLBACK ladder is host-partitioned: a demoted source is
    retried by exactly the host that owns its index, so one host's
    failure domain never serializes the others (frames for sources owned
    by other hosts stay ``None`` locally; ``last_survey_info`` carries
    the ``process_index``/``process_count`` stamps to merge on).
    """
    with obs.run("survey_measure_toas"):
        return _survey_impl(list(specs), phShiftRes, nbrBins, varyAmps)


def _survey_impl(specs, phShiftRes, nbrBins, varyAmps):
    from crimp_tpu.parallel import multihost

    pidx, pcount = multihost.process_identity()
    global _last_info
    n_total = len(specs)
    frames: list[pd.DataFrame | None] = [None] * n_total
    # per-source failure records: {"kind", "type", "message"} (classified
    # by resilience.taxonomy, so chaos tests and operators can tell a data
    # error from resource exhaustion)
    errors: dict[str, dict] = {}
    demoted: dict[str, str] = {}
    preps: dict[int, _Prepped] = {}
    fallback: list[int] = []

    for i, spec in enumerate(specs):
        try:
            preps[i] = _prep_source(spec, phShiftRes, nbrBins, varyAmps)
        except Exception as exc:  # noqa: BLE001 — per-source failure domain
            demoted[spec.name] = (f"prep: {resilience.classify(exc).value}: "
                                  f"{type(exc).__name__}: {exc}")
            fallback.append(i)

    from crimp_tpu.ops import autotune

    max_events = max((p.max_seg for p in preps.values()), default=1)
    resolved = autotune.resolve_multisource(n_total, max(max_events, 1))
    batched = sorted(preps)
    if not resolved["multisource"]:
        for i in batched:
            demoted[specs[i].name] = "knob: multisource off"
        fallback.extend(batched)
        batched = []

    # group sources whose fits can share one compiled executable, then
    # bucket each group by padded width (the whole-source generalization
    # of fit_toas_bucketed's segment bucketing)
    groups: dict[tuple, list[int]] = {}
    for i in batched:
        p = preps[i]
        groups.setdefault((p.kind, p.cfg, int(p.tpl.n_comp)), []).append(i)

    buckets: list[list[int]] = []
    for members in groups.values():
        for b in multisource.bucket_sources(
            [max(preps[i].max_seg, 1) for i in members],
            max_pad_ratio=resolved["max_pad"],
            batch_cap=resolved["batch_cap"],
        ):
            buckets.append([members[j] for j in b])

    done = 0
    occ_used = occ_total = 0
    splits = 0
    obs.beat(0, n_total, label="sources", force=True)
    # deque, not a list: pop(0) on a list shifts every element, turning a
    # many-bucket round (plus its split-retries) into O(n^2) host work
    queue = deque(buckets)
    while queue:
        bucket = queue.popleft()
        ps = [preps[i] for i in bucket]
        try:
            faultinject.fire("survey_bucket")
            bucket_frames, _, _ = compute_bucket(ps)
            width = max(max((p.max_seg for p in ps), default=1), 1)
            for i, p, frame in zip(bucket, ps, bucket_frames):
                frames[i] = frame
                occ_used += sum(t.size for t in p.seg_times)
                occ_total += width * len(p.seg_times)
        except Exception as exc:  # noqa: BLE001 — the bucket failure
            # domain walks the multisource ladder: split the batch in two
            # and retry (an OOM'd bucket usually fits as two halves), and
            # only a single-source bucket demotes to the per-source path —
            # one failure no longer serializes a whole batch
            fkind = resilience.classify(exc)
            if len(bucket) > 1:
                mid = (len(bucket) + 1) // 2
                queue.appendleft(bucket[mid:])
                queue.appendleft(bucket[:mid])
                splits += 1
                resilience.record_degradation("multisource", "split_bucket",
                                              fkind)
                logger.warning(
                    "survey bucket of %d failed (%s); splitting and "
                    "retrying", len(bucket), fkind.value, exc_info=True)
                continue  # halves re-enter the queue; done is unchanged
            resilience.record_degradation("multisource", "per_source", fkind)
            logger.warning("survey bucket failed (%s); falling back per "
                           "source", fkind.value, exc_info=True)
            for i in bucket:
                demoted[specs[i].name] = (f"bucket: {fkind.value}: "
                                          f"{type(exc).__name__}: {exc}")
            fallback.extend(bucket)
        done += len(bucket)
        obs.beat(done, n_total, label="sources")

    n_batched = sum(1 for f in frames if f is not None)
    # per-host failure domain: on a multi-process job each demoted source
    # is retried by exactly one host (deterministic index ownership), so a
    # local fallback never serializes the whole fleet behind one host
    owned = [i for i in sorted(fallback) if i % pcount == pidx]
    for i in owned:
        try:
            frames[i] = measure_source_toas(
                specs[i], phShiftRes, nbrBins, varyAmps,
                _prep=preps.get(i),
            )
        except Exception as exc:  # noqa: BLE001 — per-source domain: the
            # classified record tells operators a data error from resource
            # exhaustion; device-shaped kinds get one pinned-CPU attempt
            # (the device ladder's last rung; the run is stamped degraded)
            fkind = resilience.classify(exc)
            if fkind in resilience.CPU_FALLBACK_KINDS:
                try:
                    with resilience.pinned_cpu(fkind):
                        frames[i] = measure_source_toas(
                            specs[i], phShiftRes, nbrBins, varyAmps,
                            _prep=preps.get(i),
                        )
                except Exception as exc2:  # noqa: BLE001 — final: record
                    errors[specs[i].name] = resilience.error_record(exc2)
            else:
                errors[specs[i].name] = resilience.error_record(exc)
        done = min(done + 1, n_total)
        obs.beat(done, n_total, label="sources")
    obs.beat(n_total, n_total, label="sources", force=True)

    occupancy = 100.0 * occ_used / occ_total if occ_total else 100.0
    obs.gauge_set("bucket_occupancy_pct", round(occupancy, 2))
    _last_info = {
        "n_sources": n_total,
        "n_batched": n_batched,
        "process_index": pidx,
        "process_count": pcount,
        "n_fallback": len(fallback),
        "n_failed": sum(1 for f in frames if f is None),
        "bucket_count": len(buckets),
        "bucket_splits": splits,
        "occupancy_pct": round(occupancy, 2),
        "demoted": demoted,
        "errors": errors,
    }
    if demoted or errors:
        logger.info("survey fallback summary: %s", _last_info)
    return frames
