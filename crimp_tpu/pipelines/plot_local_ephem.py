"""Local-ephemerides table reading and plotting (CLI: localephemerides_plot).

Parity with the reference (plot_local_ephem.py:10-107): read the table,
optional time filter, then stacked F0/F1 panels vs MJD with x/y error bars
and dashed glitch-epoch markers."""

from __future__ import annotations

import pandas as pd

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402


def read_local_ephemerides(localephem: str, t_start: float | None = None, t_end: float | None = None) -> pd.DataFrame:
    df = pd.read_csv(localephem, sep=r"\s+", comment="#", header=0)
    if t_start is None:
        t_start = df["TOA_MJD_ref"].min()
    if t_end is None:
        t_end = df["TOA_MJD_ref"].max()
    mask = (df["TOA_MJD_ref"] >= t_start) & (df["TOA_MJD_ref"] <= t_end)
    return df.loc[mask].reset_index(drop=True)


def plot_local_ephemerides(local_df: pd.DataFrame, glitches=None, plotname=None):
    """Stacked F0 / F1 error-bar panels with optional glitch markers."""
    fig, axs = plt.subplots(2, 1, figsize=(10, 8), sharex=True)
    for ax, f_col, err_col, label in (
        (axs[0], "F0", "F0_err", "Frequency (Hz)"),
        (axs[1], "F1", "F1_err", r"$\dot{F}$ (Hz s$^{-1}$)"),
    ):
        ax.errorbar(
            local_df["TOA_MJD_ref"], local_df[f_col],
            xerr=local_df["TOA_MJD_ref_err"], yerr=local_df[err_col],
            fmt="o", color="k", ecolor="gray", elinewidth=1.5, capsize=2,
            markersize=6, alpha=0.7,
        )
        ax.ticklabel_format(style="sci", axis="y", scilimits=(0, 0))
        ax.set_ylabel(label)
        ax.grid(True, linestyle="--", alpha=0.3)
        if glitches:
            for g in glitches:
                ax.axvline(g, color="red", linestyle="--", linewidth=1.5, alpha=0.7)
    axs[1].set_xlabel("Time (MJD)")
    fig.tight_layout()
    if plotname is None:
        plt.close(fig)
        return None
    fig.savefig(str(plotname) + ".pdf", format="pdf", dpi=300, bbox_inches="tight")
    plt.close(fig)
    return str(plotname) + ".pdf"
