"""Phase-shift -> .tim conversion (CLI: phshifttotimfile).

Semantics parity with the reference converter (timfile.py:164-233): each
ToA is anchored at the nearest earlier integer-rotation epoch of the
spin-down model, then ToA = T_int + (dphi/2pi)/f; errors are
hypot(LL, UL)/sqrt(2) converted to microseconds; optional -pn pulse
numbers normalized to the first ToA.

TPU re-design: the reference runs a per-ToA Newton loop that re-parses the
.par three times per call (timfile.py:206-217); here the whole ToA batch is
anchored in one vectorized host solve (ops.ephem.integer_rotation_host).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from crimp_tpu.io import tim as tim_io
from crimp_tpu.models import timing
from crimp_tpu.ops.ephem import integer_rotation_host
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def phshift_to_timfile(
    ToAs: str,
    timMod,
    timfile: str = "residuals",
    tempModPP: str = "ppTemplateMod",
    inst: str = "Xray",
    addpn: bool = False,
    clobber: bool = False,
) -> pd.DataFrame:
    """Convert a ToAs.txt phase-shift table into a FORMAT-1 .tim file."""
    df = pd.read_csv(ToAs, sep=r"\s+", comment="#")
    toa_mids = df["ToA_mid"].to_numpy(dtype=float)
    dphi_cycles = df["phShift"].to_numpy(dtype=float) / (2 * np.pi)
    dphi_err_cycles = np.hypot(
        df["phShift_LL"].to_numpy(dtype=float) / (2 * np.pi),
        df["phShift_UL"].to_numpy(dtype=float) / (2 * np.pi),
    ) / np.sqrt(2)

    tm = timing.resolve(timMod)
    anchors = integer_rotation_host(tm, toa_mids)
    freq = anchors["freq_intRotation"]
    delta_t_sec = dphi_cycles / freq
    toa_tim = anchors["Tmjd_intRotation"] + delta_t_sec / 86400.0
    toa_err_us = (dphi_err_cycles / freq) * 1e6

    n = len(toa_mids)
    out = {
        "template": np.full(n, tempModPP),
        "Frequency": np.full(n, 700),
        "TOA": np.round(toa_tim, 12),
        "TOA_err": np.round(toa_err_us, 5),
        "timeunit": np.full(n, "@"),
        "flag_instrument": np.full(n, "-i"),
        "instrument": np.full(n, inst),
    }
    if addpn:
        pulse_number = anchors["ph_intRotation"]
        pulse_number = pulse_number - np.min(pulse_number)
        out["pulsenumberflag"] = np.full(n, "-pn")
        out["pulsenumber"] = np.round(pulse_number).astype(np.int64)

    tim_df = pd.DataFrame(out)
    tim_io.PulseToAs(tim_df).writetimfile(timfile, clobber=clobber)
    return tim_df


# Reference-named alias (timfile.py:164).
phshiftTotimfile = phshift_to_timfile
