"""ToA time-interval builder (CLI: timeintervalsfortoas).

Behavioral parity with the reference segmenter
(buildtimeintervalsToAs.py:64-365): bunch GTIs at gaps larger than
waitTimeCutoff, slice each bunch into ToAs of totCtsEachToA counts, clip
GTIs to each ToA window for exact livetime, skip zero-exposure windows,
merge trailing low-count intervals into their predecessor, and optionally
correct NICER count rates for the number of selected FPMs (52-detector
normalization, buildtimeintervalsToAs.py:287-290).

This stage is data-dependent host logic by design (SURVEY.md §7.1 step 6
boundary discipline): it stays numpy/pandas on CPU.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from crimp_tpu.io.events import EventFile
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

COLUMNS = ["ToA_tstart", "ToA_tend", "ToA_lenInt", "ToA_exposure", "Events", "ct_rate"]


def _clipped_exposure_days(gti: np.ndarray, t_start: float, t_end: float) -> float:
    """Livetime within [t_start, t_end]: GTIs clipped to the window."""
    keep = (gti[:, 1] > t_start) & (gti[:, 0] < t_end)
    if not keep.any():
        return 0.0
    clipped = gti[keep].copy()
    # t_start/t_end are event times inside the first/last kept GTI, so the
    # window edges replace those GTI edges outright (reference semantics,
    # buildtimeintervalsToAs.py:239-242).
    clipped[0, 0] = t_start
    clipped[-1, -1] = t_end
    return float(np.sum(clipped[:, 1] - clipped[:, 0]))


def build_time_intervals(
    evtFile: str,
    totCtsEachToA: int = 1000,
    waitTimeCutoff: float = 1.0,
    eneLow: float = 0.5,
    eneHigh: float = 10.0,
    min_counts: int | None = None,
    max_wait: float | None = None,
    outputFile: str = "timIntToAs",
    correxposure: bool = False,
) -> pd.DataFrame:
    """Build per-ToA [start, end] windows; writes <outputFile>.txt (+_bunches)."""
    if min_counts is None:
        min_counts = int(totCtsEachToA / 2)
    if max_wait is None:
        max_wait = waitTimeCutoff

    logger.info(
        "\n Running build_time_intervals: evtFile=%s totCtsEachToA=%s waitTimeCutoff=%s "
        "eneLow=%s eneHigh=%s min_counts=%s max_wait=%s outputFile=%s",
        evtFile, totCtsEachToA, waitTimeCutoff, eneLow, eneHigh, min_counts, max_wait, outputFile,
    )

    ef = EventFile(evtFile)
    keywords, gti = ef.read_gti()
    times = (
        ef.build_time_energy_df().filtenergy(eneLow, eneHigh).time_energy_df["TIME"].to_numpy()
    )

    # --- bunch GTIs at gaps > waitTimeCutoff -------------------------------
    gaps = gti[1:, 0] - gti[:-1, 1]
    bunch_breaks = np.nonzero(gaps > waitTimeCutoff)[0] + 1
    bunch_edges = np.concatenate([[0], bunch_breaks, [len(gti)]])

    bunches = []
    for lo, hi in zip(bunch_edges[:-1], bunch_edges[1:]):
        seg = gti[lo:hi]
        bunches.append(
            (
                seg[0, 0],
                seg[-1, 1],
                float(np.sum(seg[:, 1] - seg[:, 0])),
                seg[-1, 1] - seg[0, 0],
            )
        )

    with open(outputFile + "_bunches.txt", "w") as fh:
        fh.write("ToABunch_tstart \t ToABunch_tend \t ToABunch_exp \t ToABunch_lenInt\n")
        for start, end, exp_days, length in bunches:
            fh.write(f"{start}\t{end}\t{exp_days * 86400}\t{length}\n")

    # --- slice each bunch into count-limited ToA windows -------------------
    rows = []
    for start, end, _, _ in bunches:
        in_bunch = times[(times >= start) & (times <= end)]
        n_toas = int(np.ceil(len(in_bunch) / totCtsEachToA))
        for k in range(n_toas):
            chunk = in_bunch[k * totCtsEachToA : (k + 1) * totCtsEachToA] if k < n_toas - 1 else in_bunch[k * totCtsEachToA :]
            if len(chunk) == 0:
                continue
            exposure_days = _clipped_exposure_days(gti, chunk[0], chunk[-1])
            if exposure_days == 0:
                logger.warning(
                    "At %s MJD: exposure = 0 likely caused by a single timestamp in interval - skipping",
                    chunk[0],
                )
                continue
            exposure_sec = exposure_days * 86400.0
            rows.append(
                {
                    "ToA_tstart": float(chunk[0]),
                    "ToA_tend": float(chunk[-1]),
                    "ToA_lenInt": float(chunk[-1] - chunk[0]),
                    "ToA_exposure": exposure_sec,
                    "Events": len(chunk),
                    "ct_rate": len(chunk) / exposure_sec,
                }
            )

    intervals = pd.DataFrame(rows, columns=COLUMNS)
    intervals = merge_adjacent_intervals(intervals, min_counts, max_wait)
    n_total = len(intervals)

    # --- NICER FPM-selection exposure correction ---------------------------
    if keywords["TELESCOPE"] == "NICER":
        logger.warning(
            "\n If NICER event files were generated with HEASOFT 6.32+, correct for "
            "the number of selected FPMs (-ce) for accurate count rates\n"
        )
        if correxposure:
            _, fpm = ef.read_fpmsel()
            for i in range(n_total):
                window = fpm.loc[
                    (fpm["TIME"] >= intervals.at[i, "ToA_tstart"])
                    & (fpm["TIME"] <= intervals.at[i, "ToA_tend"])
                ]
                n_selected = float(np.sum(window["TOTFPMSEL"]))
                expected = 52.0 * intervals.at[i, "ToA_exposure"]
                if n_selected > 0:
                    intervals.at[i, "ct_rate"] *= expected / n_selected
    elif keywords["TELESCOPE"] == "NuSTAR":
        logger.warning(
            "\n If NuSTAR event files merge FPMA and FPMB, count rates are a factor of 2 smaller.\n"
        )

    print(f"Total number of time intervals that define the TOAs: {n_total}")
    intervals.to_csv(outputFile + ".txt", sep="\t", index=True, index_label="ToA")
    logger.info(
        "\n End of build_time_intervals run: %s intervals; wrote %s_bunches.txt and %s.txt",
        n_total, outputFile, outputFile,
    )
    return intervals


def merge_adjacent_intervals(df: pd.DataFrame, events_max: int, dtstart_max_days: float) -> pd.DataFrame:
    """Merge a row into its predecessor when Events < events_max and the gap
    to the previous interval end is < dtstart_max_days."""
    if df.empty:
        return pd.DataFrame(columns=COLUMNS)
    merged = []
    current = df.iloc[0].copy()
    for i in range(1, len(df)):
        row = df.iloc[i]
        if row["Events"] < events_max and (row["ToA_tstart"] - current["ToA_tend"]) < dtstart_max_days:
            current["ToA_tend"] = row["ToA_tend"]
            current["ToA_lenInt"] = current["ToA_tend"] - current["ToA_tstart"]
            current["ToA_exposure"] = current["ToA_exposure"] + row["ToA_exposure"]
            current["Events"] = current["Events"] + row["Events"]
            current["ct_rate"] = (
                current["Events"] / current["ToA_exposure"]
                if current["ToA_exposure"] != 0
                else float("nan")
            )
        else:
            merged.append(current[COLUMNS].copy())
            current = row.copy()
    merged.append(current[COLUMNS].copy())
    return pd.DataFrame(merged, columns=COLUMNS).reset_index(drop=True)


# Reference-named alias (buildtimeintervalsToAs.py:64).
timeintervalsToAs = build_time_intervals
