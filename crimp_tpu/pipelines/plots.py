"""Pulse-profile visualization suite (CLI: pulseprofile_plots).

Plot-registry parity with the reference (plot_pps.py:19-583): a YAML config
lists plots by type — folded profile ("pp"), phase-energy map
("phase_energy"), phase-time map ("phase_time"), time x energy grid of
profiles ("pp_grid"), before/after-epoch comparison ("before_after") —
applied to an energy/time-filtered, phase-folded event DataFrame, plus the
GTI clipping helper.
"""

from __future__ import annotations

import numpy as np
import yaml
from scipy.ndimage import gaussian_filter

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from crimp_tpu.io.events import EventFile  # noqa: E402
from crimp_tpu.ops.anchored import fold_chunked  # noqa: E402
from crimp_tpu.ops.binprofile import bin_phases  # noqa: E402


def prep_for_plotting(eventfile: str, parfile: str, enelow=0.0, enehigh=100.0, t_start=None, t_end=None):
    """Filtered (energy/time) event DataFrame with a 'foldedphases' column
    plus the window-clipped GTI list."""
    ef = EventFile(eventfile)
    df = (
        ef.build_time_energy_df()
        .filtenergy(eneLow=enelow, eneHigh=enehigh)
        .filttime(t_start, t_end)
        .time_energy_df
    )
    _, gti = ef.read_gti()
    gti = update_gti(gti, t_start, t_end)
    df = df.copy()
    df["foldedphases"] = fold_chunked(df["TIME"].to_numpy(), parfile)
    return df, gti


def update_gti(gti: np.ndarray, tstart, tend) -> np.ndarray:
    """Clip the GTI list to [tstart, tend] (plot_pps.py:44-74 semantics)."""
    if tstart is not None:
        gti = gti[gti[:, 1] > tstart]
        if len(gti) and tstart > gti[0, 0]:
            gti = gti.copy()
            gti[0, 0] = tstart
    if tend is not None:
        gti = gti[gti[:, 0] < tend]
        if len(gti) and tend < gti[-1, -1]:
            gti = gti.copy()
            gti[-1, -1] = tend
    return gti


def _two_cycles(bins, *arrays):
    cycle = 2 * np.pi if np.max(bins) > 1 else 1.0
    out = [np.append(bins, bins + cycle)]
    out.extend(np.append(a, a) for a in arrays)
    return out


def _save_or_show(fig, plotname):
    if plotname is None:
        plt.show()
    else:
        fig.savefig(str(plotname) + ".pdf", format="pdf", dpi=300, bbox_inches="tight")
        plt.close(fig)


def plotting_pp(df, nbrbins: int = 100, plotname: str | None = None):
    """Mean-normalized folded pulse profile over two cycles."""
    binned = bin_phases(df["foldedphases"], nbrbins)
    rate = binned["ctsBins"] / binned["ctsBins"].mean()
    err = binned["ctsBinsErr"] / binned["ctsBins"].mean()
    x, y, yerr = _two_cycles(binned["ppBins"], rate, err)
    fig, ax = plt.subplots(1, figsize=(12, 6))
    ax.errorbar(x, y, yerr=yerr, fmt="ok", zorder=10)
    ax.step(x, y, "k+-", where="mid", zorder=10)
    ax.set_xlim(0.0, 2 * (2 * np.pi if np.max(binned["ppBins"]) > 1 else 1))
    ax.set_xlabel("Phase (cycles)")
    ax.set_ylabel("Normalized rate")
    fig.tight_layout()
    _save_or_show(fig, plotname)


def plotting_phase_energy(df, nphasebins: int = 64, nenergybins: int = 24, smooth_sigma=0.5, plotname=None):
    """Phase-energy map: per-energy-row min-max-normalized count image."""
    phases = df["foldedphases"].to_numpy()
    energies = df["PI"].to_numpy()
    phase_edges = np.linspace(0.0, 1.0, nphasebins + 1)
    energy_edges = np.logspace(
        np.log10(np.nanmin(energies)), np.log10(np.nanmax(energies)), nenergybins + 1
    )
    H, xe, ye = np.histogram2d(phases, energies, bins=[phase_edges, energy_edges])
    img = H.T
    lo = img.min(axis=1, keepdims=True)
    hi = img.max(axis=1, keepdims=True)
    img = (img - lo) / (hi - lo)
    if smooth_sigma is not None:
        sigma = tuple(smooth_sigma) if isinstance(smooth_sigma, list) else smooth_sigma
        img = gaussian_filter(img, sigma=sigma, mode="nearest")
    fig, ax = plt.subplots(1, figsize=(12, 6))
    pcm = ax.pcolormesh(xe, ye, img, shading="auto")
    ax.set_yscale("log")
    ax.set_xlabel("Phase (cycles)")
    ax.set_ylabel("Energy")
    fig.colorbar(pcm, ax=ax, label="Min-Max scaling")
    fig.tight_layout()
    _save_or_show(fig, plotname)


def plotting_phase_time(df, nphasebins: int = 32, ntimebins: int = 12, smooth_sigma=0.5, plotname=None):
    """Phase-time map: histogram2d, per-row min-max scaling, NaN-weighted
    smoothing — the reference's own algorithm and defaults reproduced as-is
    (plot_pps.py:196-271), not a re-design."""
    phases = df["foldedphases"].to_numpy()
    times = df["TIME"].to_numpy()
    phase_edges = np.linspace(0.0, 1.0, nphasebins + 1)
    time_edges = np.linspace(np.nanmin(times), np.nanmax(times), ntimebins + 1)
    H, xe, ye = np.histogram2d(phases, times, bins=[phase_edges, time_edges])
    img = H.T
    lo = img.min(axis=1, keepdims=True)
    hi = img.max(axis=1, keepdims=True)
    denom = hi - lo
    rate = np.full_like(img, np.nan, dtype=float)
    np.divide(img - lo, denom, out=rate, where=denom != 0)
    if smooth_sigma is not None:
        sigma = tuple(smooth_sigma) if isinstance(smooth_sigma, list) else smooth_sigma
        finite = np.isfinite(rate)
        data = gaussian_filter(np.where(finite, rate, 0.0), sigma=sigma, mode="nearest")
        weight = gaussian_filter(finite.astype(float), sigma=sigma, mode="nearest")
        with np.errstate(invalid="ignore", divide="ignore"):
            rate = np.where(weight > 0, data / weight, np.nan)
    fig, ax = plt.subplots(1, figsize=(12, 6))
    pcm = ax.pcolormesh(xe, ye, rate, shading="auto")
    ax.set_xlabel("Phase (cycles)")
    ax.set_ylabel("Time (MJD)")
    fig.colorbar(pcm, ax=ax, label="Min-Max scaling")
    fig.tight_layout()
    _save_or_show(fig, plotname)


def plotting_pp_grid(df, n_timebins: int = 6, n_energybins: int = 6, nbrbins=(20, 24, 24, 24, 20, 16), plotname=None):
    """Grid of mean-normalized profiles: rows = time bins, cols = energy bins."""
    phases = df["foldedphases"].to_numpy()
    times = df["TIME"].to_numpy()
    energies = df["PI"].to_numpy()
    time_edges = np.linspace(np.nanmin(times), np.nanmax(times), n_timebins + 1)
    e_min = max(np.nanmin(energies), np.nextafter(0, 1))
    energy_edges = np.logspace(np.log10(e_min), np.log10(np.nanmax(energies)), n_energybins + 1)
    if np.isscalar(nbrbins):
        bins_per_col = [int(nbrbins)] * n_energybins
    else:
        bins_per_col = list(nbrbins)
        if len(bins_per_col) != n_energybins:
            raise ValueError("nbrbins length must equal n_energybins")

    fig, axes = plt.subplots(
        n_timebins, n_energybins, figsize=(3.8 * n_energybins, 2.9 * n_timebins), squeeze=False
    )
    panels = []
    y_lo, y_hi = np.inf, -np.inf
    for i in range(n_timebins):
        for j in range(n_energybins):
            sel = (
                (times >= time_edges[i])
                & (times < time_edges[i + 1])
                & (energies >= energy_edges[j])
                & (energies < energy_edges[j + 1])
            )
            if not sel.any():
                panels.append((i, j, None, None, None))
                continue
            binned = bin_phases(phases[sel], int(bins_per_col[j]))
            counts = binned["ctsBins"].astype(float)
            if counts.mean() <= 0:
                panels.append((i, j, None, None, None))
                continue
            norm = counts / counts.mean()
            norm_err = binned["ctsBinsErr"] / counts.mean()
            x, y, yerr = _two_cycles(binned["ppBins"], norm, norm_err)
            panels.append((i, j, x, y, yerr))
            y_lo, y_hi = min(y_lo, norm.min()), max(y_hi, norm.max())
    if not np.isfinite(y_lo):
        y_lo, y_hi = 0.85, 1.15
    else:
        pad = 0.05 * (y_hi - y_lo if y_hi > y_lo else 0.3)
        y_lo, y_hi = max(0.0, y_lo - pad), y_hi + pad

    for i, j, x, y, yerr in panels:
        ax = axes[i, j]
        if x is None:
            ax.set_visible(False)
            continue
        ax.errorbar(x, y, yerr=yerr, fmt="ok", zorder=10)
        ax.step(x, y, "k+-", where="mid", zorder=10)
        ax.set_xlim(0.0, np.max(x))
        ax.set_ylim(y_lo, y_hi)
        if i == n_timebins - 1:
            ax.set_xlabel("Phase (cycles)")
        else:
            ax.set_xticklabels([])
        if j == 0:
            ax.set_ylabel("Norm. rate")
        else:
            ax.set_yticklabels([])
        if i == 0:
            ax.set_title(f"{energy_edges[j]:.2g} - {energy_edges[j+1]:.2g} keV", fontsize=12)
        if j == n_energybins - 1:
            twin = ax.twinx()
            twin.set_ylabel(
                f"{int(time_edges[i])} - {int(time_edges[i+1])} MJD", rotation=270, labelpad=14
            )
            twin.set_yticks([])
    fig.subplots_adjust(wspace=0.02, hspace=0.02)
    _save_or_show(fig, plotname)


def plotting_pp_before_after(df, t_mjd: float, days_window=7, nbrbins: int = 48, plotname=None):
    """Two stacked profiles around t_mjd: [t-w, t] on top, [t, t+w] below."""
    phases = df["foldedphases"].to_numpy()
    times = df["TIME"].to_numpy()
    if isinstance(days_window, (list, tuple)):
        if len(days_window) != 2:
            raise ValueError("days_window must be a scalar or a (pre, post) pair")
        pre, post = map(float, days_window)
    else:
        pre = post = float(days_window)
    windows = [(t_mjd - pre, t_mjd), (t_mjd, t_mjd + post)]

    fig, axes = plt.subplots(2, 1, figsize=(8, 6), squeeze=False)
    panels = []
    y_lo, y_hi = np.inf, -np.inf
    for row, (t0, t1) in enumerate(windows):
        sel = (times >= t0) & (times <= t1)
        if not sel.any():
            panels.append((row, None, None, None, (t0, t1)))
            continue
        binned = bin_phases(phases[sel], nbrbins)
        counts = binned["ctsBins"].astype(float)
        if counts.mean() <= 0:
            panels.append((row, None, None, None, (t0, t1)))
            continue
        norm = counts / counts.mean()
        norm_err = binned["ctsBinsErr"] / counts.mean()
        x, y, yerr = _two_cycles(binned["ppBins"], norm, norm_err)
        panels.append((row, x, y, yerr, (t0, t1)))
        y_lo, y_hi = min(y_lo, norm.min()), max(y_hi, norm.max())
    if not np.isfinite(y_lo):
        y_lo, y_hi = 0.85, 1.15
    else:
        pad = 0.05 * (y_hi - y_lo if y_hi > y_lo else 0.3)
        y_lo, y_hi = max(0.0, y_lo - pad), y_hi + pad

    for row, x, y, yerr, (t0, t1) in panels:
        ax = axes[row, 0]
        if x is None:
            ax.set_visible(False)
            continue
        ax.errorbar(x, y, yerr=yerr, fmt="ok", zorder=10)
        ax.step(x, y, "k+-", where="mid", zorder=10)
        ax.set_xlim(0.0, np.max(x))
        ax.set_ylim(y_lo, y_hi)
        ax.set_ylabel("Normalized rate")
        ax.set_title(f"{int(t0)} - {int(t1)} MJD", fontsize=12)
        if row == 1:
            ax.set_xlabel("Phase (cycles)")
        else:
            ax.set_xticklabels([])
    fig.tight_layout()
    _save_or_show(fig, plotname)


PLOT_REGISTRY = {
    "pp": plotting_pp,
    "phase_energy": plotting_phase_energy,
    "phase_time": plotting_phase_time,
    "pp_grid": plotting_pp_grid,
    "before_after": plotting_pp_before_after,
}


def run_plots_from_yaml(config_path: str, df) -> None:
    """Run the plots listed in a YAML config: each item
    {type: <registry key>, params: {kwargs}}."""
    with open(config_path, "r") as fh:
        cfg = yaml.safe_load(fh) or {}
    plots = cfg.get("plots", [])
    if not isinstance(plots, list):
        raise ValueError("YAML must contain a top-level 'plots' list.")
    for i, item in enumerate(plots, 1):
        if not isinstance(item, dict):
            print(f"[WARN] plots[{i}] is not a mapping; skipping")
            continue
        fn = PLOT_REGISTRY.get(item.get("type"))
        if fn is None:
            print(f"[WARN] Unknown plot type {item.get('type')!r}; skipping")
            continue
        try:
            fn(df, **(item.get("params") or {}))
        except TypeError as exc:
            print(f"[WARN] Failed to run plot {item.get('type')!r}: {exc}")
