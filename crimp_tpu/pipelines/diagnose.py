"""Interactive ToA diagnostics dashboard (CLI: diagnosetoas).

Layout parity with the reference (diagnoseToAs.py:22-109): 7 rows (interval
length, exposure, counts, count rate, H-power, reduced chi2, phase shifts
with symmetric errors) x 2 columns (vs ToA index, vs MJD), written as an
interactive HTML file.

The runtime image carries no plotly; when it is importable the dashboard
uses it, otherwise a dependency-free fallback emits a self-contained HTML
page with the same 7x2 grid of interactive SVG panels (hover readouts via
inline JS).
"""

from __future__ import annotations

import html

import numpy as np
import pandas as pd

ROWS = [
    ("ToA_lenInt", "ToA interval length (days)"),
    ("ToA_exp", "ToA exposure (seconds)"),
    ("nbr_events", "Number of counts"),
    ("count_rate", "Count rate (/s)"),
    ("Hpower", "H-test power"),
    ("redChi2", "Reduced Chi2"),
    ("phShift", "Phase Shifts"),
]


def diagnose_toas(ToAs: str, outputFile: str = "ToADiagnosticsPlot") -> pd.DataFrame:
    """Build the dashboard HTML; returns the ToA table."""
    table = pd.read_csv(ToAs, sep=r"\s+", comment="#")
    try:
        _plotly_dashboard(table, ToAs, outputFile)
    except ImportError:
        _fallback_dashboard(table, ToAs, outputFile)
    return table


def _plotly_dashboard(table: pd.DataFrame, source: str, outputFile: str) -> None:
    from plotly.subplots import make_subplots
    import plotly.graph_objects as go

    err = np.hypot(table["phShift_LL"], table["phShift_UL"]) / np.sqrt(2)
    fig = make_subplots(
        rows=7, cols=2, shared_xaxes=True, shared_yaxes=True,
        horizontal_spacing=0.02, vertical_spacing=0.02,
    )
    for col, x in ((1, table["ToA"]), (2, table["ToA_mid"])):
        for row, (key, label) in enumerate(ROWS, start=1):
            kwargs = {}
            if key == "phShift":
                kwargs["error_y"] = dict(type="data", array=err, visible=True)
            fig.add_trace(go.Scatter(x=x, y=table[key], mode="markers", **kwargs), row=row, col=col)
            if col == 1:
                fig.update_yaxes(title_text=label, row=row, col=1)
    fig.update_xaxes(title_text="ToA number", row=7, col=1)
    fig.update_xaxes(title_text="Days (MJD)", row=7, col=2)
    fig.update_layout(
        height=1600, width=1600, showlegend=False,
        title_text="ToA properties for file " + source, font=dict(size=14),
    )
    fig.write_html(outputFile + ".html")


def _svg_panel(x, y, yerr, xlabel, ylabel, width=700, height=190) -> str:
    """One scatter panel as inline SVG with hover titles."""
    x = np.asarray(x, dtype=float)
    y = np.asarray(y, dtype=float)
    pad_l, pad_r, pad_t, pad_b = 70, 10, 8, 28
    x_lo, x_hi = np.nanmin(x), np.nanmax(x)
    y_vals = y if yerr is None else np.concatenate([y - yerr, y + yerr])
    y_lo, y_hi = np.nanmin(y_vals), np.nanmax(y_vals)
    x_span = (x_hi - x_lo) or 1.0
    y_span = (y_hi - y_lo) or 1.0

    def sx(v):
        return pad_l + (v - x_lo) / x_span * (width - pad_l - pad_r)

    def sy(v):
        return height - pad_b - (v - y_lo) / y_span * (height - pad_t - pad_b)

    parts = [
        f'<svg width="{width}" height="{height}" style="background:#fff;border:1px solid #ccc">'
    ]
    parts.append(
        f'<text x="4" y="{height/2:.0f}" font-size="10" transform="rotate(-90 10,{height/2:.0f})" text-anchor="middle">{html.escape(ylabel)}</text>'
    )
    parts.append(
        f'<text x="{(pad_l+width)/2:.0f}" y="{height-6}" font-size="10" text-anchor="middle">{html.escape(xlabel)}</text>'
    )
    for tick in np.linspace(y_lo, y_hi, 4):
        parts.append(
            f'<text x="{pad_l-4}" y="{sy(tick)+3:.1f}" font-size="9" text-anchor="end">{tick:.4g}</text>'
        )
    for tick in np.linspace(x_lo, x_hi, 6):
        parts.append(
            f'<text x="{sx(tick):.1f}" y="{height-pad_b+12}" font-size="9" text-anchor="middle">{tick:.6g}</text>'
        )
    for i in range(len(x)):
        cx, cy = sx(x[i]), sy(y[i])
        if yerr is not None:
            parts.append(
                f'<line x1="{cx:.1f}" y1="{sy(y[i]-yerr[i]):.1f}" x2="{cx:.1f}" y2="{sy(y[i]+yerr[i]):.1f}" stroke="#888"/>'
            )
        parts.append(
            f'<circle cx="{cx:.1f}" cy="{cy:.1f}" r="3" fill="#1f77b4"><title>x={x[i]:.8g}, y={y[i]:.8g}</title></circle>'
        )
    parts.append("</svg>")
    return "".join(parts)


def _fallback_dashboard(table: pd.DataFrame, source: str, outputFile: str) -> None:
    err = (np.hypot(table["phShift_LL"], table["phShift_UL"]) / np.sqrt(2)).to_numpy()
    cells = []
    for key, label in ROWS:
        yerr = err if key == "phShift" else None
        cells.append(
            "<tr><td>"
            + _svg_panel(table["ToA"], table[key], yerr, "ToA number", label)
            + "</td><td>"
            + _svg_panel(table["ToA_mid"], table[key], yerr, "Days (MJD)", label)
            + "</td></tr>"
        )
    page = (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>ToA diagnostics</title></head><body>"
        f"<h2>ToA properties for file {html.escape(source)}</h2>"
        "<table>" + "".join(cells) + "</table></body></html>"
    )
    with open(outputFile + ".html", "w") as fh:
        fh.write(page)


# Reference-named alias (diagnoseToAs.py:22).
diagnoseToAs = diagnose_toas
