"""Timing-model fitting support: free-parameter bookkeeping and the
delta-parameterization (host side).

Semantics parity with the reference utilities (utilities_fittoas.py:14-293):

- free parameters are the .par entries with fit flag 1; a flagged WAVE_OM
  expands to every WAVEk_A / WAVEk_B coefficient;
- the fit works on parameter DELTAS in phase space: the fit dict carries
  the deltas (epochs keep their base values and are never fit), and the
  full dict reconstructs as base - delta for frequency-like terms,
  base + delta for GLTD, and the raw delta for GLEP;
- GLTD is zeroed when the paired GLF0D is 0;
- model phase residuals are mean-subtracted, with the WAVE terms needing
  the FULL F0 (they are seconds-residuals scaled by F0).
"""

from __future__ import annotations

import copy
import re

import numpy as np

from crimp_tpu.models import timing
from crimp_tpu.ops import anchored

_GLEP_RE = re.compile(r"^GLEP_\d+$")
_GLTD_RE = re.compile(r"^GLTD_\d+$")
_WAVE_AB_RE = re.compile(r"^WAVE\d+_[AB]$")
_WAVE_RE = re.compile(r"^WAVE\d+$")


def list_fit_keys(parfile: dict) -> list[str]:
    """Keys with fit flag 1; WAVE_OM flag 1 expands to all WAVEk_A/B."""
    keys = [
        k
        for k, v in parfile.items()
        if isinstance(v, dict) and "value" in v and "flag" in v and v["flag"] == 1
    ]
    if "WAVE_OM" in parfile and parfile["WAVE_OM"].get("flag") == 1:
        keys = [k for k in keys if k != "WAVE_OM"]
        keys.extend(
            f"{k}_{suffix}"
            for k in parfile
            if _WAVE_RE.match(k)
            for suffix in ("A", "B")
        )
    return keys


def extract_free_params(parfile: dict, yaml_initialguesses: str | None = None):
    """(p0, keys): the free-parameter vector (zeros or YAML guesses)."""
    keys = list_fit_keys(parfile)
    if yaml_initialguesses is not None:
        from crimp_tpu.io.yamlcfg import load_prior

        prior = load_prior(yaml_initialguesses)
        if not prior.initial_guess:
            raise ValueError("No initial guesses found in YAML file.")
        missing = [k for k in keys if k not in prior.initial_guess]
        if missing:
            raise KeyError(f"Missing initial guesses for: {', '.join(missing)}")
        p0 = np.array([prior.initial_guess[k] for k in keys], dtype=float)
    else:
        p0 = np.zeros(len(keys), dtype=float)
    return p0, keys


def _zero_gltd_without_glf0d(parfile: dict) -> None:
    """GLTD is meaningless when GLF0D = 0: zero it (in place)."""
    for key, entry in parfile.items():
        if not key.startswith("GLTD_"):
            continue
        suffix = key.split("_", 1)[1]
        glf0d = parfile.get(f"GLF0D_{suffix}")
        if glf0d and glf0d.get("value") == 0:
            entry["value"] = 0


def inject_free_params(parfile: dict, pvec: np.ndarray, keys: list[str]):
    """(fit_dict, full_dict): delta-space dict and reconstructed full dict."""
    _zero_gltd_without_glf0d(parfile)

    fit_dict: dict = {}
    full_dict: dict = {}
    for key, entry in parfile.items():
        if isinstance(entry, dict) and "value" in entry and not isinstance(entry["value"], dict):
            base = entry["value"]
            keep_base = key == "PEPOCH" or _GLEP_RE.match(key) or key in ("WAVEEPOCH", "WAVE_OM")
            fit_dict[key] = base if keep_base else 0.0
            full_dict[key] = base
        else:
            fit_dict[key] = copy.deepcopy(entry)
            full_dict[key] = copy.deepcopy(entry)

    for key, delta in zip(keys, pvec):
        if key == "PEPOCH" or key in ("WAVEEPOCH", "WAVE_OM"):
            continue
        if _WAVE_AB_RE.match(key):
            base_name, coeff = key.rsplit("_", 1)
            if base_name not in parfile:
                raise KeyError(f"Parameter {base_name!r} not found in parfile.")
            base_coeff = parfile[base_name]["value"][coeff]
            fit_dict[base_name]["value"][coeff] = delta
            full_dict[base_name]["value"][coeff] = base_coeff - delta
            continue
        if key not in parfile:
            raise KeyError(f"Parameter {key!r} not found in parfile.")
        base = parfile[key]["value"]
        fit_dict[key] = delta
        if _GLEP_RE.match(key):
            full_dict[key] = delta  # the epoch itself is fit
        elif _GLTD_RE.match(key):
            full_dict[key] = base + delta
        else:
            full_dict[key] = base - delta  # phase-space sign convention
    return fit_dict, full_dict


def validate_parfile(parfile: dict) -> None:
    """Validate a flags-carrying timing model; require >= 1 free parameter."""
    if not isinstance(parfile, dict):
        raise ValueError("Initial timing model must be a dict")
    n_fit = 0
    for key, value in parfile.items():
        if key == "WAVEEPOCH" or _WAVE_RE.match(key):
            continue
        if not (isinstance(value, dict) and "value" in value and "flag" in value):
            raise ValueError(f"Parameter {key!r} must be a dict with 'value' and 'flag'")
        if not isinstance(value["value"], (int, float, np.floating)):
            raise ValueError(f"Parameter {key!r}: value must be numeric")
        if value["flag"] not in (0, 1):
            raise ValueError(f"Parameter {key!r}: fit flag must be 0 or 1")
        n_fit += value["flag"] == 1
    if n_fit == 0:
        raise ValueError("Template has no free parameters (flag==1). Nothing to optimize.")


def gaussian_nll(y, mu, sigma) -> float:
    """Gaussian negative log-likelihood."""
    r = (y - mu) / sigma
    return 0.5 * np.sum(r**2 + np.log(2.0 * np.pi * sigma**2))


def model_phase_residuals(x_mjd, timmodel: dict, pvec, keys: list[str]) -> np.ndarray:
    """Mean-subtracted model phase residuals for the delta parameters.

    Waves need the FULL F0 (seconds-residual scaling); when fitting waves the
    other wave-independent terms come from the fit (delta) dict.
    """
    fit_dict, full_dict = inject_free_params(timmodel, pvec, keys)
    fit_tm = timing.from_dict({k: v for k, v in fit_dict.items()})
    t = np.atleast_1d(np.asarray(x_mjd, dtype=np.float64))

    wave_keys = all("wave" in k.lower() for k in keys)
    any_wave = any("wave" in k.lower() for k in keys)

    if wave_keys:
        wave_dict = dict(fit_dict)
        wave_dict["F0"] = full_dict["F0"]
        phases = anchored._host_wave_phase(timing.from_dict(wave_dict), t)
    elif not any_wave:
        phases = (
            anchored._host_taylor_phase(fit_tm, t).astype(np.float64)
            + anchored._host_glitch_phase(fit_tm, t)
            + anchored._host_wave_phase(timing.from_dict(full_dict), t)
        )
    else:
        wave_dict = dict(fit_dict)
        wave_dict["F0"] = full_dict["F0"]
        phases = (
            anchored._host_taylor_phase(fit_tm, t).astype(np.float64)
            + anchored._host_glitch_phase(fit_tm, t)
            + anchored._host_wave_phase(timing.from_dict(wave_dict), t)
        )
    phases = np.asarray(phases, dtype=np.float64)
    return phases - np.mean(phases)


_LINEAR_F_RE = re.compile(r"^F(\d+)$")
_LINEAR_GL_RE = re.compile(r"^(GLPH|GLF0D|GLF0|GLF1|GLF2)_(\S+)$")
_GL_COL = {"GLPH": 0, "GLF0": 1, "GLF1": 2, "GLF2": 3, "GLF0D": 4}


def linear_key_columns(timmodel: dict, keys: list[str]) -> list[int] | None:
    """Delta-fold basis column index per free key, or None if ineligible.

    The phase model is exactly linear in the F0..F12 spin deltas and the
    per-glitch [GLPH, GLF0, GLF1, GLF2, GLF0D] amplitude deltas once the
    epochs are fixed; those keys map onto the ops/deltafold.py basis
    layout (column m < N_FREQ_TERMS is dt^(m+1)/(m+1)!; glitch blocks of
    N_GLITCH_AMP follow in GLEP order). Any other key — epochs, GLTD,
    waves, or a glitch suffix with no matching GLEP — makes the free set
    non-linear and returns None, so callers fall back to the exact path.
    Shared by the post-fit refold fast path below and the delta-basis MCMC
    likelihood (pipelines/fit_toas.py).
    """
    from crimp_tpu.ops import deltafold

    gids = [mm.group(1) for k in timmodel
            if (mm := re.match(r"GLEP_(\S+)$", k))]
    cols: list[int] = []
    for key in keys:
        m = _LINEAR_F_RE.match(key)
        if m:
            idx = int(m.group(1))
            if idx >= timing.N_FREQ_TERMS:
                return None
            cols.append(idx)
            continue
        m = _LINEAR_GL_RE.match(key)
        if m:
            if m.group(2) not in gids:
                return None
            cols.append(timing.N_FREQ_TERMS
                        + deltafold.N_GLITCH_AMP * gids.index(m.group(2))
                        + _GL_COL[m.group(1)])
            continue
        return None
    return cols


def delta_basis(fit_tm, x_mjd):
    """(N, n_params) delta-fold basis anchored at PEPOCH (fit-path
    conventions: single anchor, ``wave_in_f0=False`` — whitening waves are
    frozen at their full values and never enter the free columns).

    Returns (basis (jax array), colmax (np array of per-column max |B|))
    — colmax feeds ``deltafold.error_bound_cycles`` so callers can bound
    the f64 matmul error before trusting the linear path.
    """
    import jax.numpy as jnp

    from crimp_tpu.ops import deltafold

    t = np.atleast_1d(np.asarray(x_mjd, dtype=np.float64))
    pepoch = float(np.asarray(fit_tm.pepoch))
    delta_sec = np.asarray(
        (np.asarray(t, dtype=np.longdouble) - np.longdouble(pepoch))  # graftlint: disable=GL004 (host-side epoch-delta in anchored.py's longdouble convention; only the rounded f64 result reaches the device basis)
        * np.longdouble(anchored.SECONDS_PER_DAY),  # graftlint: disable=GL004 (same host-side epoch-delta; f64 is taken after the exact subtraction)
        dtype=np.float64,
    )
    spec = deltafold.basis_spec(fit_tm, np.asarray([pepoch]))
    anchor_idx = np.zeros(t.size, dtype=np.int64)
    b = deltafold.basis_rows(spec, jnp.asarray(delta_sec),
                             jnp.asarray(anchor_idx), wave_in_f0=False)
    colmax = np.asarray(jnp.max(jnp.abs(b), axis=0))
    return b, colmax


def model_phase_residuals_delta(x_mjd, timmodel: dict, pvec, keys: list[str],
                                cfg: dict | None = None) -> np.ndarray | None:
    """Delta-fold fast path for model_phase_residuals: B @ dp as one f64
    device matmul (ops/deltafold.py basis, single anchor at PEPOCH).

    The delta parameterization makes the objective LINEAR in the free
    spin/glitch-amplitude deltas, so the residual model is exactly a basis
    matmul; frozen whitening waves are added host-side unchanged (they do
    not depend on the free deltas — wave fits keep the exact path).
    Returns None whenever ineligible — knob off, a free key outside the
    linear family (epochs, GLTD, waves), or the predicted f64 error bound
    above the configured budget — and the caller falls back to the exact
    host-longdouble path.
    """
    from crimp_tpu.ops import deltafold

    t = np.atleast_1d(np.asarray(x_mjd, dtype=np.float64))
    if cfg is None:
        cfg = deltafold.resolve(t.size)
    if not cfg["delta_fold"] or not keys:
        return None
    cols = linear_key_columns(timmodel, keys)
    if cols is None:
        return None

    fit_dict, full_dict = inject_free_params(timmodel, pvec, keys)
    # fit-path semantics: deltas evaluate on the fit dict (base epochs,
    # GLTD zeroed in delta space — recovery columns inert, matching
    # _host_glitch_phase on fit_tm), waves frozen at their FULL values
    fit_tm = timing.from_dict(fit_dict)
    dp = np.zeros(deltafold.n_params(fit_tm.n_glitch))
    dp[cols] = np.asarray(pvec, dtype=np.float64)

    import jax.numpy as jnp

    b, colmax = delta_basis(fit_tm, t)
    if deltafold.error_bound_cycles(colmax, dp) > cfg["budget"]:
        return None
    phases = np.asarray(b @ jnp.asarray(dp), dtype=np.float64)
    full_tm = timing.from_dict(full_dict)
    if full_tm.n_wave:
        phases = phases + np.asarray(
            anchored._host_wave_phase(full_tm, t), dtype=np.float64
        )
    return phases - np.mean(phases)


def make_nll(x, y, y_err, parfile: dict, yaml_init: str | None = None):
    """(nll(pvec), p0, keys, parfile) — the MLE objective factory."""
    validate_parfile(parfile)
    p0, keys = extract_free_params(parfile, yaml_init)
    y = np.asarray(y, dtype=float)
    y_err = np.asarray(y_err, dtype=float)
    y_centered = y - np.mean(y)

    def nll(pvec):
        mu = model_phase_residuals(x, parfile, pvec, keys)
        return gaussian_nll(y_centered, mu, y_err)

    return nll, p0, keys, parfile


def rms_residual(phaseresid, model_phaseresid) -> float:
    resid = np.asarray(phaseresid) - np.asarray(model_phaseresid)
    return float(np.sqrt(np.mean(resid**2)))


def chi2_fit(phaseresid, model_phaseresid, phase_err, freeparameters) -> dict:
    resid = np.asarray(phaseresid) - np.asarray(model_phaseresid)
    chi2 = float(np.sum(resid**2 / np.asarray(phase_err) ** 2))
    dof = np.size(phaseresid) - freeparameters
    return {"chi2": chi2, "redchi2": chi2 / dof, "dof": dof}
