"""Timing-model fitting from pulse ToAs (CLI: fittoas).

Workflow parity with the reference (fit_toas.py:35-457): load a .tim file,
convert ToAs to phase residuals (TRACK -2 + -pn pulse-number tracking, else
fold to [-0.5, 0.5)), optional manual phase-wrap insertion, then fit
parameter deltas in phase space by MLE (scipy Nelder-Mead / BFGS-if-WAVE)
or by ensemble MCMC with YAML box priors; write the patched .par with
statistics, residual plots, and posterior corner plot.

TPU re-design: the MCMC replaces emcee's 320k serial model evaluations with
the pure-JAX stretch-move sampler (ops.mcmc) whose log-probability — the
delta-parameterized phase model — is itself a jitted, walker-vmapped device
function. The MLE path keeps scipy minimize on the host (the objective is a
~1e2-point fold; optimizer-bound, not data-bound).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from crimp_tpu import obs
from crimp_tpu.io import parfile as parfile_io
from crimp_tpu.io import tim as tim_io
from crimp_tpu.io.parfile import get_parameter_value
from crimp_tpu.io.yamlcfg import Prior, load_prior
from crimp_tpu.models import timing
from crimp_tpu.ops import mcmc as mcmc_ops
from crimp_tpu.ops.fold import fold_phases
from crimp_tpu.pipelines import fit_utils
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# ToA loading
# ---------------------------------------------------------------------------


def load_toas_for_fit(
    tim_file_df: pd.DataFrame,
    parfile: dict,
    t_start: float | None = None,
    t_stop: float | None = None,
    t_mjd_phasewrap=None,
    mode: str = "add",
) -> pd.DataFrame:
    """ToAs -> DataFrame ['ToA', 'phase', 'phase_err_cycle'] for fitting."""
    F0 = get_parameter_value(parfile["F0"])
    pt = tim_io.PulseToAs(tim_file_df)
    pt.time_filter(t_start, t_stop)
    pt.df = pt.df.sort_values("pulse_ToA").reset_index(drop=True)

    toas = pd.to_numeric(pt.df["pulse_ToA"], errors="coerce").to_numpy(dtype=float)
    toa_err = pd.to_numeric(pt.df["pulse_ToA_err"], errors="coerce").to_numpy(dtype=float)

    phases, _ = fold_phases(toas, parfile)
    if (
        "TRACK" in parfile
        and get_parameter_value(parfile["TRACK"]) == -2
        and "pn" in pt.df.columns
    ):
        phases = phases - pt.df["pn"].to_numpy(dtype=float)
        logger.info("Found TRACK -2 and -pn pulse numbers - tracking pulse numbers")
    else:
        phases = ((phases + 0.5) % 1.0) - 0.5
        logger.info("Phase folding between [-0.5, 0.5)")
    phases = phases - np.mean(phases)

    out = pd.DataFrame(
        {
            "ToA": toas,
            "phase": phases,
            "phase_err_cycle": (toa_err / 1e6) * F0,
        }
    )
    if t_mjd_phasewrap is not None:
        out = add_phasewrap(out, t_mjd_phasewrap, mode=mode)
        out["phase"] -= np.mean(out["phase"])
    return out


def add_phasewrap(toas_to_fit: pd.DataFrame, t_mjd, mode: str = "add") -> pd.DataFrame:
    """Cumulatively shift phases by +/-1 cycle for ToAs past each cut MJD."""
    cuts = np.atleast_1d(np.asarray(t_mjd, dtype=float))
    if cuts.size == 0:
        return toas_to_fit
    if mode.lower() == "add":
        sign = 1.0
    elif mode.lower() == "subtract":
        sign = -1.0
    else:
        raise ValueError("mode must be 'add' or 'subtract'.")
    counts = np.searchsorted(np.sort(cuts), toas_to_fit["ToA"].to_numpy(dtype=float), side="right")
    toas_to_fit["phase"] += sign * counts
    return toas_to_fit


# ---------------------------------------------------------------------------
# Device-side delta-parameterized phase model for the MCMC
# ---------------------------------------------------------------------------


def _delta_model_updates(parfile: dict, keys: list[str]):
    """Map free-parameter keys to TimingParams (field, index) updates."""
    import re

    gids = [m.group(1) for k in parfile if (m := re.match(r"GLEP_(\S+)$", k))]
    updates = []
    for key in keys:
        if re.match(r"^F\d+$", key):
            updates.append(("f", int(key[1:])))
        elif (m := re.match(r"^(GLEP|GLPH|GLF0D|GLF0|GLF1|GLF2|GLTD)_(\S+)$", key)):
            field = {
                "GLEP": "glep",
                "GLPH": "glph",
                "GLF0": "glf0",
                "GLF1": "glf1",
                "GLF2": "glf2",
                "GLF0D": "glf0d",
                "GLTD": "gltd",
            }[m.group(1)]
            updates.append((field, gids.index(m.group(2))))
        elif (m := re.match(r"^WAVE(\d+)_([AB])$", key)):
            updates.append(("wave_a" if m.group(2) == "A" else "wave_b", int(m.group(1)) - 1))
        else:
            raise KeyError(f"cannot fit parameter {key!r} on device")
    return updates


# The exact likelihood is built in two parts so the jitted ensemble cores
# (ops/mcmc.py) never retrace across run_mcmc calls: the FUNCTION depends
# only on the free-parameter structure (which TimingParams fields update,
# the wave branches) and is cached per structure, while every array — the
# ToAs, the centered data, the base model pytree — travels as a traced
# ``data`` argument. A fresh closure per run was a fresh jit cache key per
# run; a cached (theta, data) function is one compile per (structure,
# shape) family for the life of the process.
_EXACT_LP_CACHE: dict = {}


def _exact_logprob_fn(updates: tuple, f0_key_idx: int | None,
                      any_wave: bool, all_wave: bool):
    """The (theta, data) exact log-probability for one free-set structure."""
    cache_key = (updates, f0_key_idx, any_wave, all_wave)
    cached = _EXACT_LP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    import jax.numpy as jnp
    from dataclasses import replace

    from crimp_tpu.ops import fold as fold_ops

    def log_prob(theta, data):
        in_box = jnp.all((theta > data["lo"]) & (theta < data["hi"]))
        tm = data["base_tm"]
        for (field, idx), value in zip(updates, theta):
            arr = jnp.asarray(getattr(tm, field)).at[idx].set(value)
            tm = replace(tm, **{field: arr})
        # Waves are seconds-residuals scaled by the FULL F0
        # (utilities_fittoas.py:269-293).
        full_f0 = (data["full_f0"] - theta[f0_key_idx]
                   if f0_key_idx is not None else data["full_f0"])
        wave_tm = replace(tm, f=jnp.asarray(tm.f).at[0].set(full_f0))
        x_j = data["x"]
        if all_wave:
            mu = fold_ops.wave_phase(wave_tm, x_j)
        elif any_wave:
            mu = (
                fold_ops.taylor_phase(tm, x_j)
                + fold_ops.glitch_phase(tm, x_j)
                + fold_ops.wave_phase(wave_tm, x_j)
            )
        else:
            mu = (fold_ops.taylor_phase(tm, x_j) + fold_ops.glitch_phase(tm, x_j)
                  + data["frozen_waves"])
        mu = mu - jnp.mean(mu)
        resid = (data["y"] - mu) / data["yerr"]
        nll = 0.5 * jnp.sum(resid**2 + jnp.log(2 * jnp.pi * data["yerr"]**2))
        return jnp.where(in_box, -nll, -jnp.inf)

    _EXACT_LP_CACHE[cache_key] = log_prob
    return log_prob


def make_logprob_parts(parfile: dict, keys: list[str], prior: Prior, x, y, yerr):
    """(log_prob_fn, data): the exact likelihood as a stable function plus
    a traced observation pytree — pass both to ops/mcmc.py so repeated
    runs at the same shapes reuse one compiled ensemble core."""
    import jax.numpy as jnp

    from crimp_tpu.ops import fold as fold_ops

    fit_dict, full_dict = fit_utils.inject_free_params(parfile, np.zeros(len(keys)), keys)
    base_tm = timing.from_dict(fit_dict)
    full_f0_base = float(get_parameter_value(parfile["F0"]))
    updates = tuple(_delta_model_updates(parfile, keys))
    f0_key_idx = keys.index("F0") if "F0" in keys else None

    lo = jnp.asarray([prior.bounds.get(k, (-np.inf, np.inf))[0] for k in keys])
    hi = jnp.asarray([prior.bounds.get(k, (-np.inf, np.inf))[1] for k in keys])

    x_j = jnp.asarray(np.asarray(x, dtype=np.float64))
    y_centered = np.asarray(y, dtype=float)
    y_centered = jnp.asarray(y_centered - y_centered.mean())
    yerr_j = jnp.asarray(np.asarray(yerr, dtype=float))
    any_wave = any("wave" in k.lower() for k in keys)
    all_wave = all("wave" in k.lower() for k in keys) and len(keys) > 0

    # theta-independent whitening-wave phases (the non-wave-fit branch):
    # computed once here instead of once per proposal inside the scan
    frozen_waves = fold_ops.wave_phase(timing.from_dict(full_dict), x_j)
    data = {
        "lo": lo, "hi": hi, "x": x_j, "y": y_centered, "yerr": yerr_j,
        "base_tm": base_tm, "full_f0": jnp.asarray(full_f0_base),
        "frozen_waves": frozen_waves,
    }
    return _exact_logprob_fn(updates, f0_key_idx, any_wave, all_wave), data


def make_logprob(parfile: dict, keys: list[str], prior: Prior, x, y, yerr):
    """Jittable log-probability over the free-parameter delta vector."""
    log_prob_fn, data = make_logprob_parts(parfile, keys, prior, x, y, yerr)

    def log_prob(theta):
        return log_prob_fn(theta, data)

    return log_prob


def make_logprob_delta(parfile: dict, keys: list[str], prior: Prior, x, y, yerr,
                       budget: float):
    """(data, info) for the delta-basis MCMC likelihood, or (None, info).

    Within the linear regime the delta-parameterized model is exactly
    ``mu = B_free @ theta`` against the per-run precomputed delta-fold
    basis (fit_utils.delta_basis, the model_phase_residuals_delta column
    conventions), so every proposal scores as one ndim-long matvec —
    vmapped over walkers, one ``(walkers x ndim) @ (ndim x nToA)`` matmul
    per half-ensemble update (ops/mcmc.py delta_logprob).

    The host-side precision guard refuses the linear path — (None, info)
    with the reason — whenever:

    - any free key is outside the linear family (epochs, GLTD, waves:
      ``linear_key_columns`` returns None; the nonlinear parameters are
      instead frozen into the basis, fingerprinted by ``nonlinear_sha``);
    - a free key has no finite prior box (the box extent is the guard's
      domain);
    - ``error_bound_cycles`` over the WALKER BOX EXTENT (the worst-case
      |theta| inside [lo, hi] — every finite-probability walker lives
      there) exceeds ``budget``.

    Callers fall back to the exact likelihood, which is bit-identical to
    the knob-off path by construction.
    """
    import jax.numpy as jnp

    from crimp_tpu.ops import deltafold
    from crimp_tpu.ops import fold as fold_ops

    info: dict = {"eligible": False, "reason": None}
    cols = fit_utils.linear_key_columns(parfile, keys)
    if not keys or cols is None:
        info["reason"] = "nonlinear_free_param"
        return None, info

    lo = np.asarray([prior.bounds.get(k, (-np.inf, np.inf))[0] for k in keys])
    hi = np.asarray([prior.bounds.get(k, (-np.inf, np.inf))[1] for k in keys])
    if not (np.all(np.isfinite(lo)) and np.all(np.isfinite(hi))):
        info["reason"] = "unbounded_prior"
        return None, info

    fit_dict, full_dict = fit_utils.inject_free_params(parfile, np.zeros(len(keys)), keys)
    fit_tm = timing.from_dict(fit_dict)
    full_tm = timing.from_dict(full_dict)
    t = np.atleast_1d(np.asarray(x, dtype=np.float64))
    b, colmax = fit_utils.delta_basis(fit_tm, t)

    # worst-case |theta| over the prior box: outside it the log-prob is
    # -inf regardless of the model, so the box extent bounds every matmul
    # the sampler will ever trust
    dp_box = np.zeros(deltafold.n_params(fit_tm.n_glitch))
    dp_box[cols] = np.maximum(np.abs(lo), np.abs(hi))
    bound = deltafold.error_bound_cycles(colmax, dp_box)
    info.update(
        bound_cycles=bound,
        budget_cycles=float(budget),
        nonlinear_sha=deltafold.nonlinear_sha(fit_tm),
        n_toas=int(t.size),
        ndim=len(keys),
    )
    if bound > budget:
        info["reason"] = "error_bound_exceeds_budget"
        return None, info

    # center the data against the frozen whitening waves so the device
    # likelihood matches the exact path's center(B@theta + waves) exactly
    y_c = np.asarray(y, dtype=float)
    y_c = y_c - y_c.mean()
    if full_tm.n_wave:
        w = np.asarray(fold_ops.wave_phase(full_tm, jnp.asarray(t)), dtype=np.float64)
        y_c = y_c - (w - w.mean())

    info["eligible"] = True
    data = {
        "basis": jnp.asarray(np.asarray(b)[:, cols]),
        "y": jnp.asarray(y_c),
        "err": jnp.asarray(np.asarray(yerr, dtype=float)),
        "mask": jnp.ones(t.size),
        "lo": jnp.asarray(lo),
        "hi": jnp.asarray(hi),
    }
    return data, info


def run_mcmc(
    x,
    y,
    yerr,
    init_parfile: dict,
    keys: list[str],
    prior: Prior,
    steps: int = 10000,
    burn: int = 500,
    walkers: int = 32,
    corner_pdf: str | None = None,
    chain_npy: str | None = None,
    flat_npy: str | None = None,
    progress: bool = True,
    seed: int = 0,
    mcmc_delta: int | None = None,
):
    """Ensemble-MCMC posterior sampling (replaces emcee; fit_toas.py:140-202).

    ``mcmc_delta`` overrides the CRIMP_TPU_MCMC_DELTA resolution (env >
    cached bench A/B winner > off). When the delta path is on AND the
    precision guard admits the free set (make_logprob_delta), proposals
    score as basis matmuls; any guard trip or runtime failure falls back
    to the exact likelihood — bit-identical to the knob-off run, counted
    in the obs manifest (mcmc_guard_fallbacks / degraded_mcmc_*).

    Returns (chain, flat, summaries)."""
    import jax

    from crimp_tpu import resilience
    from crimp_tpu.obs import costmodel
    from crimp_tpu.ops import autotune
    from crimp_tpu.resilience import faultinject

    rng = np.random.default_rng(seed)
    ndim = len(keys)
    p0 = np.empty((walkers, ndim))
    for i, name in enumerate(keys):
        lo, hi = prior.bounds[name]
        p0[:, i] = rng.uniform(lo, hi, size=walkers)

    cfg = autotune.resolve_mcmc_delta(np.size(np.asarray(x)))
    if mcmc_delta is not None:
        cfg["mcmc_delta"] = int(bool(mcmc_delta))

    key = jax.random.PRNGKey(seed)
    obs.counter_add("mcmc_proposals_evaluated", steps * walkers)
    chain = None
    if cfg["mcmc_delta"]:
        data, delta_info = make_logprob_delta(
            init_parfile, keys, prior, x, y, yerr, budget=cfg["budget"]
        )
        if data is None:
            obs.counter_add("mcmc_guard_fallbacks", 1)
            logger.info("delta-basis MCMC guard fallback (%s); using the "
                        "exact likelihood", delta_info.get("reason"))
        else:
            try:
                faultinject.fire("mcmc_step")
                chain_j, lps_j = mcmc_ops.ensemble_sample(
                    mcmc_ops.delta_logprob, np.asarray(p0), steps, key, data=data
                )
                chain = np.asarray(chain_j)
                lps = np.asarray(lps_j)
                if np.isnan(lps).any():
                    raise resilience.NonfiniteResultError(
                        "delta-basis MCMC produced NaN log-probabilities"
                    )
                costmodel.capture(
                    "mcmc_ensemble_delta", mcmc_ops._ensemble_core,
                    mcmc_ops.delta_logprob, np.asarray(p0), data, steps, key, 2.0,
                )
                obs.counter_add("mcmc_delta_path_steps", steps)
            except Exception as exc:  # noqa: BLE001 — any delta-path failure steps the ladder to the exact-likelihood rung
                kind = resilience.classify(exc)
                resilience.record_degradation("mcmc", "exact_likelihood", kind)
                logger.warning(
                    "delta-basis MCMC failed (%s); falling back to the exact "
                    "likelihood", kind.value, exc_info=True,
                )
                chain = None

    if chain is None:
        log_prob_fn, lp_data = make_logprob_parts(init_parfile, keys, prior, x, y, yerr)
        chain_j, lps_j = mcmc_ops.ensemble_sample(
            log_prob_fn, np.asarray(p0), steps, key, data=lp_data
        )
        chain = np.asarray(chain_j)
        lps = np.asarray(lps_j)
    if chain_npy:
        np.save(chain_npy, chain)
    flat, flat_lp, summaries = mcmc_ops.summarize_chain(chain, lps, keys, burn=max(0, burn))
    if flat_npy:
        np.save(flat_npy, flat)
    if corner_pdf is not None:
        corner_plot(flat, keys, corner_pdf)
    return chain, flat, summaries


def corner_plot(flat: np.ndarray, labels: list[str], path_stem: str) -> str:
    """Posterior corner plot (own matplotlib implementation; the image has
    no `corner` package). 2-D hist panels below the diagonal, 1-D hists on
    it, with 16/50/84-percentile titles."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ndim = flat.shape[1]
    fig, axes = plt.subplots(ndim, ndim, figsize=(2.2 * ndim, 2.2 * ndim))
    axes = np.atleast_2d(axes)
    for i in range(ndim):
        for j in range(ndim):
            ax = axes[i, j]
            if j > i:
                ax.axis("off")
                continue
            if i == j:
                ax.hist(flat[:, i], bins=40, color="k", histtype="step")
                q16, q50, q84 = np.percentile(flat[:, i], [16, 50, 84])
                ax.set_title(
                    f"{labels[i]} = {q50:.3g} (+{q84 - q50:.2g}/-{q50 - q16:.2g})",
                    fontsize=8,
                )
                ax.set_yticks([])
            else:
                ax.hist2d(flat[:, j], flat[:, i], bins=40, cmap="Greys")
            if i == ndim - 1:
                ax.set_xlabel(labels[j], fontsize=8)
            if j == 0 and i > 0:
                ax.set_ylabel(labels[i], fontsize=8)
    fig.tight_layout()
    path = path_stem + ".pdf"
    fig.savefig(path, format="pdf", dpi=200)
    plt.close(fig)
    return path


def plot_residuals(toas_pre_fit: pd.DataFrame, phase_residuals_post_fit, plotname=None):
    """Pre-fit residuals + best-fit model, and post-fit (data-model) panel."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axs = plt.subplots(
        2, 1, figsize=(10, 8), sharex=True, gridspec_kw={"height_ratios": [1, 0.7]}
    )
    axs[0].errorbar(
        toas_pre_fit["ToA"], toas_pre_fit["phase"], yerr=toas_pre_fit["phase_err_cycle"],
        color="k", fmt="o", ls="", alpha=0.5, label="Pre-fit residuals",
    )
    axs[0].plot(
        toas_pre_fit["ToA"], phase_residuals_post_fit, "k-", alpha=0.5, label="Best-fit model"
    )
    axs[0].set_ylabel("Residuals (cycle)")
    axs[0].legend()
    axs[1].errorbar(
        toas_pre_fit["ToA"],
        toas_pre_fit["phase"] - phase_residuals_post_fit,
        yerr=toas_pre_fit["phase_err_cycle"],
        color="k", fmt="o", ls="", alpha=0.5, label="Post-fit (data-model) residuals",
    )
    axs[1].axhline(0, color="k")
    axs[1].set_xlabel("Time (MJD)")
    axs[1].set_ylabel("Residuals (cycle)")
    axs[1].legend()
    fig.tight_layout()
    if plotname is None:
        plt.close(fig)
        return None
    fig.savefig(str(plotname) + ".pdf", format="pdf", bbox_inches="tight")
    plt.close(fig)
    return str(plotname) + ".pdf"


# ---------------------------------------------------------------------------
# Orchestration (the CLI body)
# ---------------------------------------------------------------------------


def fit_toas(*args, **kwargs) -> dict:
    """Full fit pipeline; returns {'keys', 'values', 'stats', ...}.

    Flight-recorded as an obs run (``fit_toas``): the sampler/optimizer
    and post-fit refold land as stage spans, with ToA counts and
    delta-fold counters from the ops layer (docs/observability.md).
    """
    with obs.run("fit_toas"):
        return _fit_toas_impl(*args, **kwargs)


def _fit_toas_impl(
    timfile_path: str,
    par_in: str,
    par_out: str,
    t_start: float | None = None,
    t_end: float | None = None,
    t_mjd: list[float] | None = None,
    mode: str = "add",
    init_yaml: str | None = None,
    mcmc: bool = False,
    mcmc_steps: int = 10000,
    mcmc_burn: int = 500,
    mcmc_walkers: int = 32,
    corner_plot_path: str | None = None,
    chain_npy: str | None = None,
    flat_npy: str | None = None,
    best_fit: str = "map",
    residual_plot: str | None = None,
    seed: int = 0,
) -> dict:
    """Full fit pipeline; returns {'keys', 'values', 'stats', ...}."""
    init_par = parfile_io.read_timing_model(par_in)[2]
    F0 = get_parameter_value(init_par["F0"])
    tim_df = tim_io.read_tim(timfile_path, comment="C")
    toas_pre_fit = load_toas_for_fit(tim_df, init_par, t_start, t_end, t_mjd, mode)
    fit_utils.validate_parfile(init_par)

    misc_keys = {
        "START": toas_pre_fit["ToA"].min(),
        "FINISH": toas_pre_fit["ToA"].max(),
    }

    if mcmc:
        keys = fit_utils.list_fit_keys(init_par)
        if init_yaml is None:
            raise ValueError("init_yaml (bounds) is required for the MCMC path")
        prior = load_prior(init_yaml)
        print("Running ensemble MCMC (JAX stretch-move sampler)...")
        obs.counter_add("toas_fit_input", len(toas_pre_fit))
        with obs.span("fit_mcmc", steps=mcmc_steps, walkers=mcmc_walkers):
            _, flat, summaries = run_mcmc(
            toas_pre_fit["ToA"], toas_pre_fit["phase"], toas_pre_fit["phase_err_cycle"],
            init_par, keys, prior, steps=mcmc_steps, burn=mcmc_burn, walkers=mcmc_walkers,
            corner_pdf=corner_plot_path, chain_npy=chain_npy, flat_npy=flat_npy, seed=seed,
        )
        print("Posterior summaries (median -/+ 1sigma via 16th/84th percentiles):")
        uncertainties = {}
        for name, s in summaries.items():
            print(f"  {name}: {s['median']:.8e} -{s['minus']:.2e} +{s['plus']:.2e}")
            uncertainties[name] = max(s["minus"], s["plus"])
        best_vec = np.array([summaries[name][best_fit] for name in keys])
        _, full_dict = fit_utils.inject_free_params(init_par, best_vec, keys)
        source_label = f"MCMC (posterior {best_fit})"
    else:
        nll, p0, keys, _ = fit_utils.make_nll(
            toas_pre_fit["ToA"].to_numpy(),
            toas_pre_fit["phase"].to_numpy(),
            toas_pre_fit["phase_err_cycle"].to_numpy(),
            init_par,
            init_yaml,
        )
        from scipy.optimize import minimize

        obs.counter_add("toas_fit_input", len(toas_pre_fit))
        if any("wave" in k.lower() for k in keys):
            if any("glep_" in k.lower() for k in keys):
                logger.warning(
                    "Fitting glitch epochs and waves simultaneously is discouraged."
                )
            with obs.span("fit_mle", method="BFGS", n_free=len(keys)):
                res = minimize(nll, p0, method="BFGS", options={"maxiter": int(1e5)}, tol=1e-16, jac="3-point")
        else:
            with obs.span("fit_mle", method="Nelder-Mead", n_free=len(keys)):
                res = minimize(nll, p0, method="Nelder-Mead", options={"maxiter": int(1e5)})
        best_vec = res.x
        _, full_dict = fit_utils.inject_free_params(init_par, best_vec, keys)
        uncertainties = None
        source_label = "Maximum Likelihood Estimation"

    # post-fit refold: the delta-fold engine serves it as one basis matmul
    # when the free set is linear and the knob is on; None falls back to
    # the exact host-longdouble path (bit-identical when the knob is off)
    with obs.span("postfit_refold"):
        post_fit = fit_utils.model_phase_residuals_delta(
            toas_pre_fit["ToA"].to_numpy(), init_par, best_vec, keys
        )
        if post_fit is None:
            post_fit = fit_utils.model_phase_residuals(
                toas_pre_fit["ToA"].to_numpy(), init_par, best_vec, keys
            )
    if residual_plot is not None:
        suffix = f"_{best_fit}" if mcmc else ""
        plot_residuals(toas_pre_fit, post_fit, residual_plot + suffix)

    parfile_io.patch_par_values(
        par_in, par_out, new_values=full_dict, uncertainties=uncertainties
    )
    print("---------------------------")
    print(f"Wrote new timing model to {par_out} using {source_label} values")

    rms_cycle = fit_utils.rms_residual(toas_pre_fit["phase"].to_numpy(), post_fit)
    stats = fit_utils.chi2_fit(
        toas_pre_fit["phase"].to_numpy(), post_fit, toas_pre_fit["phase_err_cycle"].to_numpy(), len(keys)
    )
    print("Statistics of new best-fit:")
    print(f"RMS residual in cycle = {rms_cycle}")
    print(f"RMS residual in seconds = {rms_cycle / F0} (assuming F0 = {F0})")
    print(f"Chi2 = {stats['chi2']} for {stats['dof']} dof")
    print(f"reduced Chi2 = {stats['redchi2']}")

    parfile_io.patch_statistics(
        par_out,
        par_out,
        {
            "CHI2R": stats["redchi2"],
            "NTOA": len(toas_pre_fit),
            "TRES": rms_cycle / F0 * 1e6,
            "CHI2R_DOF": stats["dof"],
        },
    )
    parfile_io.patch_miscellaneous(par_out, par_out, misc_keys)
    print(f"Appended best-fit statistical properties to {par_out} par file\n")
    return {
        "keys": keys,
        "values": best_vec,
        "full_dict": full_dict,
        "stats": stats,
        "rms_cycle": rms_cycle,
        "toas": toas_pre_fit,
        "post_fit_residuals": post_fit,
    }
