"""Merge overlapping .tim files carrying pulse numbers (CLI: mergeoverlappingtims).

Semantics parity with the reference (merge_overlapping_timfiles.py:109-214):
consecutive files must share at least one ToA (matched after rounding MJDs
to 12 decimals); the integer pulse-number shift is anchored on the FIRST
overlap, every remaining overlap must then agree (hard error otherwise),
and duplicated ToAs keep the earlier file's row.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pandas as pd

from crimp_tpu.io.tim import PulseToAs, read_tim
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

TOA_ROUND_DECIMALS = 12  # fixed by design


def _load_tim(timfile: str) -> pd.DataFrame:
    df = read_tim(timfile, skiprows=1)
    absent = [col for col in ("pulse_ToA", "pn") if col not in df.columns]
    if absent:
        raise ValueError(
            f"{timfile} lacks {absent}: a mergeable .tim needs ToA epochs and "
            "a '-pn <int>' pulse-number flag on every line"
        )
    df["pn"] = pd.to_numeric(df["pn"], errors="raise").astype(np.int64)
    return df.sort_values("pulse_ToA", ignore_index=True)


def expand_inputs(inputs: list[str]) -> list[str]:
    """.tim paths, or .txt list files with one .tim per line, in order."""

    def entries(item: str) -> list[str]:
        path = Path(item)
        if path.suffix.lower() != ".txt":
            return [item]
        if not path.exists():
            raise FileNotFoundError(f"list file does not exist: {item}")
        lines = (raw.strip() for raw in path.read_text().splitlines())
        return [line for line in lines if line and not line.startswith("#")]

    timfiles = [t for item in inputs for t in entries(item)]
    absent = [t for t in timfiles if not Path(t).exists()]
    if absent:
        raise FileNotFoundError("cannot merge, inputs not found: " + ", ".join(absent))
    if len(timfiles) < 2:
        raise ValueError(
            f"merging requires at least two .tim files (got {len(timfiles)})"
        )
    return timfiles


def _overlap_keys(a: pd.DataFrame, b: pd.DataFrame):
    key_a = a["pulse_ToA"].round(TOA_ROUND_DECIMALS)
    key_b = b["pulse_ToA"].round(TOA_ROUND_DECIMALS)
    shared = pd.Index(key_a).intersection(pd.Index(key_b))
    return key_a, key_b, shared


def _merge_pair(merged: pd.DataFrame, nxt: pd.DataFrame) -> pd.DataFrame:
    key_prev, key_next, shared = _overlap_keys(merged, nxt)
    if shared.empty:
        raise ValueError(
            "consecutive .tim files share no ToAs (after rounding to "
            f"{TOA_ROUND_DECIMALS} decimals); cannot anchor a pulse-number shift"
        )

    anchor = float(np.min(shared.to_numpy(dtype=float)))
    shift = int(merged.loc[key_prev == anchor, "pn"].iloc[0]) - int(
        nxt.loc[key_next == anchor, "pn"].iloc[0]
    )
    shifted = nxt.copy()
    shifted["pn"] = (shifted["pn"] + shift).astype(np.int64)

    # After shifting, EVERY overlapping ToA must agree on pn.
    prev_map = (
        merged.assign(_k=key_prev)
        .loc[lambda d: d["_k"].isin(shared), ["_k", "pn"]]
        .drop_duplicates("_k")
        .set_index("_k")["pn"]
    )
    next_map = (
        shifted.assign(_k=key_next)
        .loc[lambda d: d["_k"].isin(shared), ["_k", "pn"]]
        .drop_duplicates("_k")
        .set_index("_k")["pn"]
    )
    joined = prev_map.to_frame("pn_prev").join(next_map.to_frame("pn_next"), how="inner")
    mismatched = joined[joined["pn_prev"] != joined["pn_next"]]
    if not mismatched.empty:
        raise ValueError(
            "Overlap validation failed: overlapping TOAs have inconsistent pulse "
            f"numbers after shifting.\nFirst mismatches:\n{mismatched.head(10)}"
        )

    merged2 = merged.assign(_k=key_prev)
    shifted = shifted.assign(_k=key_next)
    out = (
        pd.concat([merged2, shifted], ignore_index=True)
        .sort_values("pulse_ToA")
        .drop_duplicates(subset="_k", keep="first")
        .drop(columns=["_k"])
        .reset_index(drop=True)
    )
    logger.info("Applied shift %+d and merged (now %d TOAs).", shift, len(out))
    return out


def merge_tim_files(timfiles_or_listfiles: list[str]) -> pd.DataFrame:
    """Merge a sequence of .tim files with consistent pulse numbering."""
    timfiles = expand_inputs(timfiles_or_listfiles)
    logger.info("Merging %d .tim files...", len(timfiles))
    merged = _load_tim(timfiles[0])
    for tf in timfiles[1:]:
        merged = _merge_pair(merged, _load_tim(tf))
    return merged


def write_merged_tim(df: pd.DataFrame, outprefix: str, clobber: bool = False) -> None:
    """Serialize the merged table through the FORMAT-1 writer, restoring the
    -pn flag column layout."""
    out = df.copy()
    if "pn" in out.columns and "pn_flag" in out.columns:
        out["pn_flag"] = "-pn"
    PulseToAs(out).writetimfile(outprefix, clobber=clobber)
