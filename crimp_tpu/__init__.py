"""crimp_tpu — a TPU-native pulsar/magnetar timing framework.

Re-designed from scratch for JAX/XLA/Pallas with the capabilities of the
reference CRIMP package (see /root/reference): phase folding against
tempo2/PINT-style timing models, Z^2_n / H-test periodicity searches,
pulse-profile template construction and unbinned maximum-likelihood pulse
time-of-arrival (ToA) extraction, timing-model fitting (MLE + ensemble MCMC),
local ephemerides, diagnostics and plotting — with the numeric core running
as batched, sharded f64 kernels on TPU instead of serial numpy loops.

Architecture (device = dense math, host = control flow + file I/O):

- ``crimp_tpu.io``        host-side file formats (.par, template .txt, .tim,
                          FITS event files — self-contained FITS reader)
- ``crimp_tpu.models``    timing-model and pulse-profile-model pytrees
- ``crimp_tpu.ops``       jitted f64 kernels: fold, periodicity search,
                          ToA likelihood profiles, template fits, MCMC
- ``crimp_tpu.parallel``  device meshes and sharded (multi-chip) kernels
- ``crimp_tpu.pipelines`` workflow stages mirroring the reference CLI tools
- ``crimp_tpu.cli``       the 12 console entry points
"""

# Phase folding needs ~13 significant digits (total phase ~1e6 cycles vs a
# <1 µs ≈ 1.4e-7-cycle ToA target), so the framework globally opts into
# float64. On TPU f64 is software-emulated by XLA: cheap for the O(N)
# add/multiply chains folding needs, but ~100-op for transcendentals — the
# search kernels therefore reduce phases mod 1 in f64 and run trig in f32
# (ops/search.py), and the uniform-grid fast paths confine f64 to one row
# per trial tile (measured +38% trials/s on v5e).
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent compilation cache + compile telemetry: config-only at import
# (no backend init — the relay-window scripts depend on `import crimp_tpu`
# not acquiring devices). CRIMP_TPU_COMPILE_CACHE=off disables.
from crimp_tpu.utils.platform import configure_compilation_cache as _cfg_cache  # noqa: E402
from crimp_tpu.utils.profiling import install_compile_listeners as _listeners  # noqa: E402

_cfg_cache()
_listeners()

__version__ = "0.1.0"


def warmup(**kwargs):
    """AOT-lower-and-compile the hot kernels at their real shapes.

    Thin lazy delegate to :func:`crimp_tpu.aot.warmup` so sessions can
    pre-pay all compilation (and populate the persistent cache) before
    the timed window opens. Importing crimp_tpu stays cheap; calling
    this initializes the backend.
    """
    from crimp_tpu import aot

    return aot.warmup(**kwargs)
