"""Central registry of ``CRIMP_TPU_*`` environment knobs + parse helpers.

Four PRs of kernel work accumulated a dozen-plus env knobs, each read at
its call site with its own ad-hoc ``os.environ.get(...).strip().lower()``
parsing. That scattering is exactly what the graftlint GL003 rule
(crimp_tpu/analysis) now polices: every ``CRIMP_TPU_*`` read must go
through this module, every knob must be declared here, every declared
knob must carry a row in docs/tools.md, and every *numeric-affecting*
knob must be pinned in the resumable store's ``numeric_mode`` fingerprint
(ops/resumable.py) so chunks computed under different numeric modes can
never silently mix.

Registering a new knob (docs/analysis.md has the worked example):

1. add a :class:`Knob` entry to ``REGISTRY`` below;
2. add its row to the docs/tools.md environment-variable table (GL003
   fails the tier-1 gate until you do);
3. if ``numeric_key`` is set, make sure that key is pinned in
   ``ResumableSearch._numeric_mode`` (GL003 checks this too);
4. read it ONLY through the accessors here (``raw``/``env_onoff``/
   ``env_nonneg_int``/...) — a direct ``os.environ`` read of a
   ``CRIMP_TPU_*`` name anywhere else is a GL003 finding.

The word sets below are the single definition of truthy/falsy strings so
"1"/"on"/"true" handling is uniform across the library, bench.py and the
scripts (the historical parsers disagreed about "none" and "never").
Strict integer knobs (0/1 switches like CRIMP_TPU_GRID_MXU) deliberately
do NOT accept the word forms: tests pin that "on"/"yes" raise there, so a
typo'd numeric override can never silently pick a direction.

Import-safe: this module must never import jax (the analyzer and the
relay-window session scripts import it with no backend available).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

# The uniform truthy/falsy word sets. ON/OFF_WORDS are the historical
# sets every boolean-ish knob already accepted; "none" stays a recognized
# off-spelling only where a path knob needs it (env_path_or_off).
ON_WORDS = frozenset(("1", "on", "true", "always"))
OFF_WORDS = frozenset(("0", "off", "false", "never"))
AUTO_WORDS = frozenset(("", "auto"))


@dataclass(frozen=True)
class Knob:
    """One declared ``CRIMP_TPU_*`` environment knob.

    ``numeric_key`` names the entry of the resumable store's
    ``numeric_mode`` fingerprint that pins this knob's resolved value
    (None for knobs that cannot change computed bits — throughput,
    caching, bench and session-orchestration knobs). GL003 enforces the
    mapping in both directions.
    """

    name: str
    default: str  # human-readable default, mirrored by the docs row
    kind: str  # bool | enum | int | float | str | path | blocks
    numeric_key: str | None = None
    consumer: str = ""  # which layer reads it
    doc: str = ""  # one-line effect summary

    @property
    def numeric(self) -> bool:
        return self.numeric_key is not None


def _build_registry(knobs: tuple[Knob, ...]) -> dict[str, Knob]:
    out: dict[str, Knob] = {}
    for k in knobs:
        if not k.name.startswith("CRIMP_TPU_"):
            raise ValueError(f"knob {k.name!r} outside the CRIMP_TPU_ namespace")
        if k.name in out:
            raise ValueError(f"duplicate knob registration {k.name!r}")
        out[k.name] = k
    return out


REGISTRY: dict[str, Knob] = _build_registry((
    # -- kernel numeric modes (pinned in resumable numeric_mode) ------------
    Knob("CRIMP_TPU_POLY_TRIG", "auto (on for TPU backends)", "bool",
         numeric_key="poly_trig", consumer="ops/fasttrig.py",
         doc="polynomial sin/cos pair in the search kernels"),
    Knob("CRIMP_TPU_GRID_FASTPATH", "auto (nharm-based)", "bool",
         numeric_key="grid_fastpath", consumer="ops/search.py",
         doc="f32 uniform-grid fast path vs exact-f64-phase kernel"),
    Knob("CRIMP_TPU_GRID_BLOCKS", "unset (autotuner)", "blocks",
         numeric_key="grid_blocks", consumer="ops/search.py via ops/autotune.py",
         doc="hard (event_block, trial_block) override for the grid kernels"),
    Knob("CRIMP_TPU_GRID_MXU", "unset (off unless a tuner winner)", "int",
         numeric_key="grid_mxu", consumer="ops/search.py via ops/autotune.py",
         doc="factorized angle-addition matmul grid kernels on/off"),
    Knob("CRIMP_TPU_MXU_BF16", "unset (off unless a tuner winner)", "int",
         numeric_key="grid_mxu", consumer="ops/toafit.py + ops/search.py via ops/autotune.py",
         doc="bf16 MXU operands (f32 accumulation) for profile sweeps"),
    Knob("CRIMP_TPU_DELTA_FOLD", "unset (off unless a tuner winner)", "int",
         numeric_key="delta_fold", consumer="ops/anchored.py via ops/autotune.py",
         doc="incremental delta-fold engine on/off"),
    Knob("CRIMP_TPU_DELTA_FOLD_BUDGET", "1e-9 cycles", "float",
         numeric_key="delta_fold", consumer="ops/deltafold.py via ops/autotune.py",
         doc="delta-fold precision-guard budget"),
    Knob("CRIMP_TPU_MCMC_DELTA", "unset (off unless a tuner winner)", "int",
         numeric_key="mcmc_delta",
         consumer="pipelines/fit_toas.py via ops/autotune.py",
         doc="delta-basis MCMC likelihood (batched-matmul proposals) on/off"),
    # -- throughput / caching (bit-identical by construction) ---------------
    Knob("CRIMP_TPU_SHARD", "auto", "bool", consumer="parallel/mesh.py",
         doc="multi-chip auto-sharding opt-out (mesh-shape invariance is pinned by tests)"),
    Knob("CRIMP_TPU_AUTOTUNE", "auto", "enum", consumer="ops/autotune.py",
         doc="tuner policy: off / auto (cached winners only) / eager"),
    Knob("CRIMP_TPU_AUTOTUNE_CACHE", "~/.cache/crimp_tpu/autotune.json", "path",
         consumer="ops/autotune.py",
         doc="fingerprinted tuner-winner cache location"),
    Knob("CRIMP_TPU_TOA_DENSE_WINDOW", "unset (auto: 32)", "int",
         consumer="ops/toafit.py via ops/autotune.py",
         doc="dense error-scan first-window width (any value is bit-identical)"),
    Knob("CRIMP_TPU_STREAM_MIN_EVENTS", "unset (2^22)", "int",
         consumer="ops/search.py + ops/resumable.py",
         doc="event count above which grid chunks stream double-buffered (bit-exact)"),
    Knob("CRIMP_TPU_FOLD_CACHE", "unset (in-process LRU)", "enum",
         consumer="ops/deltafold.py",
         doc="fold-product cache tier: off / mem / disk / explicit dir"),
    Knob("CRIMP_TPU_COMPILE_CACHE", "~/.cache/crimp_tpu/jax_cache", "path",
         consumer="utils/platform.py (import-time config)",
         doc="persistent jax compilation cache dir; 0/off/none disables"),
    Knob("CRIMP_TPU_COMPILE_CACHE_MIN_S", "0", "float",
         consumer="utils/platform.py",
         doc="minimum compile seconds before a kernel persists to the cache"),
    Knob("CRIMP_TPU_TRACE_DIR", "unset", "path", consumer="utils/profiling.py",
         doc="jax.profiler trace directory for the hot pipeline stages"),
    Knob("CRIMP_TPU_MULTISOURCE", "unset (batched engine on)", "int",
         consumer="pipelines/survey.py via ops/autotune.py",
         doc="survey multi-source batch engine on/off (0 forces the "
             "per-source loop; per-source bits are padding-exact either way)"),
    Knob("CRIMP_TPU_MULTISOURCE_MAX_PAD", "4.0", "float",
         consumer="ops/multisource.py via ops/autotune.py",
         doc="bucket-merge padding-waste cap for survey source buckets"),
    Knob("CRIMP_TPU_MULTISOURCE_BATCH", "unset (resolved source block)", "int",
         consumer="ops/multisource.py via ops/autotune.py",
         doc="hard cap on sources per batched survey dispatch (0 = no cap)"),
    # -- multi-host execution (bit-identical by construction: the host axis
    #    carries trials/sources, never a reduction — GL005 + the 1/2/4-
    #    process bitwise pins in tests/test_multihost_smoke.py) ------------
    Knob("CRIMP_TPU_DIST", "unset (single process)", "str",
         consumer="parallel/multihost.py",
         doc="jax.distributed bring-up spec 'coordinator:port,num_processes,"
             "process_id'; unset/off = single-process. CPU backends get the "
             "gloo collectives implementation so localhost N-process jobs "
             "(bench_multihost, the multiproc test tier) can psum"),
    # -- observability (host-side telemetry; numeric-neutral by contract) ---
    Knob("CRIMP_TPU_OBS", "unset (off)", "bool", consumer="crimp_tpu/obs",
         doc="flight-recorder telemetry: spans/counters + an atomic run manifest"),
    Knob("CRIMP_TPU_OBS_DIR", "obs_runs", "path", consumer="crimp_tpu/obs",
         doc="where run manifests + JSONL event streams land"),
    Knob("CRIMP_TPU_OBS_EVENTS", "on (when obs is on)", "bool",
         consumer="crimp_tpu/obs",
         doc="append-only JSONL event stream alongside the manifest"),
    Knob("CRIMP_TPU_OBS_HEARTBEAT_S", "30 (when obs is on)", "float",
         consumer="crimp_tpu/obs/heartbeat.py",
         doc="heartbeat period: progress/ETA events + an atomically "
             "rewritten sidecar; 0/off disables"),
    Knob("CRIMP_TPU_OBS_COST", "on (when obs is on)", "bool",
         consumer="crimp_tpu/obs/costmodel.py",
         doc="XLA cost-model capture (flops/bytes per jitted kernel) feeding "
             "the manifest costmodel table and `obs roofline`; 0 disables"),
    Knob("CRIMP_TPU_OBS_HOST", "unset (jax process index)", "int",
         consumer="crimp_tpu/obs/core.py",
         doc="host index override for obs artifact suffixing: processes "
             "sharing CRIMP_TPU_OBS_DIR write host<k>-suffixed event/"
             "heartbeat/manifest files; unset = jax.process_index() when "
             "multi-host, else single-host unsuffixed names"),
    Knob("CRIMP_TPU_HBM_WARN_PCT", "90", "float",
         consumer="crimp_tpu/obs/core.py",
         doc="warn (once per run) when device peak_bytes_in_use exceeds this "
             "percent of bytes_limit at a stage boundary; 0 disables"),
    Knob("CRIMP_TPU_OBS_LEDGER", "unset (off)", "path",
         consumer="bench.py + crimp_tpu/obs/ledger.py",
         doc="append-only performance-ledger JSONL; bench.py appends its "
             "round record there at end of run"),
    # -- bench --------------------------------------------------------------
    Knob("CRIMP_TPU_BENCH_PLATFORM", "unset", "str", consumer="bench.py",
         doc="skip the bench's relay platform probe and label records with this"),
    Knob("CRIMP_TPU_BENCH_PROBE_DEADLINE_S", "2400", "float", consumer="bench.py",
         doc="total wall-clock budget for the bench's accelerator probe loop"),
    Knob("CRIMP_TPU_RELAY_PORT", "8113", "int",
         consumer="bench.py + scripts/watch_relay.sh",
         doc="accelerator relay TCP port the probe loop polls"),
    Knob("CRIMP_TPU_BENCH_PARTIAL", "unset", "path",
         consumer="bench.py + scripts/extract_rates.py",
         doc="per-sub-measurement sidecar path (session scripts set it; the "
             "extractor reads it back)"),
    Knob("CRIMP_TPU_BENCH_SCALE", "1.0", "float", consumer="bench.py",
         doc="multiplies every bench workload size (with per-stage floors)"),
    # -- session orchestration (shell) + test tier --------------------------
    Knob("CRIMP_TPU_SESSION_DEADLINE", "unset", "int",
         consumer="scripts/onchip_session.sh + scripts/watch_relay.sh",
         doc="epoch-seconds deadline past which session stages are skipped"),
    Knob("CRIMP_TPU_SESSION_DRYRUN", "0", "bool",
         consumer="scripts/onchip_session.sh",
         doc="run the session orchestration on CPU at tiny scale, relay untouched"),
    Knob("CRIMP_TPU_PROBE_BACKOFF_S", "3600", "float",
         consumer="scripts/watch_relay.sh",
         doc="suppress fallback relay probes this long after a timeout-killed one"),
    Knob("CRIMP_TPU_RUN_TPU_TESTS", "unset", "bool",
         consumer="tests/test_tpu_tier.py + scripts/onchip_session.sh",
         doc="opt into the opportunistic on-chip test tier"),
    Knob("CRIMP_TPU_TIER_FORCE_CPU", "unset", "bool",
         consumer="tests/test_tpu_tier.py + scripts/onchip_session.sh",
         doc="run the tier's workloads at tiny scale on CPU (dry-run plumbing)"),
    # -- serving (host-side orchestration; numeric-neutral by contract) -----
    Knob("CRIMP_TPU_SERVE_QUEUE", "64", "int",
         consumer="crimp_tpu/serve/admission.py",
         doc="admission-queue capacity; a full queue rejects new requests "
             "with a typed RESOURCE_EXHAUSTED (backpressure, never "
             "unbounded blocking)"),
    Knob("CRIMP_TPU_SERVE_DEADLINE_MS", "unset (no default deadline)", "float",
         consumer="crimp_tpu/serve/scheduler.py",
         doc="default per-request deadline for requests submitted without "
             "one; the scheduler degrades pre-emptively when the remaining "
             "budget cannot afford the top ladder rung"),
    Knob("CRIMP_TPU_SERVE_BREAKER", "5", "int",
         consumer="crimp_tpu/serve/breaker.py",
         doc="consecutive classified failures at a ladder rung before its "
             "circuit breaker opens (half-opens on probe); 0 disables"),
    Knob("CRIMP_TPU_SERVE_WARM_BATCH", "unset (batched warm path on)", "int",
         consumer="crimp_tpu/serve/engine.py via ops/autotune.py",
         doc="warm re-timing path: 1 stacks every warm client's delta "
             "refold into one refold_batch dispatch, 0 pins the "
             "per-request loop; per-client bits match the solo refold "
             "either way"),
    Knob("CRIMP_TPU_SERVE_PREP_OVERLAP", "unset (overlap on)", "bool",
         consumer="crimp_tpu/serve/engine.py",
         doc="overlap host-side request prep (longdouble anchoring) with "
             "the previous round's dispatch on a bounded single-worker "
             "stage; 0 pins the serial prep order (results bit-identical "
             "either way)"),
    # -- resilience ---------------------------------------------------------
    Knob("CRIMP_TPU_FAULTS", "unset (injector disarmed)", "str",
         consumer="crimp_tpu/resilience/faultinject.py",
         doc="deterministic fault plan 'kind:point:n,...' for chaos tests "
             "(test instrumentation; never set in production)"),
    Knob("CRIMP_TPU_RETRIES", "1", "int",
         consumer="crimp_tpu/resilience/policy.py",
         doc="same-mode retries after a transient classified failure "
             "(a successful retry is bit-identical)"),
    Knob("CRIMP_TPU_BACKOFF_S", "0.05", "float",
         consumer="crimp_tpu/resilience/policy.py",
         doc="base retry backoff; doubles per attempt with deterministic "
             "jitter (0 disables sleeping)"),
))


def knob(name: str) -> Knob:
    """Look up a declared knob; unknown names raise (register first)."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a registered CRIMP_TPU knob; declare it in "
            "crimp_tpu/knobs.py REGISTRY (see docs/analysis.md)"
        ) from None


def raw(name: str) -> str:
    """The stripped env value of a REGISTERED knob ('' when unset).

    This is the single sanctioned ``os.environ`` read for CRIMP_TPU
    names; graftlint GL003 flags reads anywhere else.
    """
    knob(name)  # unknown names raise — registration is not optional
    return os.environ.get(name, "").strip()  # graftlint: disable=GL003 (the registry's own accessor — the one sanctioned CRIMP_TPU env read)


def is_set(name: str) -> bool:
    """Whether the knob has a non-blank value in the environment."""
    return bool(raw(name))


def parse_onoff(value: str) -> bool | None:
    """True for the ON_WORDS, False for the OFF_WORDS, None otherwise.

    The shared truthy-string parser: callers decide whether None means
    "auto", "unset" or "malformed" (their contracts differ and are pinned
    by tests), but the recognized spellings are uniform everywhere.
    """
    low = value.strip().lower()
    if low in ON_WORDS:
        return True
    if low in OFF_WORDS:
        return False
    return None


def env_onoff(name: str, *, auto_ok: bool = True) -> bool | None:
    """Parse a boolean-word knob: True/False for on/off words, None for
    unset (or explicit "auto" when ``auto_ok``); anything else raises —
    silently treating a typo ('of', 'yes') as unset would pick whatever
    the auto-default is, the opposite of what the user plausibly meant.
    """
    env = raw(name)
    state = parse_onoff(env)
    if state is not None:
        return state
    if not env or (auto_ok and env.lower() == "auto"):
        return None
    raise ValueError(
        f"{name}={env!r} not recognized; use 1/on/true/always, "
        "0/off/false/never" + (", or auto/unset for the default" if auto_ok
                               else "")
    )


def env_nonneg_int(name: str, valid=None) -> int | None:
    """Parse an integer knob; unset/blank -> None, malformed raises
    (matching CRIMP_TPU_GRID_BLOCKS: a typo'd override must not silently
    fall back to defaults). Word forms deliberately raise here — tests pin
    that "on"/"yes" are typos for the strict 0/1 switches."""
    env = raw(name)
    if not env:
        return None
    try:
        val = int(env)
    except ValueError:
        raise ValueError(f"{name}={env!r} is not an integer") from None
    if val < 0 or (valid is not None and val not in valid):
        allowed = "/".join(map(str, valid)) if valid else ">= 0"
        raise ValueError(f"{name}={env!r} out of range (expected {allowed})")
    return val


def env_pos_float(name: str) -> float | None:
    """Parse a positive-float knob; unset/blank -> None, malformed or
    non-positive/non-finite raises (same typo discipline as
    :func:`env_nonneg_int`)."""
    env = raw(name)
    if not env:
        return None
    try:
        val = float(env)
    except ValueError:
        raise ValueError(f"{name}={env!r} is not a number") from None
    if not (0.0 < val < float("inf")):
        raise ValueError(f"{name}={env!r} out of range (expected > 0)")
    return val


def env_float(name: str, default: float) -> float:
    """Parse a float knob with a default for unset/blank; malformed raises."""
    env = raw(name)
    if not env:
        return float(default)
    try:
        return float(env)
    except ValueError:
        raise ValueError(f"{name}={env!r} is not a number") from None


def env_int(name: str, default: int) -> int:
    """Parse an integer knob with a default for unset/blank; malformed raises."""
    env = raw(name)
    if not env:
        return int(default)
    try:
        return int(env)
    except ValueError:
        raise ValueError(f"{name}={env!r} is not an integer") from None


def env_str(name: str, default: str = "") -> str:
    """The stripped string value, or ``default`` when unset/blank."""
    return raw(name) or default


def cache_home() -> str:
    """$XDG_CACHE_HOME or ~/.cache — the shared base for every on-disk
    cache tier (autotune winners, fold products, jax compile cache)."""
    return os.environ.get("XDG_CACHE_HOME", "").strip() or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
