"""Timing model as a dense JAX pytree (struct-of-arrays).

The reference carries timing models as string-keyed dicts
(readtimingmodel.py:212-233) which cannot be traced or vmapped. Here the
model is a fixed-shape pytree — F0..F12 as a (13,) vector, glitches as
padded (G,) columns, whitening waves as padded (W,) A/B coefficient
vectors — so phase folding jits once and vmaps over models (needed for the
ensemble-MCMC timing fits) as well as over event batches.

Padding conventions (mask-safe under jit/vmap):
- unused glitch rows have GLEP = +inf (the ``t >= GLEP`` mask is never true)
  and GLTD = 1 (avoids 0/0 in the recovery term; same default as the
  reference reader, readtimingmodel.py:120);
- unused wave harmonics have A = B = 0.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu.io.parfile import get_parameter_value, read_timing_model

N_FREQ_TERMS = 13  # F0..F12


@jax.tree_util.register_dataclass
@dataclass
class TimingParams:
    """Dense, jittable timing model: Taylor spin terms + glitches + waves."""

    pepoch: jax.Array  # scalar, MJD
    f: jax.Array  # (13,) frequency and derivatives F0..F12
    glep: jax.Array  # (G,) glitch epochs, MJD (+inf padding)
    glph: jax.Array  # (G,) phase jumps
    glf0: jax.Array  # (G,) frequency jumps
    glf1: jax.Array  # (G,) fdot jumps
    glf2: jax.Array  # (G,) fddot jumps
    glf0d: jax.Array  # (G,) decaying frequency jumps
    gltd: jax.Array  # (G,) recovery timescales, days (1.0 padding)
    wave_epoch: jax.Array  # scalar, MJD
    wave_om: jax.Array  # scalar, wave fundamental (rad/day)
    wave_a: jax.Array  # (W,) sine coefficients (0 padding)
    wave_b: jax.Array  # (W,) cosine coefficients (0 padding)

    @property
    def n_glitch(self) -> int:
        return int(self.glep.shape[-1])

    @property
    def n_wave(self) -> int:
        return int(self.wave_a.shape[-1])


def _value(entry) -> float:
    return float(get_parameter_value(entry))


def from_dict(params: dict, n_glitch: int | None = None, n_wave: int | None = None) -> TimingParams:
    """Build a TimingParams pytree from a reference-style parameter dict.

    Accepts both dict shapes ({key: value} and {key: {'value','flag'}}).
    ``n_glitch``/``n_wave`` set padded sizes (for bucketing models of
    different complexity to one compiled shape).
    """
    f = np.zeros(N_FREQ_TERMS)
    for i in range(N_FREQ_TERMS):
        if f"F{i}" in params:
            f[i] = _value(params[f"F{i}"])
    pepoch = _value(params.get("PEPOCH", 0.0))

    gids = []
    for key in params:
        match = re.match(r"GLEP_(\S+)$", key)
        if match:
            gids.append(match.group(1))
    G = max(n_glitch if n_glitch is not None else 0, len(gids))
    glitch_cols = {
        "glep": np.full(G, np.inf),
        "glph": np.zeros(G),
        "glf0": np.zeros(G),
        "glf1": np.zeros(G),
        "glf2": np.zeros(G),
        "glf0d": np.zeros(G),
        "gltd": np.ones(G),
    }
    base_to_col = {
        "GLEP": "glep",
        "GLPH": "glph",
        "GLF0": "glf0",
        "GLF1": "glf1",
        "GLF2": "glf2",
        "GLF0D": "glf0d",
        "GLTD": "gltd",
    }
    for j, gid in enumerate(gids):
        for base, col in base_to_col.items():
            key = f"{base}_{gid}"
            if key in params:
                glitch_cols[col][j] = _value(params[key])

    # Wave harmonics: the reference covers k = 1..N where N is the number of
    # WAVEk entries (calcphase.py:135-146 counts all WAVE* keys then iterates
    # range(1, len-1), which lands on 1..N thanks to WAVEEPOCH and WAVE_OM).
    wave_ks = sorted(
        int(m.group(1)) for key in params if (m := re.match(r"WAVE(\d+)$", key))
    )
    W = max(n_wave if n_wave is not None else 0, len(wave_ks))
    wave_a = np.zeros(W)
    wave_b = np.zeros(W)
    for idx, k in enumerate(wave_ks):
        entry = params[f"WAVE{k}"]
        pair = entry["value"] if isinstance(entry, dict) and "value" in entry else entry
        wave_a[idx] = float(pair["A"])
        wave_b[idx] = float(pair["B"])
    wave_epoch = _value(params.get("WAVEEPOCH", 0.0))
    wave_om = _value(params.get("WAVE_OM", 0.0))

    # Leaves stay HOST-side numpy: scalars parked on this TPU lose ~2.5 ulps
    # (emulated f64), which alone breaks the <1 µs ToA budget via PEPOCH.
    # jit/vmap accept numpy leaves and transfer them at call time; the
    # precision-critical paths (ops.anchored, ops.ephem host twins) read
    # them exactly from host memory.
    as_f64 = lambda x: np.asarray(x, dtype=np.float64)
    return TimingParams(
        pepoch=as_f64(pepoch),
        f=as_f64(f),
        glep=as_f64(glitch_cols["glep"]),
        glph=as_f64(glitch_cols["glph"]),
        glf0=as_f64(glitch_cols["glf0"]),
        glf1=as_f64(glitch_cols["glf1"]),
        glf2=as_f64(glitch_cols["glf2"]),
        glf0d=as_f64(glitch_cols["glf0d"]),
        gltd=as_f64(glitch_cols["gltd"]),
        wave_epoch=as_f64(wave_epoch),
        wave_om=as_f64(wave_om),
        wave_a=as_f64(wave_a),
        wave_b=as_f64(wave_b),
    )


def from_par(path: str, n_glitch: int | None = None, n_wave: int | None = None) -> TimingParams:
    """Read a .par file into a TimingParams pytree."""
    values, _, _ = read_timing_model(path)
    return from_dict(values, n_glitch=n_glitch, n_wave=n_wave)


def resolve(timMod) -> TimingParams:
    """Accept a TimingParams, a parameter dict, or a .par path."""
    if isinstance(timMod, TimingParams):
        return timMod
    if isinstance(timMod, dict):
        return from_dict(timMod)
    return from_par(str(timMod))
