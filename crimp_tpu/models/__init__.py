from crimp_tpu.models.timing import TimingParams
from crimp_tpu.models import profiles

__all__ = ["TimingParams", "profiles"]
