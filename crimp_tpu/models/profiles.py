"""Pulse-profile template families as JAX functions on a dense pytree.

The three families mirror the reference templates (templatemodels.py:24-329):

- Fourier series on phases in cycles [0,1):
    f(x) = norm + sum_j amp_j*ampShift * cos(j*2pi*x + ph_j - j*phShift)
- wrapped Cauchy (Lorentzian) on phases in radians [0,2pi):
    f(x) = norm + sum_j amp_j*ampShift/(2pi) * sinh(wid_j) /
                  (cosh(wid_j) - cos(x - cen_j - phShift))
- von Mises (wrapped Gaussian) on phases in radians:
    f(x) = norm + sum_j amp_j*ampShift/(2pi*I0(1/wid_j^2)) *
                  exp(cos(x - cen_j - phShift)/wid_j^2)

and the two likelihoods each family carries:

- a binned Gaussian log-likelihood for template construction,
- an unbinned extended Poisson log-likelihood for ToA extraction, with the
  reference's -inf guard when the normalized model goes non-positive
  (templatemodels.py:113-115,220-222,324-326) implemented mask-safely so a
  bad batch element cannot NaN-poison a vmap.

Parameters live in a fixed-shape ProfileParams pytree so fits vmap over ToA
segments. ``phShift``/``ampShift`` are the ToA observables.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.scipy.special import i0

FOURIER = "fourier"
CAUCHY = "cauchy"
VONMISES = "vonmises"
KINDS = (FOURIER, CAUCHY, VONMISES)


@jax.tree_util.register_dataclass
@dataclass
class ProfileParams:
    """Dense template parameters; ``loc`` is ph_k (Fourier) or cen_k."""

    norm: jax.Array  # scalar
    amp: jax.Array  # (K,)
    loc: jax.Array  # (K,)
    wid: jax.Array  # (K,) — unused (zeros) for Fourier
    ph_shift: jax.Array  # scalar
    amp_shift: jax.Array  # scalar

    @property
    def n_comp(self) -> int:
        return int(self.amp.shape[-1])

    def replace(self, **kw) -> "ProfileParams":
        from dataclasses import replace as _replace

        return _replace(self, **kw)


def from_template(template: dict, ph_shift: float = 0.0, amp_shift: float = 1.0) -> tuple[str, ProfileParams]:
    """(kind, params) from a template dict as read by io.template."""
    kind = template["model"].casefold()
    n = int(template["nbrComp"])
    value = lambda key: float(template[key]["value"]) if isinstance(template[key], dict) else float(template[key])
    amp = jnp.array([value(f"amp_{k}") for k in range(1, n + 1)])
    if kind == FOURIER:
        loc = jnp.array([value(f"ph_{k}") for k in range(1, n + 1)])
        wid = jnp.zeros(n)
    else:
        loc = jnp.array([value(f"cen_{k}") for k in range(1, n + 1)])
        wid = jnp.array([value(f"wid_{k}") for k in range(1, n + 1)])
    params = ProfileParams(
        norm=jnp.asarray(value("norm"), dtype=jnp.float64),
        amp=amp.astype(jnp.float64),
        loc=loc.astype(jnp.float64),
        wid=wid.astype(jnp.float64),
        ph_shift=jnp.asarray(ph_shift, dtype=jnp.float64),
        amp_shift=jnp.asarray(amp_shift, dtype=jnp.float64),
    )
    return kind, params


def to_theta(kind: str, params: ProfileParams) -> dict:
    """Flat reference-style theta dict (for file writers and reports)."""
    import numpy as np

    theta = {
        "norm": float(params.norm),
        "phShift": float(params.ph_shift),
        "ampShift": float(params.amp_shift),
    }
    for j in range(params.n_comp):
        theta[f"amp_{j + 1}"] = float(np.asarray(params.amp)[j])
        if kind == FOURIER:
            theta[f"ph_{j + 1}"] = float(np.asarray(params.loc)[j])
        else:
            theta[f"cen_{j + 1}"] = float(np.asarray(params.loc)[j])
            theta[f"wid_{j + 1}"] = float(np.asarray(params.wid)[j])
    return theta


# ---------------------------------------------------------------------------
# Curves
# ---------------------------------------------------------------------------


def fourier_curve(params: ProfileParams, x: jax.Array) -> jax.Array:
    """Fourier-series rate curve at phases x (cycles)."""
    j = jnp.arange(1, params.n_comp + 1, dtype=x.dtype)
    # (K, N) angles; K is small and static so the outer product stays cheap.
    angles = j[:, None] * (2 * jnp.pi) * x[None, :] + params.loc[:, None] - j[:, None] * params.ph_shift
    return params.norm + jnp.sum(
        params.amp[:, None] * params.amp_shift * jnp.cos(angles), axis=0
    )


def cauchy_curve(params: ProfileParams, x: jax.Array) -> jax.Array:
    """Wrapped-Cauchy rate curve at phases x (radians)."""
    delta = x[None, :] - params.loc[:, None] - params.ph_shift
    comps = (
        (params.amp[:, None] * params.amp_shift / (2 * jnp.pi))
        * jnp.sinh(params.wid[:, None])
        / (jnp.cosh(params.wid[:, None]) - jnp.cos(delta))
    )
    return params.norm + jnp.sum(comps, axis=0)


def vonmises_curve(params: ProfileParams, x: jax.Array) -> jax.Array:
    """von Mises rate curve at phases x (radians)."""
    kappa = 1.0 / params.wid**2
    delta = x[None, :] - params.loc[:, None] - params.ph_shift
    comps = (
        params.amp[:, None]
        * params.amp_shift
        / (2 * jnp.pi * i0(kappa[:, None]))
        * jnp.exp(kappa[:, None] * jnp.cos(delta))
    )
    return params.norm + jnp.sum(comps, axis=0)


_CURVES = {FOURIER: fourier_curve, CAUCHY: cauchy_curve, VONMISES: vonmises_curve}


def curve(kind: str, params: ProfileParams, x: jax.Array) -> jax.Array:
    return _CURVES[kind](params, x)


def extended_norm_factor(kind: str, params: ProfileParams) -> jax.Array:
    """Normalization used by the extended likelihood.

    Fourier normalizes by ``norm``; von Mises / Cauchy by
    2*pi*norm + sum_j amp_j*ampShift (templatemodels.py:110-121, 213-226).
    """
    if kind == FOURIER:
        return params.norm
    return 2 * jnp.pi * params.norm + jnp.sum(params.amp * params.amp_shift)


# ---------------------------------------------------------------------------
# Likelihoods
# ---------------------------------------------------------------------------


def binned_loglik(kind: str, params: ProfileParams, x: jax.Array, y: jax.Array, y_err: jax.Array) -> jax.Array:
    """Gaussian log-likelihood of binned rates y +/- y_err at phases x."""
    model = curve(kind, params, x)
    resid = (y - model) / y_err
    return jnp.sum(-0.5 * resid**2 - 0.5 * jnp.log(2 * jnp.pi * y_err**2))


def extended_loglik(
    kind: str,
    params: ProfileParams,
    x: jax.Array,
    exposure: jax.Array,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Unbinned extended Poisson log-likelihood of event phases x.

    ``mask`` marks valid events (for padded/bucketed ragged segments);
    returns -inf when the normalized model dips non-positive anywhere on the
    (masked) event set, without generating NaNs.
    """
    model = curve(kind, params, x)
    norm_factor = extended_norm_factor(kind, params)
    normalized = model / norm_factor

    if mask is None:
        n_events = x.shape[-1] * jnp.ones((), dtype=x.dtype)
        min_val = jnp.min(normalized)
        log_sum = jnp.sum(jnp.log(jnp.clip(normalized, 1e-300)))
    else:
        n_events = jnp.sum(mask)
        min_val = jnp.min(jnp.where(mask, normalized, jnp.inf))
        log_sum = jnp.sum(jnp.where(mask, jnp.log(jnp.clip(normalized, 1e-300)), 0.0))

    if kind == FOURIER:
        expected = params.norm * exposure
    else:
        expected = norm_factor * exposure / (2 * jnp.pi)
    value = -expected + n_events * jnp.log(expected) + log_sum
    return jnp.where(min_val <= 0, -jnp.inf, value)
