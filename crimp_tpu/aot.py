"""AOT warmup: lower-and-compile the hot kernels before the timed window.

Every scarce relay window was burning minutes JIT-compiling the same four
kernels before measuring anything. ``warmup()`` pays that cost up front —
ideally right after session start, while the chip grant is fresh — by
AOT-lowering each hot kernel at its REAL shapes and compiling it. Combined
with the persistent compilation cache (utils/platform.py) the compiled
binaries also survive process restarts, so the second session of a round
warms up from disk in milliseconds.

Shape discipline: the AOT calls must produce exactly the jit-cache entries
the runtime calls will look up. Dynamic arrays are described with
``jax.ShapeDtypeStruct``; *static* scalars (n_freq, nharm, blocks) and
*weak-typed* python floats (f0, df, fdot) are passed as the same python
values the runtime wrappers pass, so the traced avals match bit-for-bit.
Block sizes are resolved through the autotuner exactly as at runtime.
"""

from __future__ import annotations

import time

from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _lower_compile(report: dict, name: str, fn, *args, **kwargs) -> None:
    """AOT-compile one target; record wall time or the error (a warmup
    failure must degrade to 'that kernel JITs later', never crash)."""
    t0 = time.perf_counter()
    try:
        fn.lower(*args, **kwargs).compile()
        report["targets"][name] = {"s": round(time.perf_counter() - t0, 3)}
    except Exception as exc:  # noqa: BLE001  # graftlint: disable=GL006 (warmup is pre-run: a failed lower/compile means the kernel JITs at first use; the error string is the report, there is no retry/degradation decision to feed)
        report["targets"][name] = {
            "error": f"{type(exc).__name__}: {str(exc)[:200]}"
        }
        logger.warning("warmup target %s failed: %s", name, exc)


def warmup(
    n_events: int,
    n_trials: int,
    nharm: int = 2,
    n_fdot: int = 0,
    n_freq_2d: int | None = None,
    poly: bool | None = None,
    toa: dict | None = None,
    mcmc: dict | bool | None = None,
) -> dict:
    """Compile the hot kernels for the given problem shapes.

    - uniform-grid Z^2/H 1-D sums at (n_events, n_trials) — ``poly=None``
      warms BOTH trig paths, since the A/B benchmark times both;
    - the 2-D (f, fdot) grid kernel when ``n_fdot`` > 0 (at ``n_freq_2d``
      trial frequencies, default ``n_trials``);
    - the batched ToA fit when ``toa`` is given: a dict with keys ``tpl``
      (ProfileParams), ``n_segments``, ``n_events_max``, and optionally
      ``kind``/``cfg``;
    - the ensemble-MCMC step when ``mcmc`` is given: True for the default
      (32 walkers, 3 dims, 500 steps, standard-normal log-prob) or a dict
      with ``walkers``/``ndim``/``steps`` and optionally ``log_prob_fn``.

    Returns {"targets": {name: {"s": ...} | {"error": ...}}, "total_s",
    "counters"} — counters are the compile/cache telemetry deltas from
    utils.profiling, showing how much came from the persistent cache.
    """
    import jax
    import jax.numpy as jnp

    from crimp_tpu.ops import autotune, search
    from crimp_tpu.utils import profiling

    profiling.install_compile_listeners()
    before = profiling.compile_counters()
    report: dict = {"targets": {}}
    t_start = time.perf_counter()

    times_sds = jax.ShapeDtypeStruct((int(n_events),), jnp.float64)
    # f0/df values are irrelevant to the compiled program (weak-typed f64
    # scalars are traced by aval, not value) — any floats produce the same
    # executable the runtime call will look up.
    f0, df = 0.143, 6e-9
    poly_paths = (False, True) if poly is None else (bool(poly),)

    eb, tb = autotune.resolve_blocks("grid", int(n_events), int(n_trials))
    for p in poly_paths:
        _lower_compile(
            report, f"harmonic_sums_uniform[poly={int(p)}]",
            search.harmonic_sums_uniform, times_sds, f0, df, int(n_trials),
            int(nharm), event_block=eb, trial_block=tb, poly=p,
        )

    if n_fdot:
        nf2 = int(n_freq_2d if n_freq_2d is not None else n_trials)
        eb2, tb2 = autotune.resolve_blocks("grid", int(n_events), nf2)
        fdots_sds = jax.ShapeDtypeStruct((int(n_fdot),), jnp.float64)
        for p in poly_paths:
            _lower_compile(
                report, f"harmonic_sums_uniform_2d[poly={int(p)}]",
                search.harmonic_sums_uniform_2d, times_sds, f0, df, nf2,
                fdots_sds, int(nharm), event_block=eb2, trial_block=tb2,
                poly=p,
            )

    if toa is not None:
        from crimp_tpu.ops import toafit

        kind = toa.get("kind", toafit.ToAFitConfig().kind)
        cfg = toa.get("cfg", toafit.ToAFitConfig(kind=kind))
        s, n = int(toa["n_segments"]), int(toa["n_events_max"])
        _lower_compile(
            report, "fit_toas_batch",
            toafit.fit_toas_batch, kind, toa["tpl"],
            jax.ShapeDtypeStruct((s, n), jnp.float64),
            jax.ShapeDtypeStruct((s, n), jnp.bool_),
            jax.ShapeDtypeStruct((s,), jnp.float64),
            cfg,
        )

    if mcmc:
        from crimp_tpu.ops import mcmc as mcmc_mod

        spec = mcmc if isinstance(mcmc, dict) else {}
        walkers = int(spec.get("walkers", 32))
        ndim = int(spec.get("ndim", 3))
        steps = int(spec.get("steps", 500))
        log_prob_fn = spec.get(
            "log_prob_fn", lambda p: -0.5 * jnp.sum(p * p)
        )
        # the jitted core behind ensemble_sample: log_prob_fn/steps are
        # static, observations (``data``, None for the closure form) and
        # stretch_a travel traced — same avals as the runtime wrapper
        _lower_compile(
            report, "ensemble_sample",
            mcmc_mod._ensemble_core, log_prob_fn,
            jax.ShapeDtypeStruct((walkers, ndim), jnp.float64),
            spec.get("data") if isinstance(mcmc, dict) else None,
            steps, jax.random.PRNGKey(0), 2.0,
        )

    after = profiling.compile_counters()
    report["total_s"] = round(time.perf_counter() - t_start, 3)
    report["counters"] = {
        "cache_hits": after["cache_hits"] - before["cache_hits"],
        "cache_misses": after["cache_misses"] - before["cache_misses"],
        "backend_compile_s": round(
            after["backend_compile_s"] - before["backend_compile_s"], 4),
        "cache_retrieval_s": round(
            after["cache_retrieval_s"] - before["cache_retrieval_s"], 4),
    }
    n_ok = sum(1 for t in report["targets"].values() if "s" in t)
    logger.info("warmup: %d/%d targets compiled in %.2fs (%d cache hits)",
                n_ok, len(report["targets"]), report["total_s"],
                report["counters"]["cache_hits"])
    return report
