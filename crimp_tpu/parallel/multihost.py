"""Multi-host / multi-slice mesh construction (ICI- and DCN-aware).

``parallel.mesh`` defines the framework's sharding semantics (event axis
psum-reduced, trial/segment axes communication-free) on ANY mesh; this
module builds the meshes that make those semantics fast at pod scale:

- ``initialize()`` — one-call ``jax.distributed`` bring-up so every host
  in a pod slice (or multi-slice job) sees the GLOBAL device list. On
  TPU pods all arguments auto-detect from the environment.
- ``topology_mesh()`` — the single-slice mesh, with device order chosen
  by ``mesh_utils.create_device_mesh`` so the event axis (the psum axis,
  the only one that communicates per block) rides contiguous ICI rings
  rather than the arbitrary enumeration order a plain reshape gives.
- ``hybrid_mesh()`` — the multi-slice mesh: the TRIAL axis spans slices
  over DCN (its only traffic is the final result gather) while the
  EVENT axis stays inside each slice on ICI. This is exactly the
  "collectives ride ICI, not DCN" layout the sharded kernels assume.

The reference has no distributed layer at all (SURVEY.md §2.4); this is
the TPU-native substitute for the NCCL/MPI backend a CUDA framework
would carry. Correctness never depends on device order — the suite pins
mesh-shape invariance — so these builders are pure performance layout.
"""

from __future__ import annotations

from jax.sharding import Mesh

from crimp_tpu.parallel.mesh import EVENT_AXIS, TRIAL_AXIS, build_mesh


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               **kwargs) -> None:
    """Bring up jax.distributed so jax.devices() is the global pod view.

    On TPU pods every argument auto-detects (call with no arguments in
    each host process before any other JAX call); elsewhere pass the
    coordinator's ``host:port``, the process count, and this process's
    rank. Safe to document-and-skip on a single host: calling JAX
    without it simply keeps the local device view.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def process_identity() -> tuple[int, int]:
    """``(process_index, process_count)`` of this host in the job.

    The obs layer keys per-host artifact suffixes off this (each host's
    event stream / heartbeat / manifest gets a ``host<k>`` suffix, later
    joined by ``obs merge``). Never initializes a backend: if no backend
    is live yet — the same peek contract as ``obs.core`` — this reports
    the single-host identity ``(0, 1)`` rather than forcing bring-up.
    """
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0, 1
    try:
        from jax._src import xla_bridge

        if not (getattr(xla_bridge, "_backends", None) or {}):
            return 0, 1
        return int(jax.process_index()), int(jax.process_count())
    except Exception:  # graftlint: disable=GL006 (identity is best-effort telemetry input; a failed peek must mean single-host, never a crash)
        return 0, 1


def topology_mesh(devices=None, event_parallel: int | None = None) -> Mesh:
    """A 2-D (events x trials) mesh with ICI-topology-aware device order.

    Same shape contract as ``mesh.build_mesh`` (all devices on the event
    axis by default); the difference is only the order devices are laid
    onto the grid: ``mesh_utils.create_device_mesh`` places neighbors on
    the event axis so the per-block ``psum`` rides physical ICI rings.
    Falls back to the plain reshape ordering wherever the topology is
    unknown (CPU/virtual devices).
    """
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if event_parallel is None:
        event_parallel = n
    if n % event_parallel != 0:
        raise ValueError(f"{n} devices do not tile into event_parallel={event_parallel}")
    try:
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_device_mesh(
            (event_parallel, n // event_parallel), devices=devices
        )
    except Exception:  # graftlint: disable=GL006 (layout fallback, not a failure path: virtual/CPU devices carry no coords so the enumeration-order mesh is the same contract)
        # virtual/CPU devices carry no coords; order cannot matter there —
        # same contract, enumeration-order layout
        return build_mesh(devices, event_parallel=event_parallel)
    return Mesh(grid, (EVENT_AXIS, TRIAL_AXIS))


def hybrid_mesh(event_parallel_per_slice: int | None = None, devices=None) -> Mesh:
    """A multi-slice (events x trials) mesh: trials across DCN, events on ICI.

    For jobs spanning TPU slices (after ``initialize()``): each slice
    keeps a full event-sharded psum group on its own ICI, and the trial
    axis — whose only communication is the final gather of per-trial
    statistics — spans the slow DCN links between slices. Requires
    devices that report ``slice_index`` (real multi-slice TPU jobs);
    raises ValueError otherwise so callers can fall back to
    ``topology_mesh``.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None in slice_ids or len(slice_ids) < 2:
        raise ValueError(
            "hybrid_mesh needs a multi-slice job (devices reporting "
            "slice_index); use topology_mesh on a single slice"
        )
    from jax.experimental import mesh_utils

    n_slices = len(slice_ids)
    per_slice = len(devices) // n_slices
    if event_parallel_per_slice is None:
        event_parallel_per_slice = per_slice
    if per_slice % event_parallel_per_slice != 0:
        raise ValueError(
            f"{per_slice} devices per slice do not tile into "
            f"event_parallel_per_slice={event_parallel_per_slice}"
        )
    grid = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(event_parallel_per_slice, per_slice // event_parallel_per_slice),
        dcn_mesh_shape=(1, n_slices),
        devices=devices,
    )
    return Mesh(grid, (EVENT_AXIS, TRIAL_AXIS))


def auto_global_mesh(min_devices: int = 2) -> Mesh | None:
    """Best global mesh for this process's device view, or None below
    ``min_devices``: hybrid across slices when the job is multi-slice,
    else the ICI-topology-aware single-slice mesh."""
    import jax

    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    try:
        return hybrid_mesh(devices=devices)
    except ValueError:
        return topology_mesh(devices=devices)


__all__ = [
    "initialize",
    "process_identity",
    "topology_mesh",
    "hybrid_mesh",
    "auto_global_mesh",
    "build_mesh",
]
