"""Multi-host / multi-slice mesh construction (ICI- and DCN-aware).

``parallel.mesh`` defines the framework's sharding semantics (event axis
psum-reduced, trial/segment axes communication-free) on ANY mesh; this
module builds the meshes that make those semantics fast at pod scale:

- ``initialize()`` — one-call ``jax.distributed`` bring-up so every host
  in a pod slice (or multi-slice job) sees the GLOBAL device list. On
  TPU pods all arguments auto-detect from the environment.
- ``topology_mesh()`` — the single-slice mesh, with device order chosen
  by ``mesh_utils.create_device_mesh`` so the event axis (the psum axis,
  the only one that communicates per block) rides contiguous ICI rings
  rather than the arbitrary enumeration order a plain reshape gives.
- ``hybrid_mesh()`` — the multi-slice mesh: the TRIAL axis spans slices
  over DCN (its only traffic is the final result gather) while the
  EVENT axis stays inside each slice on ICI. This is exactly the
  "collectives ride ICI, not DCN" layout the sharded kernels assume.

The reference has no distributed layer at all (SURVEY.md §2.4); this is
the TPU-native substitute for the NCCL/MPI backend a CUDA framework
would carry. Correctness never depends on device order — the suite pins
mesh-shape invariance — so these builders are pure performance layout.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh

from crimp_tpu import knobs
from crimp_tpu.parallel.mesh import (
    EVENT_AXIS,
    SOURCE_AXIS,
    TRIAL_AXIS,
    build_mesh,
)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               **kwargs) -> None:
    """Bring up jax.distributed so jax.devices() is the global pod view.

    On TPU pods every argument auto-detects (call with no arguments in
    each host process before any other JAX call); elsewhere pass the
    coordinator's ``host:port``, the process count, and this process's
    rank. Safe to document-and-skip on a single host: calling JAX
    without it simply keeps the local device view.

    On CPU backends the collectives implementation is switched to gloo
    first (the default CPU backend cannot run cross-process psums), so
    localhost N-process jobs — bench_multihost, the multiproc test tier —
    exercise the same global-mesh dispatch path a pod does.
    """
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax without the option keeps its default  # graftlint: disable=GL006 (bring-up compat shim: a jax build without the gloo option simply keeps single-process semantics)
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


_DIST_STARTED = False


def ensure_distributed() -> tuple[int, int]:
    """Knob-driven bring-up: honor ``CRIMP_TPU_DIST``, return the identity.

    The knob value is ``coordinator:port,num_processes,process_id`` (the
    launcher stamps a distinct ``process_id`` per worker). Unset or an
    off-word means single-process — nothing is initialized. Idempotent:
    once the service is up (by this call or a real pod launcher) the call
    only reports the identity, so library entry points may call it
    unconditionally. The backend is brought up before returning —
    ``process_identity`` deliberately never initializes one, and the
    distributed service alone does not count as a live backend — so the
    identity returned is the JOB's, not the pre-bring-up ``(0, 1)``.
    """
    global _DIST_STARTED

    spec = knobs.raw("CRIMP_TPU_DIST")
    if not spec or knobs.parse_onoff(spec) is False:
        return process_identity()
    live = process_identity()
    if _DIST_STARTED or live != (0, 1):
        return live  # already brought up (or a real pod job)
    parts = [p.strip() for p in spec.split(",")]
    if len(parts) != 3:
        raise ValueError(
            f"CRIMP_TPU_DIST={spec!r}: expected "
            "'coordinator:port,num_processes,process_id'")
    initialize(coordinator_address=parts[0], num_processes=int(parts[1]),
               process_id=int(parts[2]))
    _DIST_STARTED = True
    import jax

    jax.devices()  # force backend bring-up under the distributed service
    return process_identity()


def process_identity() -> tuple[int, int]:
    """``(process_index, process_count)`` of this host in the job.

    The obs layer keys per-host artifact suffixes off this (each host's
    event stream / heartbeat / manifest gets a ``host<k>`` suffix, later
    joined by ``obs merge``). Never initializes a backend: if no backend
    is live yet — the same peek contract as ``obs.core`` — this reports
    the single-host identity ``(0, 1)`` rather than forcing bring-up.
    """
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0, 1
    try:
        from jax._src import xla_bridge

        if not (getattr(xla_bridge, "_backends", None) or {}):
            return 0, 1
        return int(jax.process_index()), int(jax.process_count())
    except Exception:  # graftlint: disable=GL006 (identity is best-effort telemetry input; a failed peek must mean single-host, never a crash)
        return 0, 1


def topology_mesh(devices=None, event_parallel: int | None = None) -> Mesh:
    """A 2-D (events x trials) mesh with ICI-topology-aware device order.

    Same shape contract as ``mesh.build_mesh`` (all devices on the event
    axis by default); the difference is only the order devices are laid
    onto the grid: ``mesh_utils.create_device_mesh`` places neighbors on
    the event axis so the per-block ``psum`` rides physical ICI rings.
    Falls back to the plain reshape ordering wherever the topology is
    unknown (CPU/virtual devices).
    """
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if event_parallel is None:
        event_parallel = n
    if n % event_parallel != 0:
        raise ValueError(f"{n} devices do not tile into event_parallel={event_parallel}")
    try:
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_device_mesh(
            (event_parallel, n // event_parallel), devices=devices
        )
    except Exception:  # graftlint: disable=GL006 (layout fallback, not a failure path: virtual/CPU devices carry no coords so the enumeration-order mesh is the same contract)
        # virtual/CPU devices carry no coords; order cannot matter there —
        # same contract, enumeration-order layout
        return build_mesh(devices, event_parallel=event_parallel)
    return Mesh(grid, (EVENT_AXIS, TRIAL_AXIS))


def hybrid_mesh(event_parallel_per_slice: int | None = None, devices=None) -> Mesh:
    """A multi-slice (events x trials) mesh: trials across DCN, events on ICI.

    For jobs spanning TPU slices (after ``initialize()``): each slice
    keeps a full event-sharded psum group on its own ICI, and the trial
    axis — whose only communication is the final gather of per-trial
    statistics — spans the slow DCN links between slices. Requires
    devices that report ``slice_index`` (real multi-slice TPU jobs);
    raises ValueError otherwise so callers can fall back to
    ``topology_mesh``.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None in slice_ids or len(slice_ids) < 2:
        raise ValueError(
            "hybrid_mesh needs a multi-slice job (devices reporting "
            "slice_index); use topology_mesh on a single slice"
        )
    from jax.experimental import mesh_utils

    n_slices = len(slice_ids)
    per_slice = len(devices) // n_slices
    if event_parallel_per_slice is None:
        event_parallel_per_slice = per_slice
    if per_slice % event_parallel_per_slice != 0:
        raise ValueError(
            f"{per_slice} devices per slice do not tile into "
            f"event_parallel_per_slice={event_parallel_per_slice}"
        )
    grid = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(event_parallel_per_slice, per_slice // event_parallel_per_slice),
        dcn_mesh_shape=(1, n_slices),
        devices=devices,
    )
    return Mesh(grid, (EVENT_AXIS, TRIAL_AXIS))


def host_device_grid(devices=None) -> np.ndarray:
    """Global devices as a (process_count, local_per_host) host-major grid.

    Row ``k`` is process ``k``'s addressable devices — the ICI domain a
    per-host event psum stays inside. Requires a rectangular job (every
    host contributes the same device count), which is how both pods and
    the localhost N-process CPU jobs are launched.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    devices = sorted(devices, key=lambda d: (int(getattr(d, "process_index", 0)),
                                             int(d.id)))
    counts: dict[int, int] = {}
    for d in devices:
        counts[int(getattr(d, "process_index", 0))] = \
            counts.get(int(getattr(d, "process_index", 0)), 0) + 1
    per_host = set(counts.values())
    if len(per_host) > 1:
        raise ValueError(
            f"non-rectangular job: per-host device counts {sorted(counts.items())}")
    return np.asarray(devices).reshape(len(counts), per_host.pop())


def global_grid_mesh(devices=None) -> Mesh:
    """The 2-D (events x trials) mesh of a multi-process job.

    The TRIAL axis spans hosts over DCN (its only traffic is the final
    per-trial result gather); the EVENT axis is each host's local devices
    on ICI, so the per-block event psum of the grid kernels never leaves
    a host. Existing sharded twins (``z2_sharded`` & co.) dispatch on
    this mesh unchanged — the axis names are the canonical ones.
    """
    grid = host_device_grid(devices)
    return Mesh(grid.T, (EVENT_AXIS, TRIAL_AXIS))


def global_source_mesh(devices=None) -> Mesh:
    """The 1-D source mesh of a multi-process job: sources data-parallel
    over every device of every host, host-major — so each host's source
    rows are a contiguous block it can load without ever materializing
    the global batch (see :func:`process_local_rows` / :func:`global_array`)."""
    grid = host_device_grid(devices)
    return Mesh(grid.reshape(-1), (SOURCE_AXIS,))


def process_local_rows(n_rows: int) -> tuple[int, int]:
    """This process's ``[lo, hi)`` block of a host-major leading axis.

    ``n_rows`` must divide evenly across processes (callers pad to the
    global device count first, which is a multiple of the host count)."""
    idx, count = process_identity()
    if n_rows % count:
        raise ValueError(f"{n_rows} rows do not tile across {count} processes")
    per = n_rows // count
    return idx * per, (idx + 1) * per


def global_array(local_rows, mesh: Mesh, spec, global_shape=None):
    """Process-local -> global bridge for host-sharded leading-axis data.

    Each host hands in ONLY its own row block (``process_local_rows`` of
    the global batch) and gets back the global jax.Array laid out by
    ``spec`` on ``mesh`` — ``jax.make_array_from_process_local_data``
    stitches the per-host shards without any host ever holding the whole
    batch. Single-process jobs degrade to a plain ``device_put``.
    """
    import jax
    from jax.sharding import NamedSharding

    local_rows = np.asarray(local_rows)
    sharding = NamedSharding(mesh, spec)
    _, count = process_identity()
    if count <= 1:
        return jax.device_put(local_rows, sharding)
    if global_shape is None:
        global_shape = (local_rows.shape[0] * count,) + local_rows.shape[1:]
    return jax.make_array_from_process_local_data(
        sharding, local_rows, tuple(global_shape))


def replicated_array(full, mesh: Mesh, spec):
    """Place host-replicated data (events, scalars) onto a global mesh.

    Every process holds the full host-side array (the event axis stays
    within a host, so event-sharded inputs are replicated ACROSS hosts);
    the callback form hands each addressable device exactly its shard.
    Single-process jobs degrade to a plain ``device_put``.
    """
    import jax
    from jax.sharding import NamedSharding

    full = np.asarray(full)
    sharding = NamedSharding(mesh, spec)
    _, count = process_identity()
    if count <= 1:
        return jax.device_put(full, sharding)
    return jax.make_array_from_callback(full.shape, sharding,
                                        lambda idx: full[idx])


def fetch_global(arr) -> np.ndarray:
    """Materialize a (possibly cross-host) jax.Array on every host.

    The multi-process twin of ``np.asarray(out)``: single-process arrays
    convert directly; arrays spanning processes go through one tiled
    ``process_allgather`` (the trial/source axis's only DCN traffic —
    the final result gather the mesh layout was chosen around).
    """
    _, count = process_identity()
    if count <= 1 or getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh's devices live on more than one process."""
    procs = {int(getattr(d, "process_index", 0))
             for d in np.asarray(mesh.devices).ravel()}
    return len(procs) > 1


def auto_global_mesh(min_devices: int = 2) -> Mesh | None:
    """Best global mesh for this process's device view, or None below
    ``min_devices``: the host-major 2-D mesh when the job is
    multi-process (trials across hosts over DCN, events on each host's
    local devices), hybrid across slices when the job is multi-slice,
    else the ICI-topology-aware single-slice mesh."""
    import jax

    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    _, count = process_identity()
    if count > 1:
        try:
            return global_grid_mesh(devices)
        except ValueError:
            pass  # non-rectangular job: fall through to the 1-D layouts
    try:
        return hybrid_mesh(devices=devices)
    except ValueError:
        return topology_mesh(devices=devices)


__all__ = [
    "initialize",
    "ensure_distributed",
    "process_identity",
    "topology_mesh",
    "hybrid_mesh",
    "host_device_grid",
    "global_grid_mesh",
    "global_source_mesh",
    "process_local_rows",
    "global_array",
    "replicated_array",
    "fetch_global",
    "spans_processes",
    "auto_global_mesh",
    "build_mesh",
]
