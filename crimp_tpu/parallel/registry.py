"""Declarative sharding registry: kernel/param regex -> PartitionSpec rules.

``parallel/mesh.py`` grew five bespoke sharded twins, each hand-writing its
``in_specs``/``out_specs`` at the ``shard_map`` call site — which meant the
sharding of a kernel lived nowhere the rest of the system could see it. The
observability stack paid for that directly: cost capture skipped every
sharded dispatch because a ``ShapeDtypeStruct`` stand-in loses shardings,
so exactly the multi-device paths had no roofline rows (ROADMAP item 4).

This module is the single source of truth instead: a table of
kernel-name-regex rules, each mapping param-name regexes to
``PartitionSpec``s (first match wins, scalars replicate by default), plus
the kernel's output specs and the mesh axes its internal ``psum`` reduces
over. ``specs_for(kernel, mesh)`` binds a rule to a concrete mesh and
hands back everything a call site or an observer needs:

- ``shard_map`` call sites ask for ``.in_specs(...)`` / ``.out_specs``;
- ``jax.device_put`` call sites ask for ``.named(param, ndim)`` (or the
  ``leading_axis_sharding`` helper for the leading-axis data-parallel
  placements);
- ``obs/costmodel.py`` asks for ``.device_count()`` and
  ``.collective_bytes(out_info)`` so the AOT-lowered per-device program
  gets per-device FLOPs/bytes AND an estimate of the bytes its collectives
  move over ICI.

Migrating a kernel onto the registry is bitwise-neutral by construction:
the specs are the SAME objects the call sites used to write inline — the
table changes where they are written down, not what the partitioner sees.
The 8-device parity pins in tests/test_parallel.py assert exactly that.

graftlint GL007 enforces the discipline: hand-written ``PartitionSpec(...)``
anywhere in ``crimp_tpu/`` outside this module needs a waiver reason.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Mesh axis names — defined HERE (the registry is the bottom of the
# parallel/ import graph); mesh.py re-exports them for compatibility.
EVENT_AXIS = "events"
TRIAL_AXIS = "trials"
SEGMENT_AXIS = "segments"
SOURCE_AXIS = "sources"

REPLICATED = P()


@dataclass(frozen=True)
class KernelRule:
    """One registry row: which kernels it covers and how they shard.

    ``kernel`` and the param patterns are ``re.search`` regexes.
    ``params`` maps param-name patterns to in-specs (first match wins);
    ``outs`` is the output-spec tuple in output order; ``reduce_axes``
    names the mesh axes the kernel psum-reduces over internally (the
    collective the cost model accounts for)."""

    kernel: str
    params: tuple[tuple[str, P], ...]
    outs: tuple[P, ...]
    reduce_axes: tuple[str, ...] = ()
    note: str = ""


RULES: tuple[KernelRule, ...] = (
    KernelRule(
        kernel=r"^sharded_sums_general$",
        params=(
            (r"^(times|weights)$", P(EVENT_AXIS)),
            (r"^freqs$", P(TRIAL_AXIS)),
            (r"^fdots$", P(None)),
        ),
        outs=(P(None, None, TRIAL_AXIS), P(None, None, TRIAL_AXIS)),
        reduce_axes=(EVENT_AXIS,),
        note="arbitrary-grid trig sums: events psum-reduced, freqs "
             "embarrassingly parallel over the trial axis",
    ),
    KernelRule(
        kernel=r"^sharded_sums_grid$",
        params=(
            (r"^(times|weights)$", P(EVENT_AXIS)),
            (r"^fdots$", P(None)),
        ),
        outs=(P(None, None, TRIAL_AXIS), P(None, None, TRIAL_AXIS)),
        reduce_axes=(EVENT_AXIS,),
        note="uniform-grid fast path: frequency range is derived from "
             "axis_index, so only events/weights are array inputs",
    ),
    KernelRule(
        kernel=r"^sharded_sums_grid3d$",
        params=(
            (r"^(times|weights)$", P(EVENT_AXIS)),
            (r"^(fdots|fddots)$", P(None)),
        ),
        outs=(P(None, None, None, TRIAL_AXIS), P(None, None, None, TRIAL_AXIS)),
        reduce_axes=(EVENT_AXIS,),
        note="uniform-grid (f, fdot, fddot) cube: frequency range derived "
             "from axis_index, fdot/fddot axes replicated, events "
             "psum-reduced exactly like the 2-D grid kernel",
    ),
    KernelRule(
        kernel=r"^semicoherent_stack$",
        params=(
            (r"^seg_(times|weights)$", P(SEGMENT_AXIS)),
            (r"^(fdots|fddots)$", P(None)),
        ),
        outs=(P(None, None, None),),
        reduce_axes=(SEGMENT_AXIS,),
        note="semi-coherent cube stack: zero-weight-padded segment rows are "
             "data parallel over the segment axis; the incoherent sum of "
             "per-segment Z^2 terms is the one psum",
    ),
    KernelRule(
        kernel=r"^delta_refold",
        params=(
            (r"^(folded|delta|anchor_idx)$", P(EVENT_AXIS)),
            (r"^(spec|dp)$", REPLICATED),
        ),
        outs=(P(EVENT_AXIS),),
        reduce_axes=(),
        note="per-event basis build + refold matmul; no collective (each "
             "row's dot runs over the replicated dp)",
    ),
    KernelRule(
        kernel=r"^(stacked_fold|toa_fit_batch_multi|source_batch)",
        params=((r".*", P(SOURCE_AXIS)),),
        outs=(P(SOURCE_AXIS),),
        reduce_axes=(),
        note="multisource engine: pure data parallelism over the stacked "
             "source axis; leading-axis sharding, no collectives",
    ),
    KernelRule(
        kernel=r"^segment_batch",
        params=((r".*", P(SEGMENT_AXIS)),),
        outs=(P(SEGMENT_AXIS),),
        reduce_axes=(),
        note="segment-batched ToA fits: data parallel over segments",
    ),
)


def _mesh_axis_size(mesh: Mesh, axis) -> int:
    """Size of one PartitionSpec entry's mesh extent (str or tuple of str)."""
    if axis is None:
        return 1
    if isinstance(axis, str):
        return int(mesh.shape[axis])
    return int(math.prod(int(mesh.shape[a]) for a in axis))


class KernelSharding:
    """A :class:`KernelRule` bound to a concrete mesh — the lookup result."""

    def __init__(self, rule: KernelRule, mesh: Mesh):
        self.rule = rule
        self.mesh = mesh

    # -- specs for dispatch --------------------------------------------------

    def spec(self, param: str, leaf=None) -> P:
        """The in-spec for one parameter (first regex match wins).

        With ``leaf`` given, 0-d leaves fall back to replication — the
        replicate-scalars default — before an unmatched name raises."""
        for pat, sp in self.rule.params:
            if re.search(pat, param):
                return sp
        if leaf is not None and np.ndim(leaf) == 0:
            return REPLICATED
        raise KeyError(
            f"sharding registry: kernel rule {self.rule.kernel!r} has no "
            f"spec for param {param!r} on mesh {dict(self.mesh.shape)} "
            f"(add a row or pass a scalar leaf)")

    def in_specs(self, *names: str) -> tuple[P, ...]:
        return tuple(self.spec(n) for n in names)

    @property
    def out_specs(self):
        """Output specs shaped for ``shard_map``: a lone spec for a
        single-output kernel, the tuple otherwise."""
        outs = self.rule.outs
        return outs[0] if len(outs) == 1 else outs

    def named(self, param: str, leaf=None) -> NamedSharding:
        """The in-spec as a ``NamedSharding`` (for ``jax.device_put`` /
        ``ShapeDtypeStruct`` placement on this mesh)."""
        return NamedSharding(self.mesh, self.spec(param, leaf))

    # -- accounting for the cost model ---------------------------------------

    def device_count(self) -> int:
        return int(math.prod(int(s) for s in self.mesh.shape.values()))

    def reduce_size(self) -> int:
        """Devices participating in the kernel's psum (1 = no collective)."""
        return int(math.prod(
            int(self.mesh.shape[a]) for a in self.rule.reduce_axes)) or 1

    def dcn_axes(self) -> tuple[str, ...]:
        """Mesh axes whose devices span more than one process.

        On the host-major 2-D global mesh this is the trial/source axis —
        the DCN leg — while the event axis stays within a host (ICI).
        Duck-typed over ``mesh.devices`` so stub-device meshes (tests)
        and real multi-process meshes both classify."""
        devs = np.asarray(self.mesh.devices)
        names = tuple(self.mesh.axis_names)
        out = []
        for ax, name in enumerate(names):
            moved = np.moveaxis(devs, ax, 0).reshape(devs.shape[ax], -1)
            for col in range(moved.shape[1]):
                procs = {int(getattr(d, "process_index", 0))
                         for d in moved[:, col]}
                if len(procs) > 1:
                    out.append(name)
                    break
        return tuple(out)

    def _reduced_buffer_bytes(self, out_info) -> float:
        """Per-shard reduced-buffer size B of the kernel's psum (the sum
        over outputs of global bytes / out-spec mesh extent)."""
        total = 0.0
        for sds, out_spec in zip(out_info, self.rule.outs):
            nbytes = (math.prod(int(d) for d in sds.shape)
                      * np.dtype(sds.dtype).itemsize)
            shards = math.prod(_mesh_axis_size(self.mesh, ax)
                               for ax in out_spec) or 1
            total += nbytes / shards
        return total

    def collective_bytes(self, out_info) -> float:
        """Estimated PER-DEVICE bytes the kernel's psum moves (both legs).

        Ring all-reduce over ``k`` devices moves ``2*(k-1)/k * B`` bytes
        per device, where ``B`` is the per-shard reduced-buffer size —
        each global output's bytes divided by the mesh extent of its
        sharded out-spec axes. ``out_info`` is an iterable of objects with
        ``.shape``/``.dtype`` (ShapeDtypeStructs or arrays), one per
        kernel output, in ``outs`` order. 0.0 when the rule reduces over
        nothing or one device."""
        split = self.collective_bytes_split(out_info)
        return split["ici"] + split["dcn"]

    def collective_bytes_split(self, out_info) -> dict[str, float]:
        """The psum's per-device byte estimate split into ICI vs DCN legs.

        Each reduce axis contributes its own ring leg over ``k_axis``
        devices: axes confined to one process ride ICI, axes spanning
        processes ride DCN. On the host-major global mesh the event psum
        therefore lands entirely on the ICI leg (it never leaves a host)
        and only a reduction spanning hosts would put bytes on DCN —
        which is exactly the layout contract ``obs roofline`` verifies."""
        out = {"ici": 0.0, "dcn": 0.0}
        if self.reduce_size() <= 1:
            return out
        buf = self._reduced_buffer_bytes(out_info)
        dcn = set(self.dcn_axes())
        for axis in self.rule.reduce_axes:
            k = _mesh_axis_size(self.mesh, axis)
            if k <= 1:
                continue
            leg = "dcn" if axis in dcn else "ici"
            out[leg] += 2.0 * (k - 1) / k * buf
        return out


def specs_for(kernel: str, mesh: Mesh) -> KernelSharding:
    """The registry lookup: the first rule whose regex matches ``kernel``,
    bound to ``mesh``. Raises ``KeyError`` for unregistered kernels — a
    sharded dispatch with no registry row is a bug, not a default."""
    for rule in RULES:
        if re.search(rule.kernel, kernel):
            return KernelSharding(rule, mesh)
    raise KeyError(
        f"sharding registry: no rule matches kernel {kernel!r}; add a "
        f"KernelRule to crimp_tpu/parallel/registry.py")


def leading_axis_sharding(mesh: Mesh, axis_name: str) -> NamedSharding:
    """Leading-axis data-parallel placement: ``P(axis_name)`` on ``mesh``.

    A spec shorter than the array rank replicates the trailing dims, so
    this is exactly the ``P(axis, None, ..., None)`` the data-parallel
    call sites used to build by hand — for any rank."""
    return NamedSharding(mesh, P(axis_name))
