"""Device meshes and sharded search kernels — the distributed backend.

The reference has no distributed layer at all (SURVEY.md §2.4: no
NCCL/MPI/Gloo anywhere); for the TPU framework the communication backend is
XLA collectives over a ``jax.sharding.Mesh``:

- the EVENT axis (the long axis: 1e5..1e8 photon times) shards across the
  ``events`` mesh axis — the analog of sequence/context parallelism. Each
  device computes partial per-trial harmonic sums over its event shard and
  a ``psum`` ring all-reduce over ICI combines them (the Z^2/H statistics
  are exactly segmented reductions, so blockwise streaming composes with
  the sharding when events exceed HBM);
- the TRIAL axis (frequency, or frequency x fdot tiles) shards across the
  ``trials`` mesh axis with no communication at all — embarrassingly
  parallel tiles, DCN-friendly across slices;
- the SEGMENT axis (independent ToA-interval fits, local-ephemeris
  windows, MCMC walkers) shards batched fits with no communication — the
  data-parallel analog;
- small state (template parameters, timing model) is replicated.

On a v4/v5 pod slice both axes ride ICI; across slices put ``trials`` on
the DCN axis (its only traffic is the final gather).

Inside each event shard the kernels are the same blockwise-streaming ones
the single-device path uses (crimp_tpu.ops.search): HBM stays bounded by
one (trial_block x event_block) tile per device regardless of total scale,
and the uniform-grid f64-lean fast path applies per shard (each trial-mesh
tile owns a contiguous frequency range, so the per-tile f64 row trick
survives sharding).

Product integration: ``auto_mesh()`` is consulted by ``PeriodSearch`` and
the batched ToA fit — a user on a multi-chip host gets all chips without
touching internals; ``CRIMP_TPU_SHARD=0`` opts out. Multi-chip correctness
is asserted in tests on a virtual 8-device CPU mesh (tests/test_parallel.py):
mesh-shape invariance of the statistics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from crimp_tpu import knobs, obs
from crimp_tpu.obs import costmodel

# Every PartitionSpec this module dispatches with comes from the
# declarative registry (GL007); the axis names live there too and are
# re-exported here for the call sites that grew up importing them from
# mesh.
from crimp_tpu.parallel.registry import (
    EVENT_AXIS,
    SEGMENT_AXIS,
    SOURCE_AXIS,
    TRIAL_AXIS,
    leading_axis_sharding,
    specs_for,
)

from crimp_tpu.ops.search import (
    DEFAULT_EVENT_BLOCK,
    DEFAULT_TRIAL_BLOCK,
    DEFAULT_TRIG_DTYPE,
    GRID_EVENT_BLOCK,
    GRID_MXU_RESEED,
    GRID_TRIAL_BLOCK,
    _blocked_trial_sums,
    _resolve_grid3d_mxu,
    _resolve_grid_mxu,
    grid_fastpath_enabled,
    harmonic_sums_uniform_2d,
    harmonic_sums_uniform_2d_mxu,
    harmonic_sums_uniform_3d,
    harmonic_sums_uniform_3d_mxu,
    resolve_blocks,
    uniform_grid,
    z2_from_sums,
)


def sharding_enabled() -> bool:
    """Global opt-out: CRIMP_TPU_SHARD=0/off disables auto sharding.

    Anything that is not an explicit off-word (including garbage) leaves
    sharding enabled — this knob predates the raise-on-typo discipline and
    scripts rely on unset/auto/unknown all meaning "on"."""
    return knobs.parse_onoff(knobs.raw("CRIMP_TPU_SHARD")) is not False


def auto_mesh(min_devices: int = 2) -> Mesh | None:
    """An all-devices event mesh when auto-sharding should kick in, else None.

    This is the product entry point: PeriodSearch and the ToA batch call it
    so a v4-8 user gets 8 chips with no code change (VERDICT r2 item 2;
    reference hot loops this distributes: periodsearch.py:63-106,
    measureToAs.py:168).
    """
    if not sharding_enabled():
        return None
    # Lazy import (multihost builds on this module): same shape contract
    # as build_mesh, but with ICI-topology-aware device order — and the
    # DCN-hybrid layout when the job spans slices. It owns the
    # min-devices threshold (returns None below it).
    from crimp_tpu.parallel.multihost import auto_global_mesh

    return auto_global_mesh(min_devices)


def build_mesh(
    devices=None, event_parallel: int | None = None, axis_names=(EVENT_AXIS, TRIAL_AXIS)
) -> Mesh:
    """A 2-D (events x trials) mesh over the given (or all) devices.

    ``event_parallel`` fixes the event-axis size; by default all devices go
    to the event axis (the data-bound regime of BASELINE configs 3/5)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if event_parallel is None:
        event_parallel = n
    if n % event_parallel != 0:
        raise ValueError(f"{n} devices do not tile into event_parallel={event_parallel}")
    grid = np.asarray(devices).reshape(event_parallel, n // event_parallel)
    return Mesh(grid, axis_names)


def _default_1d_devices():
    """Device list for the 1-D data-parallel meshes: all devices on a
    single-process job, THIS HOST'S devices on a multi-process one. The
    legacy segment/source paths place host arrays with ``jax.device_put``,
    which cannot address another process's devices — the multi-process
    twins route through ``parallel.multihost`` (global source mesh +
    process-local->global bridge) instead."""
    from crimp_tpu.parallel.multihost import process_identity

    return jax.local_devices() if process_identity()[1] > 1 else jax.devices()


def segment_mesh(devices=None) -> Mesh:
    """A 1-D mesh over all (or this host's) devices for segment-batched fits."""
    if devices is None:
        devices = _default_1d_devices()
    return Mesh(np.asarray(devices), (SEGMENT_AXIS,))


def source_mesh(devices=None) -> Mesh:
    """A 1-D mesh over all (or this host's) devices for source-batched survey
    dispatches (ops/multisource stacked folds)."""
    if devices is None:
        devices = _default_1d_devices()
    return Mesh(np.asarray(devices), (SOURCE_AXIS,))


def shard_sources(array, mesh: Mesh):
    """Place a stacked (source-major) array with its leading axis sharded.

    Pure data parallelism for the multisource engine: the stacked fold is
    elementwise per source row, so sharding the leading axis introduces no
    collectives and no reduction-order change — bitwise identical to the
    single-device dispatch (the same contract shard_segments gives the
    ToA-segment fits)."""
    return jax.device_put(np.asarray(array),
                          leading_axis_sharding(mesh, SOURCE_AXIS))


def default_dispatch_mesh() -> Mesh:
    """The mesh the sharded twins dispatch on when the caller passes none:
    the host-major 2-D (events x trials) global mesh on a multi-process
    job — trials across hosts over DCN, the per-block event psum confined
    to each host's local devices — else the classic all-devices-on-events
    mesh."""
    from crimp_tpu.parallel import multihost

    if multihost.process_identity()[1] > 1:
        return multihost.global_grid_mesh()
    return build_mesh()


def _to_mesh(arr, mesh: Mesh, plan, param: str):
    """Host array -> device array laid out by the registry plan.

    Single-process meshes take the plain ``jnp.asarray`` commit the twins
    always used; a mesh spanning processes needs every host-side input
    placed explicitly (each addressable device gets exactly its shard via
    the callback bridge — event/trial inputs are host-replicated, so
    every process holds the full host array)."""
    from crimp_tpu.parallel import multihost

    if multihost.spans_processes(mesh):
        return multihost.replicated_array(np.asarray(arr), mesh,
                                          plan.spec(param, leaf=arr))
    return jnp.asarray(arr)


def _materialize(x, mesh: Mesh) -> np.ndarray:
    """Global-safe ``np.asarray``: results sharded across processes gather
    through one tiled allgather (the trial axis's only DCN traffic)."""
    from crimp_tpu.parallel import multihost

    if multihost.spans_processes(mesh):
        return multihost.fetch_global(x)
    return np.asarray(x)


def _pad_to(x: np.ndarray, multiple: int, fill=0.0):
    n = len(x)
    padded_len = -(-n // multiple) * multiple
    if padded_len == n:
        return np.asarray(x), np.ones(n)
    out = np.full(padded_len, fill, dtype=np.asarray(x).dtype)
    out[:n] = x
    weights = np.zeros(padded_len)
    weights[:n] = 1.0
    return out, weights


# ---------------------------------------------------------------------------
# Sharded trig-sum kernels (blockwise inside each shard)
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("nharm", "mesh", "event_block", "trial_block", "trig_dtype", "poly"),
)
def _sharded_sums_general(
    times,
    weights,
    freqs,
    fdots,
    nharm: int,
    mesh: Mesh,
    event_block: int = DEFAULT_EVENT_BLOCK,
    trial_block: int = DEFAULT_TRIAL_BLOCK,
    trig_dtype=None,
    poly: bool = False,
):
    """Trig sums (n_fdot, nharm, n_freq): events sharded + psum-reduced,
    freqs sharded over the trial axis, blockwise streaming per shard."""
    dtype = DEFAULT_TRIG_DTYPE if trig_dtype is None else trig_dtype

    def kernel(t_shard, w_shard, f_shard, fd_all):
        def one_fd(fd):
            return _blocked_trial_sums(
                t_shard, f_shard, nharm, event_block, trial_block, dtype,
                lambda f_blk, t_blk: f_blk[:, None] * t_blk[None, :]
                + (0.5 * fd) * t_blk[None, :] ** 2,
                weights=w_shard,
                poly=poly,
            )

        # All per-fdot partials first, then ONE stacked all-reduce: a single
        # large psum outside the scan instead of n_fdot small ones inside it
        # (fewer rendezvous, better ICI utilization).
        c_all, s_all = jax.lax.map(one_fd, fd_all)
        return jax.lax.psum(c_all, EVENT_AXIS), jax.lax.psum(s_all, EVENT_AXIS)

    plan = specs_for("sharded_sums_general", mesh)
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=plan.in_specs("times", "weights", "freqs", "fdots"),
        out_specs=plan.out_specs,
    )(times, weights, freqs, fdots)


@partial(
    jax.jit,
    static_argnames=("n_freq", "nharm", "mesh", "event_block", "trial_block",
                     "poly", "mxu", "reseed", "mxu_bf16"),
)
def _sharded_sums_grid(
    times,
    weights,
    f0: float,
    df: float,
    n_freq: int,
    fdots,
    nharm: int,
    mesh: Mesh,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    poly: bool = False,
    mxu: bool = False,
    reseed: int = GRID_MXU_RESEED,
    mxu_bf16: bool = False,
):
    """Uniform-grid fast-path trig sums under sharding.

    ``n_freq`` must be a multiple of the trial-mesh size; each trial tile
    owns the contiguous range starting at f0 + tile*n_freq_shard*df, so the
    per-tile f64-row decomposition of the fast path is preserved. With
    ``mxu`` the per-shard kernel is the factorized matmul variant; the f64
    psum combine is identical either way.
    """
    tr_size = mesh.shape[TRIAL_AXIS]
    n_freq_shard = n_freq // tr_size

    def kernel(t_shard, w_shard, fd_all):
        tile = jax.lax.axis_index(TRIAL_AXIS)
        f0_shard = f0 + (tile * n_freq_shard) * df
        # shared-row 2-D kernel: per-tile f64 frequency rows shared across
        # fdots, per-fdot quadratic rows shared across tiles (same win as
        # the single-device path; see harmonic_sums_uniform_2d)
        if mxu and n_freq_shard % trial_block == 0:
            # pass the GLOBAL f0 plus the shard's first tile index: f_tiles
            # then rounds in the same single f64 multiply as the monolithic
            # kernel, keeping the sharded output bitwise-equal to it
            c_all, s_all = harmonic_sums_uniform_2d_mxu(
                t_shard, f0, df, n_freq_shard, fd_all, nharm,
                event_block, trial_block, weights=w_shard, poly=poly,
                reseed=reseed, mxu_bf16=mxu_bf16,
                tile0=tile * (n_freq_shard // trial_block),
            )
        elif mxu:
            c_all, s_all = harmonic_sums_uniform_2d_mxu(
                t_shard, f0_shard, df, n_freq_shard, fd_all, nharm,
                event_block, trial_block, weights=w_shard, poly=poly,
                reseed=reseed, mxu_bf16=mxu_bf16,
            )
        else:
            c_all, s_all = harmonic_sums_uniform_2d(
                t_shard, f0_shard, df, n_freq_shard, fd_all, nharm,
                event_block, trial_block, weights=w_shard, poly=poly,
            )
        return jax.lax.psum(c_all, EVENT_AXIS), jax.lax.psum(s_all, EVENT_AXIS)

    plan = specs_for("sharded_sums_grid", mesh)
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=plan.in_specs("times", "weights", "fdots"),
        out_specs=plan.out_specs,
    )(times, weights, fdots)


def _fit_block(default: int, per_shard: int) -> int:
    """Shrink a power-of-two block size to the per-shard workload so small
    inputs don't pay for a full default-sized padded tile."""
    block = default
    while block > 16 and block // 2 >= per_shard:
        block //= 2
    return block


def _sharded_sums_nd(times, freqs, fdots, nharm, mesh, trig_dtype, use_fastpath,
                     poly: bool = False, use_mxu: bool | None = None,
                     reseed: int | None = None, mxu_bf16: bool | None = None):
    """(c, s) trig sums of shape (n_fdot, nharm, n_freq) with host-side
    padding to the mesh tiling; dispatches grid fast path vs general."""
    ev_size = mesh.shape[EVENT_AXIS]
    tr_size = mesh.shape[TRIAL_AXIS]
    obs.counter_add("mesh_sharded_calls")
    obs.gauge_set("mesh_devices", ev_size * tr_size)
    n_freq = len(freqs)
    t_pad, w_pad = _pad_to(np.asarray(times, dtype=np.float64), ev_size)
    fd = jnp.asarray(np.atleast_1d(np.asarray(fdots, dtype=np.float64)))
    ev_per_shard = len(t_pad) // ev_size
    tr_per_shard = -(-n_freq // tr_size)

    grid = None
    if trig_dtype is None and grid_fastpath_enabled(nharm, use_fastpath):
        grid = uniform_grid(freqs)
    if grid is not None:
        f0, df = grid
        n_freq_pad = -(-n_freq // tr_size) * tr_size
        # The factorized-kernel knob resolves at shard scale too: the cache
        # entry that won the A/B at this per-device workload is the one that
        # transfers.
        mx, rs, b16 = _resolve_grid_mxu(ev_per_shard, tr_per_shard, poly,
                                        use_mxu, reseed, mxu_bf16)
        # Per-SHARD workload is what each device tiles, so the autotuner is
        # consulted at shard scale and _fit_block then shrinks the winner
        # to small inputs exactly as it always shrank the static default.
        g_eb, g_tb = resolve_blocks("grid_mxu" if mx else "grid",
                                    ev_per_shard, tr_per_shard, poly)
        plan = specs_for("sharded_sums_grid", mesh)
        gargs = (_to_mesh(t_pad, mesh, plan, "times"),
                 _to_mesh(w_pad, mesh, plan, "weights"), f0, df, n_freq_pad,
                 _to_mesh(fd, mesh, plan, "fdots"), nharm, mesh)
        gkw = dict(event_block=_fit_block(g_eb, ev_per_shard),
                   trial_block=_fit_block(g_tb, tr_per_shard),
                   poly=poly, mxu=mx, reseed=rs, mxu_bf16=b16)
        c, s = _sharded_sums_grid(*gargs, **gkw)
        costmodel.capture("sharded_sums_grid", _sharded_sums_grid, *gargs,
                          plan=plan, **gkw)
    else:
        f_pad, _ = _pad_to(np.asarray(freqs, dtype=np.float64), tr_size, fill=1.0)
        d_eb, d_tb = resolve_blocks("general", ev_per_shard, tr_per_shard, poly)
        plan = specs_for("sharded_sums_general", mesh)
        gargs = (_to_mesh(t_pad, mesh, plan, "times"),
                 _to_mesh(w_pad, mesh, plan, "weights"),
                 _to_mesh(f_pad, mesh, plan, "freqs"),
                 _to_mesh(fd, mesh, plan, "fdots"), nharm, mesh)
        gkw = dict(trig_dtype=trig_dtype,
                   event_block=_fit_block(d_eb, ev_per_shard),
                   trial_block=_fit_block(d_tb, tr_per_shard),
                   poly=poly)
        c, s = _sharded_sums_general(*gargs, **gkw)
        costmodel.capture("sharded_sums_general", _sharded_sums_general,
                          *gargs, plan=plan, **gkw)
    return c[:, :, :n_freq], s[:, :, :n_freq]


def z2_sharded(
    times, freqs, nharm: int = 2, mesh: Mesh | None = None, trig_dtype=None,
    use_fastpath: bool | None = None, poly: bool = False,
    use_mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> np.ndarray:
    """Z^2_n over the frequency grid, events sharded across the mesh."""
    if mesh is None:
        mesh = default_dispatch_mesh()
    c, s = _sharded_sums_nd(times, freqs, 0.0, nharm, mesh, trig_dtype,
                            use_fastpath, poly, use_mxu, reseed, mxu_bf16)
    return _materialize(jnp.sum(z2_from_sums(c[0], s[0], len(times)), axis=0), mesh)  # graftlint: disable=GL005 (sums the replicated nharm axis, not the sharded event axis; per-trial order is fixed and the 8-device bitwise pin covers it)


def h_sharded(
    times, freqs, nharm: int = 20, mesh: Mesh | None = None, trig_dtype=None,
    use_fastpath: bool | None = None, poly: bool = False,
    use_mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> np.ndarray:
    """H-test over the frequency grid, events sharded across the mesh."""
    if mesh is None:
        mesh = default_dispatch_mesh()
    c, s = _sharded_sums_nd(times, freqs, 0.0, nharm, mesh, trig_dtype,
                            use_fastpath, poly, use_mxu, reseed, mxu_bf16)
    z2_cum = jnp.cumsum(z2_from_sums(c[0], s[0], len(times)), axis=0)
    penalties = 4.0 * jnp.arange(nharm)[:, None]
    return _materialize(jnp.max(z2_cum - penalties, axis=0), mesh)


def z2_2d_sharded(
    times, freqs, fdots, nharm: int = 2, mesh: Mesh | None = None, trig_dtype=None,
    use_fastpath: bool | None = None, poly: bool = False,
    use_mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> np.ndarray:
    """Z^2_n over the (fdot, freq) grid -> (n_fdot, n_freq), events sharded
    across the mesh with psum combines (fdots replicated; the frequency axis
    shards over the trial mesh axis)."""
    if mesh is None:
        mesh = default_dispatch_mesh()
    c, s = _sharded_sums_nd(times, freqs, fdots, nharm, mesh, trig_dtype,
                            use_fastpath, poly, use_mxu, reseed, mxu_bf16)
    return _materialize(jnp.sum(z2_from_sums(c, s, len(times)), axis=1), mesh)  # graftlint: disable=GL005 (sums the replicated nharm axis, not the sharded event axis; per-trial order is fixed and the 8-device bitwise pin covers it)


def _sharded_sums_grid3d(
    times,
    weights,
    f0: float,
    df: float,
    n_freq: int,
    fdots,
    fddots,
    nharm: int,
    mesh: Mesh,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    poly: bool = False,
    mxu: bool = False,
    reseed: int = GRID_MXU_RESEED,
    mxu_bf16: bool = False,
):
    """Uniform-grid 3-D cube trig sums under sharding.

    Same contract as :func:`_sharded_sums_grid` extended with a replicated
    fddot axis: each trial tile owns a contiguous frequency range, fdots and
    fddots are replicated, and the f64 psum combine over the event axis is
    identical to the monolithic kernel's cross-block scan order.
    """
    tr_size = mesh.shape[TRIAL_AXIS]
    n_freq_shard = n_freq // tr_size

    def kernel(t_shard, w_shard, fd_all, fdd_all):
        tile = jax.lax.axis_index(TRIAL_AXIS)
        f0_shard = f0 + (tile * n_freq_shard) * df
        if mxu and n_freq_shard % trial_block == 0:
            # GLOBAL f0 plus the shard's first tile index keeps the f_tiles
            # rounding bitwise-equal to the monolithic kernel (see the 2-D
            # sharded kernel for the reasoning)
            c_all, s_all = harmonic_sums_uniform_3d_mxu(
                t_shard, f0, df, n_freq_shard, fd_all, fdd_all, nharm,
                event_block, trial_block, weights=w_shard, poly=poly,
                reseed=reseed, mxu_bf16=mxu_bf16,
                tile0=tile * (n_freq_shard // trial_block),
            )
        elif mxu:
            c_all, s_all = harmonic_sums_uniform_3d_mxu(
                t_shard, f0_shard, df, n_freq_shard, fd_all, fdd_all, nharm,
                event_block, trial_block, weights=w_shard, poly=poly,
                reseed=reseed, mxu_bf16=mxu_bf16,
            )
        else:
            c_all, s_all = harmonic_sums_uniform_3d(
                t_shard, f0_shard, df, n_freq_shard, fd_all, fdd_all, nharm,
                event_block, trial_block, weights=w_shard, poly=poly,
            )
        return jax.lax.psum(c_all, EVENT_AXIS), jax.lax.psum(s_all, EVENT_AXIS)

    plan = specs_for("sharded_sums_grid3d", mesh)
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=plan.in_specs("times", "weights", "fdots", "fddots"),
        out_specs=plan.out_specs,
    )(times, weights, fdots, fddots)


def z2_3d_sharded(
    times, freqs, fdots, fddots, nharm: int = 2, mesh: Mesh | None = None,
    use_fastpath: bool | None = None, poly: bool = False,
    use_mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> np.ndarray:
    """Z^2_n over the (fddot, fdot, freq) cube, events sharded across the
    mesh. Requires the uniform-grid fast path; a non-uniform frequency list
    falls back to the single-device general cube kernel (there is no general
    sharded kernel with a cubic phase family)."""
    if mesh is None:
        mesh = default_dispatch_mesh()
    grid = None
    if grid_fastpath_enabled(nharm, use_fastpath):
        grid = uniform_grid(freqs)
    if grid is None:
        from crimp_tpu.ops import search as _search

        obs.counter_add("mesh_grid3d_fallbacks")
        eb, tb = resolve_blocks("general", len(times), len(freqs), poly)
        power = _search.z2_power_3d(
            jnp.asarray(np.asarray(times, dtype=np.float64)),
            jnp.asarray(np.asarray(freqs, dtype=np.float64)),
            jnp.asarray(np.atleast_1d(np.asarray(fdots, dtype=np.float64))),
            jnp.asarray(np.atleast_1d(np.asarray(fddots, dtype=np.float64))),
            nharm, event_block=eb, trial_block=tb, poly=poly,
        )
        return np.asarray(power)
    f0, df = grid
    ev_size = mesh.shape[EVENT_AXIS]
    tr_size = mesh.shape[TRIAL_AXIS]
    obs.counter_add("mesh_sharded_calls")
    obs.gauge_set("mesh_devices", ev_size * tr_size)
    n_freq = len(freqs)
    t_pad, w_pad = _pad_to(np.asarray(times, dtype=np.float64), ev_size)
    fd = jnp.asarray(np.atleast_1d(np.asarray(fdots, dtype=np.float64)))
    fdd = jnp.asarray(np.atleast_1d(np.asarray(fddots, dtype=np.float64)))
    ev_per_shard = len(t_pad) // ev_size
    tr_per_shard = -(-n_freq // tr_size)
    n_freq_pad = tr_per_shard * tr_size
    # knob + block resolution at shard scale, exactly like _sharded_sums_nd
    mx, rs, b16 = _resolve_grid3d_mxu(
        ev_per_shard, tr_per_shard * len(fd) * len(fdd), poly,
        use_mxu, reseed, mxu_bf16)
    g_eb, g_tb = resolve_blocks("grid_mxu" if mx else "grid3d",
                                ev_per_shard, tr_per_shard, poly)
    plan3 = specs_for("sharded_sums_grid3d", mesh)
    gargs = (_to_mesh(t_pad, mesh, plan3, "times"),
             _to_mesh(w_pad, mesh, plan3, "weights"), f0, df, n_freq_pad,
             _to_mesh(fd, mesh, plan3, "fdots"),
             _to_mesh(fdd, mesh, plan3, "fddots"), nharm, mesh)
    gkw = dict(event_block=_fit_block(g_eb, ev_per_shard),
               trial_block=_fit_block(g_tb, tr_per_shard),
               poly=poly, mxu=mx, reseed=rs, mxu_bf16=b16)
    c, s = _sharded_sums_grid3d(*gargs, **gkw)
    costmodel.capture("sharded_sums_grid3d", _sharded_sums_grid3d, *gargs,
                      plan=plan3, **gkw)
    c, s = c[:, :, :, :n_freq], s[:, :, :, :n_freq]
    return _materialize(jnp.sum(z2_from_sums(c, s, len(times)), axis=2), mesh)  # graftlint: disable=GL005 (sums the replicated nharm axis, not the sharded event axis; per-trial order is fixed and the 8-device bitwise pin covers it)


def semicoherent_stack_sharded(
    seg_times, seg_weights, f0: float, df: float, n_freq: int,
    fdots, fddots, nharm: int, mesh: Mesh | None = None,
    event_block: int = GRID_EVENT_BLOCK, trial_block: int = GRID_TRIAL_BLOCK,
    poly: bool = False,
):
    """Incoherently stacked per-segment Z^2 over the cube, segments sharded
    across devices.

    ``seg_times``/``seg_weights`` are (S, Nmax) zero-weight-padded segment
    rows (S a multiple of the segment mesh size — callers pad with all-zero
    rows, which contribute exactly 0 to the stack). Each device runs the same
    exact per-segment 3-D kernel as the single-device loop; only the
    cross-segment summation order differs (shard-local sum, then psum), so
    parity with the loop path is reduction-order tolerance, not bitwise.
    Returns the (n_fddot, n_fdot, n_freq) stacked power as a jax array.
    """
    if mesh is None:
        mesh = segment_mesh()
    fd = jnp.asarray(np.atleast_1d(np.asarray(fdots, dtype=np.float64)))
    fdd = jnp.asarray(np.atleast_1d(np.asarray(fddots, dtype=np.float64)))

    def kernel(t_sh, w_sh, fd_all, fdd_all):
        def one_segment(rows):
            t_row, w_row = rows
            c, s = harmonic_sums_uniform_3d(
                t_row, f0, df, n_freq, fd_all, fdd_all, nharm,
                event_block, trial_block, weights=w_row, poly=poly,
            )
            # 0/1 weight totals are exact integers in f64: any summation
            # order yields identical bits, and empty pad rows normalize by 1
            n_seg = jnp.maximum(jnp.sum(w_row), 1.0)  # graftlint: disable=GL005 (exact integer-valued total of the 0/1 weight mask; order-insensitive at the bit level)
            power = z2_from_sums(c, s, n_seg)
            return jnp.sum(power, axis=2)  # graftlint: disable=GL005 (sums the replicated nharm axis inside one segment, not the sharded segment axis)
        terms = jax.lax.map(one_segment, (t_sh, w_sh))
        local = jnp.sum(terms, axis=0)  # graftlint: disable=GL005 (shard-local partial of the segment stack; the cross-segment order is pinned only to reduction-order tolerance by contract)
        return jax.lax.psum(local, SEGMENT_AXIS)

    plan = specs_for("semicoherent_stack", mesh)
    args = (jnp.asarray(np.asarray(seg_times, dtype=np.float64)),
            jnp.asarray(np.asarray(seg_weights, dtype=np.float64)), fd, fdd)
    sharded = shard_map(
        kernel,
        mesh=mesh,
        in_specs=plan.in_specs("seg_times", "seg_weights", "fdots", "fddots"),
        out_specs=plan.out_specs,
    )
    out = sharded(*args)
    costmodel.capture("semicoherent_stack", sharded, *args,
                      plan=specs_for("semicoherent_stack", mesh))
    return out


# ---------------------------------------------------------------------------
# Sharded delta-fold refold (basis built shard-local)
# ---------------------------------------------------------------------------


def delta_refold_sharded(tm, t_ref_mjd, folded, delta, anchor_idx, dp,
                         mesh: Mesh | None = None,
                         wave_in_f0: bool = True) -> np.ndarray:
    """frac(folded + B @ dp) with events sharded across the mesh.

    Each device builds ITS shard's basis rows (ops/deltafold.basis_rows is
    per-event independent) and applies the refold matmul locally — the
    full (N, 13+5G) basis never materializes on one device and there is no
    collective (each row's dot runs over the replicated dp). Bitwise
    identical to the monolithic refold: sharding splits the event axis,
    not any reduction.
    """
    from crimp_tpu.ops import deltafold

    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), (EVENT_AXIS,))
    n = len(folded)
    n_dev = mesh.shape[EVENT_AXIS]
    obs.counter_add("mesh_sharded_calls")
    obs.gauge_set("mesh_devices", n_dev)
    spec = deltafold.basis_spec(tm, t_ref_mjd)
    folded_p, _ = _pad_to(np.asarray(folded, dtype=np.float64), n_dev)
    delta_p, _ = _pad_to(np.asarray(delta, dtype=np.float64), n_dev)
    idx_p, _ = _pad_to(np.asarray(anchor_idx, dtype=np.int64), n_dev, fill=0)

    def kernel(spec_rep, ph_shard, d_shard, ai_shard, dp_rep):
        b = deltafold.basis_rows(spec_rep, d_shard, ai_shard,
                                 wave_in_f0=wave_in_f0)
        return deltafold.refold(ph_shard, b, dp_rep)

    plan = specs_for("delta_refold", mesh)
    sharded = shard_map(
        kernel,
        mesh=mesh,
        in_specs=plan.in_specs("spec", "folded", "delta", "anchor_idx", "dp"),
        out_specs=plan.out_specs,
    )
    args = (spec, jnp.asarray(folded_p), jnp.asarray(delta_p),
            jnp.asarray(idx_p), jnp.asarray(np.asarray(dp, dtype=np.float64)))
    out = sharded(*args)
    # the dispatch itself is eager; a jit wrapper exists only so cost
    # capture can AOT-lower the identical sharded program
    costmodel.capture("delta_refold_sharded", jax.jit(sharded), *args,
                      plan=plan)
    return np.asarray(out)[:n]


# ---------------------------------------------------------------------------
# Segment-axis (data-parallel) placement
# ---------------------------------------------------------------------------


def shard_segments(array: np.ndarray, mesh: Mesh, axis_name: str | None = None):
    """Place a batched (segment-major) array with its leading axis sharded —
    used to spread ToA-segment fits across chips. Works with both the 2-D
    (events x trials) mesh (leading axis on ``trials``) and the 1-D segment
    mesh."""
    if axis_name is None:
        axis_name = SEGMENT_AXIS if SEGMENT_AXIS in mesh.axis_names else TRIAL_AXIS
    return jax.device_put(array, leading_axis_sharding(mesh, axis_name))


def pad_batch_for_mesh(n: int, mesh: Mesh, axis_name: str = SEGMENT_AXIS) -> int:
    """Rows of padding needed so a leading batch axis tiles onto the mesh."""
    size = mesh.shape[axis_name]
    return (-n) % size
