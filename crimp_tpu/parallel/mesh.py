"""Device meshes and sharded search kernels — the distributed backend.

The reference has no distributed layer at all (SURVEY.md §2.4: no
NCCL/MPI/Gloo anywhere); for the TPU framework the communication backend is
XLA collectives over a ``jax.sharding.Mesh``:

- the EVENT axis (the long axis: 1e5..1e8 photon times) shards across the
  ``events`` mesh axis — the analog of sequence/context parallelism. Each
  device computes partial per-trial harmonic sums over its event shard and
  a ``psum`` ring all-reduce over ICI combines them (the Z^2/H statistics
  are exactly segmented reductions, so blockwise streaming composes with
  the sharding when events exceed HBM);
- the TRIAL axis (frequency, or frequency x fdot tiles) shards across the
  ``trials`` mesh axis with no communication at all — embarrassingly
  parallel tiles, DCN-friendly across slices;
- small state (template parameters, timing model) is replicated.

On a v4/v5 pod slice both axes ride ICI; across slices put ``trials`` on
the DCN axis (its only traffic is the final gather).

Multi-chip correctness is asserted in tests on a virtual 8-device CPU mesh
(tests/test_parallel.py): mesh-shape invariance of the statistics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from crimp_tpu.ops.search import _harmonic_sums_cycles, z2_from_sums

EVENT_AXIS = "events"
TRIAL_AXIS = "trials"


def build_mesh(
    devices=None, event_parallel: int | None = None, axis_names=(EVENT_AXIS, TRIAL_AXIS)
) -> Mesh:
    """A 2-D (events x trials) mesh over the given (or all) devices.

    ``event_parallel`` fixes the event-axis size; by default all devices go
    to the event axis (the data-bound regime of BASELINE configs 3/5)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if event_parallel is None:
        event_parallel = n
    if n % event_parallel != 0:
        raise ValueError(f"{n} devices do not tile into event_parallel={event_parallel}")
    grid = np.asarray(devices).reshape(event_parallel, n // event_parallel)
    return Mesh(grid, axis_names)


def _pad_to(x: np.ndarray, multiple: int, fill=0.0):
    n = len(x)
    padded_len = -(-n // multiple) * multiple
    if padded_len == n:
        return np.asarray(x), np.ones(n)
    out = np.full(padded_len, fill, dtype=np.asarray(x).dtype)
    out[:n] = x
    weights = np.zeros(padded_len)
    weights[:n] = 1.0
    return out, weights


def _sharded_sums(times, weights, freqs, nharm: int, mesh: Mesh, trig_dtype=None):
    """Per-harmonic trig sums with events sharded + psum-reduced
    (the fdot = 0 slice of the 2-D kernel)."""
    c, s = _sharded_sums_2d(
        times, weights, freqs, jnp.zeros(1), nharm, mesh, trig_dtype
    )
    return c[0], s[0]


def z2_sharded(times, freqs, nharm: int = 2, mesh: Mesh | None = None, trig_dtype=None) -> np.ndarray:
    """Z^2_n over the frequency grid, events sharded across the mesh."""
    if mesh is None:
        mesh = build_mesh()
    n_events = len(times)
    ev_size = mesh.shape[EVENT_AXIS]
    tr_size = mesh.shape[TRIAL_AXIS]
    t_pad, w_pad = _pad_to(np.asarray(times, dtype=np.float64), ev_size)
    f_pad, f_w = _pad_to(np.asarray(freqs, dtype=np.float64), tr_size, fill=1.0)
    c, s = _sharded_sums(
        jnp.asarray(t_pad), jnp.asarray(w_pad), jnp.asarray(f_pad), nharm, mesh, trig_dtype
    )
    power = np.asarray(jnp.sum(z2_from_sums(c, s, n_events), axis=0))
    return power[: len(freqs)]


def h_sharded(times, freqs, nharm: int = 20, mesh: Mesh | None = None, trig_dtype=None) -> np.ndarray:
    """H-test over the frequency grid, events sharded across the mesh."""
    if mesh is None:
        mesh = build_mesh()
    n_events = len(times)
    ev_size = mesh.shape[EVENT_AXIS]
    tr_size = mesh.shape[TRIAL_AXIS]
    t_pad, w_pad = _pad_to(np.asarray(times, dtype=np.float64), ev_size)
    f_pad, _ = _pad_to(np.asarray(freqs, dtype=np.float64), tr_size, fill=1.0)
    c, s = _sharded_sums(
        jnp.asarray(t_pad), jnp.asarray(w_pad), jnp.asarray(f_pad), nharm, mesh, trig_dtype
    )
    z2_cum = jnp.cumsum(z2_from_sums(c, s, n_events), axis=0)
    penalties = 4.0 * jnp.arange(nharm)[:, None]
    return np.asarray(jnp.max(z2_cum - penalties, axis=0))[: len(freqs)]


@partial(jax.jit, static_argnames=("nharm", "mesh", "trig_dtype"))
def _sharded_sums_2d(times, weights, freqs, fdots, nharm: int, mesh: Mesh, trig_dtype=None):
    """Per-harmonic trig sums over the (fdot, freq) grid, events sharded."""
    from crimp_tpu.ops.search import DEFAULT_TRIG_DTYPE

    dtype = DEFAULT_TRIG_DTYPE if trig_dtype is None else trig_dtype

    def kernel(t_shard, w_shard, f_shard, fd_all):
        def one_fd(fd):
            phase = (
                f_shard[:, None] * t_shard[None, :]
                + 0.5 * fd * t_shard[None, :] ** 2
            )  # cycles, f64
            c, s = _harmonic_sums_cycles(phase, w_shard[None, :], nharm, dtype)
            return jax.lax.psum(c, EVENT_AXIS), jax.lax.psum(s, EVENT_AXIS)

        return jax.lax.map(one_fd, fd_all)

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(EVENT_AXIS), P(EVENT_AXIS), P(TRIAL_AXIS), P(None)),
        out_specs=(P(None, None, TRIAL_AXIS), P(None, None, TRIAL_AXIS)),
    )(times, weights, freqs, fdots)


def z2_2d_sharded(
    times, freqs, fdots, nharm: int = 2, mesh: Mesh | None = None, trig_dtype=None
) -> np.ndarray:
    """Z^2_n over the (fdot, freq) grid -> (n_fdot, n_freq), events sharded
    across the mesh with psum combines (fdots replicated; the frequency axis
    shards over the trial mesh axis)."""
    if mesh is None:
        mesh = build_mesh()
    n_events = len(times)
    ev_size = mesh.shape[EVENT_AXIS]
    tr_size = mesh.shape[TRIAL_AXIS]
    t_pad, w_pad = _pad_to(np.asarray(times, dtype=np.float64), ev_size)
    f_pad, _ = _pad_to(np.asarray(freqs, dtype=np.float64), tr_size, fill=1.0)
    c, s = _sharded_sums_2d(
        jnp.asarray(t_pad), jnp.asarray(w_pad), jnp.asarray(f_pad),
        jnp.asarray(fdots, dtype=np.float64), nharm, mesh, trig_dtype,
    )
    power = np.asarray(jnp.sum(z2_from_sums(c, s, n_events), axis=1))
    return power[:, : len(freqs)]


def shard_segments(array: np.ndarray, mesh: Mesh, axis_name: str = TRIAL_AXIS):
    """Place a batched (segment-major) array with its leading axis sharded —
    used to spread ToA-segment fits across chips."""
    spec = [None] * np.ndim(array)
    spec[0] = axis_name
    return jax.device_put(array, NamedSharding(mesh, P(*spec)))
