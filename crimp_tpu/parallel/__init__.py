from crimp_tpu.parallel.mesh import build_mesh, z2_sharded, h_sharded

__all__ = ["build_mesh", "z2_sharded", "h_sharded"]
