"""Bounded admission queue with priority classes and fair-queue drain.

The serving engine's front door: requests enter through
:meth:`AdmissionQueue.offer`, which either accepts (the request becomes a
row in the next continuous-batching dispatch) or raises a TYPED
:class:`AdmissionRejected` carrying a taxonomy :class:`FailureKind` — a
full queue is RESOURCE_EXHAUSTED backpressure, a malformed request is
DATA_ERROR.  The queue never blocks and never grows without bound: under
overload the caller learns immediately and can shed, retry elsewhere, or
wait — the engine's own latency never inflates by queue depth it cannot
serve.

Priority classes (``TimingRequest.priority``: high / normal / low) get
PER-CLASS bounded sub-queues — a chatty low-priority client saturating
its own sub-queue can never evict or block high-priority admission — and
:meth:`drain` interleaves the classes by deficit round-robin with the
:data:`PRIORITY_CLASSES` weights as quanta: every non-empty class makes
progress each round (no starvation), heavier classes proportionally more.
Within a class the order stays FIFO and deadline scheduling is unchanged
(the rung scheduler sees per-request budgets exactly as before).

Capacity comes from ``CRIMP_TPU_SERVE_QUEUE`` (default 64, applied per
class); the ``serve_admission`` fault point fires inside :meth:`offer` so
chaos tests can drive admission-time failures — an injected fault
surfaces as the same classified rejection an organic one would.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from crimp_tpu import knobs, obs
from crimp_tpu.resilience import faultinject, taxonomy
from crimp_tpu.resilience.taxonomy import CrimpError, FailureKind

DEFAULT_QUEUE_CAP = 64

# Priority classes in drain-precedence order, with their deficit-round-
# robin quanta (requests per drain round while backlogged).  Weighted
# fair queueing, not strict priority: a backlogged low class still
# drains 1 request per round against high's 4.
PRIORITY_CLASSES = {"high": 4, "normal": 2, "low": 1}


class AdmissionRejected(CrimpError):
    """A request refused at the front door; ``kind`` says why.

    RESOURCE_EXHAUSTED = queue full (backpressure — try again later);
    DATA_ERROR = the request itself is malformed (retrying is pointless);
    other kinds surface injected/organic admission-path failures.
    """

    def __init__(self, message: str, kind: FailureKind):
        super().__init__(message)
        self.kind = kind


@dataclass
class TimingRequest:
    """One timing request: a survey SourceSpec plus its SLO budget.

    ``spec.name`` doubles as the client identity — it namespaces the
    client's delta-fold cache slot (``cache_tag``), so a returning client
    re-times as one ``B @ dp`` matmul against its cached fold product.
    ``deadline_s`` is the request's latency budget in seconds from
    submission; None defers to ``CRIMP_TPU_SERVE_DEADLINE_MS`` (unset =
    no deadline).  ``submitted_at`` (perf_counter seconds) is stamped at
    admission; the load generator pre-stamps the scheduled arrival time
    so open-loop latencies include queue wait.  ``priority`` names one of
    the :data:`PRIORITY_CLASSES` (default "normal"): it picks the bounded
    per-class sub-queue and the fair-queue drain weight, nothing else.
    """

    spec: object
    deadline_s: float | None = None
    submitted_at: float | None = None
    fit_kwargs: dict = field(default_factory=dict)
    priority: str = "normal"

    @property
    def client_id(self) -> str:
        return str(getattr(self.spec, "name", ""))


def queue_capacity() -> int:
    """CRIMP_TPU_SERVE_QUEUE (default 64); zero or negative raises."""
    cap = knobs.env_int("CRIMP_TPU_SERVE_QUEUE", DEFAULT_QUEUE_CAP)
    if cap < 1:
        raise ValueError(
            f"CRIMP_TPU_SERVE_QUEUE={cap!r} out of range (expected >= 1)")
    return cap


class AdmissionQueue:
    """Per-class FIFOs of admitted requests, each capped; full = typed
    rejection; drained by weighted deficit round-robin."""

    def __init__(self, capacity: int | None = None):
        self.capacity = int(capacity) if capacity is not None \
            else queue_capacity()
        if self.capacity < 1:
            raise ValueError("admission queue capacity must be >= 1")
        self._queues: dict[str, deque[TimingRequest]] = {
            cls: deque() for cls in PRIORITY_CLASSES}
        self._deficit: dict[str, int] = {cls: 0 for cls in PRIORITY_CLASSES}
        self.admitted = 0
        self.rejected = 0

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def offer(self, request: TimingRequest) -> TimingRequest:
        """Admit ``request`` or raise :class:`AdmissionRejected`.

        Every failure on this path leaves through the typed rejection —
        the serving contract's "rejected at admission with a taxonomy
        kind" leg starts here.
        """
        try:
            faultinject.fire("serve_admission")
        except Exception as exc:  # noqa: BLE001 — admission failure domain:
            # injected (or organic) faults become classified rejections
            self.rejected += 1
            obs.counter_add("serve_rejected", 1)
            raise AdmissionRejected(
                f"admission failed: {exc}", taxonomy.classify(exc)) from exc
        if not isinstance(request, TimingRequest):
            self.rejected += 1
            obs.counter_add("serve_rejected", 1)
            raise AdmissionRejected(
                f"expected a TimingRequest, got {type(request).__name__}",
                FailureKind.DATA_ERROR)
        if not request.client_id:
            self.rejected += 1
            obs.counter_add("serve_rejected", 1)
            raise AdmissionRejected(
                "request spec has no name (the client identity)",
                FailureKind.DATA_ERROR)
        if request.deadline_s is not None and \
                not (float(request.deadline_s) > 0.0):
            self.rejected += 1
            obs.counter_add("serve_rejected", 1)
            raise AdmissionRejected(
                f"deadline_s={request.deadline_s!r} must be > 0",
                FailureKind.DATA_ERROR)
        if request.priority not in PRIORITY_CLASSES:
            self.rejected += 1
            obs.counter_add("serve_rejected", 1)
            raise AdmissionRejected(
                f"priority={request.priority!r} is not a declared class "
                f"({'/'.join(PRIORITY_CLASSES)})", FailureKind.DATA_ERROR)
        if len(self._queues[request.priority]) >= self.capacity:
            self.rejected += 1
            obs.counter_add("serve_rejected", 1)
            obs.counter_add("serve_queue_full", 1)
            raise AdmissionRejected(
                f"admission queue full for class {request.priority!r} "
                f"({self.capacity} pending): resource exhausted, retry "
                "after the next batch drains",
                FailureKind.RESOURCE_EXHAUSTED)
        if request.submitted_at is None:
            request.submitted_at = time.perf_counter()
        self._queues[request.priority].append(request)
        self.admitted += 1
        obs.counter_add("serve_admitted", 1)
        obs.counter_add(f"serve_admitted_{request.priority}", 1)
        return request

    def drain(self, n: int | None = None) -> list[TimingRequest]:
        """Pop up to ``n`` admitted requests (all of them when None) —
        the next continuous-batching round's rows.

        Deficit round-robin across the priority classes: each round every
        non-empty class earns its :data:`PRIORITY_CLASSES` quantum and
        pops that many requests (FIFO within the class), so a saturated
        low class can delay a high request by at most a bounded number of
        slots per round — never starve it.  Unspent deficit carries to
        the next drain while a class stays backlogged and resets when its
        sub-queue empties (standard DRR).
        """
        total = len(self)
        take = total if n is None else min(int(n), total)
        out: list[TimingRequest] = []
        while len(out) < take:
            for cls, weight in PRIORITY_CLASSES.items():
                q = self._queues[cls]
                if not q:
                    self._deficit[cls] = 0
                    continue
                self._deficit[cls] += weight
                while q and self._deficit[cls] > 0 and len(out) < take:
                    out.append(q.popleft())
                    self._deficit[cls] -= 1
                if not q:
                    self._deficit[cls] = 0
        return out


__all__ = ["AdmissionQueue", "AdmissionRejected", "DEFAULT_QUEUE_CAP",
           "PRIORITY_CLASSES", "TimingRequest", "queue_capacity"]
