"""The resident serving engine: continuous batching over the survey core.

One long-lived :class:`ServingEngine` replaces the run-to-completion
batch CLI for "millions of users" workloads: requests enter through the
bounded admission queue (serve/admission.py) and each :meth:`step` is one
CONTINUOUS-BATCHING round — every admitted request becomes a row in the
next multisource dispatch (``bucket_sources`` → ``stacked_fold`` via
``survey.compute_bucket``), so dispatch overhead amortizes across
whatever arrived since the last round instead of per request.

Request lifecycle (docs/serving.md has the state machine):

1. **admission** — accepted or rejected with a taxonomy kind (bounded
   queue, backpressure);
2. **scheduling** — the deadline-aware scheduler (serve/scheduler.py)
   picks the highest ladder rung the remaining budget affords and the
   per-rung circuit breakers (serve/breaker.py) admit;
3. **dispatch** — cold clients batch at the picked rung; RETURNING
   clients take the delta-fold hot path: with the warm-batch knob on
   (``CRIMP_TPU_SERVE_WARM_BATCH`` via ``resolve_serve_warm_batch``, the
   default) every warm client in the round refolds in ONE
   ``deltafold.delta_refold_batch`` dispatch (rung ``warm_batched``) and
   the post-refold template fits ride the already-batched
   ``fit_sources``; with the knob off, or for a client the batch demotes
   (cache miss / nonlinear move / precision-guard trip), the request
   re-times solo (rung ``warm``) through ``measure_source_toas`` with
   ``delta_fold=1`` and ``cache_tag`` = client name — one ``B @ dp``
   matvec against the cached fold product, seeded from the client's
   first (batched, bit-identical) fold.  Per-client bits are identical
   on both warm rungs;
4. **completion** — every admitted request resolves as ``ok``
   (bit-identical to the parity-pinned reference path), ``degraded``
   (stamped via ``record_degradation``), or ``error`` with a classified
   record (DATA_ERROR never degrades — bad input fails the same on every
   rung).  No request ever returns an unclassified error.

Host-side request prep (longdouble anchoring via ``survey._prep_source``)
overlaps the previous round's dispatch: :meth:`ServingEngine.submit`
hands each admitted spec to a bounded SINGLE-worker prep stage and
:meth:`step` consumes the futures in drain order — deterministic
completion order, results bit-identical to the serial path (prep is a
pure function of the spec), and ``CRIMP_TPU_SERVE_PREP_OVERLAP=0`` pins
the serial order outright.

Failure domains are inherited from ``pipelines/survey.py``: a failed
bucket splits and retries, a single-request bucket demotes to the
per-source rung, device-shaped per-source failures get one pinned-CPU
attempt.  The ``serve_dispatch`` fault point fires on every batched and
warm dispatch (NOT on the per-source bottom rung — the ladder's floor is
the clean path, mirroring ``survey_bucket``); ``serve_warm_batch`` fires
inside the stacked warm dispatch, whose failure walks the ``serve_warm``
ladder (``warm_batched -> solo``) and demotes the batch to per-request
warm dispatches.
"""

from __future__ import annotations

import concurrent.futures
import logging
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from crimp_tpu import knobs, obs, resilience
from crimp_tpu.pipelines import survey
from crimp_tpu.resilience import faultinject
from crimp_tpu.resilience.taxonomy import FailureKind
from crimp_tpu.serve import breaker as breaker_mod
from crimp_tpu.serve import scheduler as scheduler_mod
from crimp_tpu.serve.admission import (AdmissionQueue, AdmissionRejected,
                                       TimingRequest)

logger = logging.getLogger("crimp_tpu.serve")


@dataclass
class RequestResult:
    """One request's terminal state — the serving contract's unit.

    ``status``: ``ok`` (completed bit-identically on the reference
    path), ``degraded`` (completed on a lower rung, stamped in the obs
    manifest), or ``error`` (classified failure record; ``kind`` from
    the closed taxonomy).  Rejected requests never reach a result — they
    leave :meth:`ServingEngine.submit` as :class:`AdmissionRejected`.
    """

    client_id: str
    status: str
    frame: object = None
    rung: str | None = None
    path: str | None = None  # delta / cache / batched / per_source / ...
    kind: str | None = None
    latency_s: float | None = None
    deadline_miss: bool = False
    error: dict | None = None


@dataclass
class _Pending:
    """A drained request moving through one batching round."""

    req: TimingRequest
    prep: object = None
    degraded: bool = False
    rung: str | None = None
    result: RequestResult | None = None
    extra: dict = field(default_factory=dict)


class ServingEngine:
    """Long-lived timing service over the multisource batch engine."""

    def __init__(self, queue: AdmissionQueue | None = None,
                 scheduler: scheduler_mod.DeadlineScheduler | None = None,
                 breakers: breaker_mod.RungBreakers | None = None,
                 phShiftRes: int = 1000, nbrBins: int = 15,
                 varyAmps: bool = False, mesh=None,
                 warm_batch: int | None = None,
                 prep_overlap: bool | None = None):
        self.queue = queue if queue is not None else AdmissionQueue()
        self.scheduler = scheduler if scheduler is not None \
            else scheduler_mod.DeadlineScheduler()
        self.breakers = breakers if breakers is not None \
            else breaker_mod.RungBreakers()
        self.phShiftRes = int(phShiftRes)
        self.nbrBins = int(nbrBins)
        self.varyAmps = bool(varyAmps)
        self._default_deadline = scheduler_mod.default_deadline_s()
        self._warm: set[str] = set()  # clients with a seeded fold product
        # None defers to the knob/autotune resolution per round; 0/1 and
        # True/False pin the path (bench_serving's A/B arms use this)
        self._warm_batch = warm_batch
        self._prep_overlap = prep_overlap
        self._prep_pool: concurrent.futures.ThreadPoolExecutor | None = None
        self._prep_futures: dict[int, concurrent.futures.Future] = {}
        self._closed = False
        self.counts = {"ok": 0, "degraded": 0, "error": 0,
                       "deadline_miss": 0, "steps": 0}
        # capacity note: the (optionally global, multi-host) mesh the
        # engine serves on — informational for stats()/bench_serving; the
        # dispatch paths keep routing through the multisource engine's own
        # mesh selection, so passing a mesh never changes results
        self.mesh = mesh
        self.capacity = self._capacity_note(mesh)

    @staticmethod
    def _capacity_note(mesh) -> dict:
        """Describe the serving capacity: device count, mesh axes, and the
        process (host) topology — so a multi-host deployment's stats say
        which fraction of the fleet this engine instance fronts."""
        try:
            from crimp_tpu.parallel import multihost
            pidx, pcount = multihost.process_identity()
        except Exception:  # noqa: BLE001 — capacity note is telemetry only  # graftlint: disable=GL006 (telemetry guard: the capacity note must never block engine construction)
            pidx, pcount = 0, 1
        note = {"process_index": pidx, "process_count": pcount,
                "devices": None, "mesh_axes": None}
        if mesh is not None:
            try:
                note["devices"] = int(mesh.devices.size)
                note["mesh_axes"] = {str(a): int(mesh.shape[a])
                                     for a in mesh.axis_names}
            except Exception:  # noqa: BLE001 — duck-typed mesh  # graftlint: disable=GL006 (telemetry guard: an exotic mesh object degrades to a partial note)
                pass
        return note

    # -- lifecycle ----------------------------------------------------------

    def warmup(self, **kwargs) -> dict:
        """AOT-compile the hot kernels before the first request (PR 1's
        ``warmup()`` + the persistent compile cache)."""
        import crimp_tpu

        return crimp_tpu.warmup(**kwargs)

    def close(self) -> None:
        """Shut the engine down deterministically: the prep-overlap worker
        thread is joined (never leaked past the engine's lifetime), pending
        prep futures are dropped, and subsequent :meth:`submit` calls are
        refused with a classified :class:`AdmissionRejected`. Idempotent."""
        self._closed = True
        pool, self._prep_pool = self._prep_pool, None
        self._prep_futures.clear()
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def submit(self, spec, deadline_s: float | None = None,
               priority: str = "normal") -> TimingRequest:
        """Admit one request (a survey ``SourceSpec`` or a prebuilt
        :class:`TimingRequest`); raises :class:`AdmissionRejected` with a
        taxonomy kind on refusal.  ``priority`` picks the admission
        class (high / normal / low — serve/admission.py)."""
        if self._closed:
            raise AdmissionRejected(
                "engine is closed", FailureKind.RESOURCE_EXHAUSTED)
        req = spec if isinstance(spec, TimingRequest) \
            else TimingRequest(spec=spec, deadline_s=deadline_s,
                               priority=priority)
        if req.deadline_s is None:
            req.deadline_s = self._default_deadline
        req = self.queue.offer(req)
        if self._prep_overlap_on():
            self._schedule_prep(req)
        return req

    def _prep_overlap_on(self) -> bool:
        """Constructor pin > CRIMP_TPU_SERVE_PREP_OVERLAP > on."""
        if self._prep_overlap is not None:
            return bool(self._prep_overlap)
        env = knobs.env_onoff("CRIMP_TPU_SERVE_PREP_OVERLAP")
        return True if env is None else env

    def _schedule_prep(self, req: TimingRequest) -> None:
        """Queue this request's host-side prep behind the single prep
        worker, overlapping it with whatever round is dispatching now.
        Prep is a pure function of the spec and the futures are consumed
        in drain order, so results are bit-identical to serial prep."""
        if self._prep_pool is None:
            self._prep_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="crimp-serve-prep")
        self._prep_futures[id(req)] = self._prep_pool.submit(
            survey._prep_source, req.spec, self.phShiftRes, self.nbrBins,
            self.varyAmps)

    # -- one continuous-batching round --------------------------------------

    def step(self) -> list[RequestResult]:
        """Process everything admitted since the last round; returns one
        terminal :class:`RequestResult` per drained request (input order)."""
        batch = self.queue.drain()
        if not batch:
            return []
        self.counts["steps"] += 1
        pend = [_Pending(req=r) for r in batch]
        obs.beat(0, len(pend), label="serve", force=True)

        futures = [self._prep_futures.pop(id(p.req), None) for p in pend]
        obs.gauge_set("serve_prep_overlap_ready",
                      sum(1 for f in futures if f is not None and f.done()))
        warm: list[_Pending] = []
        cold: list[_Pending] = []
        for p, fut in zip(pend, futures):
            try:
                # the overlapped prep (scheduled at admission) lands here
                # in drain order; requests admitted without one (overlap
                # off, or offered straight to the queue) prep serially —
                # either way the prep is the same pure function of the spec
                p.prep = fut.result() if fut is not None else \
                    survey._prep_source(p.req.spec, self.phShiftRes,
                                        self.nbrBins, self.varyAmps)
            except Exception as exc:  # noqa: BLE001 — per-request failure
                # domain: a malformed spec fails CLASSIFIED, poisons nothing
                p.result = self._error_result(p, resilience.error_record(exc))
                continue
            (warm if p.req.client_id in self._warm else cold).append(p)

        if warm:
            self._dispatch_warm_group(warm)

        if cold:
            self._dispatch_cold(cold)

        done = 0
        for p in pend:
            if p.result is None:  # defensive: the dispatch paths above
                # must resolve every request; an unresolved one is a bug,
                # surfaced as a classified UNKNOWN rather than a None leak
                p.result = self._error_result(p, resilience.error_record(
                    RuntimeError("request left unresolved by dispatch")))
            self._finalize(p)
            done += 1
            obs.beat(done, len(pend), label="serve")
        return [p.result for p in pend]

    def drain_all(self, max_steps: int = 1000) -> list[RequestResult]:
        """Step until the queue is empty (utility for tests/benches)."""
        out: list[RequestResult] = []
        for _ in range(max_steps):
            if not len(self.queue):
                break
            out.extend(self.step())
        return out

    # -- warm clients: the delta-fold hot path ------------------------------

    def _dispatch_warm_group(self, warm: list[_Pending]) -> None:
        """Route the round's warm clients: one stacked refold dispatch
        when the warm-batch knob resolves on (constructor pin >
        CRIMP_TPU_SERVE_WARM_BATCH > cached A/B verdict > on), else the
        per-request loop.  Both paths produce identical per-client bits —
        the knob trades dispatch count, not results."""
        from crimp_tpu.ops import autotune

        enabled = self._warm_batch
        if enabled is None:
            max_seg = max(max((p.prep.max_seg for p in warm), default=1), 1)
            enabled = autotune.resolve_serve_warm_batch(
                len(warm), max_seg)["serve_warm_batch"]
        if not enabled or len(warm) < 2:
            for p in warm:
                self._dispatch_warm(p)
            return
        self._dispatch_warm_batch(warm)

    def _dispatch_warm_batch(self, warm: list[_Pending]) -> None:
        """All warm refolds of a round as one stacked device dispatch.

        Clients group by the executable-sharing key and bucket by padded
        width exactly like the cold path, each bucket refolds through
        ``deltafold.delta_refold_batch`` (rung ``warm_batched``), and the
        post-refold template fits route through the already-batched
        ``survey.compute_bucket`` fits.  A client the batch cannot serve
        (cache miss, nonlinear move, precision-guard trip) demotes ALONE
        to the solo warm rung — that is the precision machinery choosing
        the exact path, not a degradation.  A failure of the stacked
        dispatch itself walks the ``serve_warm`` ladder
        (``warm_batched -> solo``) and demotes the bucket, stamped
        degraded.
        """
        from crimp_tpu.ops import autotune, multisource

        groups: dict[tuple, list[_Pending]] = {}
        for p in warm:
            pr = p.prep
            groups.setdefault((pr.kind, pr.cfg, int(pr.tpl.n_comp)),
                              []).append(p)
        max_seg = max(max((p.prep.max_seg for p in warm), default=1), 1)
        resolved = autotune.resolve_multisource(len(warm), max_seg)
        for members in groups.values():
            for b in multisource.bucket_sources(
                [max(m.prep.max_seg, 1) for m in members],
                max_pad_ratio=resolved["max_pad"],
                batch_cap=resolved["batch_cap"],
            ):
                self._dispatch_warm_bucket([members[j] for j in b])

    def _dispatch_warm_bucket(self, bucket: list[_Pending]) -> None:
        from crimp_tpu.ops import deltafold

        t0 = time.perf_counter()
        try:
            faultinject.fire("serve_warm_batch")
            phase_lists, t_refs, infos = deltafold.delta_refold_batch(
                [m.prep.tm for m in bucket],
                [m.prep.seg_times for m in bucket],
                tags=[m.req.client_id for m in bucket])
        except Exception as exc:  # noqa: BLE001 — stacked-refold failure
            # domain: bad data errors out, anything else drops the whole
            # bucket one serve_warm rung, to per-request warm
            self._demote_warm_bucket(bucket, exc, resilience.classify(exc))
            return
        keep: list[_Pending] = []
        kept_phases, kept_refs = [], []
        for m, pl, tr, info in zip(bucket, phase_lists, t_refs, infos):
            if pl is None:
                # per-client demotion to the solo warm rung (cache miss /
                # nonlinear / budget): normal precision machinery, not a
                # degradation — cached_fold re-runs the exact fold there
                obs.counter_add("serve_warm_batch_demotes", 1)
                self._dispatch_warm(m)
                continue
            m.extra["fold_mode"] = info.get("mode") or "delta"
            keep.append(m)
            kept_phases.append(pl)
            kept_refs.append(tr)
        if not keep:
            return
        try:
            frames, _, _ = survey.compute_bucket(
                [m.prep for m in keep], phase_lists=kept_phases,
                t_refs=kept_refs)
            wall = time.perf_counter() - t0
            self.scheduler.observe(scheduler_mod.WARM_BATCH_RUNG,
                                   wall / len(keep))
            obs.counter_add("serve_warm_batched", len(keep))
            for m, frame in zip(keep, frames):
                mode = m.extra["fold_mode"]
                obs.counter_add(f"serve_warm_{mode}", 1)
                m.result = RequestResult(
                    client_id=m.req.client_id,
                    status="degraded" if m.degraded else "ok",
                    frame=frame, rung=scheduler_mod.WARM_BATCH_RUNG,
                    path=f"delta_fold:{mode}")
        except Exception as exc:  # noqa: BLE001 — the batched-fit half of
            # the stacked dispatch shares the refold's failure domain
            self._demote_warm_bucket(keep, exc, resilience.classify(exc))

    def _demote_warm_bucket(self, bucket: list[_Pending], exc,
                            fkind) -> None:
        """Walk the serve_warm ladder: the stacked dispatch failed, so
        every member re-dispatches per-request at the solo warm rung,
        stamped degraded (DATA_ERROR errors out instead — bad input fails
        the same on every rung)."""
        if fkind is FailureKind.DATA_ERROR:
            for m in bucket:
                m.result = self._error_result(m, resilience.error_record(exc))
            return
        resilience.record_degradation("serve_warm", "solo", fkind)
        obs.counter_add("serve_warm_batch_demotes", len(bucket))
        logger.warning("warm batch of %d failed (%s); demoting to solo "
                       "warm dispatches", len(bucket), fkind.value,
                       exc_info=True)
        for m in bucket:
            m.degraded = True
            self._dispatch_warm(m)

    def _dispatch_warm(self, p: _Pending) -> None:
        t0 = time.perf_counter()
        try:
            faultinject.fire("serve_dispatch")
            frame = survey.measure_source_toas(
                p.req.spec, self.phShiftRes, self.nbrBins, self.varyAmps,
                _prep=p.prep, delta_fold=1)
            from crimp_tpu.ops import deltafold

            mode = deltafold.last_fold_info().get("mode") or "exact"
            p.result = RequestResult(
                client_id=p.req.client_id,
                status="degraded" if p.degraded else "ok", frame=frame,
                rung=scheduler_mod.WARM_RUNG, path=f"delta_fold:{mode}")
            obs.counter_add(f"serve_warm_{mode}", 1)
            self.scheduler.observe(scheduler_mod.WARM_RUNG,
                                   time.perf_counter() - t0)
        except Exception as exc:  # noqa: BLE001 — warm-path failure domain:
            # classify; bad data errors out, anything else falls to the
            # per-source exact rung (stamped degraded)
            fkind = resilience.classify(exc)
            if fkind is FailureKind.DATA_ERROR:
                p.result = self._error_result(p, resilience.error_record(exc))
                return
            resilience.record_degradation("multisource", "per_source", fkind)
            p.degraded = True
            self._dispatch_solo(p)

    # -- cold clients: batched continuous dispatch --------------------------

    def _dispatch_cold(self, cold: list[_Pending]) -> None:
        from crimp_tpu.ops import autotune, multisource

        max_seg = max(max((p.prep.max_seg for p in cold), default=1), 1)
        resolved = autotune.resolve_multisource(len(cold), max_seg)
        rung_groups: dict[str, list[_Pending]] = {}
        now = time.perf_counter()
        for p in cold:
            if not resolved["multisource"]:
                # knob off: the per-source loop IS the configured path —
                # not a degradation
                rung_groups.setdefault("per_source", []).append(p)
                p.rung = "per_source"
                continue
            remaining = None
            if p.req.deadline_s is not None and p.req.submitted_at is not None:
                remaining = p.req.deadline_s - (now - p.req.submitted_at)
            rung, forced = self.scheduler.pick_rung(remaining, self.breakers)
            if forced is not None and rung != self.scheduler.ladder[0]:
                resilience.record_degradation("multisource", rung, forced)
                obs.counter_add("serve_preemptive_degrades", 1)
                p.degraded = True
            p.rung = rung
            rung_groups.setdefault(rung, []).append(p)

        for rung in ("batched", "split_bucket"):
            if rung_groups.get(rung):
                self._dispatch_buckets(rung_groups[rung], rung, resolved)
        for p in rung_groups.get("per_source", ()):
            self._dispatch_solo(p)

    def _dispatch_buckets(self, items: list[_Pending], rung: str,
                          resolved: dict) -> None:
        from crimp_tpu.ops import multisource

        groups: dict[tuple, list[_Pending]] = {}
        for p in items:
            pr = p.prep
            groups.setdefault((pr.kind, pr.cfg, int(pr.tpl.n_comp)),
                              []).append(p)
        # deque, not a list: pop(0) shifts every pending bucket, turning
        # a many-bucket round (plus split-retries) into O(n^2) host work
        queue: deque[list[_Pending]] = deque()
        for members in groups.values():
            for b in multisource.bucket_sources(
                [max(m.prep.max_seg, 1) for m in members],
                max_pad_ratio=resolved["max_pad"],
                batch_cap=resolved["batch_cap"],
            ):
                bucket = [members[j] for j in b]
                if rung == "split_bucket" and len(bucket) > 1:
                    # pre-emptive half-buckets: the rung the scheduler
                    # picked, taken before dispatch instead of after an OOM
                    mid = (len(bucket) + 1) // 2
                    queue.append(bucket[:mid])
                    queue.append(bucket[mid:])
                else:
                    queue.append(bucket)

        while queue:
            bucket = queue.popleft()
            t0 = time.perf_counter()
            try:
                faultinject.fire("serve_dispatch")
                frames, phase_lists, t_refs = survey.compute_bucket(
                    [m.prep for m in bucket])
                wall = time.perf_counter() - t0
                self.breakers.record_success(rung)
                self.scheduler.observe(rung, wall / len(bucket))
                for m, frame, pl, tr in zip(bucket, frames, phase_lists,
                                            t_refs):
                    self._seed_client(m, pl, tr)
                    m.result = RequestResult(
                        client_id=m.req.client_id,
                        status="degraded" if m.degraded else "ok",
                        frame=frame, rung=m.rung or rung, path="batched")
            except Exception as exc:  # noqa: BLE001 — the bucket failure
                # domain walks the multisource ladder exactly like the
                # survey driver: split and retry, demote a singleton
                fkind = resilience.classify(exc)
                self.breakers.record_failure(rung, fkind)
                if len(bucket) > 1:
                    mid = (len(bucket) + 1) // 2
                    queue.appendleft(bucket[mid:])
                    queue.appendleft(bucket[:mid])
                    resilience.record_degradation("multisource",
                                                  "split_bucket", fkind)
                    for m in bucket:
                        m.degraded = True
                    continue
                resilience.record_degradation("multisource", "per_source",
                                              fkind)
                for m in bucket:
                    m.degraded = True
                    self._dispatch_solo(m)

    # -- the ladder floor: per-source (always succeeds or classifies) -------

    def _dispatch_solo(self, p: _Pending) -> None:
        t0 = time.perf_counter()

        def solo():
            # delta_fold=1 routes the fold through the fingerprinted
            # cache: the FIRST request stores the exact product (bits
            # unchanged), so this client's next request takes the
            # cache-hit / B@dp path
            return survey.measure_source_toas(
                p.req.spec, self.phShiftRes, self.nbrBins, self.varyAmps,
                _prep=p.prep, delta_fold=1)

        try:
            frame = solo()
        except Exception as exc:  # noqa: BLE001 — per-source domain: the
            # classified record separates data errors from device loss;
            # device-shaped kinds get one pinned-CPU attempt (the device
            # ladder's last rung)
            fkind = resilience.classify(exc)
            if fkind in resilience.CPU_FALLBACK_KINDS:
                try:
                    with resilience.pinned_cpu(fkind):
                        frame = solo()
                    p.degraded = True
                except Exception as exc2:  # noqa: BLE001 — final rung
                    # failed too: record the classified error
                    p.result = self._error_result(
                        p, resilience.error_record(exc2))
                    return
            else:
                p.result = self._error_result(p, resilience.error_record(exc))
                return
        # Warmth is contingent on the fold cache CONFIRMING a product was
        # stored under this client's tag (cache tier off, or a failed
        # seed, keeps the client cold) — an optimistic flag here would
        # send the next request down a guaranteed-cache-miss warm path.
        from crimp_tpu.ops import deltafold

        info = deltafold.last_fold_info()
        if info.get("stored") and info.get("tag") == p.req.client_id:
            self._warm.add(p.req.client_id)
        self.scheduler.observe("per_source", time.perf_counter() - t0)
        p.result = RequestResult(
            client_id=p.req.client_id,
            status="degraded" if p.degraded else "ok",
            frame=frame, rung=p.rung or "per_source", path="per_source")

    # -- shared plumbing ----------------------------------------------------

    def _seed_client(self, m: _Pending, phase_list, t_ref) -> None:
        """Seed the delta-fold cache from a batched (bit-identical) fold
        so this client's next request re-times as one B@dp matmul."""
        from crimp_tpu.ops import deltafold

        try:
            seg_times = m.prep.seg_times
            sizes = [t.size for t in seg_times]
            times_cat = np.concatenate(seg_times) if seg_times \
                else np.zeros(0)
            phases_cat = np.concatenate(
                [np.asarray(ph) for ph in phase_list]) if phase_list \
                else np.zeros(0)
            key = deltafold.store_product(m.prep.tm, times_cat, sizes,
                                          np.asarray(t_ref), phases_cat,
                                          tag=m.req.client_id)
            if key is not None:  # cache tier off returns None: stay cold
                self._warm.add(m.req.client_id)
        except Exception as exc:  # noqa: BLE001 — seeding is a throughput
            # optimization; its failure is classified telemetry, never a
            # request failure (the client simply stays cold)
            logger.warning("fold-cache seed failed for %s (%s)",
                           m.req.client_id,
                           resilience.error_record(exc))

    def _error_result(self, p: _Pending, rec: dict) -> RequestResult:
        obs.counter_add("serve_errors", 1)
        logger.warning("request %s failed: %s", p.req.client_id, rec)
        return RequestResult(
            client_id=p.req.client_id, status="error", rung=p.rung,
            kind=rec["kind"], error=rec)

    def _finalize(self, p: _Pending) -> None:
        res = p.result
        now = time.perf_counter()
        if p.req.submitted_at is not None:
            res.latency_s = now - p.req.submitted_at
            if p.req.deadline_s is not None and \
                    res.latency_s > p.req.deadline_s:
                res.deadline_miss = True
                self.counts["deadline_miss"] += 1
                obs.counter_add("serve_deadline_miss", 1)
        if res.status == "degraded":
            res.kind = res.kind or None
        self.counts[res.status] = self.counts.get(res.status, 0) + 1
        obs.counter_add(f"serve_{res.status}", 1)
        obs.record_span("serve_request", res.latency_s or 0.0,
                        kind="request", client=res.client_id,
                        status=res.status, rung=res.rung or "",
                        path=res.path or "")

    def stats(self) -> dict:
        """Engine telemetry: admission, completion, breaker and scheduler
        state — bench_serving folds this into its ledger record."""
        return {
            "admitted": self.queue.admitted,
            "rejected": self.queue.rejected,
            "pending": len(self.queue),
            "ok": self.counts["ok"],
            "degraded": self.counts["degraded"],
            "errors": self.counts["error"],
            "deadline_misses": self.counts["deadline_miss"],
            "steps": self.counts["steps"],
            "warm_clients": len(self._warm),
            "breakers": self.breakers.snapshot(),
            "rung_latency_est_s": self.scheduler.estimates(),
            "capacity": dict(self.capacity),
        }


__all__ = ["RequestResult", "ServingEngine"]
