"""The resident serving engine: continuous batching over the survey core.

One long-lived :class:`ServingEngine` replaces the run-to-completion
batch CLI for "millions of users" workloads: requests enter through the
bounded admission queue (serve/admission.py) and each :meth:`step` is one
CONTINUOUS-BATCHING round — every admitted request becomes a row in the
next multisource dispatch (``bucket_sources`` → ``stacked_fold`` via
``survey.compute_bucket``), so dispatch overhead amortizes across
whatever arrived since the last round instead of per request.

Request lifecycle (docs/serving.md has the state machine):

1. **admission** — accepted or rejected with a taxonomy kind (bounded
   queue, backpressure);
2. **scheduling** — the deadline-aware scheduler (serve/scheduler.py)
   picks the highest ladder rung the remaining budget affords and the
   per-rung circuit breakers (serve/breaker.py) admit;
3. **dispatch** — cold clients batch at the picked rung; RETURNING
   clients take the delta-fold hot path (``measure_source_toas`` with
   ``delta_fold=1`` and ``cache_tag`` = client name): a re-timing is one
   ``B @ dp`` matmul against the cached fold product, seeded from the
   client's first (batched, bit-identical) fold;
4. **completion** — every admitted request resolves as ``ok``
   (bit-identical to the parity-pinned reference path), ``degraded``
   (stamped via ``record_degradation``), or ``error`` with a classified
   record (DATA_ERROR never degrades — bad input fails the same on every
   rung).  No request ever returns an unclassified error.

Failure domains are inherited from ``pipelines/survey.py``: a failed
bucket splits and retries, a single-request bucket demotes to the
per-source rung, device-shaped per-source failures get one pinned-CPU
attempt.  The ``serve_dispatch`` fault point fires on every batched and
warm dispatch (NOT on the per-source bottom rung — the ladder's floor is
the clean path, mirroring ``survey_bucket``).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

import numpy as np

from crimp_tpu import obs, resilience
from crimp_tpu.pipelines import survey
from crimp_tpu.resilience import faultinject
from crimp_tpu.resilience.taxonomy import FailureKind
from crimp_tpu.serve import breaker as breaker_mod
from crimp_tpu.serve import scheduler as scheduler_mod
from crimp_tpu.serve.admission import (AdmissionQueue, AdmissionRejected,
                                       TimingRequest)

logger = logging.getLogger("crimp_tpu.serve")


@dataclass
class RequestResult:
    """One request's terminal state — the serving contract's unit.

    ``status``: ``ok`` (completed bit-identically on the reference
    path), ``degraded`` (completed on a lower rung, stamped in the obs
    manifest), or ``error`` (classified failure record; ``kind`` from
    the closed taxonomy).  Rejected requests never reach a result — they
    leave :meth:`ServingEngine.submit` as :class:`AdmissionRejected`.
    """

    client_id: str
    status: str
    frame: object = None
    rung: str | None = None
    path: str | None = None  # delta / cache / batched / per_source / ...
    kind: str | None = None
    latency_s: float | None = None
    deadline_miss: bool = False
    error: dict | None = None


@dataclass
class _Pending:
    """A drained request moving through one batching round."""

    req: TimingRequest
    prep: object = None
    degraded: bool = False
    rung: str | None = None
    result: RequestResult | None = None
    extra: dict = field(default_factory=dict)


class ServingEngine:
    """Long-lived timing service over the multisource batch engine."""

    def __init__(self, queue: AdmissionQueue | None = None,
                 scheduler: scheduler_mod.DeadlineScheduler | None = None,
                 breakers: breaker_mod.RungBreakers | None = None,
                 phShiftRes: int = 1000, nbrBins: int = 15,
                 varyAmps: bool = False, mesh=None):
        self.queue = queue if queue is not None else AdmissionQueue()
        self.scheduler = scheduler if scheduler is not None \
            else scheduler_mod.DeadlineScheduler()
        self.breakers = breakers if breakers is not None \
            else breaker_mod.RungBreakers()
        self.phShiftRes = int(phShiftRes)
        self.nbrBins = int(nbrBins)
        self.varyAmps = bool(varyAmps)
        self._default_deadline = scheduler_mod.default_deadline_s()
        self._warm: set[str] = set()  # clients with a seeded fold product
        self.counts = {"ok": 0, "degraded": 0, "error": 0,
                       "deadline_miss": 0, "steps": 0}
        # capacity note: the (optionally global, multi-host) mesh the
        # engine serves on — informational for stats()/bench_serving; the
        # dispatch paths keep routing through the multisource engine's own
        # mesh selection, so passing a mesh never changes results
        self.mesh = mesh
        self.capacity = self._capacity_note(mesh)

    @staticmethod
    def _capacity_note(mesh) -> dict:
        """Describe the serving capacity: device count, mesh axes, and the
        process (host) topology — so a multi-host deployment's stats say
        which fraction of the fleet this engine instance fronts."""
        try:
            from crimp_tpu.parallel import multihost
            pidx, pcount = multihost.process_identity()
        except Exception:  # noqa: BLE001 — capacity note is telemetry only  # graftlint: disable=GL006 (telemetry guard: the capacity note must never block engine construction)
            pidx, pcount = 0, 1
        note = {"process_index": pidx, "process_count": pcount,
                "devices": None, "mesh_axes": None}
        if mesh is not None:
            try:
                note["devices"] = int(mesh.devices.size)
                note["mesh_axes"] = {str(a): int(mesh.shape[a])
                                     for a in mesh.axis_names}
            except Exception:  # noqa: BLE001 — duck-typed mesh  # graftlint: disable=GL006 (telemetry guard: an exotic mesh object degrades to a partial note)
                pass
        return note

    # -- lifecycle ----------------------------------------------------------

    def warmup(self, **kwargs) -> dict:
        """AOT-compile the hot kernels before the first request (PR 1's
        ``warmup()`` + the persistent compile cache)."""
        import crimp_tpu

        return crimp_tpu.warmup(**kwargs)

    def submit(self, spec, deadline_s: float | None = None) -> TimingRequest:
        """Admit one request (a survey ``SourceSpec`` or a prebuilt
        :class:`TimingRequest`); raises :class:`AdmissionRejected` with a
        taxonomy kind on refusal."""
        req = spec if isinstance(spec, TimingRequest) \
            else TimingRequest(spec=spec, deadline_s=deadline_s)
        if req.deadline_s is None:
            req.deadline_s = self._default_deadline
        return self.queue.offer(req)

    # -- one continuous-batching round --------------------------------------

    def step(self) -> list[RequestResult]:
        """Process everything admitted since the last round; returns one
        terminal :class:`RequestResult` per drained request (input order)."""
        batch = self.queue.drain()
        if not batch:
            return []
        self.counts["steps"] += 1
        pend = [_Pending(req=r) for r in batch]
        obs.beat(0, len(pend), label="serve", force=True)

        warm: list[_Pending] = []
        cold: list[_Pending] = []
        for p in pend:
            try:
                p.prep = survey._prep_source(
                    p.req.spec, self.phShiftRes, self.nbrBins, self.varyAmps)
            except Exception as exc:  # noqa: BLE001 — per-request failure
                # domain: a malformed spec fails CLASSIFIED, poisons nothing
                p.result = self._error_result(p, resilience.error_record(exc))
                continue
            (warm if p.req.client_id in self._warm else cold).append(p)

        for p in warm:
            self._dispatch_warm(p)

        if cold:
            self._dispatch_cold(cold)

        done = 0
        for p in pend:
            if p.result is None:  # defensive: the dispatch paths above
                # must resolve every request; an unresolved one is a bug,
                # surfaced as a classified UNKNOWN rather than a None leak
                p.result = self._error_result(p, resilience.error_record(
                    RuntimeError("request left unresolved by dispatch")))
            self._finalize(p)
            done += 1
            obs.beat(done, len(pend), label="serve")
        return [p.result for p in pend]

    def drain_all(self, max_steps: int = 1000) -> list[RequestResult]:
        """Step until the queue is empty (utility for tests/benches)."""
        out: list[RequestResult] = []
        for _ in range(max_steps):
            if not len(self.queue):
                break
            out.extend(self.step())
        return out

    # -- warm clients: the delta-fold hot path ------------------------------

    def _dispatch_warm(self, p: _Pending) -> None:
        t0 = time.perf_counter()
        try:
            faultinject.fire("serve_dispatch")
            frame = survey.measure_source_toas(
                p.req.spec, self.phShiftRes, self.nbrBins, self.varyAmps,
                _prep=p.prep, delta_fold=1)
            from crimp_tpu.ops import deltafold

            mode = deltafold.last_fold_info().get("mode") or "exact"
            p.result = RequestResult(
                client_id=p.req.client_id, status="ok", frame=frame,
                rung="batched", path=f"delta_fold:{mode}")
            obs.counter_add(f"serve_warm_{mode}", 1)
            self.scheduler.observe("batched", time.perf_counter() - t0)
        except Exception as exc:  # noqa: BLE001 — warm-path failure domain:
            # classify; bad data errors out, anything else falls to the
            # per-source exact rung (stamped degraded)
            fkind = resilience.classify(exc)
            if fkind is FailureKind.DATA_ERROR:
                p.result = self._error_result(p, resilience.error_record(exc))
                return
            resilience.record_degradation("multisource", "per_source", fkind)
            p.degraded = True
            self._dispatch_solo(p)

    # -- cold clients: batched continuous dispatch --------------------------

    def _dispatch_cold(self, cold: list[_Pending]) -> None:
        from crimp_tpu.ops import autotune, multisource

        max_seg = max(max((p.prep.max_seg for p in cold), default=1), 1)
        resolved = autotune.resolve_multisource(len(cold), max_seg)
        rung_groups: dict[str, list[_Pending]] = {}
        now = time.perf_counter()
        for p in cold:
            if not resolved["multisource"]:
                # knob off: the per-source loop IS the configured path —
                # not a degradation
                rung_groups.setdefault("per_source", []).append(p)
                p.rung = "per_source"
                continue
            remaining = None
            if p.req.deadline_s is not None and p.req.submitted_at is not None:
                remaining = p.req.deadline_s - (now - p.req.submitted_at)
            rung, forced = self.scheduler.pick_rung(remaining, self.breakers)
            if forced is not None and rung != self.scheduler.ladder[0]:
                resilience.record_degradation("multisource", rung, forced)
                obs.counter_add("serve_preemptive_degrades", 1)
                p.degraded = True
            p.rung = rung
            rung_groups.setdefault(rung, []).append(p)

        for rung in ("batched", "split_bucket"):
            if rung_groups.get(rung):
                self._dispatch_buckets(rung_groups[rung], rung, resolved)
        for p in rung_groups.get("per_source", ()):
            self._dispatch_solo(p)

    def _dispatch_buckets(self, items: list[_Pending], rung: str,
                          resolved: dict) -> None:
        from crimp_tpu.ops import multisource

        groups: dict[tuple, list[_Pending]] = {}
        for p in items:
            pr = p.prep
            groups.setdefault((pr.kind, pr.cfg, int(pr.tpl.n_comp)),
                              []).append(p)
        queue: list[list[_Pending]] = []
        for members in groups.values():
            for b in multisource.bucket_sources(
                [max(m.prep.max_seg, 1) for m in members],
                max_pad_ratio=resolved["max_pad"],
                batch_cap=resolved["batch_cap"],
            ):
                bucket = [members[j] for j in b]
                if rung == "split_bucket" and len(bucket) > 1:
                    # pre-emptive half-buckets: the rung the scheduler
                    # picked, taken before dispatch instead of after an OOM
                    mid = (len(bucket) + 1) // 2
                    queue.append(bucket[:mid])
                    queue.append(bucket[mid:])
                else:
                    queue.append(bucket)

        while queue:
            bucket = queue.pop(0)
            t0 = time.perf_counter()
            try:
                faultinject.fire("serve_dispatch")
                frames, phase_lists, t_refs = survey.compute_bucket(
                    [m.prep for m in bucket])
                wall = time.perf_counter() - t0
                self.breakers.record_success(rung)
                self.scheduler.observe(rung, wall / len(bucket))
                for m, frame, pl, tr in zip(bucket, frames, phase_lists,
                                            t_refs):
                    self._seed_client(m, pl, tr)
                    m.result = RequestResult(
                        client_id=m.req.client_id,
                        status="degraded" if m.degraded else "ok",
                        frame=frame, rung=m.rung or rung, path="batched")
            except Exception as exc:  # noqa: BLE001 — the bucket failure
                # domain walks the multisource ladder exactly like the
                # survey driver: split and retry, demote a singleton
                fkind = resilience.classify(exc)
                self.breakers.record_failure(rung, fkind)
                if len(bucket) > 1:
                    mid = (len(bucket) + 1) // 2
                    queue.insert(0, bucket[mid:])
                    queue.insert(0, bucket[:mid])
                    resilience.record_degradation("multisource",
                                                  "split_bucket", fkind)
                    for m in bucket:
                        m.degraded = True
                    continue
                resilience.record_degradation("multisource", "per_source",
                                              fkind)
                for m in bucket:
                    m.degraded = True
                    self._dispatch_solo(m)

    # -- the ladder floor: per-source (always succeeds or classifies) -------

    def _dispatch_solo(self, p: _Pending) -> None:
        t0 = time.perf_counter()

        def solo():
            # delta_fold=1 routes the fold through the fingerprinted
            # cache: the FIRST request stores the exact product (bits
            # unchanged), so this client's next request takes the
            # cache-hit / B@dp path
            return survey.measure_source_toas(
                p.req.spec, self.phShiftRes, self.nbrBins, self.varyAmps,
                _prep=p.prep, delta_fold=1)

        try:
            frame = solo()
        except Exception as exc:  # noqa: BLE001 — per-source domain: the
            # classified record separates data errors from device loss;
            # device-shaped kinds get one pinned-CPU attempt (the device
            # ladder's last rung)
            fkind = resilience.classify(exc)
            if fkind in resilience.CPU_FALLBACK_KINDS:
                try:
                    with resilience.pinned_cpu(fkind):
                        frame = solo()
                    p.degraded = True
                except Exception as exc2:  # noqa: BLE001 — final rung
                    # failed too: record the classified error
                    p.result = self._error_result(
                        p, resilience.error_record(exc2))
                    return
            else:
                p.result = self._error_result(p, resilience.error_record(exc))
                return
        self._warm.add(p.req.client_id)
        self.scheduler.observe("per_source", time.perf_counter() - t0)
        p.result = RequestResult(
            client_id=p.req.client_id,
            status="degraded" if p.degraded else "ok",
            frame=frame, rung=p.rung or "per_source", path="per_source")

    # -- shared plumbing ----------------------------------------------------

    def _seed_client(self, m: _Pending, phase_list, t_ref) -> None:
        """Seed the delta-fold cache from a batched (bit-identical) fold
        so this client's next request re-times as one B@dp matmul."""
        from crimp_tpu.ops import deltafold

        try:
            seg_times = m.prep.seg_times
            sizes = [t.size for t in seg_times]
            times_cat = np.concatenate(seg_times) if seg_times \
                else np.zeros(0)
            phases_cat = np.concatenate(
                [np.asarray(ph) for ph in phase_list]) if phase_list \
                else np.zeros(0)
            deltafold.store_product(m.prep.tm, times_cat, sizes,
                                    np.asarray(t_ref), phases_cat,
                                    tag=m.req.client_id)
            self._warm.add(m.req.client_id)
        except Exception as exc:  # noqa: BLE001 — seeding is a throughput
            # optimization; its failure is classified telemetry, never a
            # request failure (the client simply stays cold)
            logger.warning("fold-cache seed failed for %s (%s)",
                           m.req.client_id,
                           resilience.error_record(exc))

    def _error_result(self, p: _Pending, rec: dict) -> RequestResult:
        obs.counter_add("serve_errors", 1)
        logger.warning("request %s failed: %s", p.req.client_id, rec)
        return RequestResult(
            client_id=p.req.client_id, status="error", rung=p.rung,
            kind=rec["kind"], error=rec)

    def _finalize(self, p: _Pending) -> None:
        res = p.result
        now = time.perf_counter()
        if p.req.submitted_at is not None:
            res.latency_s = now - p.req.submitted_at
            if p.req.deadline_s is not None and \
                    res.latency_s > p.req.deadline_s:
                res.deadline_miss = True
                self.counts["deadline_miss"] += 1
                obs.counter_add("serve_deadline_miss", 1)
        if res.status == "degraded":
            res.kind = res.kind or None
        self.counts[res.status] = self.counts.get(res.status, 0) + 1
        obs.counter_add(f"serve_{res.status}", 1)
        obs.record_span("serve_request", res.latency_s or 0.0,
                        kind="request", client=res.client_id,
                        status=res.status, rung=res.rung or "",
                        path=res.path or "")

    def stats(self) -> dict:
        """Engine telemetry: admission, completion, breaker and scheduler
        state — bench_serving folds this into its ledger record."""
        return {
            "admitted": self.queue.admitted,
            "rejected": self.queue.rejected,
            "pending": len(self.queue),
            "ok": self.counts["ok"],
            "degraded": self.counts["degraded"],
            "errors": self.counts["error"],
            "deadline_misses": self.counts["deadline_miss"],
            "steps": self.counts["steps"],
            "warm_clients": len(self._warm),
            "breakers": self.breakers.snapshot(),
            "rung_latency_est_s": self.scheduler.estimates(),
            "capacity": dict(self.capacity),
        }


__all__ = ["RequestResult", "ServingEngine"]
