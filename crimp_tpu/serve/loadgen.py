"""Open-loop Poisson load generator for the serving engine.

Closed-loop load (send, wait, send) hides overload: a slow server slows
its own clients down and the measured latency flatlines.  The generator
here is OPEN-LOOP — arrival times are drawn up front from a seeded
exponential inter-arrival distribution and requests are attributed to
those SCHEDULED times regardless of how far behind the engine is, so
queue wait shows up in the latency distribution exactly as a real client
would feel it (the "coordinated omission" fix).

Single-threaded and deterministic: one event loop pushes every arrival
whose scheduled time has passed, runs one continuous-batching
:meth:`~crimp_tpu.serve.engine.ServingEngine.step`, repeats.  Rejections
(backpressure) are part of the measured outcome, not an error — the
summary counts them alongside completions.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from crimp_tpu.serve.admission import AdmissionRejected, TimingRequest

logger = logging.getLogger("crimp_tpu.serve")


def poisson_arrivals(rate_hz: float, n: int, seed: int = 0) -> np.ndarray:
    """``n`` arrival offsets (seconds from start) at ``rate_hz`` mean
    request rate, seeded — the same schedule every run."""
    rate_hz = float(rate_hz)
    if rate_hz <= 0:
        raise ValueError(f"rate_hz={rate_hz!r} must be > 0")
    n = int(n)
    if n < 1:
        raise ValueError(f"n={n!r} must be >= 1")
    rng = np.random.RandomState(int(seed))
    return np.cumsum(rng.exponential(1.0 / rate_hz, size=n))


def run_load(engine, specs, rate_hz: float, seed: int = 0,
             deadline_s: float | None = None) -> dict:
    """Replay ``specs`` against ``engine`` at a Poisson ``rate_hz``.

    Each spec is one request; arrival ``i`` submits ``specs[i]`` at its
    scheduled offset with ``submitted_at`` pre-stamped to that offset so
    latency includes any queue wait.  Returns the measured summary::

        {"rate_hz", "n_requests", "completed", "ok", "degraded",
         "errors", "rejected", "deadline_misses", "wall_s",
         "requests_per_s", "p50_latency_ms", "p99_latency_ms",
         "results": [RequestResult...]}
    """
    specs = list(specs)
    arrivals = poisson_arrivals(rate_hz, len(specs), seed=seed)
    t_start = time.perf_counter()
    results = []
    rejected = 0
    i = 0
    while i < len(specs) or len(engine.queue):
        now = time.perf_counter() - t_start
        while i < len(specs) and arrivals[i] <= now:
            req = TimingRequest(spec=specs[i], deadline_s=deadline_s,
                                submitted_at=t_start + arrivals[i])
            try:
                engine.submit(req)
            except AdmissionRejected as exc:
                rejected += 1
                logger.info("request %s rejected at admission (%s)",
                            req.client_id, exc.kind.value)
            i += 1
        if len(engine.queue):
            results.extend(engine.step())
        elif i < len(specs):
            # idle until the next scheduled arrival (open-loop: we never
            # pull arrivals forward to keep the engine busy)
            time.sleep(min(max(arrivals[i] - now, 0.0), 0.05))
    wall_s = time.perf_counter() - t_start

    lat_ms = np.asarray([r.latency_s for r in results
                         if r.latency_s is not None]) * 1e3
    completed = len(results)
    return {
        "rate_hz": float(rate_hz),
        "n_requests": len(specs),
        "completed": completed,
        "ok": sum(1 for r in results if r.status == "ok"),
        "degraded": sum(1 for r in results if r.status == "degraded"),
        "errors": sum(1 for r in results if r.status == "error"),
        "rejected": rejected,
        "deadline_misses": sum(1 for r in results if r.deadline_miss),
        "wall_s": float(wall_s),
        "requests_per_s": float(completed / wall_s) if wall_s > 0 else 0.0,
        "p50_latency_ms": float(np.percentile(lat_ms, 50))
        if lat_ms.size else 0.0,
        "p99_latency_ms": float(np.percentile(lat_ms, 99))
        if lat_ms.size else 0.0,
        "results": results,
    }


__all__ = ["poisson_arrivals", "run_load"]
