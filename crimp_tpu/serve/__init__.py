"""Serving layer: a resident timing service over the batch engine.

The batch CLI pays dispatch, compile-cache lookup, and host staging per
invocation; a timing service amortizes them across a process lifetime.
:class:`ServingEngine` holds the AOT-warmed executables and the
delta-fold cache resident, admits requests through a BOUNDED queue
(backpressure, typed rejections), forms continuous batches through the
multisource engine, and degrades along the parity-pinned resilience
ladder — pre-emptively when a deadline budget demands it, reactively
when a dispatch fails, with per-rung circuit breakers remembering sick
rungs.

The serving contract (docs/serving.md): every request either completes
bit-identically, completes degraded (stamped via ``record_degradation``),
or is rejected at admission with a taxonomy kind.  No request ever
returns an unclassified error.

Off-path inertness: nothing imports this package unless serving is used;
batch pipelines are bit-identical with or without it.
"""

from crimp_tpu.serve.admission import (AdmissionQueue, AdmissionRejected,
                                       TimingRequest, queue_capacity)
from crimp_tpu.serve.breaker import RungBreakers, breaker_threshold
from crimp_tpu.serve.engine import RequestResult, ServingEngine
from crimp_tpu.serve.loadgen import poisson_arrivals, run_load
from crimp_tpu.serve.scheduler import (DeadlineScheduler, LADDER,
                                       default_deadline_s)

__all__ = [
    "AdmissionQueue", "AdmissionRejected", "DeadlineScheduler", "LADDER",
    "RequestResult", "RungBreakers", "ServingEngine", "TimingRequest",
    "breaker_threshold", "default_deadline_s", "poisson_arrivals",
    "queue_capacity", "run_load",
]
