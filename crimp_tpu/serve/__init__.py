"""Serving layer: a resident timing service over the batch engine.

The batch CLI pays dispatch, compile-cache lookup, and host staging per
invocation; a timing service amortizes them across a process lifetime.
:class:`ServingEngine` holds the AOT-warmed executables and the
delta-fold cache resident, admits requests through BOUNDED per-priority-
class queues (backpressure, typed rejections, deficit-round-robin fair
drain), forms continuous batches through the multisource engine — warm
clients re-time as ONE stacked ``refold_batch`` dispatch per round — and
degrades along the parity-pinned resilience ladders — pre-emptively when
a deadline budget demands it, reactively when a dispatch fails, with
per-rung circuit breakers remembering sick rungs.

The serving contract (docs/serving.md): every request either completes
bit-identically, completes degraded (stamped via ``record_degradation``),
or is rejected at admission with a taxonomy kind.  No request ever
returns an unclassified error.

Off-path inertness: nothing imports this package unless serving is used;
batch pipelines are bit-identical with or without it.
"""

from crimp_tpu.serve.admission import (AdmissionQueue, AdmissionRejected,
                                       PRIORITY_CLASSES, TimingRequest,
                                       queue_capacity)
from crimp_tpu.serve.breaker import RungBreakers, breaker_threshold
from crimp_tpu.serve.engine import RequestResult, ServingEngine
from crimp_tpu.serve.loadgen import poisson_arrivals, run_load
from crimp_tpu.serve.scheduler import (DeadlineScheduler, LADDER,
                                       WARM_BATCH_RUNG, WARM_RUNG,
                                       default_deadline_s)

__all__ = [
    "AdmissionQueue", "AdmissionRejected", "DeadlineScheduler", "LADDER",
    "PRIORITY_CLASSES", "RequestResult", "RungBreakers", "ServingEngine",
    "TimingRequest", "WARM_BATCH_RUNG", "WARM_RUNG", "breaker_threshold",
    "default_deadline_s", "poisson_arrivals", "queue_capacity", "run_load",
]
