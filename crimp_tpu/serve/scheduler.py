"""Deadline-aware rung scheduler: degrade pre-emptively, never fail.

Each admitted request carries a latency budget; the scheduler keeps an
EWMA of recently OBSERVED per-request latency at every rung of the
parity-pinned ``resilience.LADDERS["multisource"]`` ladder and, before
dispatch, picks the HIGHEST rung that (a) its circuit breaker admits and
(b) the remaining budget can afford.  A request whose budget cannot
afford the top rung lands on a lower rung *before* burning the budget
discovering that — the overload story is "coarser batching, same
classified completion", not a timeout.

The bottom rung is always eligible: an admitted request completes (bit-
identically or stamped degraded) no matter how sick the upper rungs are —
the degrade-never-fail half of the serving contract.  Any pick below the
top rung is stamped through ``resilience.record_degradation`` with the
kind that forced it (TIMEOUT for budget, the breaker's last classified
kind for a shed).

The ``serve_deadline`` fault point fires inside the budget evaluation;
an injected fault there classifies and forces the bottom rung (budget
treated as spent) — the chaos-test knob for "the SLO machinery itself
is failing".
"""

from __future__ import annotations

import logging

from crimp_tpu import knobs, resilience
from crimp_tpu.resilience import faultinject, taxonomy
from crimp_tpu.resilience.taxonomy import FailureKind
from crimp_tpu.serve import breaker as breaker_mod

logger = logging.getLogger("crimp_tpu.serve")

LADDER = resilience.LADDERS["multisource"]  # ("batched", "split_bucket",
#                                              "per_source")
# The warm (delta-fold) path's rung labels.  Kept DISTINCT from the cold
# multisource ladder above so warm latency observations never pollute the
# cold rungs' EWMA estimates — ``pick_rung`` only walks LADDER, so the
# warm keys in ``estimates()`` are attribution-only.  WARM_BATCH_RUNG is
# the top of ``resilience.LADDERS["serve_warm"]`` (demotions stamp
# ``warm_batched -> solo``); WARM_RUNG labels the per-request solo warm
# dispatch in results and observations.
WARM_BATCH_RUNG = resilience.LADDERS["serve_warm"][0]  # "warm_batched"
WARM_RUNG = "warm"
EWMA_ALPHA = 0.3


def default_deadline_s() -> float | None:
    """CRIMP_TPU_SERVE_DEADLINE_MS in seconds, or None when unset."""
    ms = knobs.env_pos_float("CRIMP_TPU_SERVE_DEADLINE_MS")
    return None if ms is None else ms / 1000.0


class DeadlineScheduler:
    """Pick the best affordable ladder rung for each dispatch."""

    def __init__(self, ladder: tuple = LADDER, alpha: float = EWMA_ALPHA):
        if not ladder:
            raise ValueError("scheduler needs a non-empty ladder")
        self.ladder = tuple(ladder)
        self.alpha = float(alpha)
        self._est: dict[str, float] = {}

    def observe(self, rung: str, latency_s: float) -> None:
        """Feed one observed per-request latency at ``rung`` into the EWMA."""
        latency_s = float(latency_s)
        if latency_s < 0:
            return
        prev = self._est.get(rung)
        self._est[rung] = latency_s if prev is None else \
            self.alpha * latency_s + (1.0 - self.alpha) * prev

    def estimate(self, rung: str) -> float | None:
        """EWMA latency estimate for ``rung`` (None until observed)."""
        return self._est.get(rung)

    def estimates(self) -> dict[str, float]:
        return dict(self._est)

    def pick_rung(self, remaining_s: float | None,
                  breakers: breaker_mod.RungBreakers | None = None,
                  ) -> tuple[str, FailureKind | None]:
        """The rung this request dispatches at, plus the kind that forced
        a sub-top pick (None = top rung, no degradation to stamp).

        Walks the ladder top-down; a rung is skipped when its breaker
        sheds (kind = the breaker's last classified failure) or when its
        latency estimate exceeds the remaining budget (kind = TIMEOUT).
        The bottom rung is returned unconditionally — shedding there
        would turn an admitted request into a failure, which the serving
        contract forbids.
        """
        forced: FailureKind | None = None
        try:
            faultinject.fire("serve_deadline")
        except Exception as exc:  # noqa: BLE001 — deadline-machinery
            # failure domain: classify and treat the budget as spent
            forced = taxonomy.classify(exc)
            logger.warning("deadline evaluation failed (%s); forcing the "
                           "bottom rung", forced.value)
            return self.ladder[-1], forced
        for rung in self.ladder[:-1]:
            if breakers is not None and not breakers.allow(rung):
                forced = breakers.last_kind(rung) or FailureKind.UNKNOWN
                continue
            est = self._est.get(rung)
            if remaining_s is not None and est is not None \
                    and est > remaining_s:
                forced = FailureKind.TIMEOUT
                continue
            if remaining_s is not None and remaining_s <= 0.0:
                forced = FailureKind.TIMEOUT
                continue
            return rung, None if rung == self.ladder[0] else forced
        return self.ladder[-1], forced or (
            FailureKind.TIMEOUT if remaining_s is not None else None)


__all__ = ["DeadlineScheduler", "EWMA_ALPHA", "LADDER", "WARM_BATCH_RUNG",
           "WARM_RUNG", "default_deadline_s"]
