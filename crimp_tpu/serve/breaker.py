"""Per-rung circuit breakers: a sick rung sheds to its ladder.

Without a breaker, every request under a persistent fault (dead device,
exhausted HBM) burns its own deadline budget rediscovering the same
failure at the top rung before degrading.  The breaker remembers: after
``CRIMP_TPU_SERVE_BREAKER`` (default 5) consecutive CLASSIFIED failures
at a rung it OPENS, and the scheduler routes around that rung
pre-emptively.  After a cooldown it HALF-OPENS — exactly one probe
request is allowed through; a probe success closes the breaker (the rung
is healthy again), a probe failure re-opens it.

Determinism: the cooldown is counted in DENIED CALLS, not wall-clock
seconds — chaos tests drive the full CLOSED → OPEN → HALF_OPEN → CLOSED
cycle with exact call counts and no sleeps, the same no-wall-clock
discipline as the retry policy's sha256 jitter.

Transitions are counted (``serve_breaker_open`` / ``_half_open`` /
``_close`` / ``_reopen``, plus per-rung variants) so a chaos run's
manifest proves the cycle happened.
"""

from __future__ import annotations

import logging

from crimp_tpu import knobs, obs
from crimp_tpu.resilience.taxonomy import FailureKind

logger = logging.getLogger("crimp_tpu.serve")

DEFAULT_THRESHOLD = 5
DEFAULT_COOLDOWN_CALLS = 8

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


def breaker_threshold() -> int:
    """CRIMP_TPU_SERVE_BREAKER (default 5; 0 disables)."""
    val = knobs.env_nonneg_int("CRIMP_TPU_SERVE_BREAKER")
    return DEFAULT_THRESHOLD if val is None else val


class _Rung:
    __slots__ = ("state", "failures", "denials", "probing", "last_kind")

    def __init__(self):
        self.state = CLOSED
        self.failures = 0  # consecutive classified failures while CLOSED
        self.denials = 0  # calls shed while OPEN (the cooldown counter)
        self.probing = False  # a HALF_OPEN probe is in flight
        self.last_kind: FailureKind | None = None


class RungBreakers:
    """One breaker per ladder rung (lazily created, independent states)."""

    def __init__(self, threshold: int | None = None,
                 cooldown_calls: int = DEFAULT_COOLDOWN_CALLS):
        self.threshold = breaker_threshold() if threshold is None \
            else int(threshold)
        self.cooldown_calls = max(int(cooldown_calls), 1)
        self._rungs: dict[str, _Rung] = {}

    def _rung(self, rung: str) -> _Rung:
        return self._rungs.setdefault(rung, _Rung())

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def allow(self, rung: str) -> bool:
        """Whether the scheduler may route a request to ``rung`` now.

        An OPEN rung sheds (and counts the shed toward its cooldown);
        once the cooldown elapses the rung HALF-OPENS and admits exactly
        one probe until its outcome is recorded.
        """
        if not self.enabled:
            return True
        r = self._rung(rung)
        if r.state == CLOSED:
            return True
        if r.state == OPEN:
            r.denials += 1
            if r.denials >= self.cooldown_calls:
                r.state = HALF_OPEN
                r.probing = False
                obs.counter_add("serve_breaker_half_open", 1)
                obs.counter_add(f"serve_breaker_half_open_{rung}", 1)
                logger.warning("breaker %s: open -> half_open (probe)", rung)
            else:
                obs.counter_add("serve_breaker_shed", 1)
                return False
        # HALF_OPEN: one probe at a time
        if r.probing:
            obs.counter_add("serve_breaker_shed", 1)
            return False
        r.probing = True
        return True

    def record_success(self, rung: str) -> None:
        if not self.enabled:
            return
        r = self._rung(rung)
        if r.state == HALF_OPEN:
            obs.counter_add("serve_breaker_close", 1)
            obs.counter_add(f"serve_breaker_close_{rung}", 1)
            logger.warning("breaker %s: half_open -> closed", rung)
        r.state = CLOSED
        r.failures = 0
        r.denials = 0
        r.probing = False
        r.last_kind = None

    def record_failure(self, rung: str, kind: FailureKind) -> None:
        if not self.enabled:
            return
        r = self._rung(rung)
        r.last_kind = kind
        if r.state == HALF_OPEN:
            r.state = OPEN
            r.denials = 0
            r.probing = False
            obs.counter_add("serve_breaker_reopen", 1)
            obs.counter_add(f"serve_breaker_reopen_{rung}", 1)
            logger.warning("breaker %s: probe failed (%s); half_open -> "
                           "open", rung, kind.value)
            return
        r.failures += 1
        if r.state == CLOSED and r.failures >= self.threshold:
            r.state = OPEN
            r.denials = 0
            obs.counter_add("serve_breaker_open", 1)
            obs.counter_add(f"serve_breaker_open_{rung}", 1)
            logger.warning("breaker %s: closed -> open after %d classified "
                           "failures (%s)", rung, r.failures, kind.value)

    def state(self, rung: str) -> str:
        return self._rungs[rung].state if rung in self._rungs else CLOSED

    def last_kind(self, rung: str) -> FailureKind | None:
        return self._rungs[rung].last_kind if rung in self._rungs else None

    def snapshot(self) -> dict:
        """{rung: {state, failures, denials}} for stats/manifests."""
        return {rung: {"state": r.state, "failures": r.failures,
                       "denials": r.denials}
                for rung, r in self._rungs.items()}


__all__ = ["CLOSED", "DEFAULT_COOLDOWN_CALLS", "DEFAULT_THRESHOLD",
           "HALF_OPEN", "OPEN", "RungBreakers", "breaker_threshold"]
