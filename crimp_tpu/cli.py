"""The 12 console entry points (CLI surface parity: pyproject.toml:36-48 of
the reference — timeintervalsfortoas, templatepulseprofile, measuretoas,
diagnosetoas, addphasecolumn, ephemintegerrotation, phshifttotimfile,
fittoas, localephemerides, pulseprofile_plots, localephemerides_plot,
mergeoverlappingtims). Flags mirror the reference parsers so run scripts
carry over unchanged; each tool writes a truncating <output>.log."""

from __future__ import annotations

import argparse

from crimp_tpu.utils.logging import configure_logging, get_logger, verbosity_to_level


def _bool_flag(parser, *names, help="", default=False):
    parser.add_argument(*names, help=help, default=default, action=argparse.BooleanOptionalAction)


def _add_verbosity(parser):
    parser.add_argument(
        "-v", "--verbose", action="count", default=0,
        help="WARNING if absent, -v: INFO, -vv: DEBUG",
    )


def _setup_logging(args, logfile_stem: str):
    configure_logging(
        console_level=verbosity_to_level(args.verbose),
        file_path=f"{logfile_stem}.log",
        file_level="INFO",
        force=True,
    )
    get_logger(__name__).info("\nCLI starting")


# ---------------------------------------------------------------------------


def timeintervalsfortoas(argv=None):
    parser = argparse.ArgumentParser(
        description="Creating time intervals for individual ToAs - saving info to .txt file"
    )
    parser.add_argument("evtFile", help="Fits event file", type=str)
    parser.add_argument("-tc", "--totCtsEachToA", help="Desired number of counts per ToA", type=int, default=1000)
    parser.add_argument("-wt", "--waitTimeCutoff", help="Do not allow any gap in GTI larger than this (days)", type=float, default=1)
    parser.add_argument("-el", "--eneLow", help="Low energy filter (keV), default=0.5", type=float, default=0.5)
    parser.add_argument("-eh", "--eneHigh", help="High energy filter (keV), default=10", type=float, default=10)
    parser.add_argument("-mc", "--min_counts", help="Merge intervals with fewer counts, default=totCtsEachToA/2", type=int, default=None)
    parser.add_argument("-mw", "--max_wait", help="Merge intervals closer than this (days), default=waitTimeCutoff", type=float, default=None)
    parser.add_argument("-of", "--outputFile", help="Output .txt/.log stem (default=timIntToAs)", type=str, default="timIntToAs")
    _bool_flag(parser, "-ce", "--correxposure", help="Correct exposure/rate for selected FPMs (NICER)")
    _add_verbosity(parser)
    args = parser.parse_args(argv)
    _setup_logging(args, args.outputFile)

    from crimp_tpu.pipelines.intervals import build_time_intervals

    build_time_intervals(
        args.evtFile, args.totCtsEachToA, args.waitTimeCutoff, args.eneLow, args.eneHigh,
        args.min_counts, args.max_wait, args.outputFile, args.correxposure,
    )


def templatepulseprofile(argv=None):
    parser = argparse.ArgumentParser(description="Build and fit pulse profile from event file")
    parser.add_argument("evtFile", help="Event file", type=str)
    parser.add_argument("timMod", help="Timing model (.par file)", type=str)
    parser.add_argument("-el", "--eneLow", help="lower energy cut, default=0.5 keV", type=float, default=0.5)
    parser.add_argument("-eh", "--eneHigh", help="high energy cut, default=10 keV", type=float, default=10)
    parser.add_argument("-nb", "--nbrBins", help="Number of profile bins, default=15", type=int, default=15)
    parser.add_argument("-pm", "--ppmodel", help="fourier | vonmises | cauchy", type=str, default="fourier")
    parser.add_argument("-nc", "--nbrComp", help="Number of components, default=2", type=int, default=2)
    parser.add_argument("-it", "--initTemplateMod", help="Initial template (overrides ppmodel/nbrComp)", type=str, default=None)
    _bool_flag(parser, "-fp", "--fixPhases", help="Fix phases from initial template")
    parser.add_argument("-fg", "--figure", help="Pulse-profile plot stem ('figure'.pdf)", type=str, default=None)
    parser.add_argument("-tf", "--templateFile", help="Output template .txt stem", type=str, default=None)
    _add_verbosity(parser)
    args = parser.parse_args(argv)
    _setup_logging(args, args.templateFile if args.templateFile else "logfile_buildtemplate")

    from crimp_tpu.pipelines.pulseprofile import PulseProfileFromEventFile

    PulseProfileFromEventFile(
        args.evtFile, args.timMod, args.eneLow, args.eneHigh, args.nbrBins
    ).fitpulseprofile(
        args.ppmodel, args.nbrComp, args.initTemplateMod, args.fixPhases, args.figure, args.templateFile
    )


def measuretoas(argv=None):
    parser = argparse.ArgumentParser(description="Script to measure ToAs from event file")
    parser.add_argument("evtFile", help="Name of a barycentered event file", type=str)
    parser.add_argument("timMod", help="Timing model, Tempo2 .par file should work", type=str)
    parser.add_argument("tempModPP", help="Template pulse-profile parameters", type=str)
    parser.add_argument("toagtifile", help="ToA interval .txt (from timeintervalsfortoas)", type=str)
    parser.add_argument("-el", "--enelow", help="Low energy filter, default=0.5", type=float, default=0.5)
    parser.add_argument("-eh", "--enehigh", help="High energy filter, default=10", type=float, default=10)
    parser.add_argument("-ts", "--toaStart", help="First ToA index", type=int, default=0)
    parser.add_argument("-te", "--toaEnd", help="Last ToA index (inclusive)", type=int, default=None)
    parser.add_argument("-pr", "--phShiftRes", help="Error-scan resolution 2*pi/res, default=1000", type=int, default=1000)
    parser.add_argument("-nb", "--nbrBins", help="Profile bins for chi2/plots, default=15", type=int, default=15)
    _bool_flag(parser, "-va", "--varyAmps", help="Vary pulsed fraction (not shape)")
    _bool_flag(parser, "-rv", "--readvaryparam", help="Read per-parameter vary flags from template")
    _bool_flag(parser, "-bm", "--brutemin", help="Global BRUTE minimization first")
    _bool_flag(parser, "-pp", "--plotPPs", help="Create per-ToA pulse profile plots")
    _bool_flag(parser, "-ll", "--plotLLs", help="Create per-ToA log-likelihood plots")
    parser.add_argument("-tf", "--toaFile", help="Output ToA file stem (default=ToAs)", type=str, default="ToAs")
    parser.add_argument("-mf", "--timFile", help="Output .tim stem (default=None)", type=str, default=None)
    _add_verbosity(parser)
    args = parser.parse_args(argv)
    _setup_logging(args, args.toaFile)

    from crimp_tpu.pipelines.measure_toas import measure_toas

    measure_toas(
        args.evtFile, args.timMod, args.tempModPP, args.toagtifile, args.enelow, args.enehigh,
        args.toaStart, args.toaEnd, args.phShiftRes, args.nbrBins, args.varyAmps,
        args.readvaryparam, args.brutemin, args.plotPPs, args.plotLLs, args.toaFile, args.timFile,
    )


def diagnosetoas(argv=None):
    parser = argparse.ArgumentParser(description="Script to create a diagnostic plot of ToAs")
    parser.add_argument("ToAs", help="Text file of phase shifts (from measuretoas)", type=str)
    parser.add_argument("-of", "--outputFile", help="Output HTML stem (default=ToADiagnosticsPlot)", type=str, default="ToADiagnosticsPlot")
    args = parser.parse_args(argv)

    from crimp_tpu.pipelines.diagnose import diagnose_toas

    diagnose_toas(args.ToAs, args.outputFile)


def addphasecolumn(argv=None):
    parser = argparse.ArgumentParser(description="Create and append event file with Phase column")
    parser.add_argument("evtFile", help="Name of (X-ray) fits event file", type=str)
    parser.add_argument("timMod", help="Timing model for phase folding (.par)", type=str)
    parser.add_argument("-ne", "--nonBaryEvtFile", help="Non-barycentered sibling file", type=str, default=None)
    args = parser.parse_args(argv)

    from crimp_tpu.io.events import EventFile

    EventFile(args.evtFile).add_phase_column(args.timMod, args.nonBaryEvtFile)


def ephemintegerrotation(argv=None):
    parser = argparse.ArgumentParser(
        description="Earliest MJD (with frequency and phase) giving an integer number of rotations"
    )
    parser.add_argument("tMJD", help="Time in MJD", type=float)
    parser.add_argument("timMod", help="Timing model (.par)", type=str)
    _bool_flag(parser, "-po", "--printOutput", help="Print output")
    args = parser.parse_args(argv)

    from crimp_tpu.ops.ephem import ephem_integer_rotation

    ephem_integer_rotation(args.tMJD, args.timMod, args.printOutput)


def phshifttotimfile(argv=None):
    parser = argparse.ArgumentParser(description="Convert a phase-shift text file into a .tim file")
    parser.add_argument("ToAs", help="Phase-shift .txt from measuretoas", type=str)
    parser.add_argument("timMod", help=".par timing model", type=str)
    parser.add_argument("-tf", "--timfile", help="Output .tim stem (default=residuals)", type=str, default="residuals")
    parser.add_argument("-tp", "--tempModPP", help="Template name recorded per ToA", type=str, default="ppTemplateMod")
    parser.add_argument("-in", "--inst", help="Instrument flag keyword (default=Xray)", type=str, default="Xray")
    _bool_flag(parser, "-ap", "--addpn", help="Add pulse numbering")
    _bool_flag(parser, "-cl", "--clobber", help="Override .tim file")
    args = parser.parse_args(argv)

    from crimp_tpu.pipelines.tim_tools import phshift_to_timfile

    phshift_to_timfile(args.ToAs, args.timMod, args.timfile, args.tempModPP, args.inst, args.addpn, args.clobber)


def fittoas(argv=None):
    parser = argparse.ArgumentParser(description="Script to fit ToAs to a timing model")
    parser.add_argument("timfile_path", help="path to .tim file", type=str)
    parser.add_argument("parfile", help="Initial timing .par file with fit flags", type=str)
    parser.add_argument("newparfile", help="New post-fit .par file", type=str)
    parser.add_argument("-ts", "--t_start", type=float, default=None, help="Start time for fit (MJD)")
    parser.add_argument("-te", "--t_end", type=float, default=None, help="End time for fit (MJD)")
    parser.add_argument("-tm", "--t_mjd", type=float, nargs="+", default=None, help="Phase-wrap MJDs (cumulative)")
    parser.add_argument("-md", "--mode", choices=["add", "subtract"], default="add", help="Wrap direction")
    parser.add_argument("-iy", "--init_yaml", type=str, help="YAML of initial guesses and/or bounds")
    _bool_flag(parser, "-mc", "--mcmc", help="Sample posteriors with the ensemble MCMC")
    parser.add_argument("-st", "--mcmc-steps", type=int, default=10000, help="MCMC steps (default=10000)")
    parser.add_argument("-bu", "--mcmc-burn", type=int, default=500, help="Burn-in discarded (default=500)")
    parser.add_argument("-wa", "--mcmc-walkers", type=int, default=32, help="Walkers (default=32)")
    parser.add_argument("-cp", "--corner_plot", type=str, default=None, help="Corner plot PDF stem")
    parser.add_argument("-ch", "--chain-npy", type=str, default=None, help="Save full chain .npy")
    parser.add_argument("-fl", "--flat-npy", type=str, default=None, help="Save flat chain .npy")
    parser.add_argument("-bf", "--best_fit", choices=["median", "map"], type=str, default="map")
    parser.add_argument("-rp", "--residual_plot", help="Pre/post-fit residual plot stem", type=str, default=None)
    args = parser.parse_args(argv)

    from crimp_tpu.pipelines.fit_toas import fit_toas

    fit_toas(
        args.timfile_path, args.parfile, args.newparfile,
        t_start=args.t_start, t_end=args.t_end, t_mjd=args.t_mjd, mode=args.mode,
        init_yaml=args.init_yaml, mcmc=args.mcmc, mcmc_steps=args.mcmc_steps,
        mcmc_burn=args.mcmc_burn, mcmc_walkers=args.mcmc_walkers,
        corner_plot_path=args.corner_plot, chain_npy=args.chain_npy, flat_npy=args.flat_npy,
        best_fit=args.best_fit, residual_plot=args.residual_plot,
    )


def localephemerides(argv=None):
    parser = argparse.ArgumentParser(description="Generate local [F0, F1] ephemerides in a moving-average fashion")
    parser.add_argument("timfile", help=".tim TOA file", type=str)
    parser.add_argument("parfile", help="A tempo2 .par file", type=str)
    parser.add_argument("-id", "--interval_days", help="Window length (days)", type=float, default=90.0)
    parser.add_argument("-jd", "--jump_days", help="Window shift (days)", type=float, default=15.0)
    parser.add_argument("-ts", "--t_start", help="Start from (MJD)", type=float, default=None)
    parser.add_argument("-te", "--t_end", help="Stop at (MJD)", type=float, default=None)
    parser.add_argument("-mi", "--min_interval", help="Minimum ToA span per window (days)", type=float, default=45)
    _bool_flag(parser, "-dp", "--debug_with_plots", help="Per-window residual + corner plots")
    parser.add_argument("-of", "--outputfile", help="Output table stem (default=local_ephemerides)", type=str, default="local_ephemerides")
    parser.add_argument("-ep", "--ephem_plot", help="Ephemerides plot stem (default=None)", type=str, default=None)
    _bool_flag(parser, "-cl", "--clobber", help="Override output table")
    _add_verbosity(parser)
    args = parser.parse_args(argv)
    _setup_logging(args, args.outputfile if args.outputfile else "local_ephemerides")

    from crimp_tpu.pipelines.local_ephem import generate_local_ephemerides

    generate_local_ephemerides(
        args.timfile, args.parfile, args.interval_days, args.jump_days,
        args.t_start, args.t_end, args.min_interval, args.debug_with_plots,
        args.outputfile, args.ephem_plot, args.clobber,
    )


def pulseprofile_plots(argv=None):
    parser = argparse.ArgumentParser(description="YAML-driven pulse-profile visualization suite")
    parser.add_argument("eventfile", help="Event file", type=str)
    parser.add_argument("parfile", help="A tempo2 .par file", type=str)
    parser.add_argument("yamlconfig", help="YAML listing plots to generate", type=str)
    parser.add_argument("-el", "--enelow", help="Low energy filter, default=0.3", type=float, default=0.3)
    parser.add_argument("-eh", "--enehigh", help="High energy filter, default=10", type=float, default=10)
    parser.add_argument("-ts", "--tstart", help="Events from tstart (MJD)", type=float, default=40000)
    parser.add_argument("-te", "--tend", help="Events before tend (MJD)", type=float, default=70000)
    parser.add_argument("-op", "--outputplot", help="Output plot stem", type=str, default=None)
    args = parser.parse_args(argv)

    from crimp_tpu.pipelines.plots import prep_for_plotting, run_plots_from_yaml

    df, _ = prep_for_plotting(args.eventfile, args.parfile, args.enelow, args.enehigh, args.tstart, args.tend)
    run_plots_from_yaml(args.yamlconfig, df)


def localephemerides_plot(argv=None):
    parser = argparse.ArgumentParser(description="Plot local ephemerides")
    parser.add_argument("localephem", help=".txt local-ephemerides table", type=str)
    parser.add_argument("-ts", "--t_start", help="Start from (MJD)", type=float, default=None)
    parser.add_argument("-te", "--t_end", help="Stop at (MJD)", type=float, default=None)
    parser.add_argument("-gl", "--glitches", help="Glitch MJD markers", type=float, nargs="+", default=None)
    parser.add_argument("-ep", "--ephem_plot", help="Output plot stem (default=None)", type=str, default=None)
    args = parser.parse_args(argv)

    from crimp_tpu.pipelines.plot_local_ephem import plot_local_ephemerides, read_local_ephemerides

    table = read_local_ephemerides(args.localephem, args.t_start, args.t_end)
    plot_local_ephemerides(table, glitches=args.glitches, plotname=args.ephem_plot)


def mergeoverlappingtims(argv=None):
    parser = argparse.ArgumentParser(
        description="Merge .tim files with pulse numbers (-pn) using overlapping TOAs as anchors."
    )
    parser.add_argument("timfiles", nargs="+", help=".tim files, or .txt list files of .tim names", type=str)
    parser.add_argument("-ot", "--outputtim", help="Output prefix <outputtim>.tim (default=all_merged)", type=str, default="all_merged")
    _bool_flag(parser, "-cl", "--clobber", help="Override output .tim file")
    args = parser.parse_args(argv)

    from crimp_tpu.pipelines.merge_tim import merge_tim_files, write_merged_tim

    merged = merge_tim_files(args.timfiles)
    write_merged_tim(merged, args.outputtim, clobber=args.clobber)
