"""Pallas TPU kernel for the uniform-grid Z^2 scan — the native-layer spike.

The XLA fast path (ops/search.py::harmonic_sums_uniform) already removes
most f64 work via the per-tile row decomposition; the roofline
(docs/performance.md) says the remaining cost is VPU transcendentals and
scan sequencing. This kernel owns both knobs explicitly:

- the (trial_tile x event_chunk) phase tile lives in VMEM for its whole
  lifetime (Pallas grid over (tile, event-chunk), output block revisited
  along the event axis and accumulated in place);
- sin/cos come from the fixed polynomial pair (ops/fasttrig.py) on the
  mod-1-reduced argument — no libm range reduction;
- harmonics use the same Chebyshev recurrence as the XLA kernels.

Same decomposition as the XLA fast path: phase(j0 + j_lo, t) =
frac(f_tile*t) + frac(fd*t^2/2) + j_lo*frac(df*t), with the f64 parts
(one row per trial tile + one per fdot — shared across the other axis)
precomputed OUTSIDE the kernel in chunks of ``tile_chunk`` tiles so HBM
holds (tile_chunk x n_events) f32 rows, never the full grid.

Status: correctness is pinned against the XLA kernels in
tests/test_search.py (interpret mode on CPU); the on-chip A/B against the
XLA fast path runs in the opportunistic TPU tier (tests/test_tpu_tier.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from crimp_tpu.ops import fasttrig
from crimp_tpu.ops.search import chebyshev_weighted_sums

TRIAL_TILE = 256
EVENT_CHUNK = 1024
TILE_CHUNK = 32  # trial tiles whose f64 base rows are materialized at once

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams (~0.4.34);
# resolve whichever this build ships so the kernel compiles on both sides
# of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def pallas_minimal_probe() -> float:
    """Compile and run the smallest useful Mosaic kernel (y = x + 1 on one
    (8, 128) f32 block) on the default backend; returns sum(y).

    Exists to CLASSIFY Pallas failures, not to compute: if this kernel
    cannot compile, the failure is the Mosaic toolchain/relay (r3/r4: the
    axon remote-compile helper returned HTTP 500 before any kernel code
    reached the chip), not the Z^2 kernel below. The tier A/B and
    scripts/probe_pallas_min.py use it to decide skip-vs-fail.
    """

    def kernel(x_ref, y_ref):
        y_ref[...] = x_ref[...] + 1.0

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    y = pl.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
    )(x)
    return float(jnp.sum(y))


def _make_kernel(nharm: int, trial_tile: int):
    def kernel(base_ref, b_ref, w_ref, c_ref, s_ref):
        # Inputs are (rows, 1, events) with (1, 1, event_chunk) blocks: the
        # TPU lowering constrains only the LAST TWO block dims (sublane %
        # 8 / lane % 128, or equal to the array dim) — the singleton middle
        # dim satisfies "equal", and row selection rides the untiled
        # leading dim, so no dynamic in-kernel indexing is needed.
        e = pl.program_id(1)
        cb = base_ref[0, 0, :]  # (EV,) f32, mod-1 reduced
        b = b_ref[0, 0, :]
        w = w_ref[0, 0, :]
        # Mosaic's iota is integer-only; cast after
        j_lo = jax.lax.broadcasted_iota(jnp.int32, (trial_tile, 1), 0).astype(jnp.float32)
        phase = cb[None, :] + j_lo * b[None, :]  # (T, EV)
        frac = fasttrig.centered_frac(phase)
        sin1, cos1 = fasttrig.sincos_cycles(frac)
        c_sums, s_sums = chebyshev_weighted_sums(cos1, sin1, w[None, :], nharm)  # (nharm, T)

        @pl.when(e == 0)
        def _():
            c_ref[0] = c_sums
            s_ref[0] = s_sums

        @pl.when(e > 0)
        def _():
            c_ref[0] = c_ref[0] + c_sums
            s_ref[0] = s_ref[0] + s_sums

    return kernel


@partial(
    jax.jit,
    static_argnames=("nharm", "trial_tile", "event_chunk", "interpret"),
)
def _tile_chunk_sums(
    base, b, w, nharm: int, trial_tile: int, event_chunk: int, interpret: bool
):
    """(c, s) sums (k, nharm, trial_tile) for one chunk of k trial tiles."""
    k, n_pad = base.shape
    grid = (k, n_pad // event_chunk)
    kernel = _make_kernel(nharm, trial_tile)
    out_shape = jax.ShapeDtypeStruct((k, nharm, trial_tile), jnp.float32)
    c, s = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, event_chunk), lambda i, e: (i, 0, e)),
            pl.BlockSpec((1, 1, event_chunk), lambda i, e: (0, 0, e)),
            pl.BlockSpec((1, 1, event_chunk), lambda i, e: (0, 0, e)),
        ],
        out_specs=(
            pl.BlockSpec((1, nharm, trial_tile), lambda i, e: (i, 0, 0)),
            pl.BlockSpec((1, nharm, trial_tile), lambda i, e: (i, 0, 0)),
        ),
        out_shape=(out_shape, out_shape),
        # trial tiles are independent (parallel); the event axis revisits
        # the same output block (sequential accumulation -> arbitrary)
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(base[:, None, :], b[:, None, :], w[:, None, :])
    return c, s


def z2_power_grid_pallas(
    times,
    f0: float,
    df: float,
    n_freq: int,
    nharm: int = 2,
    trial_tile: int = TRIAL_TILE,
    event_chunk: int = EVENT_CHUNK,
    tile_chunk: int = TILE_CHUNK,
    fdot: float = 0.0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Z^2_n over the uniform grid f0 + j*df via the Pallas tile kernel.

    Drop-in comparable to ops.search.z2_power_grid (same statistic, f32
    accumulation); ``interpret=True`` runs the kernel in the Pallas
    interpreter for CPU correctness tests. A nonzero ``fdot`` (signed
    Hz/s) becomes its own f64-reduced, f32-cast row added to the per-tile
    frequency row in f32 (the shared-row decomposition; frequency-
    independent), so the kernel itself is untouched.
    """
    return z2_power_2d_grid_pallas(
        times, f0, df, n_freq, [fdot], nharm, trial_tile, event_chunk,
        tile_chunk, interpret=interpret,
    )[0]


def z2_power_2d_grid_pallas(
    times,
    f0: float,
    df: float,
    n_freq: int,
    fdots,
    nharm: int = 2,
    trial_tile: int = TRIAL_TILE,
    event_chunk: int = EVENT_CHUNK,
    tile_chunk: int = TILE_CHUNK,
    interpret: bool = False,
) -> jnp.ndarray:
    """Z^2_n over the (fdot x uniform-frequency) grid -> (n_fdot, n_freq).

    The Pallas analog of ops.search.z2_power_2d_grid — the BASELINE
    config-3 shape. ``fdots`` are SIGNED Hz/s (callers on the reference
    CLI convention pass -10**log10grid). The event array, its padding, the
    weight/increment rows, and each chunk's frequency product are computed
    ONCE and shared across the fdot axis — only the (frequency-independent)
    quadratic term differs per fdot.
    """
    fd_arr = np.asarray(fdots, dtype=np.float64).reshape(-1)
    t64 = jnp.asarray(times, dtype=jnp.float64)
    n = int(t64.shape[0])
    n_pad = -(-n // event_chunk) * event_chunk
    t_pad = jnp.pad(t64, (0, n_pad - n))
    w = jnp.pad(jnp.ones(n, jnp.float32), (0, n_pad - n))[None, :]
    b64 = df * t_pad
    b = fasttrig.centered_frac(b64).astype(jnp.float32)[None, :]
    # Shared-row decomposition (same as search.harmonic_sums_uniform_2d):
    # the quadratic term is frequency-independent and the frequency row is
    # fdot-independent, so each is reduced in f64 ONCE — per fdot and per
    # tile chunk respectively — and combined in f32 (~2 ulp against the
    # fast path's 1.5e-5-cycle budget; the kernel re-reduces before trig).
    quad_rows = [
        fasttrig.centered_frac((0.5 * fd) * t_pad**2).astype(jnp.float32)
        for fd in fd_arr
    ]

    n_tiles = -(-n_freq // trial_tile)
    c_parts = [[] for _ in fd_arr]
    s_parts = [[] for _ in fd_arr]
    for chunk_start in range(0, n_tiles, tile_chunk):
        k = min(tile_chunk, n_tiles - chunk_start)
        f_tiles = f0 + (chunk_start + np.arange(k)) * (trial_tile * df)
        freq_rows = fasttrig.centered_frac(
            jnp.asarray(f_tiles)[:, None] * t_pad[None, :]).astype(jnp.float32)
        for i, qrow in enumerate(quad_rows):
            base = freq_rows + qrow[None, :]  # pure f32
            c, s = _tile_chunk_sums(
                base, b, w, nharm, trial_tile, event_chunk, interpret
            )
            c_parts[i].append(c)
            s_parts[i].append(s)

    def flat(parts):
        all_ = jnp.concatenate(parts).astype(jnp.float64)  # (n_tiles, nharm, T)
        return jnp.moveaxis(all_, 1, 0).reshape(nharm, -1)[:, :n_freq]

    return jnp.stack([
        jnp.sum((flat(c_parts[i]) ** 2 + flat(s_parts[i]) ** 2) * (2.0 / n), axis=0)
        for i in range(len(fd_arr))
    ])
