from crimp_tpu.ops import fold, anchored, ephem, binprofile, search

__all__ = ["fold", "anchored", "ephem", "binprofile", "search"]
