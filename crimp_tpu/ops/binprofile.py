"""Binned pulse profiles from folded phases.

Parity with the reference binner (binphases.py:9-39): phases may live on
[0,1) (Fourier convention) or [0,2pi) (von Mises / Cauchy convention); bins
are uniform with sqrt(N) count errors.
"""

from __future__ import annotations

import numpy as np


def bin_phases(phases: np.ndarray, nbrBins: int = 15) -> dict:
    """Histogram folded phases into a counts profile.

    Returns {'ppBins' (bin centers), 'ppBinsRange' (half-width),
    'ctsBins', 'ctsBinsErr'}.
    """
    phases = np.asarray(phases)
    if ((phases >= 0) & (phases <= 1)).all():
        upper = 1.0
    elif ((phases >= 0) & (phases <= 2 * np.pi)).all():
        upper = 2 * np.pi
    else:
        raise ValueError("phase array is not cycle folded to [0,1) or [0,2*pi)")

    half_bin = (upper / nbrBins) / 2
    centers = np.linspace(0, upper, nbrBins, endpoint=False) + half_bin
    counts = None
    if phases.size >= 1_000_000:
        # large arrays: the C++ single-pass histogram (native/crimpio.cpp)
        # avoids numpy's edge binary-search; falls through when unavailable
        from crimp_tpu.io import native

        counts = native.phase_histogram(phases, upper, nbrBins)
    if counts is None:
        edges = np.linspace(0, upper, nbrBins + 1, endpoint=True)
        counts = np.histogram(phases, bins=edges)[0]
    return {
        "ppBins": centers,
        "ppBinsRange": half_bin,
        "ctsBins": counts,
        "ctsBinsErr": np.sqrt(counts),
    }


# Reference-named alias (binphases.py:9).
binphases = bin_phases
