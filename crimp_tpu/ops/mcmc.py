"""Affine-invariant ensemble MCMC as a pure-JAX kernel.

Replaces the reference's emcee dependency (fit_toas.py:140-202,
get_local_ephem.py:195-198) with the same algorithm — Goodman & Weare
(2010) stretch moves over a walker ensemble — implemented as a
``lax.scan`` over steps with the log-probability vmapped over walkers, so
an entire 10000-step x 32-walker run is one compiled device program
instead of 320k Python-loop model evaluations.

Ensemble halves update alternately (the standard parallel-stretch scheme
emcee also uses), keeping detailed balance while staying fully batched.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("log_prob_fn", "steps"))
def ensemble_sample(
    log_prob_fn,
    p0: jax.Array,  # (walkers, ndim) initial ensemble
    steps: int,
    key: jax.Array,
    stretch_a: float = 2.0,
):
    """Run the stretch-move ensemble; returns (chain, log_probs).

    chain: (steps, walkers, ndim); log_probs: (steps, walkers).
    """
    return _ensemble_core(log_prob_fn, p0, steps, key, stretch_a)


@partial(jax.jit, static_argnames=("log_prob_fn", "steps"))
def ensemble_sample_batch(
    log_prob_fn,
    p0: jax.Array,  # (B, walkers, ndim) per-problem initial ensembles
    data,  # pytree with leading axis B: per-problem observations
    steps: int,
    key: jax.Array,
    stretch_a: float = 2.0,
):
    """Independent ensembles vmapped over a batch of problems.

    ``log_prob_fn(theta, data_b)`` scores one walker of problem b. This is
    the vmap-over-windows device program of SURVEY §3.5 (the reference runs
    one emcee per sliding window, get_local_ephem.py:104-239): every
    window/segment samples in parallel in ONE compiled call. Returns
    (chain (B, steps, walkers, ndim), log_probs (B, steps, walkers)).
    """
    n_batch = p0.shape[0]
    keys = jax.random.split(key, n_batch)

    def one(p0_b, data_b, key_b):
        return _ensemble_core(
            lambda theta: log_prob_fn(theta, data_b), p0_b, steps, key_b, stretch_a
        )

    return jax.vmap(one)(p0, data, keys)


def _ensemble_core(log_prob_fn, p0, steps: int, key, stretch_a: float):
    n_walkers, ndim = p0.shape
    half = n_walkers // 2
    lp0 = jax.vmap(log_prob_fn)(p0)

    def half_update(key, movers, movers_lp, others):
        k_part, k_z, k_accept = jax.random.split(key, 3)
        partners = others[
            jax.random.randint(k_part, (movers.shape[0],), 0, others.shape[0])
        ]
        u = jax.random.uniform(k_z, (movers.shape[0],))
        z = ((stretch_a - 1.0) * u + 1.0) ** 2 / stretch_a
        proposal = partners + z[:, None] * (movers - partners)
        prop_lp = jax.vmap(log_prob_fn)(proposal)
        log_ratio = (ndim - 1) * jnp.log(z) + prop_lp - movers_lp
        accept = jnp.log(jax.random.uniform(k_accept, (movers.shape[0],))) < log_ratio
        new = jnp.where(accept[:, None], proposal, movers)
        new_lp = jnp.where(accept, prop_lp, movers_lp)
        return new, new_lp

    def step(carry, key):
        walkers, lp = carry
        k1, k2 = jax.random.split(key)
        first, second = walkers[:half], walkers[half:]
        lp1, lp2 = lp[:half], lp[half:]
        first, lp1 = half_update(k1, first, lp1, second)
        second, lp2 = half_update(k2, second, lp2, first)
        walkers = jnp.concatenate([first, second])
        lp = jnp.concatenate([lp1, lp2])
        return (walkers, lp), (walkers, lp)

    keys = jax.random.split(key, steps)
    _, (chain, lps) = jax.lax.scan(step, (p0, lp0), keys)
    return chain, lps


def summarize_chain(chain: np.ndarray, log_probs: np.ndarray, keys: list[str], burn: int = 0):
    """Posterior summaries matching the reference's reporting
    (fit_toas.py:192-202): median, 16/84-percentile deviations, MAP."""
    flat = chain[burn:].reshape(-1, chain.shape[-1])
    flat_lp = log_probs[burn:].reshape(-1)
    i_map = int(np.argmax(flat_lp))
    summaries = {}
    for i, name in enumerate(keys):
        q16, q50, q84 = np.percentile(flat[:, i], [16, 50, 84])
        summaries[name] = {
            "median": float(q50),
            "minus": float(q50 - q16),
            "plus": float(q84 - q50),
            "map": float(flat[i_map, i]),
        }
    return flat, flat_lp, summaries
