"""Affine-invariant ensemble MCMC as a pure-JAX kernel.

Replaces the reference's emcee dependency (fit_toas.py:140-202,
get_local_ephem.py:195-198) with the same algorithm — Goodman & Weare
(2010) stretch moves over a walker ensemble — implemented as a
``lax.scan`` over steps with the log-probability vmapped over walkers, so
an entire 10000-step x 32-walker run is one compiled device program
instead of 320k Python-loop model evaluations.

Ensemble halves update alternately (the standard parallel-stretch scheme
emcee also uses), keeping detailed balance while staying fully batched.

Two things make this file the survey-scale posterior engine:

- **Compile stability.** The jitted cores take the observation set as a
  traced pytree argument (``data``) and the log-probability as a STATIC
  function of ``(theta, data)``. A caller that passes a stable
  module-level function — ``delta_logprob`` below, or the cached exact
  likelihood in pipelines/fit_toas.py — hits the same compiled executable
  on every run at the same shapes. (The old API closed the data over a
  fresh ``log_prob_fn`` per run, so ``static_argnames`` retraced every
  single ``run_mcmc`` call.)

- **The delta-basis likelihood.** ``delta_logprob`` scores a proposal as
  ``resid = y - center(basis @ theta)`` — within the linear regime of the
  delta parameterization (ops/deltafold.py) a proposal's model residuals
  are exactly one ``B @ dp`` product, so a vmapped half-ensemble update is
  a single ``(walkers x ndim) @ (ndim x nToA)`` matmul instead of a full
  Taylor+glitch+wave phase evaluation per walker. The masked form also
  serves padded multi-problem batches: padding rows carry ``mask == 0``
  and contribute exactly ``+0.0`` to the log-probability.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def delta_logprob(theta, data):
    """Linear-regime Gaussian log-probability: ``mu = basis @ theta``.

    ``data`` is a pytree dict with keys ``basis`` (N, ndim), ``y`` (N,),
    ``err`` (N,), ``mask`` (N,), ``lo``/``hi`` (ndim,). The model is
    mean-subtracted over the valid (mask == 1) rows and compared against
    the (already centered) data vector; rows with ``mask == 0`` are inert
    padding and contribute exactly +0.0 to the sum. Box priors gate the
    result to -inf outside [lo, hi].

    This one module-level function is the whole delta-basis MCMC
    likelihood: single-source fits (pipelines/fit_toas.py, mask all-ones),
    sliding-window batches (pipelines/local_ephem.py), and the stacked
    multi-source mode (ops/multisource.py) all pass it to the samplers
    below with their own ``data`` pytrees, so they share one compiled
    ensemble core per shape family.
    """
    basis, y, err, mask, lo, hi = (
        data["basis"], data["y"], data["err"], data["mask"], data["lo"],
        data["hi"],
    )
    in_box = jnp.all((theta > lo) & (theta < hi))
    mu = basis @ theta
    mu = mu - jnp.sum(mu * mask) / jnp.sum(mask)
    resid = (y - mu) / err
    nll = 0.5 * jnp.sum(mask * (resid**2 + jnp.log(2 * jnp.pi * err**2)))
    return jnp.where(in_box, -nll, -jnp.inf)


def ensemble_sample(
    log_prob_fn,
    p0: jax.Array,  # (walkers, ndim) initial ensemble
    steps: int,
    key: jax.Array,
    stretch_a: float = 2.0,
    data=None,
):
    """Run the stretch-move ensemble; returns (chain, log_probs).

    chain: (steps, walkers, ndim); log_probs: (steps, walkers).

    With ``data`` (a pytree of observations) the log-probability is called
    as ``log_prob_fn(theta, data)`` and the compiled core is reused across
    calls whenever ``log_prob_fn`` is a stable (module-level or cached)
    function — the data arrays are traced arguments, not baked-in
    constants. Without ``data`` the legacy single-argument closure form
    still works, at the cost of a retrace per distinct closure.
    """
    return _ensemble_core(log_prob_fn, p0, data, steps, key, stretch_a)


def ensemble_sample_batch(
    log_prob_fn,
    p0: jax.Array,  # (B, walkers, ndim) per-problem initial ensembles
    data,  # pytree with leading axis B: per-problem observations
    steps: int,
    key: jax.Array = None,
    stretch_a: float = 2.0,
    keys: jax.Array = None,
):
    """Independent ensembles vmapped over a batch of problems.

    ``log_prob_fn(theta, data_b)`` scores one walker of problem b. This is
    the vmap-over-windows device program of SURVEY §3.5 (the reference runs
    one emcee per sliding window, get_local_ephem.py:104-239) and the
    source axis of the multisource posterior mode (ops/multisource.py):
    every window/segment/source samples in parallel in ONE compiled call.

    Pass either ``key`` (split into one subkey per problem, the classic
    form) or pre-split per-problem ``keys`` (B, 2) — the latter lets a
    caller chunk a large batch over several dispatches while keeping every
    problem's random stream identical to the unchunked run.

    Returns (chain (B, steps, walkers, ndim), log_probs (B, steps, walkers)).
    """
    if keys is None:
        keys = jax.random.split(key, p0.shape[0])
    return _ensemble_batch_core(log_prob_fn, p0, data, steps, keys, stretch_a)


@partial(jax.jit, static_argnames=("log_prob_fn", "steps"))
def _ensemble_core(log_prob_fn, p0, data, steps: int, key, stretch_a):
    return _ensemble_scan(log_prob_fn, p0, data, steps, key, stretch_a)


@partial(jax.jit, static_argnames=("log_prob_fn", "steps"))
def _ensemble_batch_core(log_prob_fn, p0, data, steps: int, keys, stretch_a):
    def one(p0_b, data_b, key_b):
        return _ensemble_scan(log_prob_fn, p0_b, data_b, steps, key_b, stretch_a)

    return jax.vmap(one, in_axes=(0, 0, 0))(p0, data, keys)


def _ensemble_scan(log_prob_fn, p0, data, steps: int, key, stretch_a):
    # ``data is None`` is pytree STRUCTURE, so the branch is resolved at
    # trace time: the legacy closure form and the threaded-data form each
    # get their own cache entry, never a runtime conditional.
    if data is None:
        lp_fn = log_prob_fn
    else:
        def lp_fn(theta):
            return log_prob_fn(theta, data)

    n_walkers, ndim = p0.shape
    half = n_walkers // 2
    lp0 = jax.vmap(lp_fn)(p0)

    def half_update(key, movers, movers_lp, others):
        k_part, k_z, k_accept = jax.random.split(key, 3)
        partners = others[
            jax.random.randint(k_part, (movers.shape[0],), 0, others.shape[0])
        ]
        u = jax.random.uniform(k_z, (movers.shape[0],))
        z = ((stretch_a - 1.0) * u + 1.0) ** 2 / stretch_a
        proposal = partners + z[:, None] * (movers - partners)
        prop_lp = jax.vmap(lp_fn)(proposal)
        log_ratio = (ndim - 1) * jnp.log(z) + prop_lp - movers_lp
        accept = jnp.log(jax.random.uniform(k_accept, (movers.shape[0],))) < log_ratio
        new = jnp.where(accept[:, None], proposal, movers)
        new_lp = jnp.where(accept, prop_lp, movers_lp)
        return new, new_lp

    def step(carry, key):
        walkers, lp = carry
        k1, k2 = jax.random.split(key)
        first, second = walkers[:half], walkers[half:]
        lp1, lp2 = lp[:half], lp[half:]
        first, lp1 = half_update(k1, first, lp1, second)
        second, lp2 = half_update(k2, second, lp2, first)
        walkers = jnp.concatenate([first, second])
        lp = jnp.concatenate([lp1, lp2])
        return (walkers, lp), (walkers, lp)

    keys = jax.random.split(key, steps)
    _, (chain, lps) = jax.lax.scan(step, (p0, lp0), keys)
    return chain, lps


def summarize_chain(chain: np.ndarray, log_probs: np.ndarray, keys: list[str], burn: int = 0):
    """Posterior summaries matching the reference's reporting
    (fit_toas.py:192-202): median, 16/84-percentile deviations, MAP."""
    n_steps = chain.shape[0]
    if burn >= n_steps:
        raise ValueError(
            f"burn ({burn}) must be smaller than the number of recorded "
            f"steps ({n_steps}); nothing would be left to summarize"
        )
    flat = chain[burn:].reshape(-1, chain.shape[-1])
    flat_lp = log_probs[burn:].reshape(-1)
    i_map = int(np.argmax(flat_lp))
    summaries = {}
    for i, name in enumerate(keys):
        q16, q50, q84 = np.percentile(flat[:, i], [16, 50, 84])
        summaries[name] = {
            "median": float(q50),
            "minus": float(q50 - q16),
            "plus": float(q84 - q50),
            "map": float(flat[i_map, i]),
        }
    return flat, flat_lp, summaries


def effective_sample_size(chain: np.ndarray, c: float = 5.0) -> np.ndarray:
    """Autocorrelation-time effective sample size (host-side numpy).

    ``chain`` is (steps,), (steps, walkers) or (steps, walkers, ndim).
    Per dimension, the normalized autocorrelation function is averaged
    across walkers (each walker demeaned by the ensemble mean, the
    standard emcee ``integrated_time`` construction), the integrated
    autocorrelation time is ``tau = 1 + 2 * sum_{t>=1} rho(t)`` with
    Sokal's automatic windowing (smallest M with M >= c * tau(M)), and
    ESS = total samples / tau. Returns a scalar for 1-D/2-D input, an
    (ndim,) vector for 3-D input. For an AR(1) chain with coefficient
    rho the exact answer is tau = (1 + rho) / (1 - rho).
    """
    x = np.asarray(chain, dtype=np.float64)
    if x.ndim == 1:
        x = x[:, None]
    if x.ndim == 2:
        return float(_ess_one(x, c))
    if x.ndim != 3:
        raise ValueError(f"chain must be 1-D, 2-D or 3-D, got shape {x.shape}")
    return np.array([_ess_one(x[:, :, d], c) for d in range(x.shape[2])])


def _ess_one(x: np.ndarray, c: float) -> float:
    """ESS for one (steps, walkers) scalar chain."""
    n_steps, n_walkers = x.shape
    total = n_steps * n_walkers
    if n_steps < 2:
        return float(total)
    y = x - x.mean(axis=0, keepdims=True)
    # FFT autocovariance per walker, averaged across the ensemble
    n_fft = 1
    while n_fft < 2 * n_steps:
        n_fft *= 2
    f = np.fft.rfft(y, n=n_fft, axis=0)
    acov = np.fft.irfft(f * np.conjugate(f), n=n_fft, axis=0)[:n_steps].real
    acov = acov.mean(axis=1) / n_steps
    if acov[0] <= 0.0:
        return float(total)  # constant chain: every sample identical
    rho = acov / acov[0]
    # Sokal window: cumulative tau, stop at the smallest M >= c * tau(M)
    taus = 2.0 * np.cumsum(rho) - 1.0
    window = np.arange(len(taus))
    hit = np.nonzero(window >= c * taus)[0]
    m = int(hit[0]) if hit.size else len(taus) - 1
    tau = max(float(taus[m]), 1.0)
    return float(total / tau)
