"""Block-size autotuner for the blockwise Z^2/H kernels.

The (event_block, trial_block) tiling of the search kernels is a pure
throughput knob (the statistic is block-invariant — tests/test_search.py
pins that), but the optimum moves with backend, device generation, trig
path and problem size: the hand-set GRID defaults were swept on v5e
BEFORE poly trig landed (docs/performance.md). This module makes the
tuning automatic and persistent instead of a one-off script:

- ``tune()`` times a small candidate grid on the canonical A/B workload
  (crimp_tpu/utils/benchwork.py — the same problem the sweep script, the
  TPU tier and the recorded perf guards measure) and persists the winner
  in a fingerprinted on-disk cache;
- ``resolve_blocks()`` is the single resolution point the kernels call:
  explicit arguments and the ``CRIMP_TPU_GRID_BLOCKS`` env knob stay hard
  overrides, a cached winner is used when present, and the static
  module defaults remain the fallback (so a fresh machine behaves exactly
  as before until someone tunes).

Cache key schema (one JSON file, atomic tmp+rename writes)::

    <platform>|<device_kind>|<kernel>|poly<0/1>|ev<ceil log2 n_events>|tr<ceil log2 n_trials>

``kernel`` is the variant family: "grid" (uniform-grid fast path, also
used by the 2-D grid kernel — same inner tile structure), "grid_mxu"
(the factorized matmul variant — its block optimum is MXU-shaped, not
VPU-shaped, so it gets its own entries), "general" (arbitrary-frequency
blockwise kernel) or "multisource" (the survey batch engine — there the
pair means (padded per-source event width, source rows per dispatch)).
Problem sizes are bucketed to their ceil-log2 so a
7.9e5-event scan and an 8.1e5-event scan share a tuning, while 1e5 and
1e8 do not.

Env knobs:

- ``CRIMP_TPU_AUTOTUNE``: ``0/off`` = static defaults only (today's
  behavior); unset/``auto`` = use a cached winner when present, never
  time anything implicitly; ``1/on/eager`` = tune-and-persist on a cache
  miss (timing runs happen inside library calls — opt-in only).
- ``CRIMP_TPU_AUTOTUNE_CACHE``: cache file path (default
  ``$XDG_CACHE_HOME/crimp_tpu/autotune.json``).
- ``CRIMP_TPU_GRID_BLOCKS``: hard override for the grid kernels,
  unchanged semantics (malformed values raise).
- ``CRIMP_TPU_TOA_DENSE_WINDOW`` / ``CRIMP_TPU_MXU_BF16``: hard overrides
  for the ToA-engine knobs resolved by ``resolve_toafit()`` (dense
  error-scan window width; bf16 MXU profile sweeps). Malformed raises.
"""

from __future__ import annotations

import json
import logging
import pathlib
import time

from crimp_tpu import knobs, obs, resilience
from crimp_tpu.resilience import faultinject

logger = logging.getLogger(__name__)

CACHE_VERSION = 1

# The small default candidate grid tune() times: bracket both static
# defaults (2^15/512 grid, 2^16/256 general) so the winner can never be
# slower than what an untuned install would pick.
DEFAULT_CANDIDATES = (
    (1 << 14, 256),
    (1 << 14, 512),
    (1 << 15, 256),
    (1 << 15, 512),
    (1 << 15, 1024),
    (1 << 16, 256),
    (1 << 16, 512),
    (1 << 16, 1024),
    (1 << 17, 512),
)


# -- policy / key -----------------------------------------------------------


def autotune_mode() -> str:
    """'off' | 'auto' | 'eager' from CRIMP_TPU_AUTOTUNE (malformed raises)."""
    env = knobs.raw("CRIMP_TPU_AUTOTUNE").lower()
    if env in knobs.OFF_WORDS:
        return "off"
    if env in ("", "auto", "cache"):
        return "auto"
    if env in ("1", "on", "true", "eager"):
        return "eager"
    raise ValueError(
        f"CRIMP_TPU_AUTOTUNE={env!r} not recognized; expected 0/off, auto, "
        "or 1/on (eager tuning)"
    )


def cache_path() -> pathlib.Path:
    env = knobs.raw("CRIMP_TPU_AUTOTUNE_CACHE")
    if env:
        return pathlib.Path(env)
    return pathlib.Path(knobs.cache_home()) / "crimp_tpu" / "autotune.json"


def _bucket(n: int) -> int:
    """ceil(log2(n)) — problem sizes within a factor of 2 share a tuning."""
    return max(1, int(n) - 1).bit_length()


def device_fingerprint() -> tuple[str, str]:
    """(platform, device_kind) of the default device — initializes the
    backend, so only resolution paths that actually consult the cache call
    this (plain static-default resolution must stay import-safe)."""
    import jax

    dev = jax.devices()[0]
    return jax.default_backend(), getattr(dev, "device_kind", "unknown")


def cache_key(kernel: str, poly: bool, n_events: int, n_trials: int,
              platform: str | None = None, device_kind: str | None = None) -> str:
    if platform is None or device_kind is None:
        platform, device_kind = device_fingerprint()
    return "|".join([
        platform, device_kind, kernel, f"poly{int(bool(poly))}",
        f"ev{_bucket(n_events)}", f"tr{_bucket(n_trials)}",
    ])


# -- on-disk cache ----------------------------------------------------------


def _load_cache(path: pathlib.Path | None = None) -> dict:
    path = cache_path() if path is None else path
    try:
        faultinject.fire("tuner_cache")
        doc = json.loads(path.read_text())
    except OSError:
        return {}  # missing or unreadable: nothing to quarantine
    except (json.JSONDecodeError, ValueError, resilience.CacheCorruptError):
        # A torn or corrupt cache file gets quarantined (atomic rename to
        # *.corrupt) so the next tune rebuilds it, instead of being
        # silently reparsed — and refailed — on every resolution.
        resilience.quarantine_file(path, label="tuner_cache")
        return {}
    if not isinstance(doc, dict) or doc.get("version") != CACHE_VERSION:
        return {}
    entries = doc.get("entries")
    return entries if isinstance(entries, dict) else {}


def _store_entry(key: str, entry: dict, path: pathlib.Path | None = None) -> None:
    """Merge one winner into the cache file (atomic tmp+rename)."""
    path = cache_path() if path is None else path
    entries = _load_cache(path)
    entries[key] = entry
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps({"version": CACHE_VERSION, "entries": entries},
                              indent=2) + "\n")
    tmp.rename(path)


def cached_blocks(kernel: str, poly: bool, n_events: int, n_trials: int) -> tuple[int, int] | None:
    entry = _load_cache().get(cache_key(kernel, poly, n_events, n_trials))
    if not isinstance(entry, dict):
        return None
    eb, tb = entry.get("event_block"), entry.get("trial_block")
    if isinstance(eb, int) and isinstance(tb, int) and eb > 0 and tb > 0:
        return eb, tb
    return None


# -- resolution -------------------------------------------------------------

# The kernel families resolve_blocks() tunes, in one place so CLI sweeps
# (scripts/sweep_blocks.py derives its --kernel choices from this) can
# never silently miss a newly added family. "grid3d" is the jerk-search
# cube and "semicoherent" the segment-stacked cube engine; both share the
# grid static defaults and the CRIMP_TPU_GRID_BLOCKS override.
BLOCK_KERNELS = ("grid", "grid_mxu", "grid3d", "semicoherent", "general",
                 "multisource")


def static_defaults(kernel: str) -> tuple[int, int]:
    from crimp_tpu.ops import search

    if kernel == "general":
        return search.DEFAULT_EVENT_BLOCK, search.DEFAULT_TRIAL_BLOCK
    if kernel == "multisource":
        # (event_block, source_block): padded per-source event width and
        # source rows per dispatch for the survey batch engine
        from crimp_tpu.ops import multisource

        return (multisource.MULTISOURCE_EVENT_BLOCK,
                multisource.MULTISOURCE_SOURCE_BLOCK)
    return search.GRID_EVENT_BLOCK, search.GRID_TRIAL_BLOCK


def env_blocks_override(kernel: str) -> tuple[int, int] | None:
    """Live CRIMP_TPU_GRID_BLOCKS value (grid kernels only; keeps today's
    meaning — the knob has always targeted the uniform-grid fast path).
    Re-read per call so it beats the cache even when set after import."""
    if kernel in ("general", "multisource"):
        return None
    from crimp_tpu.ops import search

    if not knobs.is_set("CRIMP_TPU_GRID_BLOCKS"):
        return None
    return search._env_blocks(*static_defaults(kernel))



def _count_cache(hit: bool) -> None:
    """Autotune-cache effectiveness telemetry (no-op when obs is off)."""
    obs.counter_add("autotune_cache_hits" if hit else "autotune_cache_misses")

def resolve_blocks(kernel: str, n_events: int, n_trials: int,
                   poly: bool = False,
                   event_block: int | None = None,
                   trial_block: int | None = None) -> tuple[int, int]:
    """The single block-resolution point for the search kernels.

    Precedence: explicit arguments > CRIMP_TPU_GRID_BLOCKS (grid kernels)
    > cached tuner winner (unless CRIMP_TPU_AUTOTUNE=0) > eager tune on
    miss (only when CRIMP_TPU_AUTOTUNE=1) > static module defaults.
    Never runs timing unless eager mode is opted into.
    """
    if kernel not in BLOCK_KERNELS:
        raise ValueError(f"unknown kernel variant {kernel!r}")
    if event_block is not None and trial_block is not None:
        return int(event_block), int(trial_block)
    env = env_blocks_override(kernel)
    mode = autotune_mode()
    resolved = None
    if env is not None:
        resolved = env
    elif mode != "off":
        try:
            resolved = cached_blocks(kernel, poly, n_events, n_trials)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a search call
            logger.warning("autotune cache lookup failed (%s); using static "
                           "defaults", resilience.classify(exc).value,
                           exc_info=True)
            resolved = None
        _count_cache(resolved is not None)
        if resolved is None and mode == "eager":
            try:
                out = tune(kernel, n_events, n_trials, poly=poly)
                resolved = (out["event_block"], out["trial_block"])
            except Exception as exc:  # noqa: BLE001
                logger.warning("eager autotune failed (%s); using static "
                               "defaults", resilience.classify(exc).value,
                               exc_info=True)
                resolved = None
    if resolved is None:
        resolved = static_defaults(kernel)
    eb = int(event_block) if event_block is not None else int(resolved[0])
    tb = int(trial_block) if trial_block is not None else int(resolved[1])
    return eb, tb


# -- ToA-engine knobs (toafit) ----------------------------------------------
#
# The ToA fit exposes two throughput knobs that are numerically safe to
# tune: the dense error-scan first-window width (any value is bit-identical
# — it only moves work between the one-shot dense sweep and the fallback
# while_loop) and the bf16 MXU profile-sweep mode (accuracy-checked by
# scripts/tune_toafit.py and bench.py before it is ever cached as 1).
# Cache key: <platform>|<device_kind>|toafit|seg<log2 segments>|ev<log2 events>.
# Unlike the block sizes there is NO eager tuning path — the sweep lives in
# scripts/tune_toafit.py, which persists winners via store_toafit();
# resolve_toafit() only ever reads env + cache.

TOAFIT_DENSE_WINDOW_ENV = "CRIMP_TPU_TOA_DENSE_WINDOW"
MXU_BF16_ENV = "CRIMP_TPU_MXU_BF16"


def toafit_defaults() -> dict:
    from crimp_tpu.ops import toafit

    return {"err_dense_window": toafit.DENSE_WINDOW_DEFAULT, "mxu_bf16": 0}


def toafit_cache_key(n_segments: int, n_events: int,
                     platform: str | None = None,
                     device_kind: str | None = None) -> str:
    if platform is None or device_kind is None:
        platform, device_kind = device_fingerprint()
    return "|".join([
        platform, device_kind, "toafit",
        f"seg{_bucket(n_segments)}", f"ev{_bucket(n_events)}",
    ])


def cached_toafit(n_segments: int, n_events: int) -> dict | None:
    entry = _load_cache().get(toafit_cache_key(n_segments, n_events))
    if not isinstance(entry, dict):
        return None
    w, b = entry.get("err_dense_window"), entry.get("mxu_bf16")
    if isinstance(w, int) and w >= 0 and b in (0, 1):
        return {"err_dense_window": w, "mxu_bf16": b}
    return None


def store_toafit(n_segments: int, n_events: int, entry: dict,
                 path: pathlib.Path | None = None) -> None:
    """Persist a tuned ToA-knob winner (scripts/tune_toafit.py calls this)."""
    _store_entry(toafit_cache_key(n_segments, n_events), entry, path)


# parse helpers now live in the central knob registry; these aliases keep
# the resolver-layer call sites (and ops/resumable.py) on their old names
_env_nonneg_int = knobs.env_nonneg_int


def resolve_toafit(n_segments: int, n_events: int) -> dict:
    """Resolve {err_dense_window, mxu_bf16} for a ToA workload.

    Precedence per knob: env var (CRIMP_TPU_TOA_DENSE_WINDOW /
    CRIMP_TPU_MXU_BF16 — hard overrides, honored even with autotune off)
    > cached tuner winner (unless CRIMP_TPU_AUTOTUNE=0) > static defaults
    (DENSE_WINDOW_DEFAULT, bf16 off). Never times anything: the ToA sweep
    is explicit tooling (scripts/tune_toafit.py), not an implicit
    library-call side effect, because enabling bf16 requires an accuracy
    gate a blind timing loop cannot provide.
    """
    out = toafit_defaults()
    env_w = _env_nonneg_int(TOAFIT_DENSE_WINDOW_ENV)
    env_b = _env_nonneg_int(MXU_BF16_ENV, valid=(0, 1))
    if (env_w is None or env_b is None) and autotune_mode() != "off":
        try:
            cached = cached_toafit(n_segments, n_events)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a ToA fit
            logger.warning("toafit autotune cache lookup failed (%s); using "
                           "static defaults", resilience.classify(exc).value,
                           exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_w is not None:
        out["err_dense_window"] = env_w
    if env_b is not None:
        out["mxu_bf16"] = env_b
    return out


# -- factorized grid-kernel knob (grid_mxu) ---------------------------------
#
# CRIMP_TPU_GRID_MXU switches the uniform-grid kernels between the exact
# per-pair sincos path and the factorized angle-addition matmul path
# (ops/search.py harmonic_sums_uniform{,_2d}_mxu). Like bf16, the switch
# is accuracy-gated: only bench.py's deviation-checked A/B ever caches a
# 1, and the env var stays a hard override in both directions. The cache
# entry also carries the tuned reseed stride of the j_lo recurrence and
# whether the bf16 operand mode won alongside it. The cache key uses the
# kernel name "grid_mxu_enable" so the on/off entry can never collide
# with the "grid_mxu" BLOCK-size entries resolve_blocks() maintains.

GRID_MXU_ENV = "CRIMP_TPU_GRID_MXU"
GRID_MXU_RESEED_DEFAULT = 64


def grid_mxu_defaults() -> dict:
    return {"grid_mxu": 0, "reseed": GRID_MXU_RESEED_DEFAULT, "mxu_bf16": 0}


def grid_mxu_cache_key(poly: bool, n_events: int, n_trials: int,
                       platform: str | None = None,
                       device_kind: str | None = None) -> str:
    return cache_key("grid_mxu_enable", poly, n_events, n_trials,
                     platform=platform, device_kind=device_kind)


def cached_grid_mxu(poly: bool, n_events: int, n_trials: int) -> dict | None:
    entry = _load_cache().get(grid_mxu_cache_key(poly, n_events, n_trials))
    if not isinstance(entry, dict):
        return None
    m, r, b = entry.get("grid_mxu"), entry.get("reseed"), entry.get("mxu_bf16")
    if m in (0, 1) and isinstance(r, int) and r > 0 and b in (0, 1):
        return {"grid_mxu": m, "reseed": r, "mxu_bf16": b}
    return None


def store_grid_mxu(poly: bool, n_events: int, n_trials: int, entry: dict,
                   path: pathlib.Path | None = None) -> None:
    """Persist a gated grid_mxu A/B winner (bench.py calls this)."""
    _store_entry(grid_mxu_cache_key(poly, n_events, n_trials), entry, path)


def resolve_grid_mxu(n_events: int, n_trials: int, poly: bool = False) -> dict:
    """Resolve {grid_mxu, reseed, mxu_bf16} for a uniform-grid search.

    Precedence: CRIMP_TPU_GRID_MXU (hard override in both directions,
    honored even with autotune off; malformed raises) > cached A/B winner
    (unless CRIMP_TPU_AUTOTUNE=0) > default off. Never times anything —
    the A/B with its accuracy gate lives in bench.py, exactly like the
    bf16 knob's tune_toafit.py discipline. CRIMP_TPU_MXU_BF16 composes as
    the operand-precision override when the factorized path is on.
    """
    out = grid_mxu_defaults()
    env_m = _env_nonneg_int(GRID_MXU_ENV, valid=(0, 1))
    env_b = _env_nonneg_int(MXU_BF16_ENV, valid=(0, 1))
    if autotune_mode() != "off":
        try:
            cached = cached_grid_mxu(poly, n_events, n_trials)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a search call
            logger.warning("grid_mxu autotune cache lookup failed (%s); using "
                           "static defaults", resilience.classify(exc).value,
                           exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_m is not None:
        out["grid_mxu"] = env_m
    if env_b is not None:
        out["mxu_bf16"] = env_b
    return out


def grid3d_mxu_cache_key(poly: bool, n_events: int, n_trials: int,
                         platform: str | None = None,
                         device_kind: str | None = None) -> str:
    """Cache key for the 3-D cube's factorized-path winner. The kernel
    name "grid3d_mxu_enable" keeps it collision-free against both the
    "grid3d" block entries and the 2-D "grid_mxu_enable" entries (the 3-D
    kernel's win threshold is measured separately, by bench_jerk)."""
    return cache_key("grid3d_mxu_enable", poly, n_events, n_trials,
                     platform=platform, device_kind=device_kind)


def cached_grid3d_mxu(poly: bool, n_events: int, n_trials: int) -> dict | None:
    entry = _load_cache().get(grid3d_mxu_cache_key(poly, n_events, n_trials))
    if not isinstance(entry, dict):
        return None
    m, r, b = entry.get("grid_mxu"), entry.get("reseed"), entry.get("mxu_bf16")
    if m in (0, 1) and isinstance(r, int) and r > 0 and b in (0, 1):
        return {"grid_mxu": m, "reseed": r, "mxu_bf16": b}
    return None


def store_grid3d_mxu(poly: bool, n_events: int, n_trials: int, entry: dict,
                     path: pathlib.Path | None = None) -> None:
    """Persist a gated grid3d A/B winner (bench.py bench_jerk calls this)."""
    _store_entry(grid3d_mxu_cache_key(poly, n_events, n_trials), entry, path)


def resolve_grid3d_mxu(n_events: int, n_trials: int,
                       poly: bool = False) -> dict:
    """Resolve {grid_mxu, reseed, mxu_bf16} for the 3-D search cube.

    Same precedence as resolve_grid_mxu — CRIMP_TPU_GRID_MXU is the ONE
    shared hard override for every factorized grid kernel (no separate
    3-D env knob) > cached bench_jerk A/B winner > default off; only the
    accuracy-gated bench ever caches a 1.
    """
    out = grid_mxu_defaults()
    env_m = _env_nonneg_int(GRID_MXU_ENV, valid=(0, 1))
    env_b = _env_nonneg_int(MXU_BF16_ENV, valid=(0, 1))
    if autotune_mode() != "off":
        try:
            cached = cached_grid3d_mxu(poly, n_events, n_trials)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a search call
            logger.warning("grid3d_mxu autotune cache lookup failed (%s); "
                           "using static defaults",
                           resilience.classify(exc).value, exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_m is not None:
        out["grid_mxu"] = env_m
    if env_b is not None:
        out["mxu_bf16"] = env_b
    return out


# -- delta-fold knob --------------------------------------------------------
#
# CRIMP_TPU_DELTA_FOLD switches anchored.fold_segments between the exact
# longdouble-anchored fold and the incremental delta-fold engine
# (ops/deltafold.py: cached fold products refolded as `phases + B @ dp`).
# Like grid_mxu, the switch is accuracy-gated: only bench.py's
# deviation-checked bench_delta_fold A/B ever caches a 1, and the env var
# stays a hard override in both directions. The cache entry also carries
# the precision budget (cycles) the guard enforces before it will refold
# instead of re-anchoring; CRIMP_TPU_DELTA_FOLD_BUDGET overrides it. The
# cache key uses the kernel name "delta_fold_enable" so the entry can
# never collide with block-size entries.

DELTA_FOLD_ENV = "CRIMP_TPU_DELTA_FOLD"
DELTA_FOLD_BUDGET_ENV = "CRIMP_TPU_DELTA_FOLD_BUDGET"
# Guard threshold in cycles: 1e-9 sits two decades under the documented
# <1e-8 anchored-fold budget and ~100x under a 1 us ToA error bar.
DELTA_FOLD_BUDGET_DEFAULT = 1e-9


_env_pos_float = knobs.env_pos_float


def delta_fold_defaults() -> dict:
    return {"delta_fold": 0, "budget": DELTA_FOLD_BUDGET_DEFAULT}


def delta_fold_cache_key(n_events: int,
                         platform: str | None = None,
                         device_kind: str | None = None) -> str:
    return cache_key("delta_fold_enable", False, n_events, 1,
                     platform=platform, device_kind=device_kind)


def cached_delta_fold(n_events: int) -> dict | None:
    entry = _load_cache().get(delta_fold_cache_key(n_events))
    if not isinstance(entry, dict):
        return None
    d, b = entry.get("delta_fold"), entry.get("budget")
    if d in (0, 1) and isinstance(b, (int, float)) and 0.0 < b < float("inf"):
        return {"delta_fold": d, "budget": float(b)}
    return None


def store_delta_fold(n_events: int, entry: dict,
                     path: pathlib.Path | None = None) -> None:
    """Persist a gated delta-fold A/B winner (bench.py calls this)."""
    _store_entry(delta_fold_cache_key(n_events), entry, path)


def resolve_delta_fold(n_events: int) -> dict:
    """Resolve {delta_fold, budget} for a fold of n_events.

    Precedence per knob: CRIMP_TPU_DELTA_FOLD / CRIMP_TPU_DELTA_FOLD_BUDGET
    (hard overrides in both directions, honored even with autotune off;
    malformed raises) > cached A/B winner (unless CRIMP_TPU_AUTOTUNE=0) >
    default off with DELTA_FOLD_BUDGET_DEFAULT. Never times anything —
    the A/B with its deviation gate lives in bench.py (bench_delta_fold),
    exactly like the grid_mxu discipline. The exact fold stays the
    default, so an untouched install is bit-identical to the pre-engine
    code path.
    """
    out = delta_fold_defaults()
    env_d = _env_nonneg_int(DELTA_FOLD_ENV, valid=(0, 1))
    env_b = _env_pos_float(DELTA_FOLD_BUDGET_ENV)
    if autotune_mode() != "off":
        try:
            cached = cached_delta_fold(n_events)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a fold call
            logger.warning("delta_fold autotune cache lookup failed (%s); "
                           "using static defaults",
                           resilience.classify(exc).value, exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_d is not None:
        out["delta_fold"] = env_d
    if env_b is not None:
        out["budget"] = env_b
    return out


# -- delta-basis MCMC knob --------------------------------------------------
#
# CRIMP_TPU_MCMC_DELTA switches the ensemble sampler's likelihood
# (pipelines/fit_toas.py run_mcmc) between the exact per-proposal phase
# evaluation and the delta-basis path, where a proposal's residuals are
# one B @ dp matmul against the per-run precomputed delta-fold basis.
# Like delta_fold the switch is accuracy-gated: only bench.py's
# ESS/second + posterior-quantile-checked bench_mcmc A/B ever caches a 1,
# and the env var stays a hard override in both directions. The entry
# reuses the delta-fold precision budget (cycles) that the host-side
# guard enforces over the walker prior-box extent before admitting the
# linear path; CRIMP_TPU_DELTA_FOLD_BUDGET overrides it. The cache key
# uses the kernel name "mcmc_delta_enable" so the entry can never collide
# with the delta_fold or block-size entries.

MCMC_DELTA_ENV = "CRIMP_TPU_MCMC_DELTA"


def mcmc_delta_defaults() -> dict:
    return {"mcmc_delta": 0, "budget": DELTA_FOLD_BUDGET_DEFAULT}


def mcmc_delta_cache_key(n_toas: int,
                         platform: str | None = None,
                         device_kind: str | None = None) -> str:
    return cache_key("mcmc_delta_enable", False, n_toas, 1,
                     platform=platform, device_kind=device_kind)


def cached_mcmc_delta(n_toas: int) -> dict | None:
    entry = _load_cache().get(mcmc_delta_cache_key(n_toas))
    if not isinstance(entry, dict):
        return None
    d, b = entry.get("mcmc_delta"), entry.get("budget")
    if d in (0, 1) and isinstance(b, (int, float)) and 0.0 < b < float("inf"):
        return {"mcmc_delta": d, "budget": float(b)}
    return None


def store_mcmc_delta(n_toas: int, entry: dict,
                     path: pathlib.Path | None = None) -> None:
    """Persist a gated delta-basis MCMC A/B winner (bench.py calls this)."""
    _store_entry(mcmc_delta_cache_key(n_toas), entry, path)


def resolve_mcmc_delta(n_toas: int) -> dict:
    """Resolve {mcmc_delta, budget} for an n_toas posterior fit.

    Precedence per knob: CRIMP_TPU_MCMC_DELTA / CRIMP_TPU_DELTA_FOLD_BUDGET
    (hard overrides in both directions, honored even with autotune off;
    malformed raises) > cached A/B winner (unless CRIMP_TPU_AUTOTUNE=0) >
    default off with DELTA_FOLD_BUDGET_DEFAULT. Never times anything —
    the A/B with its ESS/s and posterior-quantile gates lives in bench.py
    (bench_mcmc), exactly like the delta_fold discipline. The exact
    likelihood stays the default, so an untouched install samples
    bit-identically to the pre-engine code path.
    """
    out = mcmc_delta_defaults()
    env_d = _env_nonneg_int(MCMC_DELTA_ENV, valid=(0, 1))
    env_b = _env_pos_float(DELTA_FOLD_BUDGET_ENV)
    if autotune_mode() != "off":
        try:
            cached = cached_mcmc_delta(n_toas)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a posterior fit
            logger.warning("mcmc_delta autotune cache lookup failed (%s); "
                           "using static defaults",
                           resilience.classify(exc).value, exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_d is not None:
        out["mcmc_delta"] = env_d
    if env_b is not None:
        out["budget"] = env_b
    return out


# -- multisource survey engine knob -----------------------------------------
#
# CRIMP_TPU_MULTISOURCE switches pipelines/survey.py between the vmapped
# multi-source batch engine and the per-source loop. Unlike grid_mxu /
# delta_fold the batched path is the DEFAULT (per-source bits are
# padding-exact by construction — docs/performance.md "Survey mode"), so
# the cached entry mostly records the measured sources_per_s and lets a
# failed promotion gate pin the loop (0) on hardware where batching loses.
# CRIMP_TPU_MULTISOURCE_MAX_PAD caps the bucket-merge padding waste and
# CRIMP_TPU_MULTISOURCE_BATCH hard-caps sources per bucket dispatch. The
# cache key uses the kernel name "multisource_enable" so the on/off entry
# can never collide with the "multisource" BLOCK-size entries
# resolve_blocks() maintains.

MULTISOURCE_ENV = "CRIMP_TPU_MULTISOURCE"
MULTISOURCE_MAX_PAD_ENV = "CRIMP_TPU_MULTISOURCE_MAX_PAD"
MULTISOURCE_BATCH_ENV = "CRIMP_TPU_MULTISOURCE_BATCH"
MULTISOURCE_MAX_PAD_DEFAULT = 4.0


def multisource_defaults() -> dict:
    return {"multisource": 1, "max_pad": MULTISOURCE_MAX_PAD_DEFAULT,
            "batch_cap": 0}


def multisource_cache_key(n_sources: int, n_events: int,
                          platform: str | None = None,
                          device_kind: str | None = None) -> str:
    return cache_key("multisource_enable", False, n_events, n_sources,
                     platform=platform, device_kind=device_kind)


def cached_multisource(n_sources: int, n_events: int) -> dict | None:
    entry = _load_cache().get(multisource_cache_key(n_sources, n_events))
    if not isinstance(entry, dict):
        return None
    m = entry.get("multisource")
    if m not in (0, 1):
        return None
    out = {"multisource": m}
    p = entry.get("max_pad")
    if isinstance(p, (int, float)) and 0.0 < p < float("inf"):
        out["max_pad"] = float(p)
    return out


def store_multisource(n_sources: int, n_events: int, entry: dict,
                      path: pathlib.Path | None = None) -> None:
    """Persist a gated multisource A/B verdict (bench.py calls this)."""
    _store_entry(multisource_cache_key(n_sources, n_events), entry, path)


def resolve_multisource(n_sources: int, n_events: int) -> dict:
    """Resolve {multisource, max_pad, batch_cap} for a survey workload.

    Precedence per knob: CRIMP_TPU_MULTISOURCE / _MAX_PAD / _BATCH (hard
    overrides, honored even with autotune off; malformed raises) > cached
    bench A/B verdict (unless CRIMP_TPU_AUTOTUNE=0) > defaults (batched
    path ON, max_pad 4.0, no batch cap). Never times anything — the A/B
    with its parity gate lives in bench.py (bench_multisource).
    """
    out = multisource_defaults()
    env_m = _env_nonneg_int(MULTISOURCE_ENV, valid=(0, 1))
    env_p = _env_pos_float(MULTISOURCE_MAX_PAD_ENV)
    env_b = _env_nonneg_int(MULTISOURCE_BATCH_ENV)
    if autotune_mode() != "off":
        try:
            cached = cached_multisource(n_sources, n_events)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a survey call
            logger.warning("multisource autotune cache lookup failed (%s); "
                           "using static defaults",
                           resilience.classify(exc).value, exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_m is not None:
        out["multisource"] = env_m
    if env_p is not None:
        out["max_pad"] = env_p
    if env_b is not None:
        out["batch_cap"] = env_b
    return out


# -- serving warm-batch knob ------------------------------------------------
#
# CRIMP_TPU_SERVE_WARM_BATCH switches the serving engine's warm re-timing
# path (serve/engine.py) between the per-request delta-refold loop and the
# stacked batch: every warm client in a round refolds in ONE
# deltafold.refold_batch dispatch. Like multisource the batched path is
# the DEFAULT (per-client bits match the solo refold by construction —
# docs/serving.md "The warm fast path"), so the cached entry mostly
# records the measured warm_requests_per_s and lets a failed promotion
# gate pin the loop (0) on hardware where stacking loses. The cache key
# uses the kernel name "serve_warm_batch_enable" so the entry can never
# collide with the other enable entries.

SERVE_WARM_BATCH_ENV = "CRIMP_TPU_SERVE_WARM_BATCH"


def serve_warm_batch_defaults() -> dict:
    return {"serve_warm_batch": 1}


def serve_warm_batch_cache_key(n_clients: int, n_events: int,
                               platform: str | None = None,
                               device_kind: str | None = None) -> str:
    return cache_key("serve_warm_batch_enable", False, n_events, n_clients,
                     platform=platform, device_kind=device_kind)


def cached_serve_warm_batch(n_clients: int, n_events: int) -> dict | None:
    entry = _load_cache().get(serve_warm_batch_cache_key(n_clients, n_events))
    if not isinstance(entry, dict):
        return None
    m = entry.get("serve_warm_batch")
    if m not in (0, 1):
        return None
    return {"serve_warm_batch": m}


def store_serve_warm_batch(n_clients: int, n_events: int, entry: dict,
                           path: pathlib.Path | None = None) -> None:
    """Persist a gated warm-batch A/B verdict (bench.py calls this)."""
    _store_entry(serve_warm_batch_cache_key(n_clients, n_events), entry, path)


def resolve_serve_warm_batch(n_clients: int, n_events: int) -> dict:
    """Resolve {serve_warm_batch} for a serving round's warm population.

    Precedence: CRIMP_TPU_SERVE_WARM_BATCH (hard override in both
    directions, honored even with autotune off; malformed raises) >
    cached bench A/B verdict (unless CRIMP_TPU_AUTOTUNE=0) > default ON.
    Never times anything — the A/B with its >1.5x throughput, p99 and
    bitwise-parity gates lives in bench.py (bench_serving's warm-heavy
    phase).
    """
    out = serve_warm_batch_defaults()
    env_m = _env_nonneg_int(SERVE_WARM_BATCH_ENV, valid=(0, 1))
    if autotune_mode() != "off":
        try:
            cached = cached_serve_warm_batch(n_clients, n_events)
        except Exception as exc:  # noqa: BLE001 — a corrupt cache or an
            # uninitializable backend must never take down a serving round
            logger.warning("serve_warm_batch autotune cache lookup failed "
                           "(%s); using static defaults",
                           resilience.classify(exc).value, exc_info=True)
            cached = None
        _count_cache(bool(cached))
        if cached:
            out.update(cached)
    if env_m is not None:
        out["serve_warm_batch"] = env_m
    return out


# -- timing / tuning --------------------------------------------------------


def sweep_candidates(kernel: str = "grid",
                     n_events: int | None = None,
                     n_trials: int | None = None,
                     poly: bool = True,
                     nharm: int = 2,
                     candidates=None,
                     repeats: int = 3,
                     on_row=None) -> list[dict]:
    """Time each (event_block, trial_block) candidate on the canonical
    benchwork workload; returns one row dict per candidate (error rows for
    candidates that fail to compile/fit — an OOM must not end the sweep).
    """
    from crimp_tpu.utils import benchwork

    n_events = benchwork.AB_N_EVENTS if n_events is None else int(n_events)
    n_trials = benchwork.AB_N_TRIALS if n_trials is None else int(n_trials)
    if candidates is None:
        candidates = DEFAULT_CANDIDATES
    # the static default is always a candidate: the tuned result can then
    # never be slower than the untuned install (acceptance criterion)
    cand = list(dict.fromkeys([tuple(c) for c in candidates]
                              + [static_defaults(kernel)]))
    sec, freqs, f0, df = benchwork.ab_workload(n_events, n_trials)
    rows = []
    for eb, tb in cand:
        try:
            rate = benchwork.candidate_rate(
                kernel, sec, freqs, f0, df, n_trials, nharm, eb, tb, poly,
                repeats=repeats,
            )
            row = {"event_block": int(eb), "trial_block": int(tb),
                   "trials_per_sec": round(float(rate), 1)}
        except Exception as exc:  # noqa: BLE001 — record and continue
            row = {"event_block": int(eb), "trial_block": int(tb),
                   "kind": resilience.classify(exc).value,
                   "error": f"{type(exc).__name__}: {str(exc)[:200]}"}
        rows.append(row)
        if on_row is not None:
            on_row(row)
    return rows


def tune(kernel: str = "grid",
         n_events: int | None = None,
         n_trials: int | None = None,
         poly: bool = True,
         nharm: int = 2,
         candidates=None,
         repeats: int = 3,
         persist: bool = True,
         on_row=None) -> dict:
    """Sweep the candidate grid, persist the winner, return it.

    The measurement runs at the canonical benchwork scale CAPPED at the
    requested problem size (timing a 1e8-event problem at full scale
    inside a tuner would cost more than it saves); the cache key still
    carries the caller's bucketed size, so a later resolve at that size
    finds the winner with zero timing runs.
    """
    from crimp_tpu.utils import benchwork

    n_events = benchwork.AB_N_EVENTS if n_events is None else int(n_events)
    n_trials = benchwork.AB_N_TRIALS if n_trials is None else int(n_trials)
    meas_events = min(n_events, benchwork.AB_N_EVENTS)
    meas_trials = min(n_trials, benchwork.AB_N_TRIALS)
    t0 = time.perf_counter()
    rows = sweep_candidates(kernel, meas_events, meas_trials, poly, nharm,
                            candidates, repeats, on_row)
    timed = [r for r in rows if "trials_per_sec" in r]
    if not timed:
        raise RuntimeError(f"autotune sweep produced no timed candidates: {rows}")
    winner = max(timed, key=lambda r: r["trials_per_sec"])
    key = cache_key(kernel, poly, n_events, n_trials)
    entry = {
        "event_block": winner["event_block"],
        "trial_block": winner["trial_block"],
        "trials_per_sec": winner["trials_per_sec"],
        "measured_events": meas_events,
        "measured_trials": meas_trials,
        "n_candidates": len(rows),
        "tune_wall_s": round(time.perf_counter() - t0, 2),
    }
    if persist:
        _store_entry(key, entry)
        logger.info("autotune: cached %s -> (%d, %d) at %.0f trials/s",
                    key, entry["event_block"], entry["trial_block"],
                    entry["trials_per_sec"])
    return {"key": key, "rows": rows, **entry}
