"""Delta-fold engine: incremental refold via event Taylor-basis matmuls.

CRIMP's real workflow is iterative — measure ToAs, fit the timing model,
refold with the updated .par, re-measure — yet every iteration re-runs the
full anchored fold (host longdouble prep + per-event Horner/glitch/wave
kernel). The model phase is exactly LINEAR in the spin Taylor terms
F0..F12 and in the glitch amplitudes (GLPH/GLF0/GLF1/GLF2/GLF0D) once the
epochs (PEPOCH, GLEP, GLTD, wave shape) are held fixed:

    phi(t; p + dp) = phi(t; p) + B(t) @ dp
    B[e, m]   = dt_e^(m+1)/(m+1)!          (dt_e seconds from PEPOCH)
    B[e, glitch amp] = [1, dt_g, dt_g^2/2, dt_g^3/6, tau (1 - e^{-dt_g/tau})]
                       masked by t >= GLEP  (dt_g seconds from GLEP)

and frac(phi + dphi) = frac(frac(phi) + dphi), so a refold under a
parameter update with unchanged epochs is ONE f64 device matmul against
the cached folded phases instead of a fresh longdouble pass:

    new_folded = frac(folded + B @ dp)

Error budget: the basis is built from the anchored per-event deltas
(dt = dt_ref[a] + d_e with d_e exact f64 seconds), so each entry carries
~1e-16 relative error; the matmul itself contributes the TPU emulated-f64
~2^-46 per multiply (the same budget analysis as ops/anchored.py:1-31).
The host-side guard bounds the refold error by

    err <= 2^-46 * sum_k max_e |B[e,k]| * |dp_k|

(the right side also bounds max|dphi|, so one bound covers both the
roundoff and the large-update regimes) and falls back to the exact
longdouble re-anchor whenever the bound exceeds the configured fraction
of the ToA error budget (default 1e-9 cycles — the documented fold budget
is <1e-8, the anchored kernel's own noise floor ~5e-9).

The FINGERPRINTED FOLD CACHE keys fold products on (event-set sha, anchor
layout sha, segment sizes, device fingerprint); a product stores the
folded phases plus the linear parameter vector and the sha of the
NON-linear parameters. A lookup with identical parameters returns the
stored phases (bit-identical — the exact path is deterministic given the
model and events); a lookup whose linear parameters moved takes the
`B @ dp` refold when the guard admits it; anything else (epoch change,
budget exceeded, cache off) re-runs the exact path and re-stores.

Resolution discipline (ops/autotune.py): CRIMP_TPU_DELTA_FOLD env (hard
override, malformed raises) > cached bench A/B winner (unless
CRIMP_TPU_AUTOTUNE=0) > default OFF — the exact path stays the default
and is bit-identical when the knob is off (it is simply never consulted).
CRIMP_TPU_FOLD_CACHE picks the storage layer: off / in-process (default)
/ on-disk. bench.py's bench_delta_fold owns the promotion gate.
"""

from __future__ import annotations

import hashlib
import logging
import pathlib
import zipfile
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu import knobs, obs, resilience
from crimp_tpu.models import timing
from crimp_tpu.obs import costmodel
from crimp_tpu.resilience import faultinject
from crimp_tpu.models.timing import N_FREQ_TERMS, TimingParams

logger = logging.getLogger(__name__)

SECONDS_PER_DAY = 86400.0
# emulated-f64 multiply noise (anchored.py budget analysis)
F64_MULT_EPS = 2.0 ** -46
# columns per glitch: GLPH, GLF0, GLF1, GLF2, GLF0D
N_GLITCH_AMP = 5

CACHE_VERSION = 2  # v2: sha256 payload footer detects torn/corrupt writes
# In-process LRU slots. Sized for the serving engine's warm population
# (bench_serving's warm-heavy phase runs >=16 resident clients): a cap
# below the working set would evict a warm client's product every round
# and silently turn its delta refolds back into exact folds.
_MEM_CAP = 64


# ---------------------------------------------------------------------------
# Linear / non-linear parameter split
# ---------------------------------------------------------------------------


def n_params(n_glitch: int) -> int:
    """Basis width: 13 Taylor columns + 5 amplitude columns per glitch."""
    return N_FREQ_TERMS + N_GLITCH_AMP * int(n_glitch)


def linear_param_vector(tm: TimingParams) -> np.ndarray:
    """The (13 + 5G,) vector the phase is linear in: [F0..F12] then
    per-glitch [GLPH, GLF0, GLF1, GLF2, GLF0D] blocks (glitch-major)."""
    f = np.asarray(tm.f, dtype=np.float64)
    cols = [f]
    for g in range(tm.n_glitch):
        cols.append(np.array([
            float(np.asarray(tm.glph)[g]),
            float(np.asarray(tm.glf0)[g]),
            float(np.asarray(tm.glf1)[g]),
            float(np.asarray(tm.glf2)[g]),
            float(np.asarray(tm.glf0d)[g]),
        ]))
    return np.concatenate(cols) if cols else f


def nonlinear_sha(tm: TimingParams) -> str:
    """sha256 over every parameter the BASIS depends on (the epochs and
    shapes): a model whose non-linear part moved can never delta-refold."""
    h = hashlib.sha256()
    for arr in (
        np.atleast_1d(np.asarray(tm.pepoch, dtype=np.float64)),
        np.asarray(tm.glep, dtype=np.float64),
        np.asarray(tm.gltd, dtype=np.float64),
        np.atleast_1d(np.asarray(tm.wave_epoch, dtype=np.float64)),
        np.atleast_1d(np.asarray(tm.wave_om, dtype=np.float64)),
        np.asarray(tm.wave_a, dtype=np.float64),
        np.asarray(tm.wave_b, dtype=np.float64),
    ):
        h.update(np.ascontiguousarray(arr).tobytes())
        h.update(b"|")
    return h.hexdigest()


def delta_params(tm_old: TimingParams, tm_new: TimingParams) -> np.ndarray | None:
    """dp = p_new - p_old when only linear parameters moved, else None."""
    if tm_old.n_glitch != tm_new.n_glitch or tm_old.n_wave != tm_new.n_wave:
        return None
    if nonlinear_sha(tm_old) != nonlinear_sha(tm_new):
        return None
    return linear_param_vector(tm_new) - linear_param_vector(tm_old)


# ---------------------------------------------------------------------------
# Basis build (anchored coordinates; jittable, shard-local safe)
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclass
class BasisSpec:
    """Host-prepared anchor geometry the basis rows are built from (the
    NON-linear half of the model, in anchored coordinates)."""

    dt_ref_sec: jax.Array  # (A,) anchor seconds from PEPOCH (exact->f64)
    glep_off: jax.Array  # (A, G) (t_ref - GLEP) seconds (-inf padding)
    gltd_sec: jax.Array  # (G,) recovery timescale seconds (1 s padding)
    glf0d_on: jax.Array  # (G,) 0 where GLTD == 0 (recovery disabled)
    wep_off: jax.Array  # (A,) (t_ref - WAVEEPOCH) seconds
    wave_om_sec: jax.Array  # scalar rad/s
    wave_a: jax.Array  # (W,)
    wave_b: jax.Array  # (W,)


def basis_spec(tm, t_ref_mjd) -> BasisSpec:
    """Build the BasisSpec for anchors t_ref (MJD) — mirrors the anchored
    prepare (prepare_anchors) conventions exactly: -inf offsets for padded
    glitches, 1 s / disabled recovery for GLTD == 0."""
    tm = timing.resolve(tm)
    t_ref = np.atleast_1d(np.asarray(t_ref_mjd, dtype=np.float64))
    ld = np.longdouble
    dt_ref = ((np.asarray(t_ref, dtype=ld) - ld(float(tm.pepoch)))
              * ld(SECONDS_PER_DAY)).astype(np.float64)
    glep = np.asarray(tm.glep)
    glep_off = np.where(
        np.isfinite(glep)[None, :],
        (t_ref[:, None] - glep[None, :]) * SECONDS_PER_DAY,
        -np.inf,
    )
    gltd = np.asarray(tm.gltd)
    as_f64 = lambda x: np.asarray(x, dtype=np.float64)
    return BasisSpec(
        dt_ref_sec=as_f64(dt_ref),
        glep_off=as_f64(glep_off),
        gltd_sec=as_f64(np.where(gltd == 0.0, 1.0, gltd * SECONDS_PER_DAY)),
        glf0d_on=as_f64(np.where(gltd == 0.0, 0.0, 1.0)),
        wep_off=as_f64((t_ref - float(tm.wave_epoch)) * SECONDS_PER_DAY),
        wave_om_sec=as_f64(float(tm.wave_om) / SECONDS_PER_DAY),
        wave_a=as_f64(tm.wave_a),
        wave_b=as_f64(tm.wave_b),
    )


@partial(jax.jit, static_argnames=("wave_in_f0",))
def basis_rows(spec: BasisSpec, delta: jax.Array, anchor_idx: jax.Array,
               wave_in_f0: bool = True) -> jax.Array:
    """(N, 13 + 5G) basis rows for events at anchored second offsets.

    Column m (m < 13) is dt^(m+1)/(m+1)! with dt the event's absolute
    seconds from PEPOCH; with whitening waves and ``wave_in_f0`` the F0
    column additionally carries the wave shape (W = F0 * shape, so
    dphi/dF0 includes it). Glitch blocks are masked by t >= GLEP. Rows are
    per-event independent, so the build shards along the event axis with
    no communication (parallel/mesh.py builds them shard-local).
    """
    dt = spec.dt_ref_sec[anchor_idx] + delta  # (N,) seconds from PEPOCH
    cols = []
    acc = dt
    cols.append(acc)
    for m in range(2, N_FREQ_TERMS + 1):
        acc = acc * dt / m  # dt^m / m!
        cols.append(acc)
    n_wave = spec.wave_a.shape[0]
    if n_wave and wave_in_f0:
        base = (delta + spec.wep_off[anchor_idx]) * spec.wave_om_sec
        shape = jnp.zeros_like(delta)
        for k in range(1, n_wave + 1):
            shape = (shape + spec.wave_a[k - 1] * jnp.sin(k * base)
                     + spec.wave_b[k - 1] * jnp.cos(k * base))
        cols[0] = cols[0] + shape
    n_glitch = spec.glep_off.shape[1]
    for g in range(n_glitch):
        dtg_raw = delta + spec.glep_off[anchor_idx, g]
        after = dtg_raw >= 0.0
        dtg = jnp.where(after, dtg_raw, 0.0)
        tau = spec.gltd_sec[g]
        recovery = spec.glf0d_on[g] * tau * (1.0 - jnp.exp(-dtg / tau))
        cols.append(jnp.where(after, 1.0, 0.0))  # GLPH
        cols.append(dtg)  # GLF0
        cols.append(0.5 * dtg**2)  # GLF1
        cols.append((1.0 / 6.0) * dtg**3)  # GLF2
        cols.append(recovery)  # GLF0D
    return jnp.stack(cols, axis=-1)


def taylor_basis_seconds(dt_sec, order: int) -> np.ndarray:
    """(..., order) pure-Taylor basis columns dt^m/m!, m = 1..order — the
    rank-``order`` delta-fold a local [F0, F1] window trial scan reduces
    to (pipelines/local_ephem.py composes it with the batched sampler)."""
    dt = np.asarray(dt_sec, dtype=np.float64)
    cols = []
    acc = dt
    for m in range(1, order + 1):
        if m > 1:
            acc = acc * dt / m
        cols.append(acc)
    return np.stack(cols, axis=-1)


@dataclass
class FoldBasis:
    """Device basis matrix + the host column maxima the guard needs."""

    b: jax.Array  # (N, P) device f64
    colmax: np.ndarray  # (P,) host max_e |B[e, k]|


def build_basis(tm, t_ref_mjd, delta, anchor_idx,
                wave_in_f0: bool = True) -> FoldBasis:
    """One-time basis build for an event set (device matmul operand)."""
    spec = basis_spec(tm, t_ref_mjd)
    b = basis_rows(spec, jnp.asarray(delta), jnp.asarray(anchor_idx),
                   wave_in_f0=wave_in_f0)
    colmax = np.asarray(jnp.max(jnp.abs(b), axis=0))
    return FoldBasis(b=b, colmax=colmax)


# ---------------------------------------------------------------------------
# Precision budget guard + refold kernel
# ---------------------------------------------------------------------------


def error_bound_cycles(colmax: np.ndarray, dp: np.ndarray) -> float:
    """Host-side bound on the refold's f64 error (cycles): 2^-46 per
    multiply against the worst-case |dphi| = sum_k max|B_k| |dp_k|."""
    return float(F64_MULT_EPS * np.dot(np.asarray(colmax),
                                       np.abs(np.asarray(dp))))


@jax.jit
def refold(folded: jax.Array, basis: jax.Array, dp: jax.Array) -> jax.Array:
    """frac(folded + B @ dp) — the incremental refold, one fused device
    pass over the basis. The matvec is evaluated as a FIXED-ORDER column
    accumulation (the column count is static and small, so this unrolls
    into the same fused multiply-adds a matvec would issue): XLA is free
    to re-tile a `@` reduction differently per shape, which would break
    the sharded-vs-monolithic bitwise pin (parallel/mesh.py)."""
    p = folded
    for k in range(basis.shape[1]):
        p = p + basis[:, k] * dp[k]
    return p - jnp.floor(p)


# ---------------------------------------------------------------------------
# Knob resolution
# ---------------------------------------------------------------------------


def resolve(n_events: int, delta_fold=None, budget=None) -> dict:
    """{'delta_fold': 0/1, 'budget': cycles} for a fold of n_events.

    Explicit arguments beat the autotune resolution (env > cached bench
    A/B winner > default off), mirroring the grid_mxu discipline.
    """
    from crimp_tpu.ops import autotune

    out = autotune.resolve_delta_fold(n_events)
    if delta_fold is not None:
        out["delta_fold"] = int(bool(delta_fold))
    if budget is not None:
        out["budget"] = float(budget)
    return out


# ---------------------------------------------------------------------------
# Fingerprinted fold cache
# ---------------------------------------------------------------------------


@dataclass
class FoldProduct:
    """An exact fold's reusable output: phases + the parameter split that
    decides whether a later request can reuse/delta them. The basis and
    the device-resident phases attach lazily on first delta use."""

    phases: np.ndarray  # (N,) folded [0,1) cycles (exact-path output)
    t_ref: np.ndarray  # (A,) anchors (MJD)
    sizes: tuple  # per-segment event counts
    pvec: np.ndarray  # linear parameter vector at fold time
    nonlin: str  # nonlinear_sha at fold time
    basis: FoldBasis | None = None
    phases_dev: jax.Array | None = None


_MEM_CACHE: OrderedDict[str, FoldProduct] = OrderedDict()
_last_info: dict = {"mode": None}


def last_fold_info() -> dict:
    """Telemetry for the most recent cached_fold call (mode: exact /
    cache / delta, guard bound, fallback reason)."""
    return dict(_last_info)


def clear_cache() -> None:
    """Drop the in-process fold cache (tests / bench isolation)."""
    _MEM_CACHE.clear()


def fold_cache_mode() -> tuple[str, pathlib.Path | None]:
    """CRIMP_TPU_FOLD_CACHE -> ('off'|'mem'|'disk', disk dir or None).

    0/off disables storage entirely; unset/auto/mem keeps products
    in-process only (default); 1/disk/on uses the default on-disk dir
    ($XDG_CACHE_HOME/crimp_tpu/foldcache); any other value is taken as an
    explicit on-disk directory path.
    """
    env = knobs.raw("CRIMP_TPU_FOLD_CACHE")
    low = env.lower()
    if low in knobs.OFF_WORDS:
        return "off", None
    if low in ("", "auto", "mem", "memory"):
        return "mem", None
    if low in ("1", "disk", "on", "true"):
        return "disk", pathlib.Path(knobs.cache_home()) / "crimp_tpu" / "foldcache"
    return "disk", pathlib.Path(env)


def fold_key(times_cat: np.ndarray, sizes, t_ref: np.ndarray,
             model_sha: str | None = None, tag: str | None = None) -> str:
    """Cache key: event-set sha + segment layout + anchor sha + device
    fingerprint (fold bits are backend-dependent, so products never cross
    backends).

    ``model_sha`` folds the model's NONLINEAR fingerprint into the key:
    two sources with identical event byte-streams but different timing
    models (the multisource survey can legitimately produce this — e.g.
    simulated sources sharing one event list) must occupy DISTINCT cache
    slots instead of evicting each other on every alternation. Linear-only
    parameter moves keep the same nonlinear sha, so the delta-refold path
    is unaffected. ``tag`` is an optional caller namespace (the survey
    passes the source name) for isolation even between identical models.
    """
    from crimp_tpu.ops import autotune

    platform, device_kind = autotune.device_fingerprint()
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(
        np.asarray(times_cat, dtype=np.float64)).tobytes())
    h.update(("|" + ",".join(str(int(s)) for s in sizes) + "|").encode())
    h.update(np.ascontiguousarray(
        np.asarray(t_ref, dtype=np.float64)).tobytes())
    h.update(f"|{platform}|{device_kind}|v{CACHE_VERSION}".encode())
    if model_sha is not None:
        h.update(f"|model:{model_sha}".encode())
    if tag is not None:
        h.update(f"|tag:{tag}".encode())
    return h.hexdigest()


def _mem_get(key: str) -> FoldProduct | None:
    prod = _MEM_CACHE.get(key)
    if prod is not None:
        _MEM_CACHE.move_to_end(key)
    return prod


def _mem_put(key: str, prod: FoldProduct) -> None:
    _MEM_CACHE[key] = prod
    _MEM_CACHE.move_to_end(key)
    while len(_MEM_CACHE) > _MEM_CAP:
        _MEM_CACHE.popitem(last=False)


def _product_sha(prod: FoldProduct) -> str:
    """sha256 over the payload arrays; the npz footer that detects a torn
    or bit-flipped product on load (satellite of the PR-9 quarantine)."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(
        np.asarray(prod.phases, dtype=np.float64)).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(prod.t_ref, dtype=np.float64)).tobytes())
    h.update(np.asarray(prod.sizes, dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(prod.pvec, dtype=np.float64)).tobytes())
    h.update(prod.nonlin.encode())
    return h.hexdigest()


def _disk_get(key: str, disk_dir: pathlib.Path) -> FoldProduct | None:
    path = disk_dir / f"{key}.npz"
    if not path.exists():
        return None  # plain miss: nothing to verify or quarantine
    try:
        faultinject.fire("fold_cache")
        with np.load(path, allow_pickle=False) as doc:
            if int(doc["version"]) != CACHE_VERSION:
                return None  # older schema, not corruption: version-miss
            prod = FoldProduct(
                phases=np.asarray(doc["phases"], dtype=np.float64),
                t_ref=np.asarray(doc["t_ref"], dtype=np.float64),
                sizes=tuple(int(s) for s in doc["sizes"]),
                pvec=np.asarray(doc["pvec"], dtype=np.float64),
                nonlin=str(doc["nonlin"]),
            )
            if str(doc["sha"]) != _product_sha(prod):
                raise resilience.CacheCorruptError(
                    f"fold cache {path.name}: sha footer mismatch")
            return prod
    except (OSError, KeyError, ValueError, EOFError, zipfile.BadZipFile,
            resilience.CacheCorruptError):
        # Torn write or bit rot: quarantine to *.corrupt and refold exact.
        resilience.quarantine_file(path, label="fold_cache")
        return None


def _disk_put(key: str, prod: FoldProduct, disk_dir: pathlib.Path) -> None:
    try:
        disk_dir.mkdir(parents=True, exist_ok=True)
        path = disk_dir / f"{key}.npz"
        tmp = disk_dir / f"{key}.npz.tmp"
        with open(tmp, "wb") as fh:  # np.savez(path) would append .npz
            np.savez(fh, version=CACHE_VERSION, phases=prod.phases,
                     t_ref=prod.t_ref, sizes=np.asarray(prod.sizes),
                     pvec=prod.pvec, nonlin=np.str_(prod.nonlin),
                     sha=np.str_(_product_sha(prod)))
        tmp.rename(path)
    except OSError as exc:
        logger.warning("fold cache write failed (%s); continuing", exc)


def store_product(tm, times_cat, sizes, t_ref, phases,
                  tag: str | None = None) -> str | None:
    """Seed the fold cache with an exact fold computed elsewhere.

    The multisource batched fold (ops/multisource.fold_sources) is
    bit-identical per source to the exact single-source path but never
    routes through this cache; the serving engine seeds each cold
    client's batched fold here (``tag`` = client name) so that client's
    NEXT request takes the cache-hit / ``B @ dp`` delta path instead of a
    fresh exact fold.  Returns the cache key, or None when the cache tier
    is off.
    """
    mode, disk_dir = fold_cache_mode()
    if mode == "off":
        return None
    tm = timing.resolve(tm)
    key = fold_key(times_cat, sizes, t_ref, model_sha=nonlinear_sha(tm),
                   tag=tag)
    prod = FoldProduct(
        phases=np.ascontiguousarray(np.asarray(phases, dtype=np.float64)),
        t_ref=np.asarray(t_ref, dtype=np.float64),
        sizes=tuple(int(s) for s in sizes),
        pvec=linear_param_vector(tm),
        nonlin=nonlinear_sha(tm),
    )
    _mem_put(key, prod)
    if mode == "disk":
        _disk_put(key, prod, disk_dir)
    obs.counter_add("delta_fold_seeded")
    return key


def _ensure_basis(prod: FoldProduct, tm, delta, anchor_idx) -> FoldBasis:
    if prod.basis is None:
        prod.basis = build_basis(tm, prod.t_ref, delta, anchor_idx)
    return prod.basis


def cached_fold(tm, times_cat, sizes, t_ref, delta, anchor_idx, exact_fn,
                budget: float, tag: str | None = None) -> tuple[np.ndarray, dict]:
    """The engine's entry point (anchored.fold_segments calls it when the
    knob resolves on): returns (folded phases (N,), info).

    Fast paths, in order: bit-identical cache hit (stored product, same
    linear vector, same nonlinear sha) -> ``B @ dp`` delta refold (linear
    move within the precision budget, always relative to the stored EXACT
    baseline so successive refolds never accumulate error) -> exact fold
    via ``exact_fn()`` (stored as the new product).
    """
    global _last_info
    tm = timing.resolve(tm)
    mode, disk_dir = fold_cache_mode()
    pvec = linear_param_vector(tm)
    nonlin = nonlinear_sha(tm)
    # "stored"/"tag" let callers (the serving engine's warmth tracking)
    # confirm THIS call left a product in the cache under THEIR tag — a
    # client whose seed never landed must stay cold.
    info: dict = {"mode": "exact", "n_events": int(np.size(times_cat)),
                  "tag": tag, "stored": mode != "off"}
    key = None
    prod = None
    if mode != "off":
        key = fold_key(times_cat, sizes, t_ref, model_sha=nonlin, tag=tag)
        info["key"] = key[:16]
        try:
            prod = _mem_get(key)
            if prod is None and mode == "disk":
                prod = _disk_get(key, disk_dir)
                if prod is not None:
                    _mem_put(key, prod)
        except Exception as exc:  # noqa: BLE001 — fold ladder: any failure
            # on the cache path drops one rung, to the exact re-anchor fold
            kind = resilience.classify(exc)
            resilience.record_degradation("fold", "exact_refold", kind)
            info["fallback"] = kind.value
            prod = None
    if prod is not None and prod.nonlin == nonlin and \
            prod.pvec.shape == pvec.shape:
        dp = pvec - prod.pvec
        if not np.any(dp):
            info["mode"] = "cache"
            obs.counter_add("delta_fold_cache_hits")
            _last_info = info
            return prod.phases.copy(), info
        basis = _ensure_basis(prod, tm, delta, anchor_idx)
        bound = error_bound_cycles(basis.colmax, dp)
        info["bound_cycles"] = bound
        if bound <= budget:
            try:
                if prod.phases_dev is None:
                    prod.phases_dev = jnp.asarray(prod.phases)
                dp_dev = jnp.asarray(dp)
                folded = np.asarray(refold(prod.phases_dev, basis.b, dp_dev))
                costmodel.capture("delta_refold", refold,
                                  prod.phases_dev, basis.b, dp_dev)
                info["mode"] = "delta"
                obs.counter_add("delta_fold_refolds")
                _last_info = info
                return folded, info
            except Exception as exc:  # noqa: BLE001 — fold ladder: a refold
                # that dies (device OOM, nonfinite output) degrades to exact
                kind = resilience.classify(exc)
                resilience.record_degradation("fold", "exact_refold", kind)
                info["fallback"] = kind.value
                obs.counter_add("delta_fold_refold_failures")
        else:
            info["fallback"] = "budget"
            obs.counter_add("delta_fold_guard_trips")
    elif prod is not None:
        info["fallback"] = "nonlinear"
        obs.counter_add("delta_fold_nonlinear_fallbacks")
    obs.counter_add("delta_fold_exact_folds")
    folded = np.asarray(exact_fn())
    if mode != "off":
        new = FoldProduct(phases=folded, t_ref=np.asarray(t_ref),
                          sizes=tuple(int(s) for s in sizes), pvec=pvec,
                          nonlin=nonlin)
        _mem_put(key, new)
        if mode == "disk":
            _disk_put(key, new, disk_dir)
    _last_info = info
    return folded, info


# ---------------------------------------------------------------------------
# Batched warm refolds (the serving engine's one-dispatch steady state)
# ---------------------------------------------------------------------------


@jax.jit
def refold_batch(folded: jax.Array, basis: jax.Array,
                 dp: jax.Array) -> jax.Array:
    """vmapped :func:`refold` over a leading client axis: (B, E) phases,
    (B, E, P) bases, (B, P) updates -> (B, E) refolded phases.

    Per-client bits match the solo kernel: vmap batches the fixed-order
    column accumulation WITHOUT reassociating it (the same argument as
    ``multisource.stacked_fold``), and padding is inert — zero basis
    columns with zero dp contribute ``+ 0.0 * 0.0`` to phases that are
    never ``-0.0`` (folded phases live in [0, 1)), which is a bitwise
    identity, while padded event rows are sliced away before return.
    """
    return jax.vmap(refold)(folded, basis, dp)


def _warm_entry(tm, seg_times, budget):
    """One client's refold operands, mirroring fold_segments' layout
    conventions byte-for-byte so the cache key matches the seeded one."""
    tm = timing.resolve(tm)
    seg = [np.atleast_1d(np.asarray(t, dtype=np.float64)) for t in seg_times]
    t_ref = np.asarray([(t[-1] - t[0]) / 2 + t[0] if t.size else 0.0
                        for t in seg])
    sizes = [t.size for t in seg]
    times_cat = np.concatenate(seg) if seg else np.zeros(0, dtype=np.float64)
    if budget is None:
        budget = resolve(times_cat.size, delta_fold=1)["budget"]
    return tm, t_ref, sizes, times_cat, float(budget)


def delta_refold_batch(tms, seg_times_lists, tags=None, budget=None):
    """Refold every admitted warm client in ONE stacked device dispatch.

    Inputs are parallel lists (one slot per client): timing models, the
    per-segment event-time lists exactly as ``fold_segments`` would see
    them, and the cache tags (the serving engine passes client ids).
    Returns ``(phase_lists, t_refs, infos)`` aligned with the inputs;
    ``phase_lists[i]`` is the per-segment refolded phases, or ``None``
    when client *i* must take the existing solo rung instead — cache
    miss, nonlinear move, or a precision-guard trip demotes ONLY that
    client (``infos[i]["fallback"]`` says why), never the batch.

    Admitted clients pad to the batch's (max events x max params) and go
    through :func:`refold_batch`; the zero padding is bitwise inert (see
    the kernel docstring), so each row equals the solo ``refold`` bits.
    Zero-``dp`` clients short-circuit to their stored product (the solo
    cache-hit path) without joining the matmul.
    """
    from crimp_tpu.ops import anchored

    n = len(tms)
    tags = list(tags) if tags is not None else [None] * n
    phase_lists: list = [None] * n
    t_refs: list = [None] * n
    infos: list = [{} for _ in range(n)]
    mode, disk_dir = fold_cache_mode()
    admitted = []  # (slot, prod, basis, dp, sizes, n_events)
    for i in range(n):
        tm, t_ref, sizes, times_cat, budget_i = _warm_entry(
            tms[i], seg_times_lists[i], budget)
        t_refs[i] = t_ref
        info = infos[i]
        info.update({"mode": None, "n_events": int(times_cat.size),
                     "tag": tags[i]})
        if mode == "off" or not times_cat.size:
            info["fallback"] = "cache_off" if mode == "off" else "empty"
            continue
        pvec = linear_param_vector(tm)
        nonlin = nonlinear_sha(tm)
        key = fold_key(times_cat, sizes, t_ref, model_sha=nonlin,
                       tag=tags[i])
        info["key"] = key[:16]
        try:
            prod = _mem_get(key)
            if prod is None and mode == "disk":
                prod = _disk_get(key, disk_dir)
                if prod is not None:
                    _mem_put(key, prod)
        except Exception as exc:  # noqa: BLE001 — cache-path failure
            # demotes this client to the solo rung, where cached_fold's
            # own fold ladder classifies and stamps it
            info["fallback"] = resilience.classify(exc).value
            continue
        if prod is None:
            info["fallback"] = "miss"
            continue
        if prod.nonlin != nonlin or prod.pvec.shape != pvec.shape:
            info["fallback"] = "nonlinear"
            continue
        dp = pvec - prod.pvec
        if not np.any(dp):
            info["mode"] = "cache"
            obs.counter_add("delta_fold_cache_hits")
            phase_lists[i] = np.split(prod.phases.copy(),
                                      np.cumsum(sizes)[:-1])
            continue
        anchor_idx = np.repeat(np.arange(len(sizes)), sizes)
        delta = anchored.anchor_deltas(times_cat, t_ref, anchor_idx)
        basis = _ensure_basis(prod, tm, delta, anchor_idx)
        bound = error_bound_cycles(basis.colmax, dp)
        info["bound_cycles"] = bound
        if bound > budget_i:
            info["fallback"] = "budget"
            obs.counter_add("delta_fold_guard_trips")
            continue
        admitted.append((i, prod, basis, dp, sizes, times_cat.size))
    if not admitted:
        return phase_lists, t_refs, infos
    n_ev = max(a[5] for a in admitted)
    n_par = max(int(a[2].b.shape[1]) for a in admitted)
    folded_pad = np.zeros((len(admitted), n_ev), dtype=np.float64)
    basis_pad = np.zeros((len(admitted), n_ev, n_par), dtype=np.float64)
    dp_pad = np.zeros((len(admitted), n_par), dtype=np.float64)
    for r, (_, prod, basis, dp, _, n_i) in enumerate(admitted):
        folded_pad[r, :n_i] = prod.phases
        basis_pad[r, :n_i, :basis.b.shape[1]] = np.asarray(basis.b)
        dp_pad[r, :dp.size] = dp
    args = (jnp.asarray(folded_pad), jnp.asarray(basis_pad),
            jnp.asarray(dp_pad))
    out = np.asarray(refold_batch(*args))
    costmodel.capture("delta_refold_batch", refold_batch, *args)
    obs.counter_add("delta_fold_refolds", len(admitted))
    for r, (i, _, _, _, sizes, n_i) in enumerate(admitted):
        infos[i]["mode"] = "delta"
        infos[i]["batched"] = True
        phase_lists[i] = np.split(
            np.ascontiguousarray(out[r, :n_i]), np.cumsum(sizes)[:-1])
    return phase_lists, t_refs, infos
