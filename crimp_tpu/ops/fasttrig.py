"""Polynomial sin/cos on mod-1-reduced phases — the cheap-transcendental path.

The search kernels reduce the trial phase mod 1 in f64 before any trig, so
the argument is ALWAYS in [-0.5, 0.5] cycles; a full libm sine pays for
range reduction and ~1e-7 relative accuracy the Z^2/H statistics cannot
use (their own f32 phase carries ~1e-5-cycle error, and the statistic's
noise floor is sqrt(N)). These fixed odd/even least-squares polynomials
evaluate sin(2*pi*x) and cos(2*pi*x) directly on the reduced argument in
~13 VPU FMAs per pair:

    max |error| = 3.1e-7 (sin), 3.6e-8 (cos)  over |x| <= 0.5

— a few times the hardware path's own f32 output rounding (~6e-8), but
two orders below the ~1e-5-cycle phase error both paths already carry and
far below the statistic's sqrt(N) noise floor.

Default: ON when the default JAX backend is a TPU, OFF elsewhere — the
round-3 on-chip A/B (v5e, 1e5 trials x 8.4e5 events) measured 91.5k vs
33.2k trials/s (2.76x) at 3.2e-4 max relative deviation on the statistic
(docs/performance.md "Z^2 roofline"). Override per-call with the
``poly_trig`` argument of ``PeriodSearch`` or globally with
``CRIMP_TPU_POLY_TRIG=1``/``0``.
"""

from __future__ import annotations

import jax.numpy as jnp

from crimp_tpu import knobs

# Least-squares fits on [-0.5, 0.5] (degree 11 odd / 12 even in x; fit and
# error bounds reproduced by tests/test_search.py::TestPolyTrig).
_SIN_COEFFS = (
    6.2831834664e00,
    -4.1341480362e01,
    8.1597658022e01,
    -7.6594929804e01,
    4.1269936976e01,
    -1.2372507211e01,
)
_COS_COEFFS = (
    9.9999999229e-01,
    -1.9739205554e01,
    6.4939172239e01,
    -8.5451165912e01,
    6.0176231390e01,
    -2.6000532120e01,
    6.5756180224e00,
)


def poly_trig_enabled(override: bool | None = None) -> bool:
    """Whether search kernels should use the polynomial sin/cos pair.

    Precedence: explicit ``override`` > ``CRIMP_TPU_POLY_TRIG`` env var >
    backend auto-default (on for TPU, off for CPU/GPU).

    A value outside the recognized on/off sets raises: silently treating a
    typo ('of', 'yes') as unset would auto-ENABLE poly trig on TPU, the
    opposite of what the user plausibly meant.

    The auto-default branch calls ``jax.default_backend()``, which
    INITIALIZES the JAX backend (a multi-second handshake through the
    accelerator relay, and a hang if the relay is wedged). It must only be
    reached from the compute path, never from entry-time/config-printing
    code — the driver-entry contract (``__graft_entry__.entry``) pins this.
    """
    if override is not None:
        return bool(override)
    state = knobs.env_onoff("CRIMP_TPU_POLY_TRIG")
    if state is not None:
        return state
    import jax

    return jax.default_backend() == "tpu"


def centered_frac(x):
    """x minus its nearest integer via floor — exactly in [-0.5, 0.5).

    Deliberately NOT ``x - jnp.round(x)``: the axon TPU path's f64
    emulation mis-lowers round, returning off-by-one results for
    arguments near a half-integer at large magnitude — measured on-chip:
    ``jnp.round(1215782.499995642) -> 1215781.0``, with a bad window
    that grows with magnitude (~|x| * 2^-31, i.e. an f32 intermediate);
    the true-CPU lowering is correct, so only on-chip runs were wrong
    and only a tier test can guard it (tests/test_tpu_tier.py). The
    mis-round leaves |frac| up to 1.5. Hardware trig forgives an integer offset
    (cos 2pi(x-n) = cos 2pi x for any integer n), which is why the bug
    stayed invisible; the range-limited polynomial pair does not, and the
    Chebyshev harmonic recurrence amplifies |cos1| > 1 exponentially in
    harmonic order — the round-4 on-chip 1e8-event H-test (nharm 20)
    returned all-NaN through exactly this hole.

    ``jnp.floor`` is verified correct on the same values. For |x| >= 1
    (and any x in [0, 1)) both steps are exact in floating point for
    |x| < 2^52: x - floor(x) subtracts values within a factor of 2
    (Sterbenz), and the half-centering subtracts 1 from a value in
    [0.5, 1). The one inexact window is x in (-0.5, 0), where
    x - floor(x) = x + 1 rounds: the result can differ from x by up to
    half an ulp of 1.0 (~1.1e-16 cycles in f64) — far below every
    consumer's tolerance, but NOT bit-exact (the old round-based
    reduction returned tiny negative x unchanged). Works for f32 and
    f64 alike.
    """
    f = x - jnp.floor(x)
    return f - (f >= 0.5).astype(f.dtype)


def sincos_cycles(frac):
    """(sin, cos) of 2*pi*frac for frac in [-0.5, 0.5] (any float dtype).

    Horner evaluation in z = frac^2: 1 mul + 5 FMA + 1 mul for sin,
    6 FMA for cos — ~13 ops for the pair.
    """
    z = frac * frac
    s = _SIN_COEFFS[-1]
    for coef in _SIN_COEFFS[-2::-1]:
        s = s * z + coef
    s = s * frac
    c = _COS_COEFFS[-1] * z + _COS_COEFFS[-2]
    for coef in _COS_COEFFS[-3::-1]:
        c = c * z + coef
    return s, c
