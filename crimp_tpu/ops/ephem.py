"""Spin ephemerides: F(t), Fdot(t), and integer-rotation anchor times.

Semantics parity with the reference (ephemTmjd.py:19-77 and
ephemIntegerRotation.py:25-86), but vectorized: the Newton iteration that
finds the nearest earlier integer-rotation epoch runs as a fixed-iteration,
convergence-masked update over a whole batch of anchor times at once —
the reference re-parses the .par file and loops serially per ToA
(timfile.py:206-217).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu.models import timing
from crimp_tpu.models.timing import N_FREQ_TERMS, TimingParams
from crimp_tpu.ops import fasttrig
from crimp_tpu.ops.fold import SECONDS_PER_DAY, phase_no_waves

from math import factorial

_INV_FACT = np.array([1.0 / factorial(n) for n in range(N_FREQ_TERMS)])


def spin_frequency(tm: TimingParams, time_mjd: jax.Array):
    """(freq, freqdot) at time_mjd from Taylor + glitch terms."""
    dt = (time_mjd - tm.pepoch) * SECONDS_PER_DAY

    # freq = sum_{n=0..12} F_n/n! dt^n ; freqdot = sum_{n=1..12} F_n/(n-1)! dt^(n-1)
    freq = jnp.zeros_like(dt)
    for n in range(N_FREQ_TERMS - 1, -1, -1):
        freq = freq * dt + tm.f[n] * _INV_FACT[n]
    fdot = jnp.zeros_like(dt)
    for n in range(N_FREQ_TERMS - 1, 0, -1):
        fdot = fdot * dt + tm.f[n] * _INV_FACT[n - 1]

    def add_glitch(carry, g):
        freq_acc, fdot_acc = carry
        glep, glf0, glf1, glf2, glf0d, gltd = g
        after = time_mjd >= glep
        dt_days = jnp.where(after, time_mjd - glep, 0.0)
        dt_sec = dt_days * SECONDS_PER_DAY
        # GLTD = 0 means "no recovery term" (fit pipeline zeroes it when
        # GLF0D = 0): guard both the exp argument and the 1/GLTD factor.
        safe_gltd = jnp.where(gltd == 0.0, 1.0, gltd)
        decay = jnp.where(gltd == 0.0, 0.0, jnp.exp(-dt_days / safe_gltd))
        dfreq = glf0 + glf1 * dt_sec + 0.5 * glf2 * dt_sec**2 + glf0d * decay
        dfdot = glf1 + glf2 * dt_sec - (glf0d / (safe_gltd * SECONDS_PER_DAY)) * decay
        return (
            freq_acc + jnp.where(after, dfreq, 0.0),
            fdot_acc + jnp.where(after, dfdot, 0.0),
        ), None

    if tm.n_glitch:
        stacked = jnp.stack([tm.glep, tm.glf0, tm.glf1, tm.glf2, tm.glf0d, tm.gltd], axis=-1)
        (freq, fdot), _ = jax.lax.scan(add_glitch, (freq, fdot), stacked)
    return freq, fdot


@jax.jit
def integer_rotation(tm: TimingParams, time_mjd: jax.Array, tol_phase: float = 1e-10, max_iter: int = 10):
    """Nearest earlier integer-rotation epochs for a batch of MJDs.

    Newton-iterates t <- t - (phi(t) - floor(phi(t0)))/f(t)/86400 with a
    per-element convergence mask; waves are excluded from the phase (the
    anchor is defined on the deterministic spin-down model only, matching
    ephemIntegerRotation.py:47-64).
    """
    target = jnp.floor(phase_no_waves(tm, time_mjd))

    def body(_, t):
        ph = phase_no_waves(tm, t)
        err = ph - target
        freq, _ = spin_frequency(tm, t)
        converged = jnp.abs(err) < tol_phase
        return jnp.where(converged, t, t - (err / freq) / SECONDS_PER_DAY)

    t_anchor = jax.lax.fori_loop(0, max_iter, body, time_mjd)
    freq, fdot = spin_frequency(tm, t_anchor)
    ph = phase_no_waves(tm, t_anchor)
    return {
        "Tmjd_intRotation": t_anchor,
        "freq_intRotation": freq,
        "freqdot_intRotation": fdot,
        "ph_intRotation": ph,
        # centered_frac, not jnp.round: this stack's round lowering is
        # off-by-one near half-integers at large magnitude (see
        # fasttrig.centered_frac); the residual here is near 0 so the
        # bug window is unreachable in practice, but the safe reduction
        # costs the same.
        "phase_residual_from_integer": fasttrig.centered_frac(ph),
    }


# ---------------------------------------------------------------------------
# Host-friendly wrappers mirroring the reference call signatures.
# ---------------------------------------------------------------------------


def ephem_at(Tmjd, timMod) -> dict:
    """F, Fdot at one or more MJDs (reference: ephemTmjd.py:19)."""
    tm = timing.resolve(timMod)
    arr = jnp.atleast_1d(jnp.asarray(Tmjd, dtype=jnp.float64))
    freq, fdot = spin_frequency(tm, arr)
    squeeze = np.isscalar(Tmjd) or np.shape(Tmjd) == ()
    to_out = lambda x: np.asarray(x)[0] if squeeze else np.asarray(x)
    return {"Tmjd": Tmjd, "freqAtTmjd": to_out(freq), "freqdotAtTmjd": to_out(fdot)}


def spin_frequency_host(tm: TimingParams, time_mjd: np.ndarray):
    """Host (exact f64) twin of spin_frequency, for precision-critical paths."""
    t = np.atleast_1d(np.asarray(time_mjd, dtype=np.float64))
    dt = (t - float(tm.pepoch)) * SECONDS_PER_DAY
    f = np.asarray(tm.f)
    freq = np.zeros_like(dt)
    for n in range(N_FREQ_TERMS - 1, -1, -1):
        freq = freq * dt + f[n] * _INV_FACT[n]
    fdot = np.zeros_like(dt)
    for n in range(N_FREQ_TERMS - 1, 0, -1):
        fdot = fdot * dt + f[n] * _INV_FACT[n - 1]
    glep = np.asarray(tm.glep)
    for g in range(tm.n_glitch):
        if not np.isfinite(glep[g]):
            continue
        after = t >= glep[g]
        dt_days = np.where(after, t - glep[g], 0.0)
        dt_sec = dt_days * SECONDS_PER_DAY
        gltd = float(np.asarray(tm.gltd)[g])
        glf0d = float(np.asarray(tm.glf0d)[g])
        glf1 = float(np.asarray(tm.glf1)[g])
        glf2 = float(np.asarray(tm.glf2)[g])
        # GLTD = 0 disables the recovery term entirely (see device twin).
        if gltd == 0.0:
            decay = 0.0
            recovery_fdot = 0.0
        else:
            decay = np.exp(-dt_days / gltd)
            recovery_fdot = -(glf0d / (gltd * SECONDS_PER_DAY)) * decay
        freq += np.where(after, float(np.asarray(tm.glf0)[g]) + glf1 * dt_sec + 0.5 * glf2 * dt_sec**2 + glf0d * decay, 0.0)
        fdot += np.where(after, glf1 + glf2 * dt_sec + recovery_fdot, 0.0)
    return freq, fdot


def integer_rotation_host(tm: TimingParams, time_mjd: np.ndarray, tol_phase: float = 1e-10, max_iter: int = 10) -> dict:
    """Host (longdouble-phase) Newton solve for integer-rotation anchors.

    The device version above is limited by the TPU's emulated-f64 phase noise
    (~4e-8 cycles at 1e6-cycle magnitudes), which exceeds tol_phase; ToA
    anchoring therefore runs this exact host twin (vectorized numpy, trivial
    cost at ToA counts).
    """
    from crimp_tpu.ops import anchored

    def phase_nw(t):
        return anchored._host_taylor_phase(tm, t) + anchored._host_glitch_phase(tm, t).astype(np.longdouble)  # graftlint: disable=GL004 (host-only Newton twin of the device solve; it extends anchored.py's longdouble phase and nothing here is ever traced)

    t = np.atleast_1d(np.asarray(time_mjd, dtype=np.float64))
    target = np.floor(phase_nw(t))
    t_cur = t.copy()
    for _ in range(max_iter):
        err = (phase_nw(t_cur) - target).astype(np.float64)
        if np.all(np.abs(err) < tol_phase):
            break
        freq, _ = spin_frequency_host(tm, t_cur)
        t_cur = np.where(np.abs(err) < tol_phase, t_cur, t_cur - (err / freq) / SECONDS_PER_DAY)
    freq, fdot = spin_frequency_host(tm, t_cur)
    ph = phase_nw(t_cur).astype(np.float64)
    return {
        "Tmjd_intRotation": t_cur,
        "freq_intRotation": freq,
        "freqdot_intRotation": fdot,
        "ph_intRotation": ph,
        "phase_residual_from_integer": ph - np.round(ph),
    }


def ephem_integer_rotation(Tmjd, timMod, printOutput: bool = False, tol_phase: float = 1e-10, max_iter: int = 10) -> dict:
    """Integer-rotation ephemerides (reference: ephemIntegerRotation.py:25)."""
    tm = timing.resolve(timMod)
    arr = np.atleast_1d(np.asarray(Tmjd, dtype=np.float64))
    out = integer_rotation_host(tm, arr, tol_phase=tol_phase, max_iter=max_iter)
    squeeze = np.isscalar(Tmjd) or np.shape(Tmjd) == ()
    result = {
        key: (np.asarray(val)[0] if squeeze else np.asarray(val))
        for key, val in out.items()
    }
    if printOutput:
        print(
            f"Input Tmjd = {Tmjd} days."
            f"\n Earliest Tmjd with integer number of rotations = {result['Tmjd_intRotation']}."
            f" Corresponding frequency = {result['freq_intRotation']}."
            f" Corresponding phase = {result['ph_intRotation']}"
            f"\n Phase residual from integer = {result['phase_residual_from_integer']}"
        )
    return result


# Reference-named aliases.
ephemTmjd = ephem_at
ephemIntegerRotation = ephem_integer_rotation
