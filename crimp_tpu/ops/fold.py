"""Phase folding: the single numerics-critical kernel of the framework.

Semantics parity with the reference fold (calcphase.py:73-176):

  phi(t) = sum_{n=1..13} F_{n-1}/n! * dt^n                (dt = (t-PEPOCH)*86400 s)
         + per glitch with t >= GLEP:
             GLPH + GLF0*dt_g + GLF1/2*dt_g^2 + GLF2/6*dt_g^3
             + GLF0D*GLTD*86400*(1 - exp(-(t-GLEP)/GLTD))  (dt_g in s, GLTD in days)
         + F0 * sum_k [ A_k sin(k*OM*(t-WEP)) + B_k cos(k*OM*(t-WEP)) ]

and the cycle-folded phase is phi - floor(phi) in [0, 1).

Precision: total phase reaches ~1e6 cycles for the bundled magnetar while
ToAs need <1e-7-cycle accuracy, so everything here is float64 (enabled
globally in crimp_tpu.__init__; XLA emulates f64 on TPU). The Taylor term
uses a Horner evaluation for tight rounding. Glitch/wave loops are
``lax.scan`` over the padded component axis — memory stays O(N_events)
regardless of component count, and XLA fuses the per-component updates.
"""

from __future__ import annotations

from math import factorial

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu.models import timing
from crimp_tpu.models.timing import N_FREQ_TERMS, TimingParams

SECONDS_PER_DAY = 86400.0

# 1/n! for the Taylor sum phi = dt * sum_k f[k]/(k+1)! dt^k.
_INV_FACTORIALS = np.array([1.0 / factorial(n + 1) for n in range(N_FREQ_TERMS)])


def taylor_phase(tm: TimingParams, time_mjd: jax.Array) -> jax.Array:
    """Taylor-expansion phase (cycles) at time_mjd."""
    dt = (time_mjd - tm.pepoch) * SECONDS_PER_DAY
    coeffs = tm.f * _INV_FACTORIALS
    # Horner: c0 + dt*(c1 + dt*(... )) then one final multiply by dt.
    acc = jnp.zeros_like(dt)
    for k in range(N_FREQ_TERMS - 1, -1, -1):
        acc = acc * dt + coeffs[k]
    return acc * dt


def glitch_phase(tm: TimingParams, time_mjd: jax.Array) -> jax.Array:
    """Summed glitch phase contributions (cycles) at time_mjd."""

    def add_one(carry, g):
        glep, glph, glf0, glf1, glf2, glf0d, gltd = g
        after = time_mjd >= glep
        # Mask before exp/polynomial so +inf-padded rows never produce NaN.
        dt_days = jnp.where(after, time_mjd - glep, 0.0)
        dt_sec = dt_days * SECONDS_PER_DAY
        recovery = jnp.where(
            gltd == 0.0,
            0.0,
            gltd * SECONDS_PER_DAY * (1.0 - jnp.exp(-dt_days / gltd)),
        )
        contrib = (
            glph
            + glf0 * dt_sec
            + 0.5 * glf1 * dt_sec**2
            + (1.0 / 6.0) * glf2 * dt_sec**3
            + glf0d * recovery
        )
        return carry + jnp.where(after, contrib, 0.0), None

    init = jnp.zeros_like(time_mjd)
    stacked = jnp.stack(
        [tm.glep, tm.glph, tm.glf0, tm.glf1, tm.glf2, tm.glf0d, tm.gltd], axis=-1
    )
    if tm.n_glitch == 0:
        return init
    total, _ = jax.lax.scan(add_one, init, stacked)
    return total


def wave_phase(tm: TimingParams, time_mjd: jax.Array) -> jax.Array:
    """Whitening-wave phase (cycles): seconds-residual sinusoids times F0."""
    if tm.n_wave == 0:
        return jnp.zeros_like(time_mjd)

    base = time_mjd - tm.wave_epoch

    def add_one(carry, kab):
        k, a, b = kab
        arg = k * tm.wave_om * base
        return carry + a * jnp.sin(arg) + b * jnp.cos(arg), None

    ks = jnp.arange(1, tm.n_wave + 1, dtype=time_mjd.dtype)
    total, _ = jax.lax.scan(
        add_one, jnp.zeros_like(time_mjd), jnp.stack([ks, tm.wave_a, tm.wave_b], axis=-1)
    )
    return total * tm.f[0]


def total_phase(tm: TimingParams, time_mjd: jax.Array) -> jax.Array:
    """Total model phase in cycles (Taylor + glitches + waves)."""
    return taylor_phase(tm, time_mjd) + glitch_phase(tm, time_mjd) + wave_phase(tm, time_mjd)


def phase_no_waves(tm: TimingParams, time_mjd: jax.Array) -> jax.Array:
    """Taylor + glitch phase only (integer-rotation anchoring uses this)."""
    return taylor_phase(tm, time_mjd) + glitch_phase(tm, time_mjd)


@jax.jit
def fold(tm: TimingParams, time_mjd: jax.Array):
    """(total_phase, cycle_folded_phase in [0,1)) for an array of MJDs."""
    total = total_phase(tm, time_mjd)
    return total, total - jnp.floor(total)


def fold_phases(time_mjd, timMod):
    """Host-friendly fold: accepts .par path / dict / TimingParams.

    Mirrors the reference entry point calcphase(timeMJD, timMod)
    (calcphase.py:152-176): returns (totalphases, cycleFoldedPhases) as numpy
    arrays with the input's shape (scalars in, scalars out).

    Precision: total phases are evaluated host-side (longdouble Taylor) and
    folded phases via the anchored device kernel (ops.anchored), because the
    TPU's emulated f64 cannot hold absolute phases of ~1e6 cycles to the
    <1e-7-cycle ToA budget. The absolute device kernel ``fold`` above remains
    for search/diagnostic uses where only relative phase matters.
    """
    from crimp_tpu.ops import anchored  # deferred: avoids an import cycle

    tm = timing.resolve(timMod)
    arr = np.atleast_1d(np.asarray(time_mjd, dtype=np.float64)).reshape(-1)
    shape = np.shape(time_mjd)
    total = anchored.host_total_phase(tm, arr).astype(np.float64)
    folded = anchored.fold_chunked(arr, tm)
    if shape == ():
        return total.item(), folded.item()
    return total.reshape(shape), folded.reshape(shape)


# Reference-named alias (calcphase.py:152).
calcphase = fold_phases
