"""Checkpointed, resumable periodicity scans.

The full-scale BASELINE workloads (1e6-trial 2-D grids, 1e8-event H-test
blind searches) run minutes-to-hours depending on hardware, and the
accelerator can disappear mid-run (preemption; a wedged relay — the
round-3 failure mode). The trial axis is embarrassingly parallel, so a
scan is naturally a sequence of independent trial chunks: this module
persists each chunk's result as it completes and recomputes only the
missing ones on restart.

Layout of a checkpoint store (a directory):

    manifest.json   problem fingerprint (event hash, grid, nharm, fdots,
                    chunking) — resume REFUSES a store whose fingerprint
                    does not match, so stale chunks can never mix into a
                    different problem's result
    chunk_00042.npy power rows for trial chunk 42, shape (n_fdot, k)

Chunks are written atomically (tmp + rename). The statistic is identical
to the unchunked kernels: each chunk is a contiguous frequency range, so
the uniform-grid fast path applies per chunk (same per-tile f64-row
decomposition; chunk boundaries align to the trial grid).

Reference parity note: the reference has no resumable scans (its serial
loops just rerun, periodsearch.py:63-125); this is TPU-native
infrastructure in the spirit of SURVEY §5's checkpoint/resume row.
"""

from __future__ import annotations

import hashlib
import json
import logging
import pathlib

import numpy as np

from crimp_tpu import obs, resilience
from crimp_tpu.resilience import faultinject

CHUNK_TRIALS = 50_000


def _fingerprint(times: np.ndarray, freqs: np.ndarray, fdots: np.ndarray,
                 nharm: int, chunk_trials: int, fddots=None,
                 semicoherent: int = 0) -> dict:
    t = np.ascontiguousarray(np.asarray(times, dtype=np.float64))
    fp = {
        # version is the KERNEL-SEMANTICS version: bump it whenever the
        # statistic computed per chunk changes meaning/precision, so chunks
        # from the old kernel can never mix into a post-fix result. v2:
        # floor-based centered_frac phase reduction (the v1 round-based
        # reduction fed out-of-range arguments to the poly-trig path —
        # r4's all-NaN on-chip config-5). v3: shared-row 2-D kernel
        # (harmonic_sums_uniform_2d) — ~2-ulp f32 combine difference per
        # phase vs the per-fdot v2 path.
        "version": 3,
        "n_events": int(t.shape[0]),
        "events_sha256": hashlib.sha256(t.tobytes()).hexdigest(),
        "n_freq": int(len(freqs)),
        "f_first": float(freqs[0]),
        "f_last": float(freqs[-1]),
        # full-grid hash, not just endpoints: a NON-uniform grid sharing
        # n/first/last with a uniform one must not adopt a store pinned to
        # grid_fastpath=True (its chunks would be a different statistic and
        # _compute_chunk would index uniform_grid()'s None)
        "freqs_sha256": hashlib.sha256(
            np.ascontiguousarray(np.asarray(freqs, dtype=np.float64)).tobytes()
        ).hexdigest(),
        "fdots": [float(f) for f in np.atleast_1d(fdots)],
        "nharm": int(nharm),
        "chunk_trials": int(chunk_trials),
    }
    # 3-D / semi-coherent keys only when the scan uses them, so every 2-D
    # store written before the cube kernels landed keeps its fingerprint
    if fddots is not None:
        fp["fddots"] = [float(f) for f in np.atleast_1d(fddots)]
    if semicoherent:
        fp["semicoherent"] = int(semicoherent)
    return fp


class ResumableScan:
    """Z^2_n over a (fdot x frequency) grid, checkpointed per trial chunk.

    ``fdots=None`` gives the 1-D scan (one all-zero fdot row, squeezed on
    return); ``fddots`` extends it to the (fddot x fdot x freq) cube
    (chunks hold the flattened (n_fddot*n_fdot, k) rows; ``run`` returns
    the cube), and ``semicoherent=S`` computes each cube chunk as the
    S-segment incoherent stack (ops/semicoherent; uniform grid required).
    ``store=None`` disables checkpointing entirely (pure
    chunked compute). Usage::

        scan = ResumableScan(times_sec, freqs, nharm=2, store="ckpt_dir")
        power = scan.run()      # computes missing chunks, returns (n_freq,)
    """

    def __init__(self, times, freqs, nharm: int = 2, fdots=None, fddots=None,
                 store: str | None = None, chunk_trials: int = CHUNK_TRIALS,
                 poly: bool | None = None, statistic: str = "z2",
                 semicoherent: int = 0):
        if statistic not in ("z2", "h"):
            raise ValueError(f"statistic must be 'z2' or 'h', got {statistic!r}")
        if statistic == "h" and (fdots is not None or fddots is not None):
            raise ValueError("the H-test scan is 1-D (fdots/fddots unsupported)")
        if semicoherent and fddots is None:
            raise ValueError(
                "semicoherent stacking is the cube scan's mode (pass fddots)")
        self.times = np.asarray(times, dtype=np.float64)
        self.freqs = np.asarray(freqs, dtype=np.float64)
        self.nharm = int(nharm)
        self.statistic = statistic
        self._squeeze = fdots is None and fddots is None
        self.fdots = np.zeros(1) if fdots is None else np.atleast_1d(
            np.asarray(fdots, dtype=np.float64))
        self.fddots = None if fddots is None else np.atleast_1d(
            np.asarray(fddots, dtype=np.float64))
        self.semicoherent = int(semicoherent)
        self.chunk_trials = int(chunk_trials)
        from crimp_tpu.ops import fasttrig, search

        if self.semicoherent and search.uniform_grid(self.freqs) is None:
            raise ValueError(
                "semi-coherent scans need a uniform frequency grid")

        # Resolve every numeric-mode knob NOW and pin it in the store
        # fingerprint: chunks computed under different trig/precision modes
        # (poly flipped between runs, fast path toggled, blocks re-tuned)
        # must never silently mix into one power array.
        self._poly_explicit = poly is not None
        self.poly = fasttrig.poly_trig_enabled(poly)
        self._fastpath = (search.uniform_grid(self.freqs) is not None
                          and search.grid_fastpath_enabled(self.nharm))
        # Block tiling resolves through the autotuner ONCE per instance
        # (explicit CRIMP_TPU_GRID_BLOCKS > cached winner > static
        # defaults) and is pinned in the store fingerprint like the trig
        # modes: every chunk of a store is computed under one tiling.
        from crimp_tpu.ops import autotune

        # The factorized-kernel knob is numeric mode too (the matmul path
        # has its own deviation budget), so it resolves once and pins like
        # poly/fastpath: [on/off, reseed stride, bf16 operands].
        self._mxu_explicit = autotune._env_nonneg_int(
            autotune.GRID_MXU_ENV, valid=(0, 1)) is not None
        if self._fastpath:
            n_tr = min(len(self.freqs), self.chunk_trials)
            if self.fddots is not None:
                # cube scans bucket the knob at the per-chunk CUBE trial
                # count — the workload the bench_jerk A/B actually gated
                r = autotune.resolve_grid3d_mxu(
                    len(self.times),
                    n_tr * len(self.fdots) * len(self.fddots),
                    poly=self.poly)
            else:
                r = autotune.resolve_grid_mxu(len(self.times), n_tr,
                                              poly=self.poly)
            self._mxu = bool(r["grid_mxu"])
            self._mxu_reseed = int(r["reseed"])
            self._mxu_bf16 = bool(r["mxu_bf16"])
        else:
            self._mxu = False
            self._mxu_reseed = autotune.GRID_MXU_RESEED_DEFAULT
            self._mxu_bf16 = False
        if self._fastpath:
            kernel = "grid_mxu" if self._mxu else (
                "grid3d" if self.fddots is not None else "grid")
        else:
            kernel = "general"
        self._blocks = autotune.resolve_blocks(
            kernel, len(self.times), min(len(self.freqs), self.chunk_trials),
            poly=self.poly,
        )
        self._blocks_explicit = autotune.env_blocks_override(kernel) is not None
        # The delta-fold engine is numeric mode as well: a driver session
        # that refolds via cached fold products (ops/deltafold.py) works
        # within the engine's precision budget, one that re-anchors exactly
        # does not — pin [on/off, budget cycles] so resumed chunks and any
        # fold products the session reuses stay coherent.
        self._deltafold_explicit = autotune._env_nonneg_int(
            autotune.DELTA_FOLD_ENV, valid=(0, 1)) is not None
        r = autotune.resolve_delta_fold(len(self.times))
        self._delta_fold = bool(r["delta_fold"])
        self._delta_fold_budget = float(r["budget"])
        self._numeric_mode = {
            "poly_trig": bool(self.poly),
            "grid_fastpath": bool(self._fastpath),
            "grid_blocks": list(self._blocks),
            "grid_mxu": [int(self._mxu), self._mxu_reseed,
                         int(self._mxu_bf16)],
            "delta_fold": [int(self._delta_fold), self._delta_fold_budget],
            # the delta-basis MCMC likelihood never runs inside a grid
            # scan, but it shares the session's numeric-mode fingerprint
            # (GL003): a store resumed under a different sampler mode must
            # be visibly incompatible rather than silently mixed
            "mcmc_delta": [
                int(autotune.resolve_mcmc_delta(len(self.times))["mcmc_delta"])
            ],
        }
        self._times_dev = None  # lazy device-resident copy of the events
        self.store = pathlib.Path(store) if store is not None else None
        self.n_chunks = -(-len(self.freqs) // self.chunk_trials)
        if self.store is not None:
            self._open_store()

    # -- store management ---------------------------------------------------

    def _open_store(self) -> None:
        fp = _fingerprint(self.times, self.freqs, self.fdots, self.nharm,
                          self.chunk_trials, fddots=self.fddots,
                          semicoherent=self.semicoherent)
        fp["statistic"] = self.statistic
        fp["numeric_mode"] = self._numeric_mode
        manifest = self.store / "manifest.json"
        if manifest.exists():
            existing = json.loads(manifest.read_text())
            if existing != fp:
                # Same problem + same kernel version, but the poly-trig /
                # fast-path PREFERENCES resolved differently (an env knob
                # or an auto threshold changed between sessions): adopt the
                # store's pinned modes so completed chunks stay usable —
                # the result is coherent under the store's mode, which is
                # what "resume" means. Block tiling adopts the same way (a
                # re-tuned autotuner winner is a preference drift, not a
                # different problem — the instance pins whatever tiling the
                # store was computed under). Anything else (different
                # problem, different kernel version, an EXPLICIT env/ctor
                # knob that conflicts) still refuses.
                mode = existing.get("numeric_mode", {})
                store_blocks = mode.get("grid_blocks")
                blocks_ok = (
                    isinstance(store_blocks, list) and len(store_blocks) == 2
                    and all(isinstance(b, int) and b > 0 for b in store_blocks)
                )
                # Stores written before the factorized kernel landed carry
                # no grid_mxu pin; they were computed with it off, so the
                # adoptable default is exactly that.
                from crimp_tpu.ops import autotune

                store_mxu = mode.get(
                    "grid_mxu", [0, autotune.GRID_MXU_RESEED_DEFAULT, 0])
                mxu_ok = (
                    isinstance(store_mxu, list) and len(store_mxu) == 3
                    and store_mxu[0] in (0, 1) and store_mxu[2] in (0, 1)
                    and isinstance(store_mxu[1], int) and store_mxu[1] > 0
                )
                # Stores written before the delta-fold engine landed carry
                # no pin; they were computed with it off at the default
                # budget, so that is the adoptable default.
                store_df = mode.get(
                    "delta_fold", [0, autotune.DELTA_FOLD_BUDGET_DEFAULT])
                df_ok = (
                    isinstance(store_df, list) and len(store_df) == 2
                    and store_df[0] in (0, 1)
                    and isinstance(store_df[1], (int, float))
                    and 0.0 < store_df[1] < float("inf")
                )
                adoptable = (
                    {k: v for k, v in existing.items() if k != "numeric_mode"}
                    == {k: v for k, v in fp.items() if k != "numeric_mode"}
                    # a malformed/legacy manifest missing the pinned modes
                    # is not adoptable — there is no mode to adopt
                    and "poly_trig" in mode and "grid_fastpath" in mode
                    and blocks_ok
                    # an EXPLICIT constructor poly= (or CRIMP_TPU_GRID_BLOCKS
                    # env) that conflicts with the store's pinned mode is a
                    # real mismatch, not a preference drift — silently
                    # adopting would hand a poly-validation run hw-trig
                    # chunks (or a hand-pinned-tiling run re-tuned chunks)
                    and not (self._poly_explicit
                             and bool(mode.get("poly_trig")) != self.poly)
                    and not (self._blocks_explicit
                             and store_blocks != list(self._blocks))
                    and mxu_ok
                    # same rule for an explicit CRIMP_TPU_GRID_MXU: a run
                    # pinned to the factorized (or exact) path must not
                    # silently inherit the other mode's chunks
                    and not (self._mxu_explicit
                             and bool(store_mxu[0]) != self._mxu)
                    and df_ok
                    # and for an explicit CRIMP_TPU_DELTA_FOLD: an exact-fold
                    # run must not silently inherit delta-refolded products
                    and not (self._deltafold_explicit
                             and bool(store_df[0]) != self._delta_fold)
                )
                if not adoptable:
                    raise ValueError(
                        f"checkpoint store {self.store} belongs to a different "
                        "problem (manifest fingerprint mismatch); refusing to mix "
                        "chunks — use a fresh store directory"
                    )
                # adopting must be VISIBLE: a run launched with (say)
                # CRIMP_TPU_POLY_TRIG=1 that resumes an hw-trig store would
                # otherwise compute hw trig with no indication why
                logging.getLogger(__name__).warning(
                    "resuming %s with the store's pinned numeric mode %s "
                    "(freshly resolved preferences were %s)",
                    self.store, mode, self._numeric_mode,
                )
                self.poly = bool(mode["poly_trig"])
                self._fastpath = bool(mode["grid_fastpath"])
                self._blocks = (int(store_blocks[0]), int(store_blocks[1]))
                self._mxu = bool(store_mxu[0])
                self._mxu_reseed = int(store_mxu[1])
                self._mxu_bf16 = bool(store_mxu[2])
                self._delta_fold = bool(store_df[0])
                self._delta_fold_budget = float(store_df[1])
                self._numeric_mode = mode
        else:
            self.store.mkdir(parents=True, exist_ok=True)
            tmp = manifest.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(fp, indent=2))
            tmp.rename(manifest)

    def _chunk_path(self, i: int) -> pathlib.Path:
        return self.store / f"chunk_{i:05d}.npy"

    def done_chunks(self) -> list[int]:
        if self.store is None:
            return []
        return sorted(
            int(p.stem.split("_")[1]) for p in self.store.glob("chunk_*.npy")
        )

    # -- compute ------------------------------------------------------------

    def _mesh(self, n_trials_chunk: int):
        """Auto-shard mesh for one chunk, mirroring PeriodSearch._mesh."""
        from crimp_tpu.ops.search import MIN_SHARD_PAIRS
        from crimp_tpu.parallel import mesh as pmesh

        if self.semicoherent:
            # the semi-coherent stack drives its own per-segment dispatch
            return None
        pairs = len(self.times) * n_trials_chunk * len(self.fdots)
        if self.fddots is not None:
            pairs *= len(self.fddots)
        if pairs < MIN_SHARD_PAIRS:
            return None
        return pmesh.auto_mesh()

    def _times_device(self):
        """Events on device, uploaded ONCE per instance (the per-chunk
        jnp.asarray re-upload was the resumable driver's transfer hotspot)."""
        if self._times_dev is None:
            import jax

            self._times_dev = jax.device_put(self.times)
        return self._times_dev

    def _stream(self) -> bool:
        """Whether fast-path chunks should take the double-buffered
        streamed kernels (big event sets only; CRIMP_TPU_STREAM_MIN_EVENTS
        governs, 0/off disables)."""
        from crimp_tpu.ops import search

        if not self._fastpath:
            return False
        threshold = search.stream_min_events()
        return threshold is not None and len(self.times) >= threshold

    def _load_chunk(self, i: int) -> np.ndarray | None:
        """A checkpointed chunk's rows, validated — or None after
        quarantining a torn one.

        A resumed store is an unaudited input: a truncated or bit-rotted
        chunk file must be recomputed, not concatenated into the power
        grid or allowed to crash the whole resume. Shape is fully
        determined by the scan geometry, so validation is exact:
        (n_rows, chunk width), floating dtype."""
        path = self._chunk_path(i)
        lo = i * self.chunk_trials
        width = min(self.chunk_trials, len(self.freqs) - lo)
        n_rows = 1 if self.statistic == "h" else len(self.fdots)
        if self.fddots is not None:
            n_rows = len(self.fdots) * len(self.fddots)
        try:
            faultinject.fire("scan_chunk")
            arr = np.load(path, allow_pickle=False)
        except (OSError, ValueError, EOFError, resilience.CacheCorruptError):
            resilience.quarantine_file(path, label="scan_chunk")
            return None
        if arr.ndim != 2 or arr.shape != (n_rows, width) \
                or not np.issubdtype(arr.dtype, np.floating):
            resilience.quarantine_file(path, label="scan_chunk")
            return None
        return arr

    def _compute_chunk_device(self, i: int):
        """(n_fdot, k) Z^2 (or (1, k) H) rows for trial chunk i, still on
        device (materialized by _compute_chunk / the pipelined run loop).

        Same dispatch as PeriodSearch: multi-device hosts auto-shard the
        event axis (psum combines), single-device hosts take the blockwise
        kernels; the uniform-grid fast path applies per chunk either way
        (a chunk is a contiguous range of the full grid). Above the
        streaming threshold the fast-path kernels stream the event axis
        chunkwise with double-buffered transfers (bit-identical sums)."""
        import jax.numpy as jnp

        from crimp_tpu.ops import search

        faultinject.fire("scan_chunk")
        lo = i * self.chunk_trials
        chunk = self.freqs[lo:lo + self.chunk_trials]
        poly = self.poly
        eb, tb = self._blocks
        # the PINNED factorized-kernel mode (part of the store fingerprint)
        mx, rs, b16 = self._mxu, self._mxu_reseed, self._mxu_bf16
        mesh = self._mesh(len(chunk))
        if self.fddots is not None:
            # cube scan: (n_fddot * n_fdot, k) rows per chunk, flattened in
            # the kernel's (fddot, fdot) row-major order; run() reshapes
            k = len(chunk)
            if self.semicoherent:
                from crimp_tpu.ops import semicoherent as semi

                grid = search.uniform_grid(self.freqs)
                rows = semi.semicoherent_z2_grid(
                    self.times, float(chunk[0]), grid[1], k, self.fdots,
                    self.fddots, nharm=self.nharm,
                    n_segments=self.semicoherent, poly=poly,
                    event_block=eb, trial_block=tb, mxu=mx, reseed=rs,
                    mxu_bf16=b16)
                return rows.reshape(-1, k)
            if mesh is not None:
                from crimp_tpu.parallel import mesh as pmesh

                rows = pmesh.z2_3d_sharded(
                    self.times, chunk, self.fdots, self.fddots, self.nharm,
                    mesh, use_fastpath=self._fastpath, poly=poly,
                    use_mxu=mx, reseed=rs, mxu_bf16=b16)
                return np.asarray(rows).reshape(-1, k)
            if self._fastpath:
                grid = search.uniform_grid(self.freqs)
                rows = search.z2_power_3d_grid(
                    self._times_device(), float(chunk[0]), grid[1], k,
                    jnp.asarray(self.fdots), jnp.asarray(self.fddots),
                    self.nharm, event_block=eb, trial_block=tb, poly=poly,
                    mxu=mx, reseed=rs, mxu_bf16=b16)
            else:
                rows = search.z2_power_3d(
                    self._times_device(), jnp.asarray(chunk),
                    jnp.asarray(self.fdots), jnp.asarray(self.fddots),
                    self.nharm, event_block=eb, trial_block=tb, poly=poly)
            return rows.reshape(-1, k)
        if mesh is not None:
            from crimp_tpu.parallel import mesh as pmesh

            # pass the PINNED fast-path decision (it is part of the store
            # fingerprint), not the auto default
            if self.statistic == "h":
                rows = pmesh.h_sharded(self.times, chunk, self.nharm,
                                       mesh=mesh, poly=poly,
                                       use_fastpath=self._fastpath,
                                       use_mxu=mx, reseed=rs,
                                       mxu_bf16=b16)[None, :]
            else:
                rows = pmesh.z2_2d_sharded(self.times, chunk, self.fdots,
                                           self.nharm, mesh=mesh, poly=poly,
                                           use_fastpath=self._fastpath,
                                           use_mxu=mx, reseed=rs,
                                           mxu_bf16=b16)
            return rows
        grid = search.uniform_grid(self.freqs)  # chunk grids inherit df
        stream = self._stream()
        if self.statistic == "h":
            if stream:
                rows = search.h_power_grid_streamed(
                    self.times, float(chunk[0]), grid[1], len(chunk),
                    self.nharm, event_block=eb, trial_block=tb, poly=poly,
                    mxu=mx, reseed=rs, mxu_bf16=b16,
                )[None, :]
            elif self._fastpath:
                rows = search.h_power_grid(
                    self._times_device(), float(chunk[0]), grid[1], len(chunk),
                    self.nharm, event_block=eb, trial_block=tb, poly=poly,
                    mxu=mx, reseed=rs, mxu_bf16=b16,
                )[None, :]
            else:
                rows = search.h_power(
                    self._times_device(), jnp.asarray(chunk), self.nharm,
                    event_block=eb, trial_block=tb, poly=poly,
                )[None, :]
        elif stream:
            rows = search.z2_power_2d_grid_streamed(
                self.times, float(chunk[0]), grid[1], len(chunk),
                self.fdots, self.nharm, event_block=eb, trial_block=tb,
                poly=poly, mxu=mx, reseed=rs, mxu_bf16=b16,
            )
        elif self._fastpath:
            rows = search.z2_power_2d_grid(
                self._times_device(), float(chunk[0]), grid[1], len(chunk),
                jnp.asarray(self.fdots), self.nharm, event_block=eb,
                trial_block=tb, poly=poly, mxu=mx, reseed=rs, mxu_bf16=b16,
            )
        else:
            rows = search.z2_power_2d(
                self._times_device(), jnp.asarray(chunk),
                jnp.asarray(self.fdots), self.nharm, event_block=eb,
                trial_block=tb, poly=poly,
            )
        return rows

    def _compute_chunk(self, i: int) -> np.ndarray:
        """Host-materialized rows for trial chunk i (sync entry point)."""
        return np.asarray(self._compute_chunk_device(i))

    def _finish_chunk(self, i: int, rows_dev, parts, progress) -> None:
        """Materialize + atomically checkpoint one computed chunk."""
        rows = np.asarray(rows_dev)
        if self.store is not None:
            tmp = self._chunk_path(i).with_suffix(".npy.tmp")
            with open(tmp, "wb") as fh:  # np.save(path) would append .npy
                np.save(fh, rows)
            tmp.rename(self._chunk_path(i))
        parts[i] = rows
        obs.counter_add("chunks_computed", 1)
        if progress is not None:
            progress(i, self.n_chunks)

    def run(self, progress=None) -> np.ndarray:
        """Compute all missing chunks (checkpointing each) and return the
        assembled (n_fdot, n_freq) power — or (n_freq,) for the 1-D scan.
        ``progress`` (optional callable) receives (chunk_index, n_chunks)
        after each chunk completes.

        The loop is pipelined: chunk i+1's kernels are DISPATCHED (async)
        before chunk i's result is pulled to the host and checkpointed, so
        the device computes while the host serializes — removing the
        per-chunk host sync of the naive compute->save loop. Checkpoint
        ordering is unchanged (chunk i is on disk before i+1's save
        starts), so a kill mid-run leaves the same resumable state.
        """
        with obs.run("resumable_scan", statistic=self.statistic,
                     n_chunks=self.n_chunks):
            obs.record_numeric_mode(self._numeric_mode)
            done = set(self.done_chunks())
            obs.counter_add("chunks_resumed", len(done))
            # seeded at 0 and incremented per checkpointed chunk in
            # _finish_chunk, so a killed run's salvaged manifest counts
            # the chunks that actually finished
            obs.counter_add("chunks_computed", 0)
            # heartbeats (progress/ETA events + the atomic sidecar) are
            # the default progress consumer; the caller's own callback
            # chains after each beat with the documented (i, n) signature
            progress = obs.heartbeat.scan_progress(
                base=len(done), total=self.n_chunks,
                label=f"{self.statistic}_chunks", echo=progress)
            parts: list[np.ndarray | None] = [None] * self.n_chunks
            pending: tuple[int, object] | None = None
            with obs.span("chunk_loop", kind="stage"):
                for i in range(self.n_chunks):
                    if i in done:
                        arr = self._load_chunk(i)
                        if arr is not None:
                            parts[i] = arr
                            continue
                        # torn chunk quarantined: fall through and recompute
                    rows_dev = resilience.retry_call(
                        lambda i=i: self._compute_chunk_device(i),
                        point="scan_chunk")
                    if pending is not None:
                        self._finish_chunk(pending[0], pending[1], parts, progress)
                    pending = (i, rows_dev)
                if pending is not None:
                    self._finish_chunk(pending[0], pending[1], parts, progress)
            power = np.concatenate(parts, axis=1)
            if self.fddots is not None:
                power = power.reshape(len(self.fddots), len(self.fdots), -1)
            return power[0] if self._squeeze else power
