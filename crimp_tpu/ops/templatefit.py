"""Binned maximum-likelihood template fitting (pulse-profile construction).

Replaces the reference's lmfit-BFGS fits (pulseprofile.py:295-564) with
scipy L-BFGS-B driving a jitted ``jax.value_and_grad`` of the Gaussian
binned NLL. The split is deliberate: the problem is tiny (≲ 20 parameters,
≲ 100 bins, run once per observation), so a robust host line search beats a
fixed-iteration on-device optimizer, while the objective+gradient stay
compiled. Box bounds (norm positivity, von Mises / Cauchy component
bounds) map directly onto L-BFGS-B's native bound support — the same
constraint semantics lmfit applies, so interior optima agree.

Free/frozen parameters follow the template 'vary' flags: the optimizer
works on the gathered free subvector; frozen entries stay at their inputs.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import scipy.optimize

from crimp_tpu.models.profiles import (
    CAUCHY,
    FOURIER,
    VONMISES,
    ProfileParams,
    binned_loglik,
)


def _flatten(params: ProfileParams) -> jnp.ndarray:
    return jnp.concatenate(
        [params.norm[None], params.amp, params.loc, params.wid]
    )


def _unflatten(vec: jnp.ndarray, template: ProfileParams) -> ProfileParams:
    K = template.n_comp
    return replace(
        template,
        norm=vec[0],
        amp=vec[1 : 1 + K],
        loc=vec[1 + K : 1 + 2 * K],
        wid=vec[1 + 2 * K : 1 + 3 * K],
    )


def _default_bounds(kind: str, x0: np.ndarray, K: int, max_rate: float):
    """(lo, hi) per flattened parameter, mirroring the reference's bounds
    (pulseprofile.py:315,402-406,493-497)."""
    lo = np.full_like(x0, -np.inf)
    hi = np.full_like(x0, np.inf)
    if kind == FOURIER:
        lo[0], hi[0] = 0.0, 1.0e6  # norm
    else:
        lo[0], hi[0] = 0.0, max(max_rate, 1e-6)
        lo[1 : 1 + K] = 0.0  # amps >= 0
        hi[1 : 1 + K] = np.inf
        lo[1 + K : 1 + 2 * K] = 0.0  # centroids in [0, 2pi]
        hi[1 + K : 1 + 2 * K] = 2 * np.pi
        lo[1 + 2 * K :] = 0.0  # widths >= 0
        hi[1 + 2 * K :] = np.inf
    return lo, hi


def fit_binned_template(
    kind: str,
    init: ProfileParams,
    bins: np.ndarray,
    rate: np.ndarray,
    rate_err: np.ndarray,
    vary: np.ndarray | None = None,
    maxiter: int = 2000,
):
    """Fit the binned profile; returns (best ProfileParams, chi2 dict).

    ``vary`` is a boolean flatten-ordered mask (norm, amps, locs, wids);
    None = all free (widths ignored for Fourier).
    """
    x0 = np.asarray(_flatten(init))
    K = init.n_comp
    n_params = x0.shape[0]
    if vary is None:
        vary = np.ones(n_params, dtype=bool)
    vary = np.asarray(vary, dtype=bool).copy()
    if kind == FOURIER:
        vary[1 + 2 * K :] = False  # widths unused

    free_idx = np.nonzero(vary)[0]
    lo, hi = _default_bounds(kind, x0, K, float(np.max(rate)))

    bins_j = jnp.asarray(bins)
    rate_j = jnp.asarray(rate)
    err_j = jnp.asarray(rate_err)
    x0_j = jnp.asarray(x0)
    free_idx_j = jnp.asarray(free_idx)

    @jax.jit
    def nll_and_grad(x_free):
        def nll(xf):
            vec = x0_j.at[free_idx_j].set(xf)
            params = _unflatten(vec, init)
            return -binned_loglik(kind, params, bins_j, rate_j, err_j)

        return jax.value_and_grad(nll)(x_free)

    def objective(x_free):
        v, g = nll_and_grad(jnp.asarray(x_free))
        return float(v), np.asarray(g, dtype=np.float64)

    result = scipy.optimize.minimize(
        objective,
        x0[free_idx],
        jac=True,
        method="L-BFGS-B",
        bounds=list(zip(lo[free_idx], hi[free_idx])),
        options={"maxiter": maxiter},
    )
    vec = x0_j.at[free_idx_j].set(jnp.asarray(result.x))
    best = _unflatten(vec, init)

    from crimp_tpu.models.profiles import curve

    model = np.asarray(curve(kind, best, bins_j))
    chi2 = float(np.sum((rate - model) ** 2 / rate_err**2))
    dof = len(rate) - int(vary.sum())
    stats = {"chi2": chi2, "dof": dof, "redchi2": chi2 / dof}
    return best, model, stats
