"""Binned maximum-likelihood template fitting (pulse-profile construction).

Replaces the reference's lmfit-BFGS fits (pulseprofile.py:295-564) with a
jitted ``jax.scipy.optimize.minimize`` BFGS on the Gaussian binned NLL.
Box bounds (von Mises / Cauchy component bounds, norm positivity) are
honored through a sigmoid reparameterization — the same mechanism lmfit
uses for bounded gradient fits, so interior optima agree.

Free/frozen parameters follow the template 'vary' flags: the optimizer
works on the gathered free subvector; frozen entries stay at their inputs.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu.models.profiles import (
    CAUCHY,
    FOURIER,
    VONMISES,
    ProfileParams,
    binned_loglik,
)


def _flatten(params: ProfileParams) -> jnp.ndarray:
    return jnp.concatenate(
        [params.norm[None], params.amp, params.loc, params.wid]
    )


def _unflatten(vec: jnp.ndarray, template: ProfileParams) -> ProfileParams:
    K = template.n_comp
    return replace(
        template,
        norm=vec[0],
        amp=vec[1 : 1 + K],
        loc=vec[1 + K : 1 + 2 * K],
        wid=vec[1 + 2 * K : 1 + 3 * K],
    )


def _default_bounds(kind: str, x0: np.ndarray, K: int, max_rate: float):
    """(lo, hi) per flattened parameter, mirroring the reference's bounds
    (pulseprofile.py:315,402-406,493-497)."""
    lo = np.full_like(x0, -np.inf)
    hi = np.full_like(x0, np.inf)
    if kind == FOURIER:
        lo[0], hi[0] = 0.0, 1.0e6  # norm
    else:
        lo[0], hi[0] = 0.0, max(max_rate, 1e-6)
        lo[1 : 1 + K] = 0.0  # amps >= 0
        hi[1 : 1 + K] = np.inf
        lo[1 + K : 1 + 2 * K] = 0.0  # centroids in [0, 2pi]
        hi[1 + K : 1 + 2 * K] = 2 * np.pi
        lo[1 + 2 * K :] = 0.0  # widths >= 0
        hi[1 + 2 * K :] = np.inf
    return lo, hi


def fit_binned_template(
    kind: str,
    init: ProfileParams,
    bins: np.ndarray,
    rate: np.ndarray,
    rate_err: np.ndarray,
    vary: np.ndarray | None = None,
    maxiter: int = 2000,
):
    """Fit the binned profile; returns (best ProfileParams, chi2 dict).

    ``vary`` is a boolean flatten-ordered mask (norm, amps, locs, wids);
    None = all free (widths ignored for Fourier).
    """
    x0 = np.asarray(_flatten(init))
    K = init.n_comp
    n_params = x0.shape[0]
    if vary is None:
        vary = np.ones(n_params, dtype=bool)
    vary = np.asarray(vary, dtype=bool).copy()
    if kind == FOURIER:
        vary[1 + 2 * K :] = False  # widths unused

    free_idx = np.nonzero(vary)[0]
    lo, hi = _default_bounds(kind, x0, K, float(np.max(rate)))

    # Sigmoid-transform doubly-bounded free params; shift-log for one-sided.
    lo_f = jnp.asarray(lo[free_idx])
    hi_f = jnp.asarray(hi[free_idx])
    both = np.isfinite(lo[free_idx]) & np.isfinite(hi[free_idx])
    lower_only = np.isfinite(lo[free_idx]) & ~np.isfinite(hi[free_idx])
    both = jnp.asarray(both)
    lower_only = jnp.asarray(lower_only)

    def to_bounded(u):
        x_sig = lo_f + (hi_f - lo_f) * jax.nn.sigmoid(u)
        x_log = lo_f + jnp.exp(jnp.clip(u, -700, 700))
        return jnp.where(both, x_sig, jnp.where(lower_only, x_log, u))

    def to_unbounded(x):
        frac = jnp.clip((x - lo_f) / jnp.where(both, hi_f - lo_f, 1.0), 1e-9, 1 - 1e-9)
        u_sig = jnp.log(frac) - jnp.log1p(-frac)
        u_log = jnp.log(jnp.clip(x - lo_f, 1e-12))
        return jnp.where(both, u_sig, jnp.where(lower_only, u_log, x))

    bins_j = jnp.asarray(bins)
    rate_j = jnp.asarray(rate)
    err_j = jnp.asarray(rate_err)
    x0_j = jnp.asarray(x0)

    def nll(u_free):
        x_free = to_bounded(u_free)
        vec = x0_j.at[jnp.asarray(free_idx)].set(x_free)
        params = _unflatten(vec, init)
        return -binned_loglik(kind, params, bins_j, rate_j, err_j)

    u0 = to_unbounded(jnp.asarray(x0[free_idx]))
    result = jax.scipy.optimize.minimize(nll, u0, method="BFGS", options={"maxiter": maxiter})
    x_free = to_bounded(result.x)
    vec = x0_j.at[jnp.asarray(free_idx)].set(x_free)
    best = _unflatten(vec, init)

    from crimp_tpu.models.profiles import curve

    model = np.asarray(curve(kind, best, bins_j))
    chi2 = float(np.sum((rate - model) ** 2 / rate_err**2))
    dof = len(rate) - int(vary.sum())
    stats = {"chi2": chi2, "dof": dof, "redchi2": chi2 / dof}
    return best, model, stats
