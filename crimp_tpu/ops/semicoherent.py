"""Semi-coherent stacked searches over the (f, fdot, fddot) cube.

The coherent cube kernel (ops/search.py harmonic_sums_uniform_3d) pays for
fddot resolution proportional to T_obs^3: the phase drift a trial must track
grows with the CUBE of the coherent span. Splitting T_obs into S
equal-duration segments, scanning each coherently at the GLOBAL phase model,
and summing the per-segment Z^2 terms incoherently keeps the (f, fdot)
sensitivity while the fddot spacing needed to keep each SEGMENT phase-
coherent coarsens by ~S^2 relative to the coherent cube (classic stack-slide
/ Hough tradeoff, astro-ph/0112006) — so a matched-coverage scan runs with
~S^2 fewer fddot trials at the cost of a sqrt(S)-ish sensitivity haircut.

Numeric contract (docs/parity.md):

- every per-segment statistic is computed at the EXACT global phase model —
  segment times are NOT re-centered, so a stack with ``fddots=[0.0]`` probes
  the same trial family as the coherent kernels;
- ``stack="incoherent"`` sums per-segment Z^2 in fixed segment order and is
  BITWISE-identical to a hand-written per-segment loop over the same padded
  rows (pinned in tests/test_semicoherent.py);
- ``stack="coherent"`` sums the per-segment trig sums (a pure re-blocking of
  the event reduction) and matches the monolithic coherent kernel to
  reduction-order tolerance — the identity the stacking parity test leans on.

Per-segment work runs through search._grid3d_sums_dispatch with the segment
validity mask as event weights, so the MXU factorization, block autotuning
and the grid resilience ladder all apply per segment; every segment row is
padded to one common length and the kernel compiles ONCE for the whole
stack.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from crimp_tpu import obs
from crimp_tpu.ops import search


def split_segments(times, n_segments: int):
    """Pad ``times`` into ``n_segments`` equal-DURATION rows + 0/1 weights.

    Returns (seg_times, seg_weights), both (S, Nmax) f64; rows are padded
    with zeros carrying zero weight. Segments are equal spans of the
    observation (np.linspace edges), not equal event counts — the phase
    model is a function of time, so duration is what bounds per-segment
    coherence loss. ``times`` must be sorted (the reference event lists
    are); raises ValueError otherwise.
    """
    t = np.asarray(times, dtype=np.float64)
    n_segments = int(n_segments)
    if n_segments < 1:
        raise ValueError(f"n_segments must be >= 1, got {n_segments}")
    if t.ndim != 1 or t.size == 0:
        raise ValueError("split_segments needs a non-empty 1-D time array")
    if np.any(np.diff(t) < 0):
        raise ValueError("split_segments needs time-sorted events")
    edges = np.linspace(t[0], t[-1], n_segments + 1)
    # searchsorted on interior edges: each event lands in exactly one
    # segment; the final edge is inclusive by construction
    bounds = np.searchsorted(t, edges[1:-1], side="left")
    starts = np.concatenate([[0], bounds])
    stops = np.concatenate([bounds, [t.size]])
    n_max = max(1, int(np.max(stops - starts)))
    seg_times = np.zeros((n_segments, n_max), dtype=np.float64)
    seg_weights = np.zeros((n_segments, n_max), dtype=np.float64)
    for i, (lo, hi) in enumerate(zip(starts, stops)):
        seg_times[i, : hi - lo] = t[lo:hi]
        seg_weights[i, : hi - lo] = 1.0
    return seg_times, seg_weights


def stacked_sums_grid(seg_times, seg_weights, f0, df, n_freq, fdots, fddots,
                      nharm: int = 2, poly: bool = False,
                      event_block: int | None = None,
                      trial_block: int | None = None,
                      mxu: bool | None = None, reseed: int | None = None,
                      mxu_bf16: bool | None = None):
    """Per-segment cube trig sums at the global phase model.

    Returns (c, s, counts): c/s are (S, n_fddot, n_fdot, nharm, n_freq)
    jax arrays, counts the (S,) valid-event totals. One python loop over
    identically-shaped padded rows -> one kernel compile; each iteration
    goes through the full grid dispatch (MXU knob, autotuned blocks,
    resilience ladder), with the pad mask as event weights.
    """
    seg_times = np.asarray(seg_times, dtype=np.float64)
    seg_weights = np.asarray(seg_weights, dtype=np.float64)
    counts = seg_weights.sum(axis=1)
    c_rows, s_rows = [], []
    for i in range(seg_times.shape[0]):
        c, s, _ = search._grid3d_sums_dispatch(
            seg_times[i], f0, df, n_freq, fdots, fddots, nharm, poly,
            event_block, trial_block, mxu, reseed, mxu_bf16,
            weights=jnp.asarray(seg_weights[i]),
        )
        c_rows.append(c)
        s_rows.append(s)
    return jnp.stack(c_rows), jnp.stack(s_rows), counts


def semicoherent_z2_grid(times, f0, df, n_freq, fdots, fddots,
                         nharm: int = 2, n_segments: int = 8,
                         stack: str = "incoherent", poly: bool = False,
                         event_block: int | None = None,
                         trial_block: int | None = None,
                         mxu: bool | None = None, reseed: int | None = None,
                         mxu_bf16: bool | None = None, mesh=None):
    """Stacked Z^2 over the uniform (fddot, fdot, freq) cube.

    ``stack="incoherent"`` (the semi-coherent statistic) sums per-segment
    Z^2 terms, each normalized by its own event count, in fixed segment
    order; ``stack="coherent"`` sums the trig sums first (equivalent to the
    monolithic coherent kernel up to reduction order — the parity bridge,
    not a faster path). Returns a (n_fddot, n_fdot, n_freq) jax array.

    Passing an explicit ``mesh`` routes the incoherent stack through the
    segment-sharded kernel (parallel/mesh.semicoherent_stack_sharded);
    cross-segment order then follows the shard-local-sum + psum schedule,
    so sharded output is reduction-order-tolerant, not bitwise.
    """
    if stack not in ("incoherent", "coherent"):
        raise ValueError(f"unknown stack mode {stack!r}")
    seg_times, seg_weights = split_segments(times, n_segments)
    n_cube = int(n_freq) * len(np.atleast_1d(fdots)) * len(np.atleast_1d(fddots))
    obs.counter_add("semicoherent_segments", int(n_segments))
    with obs.span("semicoherent_scan", n_trials=n_cube,
                  n_segments=int(n_segments), n_events=int(np.size(times)),
                  nharm=nharm, stack=stack):
        if mesh is not None and stack == "incoherent":
            from crimp_tpu.parallel import mesh as pmesh

            n_dev = int(np.prod(list(mesh.shape.values())))
            pad = (-len(seg_times)) % n_dev
            if pad:
                seg_times = np.pad(seg_times, ((0, pad), (0, 0)))
                seg_weights = np.pad(seg_weights, ((0, pad), (0, 0)))
            eb, tb = search.resolve_blocks(
                "grid3d", seg_times.shape[1], n_freq, poly,
                event_block, trial_block)
            return pmesh.semicoherent_stack_sharded(
                seg_times, seg_weights, f0, df, n_freq, fdots, fddots,
                nharm, mesh, event_block=eb, trial_block=tb, poly=poly)
        c, s, counts = stacked_sums_grid(
            seg_times, seg_weights, f0, df, n_freq, fdots, fddots, nharm,
            poly, event_block, trial_block, mxu, reseed, mxu_bf16)
        if stack == "coherent":
            c_tot = jnp.sum(c, axis=0)
            s_tot = jnp.sum(s, axis=0)
            return jnp.sum(
                search.z2_from_sums(c_tot, s_tot, float(counts.sum())),
                axis=2)
        # fixed ascending segment order — the hand-loop bitwise contract
        power = None
        for i in range(c.shape[0]):
            term = jnp.sum(
                search.z2_from_sums(c[i], s[i], max(float(counts[i]), 1.0)),
                axis=2)
            power = term if power is None else power + term
        return power


def stacked_power_from_phases(phase_segments, nharm: int = 2,
                              statistic: str = "z2",
                              stack: str = "incoherent",
                              poly: bool = False):
    """Stacked Z^2/H from already-folded per-segment phases (cycles).

    The glue for model-folded stacks (anchored.fold_segments output):
    ragged per-segment phase lists are reduced per segment with the same
    Chebyshev harmonic sums as the search kernels, then stacked. For
    ``statistic="h"`` the H-test max-over-harmonics applies to the STACKED
    per-harmonic Z^2 profile (the standard stacked-H definition). Returns
    a scalar jax value.
    """
    if statistic not in ("z2", "h"):
        raise ValueError(f"unknown statistic {statistic!r}")
    if stack not in ("incoherent", "coherent"):
        raise ValueError(f"unknown stack mode {stack!r}")
    rows = [jnp.asarray(np.asarray(p, dtype=np.float64).ravel())
            for p in phase_segments if np.size(p)]
    if not rows:
        raise ValueError("stacked_power_from_phases needs >= 1 non-empty segment")
    per_harm = None  # (nharm,) stacked per-harmonic Z^2
    c_tot = s_tot = None
    n_tot = 0.0
    for ph in rows:
        c, s = search._harmonic_sums_cycles(
            ph, jnp.ones_like(ph), nharm, poly=poly)
        if stack == "coherent":
            c_tot = c if c_tot is None else c_tot + c
            s_tot = s if s_tot is None else s_tot + s
            n_tot += float(ph.shape[0])
        else:
            term = search.z2_from_sums(c, s, float(ph.shape[0]))
            per_harm = term if per_harm is None else per_harm + term
    if stack == "coherent":
        per_harm = search.z2_from_sums(c_tot, s_tot, n_tot)
    if statistic == "z2":
        return jnp.sum(per_harm)
    z2_cum = jnp.cumsum(per_harm)
    return jnp.max(z2_cum - 4.0 * jnp.arange(nharm, dtype=jnp.float64))


def segment_h_from_model(timMod, seg_times, nharm: int = 5,
                         t_ref_mjd=None, delta_fold=None,
                         cache_tag: str | None = None,
                         row_block: int | None = None):
    """Per-segment H-test of a timing model: fold_segments -> stacked rows.

    Folds each segment's events through the anchored fold (delta-fold
    engine eligible), pads the ragged phase lists into one (S, Nmax)
    batch and scores every segment with h_power_segments_chunked at
    frequency 1.0 (the phases are already cycle-folded). Empty segments
    score 0.0. Returns a (S,) numpy array — the per-segment coherence
    diagnostic for choosing a semi-coherent segmentation.
    """
    from crimp_tpu.ops import anchored

    seg_phase, _ = anchored.fold_segments(
        timMod, seg_times, t_ref_mjd=t_ref_mjd, delta_fold=delta_fold,
        cache_tag=cache_tag)
    sizes = [np.size(p) for p in seg_phase]
    n_max = max(1, max(sizes, default=1))
    ph = np.zeros((len(seg_phase), n_max), dtype=np.float64)
    mask = np.zeros((len(seg_phase), n_max), dtype=np.float64)
    for i, p in enumerate(seg_phase):
        ph[i, : sizes[i]] = np.asarray(p, dtype=np.float64)
        mask[i, : sizes[i]] = 1.0
    out = search.h_power_segments_chunked(
        ph, mask, np.ones(len(seg_phase), dtype=np.float64),
        nharm=nharm, row_block=row_block)
    out = np.array(out)  # owning copy: np.asarray of a jax array is read-only
    out[np.asarray(sizes) == 0] = 0.0
    return out
