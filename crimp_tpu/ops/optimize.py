"""Jittable, vmappable optimizers for the fitting engines.

The reference leans on lmfit/scipy (Nelder-Mead, BFGS, brute) in serial
Python loops; those cannot batch. These primitives are fixed-iteration,
branch-free (where/cond-select) JAX implementations that vmap cleanly over
ToA segments / MCMC walkers / Monte-Carlo draws:

- ``golden_section``: 1-D bounded maximization (log-likelihood profiles);
- ``nelder_mead``: fixed-iteration simplex minimization for the small
  multi-parameter template/ToA fits;
- ``bounded_transform``: lmfit-style min/max <-> unbounded reparameterization
  so gradient methods respect box bounds.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

PHI = (5.0**0.5 - 1) / 2  # golden ratio conjugate (Python float: a module
# import may happen inside a jit trace, so no jnp values at module scope)


def golden_section(fn, lo, hi, iters: int = 60, maximize: bool = True):
    """Golden-section search on [lo, hi]; returns (x_best, f_best).

    ``fn`` maps a scalar (or batched scalar) to an objective value; lo/hi may
    be arrays for batched independent searches.
    """
    sign = 1.0 if maximize else -1.0

    def value(x):
        return sign * fn(x)

    def body(_, state):
        a, b, x1, x2, f1, f2 = state
        shrink_right = f1 > f2  # keep [a, x2]
        new_a = jnp.where(shrink_right, a, x1)
        new_b = jnp.where(shrink_right, x2, b)
        new_x1 = new_b - PHI * (new_b - new_a)
        new_x2 = new_a + PHI * (new_b - new_a)
        return (new_a, new_b, new_x1, new_x2, value(new_x1), value(new_x2))

    x1 = hi - PHI * (hi - lo)
    x2 = lo + PHI * (hi - lo)
    state = (lo, hi, x1, x2, value(x1), value(x2))
    a, b, x1, x2, f1, f2 = jax.lax.fori_loop(0, iters, body, state)
    x_best = jnp.where(f1 > f2, x1, x2)
    return x_best, sign * jnp.maximum(f1, f2)


@partial(jax.jit, static_argnames=("fn", "iters"))
def nelder_mead(fn, x0: jax.Array, init_scale=0.1, iters: int = 200):
    """Fixed-iteration Nelder-Mead minimization of ``fn`` from ``x0``.

    Branch-free (select-based) so it vmaps; evaluates the standard
    reflect/expand/contract candidates each step with a conditional shrink.
    Returns (x_best, f_best).
    """
    n = x0.shape[-1]
    simplex = jnp.concatenate(
        [x0[None, :], x0[None, :] + jnp.eye(n, dtype=x0.dtype) * init_scale], axis=0
    )
    fvals = jax.vmap(fn)(simplex)

    def step(state, _):
        simplex, fvals = state
        order = jnp.argsort(fvals)
        simplex = simplex[order]
        fvals = fvals[order]
        best_f, worst_f, second_worst_f = fvals[0], fvals[-1], fvals[-2]
        centroid = jnp.mean(simplex[:-1], axis=0)
        direction = centroid - simplex[-1]

        x_reflect = centroid + direction
        x_expand = centroid + 2.0 * direction
        x_out = centroid + 0.5 * direction
        x_in = centroid - 0.5 * direction
        f_reflect = fn(x_reflect)
        f_expand = fn(x_expand)
        f_out = fn(x_out)
        f_in = fn(x_in)

        # Candidate replacing the worst vertex (standard NM decision tree).
        use_expand = (f_reflect < best_f) & (f_expand < f_reflect)
        use_reflect = (~use_expand) & (f_reflect < second_worst_f)
        use_out = (~use_expand) & (~use_reflect) & (f_reflect < worst_f) & (f_out <= f_reflect)
        use_in = (~use_expand) & (~use_reflect) & (~use_out) & (f_in < worst_f)
        shrink = ~(use_expand | use_reflect | use_out | use_in)

        candidate = jnp.where(
            use_expand[..., None],
            x_expand,
            jnp.where(
                use_reflect[..., None],
                x_reflect,
                jnp.where(use_out[..., None], x_out, x_in),
            ),
        )
        f_candidate = jnp.where(
            use_expand,
            f_expand,
            jnp.where(use_reflect, f_reflect, jnp.where(use_out, f_out, f_in)),
        )

        replaced = simplex.at[-1].set(candidate)
        replaced_f = fvals.at[-1].set(f_candidate)
        shrunk = simplex[0][None, :] + 0.5 * (simplex - simplex[0][None, :])
        shrunk_f = jax.vmap(fn)(shrunk)

        new_simplex = jnp.where(shrink, shrunk, replaced)
        new_f = jnp.where(shrink, shrunk_f, replaced_f)
        return (new_simplex, new_f), None

    (simplex, fvals), _ = jax.lax.scan(step, (simplex, fvals), None, length=iters)
    i_best = jnp.argmin(fvals)
    return simplex[i_best], fvals[i_best]


class bounded_transform:
    """lmfit-style box-bound reparameterization: x = lo + (hi-lo)*sigmoid(u)."""

    def __init__(self, lo, hi):
        self.lo = jnp.asarray(lo)
        self.hi = jnp.asarray(hi)

    def to_bounded(self, u):
        return self.lo + (self.hi - self.lo) * jax.nn.sigmoid(u)

    def to_unbounded(self, x):
        frac = jnp.clip((x - self.lo) / (self.hi - self.lo), 1e-12, 1 - 1e-12)
        return jnp.log(frac) - jnp.log1p(-frac)
