"""Survey-scale multi-source batch engine: stacked fold / fit / H-test.

CRIMP processes one pulsar per process end to end; every prior engine
(dense ToA scans, MXU grid kernels, delta-fold refolds) inherits that
single-source shape. This module lifts the per-source device paths to a
LEADING SOURCE AXIS so hundreds of sources fold, search and ToA-fit in a
handful of device invocations (the "PulsarX mode" of ROADMAP item 1):

- :class:`StackedAnchoredModel` stacks per-source ``AnchoredModel`` blocks
  struct-of-arrays style, padding ragged anchor/glitch/wave counts to the
  batch max with INERT rows (``anchored.pad_anchored``) so the unmodified
  ``anchored_fold`` vmaps cleanly and every real source's bits are
  untouched;
- whole sources are bucketed by padded event-count shape
  (``toafit.bucket_by_pow2`` — the same policy ``fit_toas_bucketed``
  applies to segments within a source), so one compiled executable per
  bucket serves every source in it;
- the fold, the per-segment H-test reduction and ``fit_segment`` are
  vmapped across the source axis with per-source masks, chunked through
  ``autotune.resolve_blocks("multisource", ...)`` so a single dispatch
  never exceeds the tuned (event_block x source_block) cell budget.

Bitwise contract: the fold is per-event ELEMENTWISE (no event-axis
reduction), so batched fold bits equal the single-source fold bits for
every source regardless of padding. The fit and the H-test reduce over
the padded event axis, so their bits match the single-source path exactly
when the padding is exact (every source in a bucket padded to the same
width the single-source path would use); ragged buckets match to
documented tolerance instead (docs/performance.md "Survey mode").

On a multi-device host the stacked fold shards its source axis
(parallel/mesh.py SOURCE_AXIS) — pure data parallelism, no collectives,
bit-identical to the unsharded dispatch.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu import obs
from crimp_tpu.models import timing
from crimp_tpu.obs import costmodel
from crimp_tpu.resilience import faultinject
from crimp_tpu.ops import anchored, search, toafit
from crimp_tpu.ops.anchored import AnchoredModel
from crimp_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Static defaults for the "multisource" autotune key: event_block is the
# padded per-source event width, source_block the source rows per
# dispatch; together they bound a dispatch to ~event_block*source_block
# padded cells (the memory governor _source_chunk enforces).
MULTISOURCE_EVENT_BLOCK = 1 << 15
MULTISOURCE_SOURCE_BLOCK = 256


@jax.tree_util.register_dataclass
@dataclass
class StackedAnchoredModel:
    """``AnchoredModel`` with a leading source axis on every leaf (B, ...).

    Field names and meanings mirror :class:`~crimp_tpu.ops.anchored.
    AnchoredModel` exactly; ``vmap`` over this pytree therefore hands the
    unmodified single-source fold one ordinary ``AnchoredModel`` row at a
    time. Build with :func:`stack_models`.
    """

    const: jax.Array  # (B, A)
    taylor: jax.Array  # (B, A, 13)
    glep_off: jax.Array  # (B, A, G)
    glph: jax.Array  # (B, G)
    glf0: jax.Array  # (B, G)
    glf1: jax.Array  # (B, G)
    glf2: jax.Array  # (B, G)
    glf0d: jax.Array  # (B, G)
    gltd_sec: jax.Array  # (B, G)
    wep_off: jax.Array  # (B, A)
    wave_om_sec: jax.Array  # (B,)
    wave_a: jax.Array  # (B, W)
    wave_b: jax.Array  # (B, W)
    f0: jax.Array  # (B,)

    @property
    def n_source(self) -> int:
        return int(self.const.shape[0])


_FIELDS = tuple(f.name for f in dataclasses.fields(StackedAnchoredModel))


def stack_models(models: list[AnchoredModel]) -> StackedAnchoredModel:
    """Stack per-source AnchoredModels into one struct-of-arrays block.

    Ragged anchor/glitch/wave counts are padded to the batch max with the
    inert rows of ``anchored.pad_anchored`` (zero-amplitude waves,
    never-active glitches), which contribute exactly +0.0 on device — the
    stacked fold of each row stays bitwise identical to that source's
    single-model fold.
    """
    if not models:
        raise ValueError("stack_models needs at least one model")
    n_anchor = max(m.const.shape[0] for m in models)
    n_glitch = max(m.glph.shape[0] for m in models)
    n_wave = max(m.wave_a.shape[0] for m in models)
    padded = [anchored.pad_anchored(m, n_anchor, n_glitch, n_wave) for m in models]
    return StackedAnchoredModel(
        **{name: np.stack([np.asarray(getattr(m, name)) for m in padded])
           for name in _FIELDS}
    )


def inert_rows(like: StackedAnchoredModel, n: int) -> StackedAnchoredModel:
    """``n`` padding source rows shaped like ``like`` that fold to frac(0).

    Used to pad a stacked batch to a device multiple before source-axis
    sharding: zero const/taylor, never-active glitches (glep_off=-inf,
    gltd_sec=1), zero-amplitude waves.
    """
    A = like.const.shape[1]
    G = like.glph.shape[1]
    W = like.wave_a.shape[1]
    row = anchored.pad_anchored(
        AnchoredModel(
            const=np.zeros(A), taylor=np.zeros((A, like.taylor.shape[2])),
            glep_off=np.zeros((A, 0)), glph=np.zeros(0), glf0=np.zeros(0),
            glf1=np.zeros(0), glf2=np.zeros(0), glf0d=np.zeros(0),
            gltd_sec=np.zeros(0), wep_off=np.zeros(A),
            wave_om_sec=np.asarray(0.0), wave_a=np.zeros(0),
            wave_b=np.zeros(0), f0=np.asarray(1.0),
        ),
        A, G, W,
    )
    return StackedAnchoredModel(
        **{name: np.broadcast_to(
            np.asarray(getattr(row, name))[None],
            (n,) + np.shape(getattr(row, name))).copy()
           for name in _FIELDS}
    )


def concat_stacked(a: StackedAnchoredModel, b: StackedAnchoredModel) -> StackedAnchoredModel:
    return StackedAnchoredModel(
        **{name: np.concatenate([np.asarray(getattr(a, name)),
                                 np.asarray(getattr(b, name))])
           for name in _FIELDS}
    )


def _row_fold(sm: StackedAnchoredModel, delta: jax.Array, anchor_idx: jax.Array) -> jax.Array:
    # under vmap every leaf loses its source axis, so this IS an
    # AnchoredModel row — hand it to the unmodified single-source kernel
    am = AnchoredModel(**{name: getattr(sm, name) for name in _FIELDS})
    return anchored.anchored_fold(am, delta, anchor_idx)


@jax.jit
def stacked_fold(sm: StackedAnchoredModel, delta: jax.Array, anchor_idx: jax.Array) -> jax.Array:
    """Cycle-folded phases (B, E) for B sources in ONE device invocation.

    ``delta`` (B, E) are per-source anchored second offsets padded to the
    bucket width E; ``anchor_idx`` (B, E) their per-event anchor rows
    (padding slots may carry any valid index — their outputs are
    discarded). Per-row bits are identical to ``anchored_fold`` on that
    source alone: the fold is elementwise over events, and vmap batches
    the arithmetic without reassociating it.
    """
    return jax.vmap(_row_fold)(sm, delta, anchor_idx)


# ---------------------------------------------------------------------------
# Source bucketing + dispatch chunking
# ---------------------------------------------------------------------------


def bucket_sources(sizes, max_pad_ratio: float = 4.0,
                   batch_cap: int = 0) -> list[list[int]]:
    """Bucket whole sources by padded size (pow2 merge, then a batch cap).

    ``sizes`` is the per-source padding-relevant size (the survey uses the
    max per-segment event count — the width the fit/H-test pad to).
    Generalizes ``toafit.bucket_by_pow2`` from segments-within-a-source to
    sources-within-a-survey; ``batch_cap`` > 0 additionally splits each
    bucket so no single dispatch exceeds that many sources.
    """
    buckets = toafit.bucket_by_pow2(sizes, max_pad_ratio)
    if batch_cap and batch_cap > 0:
        split: list[list[int]] = []
        for b in buckets:
            split.extend(b[i:i + batch_cap] for i in range(0, len(b), batch_cap))
        buckets = split
    obs.counter_add("bucket_count", len(buckets))
    return buckets


def _source_chunk(source_block: int, event_block: int, width: int) -> int:
    """Sources per dispatch so a chunk stays under the tuned cell budget
    (~event_block * source_block padded cells), but never below 1."""
    cells = max(1, int(event_block)) * max(1, int(source_block))
    return max(1, min(int(source_block), cells // max(int(width), 1)))


def _resolve_chunk(n_sources: int, width: int) -> int:
    from crimp_tpu.ops import autotune

    eb, sb = autotune.resolve_blocks("multisource", max(width, 1),
                                     max(n_sources, 1))
    return _source_chunk(sb, eb, width)


# ---------------------------------------------------------------------------
# Batched fold across sources
# ---------------------------------------------------------------------------


def fold_sources(timing_models, seg_times_list, t_ref_list=None):
    """Anchored fold of MANY sources' ragged segments, batched on device.

    ``timing_models`` is one timing model per source (anything
    ``timing.resolve`` accepts); ``seg_times_list`` one list of per-segment
    MJD arrays per source. Per source, anchors default to each segment's
    midpoint (exactly ``anchored.fold_segments``); host prep — longdouble
    anchor phases, re-centered Taylor coefficients — runs per source, then
    the stacked f64 kernel folds every source in source-chunked vmapped
    dispatches. Returns ``(phase_lists, t_refs)``: per source, the list of
    cycle-folded [0,1) segment phase arrays plus the anchors used.

    Bitwise identical per source to ``fold_segments`` with the delta-fold
    engine off (the batched path never routes through the fold cache —
    its products are keyed per single-source call).
    """
    B = len(seg_times_list)
    if B == 0:
        return [], []
    prepped = []
    for src_i, (tm, seg_times) in enumerate(zip(timing_models, seg_times_list)):
        tm = timing.resolve(tm)
        seg_times = [np.atleast_1d(np.asarray(t, dtype=np.float64))
                     for t in seg_times]
        if t_ref_list is not None and t_ref_list[src_i] is not None:
            t_ref = np.atleast_1d(np.asarray(t_ref_list[src_i], dtype=np.float64))
        else:
            t_ref = np.asarray(
                [(t[-1] - t[0]) / 2 + t[0] if t.size else 0.0 for t in seg_times]
            )
        if t_ref.size == 0:
            # a source with no segments still needs one (dummy) anchor so
            # the stacked gather never indexes an empty table
            t_ref = np.zeros(1)
        sizes = [t.size for t in seg_times]
        anchor_idx = (np.repeat(np.arange(len(seg_times)), sizes)
                      if seg_times else np.zeros(0, dtype=np.int64))
        times_cat = np.concatenate(seg_times) if seg_times else np.zeros(0)
        delta = anchored.anchor_deltas(times_cat, t_ref, anchor_idx) \
            if times_cat.size else np.zeros(0)
        am = anchored.prepare_anchors(tm, t_ref)
        prepped.append((am, delta, anchor_idx, sizes, t_ref))
        obs.counter_add("events_folded", int(times_cat.size))
        obs.counter_add("fold_segments", len(seg_times))
    obs.counter_add("sources_batched", B)

    E_max = max(max((p[1].size for p in prepped), default=1), 1)
    chunk = _resolve_chunk(B, E_max)
    folded_rows: list[np.ndarray] = []
    for lo in range(0, B, chunk):
        faultinject.fire("fold_sources")
        part = prepped[lo:lo + chunk]
        sm = stack_models([p[0] for p in part])
        delta_pad = np.zeros((len(part), E_max))
        idx_pad = np.zeros((len(part), E_max), dtype=np.int64)
        for r, (_, delta, anchor_idx, _, _) in enumerate(part):
            delta_pad[r, : delta.size] = delta
            idx_pad[r, : anchor_idx.size] = anchor_idx
        sm, delta_dev, idx_dev, n_real, plan = _maybe_shard_sources(
            sm, delta_pad, idx_pad
        )
        # fetch_global is np.asarray on a single process; on a multi-process
        # job it is the one tiled allgather that brings every host's fold
        # rows back (the source axis's only DCN traffic)
        from crimp_tpu.parallel import multihost

        rows = multihost.fetch_global(
            stacked_fold(sm, delta_dev, idx_dev))[:n_real]
        # sharded chunks cost-model too: the committed shardings survive
        # abstraction (obs/costmodel._abstractify), so the AOT lowering is
        # the same per-device program the dispatch above just ran
        costmodel.capture("stacked_fold", stacked_fold,
                          sm, delta_dev, idx_dev, plan=plan)
        folded_rows.extend(rows)
    phase_lists = []
    t_refs = []
    for (_, delta, _, sizes, t_ref), row in zip(prepped, folded_rows):
        flat = row[: delta.size]
        phase_lists.append(list(np.split(flat, np.cumsum(sizes)[:-1]))
                           if sizes else [])
        t_refs.append(t_ref)
    return phase_lists, t_refs


def _maybe_shard_sources(sm: StackedAnchoredModel, delta: np.ndarray,
                         idx: np.ndarray):
    """Shard the source axis across devices when it pays (pure data
    parallelism; bitwise identical to the unsharded dispatch). Returns
    possibly-padded (sm, delta, idx), the real row count, and the registry
    sharding plan (None when the dispatch stays on one device).

    On a multi-process job the source axis spans HOSTS: the stacked batch
    lands on the host-major global source mesh, and each process hands the
    bridge only its own contiguous row block
    (``multihost.process_local_rows`` + ``jax.make_array_from_process_
    local_data``) — no host ever materializes the global batch on device.
    The fold stays elementwise per row, so the cross-host layout is
    bitwise identical to the single-process dispatch at equal padded
    shapes (the 1/2/4-process pins in tests/test_multihost_smoke.py).
    """
    from crimp_tpu.parallel import mesh as pmesh
    from crimp_tpu.parallel import multihost, registry

    n = sm.n_source
    if not pmesh.sharding_enabled():
        return sm, jnp.asarray(delta), jnp.asarray(idx), n, None
    n_devices = len(jax.devices())
    if n_devices < 2 or n < n_devices:
        return sm, jnp.asarray(delta), jnp.asarray(idx), n, None
    _, pcount = multihost.process_identity()
    smesh = multihost.global_source_mesh() if pcount > 1 \
        else pmesh.source_mesh()
    plan = registry.specs_for("stacked_fold", smesh)
    pad = pmesh.pad_batch_for_mesh(n, smesh, axis_name=pmesh.SOURCE_AXIS)
    if pad:
        sm = concat_stacked(sm, inert_rows(sm, pad))
        delta = np.concatenate([delta, np.zeros((pad,) + delta.shape[1:])])
        idx = np.concatenate([idx, np.zeros((pad,) + idx.shape[1:], idx.dtype)])

    if pcount > 1:
        lo, hi = multihost.process_local_rows(n + pad)

        def put(name, arr):
            arr = np.asarray(arr)
            return multihost.global_array(arr[lo:hi], smesh,
                                          plan.spec(name, leaf=arr),
                                          arr.shape)
    else:
        def put(name, arr):
            return jax.device_put(np.asarray(arr), plan.named(name))

    sm = StackedAnchoredModel(
        **{name: put(name, getattr(sm, name)) for name in _FIELDS}
    )
    return sm, put("delta", delta), put("idx", idx), n, plan


# ---------------------------------------------------------------------------
# Batched ToA fit across sources
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("kind", "cfg"))
def fit_toas_batch_multi(kind, tpls, phases, masks, exposures, cfg):
    """``toafit.fit_toas_batch`` with a PER-ROW template.

    ``tpls`` is a ProfileParams pytree whose leaves carry a leading row
    axis (one template per padded segment row) — the cross-source batch
    where sources disagree on template parameters but share the profile
    family, component count and fit config.
    """
    return jax.vmap(
        lambda tpl, x, m, t: toafit.fit_segment(kind, tpl, x, m, t, cfg)
    )(tpls, phases, masks, exposures)


def _templates_identical(tpls) -> bool:
    first = tpls[0]
    leaves0 = jax.tree_util.tree_leaves(first)
    for t in tpls[1:]:
        leaves = jax.tree_util.tree_leaves(t)
        if len(leaves) != len(leaves0) or any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(leaves0, leaves)
        ):
            return False
    return True


def fit_sources(kind, tpls, phase_lists, exposure_list, cfg):
    """ToA-fit every segment of every source in batched dispatches.

    ``tpls`` is one ProfileParams per source (same family ``kind`` and
    component count — group sources before calling); ``phase_lists`` the
    per-source lists of folded segment phases (radians already applied for
    the CAUCHY/VONMISES families); ``exposure_list`` per-source exposure
    arrays. All (source, segment) rows flatten into ONE segment batch
    padded to the bucket-wide max width. When every source carries a
    bitwise-identical template the batch routes through
    ``toafit.fit_toas_batch_auto`` (shared template, segment-axis
    auto-sharding — bits equal the single-source path when the padded
    width matches); otherwise the per-row-template vmap runs. Returns the
    flat result dict plus the per-source row slices.
    """
    rows: list[np.ndarray] = []
    row_tpl_idx: list[int] = []
    exposures: list[float] = []
    slices: list[slice] = []
    for src_i, (plist, exps) in enumerate(zip(phase_lists, exposure_list)):
        start = len(rows)
        rows.extend(plist)
        row_tpl_idx.extend([src_i] * len(plist))
        exposures.extend(np.asarray(exps, dtype=float).tolist())
        slices.append(slice(start, len(rows)))
    if not rows:
        return {}, slices
    phases, masks = toafit.pad_segments(rows)
    exposures = np.asarray(exposures, dtype=float)
    if _templates_identical(tpls):
        out = toafit.fit_toas_batch_auto(kind, tpls[0], phases, masks,
                                         exposures, cfg)
    else:
        obs.counter_add("toas_fit", len(rows))
        cfg = toafit.resolve_runtime_cfg(cfg, len(rows), phases.shape[1])
        tpl_rows = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(
                [jnp.asarray(leaves[i]) for i in row_tpl_idx]
            ),
            *tpls,
        )
        ph = jnp.asarray(phases)
        mk = jnp.asarray(masks)
        ex = jnp.asarray(exposures)
        out = fit_toas_batch_multi(kind, tpl_rows, ph, mk, ex, cfg)
        costmodel.capture("toa_fit_batch_multi", fit_toas_batch_multi,
                          kind, tpl_rows, ph, mk, ex, cfg)
    return {k: np.asarray(v) for k, v in out.items()}, slices


# ---------------------------------------------------------------------------
# Batched per-ToA H-test across sources
# ---------------------------------------------------------------------------


def h_power_sources(seg_times_list, freqs_list, nharm: int = 5):
    """Per-ToA H-test for every (source, segment) row in chunked batches.

    ``seg_times_list``: per source, the list of per-segment event MJD
    arrays; ``freqs_list``: per source, the per-segment trial frequency
    (the local ephemeris frequency at the ToA epoch). Rows are centered
    to seconds exactly like the single-source pipeline and dispatched
    through ``search.h_power_segments`` in source-block-sized chunks.
    Returns one (S_i,) H-power array per source.
    """
    rows = []
    freqs = []
    slices = []
    for seg_times, fs in zip(seg_times_list, freqs_list):
        start = len(rows)
        for t_seg in seg_times:
            t_seg = np.asarray(t_seg, dtype=np.float64)
            centered = ((t_seg - (t_seg[0] + t_seg[-1]) / 2) * 86400.0
                        if t_seg.size else t_seg)
            rows.append(centered)
        freqs.extend(np.asarray(fs, dtype=float).tolist())
        slices.append(slice(start, len(rows)))
    if not rows:
        return [np.zeros(0) for _ in seg_times_list]
    width = max(max((r.size for r in rows), default=1), 1)
    sec_padded = np.zeros((len(rows), width))
    sec_masks = np.zeros((len(rows), width), dtype=bool)
    for i, r in enumerate(rows):
        sec_padded[i, : r.size] = r
        sec_masks[i, : r.size] = True
    chunk = _resolve_chunk(len(rows), width)
    h = np.asarray(search.h_power_segments_chunked(
        sec_padded, sec_masks, np.asarray(freqs), nharm=nharm,
        row_block=chunk,
    ))
    return [h[s] for s in slices]


# ---------------------------------------------------------------------------
# Survey-scale posteriors: batched delta-basis MCMC across the source axis
# ---------------------------------------------------------------------------


def sample_posterior_sources(problems, steps: int, walkers: int,
                             seed: int = 0, stretch_a: float = 2.0):
    """Delta-basis ensemble MCMC for MANY sources in chunked batch dispatches.

    ``problems`` is one dict per source with keys ``basis`` (n_i, ndim),
    ``y`` (n_i,), ``err`` (n_i,), ``lo``/``hi`` (ndim,) — exactly the
    ``mcmc.delta_logprob`` observation pytree, typically produced by
    ``pipelines.fit_toas.make_logprob_delta`` (which also runs the
    linear-regime precision guard; guard-tripped sources belong on the
    single-source exact path, not in this batch). All sources must share
    ``ndim``; ragged ToA counts pad to the batch max with INERT rows
    (``mask == 0``) whose every log-probability TERM is exactly +0.0 —
    padding never biases a posterior. Same contract as the ragged fold
    buckets above: identical padded width reproduces bits exactly, but
    changing the padded width may regroup the reduction's partial sums,
    so a source re-run at a different width matches to float64
    reduction-order tolerance (last-ulp), not bitwise.

    Walker initialization draws uniformly inside each source's prior box
    from ``np.random.default_rng(seed)`` spawned per source index, and the
    per-source PRNG streams are pre-split from one master key — both are
    functions of (seed, source index) alone, so results are invariant to
    the source-block chunking ``_resolve_chunk`` picks.

    Returns (chains (B, steps, walkers, ndim), log_probs (B, steps,
    walkers)) as numpy arrays.
    """
    from crimp_tpu.ops import mcmc as mcmc_ops

    if not problems:
        return np.zeros((0, steps, walkers, 0)), np.zeros((0, steps, walkers))
    ndims = {np.asarray(p["basis"]).shape[1] for p in problems}
    if len(ndims) != 1:
        raise ValueError(f"all sources must share ndim, got {sorted(ndims)}")
    (ndim,) = ndims
    B = len(problems)
    n_max = max(np.asarray(p["basis"]).shape[0] for p in problems)

    basis = np.zeros((B, n_max, ndim))
    y = np.zeros((B, n_max))
    err = np.ones((B, n_max))  # padded rows keep err=1 so log() stays finite
    mask = np.zeros((B, n_max))
    lo = np.empty((B, ndim))
    hi = np.empty((B, ndim))
    p0 = np.empty((B, walkers, ndim))
    for i, p in enumerate(problems):
        nb = np.asarray(p["basis"], dtype=np.float64)
        n = nb.shape[0]
        basis[i, :n] = nb
        y[i, :n] = np.asarray(p["y"], dtype=np.float64)
        err[i, :n] = np.asarray(p["err"], dtype=np.float64)
        mask[i, :n] = 1.0
        lo[i] = np.asarray(p["lo"], dtype=np.float64)
        hi[i] = np.asarray(p["hi"], dtype=np.float64)
        rng = np.random.default_rng([seed, i])
        for d in range(ndim):
            p0[i, :, d] = rng.uniform(lo[i, d], hi[i, d], size=walkers)

    keys_all = jax.random.split(jax.random.PRNGKey(seed), B)
    chunk = _resolve_chunk(B, n_max * max(walkers, 1))
    obs.counter_add("mcmc_sources_batched", B)
    chains = np.empty((B, steps, walkers, ndim))
    lps = np.empty((B, steps, walkers))
    with obs.span("mcmc_sources", sources=B, steps=steps, walkers=walkers,
                  chunk=chunk, n_toas_padded=n_max):
        for start in range(0, B, chunk):
            sl = slice(start, min(start + chunk, B))
            data = {
                "basis": jnp.asarray(basis[sl]), "y": jnp.asarray(y[sl]),
                "err": jnp.asarray(err[sl]), "mask": jnp.asarray(mask[sl]),
                "lo": jnp.asarray(lo[sl]), "hi": jnp.asarray(hi[sl]),
            }
            c_j, l_j = mcmc_ops.ensemble_sample_batch(
                mcmc_ops.delta_logprob, jnp.asarray(p0[sl]), data, steps,
                stretch_a=stretch_a, keys=keys_all[sl],
            )
            costmodel.capture(
                "mcmc_ensemble_sources", mcmc_ops._ensemble_batch_core,
                mcmc_ops.delta_logprob, jnp.asarray(p0[sl]), data, steps,
                keys_all[sl], stretch_a,
            )
            chains[sl] = np.asarray(c_j)
            lps[sl] = np.asarray(l_j)
    return chains, lps
