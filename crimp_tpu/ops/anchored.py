"""Anchored (precision-split) phase folding.

Why this exists: the ToA budget is <1 µs ≈ 1.4e-7 cycles while the absolute
model phase reaches ~2.7e6 cycles for the bundled magnetar, i.e. ~13
significant digits — and the TPU's emulated f64 delivers only ~46-bit
multiplies (measured: rel. err 1.5e-14; and MJD-valued times lose ~6 µs of
precision in a plain host->device round-trip). Folding *absolute* phases on
device therefore cannot meet the budget.

The split (the integer/fractional anchor idea of the reference's
`ephemIntegerRotation` trick, timfile.py:206-217, generalized to the whole
fold path):

 host (numpy longdouble, exact):
   - pick anchor times t_ref (one per ToA interval / GTI chunk),
   - total model phase phi_ref at each anchor; keep only frac(phi_ref)
     combined with minus the glitch/wave values at the anchor,
   - re-centered Taylor coefficients b_m: phi_T(t_ref+d) - phi_T(t_ref)
     = sum_m b_m d^m  (binomial re-expansion, computed in longdouble),
   - event times as SECONDS RELATIVE TO THEIR ANCHOR (exact in f64),
   - per-anchor glitch/wave epoch offsets in seconds.

 device (f64, all quantities small):
   folded = frac( const[a] + Horner_b(d) + G(d; a) + W(d; a) )

 where G/W are the glitch and whitening-wave terms evaluated at the
 anchored offsets. Every device quantity is <= ~3e5 cycles for month-scale
 chunks, so the 2^-46 multiply noise lands at ~5e-9 cycles — two orders
 under budget. Verified against the reference numpy fold in the tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import comb, factorial

import jax
import jax.numpy as jnp
import numpy as np

from crimp_tpu import obs
from crimp_tpu.models import timing
from crimp_tpu.obs import costmodel
from crimp_tpu.models.timing import N_FREQ_TERMS, TimingParams

SECONDS_PER_DAY = 86400.0


@jax.tree_util.register_dataclass
@dataclass
class AnchoredModel:
    """Host-prepared, device-ready anchored timing model (A anchors)."""

    const: jax.Array  # (A,) frac(phi_ref) - G(t_ref) - W(t_ref)
    taylor: jax.Array  # (A, 13) local Taylor coeffs b_m (cycles / s^m)
    glep_off: jax.Array  # (A, G) (t_ref - GLEP) in seconds
    glph: jax.Array  # (G,)
    glf0: jax.Array  # (G,)
    glf1: jax.Array  # (G,)
    glf2: jax.Array  # (G,)
    glf0d: jax.Array  # (G,)
    gltd_sec: jax.Array  # (G,) recovery timescale in seconds (1 s padding)
    wep_off: jax.Array  # (A,) (t_ref - WAVEEPOCH) in seconds
    wave_om_sec: jax.Array  # scalar, wave fundamental in rad/s
    wave_a: jax.Array  # (W,)
    wave_b: jax.Array  # (W,)
    f0: jax.Array  # scalar (waves are seconds-residuals scaled by F0)

    @property
    def n_anchor(self) -> int:
        return int(self.const.shape[0])


# ---------------------------------------------------------------------------
# Host side (exact)
# ---------------------------------------------------------------------------


def _host_taylor_phase(tm: TimingParams, t_mjd: np.ndarray) -> np.ndarray:
    """Taylor phase at t_mjd in longdouble (host, exact)."""
    ld = np.longdouble
    dt = (np.asarray(t_mjd, dtype=ld) - ld(float(tm.pepoch))) * ld(SECONDS_PER_DAY)
    f = np.asarray(tm.f, dtype=np.float64)
    acc = np.zeros_like(dt)
    for n in range(N_FREQ_TERMS, 0, -1):
        acc = acc + ld(f[n - 1]) / ld(factorial(n)) * dt**n
    return acc


def _host_glitch_phase(tm: TimingParams, t_mjd: np.ndarray) -> np.ndarray:
    """Glitch phase at t_mjd in f64 (host; magnitudes are small)."""
    t = np.asarray(t_mjd, dtype=np.float64)
    total = np.zeros_like(t)
    glep = np.asarray(tm.glep)
    for g in range(tm.n_glitch):
        if not np.isfinite(glep[g]):
            continue
        after = t >= glep[g]
        dt_days = np.where(after, t - glep[g], 0.0)
        dt_sec = dt_days * SECONDS_PER_DAY
        gltd = float(np.asarray(tm.gltd)[g])
        recovery = (
            0.0
            if gltd == 0.0
            else gltd * SECONDS_PER_DAY * (1.0 - np.exp(-dt_days / gltd))
        )
        contrib = (
            float(np.asarray(tm.glph)[g])
            + float(np.asarray(tm.glf0)[g]) * dt_sec
            + 0.5 * float(np.asarray(tm.glf1)[g]) * dt_sec**2
            + (1.0 / 6.0) * float(np.asarray(tm.glf2)[g]) * dt_sec**3
            + float(np.asarray(tm.glf0d)[g]) * recovery
        )
        total += np.where(after, contrib, 0.0)
    return total


def _host_wave_phase(tm: TimingParams, t_mjd: np.ndarray) -> np.ndarray:
    t = np.asarray(t_mjd, dtype=np.float64)
    total = np.zeros_like(t)
    if tm.n_wave:
        base = t - float(tm.wave_epoch)
        om = float(tm.wave_om)
        a = np.asarray(tm.wave_a)
        b = np.asarray(tm.wave_b)
        for k in range(1, tm.n_wave + 1):
            arg = k * om * base
            total += a[k - 1] * np.sin(arg) + b[k - 1] * np.cos(arg)
    return total * float(np.asarray(tm.f)[0])


def host_total_phase(timMod, t_mjd) -> np.ndarray:
    """Exact (longdouble Taylor) total model phase on host, as longdouble."""
    tm = timing.resolve(timMod)
    t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
    return (
        _host_taylor_phase(tm, t)
        + _host_glitch_phase(tm, t).astype(np.longdouble)
        + _host_wave_phase(tm, t).astype(np.longdouble)
    )


def _local_taylor_coeffs(tm: TimingParams, t_ref_mjd: np.ndarray) -> np.ndarray:
    """Re-centered Taylor coefficients b_m (A, 13), longdouble -> f64.

    phi_T(t_ref + d) - phi_T(t_ref) = sum_{m=1..13} b_m d^m with
    b_m = sum_{n>=m} C(n, m) c_n dt_ref^(n-m), c_n = F_{n-1}/n! per s^n.
    """
    ld = np.longdouble
    f = np.asarray(tm.f, dtype=np.float64)
    c = np.array([ld(f[n - 1]) / ld(factorial(n)) for n in range(1, N_FREQ_TERMS + 1)])
    dt_ref = (np.asarray(t_ref_mjd, dtype=ld) - ld(float(tm.pepoch))) * ld(SECONDS_PER_DAY)
    A = dt_ref.shape[0]
    b = np.zeros((A, N_FREQ_TERMS), dtype=ld)
    for m in range(1, N_FREQ_TERMS + 1):
        acc = np.zeros(A, dtype=ld)
        for n in range(N_FREQ_TERMS, m - 1, -1):
            acc = acc * dt_ref + ld(comb(n, m)) * c[n - 1]
        b[:, m - 1] = acc
    return b.astype(np.float64)


def prepare_anchors(timMod, t_ref_mjd) -> AnchoredModel:
    """Build the device-ready AnchoredModel for anchor times t_ref (MJD)."""
    tm = timing.resolve(timMod)
    t_ref = np.atleast_1d(np.asarray(t_ref_mjd, dtype=np.float64))

    phi_ref = host_total_phase(tm, t_ref)
    frac_ref = (phi_ref - np.floor(phi_ref)).astype(np.float64)
    const = frac_ref - _host_glitch_phase(tm, t_ref) - _host_wave_phase(tm, t_ref)

    glep = np.asarray(tm.glep)
    # Padded glitches (GLEP=+inf) get a -inf offset => never active on device.
    glep_off = np.where(
        np.isfinite(glep)[None, :],
        (t_ref[:, None] - glep[None, :]) * SECONDS_PER_DAY,
        -np.inf,
    )
    gltd_sec = np.where(
        np.asarray(tm.gltd) == 0.0, 1.0, np.asarray(tm.gltd) * SECONDS_PER_DAY
    )
    gltd_zero = np.asarray(tm.gltd) == 0.0

    # Host-numpy leaves (see models.timing.from_dict): only the anchored
    # small quantities ever cross to the device, where 1e-15 relative
    # transfer noise is harmless.
    as_f64 = lambda x: np.asarray(x, dtype=np.float64)
    return AnchoredModel(
        const=as_f64(const),
        taylor=as_f64(_local_taylor_coeffs(tm, t_ref)),
        glep_off=as_f64(glep_off),
        glph=as_f64(tm.glph),
        glf0=as_f64(tm.glf0),
        glf1=as_f64(tm.glf1),
        glf2=as_f64(tm.glf2),
        glf0d=as_f64(np.where(gltd_zero, 0.0, np.asarray(tm.glf0d))),
        gltd_sec=as_f64(gltd_sec),
        wep_off=as_f64((t_ref - float(tm.wave_epoch)) * SECONDS_PER_DAY),
        wave_om_sec=as_f64(float(tm.wave_om) / SECONDS_PER_DAY),
        wave_a=as_f64(tm.wave_a),
        wave_b=as_f64(tm.wave_b),
        f0=as_f64(float(np.asarray(tm.f)[0])),
    )


def pad_anchored(am: AnchoredModel, n_anchor: int, n_glitch: int, n_wave: int) -> AnchoredModel:
    """Pad an AnchoredModel to target (A, G, W) shapes with INERT rows.

    The padding conventions are the same ones prepare_anchors already uses
    for absent terms, so padded entries contribute exactly +0.0 on device:
    extra glitch columns get glep_off=-inf (never active) with gltd_sec=1
    (no 0-division in the recovery term), extra wave harmonics get zero
    amplitudes, and extra anchors get zero const/taylor rows (they are
    only ever gathered by padded events, whose results are discarded).
    This is what lets ops/multisource stack models of ragged glitch/wave
    counts into one vmappable block without perturbing any real source's
    bits. Shrinking is not supported (raises).
    """
    A, G = am.glep_off.shape
    W = am.wave_a.shape[0]
    if n_anchor < A or n_glitch < G or n_wave < W:
        raise ValueError(
            f"pad_anchored cannot shrink ({A},{G},{W}) -> "
            f"({n_anchor},{n_glitch},{n_wave})"
        )

    def pad1(x, n, fill=0.0):
        return np.concatenate([x, np.full(n - x.shape[0], fill, dtype=x.dtype)])

    glep_off = np.full((n_anchor, n_glitch), -np.inf)
    glep_off[:A, :G] = am.glep_off
    taylor = np.zeros((n_anchor, am.taylor.shape[1]))
    taylor[:A] = am.taylor
    return AnchoredModel(
        const=pad1(am.const, n_anchor),
        taylor=taylor,
        glep_off=glep_off,
        glph=pad1(am.glph, n_glitch),
        glf0=pad1(am.glf0, n_glitch),
        glf1=pad1(am.glf1, n_glitch),
        glf2=pad1(am.glf2, n_glitch),
        glf0d=pad1(am.glf0d, n_glitch),
        gltd_sec=pad1(am.gltd_sec, n_glitch, fill=1.0),
        wep_off=pad1(am.wep_off, n_anchor),
        wave_om_sec=am.wave_om_sec,
        wave_a=pad1(am.wave_a, n_wave),
        wave_b=pad1(am.wave_b, n_wave),
        f0=am.f0,
    )


def anchor_deltas(times_mjd: np.ndarray, t_ref_mjd: np.ndarray, anchor_idx: np.ndarray) -> np.ndarray:
    """Event times as exact seconds relative to their anchor (host f64)."""
    return (
        np.asarray(times_mjd, dtype=np.float64) - np.asarray(t_ref_mjd)[anchor_idx]
    ) * SECONDS_PER_DAY


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


def _device_glitch(am: AnchoredModel, delta: jax.Array, anchor_idx: jax.Array) -> jax.Array:
    """Summed glitch phase at anchored offsets (per event)."""
    n_glitch = am.glph.shape[0]
    if n_glitch == 0:
        return jnp.zeros_like(delta)

    def add_one(carry, g):
        glep_off_g, glph, glf0, glf1, glf2, glf0d, gltd_sec = g
        dt = delta + glep_off_g[anchor_idx]
        after = dt >= 0.0
        dt = jnp.where(after, dt, 0.0)
        recovery = gltd_sec * (1.0 - jnp.exp(-dt / gltd_sec))
        contrib = (
            glph + glf0 * dt + 0.5 * glf1 * dt**2 + (1.0 / 6.0) * glf2 * dt**3 + glf0d * recovery
        )
        return carry + jnp.where(after, contrib, 0.0), None

    cols = (
        am.glep_off.T,  # (G, A)
        am.glph,
        am.glf0,
        am.glf1,
        am.glf2,
        am.glf0d,
        am.gltd_sec,
    )
    total, _ = jax.lax.scan(add_one, jnp.zeros_like(delta), cols)
    return total


def _device_wave(am: AnchoredModel, delta: jax.Array, anchor_idx: jax.Array) -> jax.Array:
    n_wave = am.wave_a.shape[0]
    if n_wave == 0:
        return jnp.zeros_like(delta)
    base = (delta + am.wep_off[anchor_idx]) * am.wave_om_sec

    def add_one(carry, kab):
        k, a, b = kab
        return carry + a * jnp.sin(k * base) + b * jnp.cos(k * base), None

    ks = jnp.arange(1, n_wave + 1, dtype=delta.dtype)
    total, _ = jax.lax.scan(
        add_one, jnp.zeros_like(delta), jnp.stack([ks, am.wave_a, am.wave_b], axis=-1)
    )
    return total * am.f0


@jax.jit
def anchored_fold(am: AnchoredModel, delta: jax.Array, anchor_idx: jax.Array) -> jax.Array:
    """Cycle-folded phases in [0,1) for events at anchored second offsets."""
    coeffs = am.taylor[anchor_idx]  # (N, 13)
    acc = jnp.zeros_like(delta)
    for m in range(N_FREQ_TERMS - 1, -1, -1):
        acc = acc * delta + coeffs[:, m]
    local = acc * delta
    phase = (
        am.const[anchor_idx]
        + local
        + _device_glitch(am, delta, anchor_idx)
        + _device_wave(am, delta, anchor_idx)
    )
    return phase - jnp.floor(phase)


# ---------------------------------------------------------------------------
# Batched host wrappers
# ---------------------------------------------------------------------------


def fold_segments(timMod, seg_times, t_ref_mjd=None, delta_fold=None,
                  cache_tag: str | None = None):
    """Anchored fold of ragged per-segment event times in ONE device call.

    The ToA-pipeline fold dance — one anchor per segment, events
    concatenated with a per-event anchor index so the kernel compiles once
    regardless of per-segment raggedness — shared by measure_toas and the
    bench workloads. ``t_ref_mjd`` defaults to each segment's midpoint
    (t0 + (t_end - t0)/2, the reference's ToA epoch). Returns
    (seg_phase_list, t_ref): cycle-folded [0,1) phases split back per
    segment, plus the anchors used. Empty segments fold to empty arrays.

    ``delta_fold`` opts the call in/out of the incremental delta-fold
    engine (ops/deltafold.py: fingerprinted fold cache + `phases + B@dp`
    refolds for linear parameter updates); None defers to
    autotune.resolve_delta_fold (CRIMP_TPU_DELTA_FOLD env > cached bench
    A/B winner > off). With the knob off this function never touches the
    engine and stays bit-identical to the pre-engine path.

    ``cache_tag`` namespaces the fold-cache key (on top of the model sha
    the key already carries) — the survey pipeline passes the source name
    so two sources can never contend for one cache slot even when their
    event byte-streams coincide.
    """
    seg_times = [np.atleast_1d(np.asarray(t, dtype=np.float64)) for t in seg_times]
    if t_ref_mjd is None:
        t_ref = np.asarray(
            [(t[-1] - t[0]) / 2 + t[0] if t.size else 0.0 for t in seg_times]
        )
    else:
        t_ref = np.atleast_1d(np.asarray(t_ref_mjd, dtype=np.float64))
    if not seg_times:
        return [], t_ref
    tm = timing.resolve(timMod)
    sizes = [t.size for t in seg_times]
    anchor_idx = np.repeat(np.arange(len(seg_times)), sizes)
    times_cat = np.concatenate(seg_times)
    obs.counter_add("events_folded", int(times_cat.size))
    obs.counter_add("fold_segments", len(seg_times))
    delta = anchor_deltas(times_cat, t_ref, anchor_idx)

    def exact():
        am = prepare_anchors(tm, t_ref)
        delta_dev = jnp.asarray(delta)
        idx_dev = jnp.asarray(anchor_idx)
        out = np.asarray(anchored_fold(am, delta_dev, idx_dev))
        costmodel.capture("anchored_fold", anchored_fold, am, delta_dev, idx_dev)
        return out

    from crimp_tpu.ops import deltafold

    cfg = deltafold.resolve(times_cat.size, delta_fold)
    if cfg["delta_fold"]:
        folded, _ = deltafold.cached_fold(
            tm, times_cat, sizes, t_ref, delta, anchor_idx, exact,
            budget=cfg["budget"], tag=cache_tag,
        )
    else:
        folded = exact()
    return list(np.split(folded, np.cumsum(sizes)[:-1])), t_ref


# ---------------------------------------------------------------------------
# Chunked host wrapper: accurate folding for arbitrary time arrays
# ---------------------------------------------------------------------------


def fold_chunked(times_mjd, timMod, chunk_days: float = 30.0):
    """Fold an arbitrary MJD array via per-chunk anchors (host orchestration).

    Splits the (sorted) time span into <= chunk_days chunks, anchors each at
    its midpoint, and runs the anchored device kernel. Returns cycle-folded
    phases in [0,1) with the input's ordering.
    """
    tm = timing.resolve(timMod)
    t = np.atleast_1d(np.asarray(times_mjd, dtype=np.float64))
    if t.size == 0:
        return np.zeros(0)
    lo = t.min()
    idx = np.minimum(
        ((t - lo) / chunk_days).astype(np.int64),
        max(int(np.ceil((t.max() - lo) / chunk_days)) - 1, 0),
    )
    # Anchor at each chunk's midpoint (any in-chunk point works).
    n_chunks = int(idx.max()) + 1
    t_ref = lo + (np.arange(n_chunks) + 0.5) * chunk_days
    am = prepare_anchors(tm, t_ref)
    delta = anchor_deltas(t, t_ref, idx)
    folded = np.asarray(anchored_fold(am, jnp.asarray(delta), jnp.asarray(idx)))
    return folded.reshape(np.shape(times_mjd))
