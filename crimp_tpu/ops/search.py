"""Periodicity searches: Z^2_n, H-test, and the 2-D (nu, nudot) Z^2 grid.

Statistic parity with the reference (periodsearch.py:57-125):

  Z^2_n(f)  = (2/N) * sum_{k=1..n} [ (sum_i cos k*theta_i)^2 + (sum_i sin k*theta_i)^2 ]
  H(f)      = max_m ( cumsum_m Z^2 terms - 4*(m-1) )
  2-D grid  : theta_i = 2*pi*(f*(t_i-t0) + 0.5*fdot*(t_i-t0)^2), with the
              nudot axis given as log10 magnitudes and applied as -10^x
              (spin-down only, periodsearch.py:95-98); t0 = (t[0]+t[-1])/2.

Design (TPU-first, replaces the reference's serial per-frequency Python
loop, which is O(N_events * N_trials * n_harm) on one core):

- events are the long axis (1e5..1e8): processed in fixed-size blocks via
  ``lax.scan`` so HBM footprint stays bounded;
- trials (frequency, or frequency x fdot) are vmapped within a block — the
  (trials x block) phase matrix is the compute tile XLA pipelines;
- harmonics use the Chebyshev recurrence cos(k t) = 2 cos t cos((k-1) t) -
  cos((k-2) t), so only ONE sin/cos pair per (trial, event) is evaluated
  regardless of harmonic count — an n_harm-fold transcendental saving over
  the reference;
- multi-chip: the same partial sums psum cleanly over an event-sharded mesh
  axis (see crimp_tpu.parallel).

Precision (the key TPU design decision): the phase accumulation f*t (+
fdot*t^2/2) runs in f64 — at 1e7-second baselines the product carries ~1e6
cycles and needs ~13 digits — but the TRIG runs in hardware f32 on the
mod-1-reduced fractional phase. f64 sin/cos on TPU is a ~100-op software
emulation (measured: a full 1e5-trial x 1e6-event all-f64 scan stalls the
chip), while the f64 multiply + floor + f32 transcendental costs a few ops.
Accuracy: the mod-1 reduction is exact to ~1e-10 cycles in f64, and f32
trig noise (~1e-7 per value) is orders below the sqrt(N) statistical noise
of the Z^2/H sums. Per-block sums accumulate in f32 (tree reduction) and
cross-block accumulation is f64. ``trig_dtype=jnp.float64`` restores the
all-f64 path for bit-level CPU parity checks.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from crimp_tpu import knobs, obs, resilience
from crimp_tpu.obs import costmodel
from crimp_tpu.ops import fasttrig
from crimp_tpu.resilience import faultinject

DEFAULT_EVENT_BLOCK = 1 << 16
DEFAULT_TRIAL_BLOCK = 256
DEFAULT_TRIG_DTYPE = jnp.float32


def _env_blocks(default_event: int, default_trial: int) -> tuple[int, int]:
    """CRIMP_TPU_GRID_BLOCKS="<event_block>,<trial_block>" override.

    Lets an on-chip sweep winner (scripts/sweep_blocks.py) be applied
    without a code edit. Read once at import; malformed values raise
    (silently ignoring a typo'd perf knob would be invisible).
    """
    env = knobs.raw("CRIMP_TPU_GRID_BLOCKS")
    if not env:
        return default_event, default_trial
    try:
        eb_s, tb_s = env.split(",")
        eb, tb = int(eb_s), int(tb_s)
        if eb <= 0 or tb <= 0:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"CRIMP_TPU_GRID_BLOCKS={env!r} not recognized; expected two "
            "positive integers '<event_block>,<trial_block>' (e.g. 32768,512)"
        ) from None
    return eb, tb


# Grid fast path: static fallback blocking (2^15 events x 512 trials, the
# pre-poly-trig TPU v5e optimum — 34.6k vs 33.1k trials/s against the
# general defaults; docs/performance.md). Since the autotuner landed these
# are only the last-resort defaults: resolve_blocks() prefers a cached
# sweep winner (scripts/sweep_blocks.py persists per-device/per-trig-path
# entries, including the factorized "grid_mxu" family), and
# CRIMP_TPU_GRID_BLOCKS stays the hard override.
GRID_EVENT_BLOCK, GRID_TRIAL_BLOCK = _env_blocks(1 << 15, 512)
# The fast path's f32 inner sweep carries phase error up to
# trial_block/2 * 2^-24 ~ 1.5e-5 cycles, which the Chebyshev recurrence
# amplifies ~linearly in harmonic number. Against the statistic's own
# noise the relative error is ~2.6*k*u independent of N (random-walk over
# events), i.e. ~8e-4 of the noise scale at k=20 — and measured directly:
# max |dH| = 7.8e-4 (1.2e-4 of sqrt-noise) at nharm=20 over a +-1e7 s
# baseline, identical argmax (r4, CPU, poly on and off). 20 is the
# conventional de Jager H-test maximum (the largest harmonic count any
# product workload sweeps; the reference's own defaults are smaller —
# nbrHarm=2 in periodsearch.py, 5 in measureToAs.py), so every product
# workload now takes the f64-lean path; beyond that, auto mode falls
# back to the exact-f64-phase general kernel.
GRID_FASTPATH_MAX_NHARM = 20
# Below this many (trial, event) pairs the dispatch/collective overhead of
# auto-sharding outweighs the parallel win (PeriodSearch._mesh).
MIN_SHARD_PAIRS = 1 << 22


def resolve_blocks(kernel: str, n_events: int, n_trials: int,
                   poly: bool = False,
                   event_block: int | None = None,
                   trial_block: int | None = None) -> tuple[int, int]:
    """Resolve (event_block, trial_block) through the autotuner.

    Thin lazy delegate to :func:`crimp_tpu.ops.autotune.resolve_blocks`
    (imported inside the call — autotune lazily imports this module, so a
    top-level import here would be circular during package init).
    Precedence: explicit args > CRIMP_TPU_GRID_BLOCKS (grid kernels only)
    > cached tuner winner > static defaults; CRIMP_TPU_AUTOTUNE=0 skips
    the cache entirely.
    """
    from crimp_tpu.ops import autotune

    return autotune.resolve_blocks(
        kernel, n_events, n_trials, poly=poly,
        event_block=event_block, trial_block=trial_block,
    )


def grid_fastpath_enabled(nharm: int, override: bool | None = None) -> bool:
    """Whether the uniform-grid f32 fast path should be used.

    Resolution order: explicit ``override`` > env ``CRIMP_TPU_GRID_FASTPATH``
    ("0"/"off" disables, "1"/"on" forces) > auto (nharm-based)."""
    if override is not None:
        return bool(override)
    state = knobs.parse_onoff(knobs.raw("CRIMP_TPU_GRID_FASTPATH"))
    if state is not None:
        return state
    return nharm <= GRID_FASTPATH_MAX_NHARM


def _block_times(times: jax.Array, block: int, weights: jax.Array | None = None):
    """Pad times to a multiple of ``block`` and reshape to (n_blocks, block).

    Padded entries carry weight 0 so they contribute nothing to the sums.
    ``weights`` lets a caller that already carries per-event validity (e.g.
    an event shard whose tail is mesh padding) thread it through.
    """
    n = times.shape[0]
    n_blocks = -(-n // block)
    padded = jnp.pad(times, (0, n_blocks * block - n))
    if weights is None:
        weights = jnp.ones(n, dtype=times.dtype)
    w_padded = jnp.pad(weights.astype(times.dtype), (0, n_blocks * block - n))
    return padded.reshape(n_blocks, block), w_padded.reshape(n_blocks, block)


def _harmonic_sums_cycles(
    phase_cycles: jax.Array, weights: jax.Array, nharm: int,
    trig_dtype=DEFAULT_TRIG_DTYPE, poly: bool = False,
):
    """(C_k, S_k) for k=1..nharm where C_k = sum_i w_i cos(2 pi k phi_i).

    ``phase_cycles``: (..., B) model phase in CYCLES (f64); the fractional
    part is extracted in f64, then trig + per-block sums run in
    ``trig_dtype``. ``poly`` swaps the hardware sin/cos for the fixed
    polynomial pair on the already-reduced argument (ops/fasttrig.py).
    Returns f64 arrays of shape (nharm, ...).
    """
    frac = fasttrig.centered_frac(phase_cycles)
    w = weights.astype(trig_dtype)
    if poly:
        sin1, cos1 = fasttrig.sincos_cycles(frac.astype(trig_dtype))
    else:
        theta = (2 * np.pi) * frac.astype(trig_dtype)
        cos1 = jnp.cos(theta)
        sin1 = jnp.sin(theta)
    c_sums, s_sums = chebyshev_weighted_sums(cos1, sin1, w, nharm)
    return c_sums.astype(jnp.float64), s_sums.astype(jnp.float64)


def chebyshev_weighted_sums(cos1, sin1, weights, nharm: int):
    """Weighted per-harmonic trig sums (nharm, ...) in the input dtype.

    Harmonic k comes from the Chebyshev recurrence cos(k t) = 2 cos t
    cos((k-1) t) - cos((k-2) t) (and its sine twin), so only the k=1
    sin/cos pair is ever evaluated; summation is over the trailing axis.
    Shared by the XLA kernels and the Pallas tile kernel.
    """
    cos_km1, sin_km1 = cos1, sin1  # k-1 term
    cos_km2 = jnp.ones_like(cos1)  # k-2 term (k=0: cos=1, sin=0)
    sin_km2 = jnp.zeros_like(sin1)
    c_list = [jnp.sum(weights * cos1, axis=-1)]
    s_list = [jnp.sum(weights * sin1, axis=-1)]
    for _ in range(1, nharm):
        cos_k = 2 * cos1 * cos_km1 - cos_km2
        sin_k = 2 * cos1 * sin_km1 - sin_km2
        c_list.append(jnp.sum(weights * cos_k, axis=-1))
        s_list.append(jnp.sum(weights * sin_k, axis=-1))
        cos_km2, sin_km2 = cos_km1, sin_km1
        cos_km1, sin_km1 = cos_k, sin_k
    return jnp.stack(c_list), jnp.stack(s_list)


def _blocked_trial_sums(
    times, freqs, nharm, event_block, trial_block, trig_dtype, phase_fn,
    weights=None, poly: bool = False,
):
    """Trig sums (nharm, n_freq), blocked on BOTH the trial and event axes.

    The live intermediate is one (trial_block, event_block) phase tile —
    HBM stays bounded no matter how many trials or events the caller asks
    for (a 1e5-trial x 1e6-event scan would otherwise materialize a
    multi-TB tensor). ``phase_fn(freq_blk, t_blk) -> cycles`` defines the
    trial family (pure frequency, frequency+fdot, ...).
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_freq = freqs.shape[0]
    n_freq_blocks = -(-n_freq // trial_block)
    freq_padded = jnp.pad(freqs, (0, n_freq_blocks * trial_block - n_freq)).reshape(
        n_freq_blocks, trial_block
    )

    def one_freq_block(freq_blk):
        def step(carry, blk):
            t_blk, w_blk = blk
            phase = phase_fn(freq_blk, t_blk)  # cycles, f64
            c, s = _harmonic_sums_cycles(phase, w_blk[None, :], nharm, trig_dtype, poly)
            return (carry[0] + c, carry[1] + s), None

        # Anchoring the init to the traced operands keeps the carry's
        # shard_map "varying" axes identical to the body output when this
        # runs inside a sharded kernel (compile-time no-op otherwise).
        anchor = 0.0 * (time_blocks[0, 0] + freq_blk[0])
        zeros = jnp.zeros((nharm, trial_block), dtype=jnp.float64) + anchor
        (c_sum, s_sum), _ = jax.lax.scan(step, (zeros, zeros), (time_blocks, weight_blocks))
        return c_sum, s_sum

    c_all, s_all = jax.lax.map(one_freq_block, freq_padded)  # (B, nharm, trial_block)
    c_all = jnp.moveaxis(c_all, 1, 0).reshape(nharm, -1)[:, :n_freq]
    s_all = jnp.moveaxis(s_all, 1, 0).reshape(nharm, -1)[:, :n_freq]
    return c_all, s_all


@partial(jax.jit, static_argnames=("nharm", "event_block", "trial_block", "trig_dtype", "poly"))
def harmonic_sums_1d(
    times: jax.Array,
    freqs: jax.Array,
    nharm: int,
    event_block: int = DEFAULT_EVENT_BLOCK,
    trial_block: int = DEFAULT_TRIAL_BLOCK,
    trig_dtype=DEFAULT_TRIG_DTYPE,
    poly: bool = False,
):
    """Trig sums (nharm, n_freq) over all events, blockwise on both axes."""
    return _blocked_trial_sums(
        times, freqs, nharm, event_block, trial_block, trig_dtype,
        lambda f_blk, t_blk: f_blk[:, None] * t_blk[None, :],
        poly=poly,
    )


def z2_from_sums(c_sum: jax.Array, s_sum: jax.Array, n_events) -> jax.Array:
    """Z^2 per harmonic from trig sums: (nharm, F) -> (nharm, F)."""
    return (c_sum**2 + s_sum**2) * (2.0 / n_events)


@partial(jax.jit, static_argnames=("nharm", "event_block", "trial_block", "trig_dtype", "poly"))
def z2_power(
    times: jax.Array,
    freqs: jax.Array,
    nharm: int = 2,
    event_block: int = DEFAULT_EVENT_BLOCK,
    trial_block: int = DEFAULT_TRIAL_BLOCK,
    trig_dtype=DEFAULT_TRIG_DTYPE,
    poly: bool = False,
) -> jax.Array:
    """Z^2_n power at each frequency (times pre-centered by the caller)."""
    c_sum, s_sum = harmonic_sums_1d(
        times, freqs, nharm, event_block, trial_block, trig_dtype, poly
    )
    return jnp.sum(z2_from_sums(c_sum, s_sum, times.shape[0]), axis=0)


@partial(jax.jit, static_argnames=("nharm", "event_block", "trial_block", "trig_dtype", "poly"))
def h_power(
    times: jax.Array,
    freqs: jax.Array,
    nharm: int = 20,
    event_block: int = DEFAULT_EVENT_BLOCK,
    trial_block: int = DEFAULT_TRIAL_BLOCK,
    trig_dtype=DEFAULT_TRIG_DTYPE,
    poly: bool = False,
) -> jax.Array:
    """H-test power at each frequency: max_m (cumsum Z^2_m - 4(m-1))."""
    c_sum, s_sum = harmonic_sums_1d(
        times, freqs, nharm, event_block, trial_block, trig_dtype, poly
    )
    z2_cum = jnp.cumsum(z2_from_sums(c_sum, s_sum, times.shape[0]), axis=0)
    penalties = 4.0 * jnp.arange(nharm, dtype=times.dtype)[:, None]
    return jnp.max(z2_cum - penalties, axis=0)


# ---------------------------------------------------------------------------
# Uniform-grid fast path
# ---------------------------------------------------------------------------


def uniform_grid(freqs: np.ndarray, rtol: float = 1e-12):
    """(f0, df) if ``freqs`` is a uniform grid, else None (host helper)."""
    f = np.asarray(freqs, dtype=np.float64)
    if f.ndim != 1 or f.size < 3:
        return None
    df = (f[-1] - f[0]) / (f.size - 1)
    if df == 0:
        return None
    recon = f[0] + df * np.arange(f.size)
    scale = max(abs(f[0]), abs(f[-1]))
    if np.max(np.abs(recon - f)) > rtol * scale:
        return None
    return float(f[0]), float(df)


@partial(jax.jit, static_argnames=("n_freq", "nharm", "event_block", "trial_block", "poly"))
def harmonic_sums_uniform(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    nharm: int,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    fdot: float | jax.Array = 0.0,
    weights: jax.Array | None = None,
    poly: bool = False,
):
    """Trig sums over the uniform grid f0 + j*df — the f64-lean fast path.

    Writing the trial index j = j0 + j_lo (tiles of ``trial_block``), the
    phase splits as f_j*t = [f0*t + (j0*df)*t] + j_lo*(df*t): the bracket is
    ONE f64 row per tile (mod-1 reduced exactly), and the inner j_lo sweep
    is pure f32 on frac(df*t) wrapped to [-0.5, 0.5), so its magnitude is
    bounded by trial_block/2 cycles — worst-case f32 frac accuracy
    ~trial_block/2 * 2^-24 ≈ 1.5e-5 cycles at the default tile (fine ToA
    grids with df*t << 1 sit near 1e-7). Both are far below the sqrt(N)
    noise of the statistic. The split removes (trial_block-1)/trial_block
    of the f64 work of the general path (f64 is software-emulated on TPU;
    measured +38% trials/s end-to-end on v5e).
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_tiles = -(-n_freq // trial_block)
    j_lo = jnp.arange(trial_block, dtype=jnp.float32)
    # b = df*t reduced mod 1 ONCE in f64 (O(N)); j_lo*b only ever needs the
    # fractional part since frac(j_lo*(b_int + b_frac)) = frac(j_lo*b_frac).
    # Wrapping to [-0.5, 0.5) bounds |j_lo*b| <= trial_block/2 cycles, so
    # the f32 frac extraction keeps ~1e-5-cycle accuracy even for coarse
    # grids (fine ToA-search grids sit orders below that).
    b_raw = df * time_blocks
    b_blocks = fasttrig.centered_frac(b_raw).astype(jnp.float32)

    def one_tile(tile_idx):
        f_tile = f0 + (tile_idx * trial_block) * df  # f64 scalar

        def step(carry, blk):
            t_blk, w_blk, b_blk = blk
            # f64: one row per tile; the fdot term rides the same row (it is
            # frequency-independent, so the j_lo sweep is untouched by it)
            base = f_tile * t_blk + (0.5 * fdot) * t_blk**2
            cb = fasttrig.centered_frac(base).astype(jnp.float32)
            phase32 = cb[None, :] + j_lo[:, None] * b_blk[None, :]
            c, s = _harmonic_sums_cycles(
                phase32, w_blk[None, :].astype(jnp.float32), nharm, jnp.float32, poly
            )
            return (carry[0] + c, carry[1] + s), None

        # Anchor the init to the traced operands so the carry's shard_map
        # "varying" axes match the body output inside sharded kernels.
        anchor = 0.0 * (time_blocks[0, 0] + f_tile)
        zeros = jnp.zeros((nharm, trial_block), dtype=jnp.float64) + anchor
        (c_sum, s_sum), _ = jax.lax.scan(
            step, (zeros, zeros), (time_blocks, weight_blocks, b_blocks)
        )
        return c_sum, s_sum

    c_all, s_all = jax.lax.map(one_tile, jnp.arange(n_tiles, dtype=jnp.float64))
    c_all = jnp.moveaxis(c_all, 1, 0).reshape(nharm, -1)[:, :n_freq]
    s_all = jnp.moveaxis(s_all, 1, 0).reshape(nharm, -1)[:, :n_freq]
    return c_all, s_all


def _grid_sums_dispatch(times, f0, df, n_freq, nharm, poly,
                        event_block, trial_block,
                        mxu, reseed, mxu_bf16):
    """(c, s, n_events) for the 1-D grid wrappers: resolves the factorized
    knob (explicit > env > cached winner > off) then the block tiling for
    whichever kernel won, and dispatches exact vs factorized."""
    n = np.shape(times)[0]
    use_mxu, rs, b16 = _resolve_grid_mxu(n, n_freq, poly, mxu, reseed, mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid", n, n_freq,
                            poly, event_block, trial_block)
    obs.counter_add("grid_trials", n_freq)
    if use_mxu:
        try:
            faultinject.fire("harmonic_sums")
            # one exact-sincos reseed row per `rs` trials per trial block
            obs.counter_add("grid_mxu_reseeds",
                            -(-int(n_freq) // max(1, int(rs))))
            dev_times = jnp.asarray(times)
            c, s = harmonic_sums_uniform_mxu(
                dev_times, f0, df, n_freq, nharm, eb, tb, poly=poly,
                reseed=rs, mxu_bf16=b16,
            )
            costmodel.capture("grid_sums_mxu", harmonic_sums_uniform_mxu,
                              dev_times, f0, df, n_freq, nharm, eb, tb,
                              poly=poly, reseed=rs, mxu_bf16=b16)
            return c, s, n
        except Exception as exc:  # noqa: BLE001 — grid ladder: a dead MXU
            # rung drops to the streamed exact-sincos kernel (bit-identical
            # to in-core exact, and it bounds device memory — the likely
            # failure cause), then to the in-core exact kernel.
            kind = resilience.classify(exc)
            eb, tb = resolve_blocks("grid", n, n_freq, poly, event_block,
                                    trial_block)
            try:
                resilience.record_degradation("grid", "streamed", kind)
                c, s = _streamed_uniform_sums(times, f0, df, n_freq, nharm,
                                              eb, tb, poly)
                return c, s, n
            except Exception as exc2:  # noqa: BLE001 — last rung: exact
                resilience.record_degradation("grid", "exact",
                                              resilience.classify(exc2))
    else:
        faultinject.fire("harmonic_sums")
    dev_times = jnp.asarray(times)
    c, s = harmonic_sums_uniform(
        dev_times, f0, df, n_freq, nharm, eb, tb, poly=poly,
    )
    costmodel.capture("grid_sums", harmonic_sums_uniform,
                      dev_times, f0, df, n_freq, nharm, eb, tb, poly=poly)
    return c, s, n


def z2_power_grid(
    times,
    f0: float,
    df: float,
    n_freq: int,
    nharm: int = 2,
    event_block: int | None = None,
    trial_block: int | None = None,
    poly: bool = False,
    mxu: bool | None = None,
    reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """Z^2_n over the uniform grid f0 + j*df (fast path; see above).

    Blocks default to the autotuner resolution (resolve_blocks): explicit
    arguments and CRIMP_TPU_GRID_BLOCKS stay hard overrides, a cached
    tuner winner is used when present, static defaults otherwise. ``mxu``
    selects the factorized matmul kernel the same way (explicit >
    CRIMP_TPU_GRID_MXU > cached A/B winner > off).
    """
    c, s, n = _grid_sums_dispatch(times, f0, df, n_freq, nharm, poly,
                                  event_block, trial_block, mxu, reseed,
                                  mxu_bf16)
    return jnp.sum(z2_from_sums(c, s, n), axis=0)


def h_power_grid(
    times,
    f0: float,
    df: float,
    n_freq: int,
    nharm: int = 20,
    event_block: int | None = None,
    trial_block: int | None = None,
    poly: bool = False,
    mxu: bool | None = None,
    reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """H-test over the uniform grid f0 + j*df (fast path)."""
    c, s, n = _grid_sums_dispatch(times, f0, df, n_freq, nharm, poly,
                                  event_block, trial_block, mxu, reseed,
                                  mxu_bf16)
    z2_cum = jnp.cumsum(z2_from_sums(c, s, n), axis=0)
    penalties = 4.0 * jnp.arange(nharm, dtype=jnp.float64)[:, None]
    return jnp.max(z2_cum - penalties, axis=0)


@partial(jax.jit, static_argnames=("n_freq", "nharm", "event_block", "trial_block", "poly"))
def harmonic_sums_uniform_2d(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    fdots: jax.Array,
    nharm: int,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    weights: jax.Array | None = None,
    poly: bool = False,
):
    """Trig sums over the (fdot x uniform-frequency) grid, sharing the f64
    rows across BOTH grid axes -> (n_fdot, nharm, n_freq) each.

    The phase at (fdot_i, trial j = j0 + j_lo) splits into three terms:

        f_j*t + fd_i*t^2/2 = [f_tile*t] + [fd_i*t^2/2] + j_lo*(df*t)

    The first bracket depends only on the TILE (one f64 row each), the
    second only on the FDOT (one f64 row each) — so the f64-emulated work
    per event block is (n_tiles + n_fdot) rows instead of the
    n_tiles*n_fdot rows paid when each fdot re-runs the 1-D fast path
    (the round-4 full-scale config 3 measured at 43% of the 1-D rate for
    exactly this reason). Each reduced term lies in [-0.5, 0.5), their
    f32 sum adds ~2 ulp (~1.2e-7 cycles) to the fast path's error budget
    (bounded by trial_block/2 * 2^-24 ~ 1.5e-5 cycles), and
    _harmonic_sums_cycles re-reduces before trig.
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_tiles = -(-n_freq // trial_block)
    j_lo = jnp.arange(trial_block, dtype=jnp.float32)
    b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
    f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    n_fdot = fd.shape[0]
    if n_fdot == 0:  # static at trace time; empty grid -> empty result
        empty = jnp.zeros((0, nharm, n_freq), jnp.float64)
        return empty, empty

    # Anchor the carry to the traced operands (shard_map varying axes).
    anchor = 0.0 * (time_blocks[0, 0] + f_tiles[0] + jnp.sum(fd))
    zeros = jnp.zeros((n_fdot, n_tiles, nharm, trial_block), jnp.float64) + anchor

    def step(carry, blk):
        t_blk, w_blk, b_blk = blk
        row_t = fasttrig.centered_frac(
            f_tiles[:, None] * t_blk[None, :]).astype(jnp.float32)       # (n_tiles, EB)
        row_q = fasttrig.centered_frac(
            (0.5 * fd)[:, None] * (t_blk * t_blk)[None, :]).astype(jnp.float32)  # (n_fdot, EB)
        w32 = w_blk.astype(jnp.float32)

        def per_fdot(q_row):
            def per_tile(t_row):
                phase32 = (t_row + q_row)[None, :] + j_lo[:, None] * b_blk[None, :]
                return _harmonic_sums_cycles(
                    phase32, w32[None, :], nharm, jnp.float32, poly
                )
            return jax.lax.map(per_tile, row_t)      # (n_tiles, nharm, TB) x2

        c, s = jax.lax.map(per_fdot, row_q)          # (n_fdot, n_tiles, nharm, TB) x2
        return (carry[0] + c, carry[1] + s), None

    (c_sum, s_sum), _ = jax.lax.scan(
        step, (zeros, zeros), (time_blocks, weight_blocks, b_blocks)
    )
    c_all = jnp.moveaxis(c_sum, 2, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
    s_all = jnp.moveaxis(s_sum, 2, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
    return c_all, s_all


def z2_power_2d_grid(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    fdots: jax.Array,
    nharm: int = 2,
    event_block: int | None = None,
    trial_block: int | None = None,
    poly: bool = False,
    mxu: bool | None = None,
    reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """Z^2_n over the (fdot x uniform-frequency) grid -> (n_fdot, n_freq).

    Built on harmonic_sums_uniform_2d: the per-tile f64 frequency rows are
    shared across fdots and the per-fdot f64 quadratic rows are shared
    across tiles. ``fdots`` are SIGNED Hz/s as in z2_power_2d. A plain
    (non-jitted) wrapper so blocks resolve through the autotuner per call;
    the heavy kernel underneath stays jitted. ``mxu`` selects the
    factorized matmul kernel (explicit > CRIMP_TPU_GRID_MXU > cached A/B
    winner > off).
    """
    times = jnp.asarray(times)
    n = times.shape[0]
    use_mxu, rs, b16 = _resolve_grid_mxu(int(n), int(n_freq), poly, mxu,
                                         reseed, mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid", int(n),
                            int(n_freq), poly, event_block, trial_block)
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    obs.counter_add("grid_trials", int(n_freq) * int(fd.shape[0]))
    if use_mxu:
        obs.counter_add("grid_mxu_reseeds",
                        -(-int(n_freq) // max(1, int(rs))) * int(fd.shape[0]))
        c, s = harmonic_sums_uniform_2d_mxu(
            times, f0, df, n_freq, fd, nharm, eb, tb, poly=poly,
            reseed=rs, mxu_bf16=b16,
        )
        costmodel.capture("grid_sums_2d_mxu", harmonic_sums_uniform_2d_mxu,
                          times, f0, df, n_freq, fd, nharm, eb, tb,
                          poly=poly, reseed=rs, mxu_bf16=b16)
    else:
        c, s = harmonic_sums_uniform_2d(
            times, f0, df, n_freq, fd, nharm, eb, tb, poly=poly,
        )
        costmodel.capture("grid_sums_2d", harmonic_sums_uniform_2d,
                          times, f0, df, n_freq, fd, nharm, eb, tb, poly=poly)
    return jnp.sum(z2_from_sums(c, s, n), axis=1)


# ---------------------------------------------------------------------------
# The (f, fdot, fddot) search cube — third-order (jerk) uniform-grid kernels
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_freq", "nharm", "event_block", "trial_block", "poly"))
def harmonic_sums_uniform_3d(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    fdots: jax.Array,
    fddots: jax.Array,
    nharm: int,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    weights: jax.Array | None = None,
    poly: bool = False,
):
    """Trig sums over the (fddot x fdot x uniform-frequency) search cube,
    sharing the f64 rows across ALL THREE grid axes
    -> (n_fddot, n_fdot, nharm, n_freq) each.

    The jerk-search phase at (fddot_l, fdot_i, trial j = j0 + j_lo) splits
    into four terms:

        f_j*t + fd_i*t^2/2 + fdd_l*t^3/6
            = [f_tile*t] + [fd_i*t^2/2] + [fdd_l*t^3/6] + j_lo*(df*t)

    One f64 row per TILE, one per FDOT, one per FDDOT — the f64-emulated
    work per event block is (n_tiles + n_fdot + n_fddot) rows instead of
    the n_tiles*n_fdot*n_fddot rows of re-running the 2-D kernel once per
    fddot. Each reduced term lies in [-0.5, 0.5); summing three of them in
    f32 adds ~3 ulp (~1.8e-7 cycles) on top of the fast path's
    trial_block/2 * 2^-24 budget, and _harmonic_sums_cycles re-reduces
    before trig. The cubic row's f64 rounding is harmless: t^3 can exceed
    2^53 at long baselines, but its RELATIVE error (~1e-16) is scaled by
    fdd*t^3/6 cycles, i.e. far below a micro-cycle for any physical jerk.
    With fddots == [0.0] the cubic row is exactly zero and the result is
    bit-identical to harmonic_sums_uniform_2d (the association
    (row_t + row_q) + row_r preserves the 2-D sum).
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_tiles = -(-n_freq // trial_block)
    j_lo = jnp.arange(trial_block, dtype=jnp.float32)
    b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
    f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    fdd = jnp.asarray(fddots, dtype=jnp.float64)
    n_fdot = fd.shape[0]
    n_fddot = fdd.shape[0]
    if n_fdot == 0 or n_fddot == 0:  # static at trace time; empty -> empty
        empty = jnp.zeros((n_fddot, n_fdot, nharm, n_freq), jnp.float64)
        return empty, empty

    # Anchor the carry to the traced operands (shard_map varying axes).
    anchor = 0.0 * (time_blocks[0, 0] + f_tiles[0] + jnp.sum(fd) + jnp.sum(fdd))
    zeros = jnp.zeros((n_fddot, n_fdot, n_tiles, nharm, trial_block),
                      jnp.float64) + anchor

    def step(carry, blk):
        t_blk, w_blk, b_blk = blk
        row_t = fasttrig.centered_frac(
            f_tiles[:, None] * t_blk[None, :]).astype(jnp.float32)       # (n_tiles, EB)
        row_q = fasttrig.centered_frac(
            (0.5 * fd)[:, None] * (t_blk * t_blk)[None, :]).astype(jnp.float32)  # (n_fdot, EB)
        row_r = fasttrig.centered_frac(
            (fdd / 6.0)[:, None] * (t_blk * t_blk * t_blk)[None, :]
        ).astype(jnp.float32)                                            # (n_fddot, EB)
        w32 = w_blk.astype(jnp.float32)

        def per_fddot(r_row):
            def per_fdot(q_row):
                def per_tile(t_row):
                    phase32 = ((t_row + q_row) + r_row)[None, :] \
                        + j_lo[:, None] * b_blk[None, :]
                    return _harmonic_sums_cycles(
                        phase32, w32[None, :], nharm, jnp.float32, poly
                    )
                return jax.lax.map(per_tile, row_t)  # (n_tiles, nharm, TB) x2
            return jax.lax.map(per_fdot, row_q)      # (n_fdot, n_tiles, nharm, TB) x2

        c, s = jax.lax.map(per_fddot, row_r)
        return (carry[0] + c, carry[1] + s), None

    (c_sum, s_sum), _ = jax.lax.scan(
        step, (zeros, zeros), (time_blocks, weight_blocks, b_blocks)
    )
    c_all = jnp.moveaxis(c_sum, 3, 2).reshape(
        n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
    s_all = jnp.moveaxis(s_sum, 3, 2).reshape(
        n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
    return c_all, s_all


def _grid3d_sums_dispatch(times, f0, df, n_freq, fdots, fddots, nharm, poly,
                          event_block, trial_block, mxu, reseed, mxu_bf16,
                          weights=None):
    """(c, s, n_events) for the 3-D cube wrappers.

    Same resolution discipline as _grid_sums_dispatch — factorized knob
    explicit > CRIMP_TPU_GRID_MXU > cached "grid3d" A/B winner > off,
    blocks through the autotuner under the "grid3d" key — and the same
    grid resilience ladder: a dead MXU rung drops to the streamed
    exact-sincos kernel, then to the in-core exact kernel. ``weights``
    (per-event validity, e.g. semi-coherent segment masks) skips the
    streamed rung because the streamed driver derives its own
    chunk-validity weights.
    """
    n = np.shape(times)[0]
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    fdd = jnp.asarray(fddots, dtype=jnp.float64)
    n_cube = int(n_freq) * int(fd.shape[0]) * int(fdd.shape[0])
    use_mxu, rs, b16 = _resolve_grid3d_mxu(n, n_cube, poly, mxu, reseed,
                                           mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid3d", n, n_freq,
                            poly, event_block, trial_block)
    obs.counter_add("grid_trials", n_cube)
    dev_times = jnp.asarray(times)
    if use_mxu:
        try:
            faultinject.fire("harmonic_sums")
            # one exact-sincos reseed row per `rs` trials per cube row
            obs.counter_add(
                "grid_mxu_reseeds",
                -(-int(n_freq) // max(1, int(rs)))
                * int(fd.shape[0]) * int(fdd.shape[0]))
            c, s = harmonic_sums_uniform_3d_mxu(
                dev_times, f0, df, n_freq, fd, fdd, nharm, eb, tb,
                weights=weights, poly=poly, reseed=rs, mxu_bf16=b16)
            costmodel.capture("grid_sums_3d_mxu", harmonic_sums_uniform_3d_mxu,
                              dev_times, f0, df, n_freq, fd, fdd, nharm,
                              eb, tb, weights=weights, poly=poly, reseed=rs,
                              mxu_bf16=b16)
            return c, s, n
        except Exception as exc:  # noqa: BLE001 — grid ladder (see 1-D twin)
            kind = resilience.classify(exc)
            eb, tb = resolve_blocks("grid3d", n, n_freq, poly, event_block,
                                    trial_block)
            if weights is None:
                try:
                    resilience.record_degradation("grid", "streamed", kind)
                    c, s = _streamed_uniform_sums(times, f0, df, n_freq,
                                                  nharm, eb, tb, poly,
                                                  fdots=fd, fddots=fdd)
                    return c, s, n
                except Exception as exc2:  # noqa: BLE001 — last rung: exact
                    resilience.record_degradation("grid", "exact",
                                                  resilience.classify(exc2))
            else:
                resilience.record_degradation("grid", "exact", kind)
    else:
        faultinject.fire("harmonic_sums")
    c, s = harmonic_sums_uniform_3d(
        dev_times, f0, df, n_freq, fd, fdd, nharm, eb, tb,
        weights=weights, poly=poly)
    costmodel.capture("grid_sums_3d", harmonic_sums_uniform_3d,
                      dev_times, f0, df, n_freq, fd, fdd, nharm, eb, tb,
                      weights=weights, poly=poly)
    return c, s, n


def z2_power_3d_grid(
    times,
    f0: float,
    df: float,
    n_freq: int,
    fdots,
    fddots,
    nharm: int = 2,
    event_block: int | None = None,
    trial_block: int | None = None,
    poly: bool = False,
    mxu: bool | None = None,
    reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """Z^2_n over the (fddot x fdot x uniform-frequency) search cube
    -> (n_fddot, n_fdot, n_freq).

    Built on harmonic_sums_uniform_3d: the per-tile, per-fdot and
    per-fddot f64 rows are each shared across the other two grid axes.
    ``fdots``/``fddots`` are SIGNED Hz/s and Hz/s^2. ``mxu`` selects the
    factorized matmul kernel (explicit > CRIMP_TPU_GRID_MXU > cached
    grid3d A/B winner > off).
    """
    c, s, n = _grid3d_sums_dispatch(times, f0, df, n_freq, fdots, fddots,
                                    nharm, poly, event_block, trial_block,
                                    mxu, reseed, mxu_bf16)
    return jnp.sum(z2_from_sums(c, s, n), axis=2)


def h_power_3d_grid(
    times,
    f0: float,
    df: float,
    n_freq: int,
    fdots,
    fddots,
    nharm: int = 20,
    event_block: int | None = None,
    trial_block: int | None = None,
    poly: bool = False,
    mxu: bool | None = None,
    reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """H-test over the (fddot x fdot x uniform-frequency) search cube
    -> (n_fddot, n_fdot, n_freq)."""
    c, s, n = _grid3d_sums_dispatch(times, f0, df, n_freq, fdots, fddots,
                                    nharm, poly, event_block, trial_block,
                                    mxu, reseed, mxu_bf16)
    z2_cum = jnp.cumsum(z2_from_sums(c, s, n), axis=2)
    penalties = 4.0 * jnp.arange(nharm, dtype=jnp.float64)[None, None, :, None]
    return jnp.max(z2_cum - penalties, axis=2)


# ---------------------------------------------------------------------------
# Factorized (matmul) uniform-grid kernels — the CRIMP_TPU_GRID_MXU path
# ---------------------------------------------------------------------------
#
# The uniform-grid phase is AFFINE in the trial index: for trial
# j = j0 + j_lo, harmonic k and fdot row i,
#
#     k*phase(j, i, e) = k*theta0(tile, i, e) + j_lo * (k * b_e)
#
# so cos/sin factor by the angle-addition identity into a per-ROW part
# (theta0: one row per tile [+ fdot], Chebyshev in k — shared across the
# whole j_lo sweep) and a per-TRIAL sweep part (cos/sin(2*pi*j_lo*k*b_e):
# rotation recurrence in j_lo + Chebyshev in k — shared across ALL tiles
# and fdot rows). The harmonic sums then become real matmuls
#
#     C_k = Xw_k @ Csw_k^T - Yw_k @ Ssw_k^T        (n_rows, EB) @ (EB, TB)
#     S_k = Yw_k @ Csw_k^T + Xw_k @ Ssw_k^T
#
# with Xw_k[r, e] = w_e*cos(2*pi*k*theta0), Yw_k the sine twin. Per-block
# transcendentals drop from O(n_rows*TB*EB) (dense: one sin/cos pair per
# (trial, event) pair) to O((n_rows + TB/reseed + 1)*EB), and the event
# reduction moves from VPU tree-sums onto the MXU. The 2-D kernel benefits
# most: its rows stack as n_fdot*n_tiles. Exact kernels above stay the
# default and the fallback, untouched at the bit level.
#
# Error budget (docs/performance.md "Factorized grid kernels" derives and
# tests/test_search.py::TestGridMXU pins it): the sweep seeds carry the
# same trial_block/2 * 2^-24 ~ 1.5e-5-cycle f32 bound as the exact fast
# path's j_lo*b product; the angle-addition rotation adds a random-walk
# drift of ~sqrt(reseed)*2^-24 per trig value between exact-sincos
# reseeds, so the default reseed=64 keeps the drift (~1e-6) at the
# poly-trig floor (3.1e-7..3.6e-7 per value) rather than above it.

GRID_MXU_RESEED = 64  # default reseed stride (autotunable; power of two
# keeps the seed product reseed*b exact in f32)


def _trig_rows(frac_cycles: jax.Array, poly: bool):
    """(cos, sin) of 2*pi*frac_cycles, input already reduced to [-0.5, 0.5)."""
    if poly:
        s, c = fasttrig.sincos_cycles(frac_cycles)
        return c, s
    theta = (2 * np.pi) * frac_cycles
    return jnp.cos(theta), jnp.sin(theta)


def _sweep_matrices(b_blk: jax.Array, trial_block: int, reseed: int, poly: bool):
    """cos/sin(2*pi*j_lo*b_e) for j_lo = 0..trial_block-1 -> (TB, EB) pair.

    Exact sincos is evaluated only at the reseed anchors j_lo = m*reseed
    (ceil(TB/reseed) rows); within a segment the pair advances by the
    angle-addition rotation cos((j+1)a) = cos(ja)cos(a) - sin(ja)sin(a),
    which is exact in infinite precision — its f32 drift random-walks at
    ~2^-24 per step and is cut back to zero at every anchor. The anchor
    phases m*reseed*b are reduced in f32, the same trial_block/2 * 2^-24
    bound the exact kernel's j_lo*b product carries.
    """
    reseed = max(1, min(int(reseed), trial_block))
    n_seg = -(-trial_block // reseed)
    seg = jnp.arange(n_seg, dtype=jnp.float32)
    seed_frac = fasttrig.centered_frac(seg[:, None] * (reseed * b_blk)[None, :])
    c_seed, s_seed = _trig_rows(seed_frac, poly)   # (n_seg, EB)
    ca, sa = _trig_rows(b_blk, poly)               # rotation by 2*pi*b

    def rot(carry, _):
        c, s = carry
        return (c * ca - s * sa, s * ca + c * sa), (c, s)

    _, (c_all, s_all) = jax.lax.scan(rot, (c_seed, s_seed), None, length=reseed)
    csw = jnp.moveaxis(c_all, 0, 1).reshape(n_seg * reseed, -1)[:trial_block]
    ssw = jnp.moveaxis(s_all, 0, 1).reshape(n_seg * reseed, -1)[:trial_block]
    return csw, ssw


def _mxu_dot(a: jax.Array, b: jax.Array, mxu_bf16: bool) -> jax.Array:
    """a @ b^T with f32 accumulation; optional bf16 operands (MXU native)."""
    if mxu_bf16:
        a = a.astype(jnp.bfloat16)
        b = b.astype(jnp.bfloat16)
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )


def _factored_harmonic_sums(cos0, sin0, weights, csw, ssw, nharm: int,
                            mxu_bf16: bool):
    """(C, S) of shape (nharm, n_rows, TB) from the factor matrices.

    ``cos0``/``sin0``: (n_rows, EB) trig of the per-row base phase theta0;
    ``csw``/``ssw``: (TB, EB) sweep matrices cos/sin(2*pi*j_lo*b). Harmonic
    k of BOTH factors comes from the same Chebyshev recurrence the dense
    kernel uses; the event reduction runs as four f32-accumulated matmuls
    per harmonic — MXU work instead of VPU transcendentals.
    """
    w = weights[None, :]
    ck, sk = cos0, sin0
    ck_m2 = jnp.ones_like(cos0)
    sk_m2 = jnp.zeros_like(sin0)
    cswk, sswk = csw, ssw
    cswk_m2 = jnp.ones_like(csw)
    sswk_m2 = jnp.zeros_like(ssw)
    c_list, s_list = [], []
    for k in range(nharm):
        if k:
            ck, ck_m2 = 2 * cos0 * ck - ck_m2, ck
            sk, sk_m2 = 2 * cos0 * sk - sk_m2, sk
            cswk, cswk_m2 = 2 * csw * cswk - cswk_m2, cswk
            sswk, sswk_m2 = 2 * csw * sswk - sswk_m2, sswk
        xw = w * ck
        yw = w * sk
        c_list.append(_mxu_dot(xw, cswk, mxu_bf16) - _mxu_dot(yw, sswk, mxu_bf16))
        s_list.append(_mxu_dot(yw, cswk, mxu_bf16) + _mxu_dot(xw, sswk, mxu_bf16))
    return (jnp.stack(c_list).astype(jnp.float64),
            jnp.stack(s_list).astype(jnp.float64))


def _mxu_1d_step(f_tiles, fdot, nharm, trial_block, poly, reseed, mxu_bf16):
    """Per-event-block scan body of the factorized 1-D kernel — shared by
    the monolithic kernel and the streamed carry update so the streamed
    result stays bit-identical to the monolithic one."""

    def step(carry, blk):
        t_blk, w_blk, b_blk = blk
        theta0 = fasttrig.centered_frac(
            f_tiles[:, None] * t_blk[None, :]
            + ((0.5 * fdot) * t_blk**2)[None, :]
        ).astype(jnp.float32)                          # (n_tiles, EB)
        c0, s0 = _trig_rows(theta0, poly)
        csw, ssw = _sweep_matrices(b_blk, trial_block, reseed, poly)
        ck, sk = _factored_harmonic_sums(
            c0, s0, w_blk.astype(jnp.float32), csw, ssw, nharm, mxu_bf16)
        return (carry[0] + ck, carry[1] + sk), None

    return step


def _mxu_2d_step(f_tiles, fd, nharm, n_tiles, trial_block, poly, reseed,
                 mxu_bf16):
    """Per-event-block scan body of the factorized 2-D kernel (shared by
    the monolithic kernel and the streamed carry update)."""
    n_fdot = fd.shape[0]

    def step(carry, blk):
        t_blk, w_blk, b_blk = blk
        row_t = fasttrig.centered_frac(
            f_tiles[:, None] * t_blk[None, :]).astype(jnp.float32)
        row_q = fasttrig.centered_frac(
            (0.5 * fd)[:, None] * (t_blk * t_blk)[None, :]).astype(jnp.float32)
        ct, st = _trig_rows(row_t, poly)               # (n_tiles, EB)
        cq, sq = _trig_rows(row_q, poly)               # (n_fdot, EB)
        # cos/sin(2*pi*(theta_tile + theta_fdot)) by angle addition: one
        # elementwise outer combine instead of n_fdot*n_tiles transcendental
        # rows — the rows then stack as the matmul's M axis
        c0 = (cq[:, None, :] * ct[None, :, :]
              - sq[:, None, :] * st[None, :, :]).reshape(n_fdot * n_tiles, -1)
        s0 = (sq[:, None, :] * ct[None, :, :]
              + cq[:, None, :] * st[None, :, :]).reshape(n_fdot * n_tiles, -1)
        csw, ssw = _sweep_matrices(b_blk, trial_block, reseed, poly)
        ck, sk = _factored_harmonic_sums(
            c0, s0, w_blk.astype(jnp.float32), csw, ssw, nharm, mxu_bf16)
        ck = ck.reshape(nharm, n_fdot, n_tiles, trial_block)
        sk = sk.reshape(nharm, n_fdot, n_tiles, trial_block)
        return (carry[0] + ck, carry[1] + sk), None

    return step


@partial(jax.jit, static_argnames=("n_freq", "nharm", "event_block",
                                   "trial_block", "poly", "reseed", "mxu_bf16"))
def harmonic_sums_uniform_mxu(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    nharm: int,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    fdot: float | jax.Array = 0.0,
    weights: jax.Array | None = None,
    poly: bool = False,
    reseed: int = GRID_MXU_RESEED,
    mxu_bf16: bool = False,
):
    """Factorized (matmul) twin of :func:`harmonic_sums_uniform`.

    Same contract and output shape (nharm, n_freq); the harmonic sums are
    computed as C_k = Xw_k @ Csw_k^T - Yw_k @ Ssw_k^T per event block (see
    the section comment above for the factorization and its error budget).
    Cross-block accumulation stays f64 in the same scan order as the exact
    kernel.
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_tiles = -(-n_freq // trial_block)
    b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
    f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
    anchor = 0.0 * (time_blocks[0, 0] + f_tiles[0])
    zeros = jnp.zeros((nharm, n_tiles, trial_block), jnp.float64) + anchor
    step = _mxu_1d_step(f_tiles, fdot, nharm, trial_block, poly, reseed,
                        mxu_bf16)
    (c_sum, s_sum), _ = jax.lax.scan(
        step, (zeros, zeros), (time_blocks, weight_blocks, b_blocks))
    c_all = c_sum.reshape(nharm, -1)[:, :n_freq]
    s_all = s_sum.reshape(nharm, -1)[:, :n_freq]
    return c_all, s_all


@partial(jax.jit, static_argnames=("n_freq", "nharm", "event_block",
                                   "trial_block", "poly", "reseed", "mxu_bf16"))
def harmonic_sums_uniform_2d_mxu(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    fdots: jax.Array,
    nharm: int,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    weights: jax.Array | None = None,
    poly: bool = False,
    reseed: int = GRID_MXU_RESEED,
    mxu_bf16: bool = False,
    tile0: int | jax.Array = 0,
):
    """Factorized (matmul) twin of :func:`harmonic_sums_uniform_2d`.

    Same contract and output shapes (n_fdot, nharm, n_freq). This is the
    kernel the factorization helps most: the dense path pays one sin/cos
    pair per (fdot, tile, trial, event) while here the transcendental count
    is O((n_tiles + n_fdot + TB/reseed)*EB) per block and the reduction is
    n_fdot*n_tiles matmul rows — deep MXU work.

    ``tile0`` offsets the tile index (traced; the sharded wrapper passes
    the shard's global first tile so f_tiles rounds in ONE f64 multiply,
    bitwise-identical to the monolithic kernel's expression — adding a
    pre-rounded f0_shard instead would cost a second rounding).
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_tiles = -(-n_freq // trial_block)
    b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
    tiles = (jnp.asarray(tile0, jnp.float64)
             + jnp.arange(n_tiles, dtype=jnp.float64))
    f_tiles = f0 + (tiles * trial_block) * df
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    n_fdot = fd.shape[0]
    if n_fdot == 0:  # static at trace time; empty grid -> empty result
        empty = jnp.zeros((0, nharm, n_freq), jnp.float64)
        return empty, empty
    anchor = 0.0 * (time_blocks[0, 0] + f_tiles[0] + jnp.sum(fd))
    zeros = jnp.zeros((nharm, n_fdot, n_tiles, trial_block), jnp.float64) + anchor
    step = _mxu_2d_step(f_tiles, fd, nharm, n_tiles, trial_block, poly,
                        reseed, mxu_bf16)
    (c_sum, s_sum), _ = jax.lax.scan(
        step, (zeros, zeros), (time_blocks, weight_blocks, b_blocks))
    c_all = jnp.moveaxis(c_sum, 0, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
    s_all = jnp.moveaxis(s_sum, 0, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
    return c_all, s_all


def _mxu_3d_step(f_tiles, fd, fdd, nharm, n_tiles, trial_block, poly,
                 reseed, mxu_bf16):
    """Per-event-block scan body of the factorized 3-D kernel (shared by
    the monolithic kernel and the streamed carry update)."""
    n_fdot = fd.shape[0]
    n_fddot = fdd.shape[0]

    def step(carry, blk):
        t_blk, w_blk, b_blk = blk
        row_t = fasttrig.centered_frac(
            f_tiles[:, None] * t_blk[None, :]).astype(jnp.float32)
        row_q = fasttrig.centered_frac(
            (0.5 * fd)[:, None] * (t_blk * t_blk)[None, :]).astype(jnp.float32)
        row_r = fasttrig.centered_frac(
            (fdd / 6.0)[:, None] * (t_blk * t_blk * t_blk)[None, :]
        ).astype(jnp.float32)
        ct, st = _trig_rows(row_t, poly)               # (n_tiles, EB)
        cq, sq = _trig_rows(row_q, poly)               # (n_fdot, EB)
        cr, sr = _trig_rows(row_r, poly)               # (n_fddot, EB)
        # two angle additions: tile (+) fdot, then (+) fddot — the cube's
        # base-phase trig costs n_tiles + n_fdot + n_fddot transcendental
        # rows while the matmul's M axis stacks n_fddot*n_fdot*n_tiles rows
        c_qt = (cq[:, None, :] * ct[None, :, :]
                - sq[:, None, :] * st[None, :, :])     # (n_fdot, n_tiles, EB)
        s_qt = (sq[:, None, :] * ct[None, :, :]
                + cq[:, None, :] * st[None, :, :])
        c0 = (cr[:, None, None, :] * c_qt[None, :, :, :]
              - sr[:, None, None, :] * s_qt[None, :, :, :]
              ).reshape(n_fddot * n_fdot * n_tiles, -1)
        s0 = (sr[:, None, None, :] * c_qt[None, :, :, :]
              + cr[:, None, None, :] * s_qt[None, :, :, :]
              ).reshape(n_fddot * n_fdot * n_tiles, -1)
        csw, ssw = _sweep_matrices(b_blk, trial_block, reseed, poly)
        ck, sk = _factored_harmonic_sums(
            c0, s0, w_blk.astype(jnp.float32), csw, ssw, nharm, mxu_bf16)
        ck = ck.reshape(nharm, n_fddot, n_fdot, n_tiles, trial_block)
        sk = sk.reshape(nharm, n_fddot, n_fdot, n_tiles, trial_block)
        return (carry[0] + ck, carry[1] + sk), None

    return step


@partial(jax.jit, static_argnames=("n_freq", "nharm", "event_block",
                                   "trial_block", "poly", "reseed", "mxu_bf16"))
def harmonic_sums_uniform_3d_mxu(
    times: jax.Array,
    f0: float,
    df: float,
    n_freq: int,
    fdots: jax.Array,
    fddots: jax.Array,
    nharm: int,
    event_block: int = GRID_EVENT_BLOCK,
    trial_block: int = GRID_TRIAL_BLOCK,
    weights: jax.Array | None = None,
    poly: bool = False,
    reseed: int = GRID_MXU_RESEED,
    mxu_bf16: bool = False,
    tile0: int | jax.Array = 0,
):
    """Factorized (matmul) twin of :func:`harmonic_sums_uniform_3d`.

    Same contract and output shapes (n_fddot, n_fdot, nharm, n_freq). The
    cube is where the factorization pays the most: the dense path's
    transcendental count is one sin/cos pair per (fddot, fdot, tile,
    trial, event) while here it is O((n_tiles + n_fdot + n_fddot +
    TB/reseed)*EB) per event block — the third grid axis costs ONE extra
    angle-addition combine, and the event reduction runs as
    n_fddot*n_fdot*n_tiles-row matmuls (deeper MXU work than the 2-D
    kernel at the same trial count). ``tile0`` offsets the tile index for
    sharded callers exactly as in harmonic_sums_uniform_2d_mxu.
    """
    time_blocks, weight_blocks = _block_times(times, event_block, weights)
    n_tiles = -(-n_freq // trial_block)
    b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
    tiles = (jnp.asarray(tile0, jnp.float64)
             + jnp.arange(n_tiles, dtype=jnp.float64))
    f_tiles = f0 + (tiles * trial_block) * df
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    fdd = jnp.asarray(fddots, dtype=jnp.float64)
    n_fdot = fd.shape[0]
    n_fddot = fdd.shape[0]
    if n_fdot == 0 or n_fddot == 0:  # static at trace time; empty -> empty
        empty = jnp.zeros((n_fddot, n_fdot, nharm, n_freq), jnp.float64)
        return empty, empty
    anchor = 0.0 * (time_blocks[0, 0] + f_tiles[0] + jnp.sum(fd) + jnp.sum(fdd))
    zeros = jnp.zeros((nharm, n_fddot, n_fdot, n_tiles, trial_block),
                      jnp.float64) + anchor
    step = _mxu_3d_step(f_tiles, fd, fdd, nharm, n_tiles, trial_block, poly,
                        reseed, mxu_bf16)
    (c_sum, s_sum), _ = jax.lax.scan(
        step, (zeros, zeros), (time_blocks, weight_blocks, b_blocks))
    c_all = jnp.moveaxis(c_sum, 0, 2).reshape(
        n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
    s_all = jnp.moveaxis(s_sum, 0, 2).reshape(
        n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
    return c_all, s_all


def _resolve_grid_mxu(n_events: int, n_trials: int, poly: bool,
                      mxu: bool | None, reseed: int | None,
                      mxu_bf16: bool | None) -> tuple[bool, int, bool]:
    """(use_mxu, reseed, mxu_bf16) for the grid wrappers.

    Explicit arguments are hard overrides; anything left None resolves
    through autotune.resolve_grid_mxu (env CRIMP_TPU_GRID_MXU > cached A/B
    winner > default off — same precedence discipline as the ToA knobs).
    """
    if mxu is not None and reseed is not None and mxu_bf16 is not None:
        return bool(mxu), int(reseed), bool(mxu_bf16)
    from crimp_tpu.ops import autotune

    r = autotune.resolve_grid_mxu(n_events, n_trials, poly=poly)
    use = bool(r["grid_mxu"]) if mxu is None else bool(mxu)
    rs = int(r["reseed"]) if reseed is None else int(reseed)
    b16 = bool(r["mxu_bf16"]) if mxu_bf16 is None else bool(mxu_bf16)
    return use, rs, b16


def _resolve_grid3d_mxu(n_events: int, n_trials: int, poly: bool,
                        mxu: bool | None, reseed: int | None,
                        mxu_bf16: bool | None) -> tuple[bool, int, bool]:
    """(use_mxu, reseed, mxu_bf16) for the 3-D cube wrappers.

    Same discipline as _resolve_grid_mxu, but the cached A/B winner lives
    under the autotune "grid3d" family (its win is gated by bench.py
    bench_jerk against the exact 3-D kernel, not by the 1-D/2-D A/B).
    CRIMP_TPU_GRID_MXU stays the one shared hard override for every
    factorized grid kernel — no separate 3-D env knob.
    """
    if mxu is not None and reseed is not None and mxu_bf16 is not None:
        return bool(mxu), int(reseed), bool(mxu_bf16)
    from crimp_tpu.ops import autotune

    r = autotune.resolve_grid3d_mxu(n_events, n_trials, poly=poly)
    use = bool(r["grid_mxu"]) if mxu is None else bool(mxu)
    rs = int(r["reseed"]) if reseed is None else int(reseed)
    b16 = bool(r["mxu_bf16"]) if mxu_bf16 is None else bool(mxu_bf16)
    return use, rs, b16


# ---------------------------------------------------------------------------
# Double-buffered streaming (host -> device overlap)
# ---------------------------------------------------------------------------

# Events per streamed chunk (rounded down to an event_block multiple).
# 2^21 f64 times = 16 MiB per transfer: big enough to amortize dispatch,
# small enough that the next chunk's host->device copy hides entirely
# under the current chunk's compute.
STREAM_EVENT_CHUNK = 1 << 21


def stream_min_events() -> int | None:
    """Event count above which the resumable driver streams chunks.

    CRIMP_TPU_STREAM_MIN_EVENTS: unset -> 2^22; "0"/"off" disables
    streaming; otherwise an integer threshold.
    """
    env = knobs.raw("CRIMP_TPU_STREAM_MIN_EVENTS").lower()
    if env in knobs.OFF_WORDS:
        return None
    if not env:
        return 1 << 22
    try:
        return int(env)
    except ValueError:
        raise ValueError(
            f"CRIMP_TPU_STREAM_MIN_EVENTS={env!r} not recognized; expected "
            "an integer event count or 0/off"
        ) from None


@lru_cache(maxsize=None)
def _grid_stream_update(nharm: int, n_tiles: int, event_block: int,
                        trial_block: int, poly: bool, donate: bool):
    """Jitted carry update for one streamed chunk of the 1-D grid kernel.

    The body replays harmonic_sums_uniform's per-tile scan EXACTLY — same
    per-block phase math, same f64 accumulation order, with the carry
    threaded across chunks instead of initialized to zero — so the
    streamed result is bit-identical to the monolithic kernel. Donating
    the accumulators lets XLA update them in place (skipped on CPU where
    donation is unimplemented and only warns).
    """

    def update(c, s, chunk_times, n_valid, f0, df, fdot):
        time_blocks = chunk_times.reshape(-1, event_block)
        w = (jnp.arange(chunk_times.shape[0]) < n_valid).astype(jnp.float64)
        weight_blocks = w.reshape(-1, event_block)
        b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
        j_lo = jnp.arange(trial_block, dtype=jnp.float32)

        def one_tile(args):
            tile_idx, c0, s0 = args
            f_tile = f0 + (tile_idx * trial_block) * df

            def step(carry, blk):
                t_blk, w_blk, b_blk = blk
                base = f_tile * t_blk + (0.5 * fdot) * t_blk**2
                cb = fasttrig.centered_frac(base).astype(jnp.float32)
                phase32 = cb[None, :] + j_lo[:, None] * b_blk[None, :]
                ck, sk = _harmonic_sums_cycles(
                    phase32, w_blk[None, :].astype(jnp.float32), nharm,
                    jnp.float32, poly,
                )
                return (carry[0] + ck, carry[1] + sk), None

            (c1, s1), _ = jax.lax.scan(
                step, (c0, s0), (time_blocks, weight_blocks, b_blocks)
            )
            return c1, s1

        return jax.lax.map(
            one_tile, (jnp.arange(n_tiles, dtype=jnp.float64), c, s)
        )

    return jax.jit(update, donate_argnums=(0, 1) if donate else ())


@lru_cache(maxsize=None)
def _grid2d_stream_update(nharm: int, n_tiles: int, event_block: int,
                          trial_block: int, poly: bool, donate: bool):
    """Jitted carry update for one streamed chunk of the 2-D grid kernel
    (same replay-the-monolithic-scan-body contract as _grid_stream_update)."""

    def update(c, s, chunk_times, n_valid, f0, df, fdots):
        time_blocks = chunk_times.reshape(-1, event_block)
        w = (jnp.arange(chunk_times.shape[0]) < n_valid).astype(jnp.float64)
        weight_blocks = w.reshape(-1, event_block)
        b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
        j_lo = jnp.arange(trial_block, dtype=jnp.float32)
        f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
        fd = jnp.asarray(fdots, dtype=jnp.float64)

        def step(carry, blk):
            t_blk, w_blk, b_blk = blk
            row_t = fasttrig.centered_frac(
                f_tiles[:, None] * t_blk[None, :]).astype(jnp.float32)
            row_q = fasttrig.centered_frac(
                (0.5 * fd)[:, None] * (t_blk * t_blk)[None, :]).astype(jnp.float32)
            w32 = w_blk.astype(jnp.float32)

            def per_fdot(q_row):
                def per_tile(t_row):
                    phase32 = (t_row + q_row)[None, :] + j_lo[:, None] * b_blk[None, :]
                    return _harmonic_sums_cycles(
                        phase32, w32[None, :], nharm, jnp.float32, poly
                    )
                return jax.lax.map(per_tile, row_t)

            ck, sk = jax.lax.map(per_fdot, row_q)
            return (carry[0] + ck, carry[1] + sk), None

        (c1, s1), _ = jax.lax.scan(
            step, (c, s), (time_blocks, weight_blocks, b_blocks)
        )
        return c1, s1

    return jax.jit(update, donate_argnums=(0, 1) if donate else ())


@lru_cache(maxsize=None)
def _grid_stream_update_mxu(nharm: int, n_tiles: int, event_block: int,
                            trial_block: int, poly: bool, reseed: int,
                            mxu_bf16: bool, donate: bool):
    """Jitted carry update for one streamed chunk of the factorized 1-D
    kernel. The body is the SAME _mxu_1d_step the monolithic kernel scans
    with (same per-block matmuls, same f64 accumulation order), so the
    streamed result is bit-identical to the monolithic one."""

    def update(c, s, chunk_times, n_valid, f0, df, fdot):
        time_blocks = chunk_times.reshape(-1, event_block)
        w = (jnp.arange(chunk_times.shape[0]) < n_valid).astype(jnp.float64)
        weight_blocks = w.reshape(-1, event_block)
        b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
        f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
        step = _mxu_1d_step(f_tiles, fdot, nharm, trial_block, poly, reseed,
                            mxu_bf16)
        (c1, s1), _ = jax.lax.scan(
            step, (c, s), (time_blocks, weight_blocks, b_blocks))
        return c1, s1

    return jax.jit(update, donate_argnums=(0, 1) if donate else ())


@lru_cache(maxsize=None)
def _grid2d_stream_update_mxu(nharm: int, n_tiles: int, event_block: int,
                              trial_block: int, poly: bool, reseed: int,
                              mxu_bf16: bool, donate: bool):
    """Jitted carry update for one streamed chunk of the factorized 2-D
    kernel (same replay-the-monolithic-scan-body contract as
    _grid_stream_update_mxu)."""

    def update(c, s, chunk_times, n_valid, f0, df, fdots):
        time_blocks = chunk_times.reshape(-1, event_block)
        w = (jnp.arange(chunk_times.shape[0]) < n_valid).astype(jnp.float64)
        weight_blocks = w.reshape(-1, event_block)
        b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
        f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
        fd = jnp.asarray(fdots, dtype=jnp.float64)
        step = _mxu_2d_step(f_tiles, fd, nharm, n_tiles, trial_block, poly,
                            reseed, mxu_bf16)
        (c1, s1), _ = jax.lax.scan(
            step, (c, s), (time_blocks, weight_blocks, b_blocks))
        return c1, s1

    return jax.jit(update, donate_argnums=(0, 1) if donate else ())


@lru_cache(maxsize=None)
def _grid3d_stream_update(nharm: int, n_tiles: int, event_block: int,
                          trial_block: int, poly: bool, donate: bool):
    """Jitted carry update for one streamed chunk of the 3-D cube kernel
    (same replay-the-monolithic-scan-body contract as _grid_stream_update)."""

    def update(c, s, chunk_times, n_valid, f0, df, fdots, fddots):
        time_blocks = chunk_times.reshape(-1, event_block)
        w = (jnp.arange(chunk_times.shape[0]) < n_valid).astype(jnp.float64)
        weight_blocks = w.reshape(-1, event_block)
        b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
        j_lo = jnp.arange(trial_block, dtype=jnp.float32)
        f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
        fd = jnp.asarray(fdots, dtype=jnp.float64)
        fdd = jnp.asarray(fddots, dtype=jnp.float64)

        def step(carry, blk):
            t_blk, w_blk, b_blk = blk
            row_t = fasttrig.centered_frac(
                f_tiles[:, None] * t_blk[None, :]).astype(jnp.float32)
            row_q = fasttrig.centered_frac(
                (0.5 * fd)[:, None] * (t_blk * t_blk)[None, :]).astype(jnp.float32)
            row_r = fasttrig.centered_frac(
                (fdd / 6.0)[:, None] * (t_blk * t_blk * t_blk)[None, :]
            ).astype(jnp.float32)
            w32 = w_blk.astype(jnp.float32)

            def per_fddot(r_row):
                def per_fdot(q_row):
                    def per_tile(t_row):
                        phase32 = ((t_row + q_row) + r_row)[None, :] \
                            + j_lo[:, None] * b_blk[None, :]
                        return _harmonic_sums_cycles(
                            phase32, w32[None, :], nharm, jnp.float32, poly
                        )
                    return jax.lax.map(per_tile, row_t)
                return jax.lax.map(per_fdot, row_q)

            ck, sk = jax.lax.map(per_fddot, row_r)
            return (carry[0] + ck, carry[1] + sk), None

        (c1, s1), _ = jax.lax.scan(
            step, (c, s), (time_blocks, weight_blocks, b_blocks)
        )
        return c1, s1

    return jax.jit(update, donate_argnums=(0, 1) if donate else ())


@lru_cache(maxsize=None)
def _grid3d_stream_update_mxu(nharm: int, n_tiles: int, event_block: int,
                              trial_block: int, poly: bool, reseed: int,
                              mxu_bf16: bool, donate: bool):
    """Jitted carry update for one streamed chunk of the factorized 3-D
    kernel (same replay-the-monolithic-scan-body contract as
    _grid_stream_update_mxu)."""

    def update(c, s, chunk_times, n_valid, f0, df, fdots, fddots):
        time_blocks = chunk_times.reshape(-1, event_block)
        w = (jnp.arange(chunk_times.shape[0]) < n_valid).astype(jnp.float64)
        weight_blocks = w.reshape(-1, event_block)
        b_blocks = fasttrig.centered_frac(df * time_blocks).astype(jnp.float32)
        f_tiles = f0 + (jnp.arange(n_tiles, dtype=jnp.float64) * trial_block) * df
        fd = jnp.asarray(fdots, dtype=jnp.float64)
        fdd = jnp.asarray(fddots, dtype=jnp.float64)
        step = _mxu_3d_step(f_tiles, fd, fdd, nharm, n_tiles, trial_block,
                            poly, reseed, mxu_bf16)
        (c1, s1), _ = jax.lax.scan(
            step, (c, s), (time_blocks, weight_blocks, b_blocks))
        return c1, s1

    return jax.jit(update, donate_argnums=(0, 1) if donate else ())


def _stream_chunks(times: np.ndarray, event_block: int, event_chunk: int):
    """Host-side chunk plan: [(padded_chunk, n_valid), ...].

    Chunk boundaries are event_block multiples and the tail is padded only
    to the next event_block multiple (not to the full chunk), so every
    per-block computation — including the padded tail block — is the same
    one the monolithic kernel runs. Every chunk carries at least TWO
    event blocks (a 1-block remainder merges into the previous chunk):
    XLA unrolls a length-1 scan and fuses its f32 body differently from
    the loop form, which would break bit-identity with the monolithic
    kernel's multi-block scan.
    """
    n = len(times)
    n_blocks = -(-n // event_block)
    bpc = max(2, event_chunk // event_block)  # blocks per chunk
    starts = list(range(0, n_blocks, bpc))
    if len(starts) > 1 and n_blocks - starts[-1] == 1:
        starts.pop()
    out = []
    for i, b0 in enumerate(starts):
        b1 = n_blocks if i + 1 == len(starts) else starts[i + 1]
        part = times[b0 * event_block:min(n, b1 * event_block)]
        n_valid = len(part)
        padded_len = (b1 - b0) * event_block
        if padded_len != n_valid:
            part = np.pad(part, (0, padded_len - n_valid))
        out.append((part, n_valid))
    return out


def _streamed_uniform_sums(times, f0, df, n_freq, nharm, event_block,
                           trial_block, poly, fdots=None, fddots=None,
                           event_chunk=None,
                           mxu: bool = False, reseed: int = GRID_MXU_RESEED,
                           mxu_bf16: bool = False):
    """Double-buffered driver shared by the streamed grid kernels.

    The host->device transfer of chunk i+1 is issued (async device_put)
    BEFORE the carry update of chunk i is dispatched, so on accelerators
    the copy runs under the compute and the per-chunk host sync of the
    naive loop disappears. Returns the same (c, s) sums as the monolithic
    harmonic_sums_uniform / _2d calls, bit-for-bit (``mxu=True`` streams
    the factorized kernels under the identical contract).
    """
    times = np.ascontiguousarray(np.asarray(times, dtype=np.float64))
    n_tiles = -(-n_freq // trial_block)
    chunk = STREAM_EVENT_CHUNK if event_chunk is None else int(event_chunk)
    plan = _stream_chunks(times, event_block, chunk)
    if len(plan) == 1:
        # one chunk IS the whole problem: delegate to the monolithic
        # kernel (trivially bit-identical, and avoids compiling a second
        # program for nothing — including the sub-2-block case where the
        # carry update's scan could not replay the monolithic loop form)
        dev_times = jnp.asarray(times)
        if fdots is None:
            if mxu:
                return harmonic_sums_uniform_mxu(
                    dev_times, f0, df, n_freq, nharm, event_block,
                    trial_block, poly=poly, reseed=reseed, mxu_bf16=mxu_bf16)
            return harmonic_sums_uniform(
                dev_times, f0, df, n_freq, nharm, event_block, trial_block,
                poly=poly)
        fd = jnp.asarray(fdots, dtype=jnp.float64)
        if fddots is not None:
            fdd = jnp.asarray(fddots, dtype=jnp.float64)
            if mxu:
                return harmonic_sums_uniform_3d_mxu(
                    dev_times, f0, df, n_freq, fd, fdd, nharm, event_block,
                    trial_block, poly=poly, reseed=reseed, mxu_bf16=mxu_bf16)
            return harmonic_sums_uniform_3d(
                dev_times, f0, df, n_freq, fd, fdd,
                nharm, event_block, trial_block, poly=poly)
        if mxu:
            return harmonic_sums_uniform_2d_mxu(
                dev_times, f0, df, n_freq, fd, nharm, event_block,
                trial_block, poly=poly, reseed=reseed, mxu_bf16=mxu_bf16)
        return harmonic_sums_uniform_2d(
            dev_times, f0, df, n_freq, fd,
            nharm, event_block, trial_block, poly=poly)
    donate = jax.default_backend() != "cpu"
    if fdots is None:
        if mxu:
            update = _grid_stream_update_mxu(nharm, n_tiles, event_block,
                                             trial_block, poly, reseed,
                                             mxu_bf16, donate)
            carry_shape = (nharm, n_tiles, trial_block)
        else:
            update = _grid_stream_update(nharm, n_tiles, event_block,
                                         trial_block, poly, donate)
            carry_shape = (n_tiles, nharm, trial_block)
        extra = (0.0,)
    elif fddots is not None:
        fdots = jnp.asarray(fdots, dtype=jnp.float64)
        fddots = jnp.asarray(fddots, dtype=jnp.float64)
        n_fdot = int(fdots.shape[0])
        n_fddot = int(fddots.shape[0])
        if mxu:
            update = _grid3d_stream_update_mxu(nharm, n_tiles, event_block,
                                               trial_block, poly, reseed,
                                               mxu_bf16, donate)
            carry_shape = (nharm, n_fddot, n_fdot, n_tiles, trial_block)
        else:
            update = _grid3d_stream_update(nharm, n_tiles, event_block,
                                           trial_block, poly, donate)
            carry_shape = (n_fddot, n_fdot, n_tiles, nharm, trial_block)
        extra = (fdots, fddots)
    else:
        fdots = jnp.asarray(fdots, dtype=jnp.float64)
        n_fdot = int(fdots.shape[0])
        if mxu:
            update = _grid2d_stream_update_mxu(nharm, n_tiles, event_block,
                                               trial_block, poly, reseed,
                                               mxu_bf16, donate)
            carry_shape = (nharm, n_fdot, n_tiles, trial_block)
        else:
            update = _grid2d_stream_update(nharm, n_tiles, event_block,
                                           trial_block, poly, donate)
            carry_shape = (n_fdot, n_tiles, nharm, trial_block)
        extra = (fdots,)
    c = jnp.zeros(carry_shape, dtype=jnp.float64)
    s = jnp.zeros(carry_shape, dtype=jnp.float64)
    dev = jax.device_put(plan[0][0])
    for i, (_, n_valid) in enumerate(plan):
        nxt = jax.device_put(plan[i + 1][0]) if i + 1 < len(plan) else None
        c, s = update(c, s, dev, n_valid, f0, df, *extra)
        dev = nxt
    # cost row for the per-chunk carry update (abstract stand-ins, so the
    # donated carry buffers are never touched); full-chunk shape = plan[0]
    costmodel.capture("grid_sums_streamed", update,
                      c, s, plan[0][0], plan[0][1], f0, df, *extra)
    if fdots is None:
        if mxu:
            c_all = c.reshape(nharm, -1)[:, :n_freq]
            s_all = s.reshape(nharm, -1)[:, :n_freq]
        else:
            c_all = jnp.moveaxis(c, 1, 0).reshape(nharm, -1)[:, :n_freq]
            s_all = jnp.moveaxis(s, 1, 0).reshape(nharm, -1)[:, :n_freq]
    elif fddots is not None:
        if mxu:
            c_all = jnp.moveaxis(c, 0, 2).reshape(
                n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
            s_all = jnp.moveaxis(s, 0, 2).reshape(
                n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
        else:
            c_all = jnp.moveaxis(c, 3, 2).reshape(
                n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
            s_all = jnp.moveaxis(s, 3, 2).reshape(
                n_fddot, n_fdot, nharm, -1)[:, :, :, :n_freq]
    elif mxu:
        c_all = jnp.moveaxis(c, 0, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
        s_all = jnp.moveaxis(s, 0, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
    else:
        c_all = jnp.moveaxis(c, 2, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
        s_all = jnp.moveaxis(s, 2, 1).reshape(n_fdot, nharm, -1)[:, :, :n_freq]
    return c_all, s_all


def z2_power_grid_streamed(
    times, f0: float, df: float, n_freq: int, nharm: int = 2,
    event_block: int | None = None, trial_block: int | None = None,
    poly: bool = False, event_chunk: int | None = None,
    mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """z2_power_grid with double-buffered host->device event streaming."""
    n = np.shape(times)[0]
    use_mxu, rs, b16 = _resolve_grid_mxu(n, n_freq, poly, mxu, reseed, mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid", n, n_freq,
                            poly, event_block, trial_block)
    c, s = _streamed_uniform_sums(times, f0, df, n_freq, nharm, eb, tb, poly,
                                  event_chunk=event_chunk, mxu=use_mxu,
                                  reseed=rs, mxu_bf16=b16)
    return jnp.sum(z2_from_sums(c, s, n), axis=0)


def h_power_grid_streamed(
    times, f0: float, df: float, n_freq: int, nharm: int = 20,
    event_block: int | None = None, trial_block: int | None = None,
    poly: bool = False, event_chunk: int | None = None,
    mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """h_power_grid with double-buffered host->device event streaming."""
    n = np.shape(times)[0]
    use_mxu, rs, b16 = _resolve_grid_mxu(n, n_freq, poly, mxu, reseed, mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid", n, n_freq,
                            poly, event_block, trial_block)
    c, s = _streamed_uniform_sums(times, f0, df, n_freq, nharm, eb, tb, poly,
                                  event_chunk=event_chunk, mxu=use_mxu,
                                  reseed=rs, mxu_bf16=b16)
    z2_cum = jnp.cumsum(z2_from_sums(c, s, n), axis=0)
    penalties = 4.0 * jnp.arange(nharm, dtype=jnp.float64)[:, None]
    return jnp.max(z2_cum - penalties, axis=0)


def z2_power_2d_grid_streamed(
    times, f0: float, df: float, n_freq: int, fdots, nharm: int = 2,
    event_block: int | None = None, trial_block: int | None = None,
    poly: bool = False, event_chunk: int | None = None,
    mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """z2_power_2d_grid with double-buffered host->device event streaming."""
    n = np.shape(times)[0]
    use_mxu, rs, b16 = _resolve_grid_mxu(n, n_freq, poly, mxu, reseed, mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid", n, n_freq,
                            poly, event_block, trial_block)
    c, s = _streamed_uniform_sums(times, f0, df, n_freq, nharm, eb, tb, poly,
                                  fdots=fdots, event_chunk=event_chunk,
                                  mxu=use_mxu, reseed=rs, mxu_bf16=b16)
    return jnp.sum(z2_from_sums(c, s, n), axis=1)


def z2_power_3d_grid_streamed(
    times, f0: float, df: float, n_freq: int, fdots, fddots, nharm: int = 2,
    event_block: int | None = None, trial_block: int | None = None,
    poly: bool = False, event_chunk: int | None = None,
    mxu: bool | None = None, reseed: int | None = None,
    mxu_bf16: bool | None = None,
) -> jax.Array:
    """z2_power_3d_grid with double-buffered host->device event streaming."""
    n = np.shape(times)[0]
    fd = jnp.asarray(fdots, dtype=jnp.float64)
    fdd = jnp.asarray(fddots, dtype=jnp.float64)
    n_cube = int(n_freq) * int(fd.shape[0]) * int(fdd.shape[0])
    use_mxu, rs, b16 = _resolve_grid3d_mxu(n, n_cube, poly, mxu, reseed,
                                           mxu_bf16)
    eb, tb = resolve_blocks("grid_mxu" if use_mxu else "grid3d", n, n_freq,
                            poly, event_block, trial_block)
    c, s = _streamed_uniform_sums(times, f0, df, n_freq, nharm, eb, tb, poly,
                                  fdots=fd, fddots=fdd,
                                  event_chunk=event_chunk,
                                  mxu=use_mxu, reseed=rs, mxu_bf16=b16)
    return jnp.sum(z2_from_sums(c, s, n), axis=2)


@partial(jax.jit, static_argnames=("nharm", "event_block", "trial_block", "trig_dtype", "poly"))
def z2_power_2d(
    times: jax.Array,
    freqs: jax.Array,
    fdots: jax.Array,
    nharm: int = 2,
    event_block: int = DEFAULT_EVENT_BLOCK,
    trial_block: int = DEFAULT_TRIAL_BLOCK,
    trig_dtype=DEFAULT_TRIG_DTYPE,
    poly: bool = False,
) -> jax.Array:
    """Z^2_n over the (fdot, freq) grid -> (n_fdot, n_freq).

    ``fdots`` are SIGNED frequency derivatives (Hz/s); callers keeping the
    reference CLI convention pass -10**log10grid.
    """

    def one_fdot(fdot):
        c_sum, s_sum = _blocked_trial_sums(
            times, freqs, nharm, event_block, trial_block, trig_dtype,
            lambda f_blk, t_blk: f_blk[:, None] * t_blk[None, :]
            + 0.5 * fdot * t_blk[None, :] ** 2,
            poly=poly,
        )
        return jnp.sum(z2_from_sums(c_sum, s_sum, times.shape[0]), axis=0)

    return jax.lax.map(one_fdot, fdots)


@partial(jax.jit, static_argnames=("nharm", "event_block", "trial_block", "trig_dtype", "poly"))
def z2_power_3d(
    times: jax.Array,
    freqs: jax.Array,
    fdots: jax.Array,
    fddots: jax.Array,
    nharm: int = 2,
    event_block: int = DEFAULT_EVENT_BLOCK,
    trial_block: int = DEFAULT_TRIAL_BLOCK,
    trig_dtype=DEFAULT_TRIG_DTYPE,
    poly: bool = False,
) -> jax.Array:
    """Z^2_n over the (fddot, fdot, freq) cube -> (n_fddot, n_fdot, n_freq).

    The arbitrary-frequency-grid fallback of the jerk search; both
    derivative axes are SIGNED (Hz/s and Hz/s^2) as in z2_power_2d.
    """

    def one_fddot(fddot):
        def one_fdot(fdot):
            c_sum, s_sum = _blocked_trial_sums(
                times, freqs, nharm, event_block, trial_block, trig_dtype,
                lambda f_blk, t_blk: f_blk[:, None] * t_blk[None, :]
                + 0.5 * fdot * t_blk[None, :] ** 2
                + (fddot / 6.0) * t_blk[None, :] ** 3,
                poly=poly,
            )
            return jnp.sum(z2_from_sums(c_sum, s_sum, times.shape[0]), axis=0)

        return jax.lax.map(one_fdot, fdots)

    return jax.lax.map(one_fddot, fddots)


@partial(jax.jit, static_argnames=("nharm", "trig_dtype"))
def h_power_segments(
    times: jax.Array,  # (S, N) per-segment event times (pre-centered), padded
    masks: jax.Array,  # (S, N) validity
    freqs: jax.Array,  # (S,) one trial frequency per segment
    nharm: int = 5,
    trig_dtype=DEFAULT_TRIG_DTYPE,
) -> jax.Array:
    """H-test power per segment at its own frequency, vmapped over segments.

    Backs the per-ToA H-test of the ToA pipeline (reference computes it
    serially per ToA, measureToAs.py:210-212)."""

    def one(t, m, f):
        phase = f * t  # cycles, f64
        c, s = _harmonic_sums_cycles(phase, m.astype(t.dtype), nharm, trig_dtype)
        n = jnp.sum(m)
        z2_cum = jnp.cumsum((c**2 + s**2) * (2.0 / n))
        return jnp.max(z2_cum - 4.0 * jnp.arange(nharm, dtype=t.dtype))

    return jax.vmap(one)(times, masks, freqs)


def h_power_segments_chunked(times, masks, freqs, nharm: int = 5,
                             row_block: int | None = None,
                             trig_dtype=DEFAULT_TRIG_DTYPE) -> np.ndarray:
    """``h_power_segments`` dispatched in row chunks of ``row_block``.

    The memory governor for survey-scale stacked batches (ops/multisource
    flattens every (source, segment) row into one call): each chunk is its
    own device dispatch, so the vmapped (rows, events, harmonics) temps
    never exceed ~row_block padded rows. Per-row bits are identical to the
    single-call path — vmap batches rows independently, so splitting the
    batch cannot reassociate any row's reduction. ``row_block`` None/<=0
    or >= the row count collapses to one call.
    """
    faultinject.fire("harmonic_sums")
    times = np.asarray(times)
    n_rows = times.shape[0]
    if row_block is None or row_block <= 0 or row_block >= n_rows:
        return np.asarray(
            h_power_segments(jnp.asarray(times), jnp.asarray(masks),
                             jnp.asarray(freqs), nharm=nharm,
                             trig_dtype=trig_dtype)
        )
    masks = np.asarray(masks)
    freqs = np.asarray(freqs)
    # pipelined like fit_toas_bucketed: dispatch every chunk first (JAX
    # async dispatch), then materialize in order
    pending = [
        h_power_segments(jnp.asarray(times[lo:lo + row_block]),
                         jnp.asarray(masks[lo:lo + row_block]),
                         jnp.asarray(freqs[lo:lo + row_block]),
                         nharm=nharm, trig_dtype=trig_dtype)
        for lo in range(0, n_rows, row_block)
    ]
    return np.concatenate([np.asarray(p) for p in pending])


class PeriodSearch:
    """Reference-compatible search API (periodsearch.py:20-125).

    ``time`` in seconds; trials are centered on t0 = (time[0]+time[-1])/2.
    The compute runs as jitted blockwise kernels on the default JAX device;
    on a multi-device host the event axis is automatically sharded across
    all chips with psum combines (crimp_tpu.parallel.mesh.auto_mesh;
    ``CRIMP_TPU_SHARD=0`` opts out) once the workload is large enough to
    amortize the collectives.
    """

    def __init__(self, time, freq, nbrHarm: int = 2, use_grid_fastpath: bool | None = None,
                 poly_trig: bool | None = None):
        self.time = np.asarray(time, dtype=np.float64)
        self.freq = np.asarray(freq, dtype=np.float64)
        self.nbrHarm = int(nbrHarm)
        self.t0 = (self.time[0] + self.time[-1]) / 2
        self.use_grid_fastpath = use_grid_fastpath
        self.poly_trig = poly_trig

    def _poly(self) -> bool:
        return fasttrig.poly_trig_enabled(self.poly_trig)

    def _centered(self) -> jax.Array:
        return jnp.asarray(self.time - self.t0)

    def _grid(self):
        """(f0, df) when the trial grid is uniform AND the fast path is on."""
        if not grid_fastpath_enabled(self.nbrHarm, self.use_grid_fastpath):
            return None
        return uniform_grid(self.freq)

    def _general_blocks(self) -> tuple[int, int]:
        """Autotuned (event_block, trial_block) for the general kernels."""
        return resolve_blocks("general", len(self.time), len(self.freq),
                              self._poly())

    def _mesh(self, n_pairs: int | None = None):
        """Device mesh for auto-sharding, or None for the single-device path."""
        if n_pairs is None:
            n_pairs = len(self.time) * len(self.freq)
        if n_pairs < MIN_SHARD_PAIRS:
            return None
        from crimp_tpu.parallel import mesh as pmesh

        return pmesh.auto_mesh()

    def ztest(self) -> np.ndarray:
        with obs.span("z2_scan", n_trials=len(self.freq),
                      n_events=len(self.time), nharm=self.nbrHarm):
            mesh = self._mesh()
            if mesh is not None:
                from crimp_tpu.parallel import mesh as pmesh

                return pmesh.z2_sharded(
                    self.time - self.t0, self.freq, self.nbrHarm, mesh,
                    use_fastpath=self.use_grid_fastpath, poly=self._poly(),
                )
            grid = self._grid()
            if grid is not None:
                f0, df = grid
                return np.asarray(
                    z2_power_grid(self._centered(), f0, df, len(self.freq), self.nbrHarm,
                                  poly=self._poly())
                )
            eb, tb = self._general_blocks()
            return np.asarray(
                z2_power(self._centered(), jnp.asarray(self.freq), self.nbrHarm,
                         event_block=eb, trial_block=tb, poly=self._poly())
            )

    def htest(self) -> np.ndarray:
        with obs.span("h_scan", n_trials=len(self.freq),
                      n_events=len(self.time), nharm=self.nbrHarm):
            mesh = self._mesh()
            if mesh is not None:
                from crimp_tpu.parallel import mesh as pmesh

                return pmesh.h_sharded(
                    self.time - self.t0, self.freq, self.nbrHarm, mesh,
                    use_fastpath=self.use_grid_fastpath, poly=self._poly(),
                )
            grid = self._grid()
            if grid is not None:
                f0, df = grid
                return np.asarray(
                    h_power_grid(self._centered(), f0, df, len(self.freq), self.nbrHarm,
                                 poly=self._poly())
                )
            eb, tb = self._general_blocks()
            return np.asarray(
                h_power(self._centered(), jnp.asarray(self.freq), self.nbrHarm,
                        event_block=eb, trial_block=tb, poly=self._poly())
            )

    def twod_ztest(self, freq_dot):
        """2-D Z^2 on a (log10 |nudot|) grid, spin-down sign enforced.

        Returns (array of rows [freq, log10_fdot, z2], DataFrame) with the
        reference's row ordering: outer loop fdot, inner loop freq.
        """
        log_fdots = np.asarray(freq_dot, dtype=np.float64)
        signed = -(10.0**log_fdots)
        with obs.span("z2_2d_scan", n_trials=len(self.freq) * len(signed),
                      n_events=len(self.time), nharm=self.nbrHarm):
            mesh = self._mesh(len(self.time) * len(self.freq) * len(signed))
            if mesh is not None:
                from crimp_tpu.parallel import mesh as pmesh

                power = pmesh.z2_2d_sharded(
                    self.time - self.t0, self.freq, signed, self.nbrHarm, mesh,
                    use_fastpath=self.use_grid_fastpath, poly=self._poly(),
                )
            elif (grid := self._grid()) is not None:
                f0, df = grid
                power = np.asarray(
                    z2_power_2d_grid(
                        self._centered(), f0, df, len(self.freq),
                        jnp.asarray(signed), self.nbrHarm, poly=self._poly(),
                    )
                )
            else:
                eb, tb = self._general_blocks()
                power = np.asarray(
                    z2_power_2d(
                        self._centered(),
                        jnp.asarray(self.freq),
                        jnp.asarray(signed),
                        self.nbrHarm,
                        event_block=eb,
                        trial_block=tb,
                        poly=self._poly(),
                    )
                )
        rows = np.column_stack(
            [
                np.tile(self.freq, len(log_fdots)),
                np.repeat(log_fdots, len(self.freq)),
                power.reshape(-1),
            ]
        )
        df = pd.DataFrame(rows, columns=["Freq", "Freq_dot", "Z2pow"])
        return rows, df

    def _threed_rows(self, log_fdots, fdd, power):
        """(rows, DataFrame) for the cube scans: outer fddot, then fdot,
        then freq (extends the reference 2-D row ordering by one axis)."""
        rows = np.column_stack(
            [
                np.tile(self.freq, len(log_fdots) * len(fdd)),
                np.tile(np.repeat(log_fdots, len(self.freq)), len(fdd)),
                np.repeat(fdd, len(self.freq) * len(log_fdots)),
                np.asarray(power).reshape(-1),
            ]
        )
        df = pd.DataFrame(
            rows, columns=["Freq", "Freq_dot", "Freq_ddot", "Z2pow"])
        return rows, df

    def threed_ztest(self, freq_dot, freq_ddot):
        """3-D Z^2 over the (freq x log10 |nudot| x signed nuddot) cube.

        ``freq_dot`` keeps twod_ztest's reference convention (log10
        magnitudes, applied as -10**x, spin-down only); ``freq_ddot`` is
        SIGNED s^-3 — the jerk axis has no reference convention and
        braking/anti-braking cubes are genuinely two-signed (see
        docs/parity.md). Returns (rows, DataFrame) ordered outer fddot,
        then fdot, then freq.
        """
        log_fdots = np.asarray(freq_dot, dtype=np.float64)
        signed = -(10.0**log_fdots)
        fdd = np.asarray(freq_ddot, dtype=np.float64)
        n_cube = len(self.freq) * len(signed) * len(fdd)
        with obs.span("z2_3d_scan", n_trials=n_cube,
                      n_events=len(self.time), nharm=self.nbrHarm):
            mesh = self._mesh(len(self.time) * n_cube)
            if mesh is not None:
                from crimp_tpu.parallel import mesh as pmesh

                power = pmesh.z2_3d_sharded(
                    self.time - self.t0, self.freq, signed, fdd,
                    self.nbrHarm, mesh,
                    use_fastpath=self.use_grid_fastpath, poly=self._poly(),
                )
            elif (grid := self._grid()) is not None:
                f0, df = grid
                power = np.asarray(
                    z2_power_3d_grid(
                        self._centered(), f0, df, len(self.freq),
                        jnp.asarray(signed), jnp.asarray(fdd), self.nbrHarm,
                        poly=self._poly(),
                    )
                )
            else:
                eb, tb = self._general_blocks()
                power = np.asarray(
                    z2_power_3d(
                        self._centered(),
                        jnp.asarray(self.freq),
                        jnp.asarray(signed),
                        jnp.asarray(fdd),
                        self.nbrHarm,
                        event_block=eb,
                        trial_block=tb,
                        poly=self._poly(),
                    )
                )
        return self._threed_rows(log_fdots, fdd, power)

    def semicoherent_ztest(self, freq_dot, freq_ddot, n_segments: int):
        """Semi-coherent stacked Z^2 over the cube (ops/semicoherent).

        Events are split into ``n_segments`` equal-duration segments, each
        scanned coherently at the GLOBAL phase model, and the per-segment
        Z^2 terms are summed incoherently — so the fddot grid only needs
        per-segment resolution (~n_segments x coarser than the coherent
        cube; docs/performance.md "Search cube"). Same axis conventions
        and row ordering as threed_ztest; requires a uniform frequency
        grid (the stack runs on the grid fast path).
        """
        from crimp_tpu.ops import semicoherent

        grid = uniform_grid(self.freq)
        if grid is None:
            raise ValueError(
                "semicoherent_ztest needs a uniform frequency grid")
        f0, df = grid
        log_fdots = np.asarray(freq_dot, dtype=np.float64)
        signed = -(10.0**log_fdots)
        fdd = np.asarray(freq_ddot, dtype=np.float64)
        power = semicoherent.semicoherent_z2_grid(
            self.time - self.t0, f0, df, len(self.freq), signed, fdd,
            nharm=self.nbrHarm, n_segments=int(n_segments),
            poly=self._poly(),
        )
        return self._threed_rows(log_fdots, fdd, power)
