"""Batched unbinned maximum-likelihood ToA extraction.

What the reference does per ToA (measureToAs.py:254-403, serial lmfit):
brute grid over phShift, Nelder-Mead refine with the normalization free,
then dozens of full re-minimizations stepping phShift by 2*pi/phShiftRes to
find the +/-1-sigma likelihood-profile bounds. ~2.4 s/ToA on CPU.

The TPU re-design rests on one algebraic fact: for all three template
families the extended log-likelihood at fixed shape is

    LL(phi, A) = -A*T + sum_i m_i log(A + s_i(phi)) + const(T, N)

(for von Mises / Cauchy the constant also absorbs -Q*T/2pi with
Q = sum_j amp_j*ampShift; derivation from templatemodels.py:98-121,
201-226, 306-329). LL is strictly concave in A with
dLL/dA = -T + sum m_i/(A+s_i), so the inner "re-optimize the norm"
solve the reference does numerically per step is a safeguarded Newton
iteration — vectorized across the whole phase grid at once. The profile
likelihood over phShift therefore evaluates as ONE dense sweep:

- Fourier: s_i(phi) = C_i . cos(j phi) + S_i . sin(j phi) — a
  (grid x events) MATMUL on precomputed per-event harmonic coefficients,
  which is exactly the MXU-shaped workload;
- von Mises / Cauchy: direct evaluation, scanned over components.

Segments are padded/bucketed (ragged event counts -> masks) and the whole
fit vmaps over ToA segments: the per-ToA loop disappears.

Error bars keep the reference's exact stepping semantics (step =
2*pi/phShiftRes; first step k* whose LL drop exceeds chi2_1(0.6827)/2;
reported bound = (k*+1)*step + step/2 including the overshoot quirk,
SURVEY.md §2.5), but evaluate the steps as vectorized chunks inside a
while_loop instead of sequential refits.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import i0

from crimp_tpu import obs
from crimp_tpu.obs import costmodel
from crimp_tpu.models.profiles import (
    CAUCHY,
    FOURIER,
    VONMISES,
    ProfileParams,
    extended_loglik,
)
from crimp_tpu.ops.optimize import bounded_transform, golden_section, nelder_mead

# 0.5 * chi2.ppf(0.6827, df=1): the 1-sigma likelihood-profile drop
# (measureToAs.py:324). Hard-coded to keep the kernel host-independent.
CHI2_1SIG_HALF = 0.4999320306186937

# Default first-window width (steps per side) of the dense two-phase error
# scan. 2W phis evaluate in ONE profile sweep, so the footprint matches the
# proven-safe brute_chunk=64 launch; W=32 covers bounds up to 32 scan steps
# (~0.2 rad at res=1000 — an order above the campaign's ~3e-2 rad bars)
# before the chunked while_loop fallback has any work left.
DENSE_WINDOW_DEFAULT = 32


class ToAFitConfig(NamedTuple):
    """Static configuration for the batched ToA fit."""

    kind: str = FOURIER
    ph_shift_res: int = 1000  # error-scan resolution: step = 2*pi/res
    n_brute: int = 128  # coarse global grid over the phShift range
    brute_chunk: int = 64  # brute phases evaluated per launch (HBM bound)
    # Iteration defaults from the measured accuracy frontier
    # (scripts/tune_toafit.py; evidence docs/tuning_cpu_r3.json): vs a
    # (n_brute=512, newton=60, refine=80) reference, newton=10..45 all sit
    # at the same ~1.8e-7 rad d_phi floor (that residual is golden-section
    # precision, not Newton error) with ZERO error-bound step flips, so
    # newton=20 is 2x the smallest swept value; refine=25 reaches the same
    # floor (refine=15 drifts 1.2e-5 rad — still three orders below the
    # ~3e-2 rad error bars). The shipped combination is measured jointly
    # (d_phi 1.8e-7, d_err 0), as is its vary_amps variant (the 2-D
    # solver runs 2*newton_iters; d_phi 1.5e-7, d_err 0).
    newton_iters: int = 20  # inner norm solve (concave, quadratic conv.)
    refine_iters: int = 25  # golden-section refine of the grid optimum
    # Alternative refine with accelerator-friendly serial depth: "grid"
    # replaces the refine_iters-long golden-section dependency chain with
    # refine_rounds vectorized fine grids of refine_grid phis each (serial
    # depth 25 -> 4). Equivalent precision at default settings; opt-in
    # pending an on-chip wall-clock A/B (tests pin mode equivalence).
    refine_mode: str = "golden"  # "golden" | "grid"
    refine_rounds: int = 4
    refine_grid: int = 33
    err_chunk: int = 32  # error-scan steps evaluated per while_loop pass
    nbins: int = 15  # binned-profile chi2 reporting
    norm_lo_frac: float = 0.01  # norm lower bound = frac * template norm
    norm_hi: float = 500.0  # norm upper bound (defineinitialfitparam:715)
    vary_amps: bool = False  # free ampShift (3-parameter fit)
    amp_lo: float = 0.01
    amp_hi: float = 100.0
    # General free-parameter path (the reference's readvaryparam mode,
    # defineinitialfitparam): indices into the flattened template vector
    # [norm, amp_1..K, loc_1..K, wid_1..K, ampShift] that are free, with
    # per-parameter box bounds. Empty = fast fixed-shape path.
    free_idx: tuple = ()
    free_lo: tuple = ()
    free_hi: tuple = ()
    nm_iters: int = 150  # Nelder-Mead iterations of the general path
    n_free: int = -1  # chi2 dof override (-1 = auto: 2 + vary_amps)
    fix_norm: bool = False  # pin the norm at the template value (the
    # readvaryparam all-fixed case: reference keeps nbrFreeParams=0 and
    # does NOT free the norm, defineinitialfitparam readvaryparam branch)
    # Dense two-phase error scan: first-window width in STEPS PER SIDE.
    # -1 = auto (DENSE_WINDOW_DEFAULT at trace time; the host wrappers may
    # first substitute an env/autotune-cache value via resolve_runtime_cfg);
    # 0 = pure chunked while_loop path (the pre-dense reference behavior).
    # Any value is bit-identical — the knob only moves work between the
    # one-shot dense sweep and the serial fallback loop.
    err_dense_window: int = -1
    # bf16 MXU profile sweeps, tri-state: -1 = auto (off at trace time;
    # resolve_runtime_cfg may enable it from CRIMP_TPU_MXU_BF16 or the
    # autotune cache), 0 = exact f32/f64 matmul, 1 = bf16 operands with f32
    # accumulation. Only the Fourier shape_at_shifts sweep is affected; the
    # binned-chi2 report stays exact.
    mxu_bf16: int = -1


def _phase_range(kind: str) -> float:
    # phShift in [-pi, pi] for Fourier, [-1.5pi, 1.5pi] for vm/cauchy
    # (defineinitialfitparam, measureToAs.py:722,767).
    return jnp.pi if kind == FOURIER else 1.5 * jnp.pi


# ---------------------------------------------------------------------------
# Shape term s_i(phi) (template minus baseline, ampShift folded in)
# ---------------------------------------------------------------------------


def _fourier_event_coeffs(tpl: ProfileParams, x: jax.Array):
    """Per-event harmonic coefficients: s_i(phi) = C_i.cos(j phi)+S_i.sin(j phi)."""
    j = jnp.arange(1, tpl.n_comp + 1, dtype=x.dtype)
    theta = 2 * jnp.pi * j[None, :] * x[:, None] + tpl.loc[None, :]  # (N, K)
    amp = tpl.amp * tpl.amp_shift
    return amp[None, :] * jnp.cos(theta), amp[None, :] * jnp.sin(theta)


def shape_at_shifts(
    kind: str, tpl: ProfileParams, x: jax.Array, phis: jax.Array, bf16: bool = False
) -> jax.Array:
    """s(x_i; phi) for all (phi, event) pairs -> (n_phi, n_event).

    ``bf16`` (Fourier only) runs the (P, K) x (K, N) matmuls with bf16
    operands and f32 accumulation (preferred_element_type) — the MXU's
    native mode. The trig factors and per-event coefficients are computed
    exactly first, so the only rounding is the K-term contraction.
    """
    if kind == FOURIER:
        C, S = _fourier_event_coeffs(tpl, x)  # (N, K)
        j = jnp.arange(1, tpl.n_comp + 1, dtype=x.dtype)
        cosj = jnp.cos(j[None, :] * phis[:, None])  # (P, K)
        sinj = jnp.sin(j[None, :] * phis[:, None])
        if bf16:
            acc = jnp.matmul(
                cosj.astype(jnp.bfloat16),
                C.T.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            ) + jnp.matmul(
                sinj.astype(jnp.bfloat16),
                S.T.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            return acc.astype(x.dtype)
        return cosj @ C.T + sinj @ S.T  # MXU matmul: (P, N)

    def add_comp(carry, comp):
        amp, cen, wid = comp
        delta = x[None, :] - cen - phis[:, None]  # (P, N)
        if kind == CAUCHY:
            term = (amp * tpl.amp_shift / (2 * jnp.pi)) * jnp.sinh(wid) / (
                jnp.cosh(wid) - jnp.cos(delta)
            )
        else:  # VONMISES
            kappa = 1.0 / wid**2
            term = (
                amp * tpl.amp_shift / (2 * jnp.pi * i0(kappa)) * jnp.exp(kappa * jnp.cos(delta))
            )
        return carry + term, None

    comps = jnp.stack([tpl.amp, tpl.loc, tpl.wid], axis=-1)
    init = jnp.zeros((phis.shape[0], x.shape[0]), dtype=x.dtype)
    total, _ = jax.lax.scan(add_comp, init, comps)
    return total


# ---------------------------------------------------------------------------
# Inner norm solve + profile likelihood
# ---------------------------------------------------------------------------


def _optimal_norm(s: jax.Array, mask: jax.Array, exposure, n_events, lo, hi, iters: int):
    """Concave inner solve: A with sum_i m_i/(A+s_i) = T, clamped to [lo,hi].

    s: (P, N); returns A (P,).
    """
    min_s = jnp.min(jnp.where(mask[None, :], s, jnp.inf), axis=1)
    feasible_lo = jnp.maximum(lo, -min_s * (1 + 1e-9) + 1e-12)
    a = jnp.clip(n_events / exposure, feasible_lo, hi)

    def body(_, a):
        denom = a[:, None] + s
        inv = jnp.where(mask[None, :], 1.0 / denom, 0.0)
        g = jnp.sum(inv, axis=1) - exposure
        gp = -jnp.sum(inv**2, axis=1)
        step = g / gp
        return jnp.clip(a - step, feasible_lo, hi)

    return jax.lax.fori_loop(0, iters, body, a)


def _optimal_norm_amp(
    kind, tpl, s, mask, exposure, n_events, cfg: "ToAFitConfig"
):
    """Joint concave inner solve for (A, b) = (norm, ampShift), per grid point.

    LL(A, b) = -A*T - c_b*b*T + sum_i m_i log(A + b*s_i) + const is jointly
    concave (log of an affine form), so a projected 2x2 Newton ascent
    converges in a few iterations; boxes follow the reference's second-stage
    refit bounds (measureToAs.py:308,461,605). s: (P, N); returns (A, b).
    """
    q0 = jnp.sum(tpl.amp * tpl.amp_shift)
    c_b = 0.0 if kind == FOURIER else q0 / (2 * jnp.pi)

    a_lo = cfg.norm_lo_frac * tpl.norm
    a_hi = cfg.norm_hi
    b_lo, b_hi = cfg.amp_lo, cfg.amp_hi
    min_s = jnp.min(jnp.where(mask[None, :], s, jnp.inf), axis=1)

    def feasible_a_lo(b):
        # keep A + b*s_i > 0 for every masked event
        return jnp.maximum(a_lo, -b * min_s * (1 + 1e-9) + 1e-12)

    a0 = jnp.clip(
        jnp.full(s.shape[0], n_events / exposure), feasible_a_lo(jnp.ones(s.shape[0])), a_hi
    )
    b0 = jnp.ones(s.shape[0])

    def body(_, state):
        a, b = state
        denom = a[:, None] + b[:, None] * s
        inv = jnp.where(mask[None, :], 1.0 / denom, 0.0)
        inv_s = inv * s
        g_a = jnp.sum(inv, axis=1) - exposure
        g_b = jnp.sum(inv_s, axis=1) - c_b * exposure
        h_aa = -jnp.sum(inv**2, axis=1)
        h_ab = -jnp.sum(inv * inv_s, axis=1)
        h_bb = -jnp.sum(inv_s**2, axis=1)
        det = h_aa * h_bb - h_ab**2
        # Damped fallback when the Hessian is near-singular (flat shape).
        # The fallback is a 1-D Newton step on A alone: -h_aa >= 0, so the
        # regularizer must be ADDED to keep the denominator positive — a
        # subtracted epsilon flips the step to descent when h_aa ~ 0.
        safe = jnp.abs(det) > 1e-30
        det = jnp.where(safe, det, 1.0)
        da = jnp.where(safe, -(h_bb * g_a - h_ab * g_b) / det, g_a / (-h_aa + 1e-30))
        db = jnp.where(safe, -(-h_ab * g_a + h_aa * g_b) / det, 0.0)
        b_new = jnp.clip(b + db, b_lo, b_hi)
        a_new = jnp.clip(a + da, feasible_a_lo(b_new), a_hi)
        return a_new, b_new

    return jax.lax.fori_loop(0, 2 * cfg.newton_iters, body, (a0, b0))


def _loglik_at(kind, tpl, s, a, b, mask, exposure, n_events):
    """Extended LL given shape values s (P,N), norms a (P,), ampShifts b (P,)."""
    vals = a[:, None] + b[:, None] * s
    positive = jnp.min(jnp.where(mask[None, :], vals, jnp.inf), axis=1) > 0
    log_sum = jnp.sum(jnp.where(mask[None, :], jnp.log(jnp.clip(vals, 1e-300)), 0.0), axis=1)
    if kind == FOURIER:
        const = n_events * jnp.log(exposure)
        ll = -a * exposure + const + log_sum
    else:
        q = jnp.sum(tpl.amp * tpl.amp_shift) * b
        const = n_events * jnp.log(exposure / (2 * jnp.pi)) - q * exposure / (2 * jnp.pi)
        ll = -a * exposure + const + log_sum
    return jnp.where(positive, ll, -jnp.inf)


def profile_loglik(kind, tpl, x, mask, exposure, phis, cfg: ToAFitConfig, warm_vec=None):
    """(LL(phi), A*(phi)) profile with the norm re-optimized per shift."""
    ll, a, _ = profile_loglik_full(kind, tpl, x, mask, exposure, phis, cfg, warm_vec)
    return ll, a


def profile_loglik_full(kind, tpl, x, mask, exposure, phis, cfg: ToAFitConfig, warm_vec=None):
    """(LL(phi), A*(phi), b*(phi)): profile over phShift with the nuisance
    parameters re-optimized per shift — the vectorized analog of the
    reference's per-step refits. Dispatches to the general Nelder-Mead
    path when cfg.free_idx names extra free template parameters;
    ``warm_vec`` (a flattened template vector) warm-starts that path."""
    if cfg.free_idx:
        return _general_profile_loglik(kind, tpl, x, mask, exposure, phis, cfg, warm_vec)
    n_events = jnp.sum(mask)
    s = shape_at_shifts(kind, tpl, x, phis, bf16=cfg.mxu_bf16 == 1)
    if cfg.vary_amps:
        a, b = _optimal_norm_amp(kind, tpl, s, mask, exposure, n_events, cfg)
    elif cfg.fix_norm:
        a = jnp.full(s.shape[0], tpl.norm)
        b = jnp.ones_like(a)
    else:
        lo = cfg.norm_lo_frac * tpl.norm
        a = _optimal_norm(s, mask, exposure, n_events, lo, cfg.norm_hi, cfg.newton_iters)
        b = jnp.ones_like(a)
    ll = _loglik_at(kind, tpl, s, a, b, mask, exposure, n_events)
    return ll, a, b


# ---------------------------------------------------------------------------
# General free-parameter path (readvaryparam)
# ---------------------------------------------------------------------------


def _flatten_tpl(tpl: ProfileParams) -> jax.Array:
    """[norm, amp_1..K, loc_1..K, wid_1..K, ampShift] flattened vector."""
    return jnp.concatenate(
        [tpl.norm[None], tpl.amp, tpl.loc, tpl.wid, tpl.amp_shift[None]]
    )


def _unflatten_tpl(vec: jax.Array, tpl: ProfileParams) -> ProfileParams:
    K = tpl.n_comp
    return tpl.replace(
        norm=vec[0],
        amp=vec[1 : 1 + K],
        loc=vec[1 + K : 1 + 2 * K],
        wid=vec[1 + 2 * K : 1 + 3 * K],
        amp_shift=vec[1 + 3 * K],
    )


def free_param_spec(kind: str, template: dict, vary_amps: bool = False):
    """(free_idx, lo, hi, n_free) from a template dict's 'vary' flags.

    Mirrors defineinitialfitparam's readvaryparam bounds
    (measureToAs.py:727-806): norm in [val/5, 5*val]; Fourier amp in
    [0, 1000], ph in [-pi, pi]; vm/cauchy amp in [0, 5*val], cen in
    val +/- 0.6, wid in [0, 30*pi]. ``n_free`` reproduces the reference's
    free-parameter count for the chi2 dof (which counts the varying
    template parameters but NOT phShift in this mode — a reference quirk
    preserved for parity).
    """
    K = int(template["nbrComp"])

    def varies(key):
        entry = template[key]
        return bool(entry["vary"]) if isinstance(entry, dict) else False

    def value(key):
        entry = template[key]
        return float(entry["value"]) if isinstance(entry, dict) else float(entry)

    idx, lo, hi = [], [], []
    n_free = 0
    if varies("norm"):
        idx.append(0)
        lo.append(value("norm") / 5)
        hi.append(value("norm") * 5)
        n_free += 1
    for k in range(1, K + 1):
        if varies(f"amp_{k}"):
            idx.append(k)
            if kind == FOURIER:
                lo.append(0.0)
                hi.append(1000.0)
            else:
                # reference bound is [0, 5*amp], which degenerates for a
                # negative amplitude — order the endpoints so the box stays
                # valid either way
                five = 5 * value(f"amp_{k}")
                lo.append(min(0.0, five))
                hi.append(max(0.0, five))
            n_free += 1
        loc_key = f"ph_{k}" if kind == FOURIER else f"cen_{k}"
        if varies(loc_key):
            idx.append(K + k)
            if kind == FOURIER:
                lo.append(-np.pi)
                hi.append(np.pi)
            else:
                lo.append(value(loc_key) - 0.6)
                hi.append(value(loc_key) + 0.6)
            n_free += 1
        if kind != FOURIER and varies(f"wid_{k}"):
            idx.append(2 * K + k)
            lo.append(0.0)
            hi.append(30 * np.pi)
            n_free += 1
    if vary_amps:
        idx.append(3 * K + 1)
        lo.append(0.01 if kind == FOURIER else 1e-6)
        hi.append(100.0 if kind == FOURIER else (500.0 if kind == VONMISES else 1e6))
        n_free += 1

    # Widen any box that excludes its own template value (e.g. a Fourier
    # phase written outside [-pi, pi], or an amplitude > 1000): the sigmoid
    # reparameterization would otherwise clip the start point to the
    # boundary and freeze the parameter there with ~zero gradient.
    flat_vals = [value("norm")]
    for k in range(1, K + 1):
        flat_vals.append(value(f"amp_{k}"))
    for k in range(1, K + 1):
        flat_vals.append(value(f"ph_{k}" if kind == FOURIER else f"cen_{k}"))
    for k in range(1, K + 1):
        flat_vals.append(value(f"wid_{k}") if kind != FOURIER else 0.0)
    flat_vals.append(1.0)  # ampShift starts at 1
    for pos, i in enumerate(idx):
        v = flat_vals[i]
        margin = abs(v) * 1e-6 + 1e-9
        if v - margin < lo[pos]:
            lo[pos] = v - margin
        if v + margin > hi[pos]:
            hi[pos] = v + margin
    return tuple(idx), tuple(lo), tuple(hi), n_free


def _general_profile_vecs(kind, tpl, x, mask, exposure, phis, cfg: ToAFitConfig, warm_vec=None):
    """Profile LL over phShift with ALL flagged template parameters refit per
    shift by a fixed-iteration bounded Nelder-Mead (vmapped over the grid);
    returns (LL, full refit parameter vector) per grid point.

    This is the batched equivalent of the reference's readvaryparam mode,
    where every error-scan step re-runs lmfit over the free parameter set
    (measureToAs.py:331-376 with vary flags from defineinitialfitparam).
    ``warm_vec`` warm-starts the simplex at a previous best-fit flattened
    template vector — the error scan passes the optimum so each step refines
    from the solution one grid step away instead of restarting cold at the
    input template (the reference's sequential refits inherit lmfit state
    the same way).
    """
    free_idx = jnp.asarray(cfg.free_idx, dtype=jnp.int32)
    tf = bounded_transform(jnp.asarray(cfg.free_lo), jnp.asarray(cfg.free_hi))
    base = _flatten_tpl(tpl)
    start = base if warm_vec is None else warm_vec
    u0 = tf.to_unbounded(start[free_idx])

    def one_phi(phi):
        def nll(u):
            vec = base.at[free_idx].set(tf.to_bounded(u))
            p = _unflatten_tpl(vec, tpl).replace(ph_shift=phi)
            return -extended_loglik(kind, p, x, exposure, mask)

        u_best, f_best = nelder_mead(nll, u0, init_scale=0.25, iters=cfg.nm_iters)
        vec_best = base.at[free_idx].set(tf.to_bounded(u_best))
        return -f_best, vec_best

    ll, vecs = jax.vmap(one_phi)(phis)
    return ll, vecs


def _general_profile_loglik(kind, tpl, x, mask, exposure, phis, cfg: ToAFitConfig, warm_vec=None):
    """(LL, norm, ampShift) view of the general profile (API twin of the
    fixed-shape branch; fit_segment uses _general_profile_vecs directly when
    it also needs the refit shape vector)."""
    ll, vecs = _general_profile_vecs(kind, tpl, x, mask, exposure, phis, cfg, warm_vec)
    return ll, vecs[:, 0], vecs[:, 1 + 3 * tpl.n_comp]


# ---------------------------------------------------------------------------
# Per-segment fit
# ---------------------------------------------------------------------------


def _binned_chi2(kind, tpl, x, mask, exposure, phi_best, a_best, b_best, cfg: ToAFitConfig):
    """chi2 of the binned profile against the best-fit model
    (measureToAs.py:383-393 semantics; mask-safe for empty bins)."""
    upper = 1.0 if kind == FOURIER else 2 * jnp.pi
    nbins = cfg.nbins
    idx = jnp.clip((x / upper * nbins).astype(jnp.int32), 0, nbins - 1)
    counts = jnp.zeros(nbins, dtype=x.dtype).at[idx].add(mask.astype(x.dtype))
    per_bin_exp = exposure / nbins
    rate = counts / per_bin_exp
    rate_err = jnp.sqrt(counts) / per_bin_exp
    centers = (jnp.arange(nbins, dtype=x.dtype) + 0.5) * (upper / nbins)
    model = (
        a_best
        + b_best * shape_at_shifts(kind, tpl, centers, jnp.asarray([phi_best]))[0]
    )
    valid = counts > 0
    chi2 = jnp.sum(jnp.where(valid, (model - rate) ** 2 / jnp.where(valid, rate_err, 1.0) ** 2, 0.0))
    n_free = cfg.n_free if cfg.n_free >= 0 else 2 + (1 if cfg.vary_amps else 0)
    # a heavily-parameterized readvaryparam fit can exhaust the bins; clamp
    # the dof at 1 so the reported redChi2 stays finite and positive
    return chi2 / max(nbins - n_free, 1)


def _error_scan(kind, tpl, x, mask, exposure, phi_best, ll_max, cfg: ToAFitConfig, warm_vec=None):
    """Likelihood-profile 1-sigma bounds: dense first window + chunked loop.

    Reproduces the reference counting: the reported bound is
    (k*+1)*step + step/2 where k* is the first step whose LL drop exceeds
    the half-chi2 threshold; if no crossing within res/2 steps the bound
    saturates (measureToAs.py:331-376). In readvaryparam mode ``warm_vec``
    (the best-fit vector) seeds every per-step Nelder-Mead so the scan
    refines from the optimum instead of restarting cold at the template.

    Two phases. Phase 1 evaluates BOTH sides' first W steps in ONE profile
    sweep — a (2W x events) launch, MXU-shaped for Fourier — and extracts
    each side's first crossing with argmax. Phase 2 is the original chunked
    while_loop, seeded at k0 = W: under vmap it runs zero iterations when
    every segment in the batch crossed inside its window (the common case —
    W=32 covers bounds an order of magnitude above typical error bars), so
    the per-side serial dependency chain disappears. Per-phi profile values
    are row-independent (the inner Newton solve never mixes grid points), so
    any W yields bit-identical bounds; the knob only moves work between the
    dense sweep and the fallback loop.

    Returns (err_lo, err_hi, loop_iters) with loop_iters the number of
    fallback while_loop bodies this segment executed (both sides summed) —
    0 means the dense window fully covered the scan.
    """
    step = (2 * jnp.pi) / cfg.ph_shift_res
    max_k = cfg.ph_shift_res // 2
    chunk = cfg.err_chunk
    W = cfg.err_dense_window if cfg.err_dense_window >= 0 else DENSE_WINDOW_DEFAULT
    W = min(W, max_k)

    def scan_profile(phis):
        ll, _ = profile_loglik(kind, tpl, x, mask, exposure, phis, cfg, warm_vec)
        return ll

    if W > 0:
        ks_w = 1 + jnp.arange(W)
        phis_dense = jnp.concatenate(
            [phi_best - ks_w * step, phi_best + ks_w * step]
        )
        dense_cross = (ll_max - scan_profile(phis_dense)) > CHI2_1SIG_HALF

        def seed(block):
            # first crossing within the window; no crossing -> saturated
            # kstop placeholder that the fallback loop overwrites (or keeps,
            # when W == max_k and the scan really saturates)
            any_cross = jnp.any(block)
            k_star = ks_w[jnp.argmax(block)]
            kstop = jnp.where(any_cross, k_star + 1, max_k + 1)
            return (jnp.asarray(W), any_cross, kstop)

        init_lo = seed(dense_cross[:W])
        init_hi = seed(dense_cross[W:])
    else:
        cold = (jnp.asarray(0), jnp.asarray(False), jnp.asarray(max_k + 1))
        init_lo = init_hi = cold

    def one_side(sign, init):
        def cond(state):
            k0, found, _ = state
            return (~found) & (k0 < max_k)

        def body(state):
            k0, found, kstop = state
            ks = k0 + 1 + jnp.arange(chunk)
            phis = phi_best + sign * ks * step
            drop = ll_max - scan_profile(phis)
            # only steps within range count
            crossed = (drop > CHI2_1SIG_HALF) & (ks <= max_k)
            any_cross = jnp.any(crossed)
            first = jnp.argmax(crossed)  # first True index
            k_star = ks[first]
            new_found = found | any_cross
            new_kstop = jnp.where(~found & any_cross, k_star + 1, kstop)
            return (k0 + chunk, new_found, new_kstop)

        k0_fin, _, kstop = jax.lax.while_loop(cond, body, init)
        iters = (k0_fin - init[0]) // chunk
        return kstop * step + step / 2, iters

    err_lo, it_lo = one_side(-1.0, init_lo)
    err_hi, it_hi = one_side(+1.0, init_hi)
    return err_lo, err_hi, it_lo + it_hi


def fit_segment(kind: str, tpl: ProfileParams, x: jax.Array, mask: jax.Array, exposure: jax.Array, cfg: ToAFitConfig) -> dict:
    """Full ToA fit of one (padded) segment; designed to be vmapped."""
    half_range = _phase_range(kind)

    # 1) coarse global brute grid (the reference's brutemin path is the
    #    default here: the grid is effectively free once vectorized).
    #    Chunked with lax.map so the vmapped (segments, phases, events)
    #    tensor never exceeds HBM: a 500-segment config-4 batch at
    #    n_brute=128 is ~8 GB per temp unchunked (OOMed a 16 GB chip).
    brute_phis = jnp.linspace(-half_range, half_range, cfg.n_brute)
    chunk = max(1, min(cfg.brute_chunk, cfg.n_brute))
    pad = (-cfg.n_brute) % chunk
    phis_pad = (
        jnp.concatenate([brute_phis, jnp.full((pad,), brute_phis[-1])])
        if pad
        else brute_phis
    )
    ll_brute = jax.lax.map(
        lambda p: profile_loglik(kind, tpl, x, mask, exposure, p, cfg)[0],
        phis_pad.reshape(-1, chunk),
    ).reshape(-1)[: cfg.n_brute]
    i_best = jnp.argmax(ll_brute)
    phi0 = brute_phis[i_best]
    grid_step = 2 * half_range / (cfg.n_brute - 1)

    # 2) refine to the true profile-likelihood optimum. Two modes:
    #    - "golden": classic golden-section — refine_iters SERIAL
    #      single-phi evaluations (a long dependency chain of tiny
    #      kernels; latency-bound on accelerators);
    #    - "grid": refine_rounds nested vectorized fine grids — each
    #      round evaluates refine_grid phis across the current bracket in
    #      ONE launch and re-centers on the argmax, shrinking the bracket
    #      by (refine_grid-1)/2 per round. Serial depth refine_iters ->
    #      refine_rounds at ~(rounds*grid)/iters times the (cheap,
    #      parallel) FLOPs. Default precision: grid_step*(2/32)^4 =
    #      7.5e-7 rad, on par with 25 golden iterations (0.618^25 *
    #      grid_step = 6e-7).
    if cfg.refine_mode == "grid":
        # refine_grid must be odd and >= 3: odd so linspace(-1, 1, g)
        # re-samples the incumbent phi_c at offset 0 (ll_max can never
        # regress between rounds), >= 3 so the bracket actually shrinks
        if cfg.refine_grid < 3 or cfg.refine_grid % 2 == 0:
            raise ValueError(
                f"refine_grid must be odd and >= 3, got {cfg.refine_grid}"
            )
        phi_c = phi0
        ll_max = ll_brute[i_best]
        half = grid_step
        for _ in range(cfg.refine_rounds):
            offs = jnp.linspace(-1.0, 1.0, cfg.refine_grid)
            phis_r = phi_c + half * offs
            ll_r, _ = profile_loglik(kind, tpl, x, mask, exposure, phis_r, cfg)
            j = jnp.argmax(ll_r)
            phi_c = phis_r[j]
            ll_max = ll_r[j]
            half = 2.0 * half / (cfg.refine_grid - 1)
        phi_best = phi_c
    elif cfg.refine_mode == "golden":
        def ll_of(phi):
            ll, _ = profile_loglik(kind, tpl, x, mask, exposure, phi[None], cfg)
            return ll[0]

        phi_best, ll_max = golden_section(
            ll_of, phi0 - grid_step, phi0 + grid_step, iters=cfg.refine_iters
        )
    else:
        raise ValueError(
            f"unknown refine_mode {cfg.refine_mode!r} (expected 'golden' or 'grid')"
        )

    # 3) nuisance parameters at the optimum — ONE solve at phi_best; general
    #    mode also yields the full refit shape vector for the chi2 model
    if cfg.free_idx:
        _, vecs = _general_profile_vecs(
            kind, tpl, x, mask, exposure, phi_best[None], cfg
        )
        vec_best = vecs[0]
        a_best = vec_best[0]
        b_best = vec_best[1 + 3 * tpl.n_comp]
    else:
        _, a_best_arr, b_best_arr = profile_loglik_full(
            kind, tpl, x, mask, exposure, phi_best[None], cfg
        )
        a_best = a_best_arr[0]
        b_best = b_best_arr[0]
        vec_best = (
            _flatten_tpl(tpl).at[0].set(a_best).at[1 + 3 * tpl.n_comp].set(b_best)
        )

    # 4) likelihood-profile error bounds (in readvaryparam mode each step's
    #    Nelder-Mead starts from the best-fit vector, not the cold template)
    warm = vec_best if cfg.free_idx else None
    err_lo, err_hi, scan_iters = _error_scan(
        kind, tpl, x, mask, exposure, phi_best, ll_max, cfg, warm
    )

    # 5) binned-profile goodness of fit (general mode evaluates the model at
    #    the REFIT shape parameters, with ampShift folded into the template)
    if cfg.free_idx:
        tpl_chi2 = _unflatten_tpl(vec_best, tpl)
        red_chi2 = _binned_chi2(
            kind, tpl_chi2, x, mask, exposure, phi_best, vec_best[0],
            jnp.ones(()), cfg,
        )
    else:
        red_chi2 = _binned_chi2(kind, tpl, x, mask, exposure, phi_best, a_best, b_best, cfg)

    return {
        "phShift": phi_best,
        "phShift_LL": err_lo,
        "phShift_UL": err_hi,
        "norm": a_best,
        "ampShift": b_best,
        "logLmax": ll_max,
        "redChi2": red_chi2,
        # fallback while_loop bodies the error scan ran (both sides): 0 when
        # the dense first window covered the whole scan — the diagnostic the
        # dense-path tests and bench A/B key off
        "errScanLoopIters": scan_iters,
        # full flattened best-fit parameter vector [norm, amps, locs, wids,
        # ampShift] — in general (readvaryparam) mode this carries the REFIT
        # shape, which callers must use to reproduce the fitted model
        "theta_best": vec_best,
    }


@partial(jax.jit, static_argnames=("kind", "cfg"))
def fit_toas_batch(
    kind: str,
    tpl: ProfileParams,
    phases: jax.Array,  # (S, Nmax) folded phases, padded
    masks: jax.Array,  # (S, Nmax) validity
    exposures: jax.Array,  # (S,)
    cfg: ToAFitConfig,
) -> dict:
    """vmap of fit_segment over ToA segments: the whole ToA run in one call."""
    return jax.vmap(lambda x, m, t: fit_segment(kind, tpl, x, m, t, cfg))(
        phases, masks, exposures
    )


def resolve_runtime_cfg(cfg: ToAFitConfig, n_segments: int, n_events: int) -> ToAFitConfig:
    """Fill the cfg's auto (-1) knobs from env / autotune cache.

    HOST-side, before the jit trace: ``cfg`` is a static argument of
    ``fit_toas_batch``, so env and cache consults must never happen inside
    the traced function. Explicit (>= 0) values always win; -1 sentinels
    resolve through ``autotune.resolve_toafit`` (env var > cached winner >
    static default). Called by the host wrappers (``fit_toas_batch_auto``,
    ``fit_toas_bucketed``); direct ``fit_toas_batch`` callers get the
    trace-time defaults (dense window on, bf16 off).
    """
    if cfg.err_dense_window >= 0 and cfg.mxu_bf16 >= 0:
        return cfg
    from crimp_tpu.ops import autotune

    knobs = autotune.resolve_toafit(n_segments, n_events)
    upd = {}
    if cfg.err_dense_window < 0:
        upd["err_dense_window"] = int(knobs["err_dense_window"])
    if cfg.mxu_bf16 < 0:
        upd["mxu_bf16"] = int(knobs["mxu_bf16"])
    return cfg._replace(**upd)


def fit_toas_batch_auto(
    kind: str,
    tpl: ProfileParams,
    phases,
    masks,
    exposures,
    cfg: ToAFitConfig,
) -> dict:
    """``fit_toas_batch`` with the SEGMENT axis auto-sharded across devices.

    On a multi-chip host (auto_mesh; ``CRIMP_TPU_SHARD=0`` opts out) the
    batch is padded to a device multiple — padding rows are fully masked
    segments, dropped from the result — and placed with its leading axis
    sharded so the vmapped per-segment fits run data-parallel with zero
    communication (the distributed analog of the reference's serial per-ToA
    loop, measureToAs.py:168). Falls back to the plain single-device batch
    whenever sharding wouldn't help (few segments, one device)."""
    from crimp_tpu.parallel import mesh as pmesh

    phases = np.asarray(phases, dtype=float)
    masks = np.asarray(masks, dtype=bool)
    exposures = np.asarray(exposures, dtype=float)
    n_seg = phases.shape[0]
    if n_seg == 0:
        return {}
    obs.counter_add("toas_fit", n_seg)
    cfg = resolve_runtime_cfg(cfg, n_seg, phases.shape[1])
    n_devices = len(jax.devices()) if pmesh.sharding_enabled() else 1
    if n_devices < 2 or n_seg < n_devices:
        ph = jnp.asarray(phases)
        mk = jnp.asarray(masks)
        ex = jnp.asarray(exposures)
        out = fit_toas_batch(kind, tpl, ph, mk, ex, cfg)
        # cost capture only on this unsharded path: abstract stand-ins
        # lose shardings, so the sharded path would cost-model a variant
        # that never ran
        costmodel.capture("toa_fit_batch", fit_toas_batch,
                          kind, tpl, ph, mk, ex, cfg)
        return out
    smesh = pmesh.segment_mesh()
    pad = pmesh.pad_batch_for_mesh(n_seg, smesh)
    if pad:
        phases = np.concatenate([phases, np.zeros((pad,) + phases.shape[1:])])
        masks = np.concatenate(
            [masks, np.zeros((pad,) + masks.shape[1:], dtype=masks.dtype)]
        )
        exposures = np.concatenate([exposures, np.ones(pad)])
    out = fit_toas_batch(
        kind,
        tpl,
        pmesh.shard_segments(phases, smesh),
        pmesh.shard_segments(masks, smesh),
        pmesh.shard_segments(exposures, smesh),
        cfg,
    )
    return {k: v[:n_seg] for k, v in out.items()}


# Sortedness results keyed by array identity so repeated interval slicing of
# the SAME event array (the measure_toas / GTI pattern) pays the O(n) check
# once. The stored base-array reference keeps id() stable and valid; a
# single-slot cache bounds memory to one retained event array. The lock
# matters: slice_sorted_intervals runs on the serve prep-overlap worker
# thread, so an unguarded clear()+store could tear against the main thread.
_SORTED_LOCK = threading.Lock()
_SORTED_CACHE: dict[int, tuple[np.ndarray, bool]] = {}


def _is_sorted_cached(times: np.ndarray) -> bool:
    key = id(times)
    with _SORTED_LOCK:
        hit = _SORTED_CACHE.get(key)
        if hit is not None and hit[0] is times:
            return hit[1]
    ok = bool(np.all(np.diff(times) >= 0))
    with _SORTED_LOCK:
        _SORTED_CACHE.clear()
        _SORTED_CACHE[key] = (times, ok)
    return ok


def slice_sorted_intervals(times, starts, ends,
                           assume_sorted: bool = False) -> list[np.ndarray]:
    """Per-interval event segments of ``times`` over inclusive [start, end]
    windows (host helper).

    Sorted input (one O(n) check per distinct array — results are cached by
    identity — unless the caller vouches with ``assume_sorted``) gets
    O(log n) binary-search slices per interval; unsorted input falls back to
    boolean masks — the intervals × events product makes per-interval masks
    the dominant host cost of segment prep on campaign-sized event lists."""
    times = np.asarray(times)
    if not assume_sorted:
        assume_sorted = _is_sorted_cached(times)
    if assume_sorted:
        return [
            times[np.searchsorted(times, s, "left"):
                  np.searchsorted(times, e, "right")]
            for s, e in zip(starts, ends)
        ]
    return [times[(times >= s) & (times <= e)] for s, e in zip(starts, ends)]


def pad_segments(phase_list: list[np.ndarray]) -> tuple[np.ndarray, np.ndarray]:
    """Pad ragged per-segment phase arrays to (S, Nmax) + mask (host helper)."""
    n_max = max((len(p) for p in phase_list), default=1)
    S = len(phase_list)
    phases = np.zeros((S, n_max))
    masks = np.zeros((S, n_max), dtype=bool)
    used = 0
    for i, p in enumerate(phase_list):
        phases[i, : len(p)] = p
        masks[i, : len(p)] = True
        used += len(p)
    # padding-waste telemetry: cells the masked kernels compute vs cells
    # that carry real events (the bucketed path exists to shrink this gap)
    obs.counter_add("pad_cells_total", S * n_max)
    obs.counter_add("pad_cells_used", used)
    return phases, masks


def bucket_by_pow2(sizes, max_pad_ratio: float = 4.0) -> list[list[int]]:
    """Group indices of ``sizes`` into power-of-two size buckets.

    The shared bucketing policy of the batched engines: sort by size
    (stable), assign each item its ceil-pow2 capacity, and merge
    consecutive capacities while the padding waste for the smallest member
    stays under ``max_pad_ratio``. Used by ``fit_toas_bucketed``
    (segments-within-a-source) and by ops/multisource (whole sources
    within a survey). Returns buckets of ORIGINAL indices, smallest sizes
    first; homogeneous inputs collapse to a single bucket.
    """
    sizes = np.asarray(sizes)
    if sizes.size == 0:
        return []
    order = np.argsort(sizes, kind="stable")
    # bucket boundaries: next power of two of each item's size
    pow2 = 1 << np.ceil(np.log2(np.maximum(sizes[order], 1))).astype(int)
    buckets: list[list[int]] = []
    current: list[int] = []
    current_cap = pow2[0]
    for pos, idx in enumerate(order):
        cap = pow2[pos]
        if current and cap > current_cap and cap > max_pad_ratio * sizes[current[0]]:
            buckets.append(current)
            current = []
        current.append(int(idx))
        current_cap = cap
    if current:
        buckets.append(current)
    return buckets


def fit_toas_bucketed(
    kind: str,
    tpl: ProfileParams,
    phase_list: list[np.ndarray],
    exposures: np.ndarray,
    cfg: ToAFitConfig,
    max_pad_ratio: float = 4.0,
) -> dict:
    """Batched ToA fit with SIZE-BUCKETED padding (host orchestration).

    Pad-to-global-max wastes compute when segment event counts are
    heterogeneous (a merged campaign can mix 1e3- and 1e5-event intervals:
    padding everything to 1e5 inflates the likelihood sweeps ~100x for the
    small segments). Segments are grouped into power-of-two size buckets
    (consecutive buckets merged while the padding waste stays under
    ``max_pad_ratio``), each bucket runs one ``fit_toas_batch`` compile/
    execute, and results scatter back to the original order. Homogeneous
    inputs collapse to a single bucket — identical to the plain path.

    The bucket loop is PIPELINED: each iteration pads bucket k+1 on the host
    while the device still runs bucket k's fit — JAX async dispatch returns
    unmaterialized device arrays immediately, and only a second pass calls
    np.asarray (which blocks). Host prep therefore overlaps device compute
    instead of serializing with it.
    """
    sizes = np.asarray([len(p) for p in phase_list])
    if len(phase_list) == 0:
        return {}
    cfg = resolve_runtime_cfg(cfg, len(phase_list), int(sizes.max()))
    buckets = bucket_by_pow2(sizes, max_pad_ratio)

    exposures = np.asarray(exposures, dtype=float)
    # Pass 1 — dispatch: pad + enqueue every bucket's fit without touching
    # the results (device arrays, still computing). Padding bucket k+1 runs
    # while the device chews on bucket k.
    pending: list[tuple[list[int], dict]] = []
    for bucket in buckets:
        phases, masks = pad_segments([phase_list[i] for i in bucket])
        res = fit_toas_batch_auto(kind, tpl, phases, masks, exposures[bucket], cfg)
        pending.append((bucket, res))
    # Pass 2 — materialize: np.asarray blocks on each device buffer in
    # dispatch order and scatters back to the original segment order.
    # Each drained bucket is a heartbeat boundary: this is where a long
    # ToA extraction actually waits on the device, so progress/ETA here
    # tracks real completion rather than async dispatch.
    out: dict[str, np.ndarray] = {}
    for b_done, (bucket, res) in enumerate(pending):
        obs.beat(b_done, len(pending), label="toa_buckets")
        for key, val in res.items():
            arr = np.asarray(val)
            if key not in out:
                out[key] = np.zeros((len(phase_list),) + arr.shape[1:], dtype=arr.dtype)
            out[key][bucket] = arr
    obs.beat(len(pending), len(pending), label="toa_buckets", force=True)
    return out
