"""Batched unbinned maximum-likelihood ToA extraction.

What the reference does per ToA (measureToAs.py:254-403, serial lmfit):
brute grid over phShift, Nelder-Mead refine with the normalization free,
then dozens of full re-minimizations stepping phShift by 2*pi/phShiftRes to
find the +/-1-sigma likelihood-profile bounds. ~2.4 s/ToA on CPU.

The TPU re-design rests on one algebraic fact: for all three template
families the extended log-likelihood at fixed shape is

    LL(phi, A) = -A*T + sum_i m_i log(A + s_i(phi)) + const(T, N)

(for von Mises / Cauchy the constant also absorbs -Q*T/2pi with
Q = sum_j amp_j*ampShift; derivation from templatemodels.py:98-121,
201-226, 306-329). LL is strictly concave in A with
dLL/dA = -T + sum m_i/(A+s_i), so the inner "re-optimize the norm"
solve the reference does numerically per step is a safeguarded Newton
iteration — vectorized across the whole phase grid at once. The profile
likelihood over phShift therefore evaluates as ONE dense sweep:

- Fourier: s_i(phi) = C_i . cos(j phi) + S_i . sin(j phi) — a
  (grid x events) MATMUL on precomputed per-event harmonic coefficients,
  which is exactly the MXU-shaped workload;
- von Mises / Cauchy: direct evaluation, scanned over components.

Segments are padded/bucketed (ragged event counts -> masks) and the whole
fit vmaps over ToA segments: the per-ToA loop disappears.

Error bars keep the reference's exact stepping semantics (step =
2*pi/phShiftRes; first step k* whose LL drop exceeds chi2_1(0.6827)/2;
reported bound = (k*+1)*step + step/2 including the overshoot quirk,
SURVEY.md §2.5), but evaluate the steps as vectorized chunks inside a
while_loop instead of sequential refits.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import i0

from crimp_tpu.models.profiles import CAUCHY, FOURIER, VONMISES, ProfileParams
from crimp_tpu.ops.optimize import golden_section

# 0.5 * chi2.ppf(0.6827, df=1): the 1-sigma likelihood-profile drop
# (measureToAs.py:324). Hard-coded to keep the kernel host-independent.
CHI2_1SIG_HALF = 0.4999320306186937


class ToAFitConfig(NamedTuple):
    """Static configuration for the batched ToA fit."""

    kind: str = FOURIER
    ph_shift_res: int = 1000  # error-scan resolution: step = 2*pi/res
    n_brute: int = 128  # coarse global grid over the phShift range
    newton_iters: int = 30  # inner norm solve
    refine_iters: int = 50  # golden-section refine of the grid optimum
    err_chunk: int = 32  # error-scan steps evaluated per while_loop pass
    nbins: int = 15  # binned-profile chi2 reporting
    norm_lo_frac: float = 0.01  # norm lower bound = frac * template norm
    norm_hi: float = 500.0  # norm upper bound (defineinitialfitparam:715)
    vary_amps: bool = False  # free ampShift (3-parameter fit)
    amp_lo: float = 0.01
    amp_hi: float = 100.0


def _phase_range(kind: str) -> float:
    # phShift in [-pi, pi] for Fourier, [-1.5pi, 1.5pi] for vm/cauchy
    # (defineinitialfitparam, measureToAs.py:722,767).
    return jnp.pi if kind == FOURIER else 1.5 * jnp.pi


# ---------------------------------------------------------------------------
# Shape term s_i(phi) (template minus baseline, ampShift folded in)
# ---------------------------------------------------------------------------


def _fourier_event_coeffs(tpl: ProfileParams, x: jax.Array):
    """Per-event harmonic coefficients: s_i(phi) = C_i.cos(j phi)+S_i.sin(j phi)."""
    j = jnp.arange(1, tpl.n_comp + 1, dtype=x.dtype)
    theta = 2 * jnp.pi * j[None, :] * x[:, None] + tpl.loc[None, :]  # (N, K)
    amp = tpl.amp * tpl.amp_shift
    return amp[None, :] * jnp.cos(theta), amp[None, :] * jnp.sin(theta)


def shape_at_shifts(kind: str, tpl: ProfileParams, x: jax.Array, phis: jax.Array) -> jax.Array:
    """s(x_i; phi) for all (phi, event) pairs -> (n_phi, n_event)."""
    if kind == FOURIER:
        C, S = _fourier_event_coeffs(tpl, x)  # (N, K)
        j = jnp.arange(1, tpl.n_comp + 1, dtype=x.dtype)
        cosj = jnp.cos(j[None, :] * phis[:, None])  # (P, K)
        sinj = jnp.sin(j[None, :] * phis[:, None])
        return cosj @ C.T + sinj @ S.T  # MXU matmul: (P, N)

    def add_comp(carry, comp):
        amp, cen, wid = comp
        delta = x[None, :] - cen - phis[:, None]  # (P, N)
        if kind == CAUCHY:
            term = (amp * tpl.amp_shift / (2 * jnp.pi)) * jnp.sinh(wid) / (
                jnp.cosh(wid) - jnp.cos(delta)
            )
        else:  # VONMISES
            kappa = 1.0 / wid**2
            term = (
                amp * tpl.amp_shift / (2 * jnp.pi * i0(kappa)) * jnp.exp(kappa * jnp.cos(delta))
            )
        return carry + term, None

    comps = jnp.stack([tpl.amp, tpl.loc, tpl.wid], axis=-1)
    init = jnp.zeros((phis.shape[0], x.shape[0]), dtype=x.dtype)
    total, _ = jax.lax.scan(add_comp, init, comps)
    return total


# ---------------------------------------------------------------------------
# Inner norm solve + profile likelihood
# ---------------------------------------------------------------------------


def _optimal_norm(s: jax.Array, mask: jax.Array, exposure, n_events, lo, hi, iters: int):
    """Concave inner solve: A with sum_i m_i/(A+s_i) = T, clamped to [lo,hi].

    s: (P, N); returns A (P,).
    """
    min_s = jnp.min(jnp.where(mask[None, :], s, jnp.inf), axis=1)
    feasible_lo = jnp.maximum(lo, -min_s * (1 + 1e-9) + 1e-12)
    a = jnp.clip(n_events / exposure, feasible_lo, hi)

    def body(_, a):
        denom = a[:, None] + s
        inv = jnp.where(mask[None, :], 1.0 / denom, 0.0)
        g = jnp.sum(inv, axis=1) - exposure
        gp = -jnp.sum(inv**2, axis=1)
        step = g / gp
        return jnp.clip(a - step, feasible_lo, hi)

    return jax.lax.fori_loop(0, iters, body, a)


def _loglik_at(kind, tpl, s, a, mask, exposure, n_events):
    """Extended LL given shape values s (P,N) and norms a (P,)."""
    vals = a[:, None] + s
    positive = jnp.min(jnp.where(mask[None, :], vals, jnp.inf), axis=1) > 0
    log_sum = jnp.sum(jnp.where(mask[None, :], jnp.log(jnp.clip(vals, 1e-300)), 0.0), axis=1)
    if kind == FOURIER:
        const = n_events * jnp.log(exposure)
        ll = -a * exposure + const + log_sum
    else:
        q = jnp.sum(tpl.amp * tpl.amp_shift)
        const = n_events * jnp.log(exposure / (2 * jnp.pi)) - q * exposure / (2 * jnp.pi)
        ll = -a * exposure + const + log_sum
    return jnp.where(positive, ll, -jnp.inf)


def profile_loglik(kind, tpl, x, mask, exposure, phis, cfg: ToAFitConfig):
    """(LL(phi), A*(phi)) profile with the norm re-optimized per shift."""
    n_events = jnp.sum(mask)
    s = shape_at_shifts(kind, tpl, x, phis)
    lo = cfg.norm_lo_frac * tpl.norm
    a = _optimal_norm(s, mask, exposure, n_events, lo, cfg.norm_hi, cfg.newton_iters)
    ll = _loglik_at(kind, tpl, s, a, mask, exposure, n_events)
    return ll, a


# ---------------------------------------------------------------------------
# Per-segment fit
# ---------------------------------------------------------------------------


def _binned_chi2(kind, tpl, x, mask, exposure, phi_best, a_best, cfg: ToAFitConfig):
    """chi2 of the binned profile against the best-fit model
    (measureToAs.py:383-393 semantics; mask-safe for empty bins)."""
    upper = 1.0 if kind == FOURIER else 2 * jnp.pi
    nbins = cfg.nbins
    idx = jnp.clip((x / upper * nbins).astype(jnp.int32), 0, nbins - 1)
    counts = jnp.zeros(nbins, dtype=x.dtype).at[idx].add(mask.astype(x.dtype))
    per_bin_exp = exposure / nbins
    rate = counts / per_bin_exp
    rate_err = jnp.sqrt(counts) / per_bin_exp
    centers = (jnp.arange(nbins, dtype=x.dtype) + 0.5) * (upper / nbins)
    model = (
        a_best
        + shape_at_shifts(kind, tpl, centers, jnp.asarray([phi_best]))[0]
    )
    valid = counts > 0
    chi2 = jnp.sum(jnp.where(valid, (model - rate) ** 2 / jnp.where(valid, rate_err, 1.0) ** 2, 0.0))
    n_free = 2 + (1 if cfg.vary_amps else 0)
    return chi2 / (nbins - n_free)


def _error_scan(kind, tpl, x, mask, exposure, phi_best, ll_max, cfg: ToAFitConfig):
    """Likelihood-profile 1-sigma bounds by chunked vectorized stepping.

    Reproduces the reference counting: the reported bound is
    (k*+1)*step + step/2 where k* is the first step whose LL drop exceeds
    the half-chi2 threshold; if no crossing within res/2 steps the bound
    saturates (measureToAs.py:331-376).
    """
    step = (2 * jnp.pi) / cfg.ph_shift_res
    max_k = cfg.ph_shift_res // 2
    chunk = cfg.err_chunk

    def scan_profile(phis):
        ll, _ = profile_loglik(kind, tpl, x, mask, exposure, phis, cfg)
        return ll

    def one_side(sign):
        def cond(state):
            k0, found, _ = state
            return (~found) & (k0 < max_k)

        def body(state):
            k0, found, kstop = state
            ks = k0 + 1 + jnp.arange(chunk)
            phis = phi_best + sign * ks * step
            drop = ll_max - scan_profile(phis)
            # only steps within range count
            crossed = (drop > CHI2_1SIG_HALF) & (ks <= max_k)
            any_cross = jnp.any(crossed)
            first = jnp.argmax(crossed)  # first True index
            k_star = ks[first]
            new_found = found | any_cross
            new_kstop = jnp.where(~found & any_cross, k_star + 1, kstop)
            return (k0 + chunk, new_found, new_kstop)

        init = (jnp.asarray(0), jnp.asarray(False), jnp.asarray(max_k + 1))
        _, found, kstop = jax.lax.while_loop(cond, body, init)
        return kstop * step + step / 2

    return one_side(-1.0), one_side(+1.0)


def fit_segment(kind: str, tpl: ProfileParams, x: jax.Array, mask: jax.Array, exposure: jax.Array, cfg: ToAFitConfig) -> dict:
    """Full ToA fit of one (padded) segment; designed to be vmapped."""
    half_range = _phase_range(kind)

    # 1) coarse global brute grid (the reference's brutemin path is the
    #    default here: the grid is effectively free once vectorized)
    brute_phis = jnp.linspace(-half_range, half_range, cfg.n_brute)
    ll_brute, _ = profile_loglik(kind, tpl, x, mask, exposure, brute_phis, cfg)
    i_best = jnp.argmax(ll_brute)
    phi0 = brute_phis[i_best]
    grid_step = 2 * half_range / (cfg.n_brute - 1)

    # 2) golden-section refine to the true profile-likelihood optimum
    def ll_of(phi):
        ll, _ = profile_loglik(kind, tpl, x, mask, exposure, phi[None], cfg)
        return ll[0]

    phi_best, ll_max = golden_section(
        ll_of, phi0 - grid_step, phi0 + grid_step, iters=cfg.refine_iters
    )
    _, a_best_arr = profile_loglik(kind, tpl, x, mask, exposure, phi_best[None], cfg)
    a_best = a_best_arr[0]

    # 3) likelihood-profile error bounds
    err_lo, err_hi = _error_scan(kind, tpl, x, mask, exposure, phi_best, ll_max, cfg)

    # 4) binned-profile goodness of fit
    red_chi2 = _binned_chi2(kind, tpl, x, mask, exposure, phi_best, a_best, cfg)

    return {
        "phShift": phi_best,
        "phShift_LL": err_lo,
        "phShift_UL": err_hi,
        "norm": a_best,
        "logLmax": ll_max,
        "redChi2": red_chi2,
    }


@partial(jax.jit, static_argnames=("kind", "cfg"))
def fit_toas_batch(
    kind: str,
    tpl: ProfileParams,
    phases: jax.Array,  # (S, Nmax) folded phases, padded
    masks: jax.Array,  # (S, Nmax) validity
    exposures: jax.Array,  # (S,)
    cfg: ToAFitConfig,
) -> dict:
    """vmap of fit_segment over ToA segments: the whole ToA run in one call."""
    return jax.vmap(lambda x, m, t: fit_segment(kind, tpl, x, m, t, cfg))(
        phases, masks, exposures
    )


def pad_segments(phase_list: list[np.ndarray]) -> tuple[np.ndarray, np.ndarray]:
    """Pad ragged per-segment phase arrays to (S, Nmax) + mask (host helper)."""
    n_max = max((len(p) for p in phase_list), default=1)
    S = len(phase_list)
    phases = np.zeros((S, n_max))
    masks = np.zeros((S, n_max), dtype=bool)
    for i, p in enumerate(phase_list):
        phases[i, : len(p)] = p
        masks[i, : len(p)] = True
    return phases, masks
