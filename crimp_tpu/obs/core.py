"""Spans + metrics core and the flight recorder.

Design constraints (enforced by tests/test_obs.py):

- **Disabled is free.** With ``CRIMP_TPU_OBS`` off there is no active
  :class:`RunRecorder`; :func:`span` returns the shared :data:`NULL_SPAN`
  singleton and :func:`counter_add`/:func:`gauge_set`/:func:`record_span`
  return after a single module-global ``None`` check. Zero allocations,
  zero filesystem writes, zero branches beyond the guard.
- **Thread-safe.** The double-buffered host→device streaming path runs
  producer threads; all registry mutation happens under one re-entrant
  lock and span parentage is tracked per-thread, so concurrent stages
  record correctly instead of racing a bare dict.
- **Crash-durable.** When events are on, every span open/close, counter,
  gauge and heartbeat event is appended (and flushed) to a JSONL stream
  as it happens, each stamped with a run-relative monotonic ``t_s``; the
  manifest is written atomically (tmp + rename) at run end. A killed run
  therefore leaves enough on disk for ``obs salvage`` to reconstruct a
  best-effort manifest (see :mod:`crimp_tpu.obs.salvage`).
- **Host-side by construction.** Never imports jax at module level and
  never initializes a backend: platform identity is probed only from
  backends some *other* code already brought up. graftlint GL001 bans
  calls into this package from traced code.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading
import time

from crimp_tpu import knobs

logger = logging.getLogger("crimp_tpu.obs")

OBS_SCHEMA = "crimp_tpu.obs"
OBS_SCHEMA_VERSION = 1

_LOCK = threading.RLock()
_RUN: "RunRecorder | None" = None
_LAST_MANIFEST: str | None = None
_RUN_SEQ = 0
_TLS = threading.local()


class _NullSpan:
    """The disabled-path span: a shared, stateless no-op context manager.

    ``span()`` returns this exact singleton whenever no run is active, so
    instrumented hot loops allocate nothing when obs is off (the overhead
    test pins ``span(...) is NULL_SPAN``).
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


def _host_identity() -> tuple[int, int]:
    """``(host_index, host_count)`` for per-host artifact suffixing.

    ``CRIMP_TPU_OBS_HOST`` overrides (for launchers that co-locate
    processes on one obs dir without ``jax.distributed`` — which is also
    the heartbeat-sidecar collision fix); its host COUNT is only the
    lower bound ``max(2, idx + 1)``, enough to engage the suffix. Unset,
    identity comes from ``parallel/multihost.process_identity()`` — but
    only when jax is already imported; obs never drags jax in.
    """
    idx = knobs.env_nonneg_int("CRIMP_TPU_OBS_HOST")
    if idx is not None:
        return idx, max(2, idx + 1)
    if "jax" not in sys.modules:
        return 0, 1
    try:
        from crimp_tpu.parallel.multihost import process_identity

        return process_identity()
    except Exception:  # noqa: BLE001 — identity is best-effort  # graftlint: disable=GL006 (telemetry guard: a failed identity probe must mean single-host, never a crashed run start)
        return 0, 1


def enabled() -> bool:
    """Whether ``CRIMP_TPU_OBS`` asks for telemetry (malformed raises)."""
    return bool(knobs.env_onoff("CRIMP_TPU_OBS"))


def active() -> "RunRecorder | None":
    """The in-flight run recorder, or None (the common, disabled case)."""
    return _RUN


def last_manifest_path() -> str | None:
    """Path of the most recently finalized manifest in this process."""
    return _LAST_MANIFEST


def _stack() -> list:
    try:
        return _TLS.stack
    except AttributeError:
        _TLS.stack = []
        return _TLS.stack


class Span:
    """A live hierarchical span; records on ``__exit__``.

    Parentage comes from the per-thread span stack: spans opened on a
    producer thread parent to that thread's innermost open span, falling
    back to the run root. Construction reserves the span's slot in the
    recorder so children opened before the parent closes still point at
    a real index.
    """

    __slots__ = ("_rec", "_row", "_t0", "index")

    def __init__(self, rec: "RunRecorder", name: str, kind: str, attrs: dict):
        stack = _stack()
        parent = stack[-1] if stack else 0
        self._rec = rec
        self._t0 = time.perf_counter()
        self._row = {
            "name": str(name),
            "kind": str(kind),
            "t0_s": round(self._t0 - rec.t0, 6),
            "dur_s": None,
            "parent": parent,
            "thread": rec._thread_ordinal(),
            "attrs": dict(attrs),
        }
        if kind == "stage":
            stats = _hbm_stats()
            if stats and isinstance(stats.get("bytes_in_use"), (int, float)):
                self._row["attrs"]["hbm_enter_bytes"] = stats["bytes_in_use"]
        with _LOCK:
            self.index = len(rec.spans)
            rec.spans.append(self._row)
        stack.append(self.index)
        rec._emit({"ev": "span_open", "i": self.index,
                   **{k: self._row[k] for k in
                      ("name", "kind", "t0_s", "parent", "thread")}})

    def set(self, **attrs):
        """Attach attributes to the span while it is open."""
        self._row["attrs"].update(attrs)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        stack = _stack()
        if stack and stack[-1] == self.index:
            stack.pop()
        elif self.index in stack:  # unbalanced exit (generator teardown)
            stack.remove(self.index)
        self._row["dur_s"] = round(dur, 6)
        if exc_type is not None:
            self._row["attrs"]["error"] = f"{exc_type.__name__}: {exc}"
        if self._row["kind"] == "stage":
            stats = _hbm_stats()
            if stats:
                if isinstance(stats.get("bytes_in_use"), (int, float)):
                    self._row["attrs"]["hbm_exit_bytes"] = stats["bytes_in_use"]
                if isinstance(stats.get("peak_bytes_in_use"), (int, float)):
                    self._row["attrs"]["hbm_peak_bytes"] = \
                        stats["peak_bytes_in_use"]
                self._rec._hbm_update(stats)
        self._rec._emit({"ev": "span", "i": self.index, **self._row})
        return False


class RunRecorder:
    """Accumulates one run's spans/counters/gauges; writes the artifacts.

    Span 0 is always the run root. ``finalize()`` closes the root span,
    gathers environment-shaped context (knob snapshot, platform/device
    identity, compile telemetry) and atomically writes the manifest.
    """

    def __init__(self, name: str, attrs: dict):
        global _RUN_SEQ
        with _LOCK:
            _RUN_SEQ += 1
            seq = _RUN_SEQ
        self.name = str(name)
        self.t0 = time.perf_counter()
        self.t0_unix = time.time()
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(self.t0_unix))
        self.host, self.hosts = _host_identity()
        if self.hosts > 1:
            # multi-host: the run_id must be HOST-INVARIANT so `obs merge`
            # can join the per-host streams — pid would differ per host, so
            # it is dropped. (Second-level clock skew between hosts can
            # still split the stamp; `obs merge --force` joins anyway.)
            self.run_id = f"{self.name}-{stamp}-mh-r{seq}"
        else:
            self.run_id = f"{self.name}-{stamp}-p{os.getpid()}-r{seq}"
        # per-host artifact suffix: events/heartbeat/manifest filenames of
        # co-located processes must never collide on a shared obs dir
        self.host_tag = f".host{self.host}" if self.hosts > 1 else ""
        self.dir = knobs.env_str("CRIMP_TPU_OBS_DIR", "obs_runs")
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.costmodel: dict[str, dict] = {}
        self.numeric_mode: dict | None = None
        self.error: str | None = None
        self.degraded: list[str] = []
        self.spans: list[dict] = [{
            "name": self.name, "kind": "run", "t0_s": 0.0, "dur_s": None,
            "parent": None, "thread": 0, "attrs": dict(attrs),
        }]
        self._threads: dict[int, int] = {threading.get_ident(): 0}
        self._events = None
        self.hb = None  # lazy per-run heartbeat state (obs/heartbeat.py)
        self.hbm_start = _hbm_stats()  # None on CPU / no accelerator
        self._hbm_warned = False
        try:
            os.makedirs(self.dir, exist_ok=True)
            if knobs.env_onoff("CRIMP_TPU_OBS_EVENTS") is not False:
                path = os.path.join(
                    self.dir, self.run_id + self.host_tag + ".events.jsonl")
                self._events = open(path, "a", encoding="utf-8")
        except OSError:
            # Telemetry must never fail a run: a read-only or full obs dir
            # just means no events stream for this run.
            self._note_write_error("events open")
        # The knob snapshot rides in run_start so a salvaged manifest can
        # carry the same environment record a finalized one does.
        self._emit({"ev": "run_start", "schema": OBS_SCHEMA,
                    "schema_version": OBS_SCHEMA_VERSION,
                    "run_id": self.run_id, "name": self.name,
                    "host": self.host, "host_count": self.hosts,
                    "t_start_unix": round(self.t0_unix, 3),
                    "knobs": _knob_snapshot(),
                    "attrs": dict(attrs)})

    def _thread_ordinal(self) -> int:
        ident = threading.get_ident()
        with _LOCK:
            return self._threads.setdefault(ident, len(self._threads))

    def _note_write_error(self, where: str) -> None:
        """Record a telemetry write failure and stop writing for the run."""
        with _LOCK:
            if self._events is not None:
                try:
                    self._events.close()
                except OSError:
                    pass
                self._events = None
            self.counters["telemetry_write_errors"] = \
                self.counters.get("telemetry_write_errors", 0) + 1
        logger.warning(
            "obs %s write failed (ENOSPC/read-only?); further telemetry "
            "writes disabled for run %s", where, self.run_id)

    def _emit(self, event: dict) -> None:
        if self._events is None:
            return
        with _LOCK:
            if self._events is None:  # closed by finalize on another thread
                return
            event.setdefault("t_s", round(time.perf_counter() - self.t0, 6))
            try:
                json.dump(event, self._events, default=str)
                self._events.write("\n")
                self._events.flush()
            except OSError:
                self._note_write_error("events")

    def manifest(self) -> dict:
        """The manifest document (schema contract in docs/observability.md)."""
        return {
            "schema": OBS_SCHEMA,
            "schema_version": OBS_SCHEMA_VERSION,
            "run_id": self.run_id,
            "name": self.name,
            "host": self.host,
            "host_count": self.hosts,
            "t_start_unix": round(self.t0_unix, 3),
            "wall_s": self.spans[0]["dur_s"],
            "error": self.error,
            "degraded": bool(self.degraded),
            "degradations": list(self.degraded),
            "platform": _platform_identity(),
            "knobs": _knob_snapshot(),
            "numeric_mode": self.numeric_mode,
            "compile": _compile_snapshot(),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "costmodel": dict(self.costmodel),
            "spans": list(self.spans),
        }

    def _hbm_update(self, stats: dict) -> None:
        """Fold one device memory_stats sample into the run's HBM gauges.

        Tracks the run-wide high water (``hbm_peak_bytes``) and warns —
        once per run — when the device's own peak crosses the
        CRIMP_TPU_HBM_WARN_PCT fraction of its byte limit.
        """
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use", in_use)
        limit = stats.get("bytes_limit")
        with _LOCK:
            if isinstance(in_use, (int, float)):
                self.gauges["hbm_bytes_in_use"] = in_use
            if isinstance(peak, (int, float)):
                prev = self.gauges.get("hbm_peak_bytes", 0)
                self.gauges["hbm_peak_bytes"] = max(prev, peak)
        if (not self._hbm_warned and isinstance(peak, (int, float))
                and isinstance(limit, (int, float)) and limit > 0):
            warn_pct = knobs.env_float("CRIMP_TPU_HBM_WARN_PCT", 90.0)
            pct = 100.0 * peak / limit
            if warn_pct > 0 and pct >= warn_pct:
                self._hbm_warned = True
                with _LOCK:
                    self.counters["hbm_warn_trips"] = \
                        self.counters.get("hbm_warn_trips", 0) + 1
                logger.warning(
                    "HBM high water %.1f%% of limit (%d / %d bytes) — above "
                    "CRIMP_TPU_HBM_WARN_PCT=%g", pct, peak, limit, warn_pct)
                self._emit({"ev": "ctr", "k": "hbm_warn_trips", "v": 1})

    def finalize(self) -> str | None:
        """Close the root span, write the manifest atomically, return its path.

        Returns None (and logs) when the obs dir rejects the write — a run
        that computed correctly must not die on its telemetry epilogue.
        """
        end = _hbm_stats()
        if end and isinstance(end.get("bytes_in_use"), (int, float)):
            with _LOCK:
                self.gauges["hbm_run_end_bytes"] = end["bytes_in_use"]
                start = (self.hbm_start or {}).get("bytes_in_use")
                if isinstance(start, (int, float)):
                    # held-buffer delta across the run: a persistent growth
                    # here is the leak signal (caches are expected to show
                    # a bounded, explainable delta)
                    self.gauges["hbm_leak_bytes"] = end["bytes_in_use"] - start
            self._emit({"ev": "gauge", "k": "hbm_run_end_bytes",
                        "v": end["bytes_in_use"]})
        with _LOCK:
            if self.spans[0]["dur_s"] is None:
                self.spans[0]["dur_s"] = round(time.perf_counter() - self.t0, 6)
            doc = self.manifest()
            path = os.path.join(
                self.dir, self.run_id + self.host_tag + ".manifest.json")
            tmp = path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(doc, fh, indent=1, sort_keys=False, default=str)
                    fh.write("\n")
                os.replace(tmp, path)
            except OSError:
                self._note_write_error("manifest")
                return None
            if self._events is not None:
                self._emit({"ev": "run_end", "run_id": self.run_id,
                            "wall_s": self.spans[0]["dur_s"],
                            "manifest": path, "error": self.error})
                if self._events is not None:
                    try:
                        self._events.close()
                    except OSError:
                        pass
                    self._events = None
        return path


def _knob_snapshot() -> dict[str, str]:
    """Raw env values of every *set* registered knob (missing key = unset).

    Reading through :func:`knobs.raw` keeps GL003's single-sanctioned-read
    invariant; recording only set knobs makes knob drift a plain dict
    diff (appeared / disappeared / changed).
    """
    snap = {}
    for name in sorted(knobs.REGISTRY):
        val = knobs.raw(name)
        if val:
            snap[name] = val
    return snap


def _platform_identity() -> dict:
    """Backend/device identity from already-initialized backends only.

    Importing jax (cheap, likely already done) is fine; *initializing a
    backend is not* — ``import crimp_tpu`` and the obs CLI must never
    acquire devices. So we peek at ``jax._src.xla_bridge``'s backend
    table and return a stub when nothing has been brought up yet.
    """
    out = {"python": sys.version.split()[0], "backend": None, "devices": []}
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    try:
        out["jax"] = jax.__version__
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None) or {}
        for plat, backend in backends.items():
            out["backend"] = out["backend"] or plat
            for d in backend.devices():
                dev = {"id": d.id, "platform": d.platform,
                       "kind": getattr(d, "device_kind", "")}
                try:
                    stats = d.memory_stats()
                except Exception:  # noqa: BLE001 — CPU devices have none  # graftlint: disable=GL006 (telemetry guard: memory_stats is absent on CPU backends; obs cannot import resilience without a cycle)
                    stats = None
                if stats:
                    dev["bytes_in_use"] = stats.get("bytes_in_use")
                    dev["bytes_limit"] = stats.get("bytes_limit")
                out["devices"].append(dev)
    except Exception:  # noqa: BLE001 — identity is best-effort telemetry  # graftlint: disable=GL006 (telemetry guard: platform identity must never fail a run; obs cannot import resilience without a cycle)
        pass
    return out


def _hbm_stats() -> dict | None:
    """One ``device.memory_stats()`` sample from the live backend, or None.

    Same never-initialize contract as :func:`_platform_identity`: only
    backends some other code already brought up are consulted, and CPU
    devices (whose ``memory_stats`` returns None or raises) degrade to
    None — the HBM gauges simply don't exist for CPU runs.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None) or {}
        for backend in backends.values():
            for d in backend.devices():
                stats = d.memory_stats()
                if stats:
                    return {"bytes_in_use": stats.get("bytes_in_use"),
                            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                            "bytes_limit": stats.get("bytes_limit")}
    except Exception:  # noqa: BLE001 — watermarks are best-effort telemetry  # graftlint: disable=GL006 (telemetry guard: memory_stats is backend-dependent; HBM sampling must never fail a span)
        pass
    return None


def _compile_snapshot() -> dict | None:
    """The compile-cache telemetry, when the profiling listeners exist."""
    try:
        from crimp_tpu.utils import profiling
        return profiling.compile_counters()
    except Exception:  # noqa: BLE001 — telemetry must never fail a run  # graftlint: disable=GL006 (telemetry guard: compile-cache counters are optional; obs cannot import resilience without a cycle)
        return None


@contextlib.contextmanager
def run(name: str, **attrs):
    """Flight-record a pipeline entry point.

    No-op (yields None) when obs is disabled. When a run is already
    active, the inner entry point becomes a ``kind="run"`` span of the
    outer run (bench wrapping a pipeline), so nesting never produces two
    manifests for one invocation. Otherwise starts a RunRecorder and, on
    exit — error or not — finalizes it into an atomic manifest.
    """
    global _RUN, _LAST_MANIFEST
    if not enabled():
        yield None
        return
    with _LOCK:
        outer = _RUN
        if outer is None:
            rec = RunRecorder(name, attrs)
            _RUN = rec
    if outer is not None:
        with Span(outer, name, "run", attrs) as s:
            yield s
        return
    try:
        yield rec
    except BaseException as exc:
        rec.error = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        with _LOCK:
            _RUN = None
        _stack().clear()
        # finalize (manifest I/O) outside the lock; publish under it so a
        # reader on another thread never sees a torn last-manifest pointer
        manifest = rec.finalize()
        with _LOCK:
            _LAST_MANIFEST = manifest


def span(name: str, kind: str = "stage", **attrs):
    """A hierarchical span context; the shared no-op when no run is active."""
    rec = _RUN
    if rec is None:
        return NULL_SPAN
    return Span(rec, name, kind, attrs)


def record_span(name: str, dur_s: float, kind: str = "kernel", **attrs) -> None:
    """Record an already-timed interval (the ``profiling.timed`` shim).

    The span is parented to the calling thread's innermost open span and
    back-dated so ``t0_s + dur_s`` lands at "now".
    """
    rec = _RUN
    if rec is None:
        return
    stack = _stack()
    row = {
        "name": str(name), "kind": str(kind),
        "t0_s": round(max(0.0, time.perf_counter() - rec.t0 - dur_s), 6),
        "dur_s": round(float(dur_s), 6),
        "parent": stack[-1] if stack else 0,
        "thread": rec._thread_ordinal(),
        "attrs": dict(attrs),
    }
    with _LOCK:
        idx = len(rec.spans)
        rec.spans.append(row)
    rec._emit({"ev": "span", "i": idx, **row})


def current_span_name(default: str | None = None) -> str | None:
    """Leaf name of the calling thread's innermost open span (the run root
    when none is open on this thread); ``default`` when no run is active."""
    rec = _RUN
    if rec is None:
        return default
    stack = _stack()
    idx = stack[-1] if stack else 0
    try:
        return rec.spans[idx]["name"]
    except (IndexError, KeyError):
        return default


def record_cost(name: str, row: dict) -> None:
    """Attach one cost-model row to the active run (no-op when none).

    Keyed by kernel name — the same name the span layer sees — so the
    roofline join is a plain dict lookup. Last capture wins; the rows are
    per-(shape, platform) properties of the executable, so a re-capture
    under the same run is the same row (or a deliberate shape change).
    """
    rec = _RUN
    if rec is None:
        return
    with _LOCK:
        rec.costmodel[str(name)] = dict(row)
    rec._emit({"ev": "cost", "k": str(name), "row": dict(row)})


def counter_add(name: str, value: float = 1) -> None:
    """Add to a monotonic counter of the active run (no-op when none)."""
    rec = _RUN
    if rec is None:
        return
    with _LOCK:
        rec.counters[name] = rec.counters.get(name, 0) + value
    rec._emit({"ev": "ctr", "k": str(name), "v": value})


def gauge_set(name: str, value: float) -> None:
    """Set a point-in-time gauge of the active run (no-op when none)."""
    rec = _RUN
    if rec is None:
        return
    with _LOCK:
        rec.gauges[name] = value
    rec._emit({"ev": "gauge", "k": str(name), "v": value})


def mark_degraded(reason: str) -> None:
    """Stamp the active run degraded (a ladder rung was taken).

    No-op when no run is active. The reasons accumulate in the manifest's
    ``degradations`` list and flip its ``degraded`` flag; the perf ledger
    excludes degraded rounds from the green baseline.
    """
    rec = _RUN
    if rec is None:
        return
    with _LOCK:
        rec.degraded.append(str(reason))
    rec._emit({"ev": "degraded", "reason": str(reason)})


def record_numeric_mode(mode: dict) -> None:
    """Attach the resumable ``numeric_mode`` fingerprint to the run."""
    rec = _RUN
    if rec is None:
        return
    with _LOCK:
        rec.numeric_mode = json.loads(json.dumps(mode, default=str))
    rec._emit({"ev": "numeric_mode", "mode": rec.numeric_mode})
