"""Crash salvage + live tail: consuming the append-only event stream.

The manifest is written atomically at ``finalize()`` — a run killed by
SIGKILL, OOM, or a driver timeout (the r5 bench, rc=124 after ~40 min)
never reaches it and used to leave nothing diffable. But the JSONL event
stream *is* flushed per event, so everything up to the kill is on disk:
``salvage()`` replays it into a best-effort manifest (open spans closed
at the last event's timestamp, counters/gauges re-summed, the knob
snapshot recovered from ``run_start``) that passes ``validate_manifest``
and therefore feeds the same ``obs summary|diff|ledger`` tooling as a
clean run — just marked ``"salvaged": true`` so nobody mistakes its
lower-bound durations for measurements.

``tail()`` is the live view of the same stream: it follows the newest
``*.events.jsonl`` of a run directory, rendering heartbeats (progress,
rate, ETA, open span) and stage closes as they append, and exits when
``run_end`` arrives. Both entry points are wired into the obs CLI
(``python -m crimp_tpu.obs salvage|tail``).
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

from crimp_tpu.obs.core import OBS_SCHEMA, OBS_SCHEMA_VERSION


def read_events(path: str) -> list[dict]:
    """Parse a JSONL event stream, tolerating a torn final line.

    A run killed mid-``write()`` can leave a truncated last record; every
    line that parses is kept, anything that does not is skipped (the
    stream is append-only, so damage can only be at the tail).
    """
    events: list[dict] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail (or mid-write garbage): best effort
            if isinstance(ev, dict):
                events.append(ev)
    return events


def _last_t(events: list[dict]) -> float:
    """The run-relative timestamp of the last stamped event."""
    t = 0.0
    for ev in events:
        ts = ev.get("t_s")
        if isinstance(ts, (int, float)):
            t = max(t, float(ts))
        # closed spans know their own end even without a stamp
        if ev.get("ev") == "span":
            t0, dur = ev.get("t0_s"), ev.get("dur_s")
            if isinstance(t0, (int, float)) and isinstance(dur, (int, float)):
                t = max(t, float(t0) + float(dur))
    return t


def salvage(events_path: str) -> dict:
    """Reconstruct a best-effort manifest document from an event stream.

    The result carries every field a finalized manifest does (it passes
    ``validate_manifest`` with zero problems) plus ``"salvaged": true``.
    Open spans — including the run root — are closed at the last event's
    timestamp, so their durations are lower bounds on the truth.
    """
    events = read_events(events_path)
    if not events:
        raise ValueError(f"{events_path}: no parseable events")
    start = next((e for e in events if e.get("ev") == "run_start"), None)
    if start is None:
        raise ValueError(f"{events_path}: no run_start event (not an obs "
                         "event stream?)")
    last_t = _last_t(events)
    run_id = start.get("run_id") or os.path.basename(events_path).replace(
        ".events.jsonl", "")
    name = start.get("name") or run_id
    spans: list[dict] = [{
        "name": name, "kind": "run", "t0_s": 0.0, "dur_s": None,
        "parent": None, "thread": 0, "attrs": dict(start.get("attrs") or {}),
    }]
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    costmodel: dict[str, dict] = {}
    numeric_mode = None
    error = None
    backend = None
    heartbeat = None
    ended = False

    def _seat(i: int) -> dict:
        # The span table is append-only and index-addressed; a gap can
        # only come from events lost at a torn tail, so pad with
        # explicitly-unknown rows rather than shifting indices.
        while len(spans) <= i:
            spans.append({"name": "?", "kind": "lost", "t0_s": last_t,
                          "dur_s": None, "parent": 0,
                          "thread": 0, "attrs": {}})
        return spans[i]

    for ev in events:
        kind = ev.get("ev")
        if kind == "span_open":
            i = ev.get("i")
            if isinstance(i, int) and i > 0:
                row = _seat(i)
                row.update({k: ev[k] for k in
                            ("name", "kind", "t0_s", "parent", "thread")
                            if k in ev})
        elif kind == "span":
            i = ev.get("i")
            if isinstance(i, int) and i >= 0:
                row = _seat(i)
                row.update({k: ev[k] for k in
                            ("name", "kind", "t0_s", "dur_s", "parent",
                             "thread", "attrs") if k in ev})
        elif kind == "ctr":
            k, v = ev.get("k"), ev.get("v")
            if isinstance(k, str) and isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        elif kind == "gauge":
            k, v = ev.get("k"), ev.get("v")
            if isinstance(k, str) and isinstance(v, (int, float)):
                gauges[k] = v
        elif kind == "cost":
            k, row = ev.get("k"), ev.get("row")
            if isinstance(k, str) and isinstance(row, dict):
                costmodel[k] = row  # last capture wins, like record_cost
        elif kind == "numeric_mode":
            if isinstance(ev.get("mode"), dict):
                numeric_mode = ev["mode"]
        elif kind == "heartbeat":
            heartbeat = {k: v for k, v in ev.items() if k != "ev"}
            if ev.get("backend"):
                backend = ev["backend"]
        elif kind == "run_end":
            ended = True
            if ev.get("error"):
                error = str(ev["error"])
            if isinstance(ev.get("wall_s"), (int, float)):
                spans[0]["dur_s"] = ev["wall_s"]
    for row in spans:
        if row["dur_s"] is None:
            row["dur_s"] = round(max(0.0, last_t - float(row["t0_s"])), 6)
    # Span 0's parent must be null and parents must precede children;
    # anything the stream got wrong gets clamped so the doc validates.
    spans[0]["parent"] = None
    for i, row in enumerate(spans[1:], start=1):
        p = row.get("parent")
        if not isinstance(p, int) or not (0 <= p < i):
            row["parent"] = 0
    doc_host = start.get("host")
    return {
        "schema": start.get("schema") or OBS_SCHEMA,
        "schema_version": start.get("schema_version") or OBS_SCHEMA_VERSION,
        "run_id": run_id,
        "name": name,
        # host identity survives salvage so `obs merge` can lane the
        # reconstruction like a finalized per-host manifest
        "host": doc_host if isinstance(doc_host, int) else 0,
        "host_count": start.get("host_count")
        if isinstance(start.get("host_count"), int) else 1,
        "t_start_unix": start.get("t_start_unix") or 0.0,
        "wall_s": spans[0]["dur_s"],
        "error": error,
        "platform": {"python": sys.version.split()[0], "backend": backend,
                     "devices": []},
        "knobs": dict(start.get("knobs") or {}),
        "numeric_mode": numeric_mode,
        "compile": None,
        "counters": counters,
        "gauges": gauges,
        "costmodel": costmodel,
        "spans": spans,
        "salvaged": not ended,
        "heartbeat": heartbeat,
    }


def salvage_file(events_path: str, out: str | None = None) -> str:
    """Salvage ``events_path`` and write the manifest atomically.

    Default output sits next to the stream as
    ``<run_id>.salvaged.manifest.json`` — deliberately NOT the
    ``.manifest.json`` name, so a salvage can never shadow (or be
    shadowed by) a finalize racing it.
    """
    doc = salvage(events_path)
    if out is None:
        base = events_path
        if base.endswith(".events.jsonl"):
            base = base[: -len(".events.jsonl")]
        out = base + ".salvaged.manifest.json"
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False, default=str)
        fh.write("\n")
    os.replace(tmp, out)
    return out


def resolve_events(target: str) -> str:
    """``target`` may be an events file or a run directory (newest wins)."""
    if os.path.isdir(target):
        streams = glob.glob(os.path.join(target, "*.events.jsonl"))
        if not streams:
            raise FileNotFoundError(f"{target}: no *.events.jsonl streams")
        return max(streams, key=os.path.getmtime)
    if not os.path.exists(target):
        raise FileNotFoundError(target)
    return target


def _fmt_hb(ev: dict) -> str:
    done, total = ev.get("done"), ev.get("total")
    frac = ev.get("frac")
    rate, eta = ev.get("rate_per_s"), ev.get("eta_s")
    bits = [f"[hb +{ev.get('t_s', 0):.0f}s]"]
    if done is not None:
        bits.append(f"{done}/{total if total is not None else '?'}")
    if frac is not None:
        bits.append(f"{100.0 * frac:.1f}%")
    if rate is not None:
        bits.append(f"{rate:.3g}/s")
    if eta is not None:
        bits.append(f"eta {eta:.0f}s")
    if ev.get("label"):
        bits.append(str(ev["label"]))
    if ev.get("span"):
        bits.append(f"span={ev['span']}")
    if ev.get("backend"):
        bits.append(f"backend={ev['backend']}")
    return "  ".join(bits)


def _render(ev: dict, out) -> bool:
    """Print one event's tail line; returns True when the run ended."""
    kind = ev.get("ev")
    if kind == "run_start":
        print(f"run {ev.get('run_id', '?')} started", file=out)
    elif kind == "heartbeat":
        print(_fmt_hb(ev), file=out)
    elif kind == "span" and ev.get("kind") in ("stage", "run"):
        dur = ev.get("dur_s")
        dur_txt = f"{dur:.3f}s" if isinstance(dur, (int, float)) else "?"
        print(f"[span] {ev.get('name', '?')} {dur_txt}", file=out)
    elif kind == "run_end":
        wall = ev.get("wall_s")
        wall_txt = f"{wall:.3f}s" if isinstance(wall, (int, float)) else "?"
        print(f"run ended  wall={wall_txt}  manifest={ev.get('manifest', '?')}"
              + (f"  ERROR: {ev['error']}" if ev.get("error") else ""),
              file=out)
        return True
    return False


def tail(target: str, follow: bool = True, interval: float = 2.0,
         max_seconds: float | None = None, out=None) -> int:
    """Follow a live event stream, rendering progress/ETA to ``out``.

    Renders existing content immediately; with ``follow`` keeps reading
    appended lines every ``interval`` seconds until ``run_end`` (exit 0)
    or ``max_seconds`` elapses without one (exit 1). ``follow=False``
    (the CLI's ``--once``) renders what is there and exits 0 if the run
    already ended, 1 if it is still (or forever) in flight.
    """
    out = out if out is not None else sys.stdout
    path = resolve_events(target)
    print(f"tailing {path}", file=out)
    t0 = time.monotonic()
    ended = False
    buf = ""
    with open(path, encoding="utf-8") as fh:
        while True:
            chunk = fh.read()
            if chunk:
                buf += chunk
                *lines, buf = buf.split("\n")
                for line in lines:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if _render(ev, out):
                        ended = True
            if ended:
                return 0
            if not follow:
                return 1
            if max_seconds is not None \
                    and time.monotonic() - t0 >= max_seconds:
                print("tail: gave up waiting for run_end", file=out)
                return 1
            time.sleep(interval)
