"""Reporter: summarize / diff manifests, export Chrome trace + Prometheus.

``diff`` is the operational payoff: "why was run B slow" answered from
artifacts. It rolls both span trees up by path, attributes the wall-time
delta stage-by-stage, and surfaces counter deltas plus knob /
numeric-mode drift — the exact signals that would have flagged the
r3–r5 CPU-fallback benches without hand-diffing JSON.
"""

from __future__ import annotations

import json
import math

from crimp_tpu.obs.manifest import span_paths


def _sec(val) -> str:
    """Seconds for humans; '?' for a partial doc's missing/null field."""
    return f"{val:.3f}s" if isinstance(val, (int, float)) else "?"


def span_rollup(doc: dict) -> dict[str, dict]:
    """Aggregate span durations by path: path -> {sum_s, count, kind}."""
    out: dict[str, dict] = {}
    for path, row in zip(span_paths(doc), doc.get("spans") or []):
        dur = row.get("dur_s")
        if dur is None:
            continue
        agg = out.setdefault(path, {"sum_s": 0.0, "count": 0, "kind": row["kind"]})
        agg["sum_s"] += float(dur)
        agg["count"] += 1
    for agg in out.values():
        agg["sum_s"] = round(agg["sum_s"], 6)
    return out


def summarize(doc: dict, top: int = 12) -> str:
    """Human-readable one-run summary (the ``summary`` subcommand)."""
    plat = doc.get("platform") or {}
    lines = []
    if doc.get("salvaged"):
        lines.append("SALVAGED reconstructed from the event stream of a "
                     "killed run; durations are lower bounds")
    lines += [
        f"run      {doc.get('run_id') or '?'}",
        f"name     {doc.get('name') or '?'}",
        f"wall     {_sec(doc.get('wall_s'))}"
        + (f"   ERROR: {doc['error']}" if doc.get("error") else ""),
        f"backend  {plat.get('backend') or 'none initialized'}"
        f"  devices={len(plat.get('devices') or [])}",
    ]
    if doc.get("numeric_mode"):
        lines.append("numeric  " + json.dumps(doc["numeric_mode"], sort_keys=True))
    snap = doc.get("knobs") or {}
    if snap:
        lines.append(f"knobs    {len(snap)} set: "
                     + " ".join(f"{k}={v}" for k, v in sorted(snap.items())))
    rollup = span_rollup(doc)
    rollup.pop(doc.get("name"), None)  # the root just restates wall_s
    if rollup:
        lines.append(f"spans    ({min(top, len(rollup))} of {len(rollup)} paths by total time)")
        ranked = sorted(rollup.items(), key=lambda kv: -kv[1]["sum_s"])
        for path, agg in ranked[:top]:
            lines.append(f"  {agg['sum_s']:9.3f}s  x{agg['count']:<4d} {path}")
    counters = doc.get("counters") or {}
    if counters:
        lines.append("counters")
        for name, val in sorted(counters.items()):
            lines.append(f"  {_num(val):>12}  {name}")
    gauges = doc.get("gauges") or {}
    if gauges:
        lines.append("gauges")
        for name, val in sorted(gauges.items()):
            lines.append(f"  {_num(val):>12}  {name}")
    cm = doc.get("costmodel") or {}
    if cm:
        lines.append(f"cost     {len(cm)} kernel cost row(s) "
                     "(`obs roofline` joins them against span times)")
    comp = doc.get("compile") or {}
    if comp:
        lines.append(
            "compile  hits=%s misses=%s backend_compile=%.2fs" % (
                comp.get("cache_hits", 0), comp.get("cache_misses", 0),
                comp.get("backend_compile_s", 0.0)))
    return "\n".join(lines)


def _num(val) -> str:
    if isinstance(val, float) and not val.is_integer():
        return f"{val:.4g}"
    return str(int(val))


def diff(a: dict, b: dict, min_delta_s: float = 0.005) -> dict:
    """Structured A→B comparison: stage slowdowns, counter/knob drift.

    ``stages`` is sorted by |delta| descending, so the first entry *is*
    the slowdown attribution. Stages whose delta is under ``min_delta_s``
    are dropped (timer noise, not signal).
    """
    ra, rb = span_rollup(a), span_rollup(b)
    # the root span just restates wall_s (reported separately) — left in,
    # it would always outrank the actual per-stage attribution
    ra.pop(a.get("name"), None)
    rb.pop(b.get("name"), None)
    stages = []
    for path in sorted(set(ra) | set(rb)):
        sa = ra.get(path, {}).get("sum_s", 0.0)
        sb = rb.get(path, {}).get("sum_s", 0.0)
        delta = sb - sa
        if abs(delta) < min_delta_s:
            continue
        stages.append({
            "path": path, "a_s": round(sa, 6), "b_s": round(sb, 6),
            "delta_s": round(delta, 6),
            "ratio": round(sb / sa, 3) if sa > 0 else None,
            "count_a": ra.get(path, {}).get("count", 0),
            "count_b": rb.get(path, {}).get("count", 0),
        })
    stages.sort(key=lambda s: -abs(s["delta_s"]))

    ca, cb = a.get("counters") or {}, b.get("counters") or {}
    counters = {
        name: {"a": ca.get(name, 0), "b": cb.get(name, 0),
               "delta": _round6(cb.get(name, 0) - ca.get(name, 0))}
        for name in sorted(set(ca) | set(cb))
        if ca.get(name, 0) != cb.get(name, 0)
    }

    ka, kb = a.get("knobs") or {}, b.get("knobs") or {}
    knob_drift = {
        name: {"a": ka.get(name), "b": kb.get(name)}
        for name in sorted(set(ka) | set(kb))
        if ka.get(name) != kb.get(name)
    }

    na, nb = a.get("numeric_mode"), b.get("numeric_mode")
    numeric_drift = None
    if na != nb:
        keys = set(na or {}) | set(nb or {})
        numeric_drift = {
            key: {"a": (na or {}).get(key), "b": (nb or {}).get(key)}
            for key in sorted(keys)
            if (na or {}).get(key) != (nb or {}).get(key)
        }

    pa = (a.get("platform") or {}).get("backend")
    pb = (b.get("platform") or {}).get("backend")
    wa, wb = a.get("wall_s"), b.get("wall_s")
    both_walls = all(isinstance(w, (int, float)) for w in (wa, wb))
    return {
        "a": a.get("run_id") or "?", "b": b.get("run_id") or "?",
        "wall_a_s": wa, "wall_b_s": wb,
        "wall_delta_s": _round6(wb - wa) if both_walls else None,
        "salvaged": ({"a": bool(a.get("salvaged")), "b": bool(b.get("salvaged"))}
                     if (a.get("salvaged") or b.get("salvaged")) else None),
        "backend_drift": None if pa == pb else {"a": pa, "b": pb},
        "stages": stages,
        "counters": counters,
        "knob_drift": knob_drift,
        "numeric_mode_drift": numeric_drift,
    }


def _round6(val):
    return round(val, 6) if isinstance(val, float) else val


def render_diff(d: dict, top: int = 12) -> str:
    """Human-readable rendering of a :func:`diff` result."""
    delta = d["wall_delta_s"]
    delta_txt = f"{delta:+.3f}s" if isinstance(delta, (int, float)) else "?"
    lines = [
        f"A  {d['a']}   wall {_sec(d['wall_a_s'])}",
        f"B  {d['b']}   wall {_sec(d['wall_b_s'])}   delta {delta_txt}",
    ]
    if d.get("salvaged"):
        which = "+".join(k.upper() for k in ("a", "b") if d["salvaged"][k])
        lines.append(f"SALVAGED {which}  (killed-run reconstruction; "
                     "durations are lower bounds)")
    if d["backend_drift"]:
        lines.append(f"BACKEND DRIFT  {d['backend_drift']['a']} -> "
                     f"{d['backend_drift']['b']}")
    if d["stages"]:
        lines.append("stage attribution (delta B-A, worst first)")
        for s in d["stages"][:top]:
            ratio = f" x{s['ratio']:.2f}" if s["ratio"] else ""
            lines.append(f"  {s['delta_s']:+9.3f}s{ratio:>8}  {s['path']}"
                         f"  ({s['a_s']:.3f}s -> {s['b_s']:.3f}s)")
    else:
        lines.append("stage attribution: no stage moved beyond noise")
    if d["counters"]:
        lines.append("counter deltas")
        for name, row in d["counters"].items():
            lines.append(f"  {_num(row['a']):>10} -> {_num(row['b']):<10} {name}")
    if d["knob_drift"]:
        lines.append("KNOB DRIFT")
        for name, row in d["knob_drift"].items():
            lines.append(f"  {name}: {row['a'] or '<unset>'} -> {row['b'] or '<unset>'}")
    if d["numeric_mode_drift"]:
        lines.append("NUMERIC-MODE DRIFT")
        for key, row in d["numeric_mode_drift"].items():
            lines.append(f"  {key}: {row['a']!r} -> {row['b']!r}")
    return "\n".join(lines)


def chrome_trace(doc: dict) -> dict:
    """Chrome trace-event JSON (load in Perfetto / chrome://tracing).

    Complete events ("ph": "X") with microsecond timestamps relative to
    run start; obs thread ordinals become trace tids. A merged multi-host
    manifest (``obs merge``) renders one LANE (trace pid) per host —
    pid = host + 1, each with its own process_name metadata row — so the
    per-host subtrees sit side by side on the shared run clock; the
    synthetic run root stays on pid 1 alongside host 0.
    """
    merged = bool(doc.get("merged"))
    events = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": f"{doc['name']} ({doc['run_id']})"}},
    ]
    if merged:
        for hr in doc.get("hosts") or []:
            h = hr.get("host", 0)
            if h == 0:
                continue  # host 0 shares pid 1 with the run root's row
            events.append({
                "ph": "M", "name": "process_name", "pid": h + 1, "tid": 0,
                "args": {"name": f"host{h} · {doc['name']} "
                                 f"({doc['run_id']})"}})
    for row in doc["spans"]:
        if row.get("dur_s") is None:
            continue
        pid = (int(row.get("host", 0)) + 1) if merged else 1
        events.append({
            "ph": "X", "pid": pid, "tid": row["thread"],
            "name": row["name"], "cat": row["kind"],
            "ts": round(row["t0_s"] * 1e6, 1),
            "dur": round(row["dur_s"] * 1e6, 1),
            "args": row.get("attrs") or {},
        })
    for name, val in sorted((doc.get("counters") or {}).items()):
        events.append({"ph": "C", "pid": 1, "tid": 0, "name": name,
                       "ts": round(doc["wall_s"] * 1e6, 1),
                       "args": {"value": val}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _prom_label(val: str) -> str:
    return str(val).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _prom_num(val) -> str:
    """A sample value in exposition-format 0.0.4 spelling.

    Python's ``nan``/``inf`` reprs are unparseable to Prometheus — the
    format wants ``NaN``/``+Inf``/``-Inf``. Finite values keep their
    native rendering (ints stay ``3``, not ``3.0``). A non-numeric value
    (a partial/hand-edited manifest) becomes NaN rather than a line the
    scraper rejects wholesale.
    """
    try:
        num = float(val)
    except (TypeError, ValueError):
        return "NaN"
    if math.isnan(num):
        return "NaN"
    if math.isinf(num):
        return "+Inf" if num > 0 else "-Inf"
    return str(val)


def prometheus(doc: dict) -> str:
    """Prometheus text exposition (format 0.0.4) for one manifest.

    Every series carries a ``host`` label: the writing process index for
    a per-host manifest (0 on single-host runs), or the source host for
    a merged multi-host document — whose wall/counter/gauge series are
    emitted once per host from ``hosts[]`` (the aggregate is one PromQL
    ``sum()``/``max()`` away, and emitting both would double-count) and
    whose span series follow each span row's ``host`` field.
    """
    run = _prom_label(doc["run_id"])
    merged = bool(doc.get("merged")) and isinstance(doc.get("hosts"), list)
    host0 = doc["host"] if isinstance(doc.get("host"), int) else 0
    sources = ([(hr.get("host", i), hr) for i, hr in enumerate(doc["hosts"])]
               if merged else [(host0, doc)])
    lines = [
        "# HELP crimp_tpu_run_wall_seconds total wall time of the run",
        "# TYPE crimp_tpu_run_wall_seconds gauge",
    ]
    for h, src in sources:
        lines.append(f'crimp_tpu_run_wall_seconds{{run="{run}",host="{h}"}} '
                     f'{_prom_num(src["wall_s"])}')
    lines += [
        "# HELP crimp_tpu_counter_total run counters (events folded, ToAs fit, cache hits, ...)",
        "# TYPE crimp_tpu_counter_total counter",
    ]
    for h, src in sources:
        for name, val in sorted((src.get("counters") or {}).items()):
            lines.append(
                f'crimp_tpu_counter_total{{run="{run}",host="{h}",'
                f'name="{_prom_label(name)}"}} {_prom_num(val)}')
    lines += [
        "# HELP crimp_tpu_gauge run gauges (padding waste, device counts, ...)",
        "# TYPE crimp_tpu_gauge gauge",
    ]
    for h, src in sources:
        for name, val in sorted((src.get("gauges") or {}).items()):
            lines.append(
                f'crimp_tpu_gauge{{run="{run}",host="{h}",'
                f'name="{_prom_label(name)}"}} {_prom_num(val)}')
    lines += [
        "# HELP crimp_tpu_span_seconds total seconds per span path",
        "# TYPE crimp_tpu_span_seconds gauge",
        "# HELP crimp_tpu_span_count spans recorded per span path",
        "# TYPE crimp_tpu_span_count gauge",
    ]
    rollup: dict[tuple[int, str], dict] = {}
    for path, row in zip(span_paths(doc), doc.get("spans") or []):
        dur = row.get("dur_s")
        if dur is None:
            continue
        h = int(row.get("host", host0)) if merged else host0
        agg = rollup.setdefault((h, path), {"sum_s": 0.0, "count": 0})
        agg["sum_s"] += float(dur)
        agg["count"] += 1
    for (h, path), agg in sorted(rollup.items()):
        label = f'run="{run}",host="{h}",path="{_prom_label(path)}"'
        lines.append(f"crimp_tpu_span_seconds{{{label}}} "
                     f"{_prom_num(round(agg['sum_s'], 6))}")
        lines.append(f"crimp_tpu_span_count{{{label}}} {_prom_num(agg['count'])}")
    return "\n".join(lines) + "\n"
