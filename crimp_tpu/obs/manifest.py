"""Run-manifest schema: validation + loading.

The manifest is deliberately plain JSON with a flat span table (parent
indices, not nesting) so it stays diffable with standard tools and cheap
to validate without a jsonschema dependency. ``validate_manifest``
returns a list of problems (empty = valid) rather than raising, so the
reporter can degrade gracefully on partially-written artifacts while
tests can assert exact emptiness.
"""

from __future__ import annotations

import json

from crimp_tpu.obs.core import OBS_SCHEMA, OBS_SCHEMA_VERSION

# field name -> allowed types (None listed explicitly where nullable)
_TOP_FIELDS: dict[str, tuple] = {
    "schema": (str,),
    "schema_version": (int,),
    "run_id": (str,),
    "name": (str,),
    "t_start_unix": (int, float),
    "wall_s": (int, float),
    "error": (str, type(None)),
    "platform": (dict,),
    "knobs": (dict,),
    "numeric_mode": (dict, type(None)),
    "compile": (dict, type(None)),
    "counters": (dict,),
    "gauges": (dict,),
    "spans": (list,),
}

_SPAN_FIELDS: dict[str, tuple] = {
    "name": (str,),
    "kind": (str,),
    "t0_s": (int, float),
    "dur_s": (int, float, type(None)),
    "parent": (int, type(None)),
    "thread": (int,),
    "attrs": (dict,),
}


def validate_manifest(doc) -> list[str]:
    """Schema-check a manifest document; returns problems (empty = valid)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"manifest is {type(doc).__name__}, expected object"]
    for field, types in _TOP_FIELDS.items():
        if field not in doc:
            problems.append(f"missing top-level field {field!r}")
        elif not isinstance(doc[field], types):
            problems.append(
                f"{field!r} is {type(doc[field]).__name__}, expected "
                + "/".join(t.__name__ for t in types))
    # optional extensions (salvaged reconstructions carry these)
    if "salvaged" in doc and not isinstance(doc["salvaged"], bool):
        problems.append(
            f"'salvaged' is {type(doc['salvaged']).__name__}, expected bool")
    if "heartbeat" in doc and not isinstance(doc["heartbeat"],
                                             (dict, type(None))):
        problems.append(
            f"'heartbeat' is {type(doc['heartbeat']).__name__}, "
            "expected object/null")
    # optional extensions (PR-9 resilience layer; pre-PR manifests lack them)
    if "degraded" in doc and not isinstance(doc["degraded"], bool):
        problems.append(
            f"'degraded' is {type(doc['degraded']).__name__}, expected bool")
    if "degradations" in doc and not isinstance(doc["degradations"], list):
        problems.append(
            f"'degradations' is {type(doc['degradations']).__name__}, "
            "expected list")
    # optional extensions (multi-host observability; single-host and older
    # manifests lack them)
    for field in ("host", "host_count"):
        if field in doc and not isinstance(doc[field], int):
            problems.append(
                f"{field!r} is {type(doc[field]).__name__}, expected int")
    if "merged" in doc and not isinstance(doc["merged"], bool):
        problems.append(
            f"'merged' is {type(doc['merged']).__name__}, expected bool")
    if "hosts" in doc:
        hosts = doc["hosts"]
        if not isinstance(hosts, list):
            problems.append(
                f"'hosts' is {type(hosts).__name__}, expected list")
        else:
            for i, row in enumerate(hosts):
                if not isinstance(row, dict):
                    problems.append(
                        f"hosts[{i}] is {type(row).__name__}, "
                        "expected object")
    # optional extension (PR-10 cost-model layer; older manifests lack it)
    if "costmodel" in doc:
        cm = doc["costmodel"]
        if not isinstance(cm, dict):
            problems.append(
                f"'costmodel' is {type(cm).__name__}, expected object")
        else:
            for key, row in cm.items():
                if not isinstance(row, dict):
                    problems.append(
                        f"costmodel[{key!r}] is {type(row).__name__}, "
                        "expected object")
    if doc.get("schema") not in (None, OBS_SCHEMA):
        problems.append(f"schema is {doc.get('schema')!r}, expected {OBS_SCHEMA!r}")
    ver = doc.get("schema_version")
    if isinstance(ver, int) and ver > OBS_SCHEMA_VERSION:
        problems.append(
            f"schema_version {ver} is newer than this reader "
            f"({OBS_SCHEMA_VERSION}); upgrade crimp_tpu to diff it")
    spans = doc.get("spans")
    if isinstance(spans, list):
        if not spans:
            problems.append("spans is empty (span 0 must be the run root)")
        for i, row in enumerate(spans):
            if not isinstance(row, dict):
                problems.append(f"spans[{i}] is {type(row).__name__}, expected object")
                continue
            for field, types in _SPAN_FIELDS.items():
                if field not in row:
                    problems.append(f"spans[{i}] missing field {field!r}")
                elif not isinstance(row[field], types):
                    problems.append(
                        f"spans[{i}].{field} is {type(row[field]).__name__}, "
                        "expected " + "/".join(t.__name__ for t in types))
            parent = row.get("parent")
            if i == 0:
                if parent is not None:
                    problems.append("spans[0].parent must be null (run root)")
            elif isinstance(parent, int) and not (0 <= parent < i):
                problems.append(
                    f"spans[{i}].parent={parent} out of range (parents "
                    "precede children)")
    for field in ("counters", "gauges"):
        table = doc.get(field)
        if isinstance(table, dict):
            for key, val in table.items():
                if not isinstance(val, (int, float)):
                    problems.append(
                        f"{field}[{key!r}] is {type(val).__name__}, expected number")
    return problems


def load_manifest(path: str) -> dict:
    """Load + validate a manifest file; raises ValueError on a bad one."""
    with open(path, encoding="utf-8") as fh:
        try:
            doc = json.load(fh)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{path}: not valid JSON ({exc})") from None
    problems = validate_manifest(doc)
    if problems:
        head = "; ".join(problems[:4]) + ("; ..." if len(problems) > 4 else "")
        raise ValueError(f"{path}: invalid manifest ({head})")
    return doc


def span_paths(doc: dict) -> list[str]:
    """``/``-joined name path for every span (root = its bare name).

    The path is the diff key: two runs of the same pipeline produce the
    same paths for the same stages regardless of absolute timing.
    """
    spans = doc.get("spans") or []
    paths: list[str] = []
    for i, row in enumerate(spans):
        parent = row.get("parent")
        if parent is None or not (0 <= parent < i):
            paths.append(row["name"])
        else:
            paths.append(paths[parent] + "/" + row["name"])
    return paths
