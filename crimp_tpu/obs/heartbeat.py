"""Live progress heartbeats: periodic events + an atomic ETA sidecar.

The PR-6 flight recorder only pays off after a clean ``finalize()``; a
long grid scan in flight is a black box until then. :func:`beat` closes
that gap: instrumented loops report ``(done, total)`` progress and, at
most once per ``CRIMP_TPU_OBS_HEARTBEAT_S`` seconds (default 30), the
active run appends a ``heartbeat`` event to its JSONL stream *and*
atomically rewrites a small ``<run_id>.heartbeat.json`` sidecar with the
progress fraction, observed rate, ETA, the calling thread's deepest open
span path and the backend — everything ``obs tail`` or an operator's
``watch cat`` needs to see where a wedged session actually is.

Contracts (pinned by tests/test_obs.py):

- **Disabled is free.** With no active run, :func:`beat` returns after
  the same single ``None`` check as the other obs hooks — no clock read,
  no allocation, no filesystem write. ``CRIMP_TPU_OBS_HEARTBEAT_S=0``
  (or ``off``) disables heartbeats even when obs is on.
- **Monotonic-clock based.** Rates and ETAs come from
  ``time.perf_counter()`` deltas against the run's own ``t0``; wall-clock
  jumps (NTP, suspend) cannot produce negative ETAs.
- **Rate from observed work only.** The first beat anchors the window, so
  a resumable scan that instantly "completes" its restored chunks does
  not inflate the rate estimate for the chunks it still has to compute.
"""

from __future__ import annotations

import json
import os
import threading
import time

from crimp_tpu import knobs
from crimp_tpu.obs import core

DEFAULT_PERIOD_S = 30.0


def period_s() -> float | None:
    """The heartbeat period, or None when disabled.

    Unset/blank means the 30 s default (heartbeats ride on the obs
    enable, they do not need their own opt-in); ``0``/``off`` disables;
    a positive float overrides; anything else raises (same typo
    discipline as every other knob — a malformed period must not
    silently pick a default).
    """
    env = knobs.raw("CRIMP_TPU_OBS_HEARTBEAT_S")
    if not env:
        return DEFAULT_PERIOD_S
    if knobs.parse_onoff(env) is False:
        return None
    try:
        val = float(env)
    except ValueError:
        raise ValueError(
            f"CRIMP_TPU_OBS_HEARTBEAT_S={env!r} is not a number") from None
    if not (0.0 < val < float("inf")):
        raise ValueError(
            f"CRIMP_TPU_OBS_HEARTBEAT_S={env!r} out of range (expected > 0, "
            "or 0/off to disable)")
    return val


def _open_span_path(rec) -> str:
    """The calling thread's deepest open span, as a '/'-joined path."""
    stack = core._stack()
    idx = stack[-1] if stack else 0
    parts: list[str] = []
    with core._LOCK:
        while idx is not None and 0 <= idx < len(rec.spans):
            parts.append(rec.spans[idx]["name"])
            idx = rec.spans[idx]["parent"]
    return "/".join(reversed(parts)) or rec.name


def beat(done: float, total: float | None, label: str | None = None,
         force: bool = False) -> dict | None:
    """Report progress; emit a heartbeat if the period has elapsed.

    Returns the heartbeat document when one was emitted, else None.
    ``done``/``total`` are in whatever unit the caller is looping over
    (chunks, buckets, bench stages); ``force`` bypasses the rate limit
    for boundaries worth recording regardless (stage starts, final
    completion).
    """
    rec = core.active()
    if rec is None:
        return None
    now = time.perf_counter()
    with core._LOCK:
        hb = rec.hb
        if hb is None:
            hb = rec.hb = {
                "period": period_s(),
                # host_tag keeps co-located processes (multi-host jobs, or
                # CRIMP_TPU_OBS_HOST-tagged launchers) from clobbering each
                # other's sidecar on a shared obs dir
                "path": os.path.join(
                    rec.dir,
                    rec.run_id + rec.host_tag + ".heartbeat.json"),
                "last": None,       # perf_counter of the last emission
                "label": None,      # rate window anchor: label at t_first
                "t_first": None,
                "done_first": None,
            }
        if hb["period"] is None:
            return None
        if hb["label"] != label or hb["t_first"] is None \
                or (hb["done_first"] is not None and done < hb["done_first"]):
            # New phase (or a caller restarting its count): re-anchor the
            # rate window so ETAs reflect this phase's observed rate only.
            hb["label"] = label
            hb["t_first"] = now
            hb["done_first"] = done
        if not force and hb["last"] is not None \
                and now - hb["last"] < hb["period"]:
            return None
        hb["last"] = now
        span_path = _open_span_path(rec)
    rate = None
    eta = None
    dt = now - hb["t_first"]
    dwork = done - hb["done_first"]
    if dt > 0 and dwork > 0:
        rate = dwork / dt
        if total is not None and total > done:
            eta = (total - done) / rate
    doc = {
        "run_id": rec.run_id,
        "name": rec.name,
        "host": rec.host,
        "t_s": round(now - rec.t0, 3),
        "t_unix": round(time.time(), 3),
        "label": label,
        "done": done,
        "total": total,
        "frac": round(done / total, 6) if total else None,
        "rate_per_s": round(rate, 6) if rate is not None else None,
        "eta_s": round(eta, 3) if eta is not None else None,
        "span": span_path,
        "backend": core._platform_identity()["backend"],
    }
    rec._emit({"ev": "heartbeat",
               **{k: doc[k] for k in ("t_s", "label", "done", "total",
                                      "frac", "rate_per_s", "eta_s",
                                      "span", "backend")}})
    if hb["path"] is not None:
        # per-thread tmp name: two threads beating concurrently must not
        # replace each other's tmp file out from under the open() — the
        # final os.replace is atomic either way, last writer wins
        tmp = hb["path"] + f".{threading.get_ident()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, default=str)
                fh.write("\n")
            os.replace(tmp, hb["path"])
        except OSError:
            # ENOSPC/read-only obs dir mid-scan: a heartbeat must never
            # kill the run. Stop writing the sidecar, keep computing.
            hb["path"] = None
            rec._note_write_error("heartbeat sidecar")
    return doc


def check_sidecar(target: str, max_age_s: float,
                  now_unix: float | None = None) -> tuple[bool, str, dict | None]:
    """Liveness-probe a heartbeat sidecar: ``(fresh, reason, doc)``.

    ``target`` is a ``*.heartbeat.json`` file or a run directory (the
    newest sidecar in it wins — the serving/liveness probe case where the
    prober knows the obs dir, not the run id).  Freshness compares the
    sidecar's wall-clock ``t_unix`` stamp against ``now_unix`` (default:
    ``time.time()``): fresh iff ``now - t_unix <= max_age_s``.

    Missing, torn (partially-written or unparseable — the atomic-rename
    contract makes this "should never happen", which is exactly why a
    probe must treat it as dead, not crash) and stale sidecars are all
    NOT-fresh outcomes with a reason, never exceptions: a liveness probe
    that errors out is indistinguishable from a dead service.
    """
    max_age_s = float(max_age_s)
    if not (max_age_s > 0):
        raise ValueError(
            f"max_age_s={max_age_s!r} out of range (expected > 0)")
    path = target
    if os.path.isdir(target):
        cands = sorted(
            (os.path.join(target, f) for f in os.listdir(target)
             if f.endswith(".heartbeat.json")),
            key=lambda p: os.path.getmtime(p))
        if not cands:
            return False, f"no *.heartbeat.json in {target}", None
        path = cands[-1]
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return False, f"missing: {path}", None
    except (OSError, ValueError) as exc:
        return False, f"torn/unreadable: {path} ({exc})", None
    t_unix = doc.get("t_unix") if isinstance(doc, dict) else None
    if not isinstance(t_unix, (int, float)):
        return False, f"torn: {path} has no t_unix stamp", doc \
            if isinstance(doc, dict) else None
    age = (time.time() if now_unix is None else float(now_unix)) - t_unix
    if age > max_age_s:
        return False, f"stale: last beat {age:.1f}s ago " \
                      f"(max {max_age_s:g}s)", doc
    return True, f"fresh: last beat {age:.1f}s ago", doc


def scan_progress(base: float = 0, total: float | None = None,
                  label: str | None = None, echo=None):
    """A ``progress(i, n)``-shaped callback that feeds :func:`beat`.

    ``base`` seats the count for resumable scans that restored chunks
    (the heartbeat's ``done`` covers the whole scan, its rate window only
    the work this process performed). Completion beats force through the
    rate limit so a finished scan always leaves a 100% heartbeat.
    ``echo`` chains the caller's own callback (a printed status line, the
    previous ad-hoc lambda) after the beat.
    """
    state = {"calls": 0}

    def progress(i, n):
        state["calls"] += 1
        done = base + state["calls"]
        full = total if total is not None else n
        beat(done, full, label=label, force=done >= full)
        if echo is not None:
            echo(i, n)

    return progress
