"""Multi-host trace aggregation: join per-host event streams into one manifest.

A multi-host run writes one ``<run_id>.host<k>.events.jsonl`` stream (and
heartbeat sidecar) per process — coordinated run_ids come from
``obs.core`` dropping the pid from multi-host run ids. No single host
ever holds the whole picture, so ``obs merge`` replays every per-host
stream through the salvage machinery (torn tails on killed hosts are
tolerated by construction) and joins them BY RUN_ID into one document:

- one synthetic run root whose children are per-host subtree roots named
  ``host<k>`` (kind ``"host"``); every merged span carries a ``"host"``
  field, which the Chrome-trace exporter turns into per-host lanes
  (pid = host + 1) and the Prometheus exporter into a ``host`` label;
- counters are summed across hosts (they are monotonic totals), gauges
  take the per-key max (high-water semantics; per-host values survive in
  ``hosts[]``), cost-model rows are unioned (SPMD hosts capture identical
  rows, so collisions are re-captures, not conflicts);
- ``wall_s`` is the max across hosts; ``"merged": true`` and a
  ``hosts[]`` table (per-host run_id/wall/error/salvaged/counters/gauges)
  mark the document, and it passes ``validate_manifest`` with zero
  problems so ``summary``/``diff``/``roofline``/``ledger`` consume it
  like any single-host manifest.

Import-safe: no jax, pure event-stream and dict work.
"""

from __future__ import annotations

import glob
import json
import os
import re

from crimp_tpu.obs import salvage as slv

_HOST_STEM_RE = re.compile(r"\.host(\d+)$")


def resolve_streams(targets: list[str],
                    run_id: str | None = None) -> list[str]:
    """Expand CLI targets into event-stream paths.

    A single directory target selects one run's host streams: all
    ``*.events.jsonl`` are grouped by run_id (the stem with any
    ``.host<k>`` suffix stripped). With ``run_id`` the matching group is
    chosen (exact stem, else unique substring — enough of the id to be
    unambiguous works); otherwise the most recently touched group wins.
    Explicit file lists pass through untouched.
    """
    if len(targets) == 1 and os.path.isdir(targets[0]):
        streams = glob.glob(os.path.join(targets[0], "*.events.jsonl"))
        if not streams:
            raise FileNotFoundError(f"{targets[0]}: no *.events.jsonl streams")
        groups: dict[str, list[str]] = {}
        for s in streams:
            stem = os.path.basename(s)[: -len(".events.jsonl")]
            stem = _HOST_STEM_RE.sub("", stem)
            groups.setdefault(stem, []).append(s)
        if run_id is not None:
            if run_id in groups:
                return sorted(groups[run_id])
            hits = [k for k in groups if run_id in k]
            if len(hits) != 1:
                raise FileNotFoundError(
                    f"{targets[0]}: run_id {run_id!r} matches "
                    f"{sorted(hits) if hits else 'no'} stream group(s) of "
                    f"{sorted(groups)}")
            return sorted(groups[hits[0]])
        best = max(groups.values(),
                   key=lambda g: max(os.path.getmtime(s) for s in g))
        return sorted(best)
    if run_id is not None:
        raise ValueError(
            "obs merge: --run-id selects a group within a directory "
            "target; drop it when listing stream files explicitly")
    return list(targets)


def _host_of(path: str, doc: dict, used: set[int], ordinal: int) -> int:
    """Host index for one stream: the run_start's ``host`` field, else the
    ``.host<k>`` filename suffix, else the first free ordinal."""
    h = doc.get("host")
    if isinstance(h, int) and h not in used:
        return h
    m = _HOST_STEM_RE.search(
        os.path.basename(path).replace(".events.jsonl", ""))
    if m and int(m.group(1)) not in used:
        return int(m.group(1))
    while ordinal in used:
        ordinal += 1
    return ordinal


def merge_streams(paths: list[str], force: bool = False) -> dict:
    """Join per-host event streams into one merged manifest document.

    Raises ``ValueError`` when the streams carry different run_ids —
    they are different runs, not hosts of one run — unless ``force``
    (clock skew at the stamp second can legitimately split an id).
    """
    if not paths:
        raise ValueError("obs merge: no event streams given")
    replayed: list[tuple[str, dict]] = []
    for p in paths:
        replayed.append((p, slv.salvage(p)))
    run_ids = sorted({doc["run_id"] for _, doc in replayed})
    if len(run_ids) > 1 and not force:
        raise ValueError(
            "obs merge: streams carry different run_ids "
            f"{run_ids} (different runs? clock skew? use --force to join "
            "anyway)")
    used: set[int] = set()
    docs: list[tuple[int, str, dict]] = []
    for i, (p, doc) in enumerate(replayed):
        h = _host_of(p, doc, used, i)
        used.add(h)
        docs.append((h, p, doc))
    docs.sort(key=lambda t: t[0])
    base = docs[0][2]

    wall = max((doc["wall_s"] or 0.0) for _, _, doc in docs)
    spans: list[dict] = [{
        "name": base["name"], "kind": "run", "t0_s": 0.0,
        "dur_s": round(float(wall), 6), "parent": None, "thread": 0,
        "attrs": {"hosts": len(docs)},
    }]
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    costmodel: dict[str, dict] = {}
    hosts_table: list[dict] = []
    error = None
    any_salvaged = False
    for h, path, doc in docs:
        offset = len(spans)
        for j, row in enumerate(doc.get("spans") or []):
            r = dict(row)
            r["host"] = h
            if j == 0:
                # the host's run root becomes its lane root under the
                # merged run root
                r.update({"name": f"host{h}", "kind": "host", "parent": 0})
            else:
                p_idx = r.get("parent")
                r["parent"] = (p_idx + offset
                               if isinstance(p_idx, int) else offset)
            spans.append(r)
        for k, v in (doc.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for k, v in (doc.get("gauges") or {}).items():
            if isinstance(v, (int, float)):
                gauges[k] = max(gauges.get(k, v), v)
        for k, row in (doc.get("costmodel") or {}).items():
            if isinstance(row, dict):
                costmodel.setdefault(k, row)
        if doc.get("error") and error is None:
            error = f"host{h}: {doc['error']}"
        any_salvaged = any_salvaged or bool(doc.get("salvaged"))
        hosts_table.append({
            "host": h,
            "stream": os.path.basename(path),
            "run_id": doc["run_id"],
            "wall_s": doc["wall_s"],
            "error": doc.get("error"),
            "salvaged": bool(doc.get("salvaged")),
            "counters": dict(doc.get("counters") or {}),
            "gauges": dict(doc.get("gauges") or {}),
        })
    return {
        "schema": base["schema"],
        "schema_version": base["schema_version"],
        "run_id": base["run_id"],
        "name": base["name"],
        "host_count": len(docs),
        "t_start_unix": min(doc.get("t_start_unix") or 0.0
                            for _, _, doc in docs),
        "wall_s": round(float(wall), 6),
        "error": error,
        "platform": dict(base.get("platform") or {}),
        "knobs": dict(base.get("knobs") or {}),
        "numeric_mode": base.get("numeric_mode"),
        "compile": base.get("compile"),
        "counters": counters,
        "gauges": gauges,
        "costmodel": costmodel,
        "spans": spans,
        "merged": True,
        "hosts": hosts_table,
        "salvaged": any_salvaged,
    }


def merge_file(paths: list[str], out: str | None = None,
               force: bool = False) -> str:
    """Merge streams and write the manifest atomically; returns its path.

    Default output sits next to the first stream as
    ``<run_id>.merged.manifest.json`` — like salvage, deliberately NOT
    the plain ``.manifest.json`` name any live host could still finalize.
    """
    doc = merge_streams(paths, force=force)
    if out is None:
        out = os.path.join(os.path.dirname(paths[0]) or ".",
                           doc["run_id"] + ".merged.manifest.json")
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False, default=str)
        fh.write("\n")
    os.replace(tmp, out)
    return out
