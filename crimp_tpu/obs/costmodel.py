"""XLA cost-model capture: flops/bytes per jitted kernel, from the compiler.

The flight recorder knows how LONG a kernel ran; this module captures how
much WORK the compiled executable represents — ``cost_analysis()`` (flops,
bytes accessed, transcendentals) and ``memory_analysis()`` (argument/
output/temp/generated-code bytes) from the AOT-compiled form of the same
jitted function the call site just dispatched. :mod:`crimp_tpu.obs.roofline`
joins these rows against measured span durations to turn raw seconds into
achieved FLOP/s, arithmetic intensity and %-of-peak — the "as fast as the
hardware allows" metric the ROADMAP north star actually asks for.

Contracts (pinned by tests/test_costmodel.py):

- **Disabled is free.** With no active obs run, :func:`capture` returns
  after one ``active() is None`` check — it never touches the function,
  the arguments, or jax. ``CRIMP_TPU_OBS_COST=0`` disables capture while
  the rest of obs stays on (malformed values raise, like every knob).
- **Repeat shapes cost nothing.** Rows are cached per
  (kernel, platform, arg shapes/dtypes/statics, numeric-mode knobs)
  fingerprint: an in-process dict first, then the autotune cache file
  (``cost|``-prefixed keys ride the same atomic-rename JSON the tuner
  winners live in), so a re-run of a tuned shape never re-lowers.
- **Never raises, never recomputes.** Lowering happens on abstract
  ``ShapeDtypeStruct`` stand-ins (no device buffers are touched, donated
  arguments included), and the AOT compile lands in the same executable
  cache the runtime call already populated. Backends without
  ``cost_analysis``/``memory_analysis`` (CPU PJRT versions vary) degrade
  to partial rows; any failure degrades to "no row", counted in
  ``costmodel_capture_errors``.
"""

from __future__ import annotations

import hashlib
import logging
import sys

from crimp_tpu import knobs
from crimp_tpu.obs import core as obs_core

logger = logging.getLogger("crimp_tpu.obs.costmodel")

# One in-process row cache per fingerprint; shared across runs (the row is
# a property of the compiled executable, not of any particular run).
_MEM_CACHE: dict[str, dict] = {}


def cost_capture_on() -> bool:
    """Whether CRIMP_TPU_OBS_COST asks for capture (default on; malformed
    raises — the knob-registry typo discipline)."""
    return knobs.env_onoff("CRIMP_TPU_OBS_COST") is not False


def _platform_peek() -> str:
    """``backend|device_kind`` from already-initialized backends only.

    Same never-initialize contract as ``obs.core._platform_identity``:
    capture runs right after a kernel dispatch, so a backend is live in
    practice — but cost capture must never be the thing that brings one up.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return "none|none"
    try:
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None) or {}
        for plat, backend in backends.items():
            devs = backend.devices()
            kind = getattr(devs[0], "device_kind", "") if devs else ""
            return f"{plat}|{kind}"
    except Exception:  # noqa: BLE001 — identity is best-effort telemetry  # graftlint: disable=GL006 (telemetry guard: platform peek must never fail a capture)
        pass
    return "none|none"


def _leaf_devices(leaf) -> int:
    """Devices a committed array leaf spans (1 for numpy/uncommitted)."""
    try:
        ds = getattr(getattr(leaf, "sharding", None), "device_set", ())
        return len(ds) if ds else 1
    except TypeError:  # pragma: no cover - exotic sharding objects
        return 1


def _leaf_sig(leaf) -> str:
    """One fingerprint token per argument leaf: shape+dtype (+ sharding
    spec for multi-device arrays — a sharded dispatch must never alias
    the unsharded row of the same shape), repr for plain statics,
    axis-name/size table for meshes, type name for anything opaque."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        sig = f"{dtype}[{','.join(str(d) for d in shape)}]"
        if _leaf_devices(leaf) > 1:
            sh = leaf.sharding
            sig += f"@{getattr(sh, 'spec', sh)}x{_leaf_devices(leaf)}"
        return sig
    axes = getattr(leaf, "axis_names", None)
    if shape is not None and axes is not None:  # a Mesh (duck-typed)
        return "mesh[" + ",".join(f"{a}={shape[a]}" for a in axes) + "]"
    if isinstance(leaf, (bool, int, float, complex, str, bytes, type(None))):
        return repr(leaf)
    return type(leaf).__name__


def _numeric_knob_sig() -> str:
    """Set numeric-affecting knobs, so a numeric-mode flip (poly trig,
    delta-fold budget, ...) can never alias a cached cost row."""
    parts = []
    for name in sorted(knobs.REGISTRY):
        if knobs.REGISTRY[name].numeric:
            val = knobs.raw(name)
            if val:
                parts.append(f"{name}={val}")
    return ";".join(parts)


def _plan_sig(plan) -> str:
    """Fingerprint token for a registry sharding plan (duck-typed so this
    module never imports the registry or jax at module scope)."""
    if plan is None:
        return ""
    mesh = plan.mesh
    return ("plan:" + plan.rule.kernel + ";"
            + ",".join(f"{a}={mesh.shape[a]}" for a in mesh.axis_names))


def fingerprint(name: str, args: tuple, kwargs: dict, plan=None) -> str:
    """``cost|<platform>|<device_kind>|<kernel>|<sha>`` — the disk-cache key.

    The sha covers every argument leaf's shape/dtype/sharding (or static
    value), the set numeric-mode knobs, and the registry plan's mesh shape
    when one is given (a 4-device and an 8-device lowering of the same
    shapes are different per-device programs); the readable prefix keeps
    the shared autotune cache file greppable.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    body = "|".join([str(treedef), _numeric_knob_sig(), _plan_sig(plan)]
                    + [_leaf_sig(leaf) for leaf in leaves])
    sha = hashlib.sha1(body.encode()).hexdigest()[:16]
    return f"cost|{_platform_peek()}|{name}|{sha}"


def _abstractify(x):
    """Array leaves -> ShapeDtypeStruct so lowering never touches buffers
    (donated streamed-carry arguments included); statics pass through.

    Committed multi-device shardings are PRESERVED on the stand-in — this
    is what hands the registry's shardings to the AOT lowering, so the
    compiled form is the per-device GSPMD program and the cost row reads
    per-device flops/bytes instead of skipping sharded dispatches."""
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        if _leaf_devices(x) > 1:
            try:
                return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype,
                                            sharding=x.sharding)
            except TypeError:  # pragma: no cover - very old jax
                pass
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


def analyze(fn, args: tuple, kwargs: dict, plan=None) -> dict:
    """Lower + AOT-compile ``fn`` on abstract stand-ins; extract the row.

    The AOT compile lands in the same executable cache the runtime call
    already populated, so for a kernel that just ran this costs one
    retrace, not a recompile. Missing analyses (backend-dependent) leave
    their fields None — a partial row, never an exception out of here
    beyond what :func:`capture` swallows.

    With a registry ``plan`` (parallel/registry.KernelSharding) — or
    committed multi-device argument shardings — the row additionally
    carries ``devices``, ``sharded``, ``reduce_axes`` and the estimated
    per-device ``collective_bytes`` the kernel's psum moves (ring
    all-reduce model over the plan's reduce axes and output shapes).
    """
    import jax

    aargs = jax.tree_util.tree_map(_abstractify, args)
    akwargs = jax.tree_util.tree_map(_abstractify, kwargs)
    lowered = fn.lower(*aargs, **akwargs)
    compiled = lowered.compile()
    devices = max([1] + [_leaf_devices(leaf) for leaf in
                         jax.tree_util.tree_leaves((aargs, akwargs))])
    if plan is not None:
        devices = max(devices, int(plan.device_count()))
    row: dict = {"flops": None, "bytes_accessed": None, "transcendentals": None,
                 "argument_bytes": None, "output_bytes": None,
                 "temp_bytes": None, "peak_bytes": None,
                 "generated_code_bytes": None,
                 "devices": devices, "sharded": devices > 1}
    if plan is not None:
        row["reduce_axes"] = list(plan.rule.reduce_axes)
        try:
            outs = jax.tree_util.tree_leaves(lowered.out_info)
            split = plan.collective_bytes_split(outs)
            row["collective_bytes"] = float(split["ici"] + split["dcn"])
            row["collective_bytes_ici"] = float(split["ici"])
            row["collective_bytes_dcn"] = float(split["dcn"])
        except Exception:  # noqa: BLE001 — out_info is jax-version-dependent  # graftlint: disable=GL006 (telemetry guard: collective accounting degrades to None on jax builds without lowered.out_info)
            row["collective_bytes"] = None
            row["collective_bytes_ici"] = None
            row["collective_bytes_dcn"] = None
    # per-host rows: under multi-process dispatch every host captures its
    # own row; the stamps keep `obs merge` from folding hosts together
    try:
        from crimp_tpu.parallel import multihost
        row["process_index"], row["process_count"] = \
            multihost.process_identity()
    except Exception:  # noqa: BLE001 — identity is best-effort telemetry  # graftlint: disable=GL006 (telemetry guard: process identity must never fail a capture)
        row["process_index"], row["process_count"] = 0, 1
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent analysis  # graftlint: disable=GL006 (telemetry guard: cost_analysis is absent on some PJRT backends; partial rows are the contract)
        ca = None
    if isinstance(ca, (list, tuple)):  # jax<=0.4.x returns [dict]
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        for field, key in (("flops", "flops"),
                           ("bytes_accessed", "bytes accessed"),
                           ("transcendentals", "transcendentals")):
            val = ca.get(key)
            if isinstance(val, (int, float)):
                row[field] = float(val)
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent analysis  # graftlint: disable=GL006 (telemetry guard: memory_analysis is absent on some PJRT backends; partial rows are the contract)
        ma = None
    if ma is not None:
        for field, attr in (
                ("argument_bytes", "argument_size_in_bytes"),
                ("output_bytes", "output_size_in_bytes"),
                ("temp_bytes", "temp_size_in_bytes"),
                ("peak_bytes", "peak_memory_in_bytes"),
                ("generated_code_bytes", "generated_code_size_in_bytes")):
            val = getattr(ma, attr, None)
            if isinstance(val, (int, float)):
                row[field] = int(val)
        if row["peak_bytes"] is None and row["temp_bytes"] is not None:
            # older jax has no peak field: argument+output+temp is the
            # executable's simultaneous-buffer upper bound
            row["peak_bytes"] = sum(row[f] or 0 for f in
                                    ("argument_bytes", "output_bytes",
                                     "temp_bytes"))
    return row


def capture(name: str, fn, *args, plan=None, **kwargs) -> dict | None:
    """Record the cost-model row for one jitted call under span name ``name``.

    Call sites invoke this right after dispatching ``fn(*args, **kwargs)``
    with the SAME arguments. ``plan`` (keyword-only, never forwarded to
    ``fn``) is the registry sharding plan of a sharded dispatch —
    ``parallel/registry.specs_for(...)`` — and turns on per-device and
    collective-bytes accounting. Returns the row (also recorded on the
    active run, keyed so ``obs roofline`` can join it against the span
    rollup), or None: no active run, capture knob off, or a capture
    failure — in which case the pipeline proceeds untouched.
    """
    rec = obs_core.active()
    if rec is None:
        return None
    if not cost_capture_on():
        return None
    try:
        key = fingerprint(name, args, kwargs, plan=plan)
        row = _MEM_CACHE.get(key)
        cache = "mem"
        if row is None:
            row = _disk_get(key)
            cache = "disk"
        if row is None:
            row = analyze(fn, args, kwargs, plan=plan)
            cache = "miss"
            _disk_put(key, row)
        _MEM_CACHE[key] = row
        out = dict(row)
        out["fingerprint"] = key
        out["cache"] = cache
        span = obs_core.current_span_name()
        if span:
            out["span"] = span
        obs_core.record_cost(name, out)
        obs_core.counter_add("costmodel_rows")
        return out
    except Exception as exc:  # noqa: BLE001 — capture must never fail the kernel that just succeeded  # graftlint: disable=GL006 (telemetry guard: cost capture degrades to no-row; obs cannot import resilience without a cycle)
        logger.debug("cost capture failed for %s: %s", name, exc)
        obs_core.counter_add("costmodel_capture_errors")
        return None


# -- disk tier (the autotune cache file, "cost|" keys) ----------------------


def _disk_get(key: str) -> dict | None:
    from crimp_tpu.ops import autotune

    entry = autotune._load_cache().get(key)
    if not isinstance(entry, dict):
        return None
    return {k: v for k, v in entry.items()
            if k not in ("fingerprint", "cache", "span")}


def _disk_put(key: str, row: dict) -> None:
    from crimp_tpu.ops import autotune

    try:
        autotune._store_entry(key, row)
    except OSError:
        # a read-only or full cache dir just means no persistence tier;
        # the in-process cache still dedups this run
        logger.debug("cost cache store failed for %s", key)


def reset_mem_cache() -> None:
    """Test hook: forget every in-process row."""
    _MEM_CACHE.clear()
