"""crimp_tpu.obs — host-side flight-recorder telemetry.

Three pieces (docs/observability.md has the full contracts):

- **Spans + metrics core** (:mod:`crimp_tpu.obs.core`): hierarchical
  spans (run -> pipeline stage -> kernel) plus typed counters/gauges for
  the quantities the engines compute and previously dropped on the floor
  (events folded, ToAs fit, padding waste, delta-fold hit/guard trips,
  autotune/fold-cache hits, MXU reseeds, compile telemetry).
- **Flight recorder**: every pipeline entry point wrapped in
  :func:`run` emits an append-only JSONL event stream and an atomic
  end-of-run JSON manifest (span tree, counters, knob snapshot, the
  resumable ``numeric_mode`` fingerprint, platform/device identity).
- **Reporter** (:mod:`crimp_tpu.obs.report`, CLI ``python -m
  crimp_tpu.obs``): summarize a manifest, diff two runs (span-level
  slowdown attribution, counter deltas, knob drift), export Chrome
  trace-event JSON and Prometheus text exposition.
- **Cost model + roofline** (:mod:`crimp_tpu.obs.costmodel`,
  :mod:`crimp_tpu.obs.roofline`): XLA ``cost_analysis``/``memory_analysis``
  rows per jitted kernel (cached through the autotune machinery), HBM
  watermarks at stage boundaries, and the ``obs roofline`` join that turns
  measured span seconds into achieved FLOP/s and %-of-peak.
- **Live + longitudinal layer**: :mod:`crimp_tpu.obs.heartbeat`
  (periodic progress/ETA events + an atomic sidecar, the default
  ``progress`` of long scans), :mod:`crimp_tpu.obs.salvage`
  (``obs salvage`` reconstructs a manifest from a killed run's event
  stream; ``obs tail`` follows a live one) and
  :mod:`crimp_tpu.obs.ledger` (``obs ledger add|show|check``: classify
  bench records, compute the green on-chip baseline, gate regressions).

Everything here is host-side by construction: graftlint GL001 flags any
call into this package reachable from traced code. Disabled
(``CRIMP_TPU_OBS`` unset/off, the default) every hook is a strict no-op
— :func:`span` returns a shared singleton and :func:`counter_add`
returns after one global ``None`` check, so hot loops pay zero
allocations and no pipeline byte changes.

Import-safe: this package never imports jax (the reporter CLI and the
relay-window scripts must run with no backend available).
"""

from crimp_tpu.obs.core import (  # noqa: F401
    NULL_SPAN,
    OBS_SCHEMA,
    OBS_SCHEMA_VERSION,
    active,
    counter_add,
    current_span_name,
    enabled,
    gauge_set,
    last_manifest_path,
    mark_degraded,
    record_cost,
    record_numeric_mode,
    record_span,
    run,
    span,
)
from crimp_tpu.obs import costmodel, heartbeat  # noqa: F401
from crimp_tpu.obs.heartbeat import beat  # noqa: F401
