"""Entry point for ``python -m crimp_tpu.obs``."""

import sys

from crimp_tpu.obs.cli import main

if __name__ == "__main__":
    sys.exit(main())
