"""Append-only performance ledger: classify, baseline, gate.

The fleet-level fact "the last green on-chip record is r4" used to be
hand-tracked ROADMAP prose. This module computes it from the artifacts
already on disk: it ingests bench driver records (``BENCH_r*.json``),
raw bench record lines (``bench.log`` / ``results.jsonl``) and obs run
manifests into normalized ledger entries, classifies each by
``platform``/``platform_fallback`` (bench.py stamps these), excludes
everything that is not a green on-chip run from the baseline, and gates
new records against the per-metric baseline with a tolerance band
(``obs ledger check --fail-on-regression --tolerance-pct N`` for CI).

Classification vocabulary (one per entry):

- ``onchip``       — parsed record, zero rc, accelerator platform. Only
                     these contribute to (and are gated against) the
                     baseline.
- ``cpu_fallback`` — ran on CPU. Records predating the
                     ``platform_fallback`` stamp (r3/r4's drivers) are
                     conservatively classified here too, as is any obs
                     manifest whose backend is ``cpu``: nothing that ran
                     on CPU may ever seed an on-chip baseline.
- ``cpu_pinned``   — CPU with ``platform_fallback: false`` (the operator
                     forced CPU; excluded, but not an outage signal).
- ``carried``      — a carry-forward record (bench re-emitting the last
                     real measurement); never baseline material.
- ``degraded``     — the run completed only by taking a resilience
                     ladder rung (manifest ``degraded`` flag, or a bench
                     record stamped ``degraded``); its numbers reflect a
                     lower rung, so it never feeds the green baseline.
- ``failed``       — nonzero rc or no parseable record (r1's crash, r5's
                     rc=124 polling timeout).
- ``unknown``      — a parsed record from before the ``platform`` stamp
                     (r2); excluded, since its provenance is a guess.

A driver record ``BENCH_rNN.json`` additionally pulls in its sibling
``onchip_results_rNN/bench.log`` when present: the driver ran on the CPU
fallback during a relay outage, but the session's own on-chip record —
the one ROADMAP prose pointed at by hand — is the last record line of
that log, and it lands in the ledger as round NN's on-chip entry.
"""

from __future__ import annotations

import json
import os
import re

from crimp_tpu import knobs

LEDGER_SCHEMA = "crimp_tpu.obs.ledger"
LEDGER_SCHEMA_VERSION = 1

GREEN_CLASSES = frozenset(("onchip",))

# metric name -> (where it lives in a bench record, which direction is
# better). "higher" gates throughput, "lower" gates walls and compile
# telemetry.
METRICS: dict[str, dict] = {
    "toas_per_sec": {"field": "value", "better": "higher"},
    "north_star_wall_s": {"field": "north_star_wall_s", "better": "lower"},
    "z2_trials_per_sec": {"field": "z2_trials_per_sec", "better": "higher"},
    "z2_trials_per_sec_poly": {"field": "z2_trials_per_sec_poly",
                               "better": "higher"},
    "config4_toas_per_sec": {"field": "config4_toas_per_sec",
                             "better": "higher"},
    "sources_per_s": {"field": "sources_per_s", "better": "higher"},
    "ess_per_s": {"field": "ess_per_s", "better": "higher"},
    "warmup_s": {"field": "warmup_s", "better": "lower"},
    "backend_compile_s": {"field": ("compile_cache", "backend_compile_s"),
                          "better": "lower"},
    "requests_per_s": {"field": "requests_per_s", "better": "higher"},
    "p99_latency_ms": {"field": "p99_latency_ms", "better": "lower"},
    # steady-state warm re-timing throughput (bench_serving's warm-heavy
    # phase: >=16 resident clients refolding per round)
    "warm_requests_per_s": {"field": "warm_requests_per_s",
                            "better": "higher"},
    # grid-search cube throughput (bench.py bench_jerk): equivalent-coherent
    # cube trials per second, so semi-coherent rounds are comparable to
    # coherent ones at matched coverage
    "trials_per_s": {"field": "trials_per_s", "better": "higher"},
}


def classify(record: dict | None, rc: int | None = None) -> str:
    """One class per record; see the module docstring for the vocabulary."""
    if rc not in (None, 0):
        return "failed"
    if not isinstance(record, dict):
        return "failed"
    if record.get("carried"):
        return "carried"
    if record.get("degraded"):
        return "degraded"
    platform = record.get("platform")
    if platform == "cpu":
        if record.get("platform_fallback") is False:
            return "cpu_pinned"
        # stamped true, or a pre-stamp legacy record: both mean "did not
        # run on the accelerator", which is all the baseline cares about
        return "cpu_fallback"
    if not platform:
        return "unknown"
    return "onchip"


def extract_metrics(record: dict) -> dict[str, float]:
    """The gateable metric values present in a bench record."""
    out: dict[str, float] = {}
    for name, spec in METRICS.items():
        field = spec["field"]
        if isinstance(field, tuple):
            val = record
            for part in field:
                val = val.get(part) if isinstance(val, dict) else None
        else:
            val = record.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[name] = float(val)
    return out


def entry_from_record(record: dict | None, *, source: str, kind: str = "bench",
                      round_n: int | None = None,
                      rc: int | None = None) -> dict:
    """Normalize one bench record (or its absence) into a ledger entry.

    The ``process_index``/``process_count`` stamps ride along (defaulting
    to the single-process identity for records predating the stamp) so the
    green baseline never mixes single-host and N-host rates — ``check()``
    gates a candidate only against greens with the same process count."""
    rec = record if isinstance(record, dict) else {}
    return {
        "schema": LEDGER_SCHEMA,
        "v": LEDGER_SCHEMA_VERSION,
        "kind": kind,
        "source": source,
        "round": round_n,
        "rc": rc,
        "class": classify(record, rc),
        "platform": rec.get("platform"),
        "platform_fallback": rec.get("platform_fallback"),
        "carried": bool(rec.get("carried")),
        "process_index": int(rec.get("process_index") or 0),
        "process_count": int(rec.get("process_count") or 1),
        "metrics": extract_metrics(rec),
    }


def _entry_from_manifest(doc: dict, source: str) -> dict:
    backend = (doc.get("platform") or {}).get("backend")
    if backend and backend != "cpu":
        cls = "onchip"
    elif backend == "cpu":
        cls = "cpu_fallback"
    else:
        cls = "unknown"
    if doc.get("degraded"):
        cls = "degraded"  # completed on a ladder rung, not the normal path
    if doc.get("salvaged"):
        cls = "failed"  # a killed run's lower-bound walls are not baselines
    metrics = {}
    wall = doc.get("wall_s")
    if isinstance(wall, (int, float)):
        metrics["run_wall_s"] = float(wall)
    # cost-model extensions: recorded for longitudinal history, but NOT
    # in METRICS — check() skips them, so they cannot gate a round yet
    hbm_peak = (doc.get("gauges") or {}).get("hbm_peak_bytes")
    if isinstance(hbm_peak, (int, float)):
        metrics["hbm_peak_bytes"] = float(hbm_peak)
    if doc.get("costmodel"):
        try:
            from crimp_tpu.obs import roofline
            analysis = roofline.analyze(doc)
            for key in ("worst_pct", "best_pct"):
                val = analysis.get(key)
                if isinstance(val, (int, float)):
                    metrics[f"roofline_{key}"] = float(val)
        except Exception:  # noqa: BLE001 — a sparse manifest yields no roofline metric, never a failed ingest  # graftlint: disable=GL006 (telemetry guard: roofline join is optional ledger enrichment)
            pass
    return {
        "schema": LEDGER_SCHEMA, "v": LEDGER_SCHEMA_VERSION,
        "kind": "obs_manifest", "source": source,
        "round": _round_from_name(source), "rc": None, "class": cls,
        "platform": backend, "platform_fallback": None, "carried": False,
        "metrics": metrics,
    }


def _round_from_name(path: str) -> int | None:
    # BENCH_r04.json -> 4; onchip_results_r4/bench.log -> 4
    m = re.search(r"_r0*(\d+)(?:\D|$)", path)
    return int(m.group(1)) if m else None


def _record_lines(path: str) -> list[dict]:
    """Every parseable bench-record JSON line of a log/JSONL file."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict) and "metric" in doc:
                out.append(doc)
    return out


def entries_from_path(path: str) -> list[dict]:
    """Ingest one artifact into ledger entries (see module docstring).

    Driver records fan out into the driver entry plus the sibling
    ``onchip_results_rNN/bench.log`` session record when one exists.
    """
    base = os.path.basename(path)
    if base.endswith((".log", ".jsonl")):
        records = _record_lines(path)
        if not records:
            return [entry_from_record(None, source=path, kind="bench_log",
                                      round_n=_round_from_name(path))]
        return [entry_from_record(records[-1], source=path, kind="bench_log",
                                  round_n=_round_from_name(path))]
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if doc.get("schema") == "crimp_tpu.obs":
        return [_entry_from_manifest(doc, path)]
    if "parsed" in doc and ("rc" in doc or "cmd" in doc):
        round_n = doc.get("n") if isinstance(doc.get("n"), int) \
            else _round_from_name(path)
        entries = [entry_from_record(doc.get("parsed"), source=path,
                                     kind="bench_driver", round_n=round_n,
                                     rc=doc.get("rc"))]
        if round_n is not None:
            sibling = os.path.join(os.path.dirname(os.path.abspath(path)),
                                   f"onchip_results_r{round_n}", "bench.log")
            if os.path.exists(sibling):
                entries.extend(entries_from_path(sibling))
        return entries
    if "metric" in doc:
        return [entry_from_record(doc, source=path, kind="bench",
                                  round_n=_round_from_name(path))]
    raise ValueError(f"{path}: not a bench record, driver record, or obs "
                     "manifest")


def append(path: str, entries: list[dict]) -> None:
    """Append normalized entries to the ledger JSONL (append-only)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        for entry in entries:
            json.dump(entry, fh, default=str)
            fh.write("\n")


def read(path: str) -> list[dict]:
    """All entries of a ledger file (missing file = empty ledger)."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict):
                out.append(doc)
    return out


def _ordered(entries: list[dict]) -> list[dict]:
    # Stable order: by round (unknown rounds first, as ambient history),
    # then ingestion order — "latest" is the last element.
    def key(pair):
        i, e = pair
        rnd = e.get("round")
        return (rnd if isinstance(rnd, int) else -1, i)

    return [e for _, e in sorted(enumerate(entries), key=key)]


def baseline(entries: list[dict]) -> dict[str, dict]:
    """Per-metric green baseline: the latest green entry carrying it."""
    base: dict[str, dict] = {}
    for e in _ordered(entries):
        if e.get("class") not in GREEN_CLASSES:
            continue
        for metric, value in (e.get("metrics") or {}).items():
            base[metric] = {"value": value, "round": e.get("round"),
                            "source": e.get("source")}
    return base


def check(entries: list[dict], tolerance_pct: float = 5.0) -> dict:
    """Gate the latest green entry against the baseline of the rest.

    The latest green entry (by round, then ingestion order) is the
    candidate; the baseline is computed from the green entries before it.
    With a single green entry there is nothing to compare — it *is* the
    baseline and the check passes. Non-green entries are reported as
    excluded. A metric regresses when it is worse than baseline by more
    than ``tolerance_pct`` percent in its metric's bad direction.

    Greens whose ``process_count`` differs from the candidate's are
    excluded from its baseline (reported with class
    ``other_process_count``): a 4-host aggregate rate must never gate —
    or be gated by — a single-host run of the same metric.
    """
    ordered = _ordered(entries)
    greens = [e for e in ordered
              if e.get("class") in GREEN_CLASSES and e.get("metrics")]
    if greens:
        cand_pc = int(greens[-1].get("process_count") or 1)
        mismatched = [e for e in greens[:-1]
                      if int(e.get("process_count") or 1) != cand_pc]
        greens = [e for e in greens if e not in mismatched]
    else:
        mismatched = []
    excluded = [{"source": e.get("source"), "round": e.get("round"),
                 "class": e.get("class")}
                for e in ordered if e.get("class") not in GREEN_CLASSES]
    excluded += [{"source": e.get("source"), "round": e.get("round"),
                  "class": "other_process_count"} for e in mismatched]
    report = {
        "tolerance_pct": tolerance_pct,
        "entries": len(entries),
        "excluded": excluded,
        "baseline": {},
        "baseline_round": None,
        "candidate": None,
        "regressions": [],
        "improvements": [],
        "ok": True,
    }
    if not greens:
        return report
    candidate, prior = greens[-1], greens[:-1]
    base = baseline(prior if prior else [candidate])
    report["baseline"] = base
    rounds = [b["round"] for b in base.values() if b["round"] is not None]
    report["baseline_round"] = max(rounds) if rounds else None
    report["candidate"] = {"source": candidate.get("source"),
                           "round": candidate.get("round"),
                           "metrics": candidate.get("metrics")}
    if not prior:
        return report
    tol = tolerance_pct / 100.0
    for metric, cand_val in (candidate.get("metrics") or {}).items():
        if metric not in base or metric not in METRICS:
            continue
        base_val = base[metric]["value"]
        if base_val == 0:
            continue
        higher = METRICS[metric]["better"] == "higher"
        delta_pct = 100.0 * (cand_val - base_val) / abs(base_val)
        worse = cand_val < base_val * (1.0 - tol) if higher \
            else cand_val > base_val * (1.0 + tol)
        row = {"metric": metric, "candidate": cand_val, "baseline": base_val,
               "baseline_round": base[metric]["round"],
               "delta_pct": round(delta_pct, 2)}
        if worse:
            report["regressions"].append(row)
        elif (delta_pct > 0) == higher and delta_pct != 0:
            report["improvements"].append(row)
    report["ok"] = not report["regressions"]
    return report


def render_check(report: dict) -> str:
    """Human-readable rendering of a :func:`check` report."""
    lines = [f"ledger: {report['entries']} entries, tolerance "
             f"{report['tolerance_pct']:g}%"]
    for e in report["excluded"]:
        rnd = f"r{e['round']}" if e["round"] is not None else "r?"
        lines.append(f"  excluded  {rnd:<4} {e['class']:<13} {e['source']}")
    if not report["baseline"]:
        lines.append("no green on-chip entries: nothing to gate")
        return "\n".join(lines)
    rnd = report["baseline_round"]
    lines.append(f"green baseline (round "
                 f"{'r%d' % rnd if rnd is not None else '?'}):")
    for metric, b in sorted(report["baseline"].items()):
        lines.append(f"  {metric:<24} {b['value']:<12g} {b['source']}")
    cand = report["candidate"]
    if cand is not None:
        crnd = f"r{cand['round']}" if cand["round"] is not None else "r?"
        lines.append(f"candidate {crnd}: {cand['source']}")
    for row in report["regressions"]:
        lines.append(
            f"  REGRESSION  {row['metric']}: {row['candidate']:g} vs "
            f"baseline {row['baseline']:g} (r{row['baseline_round']}) "
            f"{row['delta_pct']:+.1f}%")
    for row in report["improvements"]:
        lines.append(
            f"  improved    {row['metric']}: {row['candidate']:g} vs "
            f"baseline {row['baseline']:g} {row['delta_pct']:+.1f}%")
    lines.append("OK" if report["ok"] else "FAIL")
    return "\n".join(lines)


def env_ledger_path() -> str | None:
    """The CRIMP_TPU_OBS_LEDGER path, or None when unset/disabled."""
    env = knobs.raw("CRIMP_TPU_OBS_LEDGER")
    if not env or knobs.parse_onoff(env) is False:
        return None
    return env


def append_bench_record(record: dict, *, source: str,
                        round_n: int | None = None) -> str | None:
    """Bench's end-of-round hook: append when the ledger knob is set.

    Returns the ledger path written to, or None when the knob is off.
    Never raises — the official record on stdout must not be lost to a
    full disk under the ledger path.
    """
    path = env_ledger_path()
    if path is None:
        return None
    try:
        append(path, [entry_from_record(record, source=source, kind="bench",
                                        round_n=round_n)])
    except OSError:
        return None
    return path
