"""Reporter CLI: ``python -m crimp_tpu.obs <subcommand>``.

Subcommands:

- ``summary MANIFEST``        one-run summary (spans, counters, knobs)
- ``diff A B``                attribute A→B slowdown; flag knob/numeric drift
- ``trace MANIFEST [-o OUT]`` export Chrome trace-event JSON (Perfetto)
- ``prom MANIFEST [-o OUT]``  export Prometheus text exposition
- ``validate MANIFEST``       schema-check a manifest

Exit codes: 0 = ok, 1 = validation problems / drift found with
``--fail-on-drift``, 2 = usage or I/O error.
"""

from __future__ import annotations

import argparse
import json
import sys

from crimp_tpu.obs import report as rpt
from crimp_tpu.obs.manifest import load_manifest, validate_manifest


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m crimp_tpu.obs",
        description="crimp_tpu flight-recorder reporter: summarize, diff "
                    "and export run manifests.")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summary", help="summarize one run manifest")
    s.add_argument("manifest")
    s.add_argument("--format", choices=("text", "json"), default="text")

    d = sub.add_parser("diff", help="compare two run manifests (A -> B)")
    d.add_argument("a")
    d.add_argument("b")
    d.add_argument("--format", choices=("text", "json"), default="text")
    d.add_argument("--min-delta-s", type=float, default=0.005,
                   help="ignore stage deltas below this (timer noise)")
    d.add_argument("--fail-on-drift", action="store_true",
                   help="exit 1 when knobs, numeric_mode or backend drifted")

    t = sub.add_parser("trace", help="export Chrome trace-event JSON")
    t.add_argument("manifest")
    t.add_argument("-o", "--out", default=None, help="output path (default stdout)")

    m = sub.add_parser("prom", help="export Prometheus text exposition")
    m.add_argument("manifest")
    m.add_argument("-o", "--out", default=None, help="output path (default stdout)")

    v = sub.add_parser("validate", help="schema-check a manifest")
    v.add_argument("manifest")
    return p


def _write(text: str, out: str | None) -> None:
    if out:
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text if text.endswith("\n") else text + "\n")


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "validate":
            with open(args.manifest, encoding="utf-8") as fh:
                doc = json.load(fh)
            problems = validate_manifest(doc)
            for prob in problems:
                print(f"{args.manifest}: {prob}")
            print(f"{args.manifest}: "
                  + ("OK" if not problems else f"{len(problems)} problem(s)"))
            return 1 if problems else 0

        if args.cmd == "summary":
            doc = load_manifest(args.manifest)
            if args.format == "json":
                print(json.dumps({"summary": rpt.span_rollup(doc),
                                  "counters": doc.get("counters"),
                                  "gauges": doc.get("gauges"),
                                  "knobs": doc.get("knobs"),
                                  "run_id": doc["run_id"],
                                  "wall_s": doc["wall_s"]}, indent=2))
            else:
                print(rpt.summarize(doc))
            return 0

        if args.cmd == "diff":
            a = load_manifest(args.a)
            b = load_manifest(args.b)
            d = rpt.diff(a, b, min_delta_s=args.min_delta_s)
            if args.format == "json":
                print(json.dumps(d, indent=2))
            else:
                print(rpt.render_diff(d))
            drifted = bool(d["knob_drift"] or d["numeric_mode_drift"]
                           or d["backend_drift"])
            return 1 if (args.fail_on_drift and drifted) else 0

        if args.cmd == "trace":
            doc = load_manifest(args.manifest)
            _write(json.dumps(rpt.chrome_trace(doc), indent=1), args.out)
            return 0

        if args.cmd == "prom":
            doc = load_manifest(args.manifest)
            _write(rpt.prometheus(doc), args.out)
            return 0
    except (OSError, ValueError) as exc:
        print(f"obs: {exc}", file=sys.stderr)
        return 2
    return 2
