"""Reporter CLI: ``python -m crimp_tpu.obs <subcommand>``.

Subcommands:

- ``summary MANIFEST``        one-run summary (spans, counters, knobs)
- ``diff A B``                attribute A→B slowdown; flag knob/numeric drift
- ``trace MANIFEST [-o OUT]`` export Chrome trace-event JSON (Perfetto)
- ``prom MANIFEST [-o OUT]``  export Prometheus text exposition
- ``roofline MANIFEST``       join cost-model rows x span durations into a
                              per-kernel %-of-peak table (``--fail-below``)
- ``validate MANIFEST``       schema-check a manifest
- ``merge STREAMS...``        join per-host event streams of one
                              multi-host run into a single validated
                              manifest (``"merged": true``, per-host
                              Chrome lanes via ``--trace-out``)
- ``salvage EVENTS``          reconstruct a manifest from a killed run's
                              event stream (``"salvaged": true``)
- ``tail TARGET``             follow a live event stream (progress/ETA)
- ``heartbeat-check SIDECAR --max-age-s N``
                              liveness probe: exit 0 when the sidecar is
                              fresher than N seconds, 1 when stale,
                              missing or torn
- ``ledger add|show|check``   the append-only performance ledger

Exit codes: 0 = ok, 1 = validation problems / drift found with
``--fail-on-drift`` / regression with ``--fail-on-regression`` / roofline
worst kernel below ``--fail-below`` / tail without a run end, 2 = usage
or I/O error.
"""

from __future__ import annotations

import argparse
import json
import sys

from crimp_tpu.obs import heartbeat as hbt
from crimp_tpu.obs import ledger as ldg
from crimp_tpu.obs import merge as mrg
from crimp_tpu.obs import report as rpt
from crimp_tpu.obs import roofline as rfl
from crimp_tpu.obs import salvage as slv
from crimp_tpu.obs.manifest import load_manifest, validate_manifest


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m crimp_tpu.obs",
        description="crimp_tpu flight-recorder reporter: summarize, diff "
                    "and export run manifests.")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summary", help="summarize one run manifest")
    s.add_argument("manifest")
    s.add_argument("--format", choices=("text", "json"), default="text")

    d = sub.add_parser("diff", help="compare two run manifests (A -> B)")
    d.add_argument("a")
    d.add_argument("b")
    d.add_argument("--format", choices=("text", "json"), default="text")
    d.add_argument("--min-delta-s", type=float, default=0.005,
                   help="ignore stage deltas below this (timer noise)")
    d.add_argument("--fail-on-drift", action="store_true",
                   help="exit 1 when knobs, numeric_mode or backend drifted")

    t = sub.add_parser("trace", help="export Chrome trace-event JSON")
    t.add_argument("manifest")
    t.add_argument("-o", "--out", default=None, help="output path (default stdout)")

    m = sub.add_parser("prom", help="export Prometheus text exposition")
    m.add_argument("manifest")
    m.add_argument("-o", "--out", default=None, help="output path (default stdout)")

    r = sub.add_parser(
        "roofline", help="per-kernel achieved FLOP/s, intensity and "
                         "%-of-peak from the manifest's cost-model rows")
    r.add_argument("manifest")
    r.add_argument("--format", choices=("text", "json"), default="text")
    r.add_argument("--fail-below", type=float, default=None, metavar="PCT",
                   help="exit 1 when the worst measured kernel sits below "
                        "this percent of its roofline")

    v = sub.add_parser("validate", help="schema-check a manifest")
    v.add_argument("manifest")

    mg = sub.add_parser(
        "merge", help="join per-host event streams of one multi-host run "
                      "into a single validated manifest")
    mg.add_argument("streams", nargs="+",
                    help="per-host *.events.jsonl files, or one run "
                         "directory (newest run's host group wins)")
    mg.add_argument("-o", "--out", default=None,
                    help="output path (default: <run_id>.merged."
                         "manifest.json next to the first stream)")
    mg.add_argument("--run-id", default=None,
                    help="with a directory target: merge this run's host "
                         "group instead of the newest one (a unique "
                         "substring of the id is enough)")
    mg.add_argument("--force", action="store_true",
                    help="join streams whose run_ids disagree (clock skew "
                         "at the stamp second)")
    mg.add_argument("--trace-out", default=None, metavar="PATH",
                    help="also export the merged Chrome trace (per-host "
                         "lanes) to PATH")

    sv = sub.add_parser(
        "salvage", help="reconstruct a best-effort manifest from a killed "
                        "run's event stream")
    sv.add_argument("events", help="*.events.jsonl file or a run directory "
                                   "(newest stream wins)")
    sv.add_argument("-o", "--out", default=None,
                    help="output path (default: <run>.salvaged.manifest.json "
                         "next to the stream)")

    tl = sub.add_parser("tail", help="follow a live event stream, rendering "
                                     "progress/ETA heartbeats")
    tl.add_argument("target", help="run directory or *.events.jsonl file")
    tl.add_argument("--once", action="store_true",
                    help="render what is there and exit (0 only if the run "
                         "already ended)")
    tl.add_argument("--interval", type=float, default=2.0,
                    help="poll period in seconds")
    tl.add_argument("--max-seconds", type=float, default=None,
                    help="give up (exit 1) after this long without run_end")

    hb = sub.add_parser(
        "heartbeat-check", help="liveness-probe a heartbeat sidecar "
                                "(exit 0 fresh, 1 stale/missing/torn)")
    hb.add_argument("sidecar", help="*.heartbeat.json file or a run "
                                    "directory (newest sidecar wins)")
    hb.add_argument("--max-age-s", type=float, required=True,
                    help="maximum sidecar age in seconds to count as alive")
    hb.add_argument("--format", choices=("text", "json"), default="text")

    lg = sub.add_parser("ledger", help="append-only performance ledger: "
                                       "classify records, baseline, gate")
    lg.add_argument("action", choices=("add", "show", "check"))
    lg.add_argument("paths", nargs="*",
                    help="bench records (BENCH_r*.json), bench logs, or obs "
                         "manifests to ingest")
    lg.add_argument("--ledger", default=None,
                    help="ledger JSONL path (default: $CRIMP_TPU_OBS_LEDGER)")
    lg.add_argument("--format", choices=("text", "json"), default="text")
    lg.add_argument("--tolerance-pct", type=float, default=5.0,
                    help="regression tolerance band per metric")
    lg.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when the latest green entry regresses")
    return p


def _ledger_entries(args) -> tuple[list[dict], str | None]:
    """Entries for a ledger action: stored ledger rows + listed artifacts."""
    path = args.ledger if args.ledger is not None else ldg.env_ledger_path()
    entries = ldg.read(path) if path else []
    for src in args.paths:
        entries.extend(ldg.entries_from_path(src))
    return entries, path


def _cmd_ledger(args) -> int:
    if args.action == "add":
        path = args.ledger if args.ledger is not None else ldg.env_ledger_path()
        if not path:
            print("obs ledger add: no ledger path (--ledger or "
                  "CRIMP_TPU_OBS_LEDGER)", file=sys.stderr)
            return 2
        if not args.paths:
            print("obs ledger add: nothing to ingest", file=sys.stderr)
            return 2
        entries = []
        for src in args.paths:
            entries.extend(ldg.entries_from_path(src))
        ldg.append(path, entries)
        print(f"appended {len(entries)} entrie(s) to {path}")
        return 0
    entries, _ = _ledger_entries(args)
    if args.action == "show":
        doc = {"entries": entries, "baseline": ldg.baseline(entries)}
        if args.format == "json":
            print(json.dumps(doc, indent=2))
        else:
            for e in entries:
                rnd = f"r{e.get('round')}" if e.get("round") is not None \
                    else "r?"
                print(f"{rnd:<4} {e.get('class', '?'):<13} "
                      f"{e.get('kind', '?'):<13} {e.get('source', '?')}")
            for metric, b in sorted(doc["baseline"].items()):
                print(f"baseline {metric:<24} {b['value']:<12g} {b['source']}")
        return 0
    report = ldg.check(entries, tolerance_pct=args.tolerance_pct)
    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        print(ldg.render_check(report))
    return 1 if (args.fail_on_regression and not report["ok"]) else 0


def _write(text: str, out: str | None) -> None:
    if out:
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text if text.endswith("\n") else text + "\n")


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "validate":
            with open(args.manifest, encoding="utf-8") as fh:
                doc = json.load(fh)
            problems = validate_manifest(doc)
            for prob in problems:
                print(f"{args.manifest}: {prob}")
            print(f"{args.manifest}: "
                  + ("OK" if not problems else f"{len(problems)} problem(s)"))
            return 1 if problems else 0

        if args.cmd == "summary":
            doc = load_manifest(args.manifest)
            if args.format == "json":
                print(json.dumps({"summary": rpt.span_rollup(doc),
                                  "counters": doc.get("counters"),
                                  "gauges": doc.get("gauges"),
                                  "knobs": doc.get("knobs"),
                                  "run_id": doc["run_id"],
                                  "wall_s": doc["wall_s"]}, indent=2))
            else:
                print(rpt.summarize(doc))
            return 0

        if args.cmd == "diff":
            a = load_manifest(args.a)
            b = load_manifest(args.b)
            d = rpt.diff(a, b, min_delta_s=args.min_delta_s)
            if args.format == "json":
                print(json.dumps(d, indent=2))
            else:
                print(rpt.render_diff(d))
            drifted = bool(d["knob_drift"] or d["numeric_mode_drift"]
                           or d["backend_drift"])
            return 1 if (args.fail_on_drift and drifted) else 0

        if args.cmd == "trace":
            doc = load_manifest(args.manifest)
            _write(json.dumps(rpt.chrome_trace(doc), indent=1), args.out)
            return 0

        if args.cmd == "prom":
            doc = load_manifest(args.manifest)
            _write(rpt.prometheus(doc), args.out)
            return 0

        if args.cmd == "roofline":
            doc = load_manifest(args.manifest)
            analysis = rfl.analyze(doc)
            if args.format == "json":
                print(json.dumps(analysis, indent=2))
            else:
                print(rfl.render(analysis))
            if args.fail_below is not None:
                worst = analysis.get("worst_pct")
                if worst is None:
                    print("obs roofline: --fail-below set but no kernel had "
                          "both a cost row and a measured span",
                          file=sys.stderr)
                    return 1
                if worst < args.fail_below:
                    print(f"obs roofline: worst kernel {worst:.2f}% of roof "
                          f"< --fail-below {args.fail_below:g}%",
                          file=sys.stderr)
                    return 1
            return 0

        if args.cmd == "merge":
            streams = mrg.resolve_streams(args.streams, run_id=args.run_id)
            out = mrg.merge_file(streams, args.out, force=args.force)
            doc = load_manifest(out)  # a merge that fails validation is a bug
            print(out)
            if args.trace_out:
                _write(json.dumps(rpt.chrome_trace(doc), indent=1),
                       args.trace_out)
            print(rpt.summarize(doc), file=sys.stderr)
            return 0

        if args.cmd == "salvage":
            events = slv.resolve_events(args.events)
            out = slv.salvage_file(events, args.out)
            doc = load_manifest(out)  # a salvage that fails validation is a bug
            print(out)
            print(rpt.summarize(doc), file=sys.stderr)
            return 0

        if args.cmd == "tail":
            return slv.tail(args.target, follow=not args.once,
                            interval=args.interval,
                            max_seconds=args.max_seconds)

        if args.cmd == "heartbeat-check":
            # missing/torn/stale are NOT usage errors: check_sidecar
            # absorbs them into (fresh=False, reason) so a dead service
            # probes as exit 1, never 2
            fresh, reason, doc = hbt.check_sidecar(args.sidecar,
                                                   args.max_age_s)
            if args.format == "json":
                print(json.dumps({"fresh": fresh, "reason": reason,
                                  "heartbeat": doc}, indent=2))
            else:
                print(f"heartbeat-check: {reason}")
            return 0 if fresh else 1

        if args.cmd == "ledger":
            return _cmd_ledger(args)
    except (OSError, ValueError) as exc:
        print(f"obs: {exc}", file=sys.stderr)
        return 2
    return 2
