"""Roofline join: cost-model rows x span durations -> efficiency of peak.

Given a manifest that carries a ``costmodel`` table (obs/costmodel.py) and
measured kernel spans, compute per-kernel achieved FLOP/s, bytes/s,
arithmetic intensity, and the fraction of the backend's roofline actually
reached — plus the compute-vs-memory-bound verdict PulsarX (arXiv
2309.02544) and the GPU jerk search (arXiv 1911.01353) use to argue about
their folding/search kernels. Surfaced as ``python -m crimp_tpu.obs
roofline`` (``--fail-below PCT`` turns the worst measured kernel into a CI
gate).

Peak-table provenance: per-chip dense bf16/f32 matmul peaks and HBM
bandwidths from the published Google Cloud TPU spec sheets (v2-v6e). The
CPU entry is an order-of-magnitude placeholder (one AVX2-class core times
the virtual-device count is wrong in both directions depending on the
host) — CPU rows exist so fallback runs still render, but their
%-of-peak is a sanity indicator, not a measurement. Rows whose kernel has
cost data but no matching span (or vice versa) degrade to partial rows
with a null percentage; nothing here raises on a sparse manifest.

Import-safe: no jax, everything computed from the manifest document.
"""

from __future__ import annotations

from crimp_tpu.obs.manifest import span_paths

# device_kind substring (lowercased, first match wins) -> per-chip peaks.
# flops = dense matmul peak (bf16 where the generation has MXU bf16,
# which is what the MXU kernels hit; the VPU f32 paths sit below it),
# bytes_per_s = HBM bandwidth. Sources: Google Cloud TPU system
# architecture pages (per-chip numbers), in table order v2..v6e.
# ici_bytes_per_s = aggregate per-chip inter-chip-interconnect bandwidth
# (approximate — the spec sheets quote per-link Gbps and link counts vary
# by topology slice); it prices the ring all-reduce the sharded kernels'
# collective_bytes estimate assumes. dcn_bytes_per_s = per-host
# data-center-network bandwidth (the inter-slice/inter-host leg of a
# multi-process mesh; ~200 Gbps NICs on current TPU hosts, ~100 Gbps on
# older generations) — it prices the cross-host leg of
# collective_bytes_split, which on the host-major mesh should be ZERO for
# the event psum; a non-zero DCN leg is the layout bug the verdict flags.
PEAKS: tuple[tuple[str, dict], ...] = (
    ("v6", {"flops": 918e12, "bytes_per_s": 1.64e12,
            "ici_bytes_per_s": 448e9,
            "dcn_bytes_per_s": 25e9,
            "source": "TPU v6e spec (bf16 dense, HBM 1640 GB/s, "
                      "ICI ~448 GB/s approx)"}),
    ("v5p", {"flops": 459e12, "bytes_per_s": 2.765e12,
             "ici_bytes_per_s": 600e9,
             "dcn_bytes_per_s": 25e9,
             "source": "TPU v5p spec (bf16 dense, HBM 2765 GB/s, "
                       "ICI ~600 GB/s approx)"}),
    ("v5", {"flops": 197e12, "bytes_per_s": 8.19e11,
            "ici_bytes_per_s": 200e9,
            "dcn_bytes_per_s": 12.5e9,
            "source": "TPU v5e spec (bf16 dense, HBM 819 GB/s, "
                      "ICI ~200 GB/s approx)"}),
    ("v4", {"flops": 275e12, "bytes_per_s": 1.228e12,
            "ici_bytes_per_s": 300e9,
            "dcn_bytes_per_s": 12.5e9,
            "source": "TPU v4 spec (bf16 dense, HBM 1228 GB/s, "
                      "ICI ~300 GB/s approx)"}),
    ("v3", {"flops": 123e12, "bytes_per_s": 9.0e11,
            "ici_bytes_per_s": 140e9,
            "dcn_bytes_per_s": 12.5e9,
            "source": "TPU v3 spec (bf16 dense, HBM 900 GB/s, "
                      "ICI ~140 GB/s approx)"}),
    ("v2", {"flops": 45e12, "bytes_per_s": 7.0e11,
            "ici_bytes_per_s": 62.5e9,
            "dcn_bytes_per_s": 12.5e9,
            "source": "TPU v2 spec (bf16 dense, HBM 700 GB/s, "
                      "ICI ~62.5 GB/s approx)"}),
    ("cpu", {"flops": 1e11, "bytes_per_s": 5e10,
             "ici_bytes_per_s": 1e10,
             "dcn_bytes_per_s": 1e9,
             "source": "CPU fallback placeholder (order of magnitude: one "
                       "AVX2-class core + DDR channel; 'ICI' = shared "
                       "memory fabric placeholder)"}),
)


def peak_for(platform: dict | None) -> dict | None:
    """The peak-table entry for a manifest's platform block, or None.

    Matches the first device's ``kind`` first (distinguishes TPU
    generations), then the backend name (catches bare "cpu").
    """
    plat = platform or {}
    devices = plat.get("devices") or []
    kind = str((devices[0] or {}).get("kind", "")).lower() if devices else ""
    backend = str(plat.get("backend") or "").lower()
    for needle, entry in PEAKS:
        if needle in kind:
            return dict(entry)
    for needle, entry in PEAKS:
        if needle in backend:
            return dict(entry)
    return None


def _leaf_rollup(doc: dict) -> dict[str, dict]:
    """Span durations aggregated by LEAF name (the cost rows' join key).

    The manifest rollup keys on full ``/`` paths; cost rows key on the
    span name ``profiling.timed()``/``obs.span()`` emitted — the leaf.
    """
    out: dict[str, dict] = {}
    for path, row in zip(span_paths(doc), doc.get("spans") or []):
        dur = row.get("dur_s")
        if dur is None:
            continue
        leaf = path.rsplit("/", 1)[-1]
        agg = out.setdefault(leaf, {"sum_s": 0.0, "count": 0})
        agg["sum_s"] += float(dur)
        agg["count"] += 1
    return out


def analyze(doc: dict) -> dict:
    """The roofline join for one manifest.

    Returns ``{"backend", "device_kind", "peak", "rows", "aggregate",
    "worst_pct", "best_pct"}``. Each row: kernel name, calls, measured
    seconds, flops/bytes from the cost model, achieved flops/s + bytes/s,
    arithmetic intensity (flops/byte), ``pct_of_roof`` (achieved flops
    over the roofline at that intensity — min(peak_flops, intensity *
    peak_bandwidth)), and ``bound`` ("compute" / "memory" by the ridge
    point, or "comm" when the collective dominates — see below).

    Sharded rows (cost rows with ``devices > 1``, captured from the
    GSPMD-partitioned program, so flops/bytes are already PER DEVICE)
    additionally carry ``devices``, the aggregate achieved rates
    (``agg_flops_per_s``/``agg_bytes_per_s`` = per-device x devices),
    ``collective_bytes_per_call`` (the registry's ring all-reduce
    estimate, split into ``collective_bytes_ici``/``collective_bytes_dcn``
    legs on manifests captured under a multi-process mesh), and
    ``comm_vs_roof`` — the ratio of the estimated collective time (each
    leg priced at its own bandwidth: ICI within a host, DCN across
    hosts) to the per-device compute/memory roofline time; above 1.0 the
    verdict flips to ``bound = "comm"``, with ``comm_leg`` naming the
    dominant leg. Rows captured on a multi-process run carry their
    ``process_index``/``process_count`` stamps (per-host rows). When any sharded row exists, ``aggregate``
    holds the N-device roofline (single-chip peaks x the widest row's
    device count; per-row pct_of_roof is per-device and is unchanged by
    that uniform scaling). Fields degrade to None wherever the manifest
    is partial (CPU rows without cost_analysis, cost rows without a
    matching span, no peak entry).
    """
    plat = doc.get("platform") or {}
    devices = plat.get("devices") or []
    kind = (devices[0] or {}).get("kind") if devices else None
    peak = peak_for(plat)
    durs = _leaf_rollup(doc)
    ridge = (peak["flops"] / peak["bytes_per_s"]) if peak else None
    rows = []
    for name, cost in sorted((doc.get("costmodel") or {}).items()):
        if not isinstance(cost, dict):
            continue
        agg = durs.get(name)
        if agg is None and cost.get("span") \
                and cost["span"] != doc.get("name"):
            # fall back to the enclosing stage span the row was captured
            # under — but never to the run root, whose duration is the
            # whole run and would fabricate a meaningless rate
            agg = durs.get(str(cost["span"]))
        dur = agg["sum_s"] if agg else None
        calls = agg["count"] if agg else 0
        flops = cost.get("flops")
        nbytes = cost.get("bytes_accessed")
        # the cost row is per CALL; the rollup sums over calls
        tot_flops = flops * calls if isinstance(flops, (int, float)) else None
        tot_bytes = nbytes * calls if isinstance(nbytes, (int, float)) else None
        fps = tot_flops / dur if tot_flops is not None and dur else None
        bps = tot_bytes / dur if tot_bytes is not None and dur else None
        intensity = (flops / nbytes
                     if isinstance(flops, (int, float))
                     and isinstance(nbytes, (int, float)) and nbytes else None)
        pct = None
        bound = None
        if peak and intensity is not None:
            roof = min(peak["flops"], intensity * peak["bytes_per_s"])
            bound = "compute" if intensity >= ridge else "memory"
            if fps is not None and roof > 0:
                pct = 100.0 * fps / roof
        ndev = cost.get("devices")
        ndev = int(ndev) if isinstance(ndev, (int, float)) and ndev >= 1 else 1
        coll = cost.get("collective_bytes")
        coll = float(coll) if isinstance(coll, (int, float)) else None
        coll_ici = cost.get("collective_bytes_ici")
        coll_ici = (float(coll_ici)
                    if isinstance(coll_ici, (int, float)) else None)
        coll_dcn = cost.get("collective_bytes_dcn")
        coll_dcn = (float(coll_dcn)
                    if isinstance(coll_dcn, (int, float)) else None)
        if coll is not None and coll_ici is None:
            # pre-split manifests: the whole estimate rode ICI
            coll_ici, coll_dcn = coll, 0.0
        comm_vs_roof = None
        comm_leg = None
        if ndev > 1 and peak and peak.get("ici_bytes_per_s") \
                and coll_ici is not None \
                and isinstance(flops, (int, float)) \
                and isinstance(nbytes, (int, float)):
            # per-device, per-call: the time the collective needs on the
            # interconnect (ICI leg + DCN leg, each priced at its own
            # bandwidth) vs the time the compute/memory roofline grants
            # the kernel body — whichever dominates names the binding
            # resource
            t_roof = max(flops / peak["flops"], nbytes / peak["bytes_per_s"])
            t_ici = coll_ici / peak["ici_bytes_per_s"]
            t_dcn = ((coll_dcn or 0.0)
                     / (peak.get("dcn_bytes_per_s") or peak["ici_bytes_per_s"]))
            if t_roof > 0:
                comm_vs_roof = (t_ici + t_dcn) / t_roof
                if t_ici or t_dcn:
                    comm_leg = "dcn" if t_dcn > t_ici else "ici"
                if comm_vs_roof > 1.0:
                    bound = "comm"
        rows.append({
            "name": name,
            "calls": calls,
            "sum_s": round(dur, 6) if dur is not None else None,
            "flops_per_call": flops,
            "bytes_per_call": nbytes,
            "flops_per_s": fps,
            "bytes_per_s": bps,
            "intensity": round(intensity, 4) if intensity is not None else None,
            "pct_of_roof": round(pct, 3) if pct is not None else None,
            "bound": bound,
            "devices": ndev,
            "agg_flops_per_s": fps * ndev if fps is not None else None,
            "agg_bytes_per_s": bps * ndev if bps is not None else None,
            "collective_bytes_per_call": coll,
            "collective_bytes_ici": coll_ici,
            "collective_bytes_dcn": coll_dcn,
            "comm_vs_roof": (round(comm_vs_roof, 3)
                             if comm_vs_roof is not None else None),
            "comm_leg": comm_leg,
            "process_index": cost.get("process_index"),
            "process_count": cost.get("process_count"),
            "peak_bytes": cost.get("peak_bytes"),
            "span": cost.get("span"),
        })
    rows.sort(key=lambda r: -(r["sum_s"] or 0.0))
    pcts = [r["pct_of_roof"] for r in rows if r["pct_of_roof"] is not None]
    shard_devs = [r["devices"] for r in rows if r["devices"] > 1]
    aggregate = None
    if shard_devs and peak:
        n = max(shard_devs)
        aggregate = {
            "devices": n,
            "flops": peak["flops"] * n,
            "bytes_per_s": peak["bytes_per_s"] * n,
            "ici_bytes_per_s": peak.get("ici_bytes_per_s"),
            "dcn_bytes_per_s": peak.get("dcn_bytes_per_s"),
        }
    return {
        "run_id": doc.get("run_id"),
        "backend": plat.get("backend"),
        "device_kind": kind,
        "peak": peak,
        "rows": rows,
        "aggregate": aggregate,
        "worst_pct": min(pcts) if pcts else None,
        "best_pct": max(pcts) if pcts else None,
    }


def _eng(val, unit: str) -> str:
    """Engineering-notation humanization ('1.2 GF/s'); '?' for None."""
    if not isinstance(val, (int, float)):
        return "?"
    for scale, prefix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(val) >= scale:
            return f"{val / scale:.2f} {prefix}{unit}"
    return f"{val:.2f} {unit}"


def render(analysis: dict, top: int = 20) -> str:
    """Human-readable roofline table, heaviest kernels first."""
    peak = analysis.get("peak")
    lines = [f"run      {analysis.get('run_id') or '?'}",
             f"backend  {analysis.get('backend') or 'none recorded'}"
             + (f"  ({analysis['device_kind']})"
                if analysis.get("device_kind") else "")]
    if peak:
        lines.append(
            f"peaks    {_eng(peak['flops'], 'FLOP/s')}  "
            f"{_eng(peak['bytes_per_s'], 'B/s')}  "
            f"ridge {peak['flops'] / peak['bytes_per_s']:.1f} flop/byte  "
            f"[{peak['source']}]")
    else:
        lines.append("peaks    no table entry for this backend; "
                     "%-of-roof unavailable")
    rows = analysis.get("rows") or []
    if not rows:
        lines.append("no cost-model rows in this manifest (CRIMP_TPU_OBS_COST "
                     "off, or no instrumented kernels ran)")
        return "\n".join(lines)
    lines.append(f"{'kernel':<22} {'calls':>5} {'time':>9} {'flop/call':>10} "
                 f"{'achieved':>12} {'intens':>7} {'%roof':>6} {'dev':>3}"
                 "  bound")
    for r in rows[:top]:
        dur = f"{r['sum_s']:.3f}s" if r["sum_s"] is not None else "?"
        pct = f"{r['pct_of_roof']:.1f}" if r["pct_of_roof"] is not None else "?"
        lines.append(
            f"{r['name']:<22} {r['calls']:>5} {dur:>9} "
            f"{_eng(r['flops_per_call'], 'F'):>10} "
            f"{_eng(r['flops_per_s'], 'F/s'):>12} "
            f"{r['intensity'] if r['intensity'] is not None else '?':>7} "
            f"{pct:>6} {r.get('devices', 1):>3}  {r['bound'] or '?'}")
    agg = analysis.get("aggregate")
    if agg:
        lines.append(
            f"sharded  {agg['devices']}-device aggregate roof: "
            f"{_eng(agg['flops'], 'FLOP/s')}  "
            f"{_eng(agg['bytes_per_s'], 'B/s')}  "
            f"ici {_eng(agg.get('ici_bytes_per_s'), 'B/s')}  "
            f"dcn {_eng(agg.get('dcn_bytes_per_s'), 'B/s')}")
        for r in rows[:top]:
            if r.get("devices", 1) <= 1:
                continue
            ratio = r.get("comm_vs_roof")
            coll = (f"collective ici "
                    f"{_eng(r.get('collective_bytes_ici'), 'B')}"
                    f" + dcn {_eng(r.get('collective_bytes_dcn'), 'B')}/call"
                    if r.get("collective_bytes_ici") is not None
                    else "collective "
                    f"{_eng(r['collective_bytes_per_call'], 'B')}/call")
            host = ""
            if isinstance(r.get("process_count"), int) \
                    and r["process_count"] > 1:
                host = (f"  host {r.get('process_index')}"
                        f"/{r['process_count']}")
            leg = f" [{r['comm_leg']}]" if r.get("comm_leg") else ""
            lines.append(
                f"  {r['name']}: x{r['devices']}  "
                f"agg {_eng(r['agg_flops_per_s'], 'F/s')}  "
                f"{coll}"
                f"  t_comm/t_roof "
                f"{ratio if ratio is not None else '?'}{leg}"
                f"  {(r['bound'] or '?') + '-bound'}{host}")
    worst = analysis.get("worst_pct")
    if worst is not None:
        lines.append(f"worst measured kernel: {worst:.2f}% of roof")
    return "\n".join(lines)
