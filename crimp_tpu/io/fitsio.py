"""Self-contained FITS binary-table reader/writer.

The runtime image has no astropy, so the framework carries its own minimal
FITS layer covering what X-ray event files need (behavioral parity target:
the astropy usage in /root/reference/src/crimp/eventfile.py:67-375):

- read primary + BINTABLE extension headers (keyword -> value),
- decode binary-table columns (L/X/B/I/J/K/E/D/A + fixed repeat counts)
  honoring TSCALn/TZEROn,
- append a column to a table HDU and write the whole file back out
  (used by ``addphasecolumn``).

FITS structure recap: a file is a sequence of HDUs; each HDU is an ASCII
header of 80-char cards in 2880-byte blocks terminated by END, followed by
big-endian binary data padded to 2880 bytes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

BLOCK = 2880
CARD = 80

# FITS TFORM letter -> (numpy dtype builder, itemsize in bytes)
_TFORM_DTYPES = {
    "L": (">i1", 1),  # logical, stored as 'T'/'F' bytes
    "X": (">u1", None),  # bit array: repeat = number of BITS
    "B": (">u1", 1),
    "I": (">i2", 2),
    "J": (">i4", 4),
    "K": (">i8", 8),
    "E": (">f4", 4),
    "D": (">f8", 8),
    "C": (">c8", 8),
    "M": (">c16", 16),
    "A": ("S", 1),  # character
}


def _parse_tform(tform: str) -> tuple[int, str]:
    """Parse a TFORM value like '1D', '8X', '32A' into (repeat, code)."""
    tform = tform.strip()
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    code = tform[i]
    if code == "P" or code == "Q":
        raise NotImplementedError("variable-length FITS arrays are not supported")
    return repeat, code


def _tform_nbytes(tform: str) -> int:
    repeat, code = _parse_tform(tform)
    if code == "X":
        return (repeat + 7) // 8
    if code == "A":
        return repeat
    return repeat * _TFORM_DTYPES[code][1]


def _parse_card(card: str) -> tuple[str, object, str] | None:
    """Parse one 80-char header card into (keyword, value, comment)."""
    keyword = card[:8].strip()
    if not keyword or keyword in ("COMMENT", "HISTORY", "END"):
        return None
    if card[8:10] != "= ":
        return None
    body = card[10:]
    comment = ""
    if body.lstrip().startswith("'"):
        # String value: ends at first single quote not doubled.
        s = body.lstrip()
        out, i = [], 1
        while i < len(s):
            if s[i] == "'":
                if i + 1 < len(s) and s[i + 1] == "'":
                    out.append("'")
                    i += 2
                    continue
                break
            out.append(s[i])
            i += 1
        value: object = "".join(out).rstrip()
        rest = s[i + 1 :]
        if "/" in rest:
            comment = rest.split("/", 1)[1].strip()
    else:
        if "/" in body:
            raw, comment = body.split("/", 1)
            comment = comment.strip()
        else:
            raw = body
        raw = raw.strip()
        if raw in ("T", "F"):
            value = raw == "T"
        elif raw == "":
            value = None
        else:
            try:
                value = int(raw)
            except ValueError:
                try:
                    value = float(raw.replace("D", "E").replace("d", "e"))
                except ValueError:
                    value = raw
    return keyword, value, comment


@dataclass
class HDU:
    """One FITS header-data unit: parsed header, raw cards, and data.

    Data access is lazy: ``_raw`` is a zero-copy view into the mmap'd file;
    the structured-table view and per-column decoding happen on demand so
    opening a multi-GB event file costs only the header walk."""

    header: dict = field(default_factory=dict)
    cards: list = field(default_factory=list)  # raw 80-char cards in file order
    _raw: memoryview | bytes | None = None  # raw data block (any HDU type)
    _table: np.ndarray | None = None  # materialized structured table (BINTABLE)
    _decoded: dict = field(default_factory=dict)  # column cache

    @property
    def name(self) -> str:
        return str(self.header.get("EXTNAME", "")).strip()

    @property
    def is_table(self) -> bool:
        return str(self.header.get("XTENSION", "")).strip() == "BINTABLE"

    @property
    def data(self) -> np.ndarray | None:
        """Structured-array view of a BINTABLE (lazy, zero-copy until written)."""
        if self._table is None and self.is_table and self._raw is not None:
            dtype = _table_dtype(self.header)
            nrows = int(self.header["NAXIS2"])
            self._table = np.frombuffer(
                self._raw, dtype=dtype, count=nrows
            )
        return self._table

    @data.setter
    def data(self, value: np.ndarray | None) -> None:
        self._table = value
        self._decoded = {}

    def column(self, name: str) -> np.ndarray:
        """Decoded (TSCAL/TZERO-applied) column by name (case-insensitive)."""
        table = self.data
        if table is None:
            raise KeyError(f"HDU {self.name!r} has no table data")
        for i in range(1, int(self.header["TFIELDS"]) + 1):
            ttype = str(self.header.get(f"TTYPE{i}", f"COL{i}")).strip()
            if ttype.upper() == name.upper():
                if ttype not in self._decoded:
                    self._decoded[ttype] = _decode_column(self.header, table, i, ttype)
                return self._decoded[ttype]
        raise KeyError(f"column {name!r} not in table {self.name!r}")

    @property
    def columns(self) -> dict:
        """All decoded columns (materializes everything; prefer column())."""
        if self.data is not None:
            for i in range(1, int(self.header["TFIELDS"]) + 1):
                ttype = str(self.header.get(f"TTYPE{i}", f"COL{i}")).strip()
                if ttype not in self._decoded:
                    self._decoded[ttype] = _decode_column(self.header, self.data, i, ttype)
        return self._decoded


class FITSFile:
    """A parsed FITS file: primary HDU + extensions, addressable by EXTNAME."""

    def __init__(self, hdus: list[HDU]):
        self.hdus = hdus

    def __getitem__(self, key: str | int) -> HDU:
        if isinstance(key, int):
            return self.hdus[key]
        for hdu in self.hdus:
            if hdu.name.upper() == key.upper():
                return hdu
        raise KeyError(f"no HDU named {key!r}")

    def __contains__(self, key: str) -> bool:
        try:
            self[key]
            return True
        except KeyError:
            return False


def _read_header(buf, pos: int) -> tuple[dict, list, int]:
    header: dict = {}
    cards: list = []
    done = False
    while not done:
        block = bytes(buf[pos : pos + BLOCK])
        if len(block) < BLOCK:
            raise ValueError("truncated FITS header")
        pos += BLOCK
        for i in range(0, BLOCK, CARD):
            card = block[i : i + CARD].decode("ascii", "replace")
            if card.startswith("END") and card[3:].strip() == "":
                done = True
                break
            parsed = _parse_card(card)
            cards.append(card)
            if parsed:
                keyword, value, _ = parsed
                header[keyword] = value
    return header, cards, pos


def _table_dtype(header: dict) -> np.dtype:
    nfields = int(header["TFIELDS"])
    fields = []
    for i in range(1, nfields + 1):
        name = str(header.get(f"TTYPE{i}", f"COL{i}")).strip()
        tform = str(header[f"TFORM{i}"]).strip()
        repeat, code = _parse_tform(tform)
        if code == "X":
            nbytes = (repeat + 7) // 8
            fields.append((name, ">u1", (nbytes,)) if nbytes > 1 else (name, ">u1"))
        elif code == "A":
            fields.append((name, f"S{repeat}"))
        else:
            base = _TFORM_DTYPES[code][0]
            fields.append((name, base, (repeat,)) if repeat > 1 else (name, base))
    return np.dtype(fields)


def _decode_column(header: dict, table: np.ndarray, index: int, name: str) -> np.ndarray:
    """Decode one column: native-endian copy with TSCAL/TZERO applied."""
    arr = np.asarray(table[name])
    if arr.dtype.kind in "iufc":
        arr = arr.astype(arr.dtype.newbyteorder("="))
    tscal = header.get(f"TSCAL{index}")
    tzero = header.get(f"TZERO{index}")
    if tscal is not None or tzero is not None:
        scale = float(tscal) if tscal is not None else 1.0
        zero = float(tzero) if tzero is not None else 0.0
        # Unsigned-int convention (TZERO=2^(bits-1), TSCAL=1) keeps ints.
        if scale == 1.0 and zero == float(int(zero)) and arr.dtype.kind == "i":
            arr = arr.astype(np.int64) + int(zero)
        else:
            arr = arr.astype(np.float64) * scale + zero
    return arr


def read_fits(path: str) -> FITSFile:
    """Parse a FITS file into lazily-decoded HDUs (mmap-backed: opening a
    multi-GB file costs only the header walk)."""
    import mmap

    with open(path, "rb") as fh:
        try:
            buf = memoryview(mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ))
        except (ValueError, OSError):  # empty file / mmap-hostile fs
            buf = memoryview(fh.read())
    hdus: list[HDU] = []
    pos = 0
    while pos < len(buf):
        header, cards, pos = _read_header(buf, pos)
        hdu = HDU(header=header, cards=cards)
        naxis = int(header.get("NAXIS", 0) or 0)
        if naxis > 0:
            bitpix = abs(int(header.get("BITPIX", 8)))
            nbytes = bitpix // 8
            for ax in range(1, naxis + 1):
                nbytes *= int(header.get(f"NAXIS{ax}", 0) or 0)
            nbytes += int(header.get("PCOUNT", 0) or 0)
            # Raw block kept for EVERY HDU type so write_fits round-trips
            # image extensions and primary arrays untouched.
            hdu._raw = buf[pos : pos + nbytes]
            pos += (nbytes + BLOCK - 1) // BLOCK * BLOCK
        hdus.append(hdu)
    return FITSFile(hdus)


# ---------------------------------------------------------------------------
# Writing: append a column to a BINTABLE HDU and serialize the file back.
# ---------------------------------------------------------------------------


def _format_card(keyword: str, value, comment: str = "") -> str:
    if isinstance(value, bool):
        body = f"{'T' if value else 'F':>20}"
    elif isinstance(value, (int, np.integer)):
        body = f"{int(value):>20}"
    elif isinstance(value, (float, np.floating)):
        body = f"{float(value):>20.14G}"
    else:
        text = str(value).replace("'", "''")
        body = f"'{text:<8}'"
    card = f"{keyword:<8}= {body}"
    if comment:
        card += f" / {comment}"
    return card[:CARD].ljust(CARD)


def _pad_block(data: bytes, fill: bytes = b"\x00") -> bytes:
    rem = len(data) % BLOCK
    if rem:
        data += fill * (BLOCK - rem)
    return data


def _serialize_header(cards: list[str]) -> bytes:
    text = "".join(card.ljust(CARD)[:CARD] for card in cards) + "END".ljust(CARD)
    return _pad_block(text.encode("ascii"), b" ")


def write_fits(path: str, fits: FITSFile) -> None:
    """Serialize a FITSFile: modified tables are re-encoded; every other
    HDU's data block (image extensions, primary arrays) is copied verbatim."""
    out = bytearray()
    for hdu in fits.hdus:
        out += _serialize_header(hdu.cards)
        if hdu._table is not None:
            out += _pad_block(hdu._table.tobytes())
        elif hdu._raw is not None:
            out += _pad_block(bytes(hdu._raw))
    with open(path, "wb") as fh:
        fh.write(bytes(out))


def add_table_column(hdu: HDU, name: str, values: np.ndarray, tform: str = "D") -> None:
    """Append a column to a BINTABLE HDU in place (data + header cards)."""
    if hdu.data is None:
        raise ValueError("HDU has no table data")
    old_dtype = hdu.data.dtype
    if name in old_dtype.names:
        raise ValueError(f"column {name!r} already exists")
    repeat, code = _parse_tform(tform)
    if repeat != 1:
        raise NotImplementedError("add_table_column supports scalar columns only")
    base = _TFORM_DTYPES[code][0]
    new_fields = [(n, old_dtype[n]) for n in old_dtype.names]
    new_fields.append((name, np.dtype(base)))
    new_dtype = np.dtype(new_fields)
    new_data = np.empty(len(hdu.data), dtype=new_dtype)
    for n in old_dtype.names:
        new_data[n] = hdu.data[n]
    new_data[name] = np.asarray(values)
    hdu.data = new_data

    nfields = int(hdu.header["TFIELDS"]) + 1
    naxis1 = new_dtype.itemsize
    hdu.header["TFIELDS"] = nfields
    hdu.header["NAXIS1"] = naxis1
    hdu.header[f"TTYPE{nfields}"] = name
    hdu.header[f"TFORM{nfields}"] = tform
    hdu._decoded[name] = np.asarray(values)

    # Rewrite the affected cards; append the new TTYPE/TFORM before END.
    new_cards = []
    for card in hdu.cards:
        keyword = card[:8].strip()
        if keyword == "TFIELDS":
            new_cards.append(_format_card("TFIELDS", nfields))
        elif keyword == "NAXIS1":
            new_cards.append(_format_card("NAXIS1", naxis1))
        else:
            new_cards.append(card)
    new_cards.append(_format_card(f"TTYPE{nfields}", name))
    new_cards.append(_format_card(f"TFORM{nfields}", tform))
    hdu.cards = new_cards
