"""tempo2/PINT FORMAT-1 ``.tim`` ToA files (read/write).

Format parity with the reference (timfile.py:25-161): the first line is
``FORMAT 1``; each data line is
``template frequency toa_mjd toa_err_us site [-flag value ...]`` with one
leading space, ``C`` comments, and trailing flag pairs (``-i``, ``-pn``; the
``pn`` pulse-number column is coerced to integer).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

FIXED_COLUMNS = ["template", "frequency", "pulse_ToA", "pulse_ToA_err", "time_ref"]


def read_tim(path: str, comment: str = "C", skiprows: int = 1) -> pd.DataFrame:
    """Read a .tim file into a DataFrame with fixed + flag columns."""
    rows = []
    with open(path, "r") as fh:
        for i, raw in enumerate(fh):
            if i < skiprows:
                continue
            line = raw.strip()
            if not line or line.startswith(comment):
                continue
            rows.append(line.split())
    records = []
    for tokens in rows:
        rec = dict(zip(FIXED_COLUMNS, tokens[:5]))
        extras = tokens[5:]
        j = 0
        while j < len(extras):
            tok = extras[j]
            if tok.startswith("-"):
                key = tok.lstrip("-")
                rec[f"{key}_flag"] = tok
                rec[key] = extras[j + 1] if j + 1 < len(extras) else None
                j += 2
            else:
                j += 1
        records.append(rec)
    df = pd.DataFrame(records)
    for col in ["frequency", "pulse_ToA", "pulse_ToA_err"]:
        if col in df.columns:
            df[col] = pd.to_numeric(df[col], errors="coerce")
    if "pn" in df.columns:
        df["pn"] = pd.to_numeric(df["pn"], errors="coerce").astype("Int64")
    return df


def write_tim(path_stem: str, df: pd.DataFrame, clobber: bool = False) -> str:
    """Write a ToA DataFrame as ``<path_stem>.tim`` (FORMAT 1)."""
    path = path_stem + ".tim"
    mode = "w" if clobber else "x"
    with open(path, mode) as fh:
        fh.write("FORMAT 1\n")
        for _, row in df.iterrows():
            fields = [str(v) for v in row.tolist() if v is not None and v == v]
            fh.write(" " + " ".join(fields) + "\n")
    return path


class PulseToAs:
    """DataFrame wrapper for .tim content: reset / time filter / write."""

    def __init__(self, pulsetoas: pd.DataFrame):
        self._original = pulsetoas.copy()
        self.df = pulsetoas.copy()

    def reset(self) -> "PulseToAs":
        self.df = self._original.copy()
        return self

    def time_filter(
        self,
        t_start: float | None = None,
        t_end: float | None = None,
        inplace: bool = True,
    ):
        lo = -np.inf if t_start is None else t_start
        hi = np.inf if t_end is None else t_end
        mask = self.df["pulse_ToA"].between(lo, hi)
        if inplace:
            self.df = self.df.loc[mask].copy()
            return self
        return self.df.loc[mask].copy()

    def writetimfile(self, timfilename: str, clobber: bool = False) -> None:
        write_tim(timfilename, self.df, clobber=clobber)


# Reference-named alias.
readtimfile = read_tim
